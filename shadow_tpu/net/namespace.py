"""Per-host network namespace: localhost + internet interfaces, port
association, ephemeral port allocation.

Parity: reference `src/main/host/network/namespace.rs` — each host owns a
loopback interface (127.0.0.1) and an internet interface (its public IP);
ephemeral ports are drawn uniformly from [10000, 65535] with the host RNG,
falling back to a linear search when the space is crowded
(`namespace.rs:19-26,210-232`). The RNG draw makes port assignment part of
the determinism contract.
"""

from __future__ import annotations

from typing import Optional

from ..core.config import QDiscMode
from ..core.rng import Xoshiro256pp
from .interface import NetworkInterface, WILDCARD_PEER, InterfaceSocket
from .packet import Protocol

EPHEMERAL_PORT_MIN = 10000
EPHEMERAL_PORT_MAX = 65535  # inclusive


class NoPortsError(RuntimeError):
    pass


class NetworkNamespace:
    def __init__(
        self,
        public_ip: str,
        qdisc: QDiscMode = QDiscMode.FIFO,
        pcap_factory=None,
    ):
        """`pcap_factory(iface_name)` returns a per-interface capture hook
        (or None) — captures are per-interface files (lo.pcap/eth0.pcap)
        like the reference's."""
        self.public_ip = public_ip
        lo_hook = pcap_factory("lo") if pcap_factory else None
        eth_hook = pcap_factory("eth0") if pcap_factory else None
        self.localhost = NetworkInterface("127.0.0.1", qdisc, lo_hook)
        self.internet = NetworkInterface(public_ip, qdisc, eth_hook)

    def purge_for_fault(self) -> int:
        """Host crash (faults/schedule.py): the simulated kernel's
        networking state is gone — every association, every queued
        ready-socket. Respawned processes re-bind their ports on a
        clean namespace, exactly like a power cycle. Returns the number
        of associations dropped."""
        n = 0
        for iface in (self.localhost, self.internet):
            n += len(iface._associations)
            iface._associations.clear()
            iface._ready_fifo.clear()
            iface._ready_rr.clear()
            iface._ready_set.clear()
        return n

    def interface_for(self, ip: str) -> Optional[NetworkInterface]:
        if ip == "127.0.0.1":
            return self.localhost
        if ip == self.public_ip:
            return self.internet
        return None

    def interfaces_for_bind(self, bind_ip: str) -> list[NetworkInterface]:
        """0.0.0.0 binds to every interface."""
        if bind_ip == "0.0.0.0":
            return [self.localhost, self.internet]
        iface = self.interface_for(bind_ip)
        return [iface] if iface else []

    def is_port_free(
        self, protocol: Protocol, port: int, bind_ip: str = "0.0.0.0",
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> bool:
        # A port is taken if any interface the bind covers has an association.
        ifaces = (
            [self.localhost, self.internet]
            if bind_ip == "0.0.0.0"
            else self.interfaces_for_bind(bind_ip)
        )
        return all(not i.is_associated(protocol, port, peer) for i in ifaces)

    def get_random_free_port(
        self,
        protocol: Protocol,
        rng: Xoshiro256pp,
        bind_ip: str = "0.0.0.0",
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> int:
        """Random draw first (RNG-consuming, determinism-relevant), linear
        scan fallback (`namespace.rs:210-232`)."""
        span = EPHEMERAL_PORT_MAX - EPHEMERAL_PORT_MIN + 1
        for _ in range(10):
            port = rng.randrange(EPHEMERAL_PORT_MIN, EPHEMERAL_PORT_MAX + 1)
            if self.is_port_free(protocol, port, bind_ip, peer):
                return port
        start = rng.randrange(EPHEMERAL_PORT_MIN, EPHEMERAL_PORT_MAX + 1)
        for off in range(span):
            port = EPHEMERAL_PORT_MIN + (start - EPHEMERAL_PORT_MIN + off) % span
            if self.is_port_free(protocol, port, bind_ip, peer):
                return port
        raise NoPortsError(f"no free {protocol.name} ephemeral ports")

    def associate(
        self,
        socket: InterfaceSocket,
        protocol: Protocol,
        bind_ip: str,
        port: int,
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> None:
        for iface in self.interfaces_for_bind(bind_ip):
            iface.associate(socket, protocol, port, peer)

    def disassociate(
        self,
        protocol: Protocol,
        bind_ip: str,
        port: int,
        peer: tuple[str, int] = WILDCARD_PEER,
    ) -> None:
        for iface in self.interfaces_for_bind(bind_ip):
            iface.disassociate(protocol, port, peer)
