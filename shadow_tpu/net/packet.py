"""Simulated packets.

Parity: reference `src/main/network/packet.rs` (PacketRc wrapper) +
`src/main/routing/packet.c` (payload, TCP/UDP headers, priority, and the
22-state delivery-status lifecycle used for tracing).

TPU note: this object form feeds the CPU syscall plane; the TPU network plane
carries the same information as SoA arrays (see `shadow_tpu/tpu/`), with
`Packet.as_record()` defining the array schema.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Callable, Optional

CONFIG_MTU = 1500  # bytes (`src/main/core/definitions.h:124-129`)
CONFIG_HEADER_SIZE_TCPIPETH = 54  # eth(14) + ip(20) + tcp(20)
CONFIG_HEADER_SIZE_UDPIPETH = 42  # eth(14) + ip(20) + udp(8)


class Protocol(enum.IntEnum):
    LOCAL = 0
    TCP = 1
    UDP = 2


class PacketStatus(enum.IntEnum):
    """Delivery-status lifecycle flags (`network/packet.rs:16-39`)."""

    SND_CREATED = 0
    SND_TCP_ENQUEUE_THROTTLED = 1
    SND_TCP_ENQUEUE_RETRANSMIT = 2
    SND_TCP_DEQUEUE_RETRANSMIT = 3
    SND_TCP_RETRANSMITTED = 4
    SND_SOCKET_BUFFERED = 5
    SND_INTERFACE_SENT = 6
    INET_SENT = 7
    INET_DROPPED = 8
    ROUTER_ENQUEUED = 9
    ROUTER_DEQUEUED = 10
    ROUTER_DROPPED = 11
    RCV_INTERFACE_RECEIVED = 12
    RCV_INTERFACE_DROPPED = 13
    RCV_SOCKET_PROCESSED = 14
    RCV_SOCKET_DROPPED = 15
    RCV_TCP_ENQUEUE_UNORDERED = 16
    RCV_SOCKET_BUFFERED = 17
    RCV_SOCKET_DELIVERED = 18
    DESTROYED = 19
    RELAY_CACHED = 20
    RELAY_FORWARDED = 21
    # injected fault-plane drop (crashed host, downed interface, burst
    # corruption — faults/schedule.py): its own status so trackers can
    # keep the `fault` drop bucket apart from wire loss
    FAULT_DROPPED = 22


# Optional global hook for packet tracing (the tracker/pcap layers register
# here; kept module-level so Packet stays lean). A hook that only reacts
# to a few statuses should early-out itself (the Manager's tracker hook
# does) — the module filters nothing, so a replacement full-stream
# tracer sees every transition.
status_trace_hook: Optional[Callable[["Packet", PacketStatus], None]] = None


@dataclass
class TcpHeader:
    """TCP header fields carried by simulated packets (`routing/packet.c`)."""

    seq: int = 0
    ack: int = 0
    window: int = 0
    flags: int = 0  # TcpFlags bitfield (see shadow_tpu.tcp)
    window_scale: Optional[int] = None
    timestamp: int = 0
    timestamp_echo: int = 0
    sel_acks: tuple = ()  # selective-ack ranges ((start, end), ...)
    sack_permitted: bool = False  # RFC 2018 option on SYN


class Packet:
    """One simulated packet.

    Addresses are (ipv4_string, port) tuples. `priority` is the host-assigned
    monotone FIFO priority (`host.rs:679-720`); lower forwards first.
    """

    __slots__ = (
        "protocol",
        "src",
        "dst",
        "payload",
        "header",
        "priority",
        "statuses",
        "_total_size",
    )

    def __init__(
        self,
        protocol: Protocol,
        src: tuple[str, int],
        dst: tuple[str, int],
        payload: bytes = b"",
        header: Optional[TcpHeader] = None,
        priority: int = 0,
    ):
        self.protocol = protocol
        self.src = src
        self.dst = dst
        self.payload = payload
        self.header = header
        self.priority = priority
        self._total_size = len(payload) + self.header_size()
        self.statuses: list[PacketStatus] = []
        self.add_status(PacketStatus.SND_CREATED)

    # -- sizes --------------------------------------------------------------

    def payload_size(self) -> int:
        return len(self.payload)

    def header_size(self) -> int:
        if self.protocol == Protocol.TCP:
            return CONFIG_HEADER_SIZE_TCPIPETH
        if self.protocol == Protocol.UDP:
            return CONFIG_HEADER_SIZE_UDPIPETH
        return 0

    def total_size(self) -> int:
        """Header + payload bytes, the unit of rate limiting (payload is
        immutable after construction, so this is precomputed)."""
        return self._total_size

    def is_control(self) -> bool:
        """Zero-payload control packets are never dropped by path loss
        (`worker.rs:364-367`)."""
        return self.payload_size() == 0

    # -- tracing ------------------------------------------------------------

    def add_status(self, status: PacketStatus) -> None:
        self.statuses.append(status)
        if status_trace_hook is not None:
            status_trace_hook(self, status)

    def __repr__(self) -> str:
        return (
            f"Packet({self.protocol.name} {self.src[0]}:{self.src[1]}->"
            f"{self.dst[0]}:{self.dst[1]} len={self.payload_size()} prio={self.priority})"
        )

    def as_record(self) -> dict:
        """Flat record form — the schema mirrored by the TPU SoA arrays."""
        h = self.header or TcpHeader()
        return {
            "protocol": int(self.protocol),
            "src_ip": self.src[0],
            "src_port": self.src[1],
            "dst_ip": self.dst[0],
            "dst_port": self.dst[1],
            "payload_len": self.payload_size(),
            "priority": self.priority,
            "seq": h.seq,
            "ack": h.ack,
            "window": h.window,
            "flags": h.flags,
        }


class PacketDevice:
    """Anything that produces/consumes packets at an address
    (`src/main/network/mod.rs:15-19`): NICs, routers."""

    def get_address(self) -> str:
        raise NotImplementedError

    def pop(self) -> Optional[Packet]:
        raise NotImplementedError

    def push(self, packet: Packet) -> None:
        raise NotImplementedError
