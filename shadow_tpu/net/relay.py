"""Rate-limited packet relays.

Parity: reference `src/main/network/relay/` — a `Relay` is the active
forwarder between `PacketDevice`s. It pulls packets from a source device and
pushes them to destination devices resolved through the host, enforcing an
optional byte-rate limit with a token bucket. State machine Idle → Pending →
Forwarding (`relay/mod.rs:67-77`); when out of tokens it caches the blocked
packet and schedules itself to resume exactly when enough tokens will exist.

Token bucket (`relay/token_bucket.rs`): refills `increment` tokens every
`interval` (1ms), lazily applying missed refills; capacity = increment + one
MTU of burst allowance so unfragmented packets can't strand tokens
(`relay/mod.rs:277-318`). Rate limits are bypassed during the bootstrap
period and for device-local (src == dst) deliveries (`relay/mod.rs:202,224`).
"""

from __future__ import annotations

from typing import Optional

from ..core import simtime
from .packet import CONFIG_MTU, Packet, PacketStatus

_IDLE = 0
_PENDING = 1
_FORWARDING = 2


class TokenBucket:
    """Discrete-interval token bucket; times are emulated-time ns ints."""

    __slots__ = ("capacity", "balance", "refill_increment", "refill_interval", "last_refill")

    def __init__(self, capacity: int, refill_increment: int, refill_interval: int):
        if capacity <= 0 or refill_increment <= 0 or refill_interval <= 0:
            raise ValueError("token bucket args must be positive")
        self.capacity = capacity
        self.balance = capacity
        self.refill_increment = refill_increment
        self.refill_interval = refill_interval
        self.last_refill = 0

    def conforming_remove(self, decrement: int, now: int) -> tuple[bool, int]:
        """Try to remove `decrement` tokens at time `now`. Returns
        (True, new_balance) on success or (False, wait_ns) where `wait_ns` is
        the duration until enough tokens will exist (aligned to refill
        boundaries)."""
        next_refill_span = self._lazy_refill(now)
        if decrement <= self.balance:
            self.balance -= decrement
            return True, self.balance
        required = decrement - self.balance
        num_refills = -(-required // self.refill_increment)  # ceil div
        if num_refills == 0:
            return False, 0
        wait = next_refill_span + (num_refills - 1) * self.refill_interval
        return False, wait

    def _lazy_refill(self, now: int) -> int:
        """Apply any refill events that have passed; return ns to the next."""
        span = now - self.last_refill
        if span >= self.refill_interval:
            num = span // self.refill_interval
            self.balance = min(
                self.balance + num * self.refill_increment, self.capacity
            )
            self.last_refill += num * self.refill_interval
            span = now - self.last_refill
        return self.refill_interval - span


def create_token_bucket(bytes_per_second: int) -> TokenBucket:
    """Shadow's relay bucket: 1ms refills of rate/1000 (min 1) bytes, with one
    MTU of extra capacity as burst allowance (`relay/mod.rs:277-296`)."""
    refill_interval = simtime.MILLISECOND
    refill_size = max(1, bytes_per_second // 1000)
    return TokenBucket(refill_size + CONFIG_MTU, refill_size, refill_interval)


class Relay:
    """Forwards packets from one source device until out of packets/tokens.

    The host supplies the environment:
      host.get_packet_device(ip) -> PacketDevice   (routing table)
      host.schedule_relay_task(callback, delay_ns) (self-scheduling)
      host.now() -> int                            (emulated time)
      host.is_bootstrapping() -> bool              (rate-limit bypass)
    """

    def __init__(self, host, src_dev_address: str, bytes_per_second: Optional[int]):
        self._host = host
        self._src_address = src_dev_address
        self._base_bytes_per_second = bytes_per_second
        self._rate_limiter = (
            create_token_bucket(bytes_per_second) if bytes_per_second is not None else None
        )
        self._state = _IDLE
        self._next_packet: Optional[Packet] = None

    def set_fault_divisor(self, div: int) -> None:
        """Fault-plane bandwidth degradation (host_degrade): rebuild the
        bucket at base_rate // div. Rebuilding starts the new bucket at
        full (degraded) capacity and re-anchors its refill phase at the
        event instant — documented modeling choice (docs/robustness.md):
        a degradation event resets the bucket."""
        if self._base_bytes_per_second is None:
            return
        rate = max(1, self._base_bytes_per_second // max(int(div), 1))
        bucket = create_token_bucket(rate)
        bucket.last_refill = self._host.now()
        self._rate_limiter = bucket

    def notify(self) -> None:
        """Source device became non-empty; start forwarding after the current
        stack unwinds (lets socket data accumulate for batched forwards)."""
        if self._state == _IDLE:
            self._forward_later(0)
        # Pending/Forwarding: a run is already scheduled or active.

    def _forward_later(self, delay_ns: int) -> None:
        assert self._state != _PENDING
        self._state = _PENDING
        self._host.schedule_relay_task(self._run_forward_task, delay_ns)

    def _run_forward_task(self) -> None:
        self._state = _IDLE
        blocking = self._forward_until_blocked()
        if blocking is not None:
            self._forward_later(blocking)

    def _forward_until_blocked(self) -> Optional[int]:
        host = self._host
        bootstrapping = host.is_bootstrapping()
        self._state = _FORWARDING
        src = host.get_packet_device(self._src_address)
        while True:
            packet = self._next_packet
            self._next_packet = None
            if packet is None:
                packet = src.pop()
            if packet is None:
                self._state = _IDLE
                return None
            # Local deliveries (loopback; inet device talking to itself) are
            # exempt from rate limits.
            is_local = src.get_address() == packet.dst[0]
            if not bootstrapping and not is_local and self._rate_limiter is not None:
                ok, result = self._rate_limiter.conforming_remove(
                    packet.total_size(), host.now()
                )
                if not ok:
                    packet.add_status(PacketStatus.RELAY_CACHED)
                    self._next_packet = packet
                    self._state = _IDLE
                    return result
            packet.add_status(PacketStatus.RELAY_FORWARDED)
            if is_local:
                src.push(packet)
            else:
                host.get_packet_device(packet.dst[0]).push(packet)
