"""Per-host inbound router with CoDel active queue management.

Parity: reference `src/main/network/router/` — CoDel per RFC 8289 with
Shadow's parameters: TARGET = 10ms (vs the RFC's 5ms), INTERVAL = 100ms,
unbounded limit (`codel_queue.rs:23-33`). The router holds packets inbound
from the simulated internet until the host pops them.

TPU note: the CoDel decision (standing delay vs TARGET, control-law drop
times) is pure arithmetic on enqueue timestamps, which makes it a natural
fit for ring-buffer timestamp arrays on device (`shadow_tpu/tpu/plane.py`).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

from ..core import simtime
from .packet import Packet, PacketDevice, PacketStatus, CONFIG_MTU

TARGET = 10 * simtime.MILLISECOND
INTERVAL = 100 * simtime.MILLISECOND

_STORE = 0
_DROP = 1


class CoDelQueue:
    """RFC 8289 CoDel ("controlled delay") AQM queue."""

    __slots__ = (
        "_elements",
        "_total_bytes",
        "_mode",
        "_interval_end",
        "_drop_next",
        "_current_drop_count",
        "_previous_drop_count",
        "dropped_count",
    )

    def __init__(self):
        self._elements: deque[tuple[Packet, int]] = deque()
        self._total_bytes = 0
        self._mode = _STORE
        self._interval_end: Optional[int] = None
        self._drop_next: Optional[int] = None
        self._current_drop_count = 0
        self._previous_drop_count = 0
        self.dropped_count = 0

    def __len__(self) -> int:
        return len(self._elements)

    def push(self, packet: Packet, now: int) -> None:
        packet.add_status(PacketStatus.ROUTER_ENQUEUED)
        self._total_bytes += packet.total_size()
        self._elements.append((packet, now))

    def pop(self, now: int) -> Optional[Packet]:
        """Next packet conforming to the standing-delay requirement; CoDel may
        drop packets during this operation."""
        item = self._codel_pop(now)
        if item is None:
            self._mode = _STORE  # empty queue is always a good state
            return None
        packet, ok_to_drop = item
        if not ok_to_drop:
            self._mode = _STORE
            packet.add_status(PacketStatus.ROUTER_DEQUEUED)
            return packet
        if self._mode == _STORE:
            out = self._drop_from_store_mode(now, packet)
        else:
            out = self._drop_from_drop_mode(now, packet)
        if out is not None:
            out.add_status(PacketStatus.ROUTER_DEQUEUED)
        return out

    # -- internals (names follow the RFC's dodequeue/control-law structure) --

    def _drop_from_store_mode(self, now: int, packet: Packet) -> Optional[Packet]:
        self._drop_packet(packet)
        nxt = self._codel_pop(now)
        self._mode = _DROP
        # Restart from the drop rate that last controlled the queue.
        delta = self._current_drop_count - self._previous_drop_count
        if self._was_dropping_recently(now) and delta > 1:
            self._current_drop_count = delta
        else:
            self._current_drop_count = 1
        self._drop_next = self._control_law(now, self._current_drop_count)
        self._previous_drop_count = self._current_drop_count
        return nxt[0] if nxt else None

    def _drop_from_drop_mode(self, now: int, packet: Packet) -> Optional[Packet]:
        item: Optional[tuple[Packet, bool]] = (packet, True)
        while item is not None and self._mode == _DROP and self._should_drop(now):
            self._drop_packet(item[0])
            self._current_drop_count += 1
            item = self._codel_pop(now)
            if item is not None and item[1]:
                self._drop_next = self._control_law(
                    self._drop_next, self._current_drop_count
                )
            else:
                self._mode = _STORE
        return item[0] if item else None

    def _codel_pop(self, now: int) -> Optional[tuple[Packet, bool]]:
        if not self._elements:
            self._interval_end = None
            return None
        packet, enqueue_ts = self._elements.popleft()
        self._total_bytes -= packet.total_size()
        standing_delay = now - enqueue_ts
        return packet, self._process_standing_delay(now, standing_delay)

    def _process_standing_delay(self, now: int, standing_delay: int) -> bool:
        if standing_delay < TARGET or self._total_bytes <= CONFIG_MTU:
            self._interval_end = None
            return False
        if self._interval_end is None:
            # just entered the bad state: wait one full interval before dropping
            self._interval_end = now + INTERVAL
            return False
        return now >= self._interval_end

    def _should_drop(self, now: int) -> bool:
        return self._drop_next is not None and now >= self._drop_next

    def _was_dropping_recently(self, now: int) -> bool:
        if self._drop_next is None:
            return False
        return max(0, now - self._drop_next) < INTERVAL * 16

    @staticmethod
    def _control_law(time: int, count: int) -> int:
        """`time + INTERVAL / sqrt(count)` — drop faster while above target."""
        return time + round(INTERVAL / math.sqrt(count) if count else INTERVAL)

    def _drop_packet(self, packet: Packet) -> None:
        self.dropped_count += 1
        packet.add_status(PacketStatus.ROUTER_DROPPED)

    def drain(self) -> list[Packet]:
        """Empty the queue without CoDel accounting (fault purge): the
        queue state machine resets to STORE as if freshly built."""
        out = [p for p, _ts in self._elements]
        self._elements.clear()
        self._total_bytes = 0
        self._mode = _STORE
        self._interval_end = None
        self._drop_next = None
        self._current_drop_count = 0
        self._previous_drop_count = 0
        return out


class Router(PacketDevice):
    """Per-host entry point for packets arriving from the simulated internet
    (`router/mod.rs:16-78`). Pushing routes outward via the host's
    send-packet hook; popping drains the inbound CoDel queue."""

    def __init__(self, address: str, send_packet_hook, clock):
        """`send_packet_hook(packet)` forwards to the simulated internet
        (Worker.send_packet); `clock()` returns current emulated time ns."""
        self._address = address
        self._send = send_packet_hook
        self._clock = clock
        self._inbound = CoDelQueue()

    def get_address(self) -> str:
        return self._address

    def route_incoming_packet(self, packet: Packet) -> None:
        self._inbound.push(packet, self._clock())

    def pop(self) -> Optional[Packet]:
        return self._inbound.pop(self._clock())

    def push(self, packet: Packet) -> None:
        self._send(packet)

    def inbound_len(self) -> int:
        return len(self._inbound)

    def purge_for_fault(self) -> int:
        """A host crash loses everything queued at its inbound router
        (faults/schedule.py host_crash). Returns the drop count; each
        purged packet gets FAULT_DROPPED so trackers bucket it apart
        from CoDel/wire drops."""
        n = 0
        for packet in self._inbound.drain():
            packet.add_status(PacketStatus.FAULT_DROPPED)
            n += 1
        return n
