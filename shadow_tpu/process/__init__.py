"""The process plane: emulated processes driven by the host event loop.

Parity: reference `src/main/host/process.rs` / `thread.rs` /
`syscall/syscall_condition.c`. Applications here are Python coroutines
against the simulated-kernel API (the analogue of Shadow's managed native
processes; the native interposition plane arrives with the C++ runtime).
"""

from .condition import SysCallCondition
from .process import ProcessState, SimProcess, Syscalls

__all__ = ["SysCallCondition", "SimProcess", "ProcessState", "Syscalls"]
