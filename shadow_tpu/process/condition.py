"""Blocked-syscall conditions.

Parity: reference `src/main/host/syscall/syscall_condition.c` — the object
representing "this thread is parked until X": a trigger composed of a file
reaching monitored state bits, and/or a timeout. When any leg fires, the
condition schedules a host task that resumes the blocked process, and
disarms its other legs (fire-once semantics). The reference also triggers
on signals; signal delivery here routes through `SimProcess.signal`, which
cancels the condition directly.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core.event import TaskRef
from ..kernel.status import FileState, ListenerFilter


class SysCallCondition:
    """Fire-once waiter on (file-state, timeout).

    `wakeup(reason)` is called exactly once from a host task context;
    `reason` is "file", "timeout", or "cancel" (signal/kill).
    """

    def __init__(
        self,
        host,
        *,
        file=None,
        state_mask: FileState = FileState.NONE,
        timeout_at_ns: Optional[int] = None,
        wakeup: Callable[[str], None],
        allow_forever: bool = False,
    ):
        self._host = host
        self._file = file
        self._state_mask = state_mask
        self._timeout_at = timeout_at_ns
        self._wakeup = wakeup
        self._allow_forever = allow_forever
        self._fired = False
        self._listener_handle: Optional[int] = None

    def arm(self) -> None:
        if self._file is not None and self._state_mask:
            # already satisfied? fire on the next task (never synchronously,
            # matching the reference's task-deferred wakeups)
            if self._file.state & self._state_mask:
                self._schedule("file")
                return
            self._listener_handle = self._file.add_listener(
                self._state_mask, ListenerFilter.OFF_TO_ON, self._on_file_event
            )
        if self._timeout_at is not None:
            # The host event queue has no unschedule; when another leg wins,
            # this task fires as a no-op against the _fired guard (same
            # shape as expired reference conditions).
            delay = max(0, self._timeout_at - self._host.now())
            self._host.schedule_task_with_delay(
                TaskRef(lambda h: self._fire("timeout"), "condition-timeout"),
                delay,
            )
        if not (self._file is not None and self._state_mask) \
                and self._timeout_at is None and not self._allow_forever:
            raise ValueError("condition with no trigger would park forever")

    def cancel(self) -> None:
        """Signal/kill: wake the blocked thread with EINTR semantics."""
        self._fire("cancel")

    def _on_file_event(self, state, changed, cb_queue) -> None:
        # resume via a host task, never from inside a notification flush
        cb_queue.add(lambda _cq: self._schedule("file"))

    def _schedule(self, reason: str) -> None:
        self._host.schedule_task_with_delay(
            TaskRef(lambda h: self._fire(reason), "condition-wakeup"), 0
        )

    def _fire(self, reason: str) -> None:
        if self._fired:
            return
        self._fired = True
        if self._listener_handle is not None and self._file is not None:
            self._file.remove_listener(self._listener_handle)
            self._listener_handle = None
        self._wakeup(reason)
