"""Managed native processes: real Linux binaries under interposition.

Parity: reference `src/main/host/managed_thread.rs` + `process.rs` — spawn
the binary with the shim preloaded (`inject_preloads`,
`managed_thread.rs:546-640`), then service its syscalls over the
shared-memory IPC channel: each trapped syscall arrives as a `ShimEvent`,
and the simulator answers with an emulated result (`SyscallComplete`) or
tells the shim to execute it natively (`SyscallDoNative`) — the dispatch
split in `syscall/handler/mod.rs`.

Round-1 scope: the syscall server virtualizes *time* (clock_gettime /
gettimeofday / time / nanosleep / clock_nanosleep answered from the
simulation clock, sleeps advancing it with zero wall-time) and identity
(getpid), passes everything else through natively, and reads/writes the
managed process's memory with process_vm_readv/writev — the
`MemoryCopier` half of the reference's memory manager
(`memory_copier.rs:185,246`). Full event-loop integration (one Host task
per resume, blocking syscalls parking on conditions) is the next layer.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
import resource
import struct
import subprocess
import threading
import time as _time
from typing import Callable, Optional

from ..core import simtime
from ..core import worker as worker_mod
from ..core.event import TaskRef
from ..kernel import errors as kerrors
from ..kernel import futex as kfutex
from ..kernel.status import FileState, StatefulFile
from .condition import SysCallCondition
from .memory import MAPPING_SYSCALLS, MemoryRegions
from .process import ProcessState
from .syscall_handler import (SYS_tgkill, DispatchCtx, NativeSyscall,
                              NativeSyscallRewrite,
                              SyscallHandler, _libc_syscall)

log = logging.getLogger("shadow_tpu.process")
from ..interpose import (
    EVENT_ADD_THREAD_REQ,
    EVENT_ADD_THREAD_RES,
    EVENT_PROCESS_DEATH,
    EVENT_START_RES,
    EVENT_SYSCALL,
    EVENT_SYSCALL_COMPLETE,
    EVENT_SYSCALL_DO_NATIVE,
    IpcChannel,
    ShimEvent,
)

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "interpose")
SHIM_PATH = os.path.join(_DIR, "libshadow_shim.so")
PRELOAD_LIBC_PATH = os.path.join(_DIR, "libshadow_preload_libc.so")
PRELOAD_OPENSSL_PATH = os.path.join(_DIR, "libshadow_preload_openssl.so")
PRELOAD_COMBINED_PATH = os.path.join(_DIR, "libshadow_preload.so")
PRELOAD_COMBINED_SSL_PATH = os.path.join(_DIR, "libshadow_preload_ssl.so")


def _preload_chain(openssl_rng: bool = False) -> str:
    """LD_PRELOAD value. Preferred: ONE combined library (wrappers +
    injector constructor) that pulls the shim in as a DT_NEEDED
    dependency — the reference's preload-injector design
    (`src/lib/preload-injector/injector.c`): the shim loads without its
    symbols ever entering the interposition scope, and the managed
    namespace sees a single preload entry. The `openssl_rng` variant
    additionally shadows libcrypto's RAND entry points. Falls back to
    the legacy three-entry chain when the combined libs are absent
    (mid-build checkouts)."""
    combined = (PRELOAD_COMBINED_SSL_PATH if openssl_rng
                else PRELOAD_COMBINED_PATH)
    if os.path.exists(combined):
        return combined
    parts = []
    if openssl_rng and os.path.exists(PRELOAD_OPENSSL_PATH):
        parts.append(PRELOAD_OPENSSL_PATH)
    if os.path.exists(PRELOAD_LIBC_PATH):
        parts.append(PRELOAD_LIBC_PATH)
    parts.append(SHIM_PATH)
    return " ".join(parts)

# x86_64 syscall numbers the server emulates
SYS_write = 1
SYS_getpid = 39
SYS_nanosleep = 35
SYS_clone = 56
SYS_fork = 57
SYS_execve = 59
SYS_exit = 60
SYS_kill = 62
SYS_gettimeofday = 96
SYS_time = 201
SYS_clock_gettime = 228
SYS_clock_nanosleep = 230
SYS_exit_group = 231

CLONE_VM = 0x100
CLONE_VFORK = 0x4000
CLONE_CHILD_CLEARTID = 0x200000


def _i32_exit(v: int) -> int:
    """exit_group status as the kernel reports it: low 8 bits, never
    negative (exit(-1) is WEXITSTATUS 255, not a signal death)."""
    return v & 0xFF


from .syscall_handler import _libc  # the package's one libc handle


class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


class MemoryCopier:
    """Read/write another process's memory (`memory_copier.rs`)."""

    def __init__(self, pid: int):
        self.pid = pid

    def read(self, remote_addr: int, n: int) -> bytes:
        buf = ctypes.create_string_buffer(n)
        local = _IoVec(ctypes.cast(buf, ctypes.c_void_p), n)
        remote = _IoVec(ctypes.c_void_p(remote_addr), n)
        got = _libc.process_vm_readv(
            self.pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
        )
        if got != n:
            # third arg = faulting address, for region diagnostics
            raise OSError(ctypes.get_errno(), "process_vm_readv failed",
                          hex(remote_addr))
        return buf.raw

    def write(self, remote_addr: int, data: bytes) -> None:
        buf = ctypes.create_string_buffer(data, len(data))
        local = _IoVec(ctypes.cast(buf, ctypes.c_void_p), len(data))
        remote = _IoVec(ctypes.c_void_p(remote_addr), len(data))
        got = _libc.process_vm_writev(
            self.pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
        )
        if got != len(data):
            raise OSError(ctypes.get_errno(), "process_vm_writev failed",
                          hex(remote_addr))


class SyscallServer:
    """Answers one managed process's syscall stream with virtual time.

    `clock` returns the simulation time in ns; `advance` moves it forward
    (standalone use drives a plain counter; event-loop integration hands
    these to the Host)."""

    def __init__(self, *, virtual_pid: int = 1000,
                 clock: Optional[Callable[[], int]] = None,
                 advance: Optional[Callable[[int], None]] = None):
        self._vtime = 0
        self.clock = clock or (lambda: self._vtime)
        self.advance = advance or self._advance_own
        self.virtual_pid = virtual_pid
        self.native_pid: Optional[int] = None  # set once the child is spawned
        self.syscall_counts: dict[int, int] = {}
        self.mem: Optional[MemoryCopier] = None

    def _advance_own(self, delta_ns: int) -> None:
        self._vtime += delta_ns

    # -- dispatch -------------------------------------------------------

    def handle(self, nr: int, args) -> Optional[int]:
        """Returns an emulated retval, or None for native passthrough."""
        self.syscall_counts[nr] = self.syscall_counts.get(nr, 0) + 1
        if nr == SYS_getpid:
            return self.virtual_pid
        if nr == SYS_clock_gettime:
            return self._clock_gettime(args[0], args[1])
        if nr == SYS_gettimeofday:
            return self._gettimeofday(args[0])
        if nr == SYS_time:
            t = simtime.emulated_from_sim(self.clock()) // simtime.SECOND
            if args[0]:
                self.mem.write(args[0], struct.pack("<q", t))
            return t
        if nr in (SYS_nanosleep, SYS_clock_nanosleep):
            return self._nanosleep(nr, args)
        if nr == SYS_kill:
            return self._kill(args[0], args[1])
        return None  # DO_NATIVE

    def _kill(self, target: int, sig: int) -> Optional[int]:
        """kill(2) with pid translation: the process only knows virtual
        pids (getpid returns one), so a native passthrough would target an
        unrelated — or nonexistent — real process. Translate the pids we
        know; fail with ESRCH for ones we don't rather than leak a signal
        outside the simulation (`process.rs:1309` signal dispatch)."""
        import errno as _errno

        target = ctypes.c_int64(target).value  # sign-extend from u64
        if target in (self.virtual_pid, 0, -self.virtual_pid) and self.native_pid:
            try:
                os.kill(self.native_pid, sig)
            except ProcessLookupError:
                return -_errno.ESRCH
            except PermissionError:
                return -_errno.EPERM
            return 0
        return -_errno.ESRCH

    def _clock_gettime(self, clockid: int, ts_addr: int) -> int:
        now = self.clock()
        if clockid in simtime.MONOTONIC_CLOCK_IDS:
            ns = now
        else:  # REALTIME & friends observe the emulated epoch
            ns = simtime.emulated_from_sim(now)
        if ts_addr:
            self.mem.write(ts_addr, struct.pack("<qq", ns // 10**9, ns % 10**9))
        return 0

    def _gettimeofday(self, tv_addr: int) -> int:
        ns = simtime.emulated_from_sim(self.clock())
        if tv_addr:
            self.mem.write(tv_addr, struct.pack("<qq", ns // 10**9,
                                                (ns % 10**9) // 1000))
        return 0

    def _nanosleep(self, nr: int, args) -> int:
        TIMER_ABSTIME = 1
        req_addr = args[2] if nr == SYS_clock_nanosleep else args[0]
        raw = self.mem.read(req_addr, 16)
        sec, nsec = struct.unpack("<qq", raw)
        t = sec * simtime.SECOND + nsec
        if nr == SYS_clock_nanosleep and args[1] & TIMER_ABSTIME:
            # absolute deadline on the given clock; REALTIME deadlines are
            # relative to the emulated epoch
            clockid = args[0]
            now = (self.clock() if clockid in simtime.MONOTONIC_CLOCK_IDS
                   else simtime.emulated_from_sim(self.clock()))
            t -= now
        if t > 0:
            self.advance(t)
        return 0


class ManagedProcess:
    """Spawn + serve one native binary under the shim."""

    def __init__(self, argv: list[str], server: Optional[SyscallServer] = None,
                 capture_output: bool = True, env: Optional[dict] = None):
        if not os.path.exists(SHIM_PATH):
            from .. import interpose

            interpose.build()
        self.server = server or SyscallServer()
        self.ipc = IpcChannel.create()
        full_env = dict(env if env is not None else os.environ)
        # preload injection (`managed_thread.rs` inject_preloads)
        preload = full_env.get("LD_PRELOAD", "")
        full_env["LD_PRELOAD"] = (
            _preload_chain() + (" " + preload if preload else "")
        )
        full_env["SHADOW_TPU_IPC_HANDLE"] = self.ipc.block.serialize()
        self.proc = subprocess.Popen(
            argv,
            env=full_env,
            stdout=subprocess.PIPE if capture_output else None,
            stderr=subprocess.PIPE if capture_output else None,
        )
        self.server.mem = MemoryCopier(self.proc.pid)
        self.server.native_pid = self.proc.pid
        self.native_pid: Optional[int] = None
        self.death_seen = threading.Event()
        self._serve_thread = threading.Thread(target=self._serve, daemon=True)
        self._serve_thread.start()

    def _serve(self) -> None:
        while True:
            ev = self.ipc.recv_from_shim()
            if ev is None:
                return  # channel closed
            if ev.kind == EVENT_START_RES:
                self.native_pid = int(ev.u.add_thread_res.child_native_tid)
                continue
            if ev.kind == EVENT_PROCESS_DEATH:
                self.death_seen.set()
                continue
            if ev.kind != EVENT_SYSCALL:
                continue
            nr = int(ev.u.syscall.number)
            args = [int(ev.u.syscall.args[i]) for i in range(6)]
            try:
                ret = self.server.handle(nr, args)
            except OSError:
                ret = None  # memory gone (racing exit): let it run natively
            reply = ShimEvent()
            if ret is None:
                reply.kind = EVENT_SYSCALL_DO_NATIVE
            else:
                reply.kind = EVENT_SYSCALL_COMPLETE
                reply.u.complete.retval = ret
                reply.u.complete.restartable = 1
            try:
                self.ipc.send_to_shim(reply)
            except OSError:
                return

    def wait(self, timeout: Optional[float] = None):
        """Wait for exit; returns (exit_code, stdout, stderr)."""
        out, err = self.proc.communicate(timeout=timeout)
        self.ipc.close()  # unblock the server thread
        self._serve_thread.join(timeout=5)
        self.ipc.block.free()  # unlink the /dev/shm object
        return self.proc.returncode, out, err


class ManagedThread:
    """Simulator-side record of one native thread of a managed process.

    Parity: reference `ManagedThread` (`managed_thread.rs`) — owns the
    thread's IPC channel, park state for blocked syscalls, and the
    CLONE_CHILD_CLEARTID bookkeeping that lets pthread_join block on the
    EMULATED futex (`thread.rs` handles the clear + wake explicitly; the
    kernel's native clear happens too, but no native waiter exists).

    Thread ids stay NATIVE in this rebuild (glibc writes the native tid
    into its own pthread struct via CLONE_PARENT_SETTID before we ever see
    it); only process ids are virtual.
    """

    __slots__ = ("process", "ipc", "native_tid", "parked_condition",
                 "park_deadline", "park_call", "park_restartable",
                 "futex_waiter", "wait_epoll",
                 "ctid_addr", "dead", "is_main", "tindex", "sig_blocked",
                 "sigwait_set", "sigwait_info_ptr", "suspend_saved",
                 "pinned_cpu", "vfork_child")

    def __init__(self, process, ipc, is_main: bool = False):
        self.process = process
        self.ipc = ipc
        self.native_tid: Optional[int] = None
        self.parked_condition = None
        self.park_deadline: Optional[int] = None
        self.park_call = None  # (nr, args) of the blocked syscall
        self.park_restartable = True  # SA_RESTART eligibility of the park
        self.sig_blocked = 0  # virtualized blocked-signal mask
        self.sigwait_set = 0  # nonzero while parked in rt_sigtimedwait
        self.sigwait_info_ptr = 0  # its siginfo output pointer
        self.suspend_saved = None  # pre-sigsuspend mask to restore
        self.pinned_cpu = None  # last CPU this native thread was pinned to
        # posix_spawn/system(3): the VM-sharing helper "thread" is really
        # a vfork child-to-be; this is the placeholder process its
        # execve (or _exit) materializes/finalizes
        self.vfork_child: Optional["ManagedSimProcess"] = None
        self.futex_waiter = None
        self.wait_epoll = None
        self.ctid_addr = 0
        self.dead = False
        self.is_main = is_main
        # stable per-process ordinal (creation order, which is
        # sim-deterministic — native tids are NOT, so strace prints this)
        self.tindex = process._next_tindex()


class ManagedSimProcess:
    """A native binary coordinated by the simulation event loop.

    Parity: the reference's resume model (`managed_thread.rs:185-322`,
    `Host::resume` `host.rs:474-501`): the worker thread executing this
    host hands control to ONE managed thread at a time (which runs
    natively, sim time frozen) and services its syscalls inline until one
    *blocks*; blocking syscalls park that thread on a `SysCallCondition`
    and the event loop resumes whichever thread's condition fires next —
    threads of a process never run concurrently, which is what keeps the
    simulation deterministic.

    clone() with CLONE_VM follows the AddThread handshake (reference
    `managed_thread.rs:349-428`): allocate a child channel, let the shim
    run the native clone with a trampoline, schedule the child's first
    resume as a host task. fork-like clone creates a child
    ManagedSimProcess whose descriptor table is forked from the parent's
    (`process.rs:591` new_forked_process).
    """

    def _init_common(self, host, name: str, argv: list[str],
                     output_dir: Optional[str] = None) -> None:
        self.host = host
        self.name = name
        self.argv = argv
        self.pid = host.next_pid()
        # process groups / sessions (`process.rs:1092-1094`): top-level
        # processes live in init's group and session (pgid=sid=1, like
        # the reference's ProcessId::INIT), so setsid()/setpgid(0,0)
        # daemonization works; fork inherits
        self.pgid = 1
        self.sid = 1
        self.exit_status: Optional[int] = None
        self.kill_signal: Optional[int] = None
        self.server = SyscallServer(virtual_pid=self.pid,
                                    clock=self._clock_ns)
        # the shared clock powering the in-shim time fast path
        self.proc_clock = None
        self.ipc: Optional[IpcChannel] = None
        self.proc = None
        self._death_seen = False
        self._output_dir = output_dir
        self._cwd: Optional[str] = None  # per-host data dir once spawned
        self._stdout = self._stderr = None
        self._tindex_counter = 0
        self.strace = None  # StraceLogger when strace_logging_mode is on
        self.regions: Optional[MemoryRegions] = None  # set at spawn
        self._pending_signals: set[int] = set()  # blocked-everywhere sigs
        # threads (main first); clone in flight between ADD_THREAD_REQ and
        # ADD_THREAD_RES parks here
        self.threads: list[ManagedThread] = []
        self._pending_clone = None
        self._pending_clone_call = None
        # fork/wait bookkeeping (`handler/wait.rs`): children + the file
        # wait4 blocks on; parent links back for getppid
        self.children: list["ManagedSimProcess"] = []
        self.parent: Optional["ManagedSimProcess"] = None
        self.reaped = False
        self.child_waiter = StatefulFile()
        self._exit_code: Optional[int] = None
        # Serializes IPC close/free between the worker thread (cleanup) and
        # the ChildPidWatcher thread (death callback): the callback must
        # never touch a freed shmem mapping.
        self._ipc_lock = threading.Lock()
        host.processes.append(self)

    def _next_tindex(self) -> int:
        t = self._tindex_counter
        self._tindex_counter += 1
        return t

    def __init__(self, host, name: str, argv: list[str],
                 output_dir: Optional[str] = None,
                 strace_mode: str = "off"):
        self._init_common(host, name, argv, output_dir)
        self.state = ProcessState.PENDING
        # the simulated-kernel dispatch table (network, readiness, sleep)
        self.handler = SyscallHandler(self)
        from .strace import make_logger

        self.strace = make_logger(output_dir, name, strace_mode)
        self._strace_mode = strace_mode

    @classmethod
    def forked(cls, parent: "ManagedSimProcess") -> "ManagedSimProcess":
        """The simulator-side half of fork(2): a child process object that
        shares the parent's open files through a forked descriptor table.
        The native child is created by the parent's shim; `_finish_fork`
        wires its pid in once the clone returns."""
        self = cls.__new__(cls)
        # monotone fork ordinal (len(children) would reuse a name after an
        # aborted fork and truncate the earlier child's output files)
        parent._fork_counter = getattr(parent, "_fork_counter", 0)
        fork_ix = parent._fork_counter
        parent._fork_counter += 1
        self._init_common(parent.host, f"{parent.name}.fork{fork_ix}",
                          parent.argv, output_dir=parent._output_dir)
        self.state = ProcessState.RUNNING  # the native child exists shortly
        self.handler = SyscallHandler(
            self, table=parent.handler._table.fork_into())
        # fork(2) inherits signal dispositions and stdio shadows (the
        # fork_into table preserves slot numbering, so the low-fd
        # override map transfers verbatim — each shadow needs its own
        # ref in the child table)
        self.handler.sig_actions = dict(parent.handler.sig_actions)
        self.handler._low_overrides = dict(parent.handler._low_overrides)
        # fork(2) inherits rlimits and nice
        self.handler._rlimits = dict(parent.handler._rlimits)
        self.handler._nice = parent.handler._nice
        from .strace import make_logger

        self._strace_mode = getattr(parent, "_strace_mode", "off")
        self.strace = make_logger(self._output_dir, self.name,
                                  self._strace_mode)
        # fast path stays disabled (proc_clock None): the clock block would
        # be shared with the parent
        self.ipc = IpcChannel.create()
        self.threads = [ManagedThread(self, self.ipc, is_main=True)]
        self.parent = parent
        self.pgid = parent.pgid  # fork inherits group and session
        self.sid = parent.sid
        parent.children.append(self)
        return self

    @classmethod
    def vfork_placeholder(cls, parent: "ManagedSimProcess") \
            -> "ManagedSimProcess":
        """The simulator-side identity of a posix_spawn/system(3) helper:
        a child process that exists from the app's point of view (clone
        returned its pid) but whose own image only arrives at execve.
        vfork shares the VM but COPIES the fd table, so the helper's
        syscalls (posix_spawn file_actions: dup2/close) dispatch against
        its OWN handler from clone time — the parent's table stays
        untouched. Memory and futexes stay shared with the parent."""
        self = cls.__new__(cls)
        parent._fork_counter = getattr(parent, "_fork_counter", 0)
        ix = parent._fork_counter
        parent._fork_counter += 1
        self._init_common(parent.host, f"{parent.name}.spawn{ix}",
                          parent.argv, output_dir=parent._output_dir)
        self.state = ProcessState.RUNNING
        self.handler = SyscallHandler(
            self, table=parent.handler._table.fork_into())
        self.handler._low_overrides = dict(parent.handler._low_overrides)
        self.handler.sig_actions = dict(parent.handler.sig_actions)
        self.handler._rlimits = dict(parent.handler._rlimits)
        self.handler._nice = parent.handler._nice
        self.handler.futexes = parent.handler.futexes  # shared VM
        self.server.mem = parent.server.mem  # shared VM
        self.pgid = parent.pgid
        self.sid = parent.sid
        self.parent = parent
        self._vfork_parent_wait = None  # (thread, retval) once suspended
        from .strace import make_logger

        self._strace_mode = getattr(parent, "_strace_mode", "off")
        self.strace = make_logger(self._output_dir, self.name,
                                  self._strace_mode)
        parent.children.append(self)
        return self

    def _erase_placeholder(self) -> None:
        """A vfork clone that failed natively: the placeholder was never
        observable (clone returned an error), so remove every trace."""
        if self.handler is not None:
            self.handler.close_all()
        if self.parent is not None and self in self.parent.children:
            self.parent.children.remove(self)
        if self in self.host.processes:
            self.host.processes.remove(self)

    def _abort_fork(self) -> None:
        """The native fork failed: erase the phantom child entirely —
        release the forked descriptor references (or the parent's sockets
        would never close) and disappear from all bookkeeping."""
        if self.parent is not None and self in self.parent.children:
            self.parent.children.remove(self)
        if self in self.host.processes:
            self.host.processes.remove(self)
        self.state = ProcessState.KILLED
        self.kill_signal = 9
        self._close_descriptors()
        self._cleanup()

    def _finish_fork(self, native_pid: int) -> None:
        """Parent's ADD_THREAD_RES arrived: the native child exists."""
        self.server.mem = MemoryCopier(native_pid)
        self.server.native_pid = native_pid
        self.regions = MemoryRegions(native_pid)
        self.threads[0].native_tid = native_pid
        from .pidwatcher import get_watcher

        get_watcher().watch(native_pid, self._on_child_death)
        self.host.schedule_task_with_delay(
            TaskRef(lambda h: self._start_thread(self.threads[0]),
                    "fork-child-start"), 0,
        )

    @property
    def is_alive(self) -> bool:
        return self.state in (ProcessState.PENDING, ProcessState.RUNNING)

    # -- lifecycle ------------------------------------------------------

    # accelerator-harness variables that must never leak into managed
    # processes: a managed python importing an injected sitecustomize
    # (PYTHONPATH site dirs) would initialize TPU runtime plumbing under
    # the shim and abort ("event_loop.cc Invalid IPAddress")
    _ENV_SCRUB_PREFIXES = ("PALLAS_AXON_", "AXON_", "JAX_", "TPU_",
                           "LIBTPU", "XLA_")

    @classmethod
    def _scrub_env(cls, env: dict) -> dict:
        out = {k: v for k, v in env.items()
               if not k.startswith(cls._ENV_SCRUB_PREFIXES)}
        pp = out.get("PYTHONPATH")
        if pp:
            kept = [p for p in pp.split(os.pathsep)
                    if ".axon_site" not in p]
            if kept:
                out["PYTHONPATH"] = os.pathsep.join(kept)
            else:
                out.pop("PYTHONPATH", None)
        return out

    def _launch_native(self, argv: list[str],
                       app_env: Optional[dict] = None,
                       executable: Optional[str] = None) -> None:
        """Start (or restart, for execve) the native process with the
        shim environment: fresh IPC channel, main thread, clock block,
        memory/region plumbing, and the death watcher."""
        from .. import interpose

        interpose.build()  # once per process; make no-ops when current
        self.ipc = IpcChannel.create()
        self.threads = [ManagedThread(self, self.ipc, is_main=True)]
        # scrub only the INHERITED environment: an execve-supplied envp is
        # the app's explicit choice and must pass through verbatim
        env = self._scrub_env(dict(os.environ)) if app_env is None \
            else dict(app_env)
        preload = env.get("LD_PRELOAD", "")
        use_ssl_rng = bool(getattr(
            getattr(self.host, "config_experimental", None),
            "use_preload_openssl_rng", True))
        env["LD_PRELOAD"] = _preload_chain(use_ssl_rng) + (
            " " + preload if preload else "")
        env["SHADOW_TPU_IPC_HANDLE"] = self.ipc.block.serialize()
        hosts_path = getattr(self.host, "hosts_file_path", None)
        if hosts_path:
            env["SHADOW_TPU_HOSTS_FILE"] = hosts_path
        # shared clock block: the shim answers clock_gettime/gettimeofday/
        # time locally from it, zero IPC round trips (`shim_sys.c:25-80`)
        from ..interpose import ProcessClock

        self.proc_clock = ProcessClock()
        latency = 0
        if getattr(self.host, "model_unblocked_syscall_latency", False):
            exp = getattr(self.host, "config_experimental", None)
            latency = getattr(exp, "unblocked_syscall_latency", 1000) or 0
        self.proc_clock.configure(
            simtime.EMUTIME_SIMULATION_START_UNIX_NS, latency
        )
        env["SHADOW_TPU_SHMEM_HANDLE"] = self.proc_clock.serialize()
        if self._output_dir and self._stdout is None:
            os.makedirs(self._output_dir, exist_ok=True)
            self._stdout = open(os.path.join(self._output_dir,
                                             f"{self.name}.stdout"), "wb")
            self._stderr = open(os.path.join(self._output_dir,
                                             f"{self.name}.stderr"), "wb")
        # Per-host filesystem view (`regular_file.c:277-329` + the
        # reference's per-host data dirs): the process starts in ITS
        # host's data directory, so two hosts writing the same relative
        # filename land in separate per-host trees instead of colliding
        # in the simulator's cwd. An execve re-spawn passes the old
        # image's live cwd through self._cwd (chdir survives exec).
        cwd = self._cwd
        if cwd is None and self._output_dir:
            cwd = self._cwd = os.path.abspath(self._output_dir)

        # The virtual descriptor range starts at VFD_BASE (= 700, kept
        # below FD_SETSIZE so select() works on virtual fds). Cap the
        # NATIVE table so the kernel can never hand out an fd that
        # collides with it — the process just sees EMFILE at 700 open
        # files, like any rlimit-ed process. The VISIBLE limit is
        # different: getrlimit/prlimit64 are virtualized to report 1024
        # (the whole native+virtual range) because glibc validates fds
        # against sysconf(_SC_OPEN_MAX) — e.g.
        # posix_spawn_file_actions_adddup2 rejects any fd >= the soft
        # limit with EBADF at ADD time, which would make every virtual
        # fd unusable in file actions. The preexec closure runs
        # post-fork: it must only make the one syscall (no imports, no
        # allocation — resource is imported at module scope).
        # clamp to the simulator's own hard limit: asking for (700, 700)
        # under e.g. `ulimit -Hn 512` would raise EPERM in preexec and
        # abort every spawn
        _fd_cap = min(700, resource.getrlimit(resource.RLIMIT_NOFILE)[1])

        def _limit_fds():
            resource.setrlimit(resource.RLIMIT_NOFILE, (_fd_cap, _fd_cap))

        inherit = getattr(self, "_inherit_stdio", None) or {}
        self.proc = subprocess.Popen(
            argv, env=env, executable=executable, cwd=cwd,
            preexec_fn=_limit_fds,
            stdin=inherit.get(0, None),
            stdout=inherit.get(1, self._stdout or subprocess.DEVNULL),
            stderr=inherit.get(2, self._stderr or subprocess.DEVNULL),
        )
        for fd in inherit.values():
            os.close(fd)  # the child holds its own dups now
        self._inherit_stdio = None
        self.server.mem = MemoryCopier(self.proc.pid)
        self.server.native_pid = self.proc.pid
        # region bookkeeping (`memory_manager/mod.rs:616-709`): seeded from
        # /proc/<pid>/maps, invalidated by mapping syscalls in dispatch
        self.regions = MemoryRegions(self.proc.pid)
        self.state = ProcessState.RUNNING
        # Liveness guarantee (`childpid_watcher.rs`): if the child dies
        # without the shim destructor running (SIGKILL, segfault), close
        # the IPC writer so a recv_from_shim blocked on the worker thread
        # returns instead of deadlocking the simulation.
        from .pidwatcher import get_watcher

        get_watcher().watch(self.proc.pid, self._on_child_death)

    _SYS_pidfd_getfd = 438

    def _steal_stdio(self, old_pid: int) -> dict:
        """Duplicate a dying incarnation's stdio fds into the simulator
        (pidfd_getfd(2)) so they survive the exec-as-respawn. Default
        log-file stdio (same inode as our .stdout/.stderr sinks) is left
        to the normal wiring — only redirects travel."""
        out: dict[int, int] = {}
        defaults = {}
        for sink, gfd in ((self._stdout, 1), (self._stderr, 2)):
            if sink is not None:
                try:
                    st = os.fstat(sink.fileno())
                    defaults[gfd] = (st.st_dev, st.st_ino)
                except OSError:
                    pass
        try:
            pidfd = os.pidfd_open(old_pid)
        except OSError:
            return out
        try:
            import ctypes

            libc = ctypes.CDLL(None, use_errno=True)
            for gfd in (0, 1, 2):
                local = libc.syscall(self._SYS_pidfd_getfd, pidfd, gfd, 0)
                if local < 0:
                    continue
                try:
                    st = os.fstat(local)
                    ident = (st.st_dev, st.st_ino)
                    # stdin: only carry real redirects, not the tty/null
                    import stat as _stat

                    if gfd == 0 and _stat.S_ISCHR(st.st_mode):
                        raise OSError
                    if defaults.get(gfd) == ident:
                        raise OSError  # default log sink: normal wiring
                except OSError:
                    os.close(local)
                    continue
                out[gfd] = local
        finally:
            os.close(pidfd)
        return out

    def spawn(self) -> None:
        assert self.state == ProcessState.PENDING
        self._launch_native(self.argv)
        self._resume(self.threads[0])

    def stop(self, signal_nr: int = 15) -> None:
        if self.state != ProcessState.RUNNING:
            return
        if self.proc is not None:
            self.proc.send_signal(signal_nr)
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)
        elif self.server.native_pid is not None:
            # forked child: not our native child — signal by pid; its
            # native parent (the managed parent) reaps or abandons the
            # zombie, which the kernel collects at that parent's exit
            try:
                os.kill(self.server.native_pid, signal_nr)
            except (ProcessLookupError, PermissionError):
                pass
        self.state = ProcessState.KILLED
        self.kill_signal = signal_nr
        self._abort_pending_clone()
        self._cancel_all_parks()
        self._close_descriptors()
        self._cleanup()
        self._notify_parent()

    # -- virtual signal delivery (`process.rs:1309`, shim/src/syscall.rs) --

    # syscalls Linux restarts under SA_RESTART (signal(7)); the rest
    # return EINTR after the handler runs
    _RESTARTABLE = frozenset((
        0, 1, 19, 20, 43, 42, 44, 45, 46, 47, 61, 247, 288,  # io + wait
    ))

    def deliver_signal(self, sig: int, self_directed: bool = False) -> None:
        """Deliver `sig` at simulated time, under simulator control:

        - ignored (explicitly or by default): nothing happens;
        - default-terminate: the process is stopped through the process
          plane at the current sim instant (state KILLED, kill_signal =
          sig — `expected_final_state: signaled` checks see exactly this,
          with no native-kill/death-watcher race);
        - handler installed: the native signal is forwarded (the app's
          real handler runs inside the shim's blocked recv loop), after
          which parked syscalls either restart (SA_RESTART + restartable
          class) or complete with -EINTR.

        Effects on ANOTHER process run as a delay-0 host task so the
        SENDER's syscall completes first (delivery must not re-enter the
        target's resume loop from the sender's stack). A SELF-directed
        signal must act before the caller executes another instruction
        (`kill -9 $$` may never reach its own exit), so it forwards
        natively right away — the death/handler lands at the caller's own
        kill() call, a precise simulated instant."""
        if self.state != ProcessState.RUNNING or self.handler is None:
            return  # handler None: vfork placeholder awaiting its exec
        # a parked sigwait consumes the signal without running a handler
        # (`rt_sigtimedwait(2)`) — checked before disposition since
        # sigwait catches ignored and default-disposition signals alike,
        # and before the mask gate since sigwait'd signals are blocked
        bit = 1 << (sig - 1)
        for t in sorted(self.threads, key=lambda th: th.tindex):
            if t.dead or t.parked_condition is None:
                continue
            if getattr(t, "sigwait_set", 0) & bit:
                # delay-0 task, like every other delivery effect: resuming
                # the waiter inline on the SENDER's stack would block the
                # sender's worker in the target's resume loop
                self.host.schedule_task_with_delay(
                    TaskRef(lambda h: self._sigwait_deliver(sig),
                            "sigwait-deliver"), 0)
                return
        kind, sa_restart = self.handler.signal_disposition(sig)
        # SIGCONT's job control is unmodeled, but an INSTALLED handler for
        # it still runs (common resume-detection idiom)
        if kind == "ignore" or (sig == 18 and kind != "handler"):
            return
        # every live thread blocks it (virtual masks are authoritative):
        # the signal stays pending until rt_sigprocmask unblocks it
        # (SIGKILL is unmaskable). This holds for raise()/self-kill too —
        # a self-directed blocked signal pends, like Linux.
        if sig != 9:
            live = [t for t in self.threads if not t.dead]
            if live and all(t.sig_blocked & bit for t in live):
                self._pending_signals.add(sig)
                return
        if self_directed:
            # target a mask-eligible native thread (tgkill), not the
            # process: a process-directed kill would let the native kernel
            # run the handler on a virtually-masked thread. Fall back to
            # the process when no tgkill lands (stale/unknown tids).
            live = [t for t in sorted(self.threads,
                                      key=lambda th: th.tindex)
                    if not t.dead and not t.sig_blocked & bit]
            if not any(self._signal_native_thread(t, sig) for t in live) \
                    and self.server.native_pid:
                try:
                    os.kill(self.server.native_pid, sig)
                except ProcessLookupError:
                    pass
            return
        if kind == "default" or sig == 9:
            self.host.schedule_task_with_delay(
                TaskRef(lambda h: self.stop(sig), "signal-terminate"), 0)
            return
        self.host.schedule_task_with_delay(
            TaskRef(lambda h: self._deliver_handled(sig, sa_restart),
                    "signal-deliver"), 0)

    def _sigwait_deliver(self, sig: int) -> None:
        """Deferred half of a sigwait consumption: re-scan (the waiter may
        have unparked since) and complete, or fall back to a fresh
        delivery decision."""
        if self.state != ProcessState.RUNNING:
            return
        bit = 1 << (sig - 1)
        for t in sorted(self.threads, key=lambda th: th.tindex):
            if t.dead or t.parked_condition is None:
                continue
            if t.sigwait_set & bit:
                self._complete_sigwait(t, sig)
                return
        self.deliver_signal(sig)  # nobody waiting anymore: normal path

    def _complete_sigwait(self, thread: ManagedThread, sig: int) -> None:
        """A parked rt_sigtimedwait consumes `sig`: complete with the
        signal number, write minimal siginfo, run no handler."""
        # pop the sigwait claim BEFORE cancel(): the condition's cancel
        # wakeup runs _unpark, which clears these fields as stale
        info_ptr, thread.sigwait_info_ptr = thread.sigwait_info_ptr, 0
        thread.sigwait_set = 0
        cond, thread.parked_condition = thread.parked_condition, None
        if cond is not None:
            cond.cancel()
        self.handler._drop_wait_epoll(thread)
        if info_ptr:
            try:
                self.handler.write_siginfo(info_ptr, sig)
            except OSError:
                pass
        nr, pargs = thread.park_call or (0, ())
        self._strace(thread, nr, pargs, sig)
        self._reply_complete(thread, sig)
        self._resume(thread)

    def signals_unblocked(self, bits: int) -> None:
        """A thread's rt_sigprocmask just unblocked `bits`: re-deliver any
        matching pending process-directed signals (signal(7) pending-set
        semantics)."""
        for sig in sorted(self._pending_signals):
            if bits & (1 << (sig - 1)):
                self._pending_signals.discard(sig)
                self.deliver_signal(sig)

    def _signal_native_thread(self, thread, sig: int) -> bool:
        """tgkill the chosen recipient's native thread so the app handler
        runs on exactly the thread the virtual mask selection picked (a
        process-directed os.kill would let the native kernel pick any
        thread, including virtually-masked ones)."""
        native_pid = self.server.native_pid
        tid = thread.native_tid
        if not native_pid or not tid:
            return False
        return _libc_syscall(SYS_tgkill, native_pid, tid, sig) == 0

    def _deliver_handled(self, sig: int, sa_restart: bool) -> None:
        if self.state != ProcessState.RUNNING:
            return
        # A process-directed signal interrupts exactly ONE thread, like
        # the kernel picking a single recipient (signal(7)); the lowest
        # tindex whose virtual mask admits the signal, parked threads
        # preferred (they're the ones whose syscalls must EINTR). Without
        # this, a periodic ITIMER_REAL would EINTR every blocked syscall
        # in a multithreaded process on every tick.
        bit = 1 << (sig - 1)
        eligible = [t for t in sorted(self.threads,
                                      key=lambda th: th.tindex)
                    if not t.dead and not t.sig_blocked & bit]
        if not eligible:
            self._pending_signals.add(sig)  # raced with a mask change
            return
        # parked threads first (their syscalls must EINTR), then running
        # ones; a failed tgkill (stale tid racing native death) falls
        # through to the next candidate, then to a process-directed kill
        # so a handled signal is never silently dropped
        ordered = sorted(eligible,
                         key=lambda t: (t.parked_condition is None,
                                        t.tindex))
        recipient = None
        for cand in ordered:
            # pending BEFORE any EINTR completion: the kernel delivers it
            # when the shim's blocked futex recv restarts, so the app's
            # handler has run by the time its syscall returns EINTR
            if self._signal_native_thread(cand, sig):
                recipient = cand
                break
        if recipient is None:
            native = self.server.native_pid
            if not native:
                return
            try:
                os.kill(native, sig)
            except ProcessLookupError:
                return  # process is gone; nothing to interrupt
            recipient = ordered[0]
        for t in (recipient,):
            if t.parked_condition is None or t.dead:
                continue
            cond, t.parked_condition = t.parked_condition, None
            cond.cancel()
            self.handler._drop_wait_epoll(t)
            t.sigwait_set = 0  # this park is over; drop any stale
            t.sigwait_info_ptr = 0  # sigwait claim on future parks
            nr, pargs = t.park_call or (0, ())
            if t.suspend_saved is not None:
                # leaving a sigsuspend park: the pre-suspend mask comes
                # back before the EINTR completes (`sigsuspend(2)`)
                t.sig_blocked, t.suspend_saved = t.suspend_saved, None
            if sa_restart and nr in self._RESTARTABLE \
                    and getattr(t, "park_restartable", True):
                # restart as if freshly issued (usually re-parks)
                if not self._handle_syscall_event(t, nr, list(pargs)):
                    self._resume(t)
            else:
                import errno as _errno

                # a futex waiter must leave the table or a later WAKE
                # would be consumed by this dead entry and strand a real
                # waiter (mirror _sys_futex's timeout cleanup)
                w, t.futex_waiter = t.futex_waiter, None
                if w is not None and not (w.state & FileState.FUTEX_WAKEUP):
                    self.handler.futexes.remove_waiter(w)
                    self._reply_complete(t, -_errno.EINTR)
                elif w is not None:
                    self._reply_complete(t, 0)  # the wake already counted it
                else:
                    self._reply_complete(t, -_errno.EINTR)
                self._resume(t)
            break

    def _cancel_all_parks(self) -> None:
        for t in self.threads:
            if t.parked_condition is not None:
                cond, t.parked_condition = t.parked_condition, None
                cond.cancel()

    # -- the inline resume loop ----------------------------------------

    def _resume(self, thread: ManagedThread) -> None:
        """Service ONE managed thread until it blocks, exits, or dies (runs
        on the worker thread currently executing this host, like the
        reference `managed_thread.rs:185-322` resume loop)."""
        # managed threads follow their worker's CPU pin so host-affine
        # cache state stays warm across control transfers
        # (`managed_thread.rs:533-544`, affinity.c migration)
        wcpu = worker_mod.current_cpu()
        if wcpu is not None and thread.native_tid \
                and thread.pinned_cpu != wcpu:
            try:
                os.sched_setaffinity(thread.native_tid, {wcpu})
                thread.pinned_cpu = wcpu
            except OSError:
                thread.pinned_cpu = wcpu  # don't retry a dead/foreign tid
        # CPU model: the wall time between handing control to the shim and
        # its next event is native execution; charge it to the simulated
        # CPU (`process.rs:465-482` cpu-delay timer). Only measured when
        # the model is on — the charges are wall-time based and therefore
        # nondeterministic by design.
        cpu = self.host.cpu
        charge = cpu is not None and cpu.threshold is not None
        while True:
            if charge:
                # The CPU model charges native exec wall time by
                # design (process.rs:465-482); off by default.
                t0 = _time.monotonic_ns()  # shadowlint: disable=SL101 -- CPU model, see above
                ev = thread.ipc.recv_from_shim()
                cpu.add_delay(_time.monotonic_ns() - t0)  # shadowlint: disable=SL101 -- CPU model, see above
            else:
                ev = thread.ipc.recv_from_shim()
            if ev is None:
                if thread.vfork_child is not None:
                    # only the spawn helper's native process died, not
                    # ours: finalize the vfork child, keep the parent
                    self._finalize_vfork_helper(thread, None,
                                                kill_signal=9)
                    return
                self._reap()
                return
            if ev.kind == EVENT_START_RES:
                if thread.native_tid is None:
                    thread.native_tid = int(
                        ev.u.add_thread_res.child_native_tid)
                continue
            if ev.kind == EVENT_PROCESS_DEATH:
                self._death_seen = True
                continue
            if ev.kind == EVENT_ADD_THREAD_RES:
                if self._finish_clone(
                        thread, int(ev.u.add_thread_res.child_native_tid)):
                    return  # vfork: parent parked until child exec/exit
                continue
            if ev.kind != EVENT_SYSCALL:
                continue
            nr = int(ev.u.syscall.number)
            args = [int(ev.u.syscall.args[i]) for i in range(6)]

            if nr == SYS_exit_group:
                self._strace(thread, nr, args, "<noreturn>")
                self._handle_exit_group(thread, args)
                return
            if nr == SYS_exit:
                self._strace(thread, nr, args, "<noreturn>")
                if self._handle_thread_exit(thread, args):
                    return  # thread (or process) left the running set
                continue
            if nr == SYS_clone and (args[0] & CLONE_VM):
                self._begin_clone_thread(thread, args)
                continue  # next recv: ADD_THREAD_RES from the parent shim
            if nr in (SYS_fork, SYS_clone):
                self._begin_fork(thread, nr, args)
                continue
            if nr == SYS_execve:
                if thread.vfork_child is not None:
                    if self._exec_vfork_child(thread, args):
                        return  # helper retired; child process launched
                    continue
                if self._begin_exec(thread, args):
                    return  # old incarnation retired; new one resumed
                continue

            if self._handle_syscall_event(thread, nr, args):
                return  # parked on a condition; no reply yet

    # -- execve ----------------------------------------------------------

    def _read_cstr(self, addr: int, cap: int = 4096) -> bytes:
        """NUL-terminated string from process memory, chunk-read so a
        string near an unmapped page boundary still resolves."""
        out = b""
        chunk = 256
        while len(out) < cap:
            take = min(chunk, cap - len(out))
            try:
                data = self.handler.mem.read(addr + len(out), take)
            except OSError:
                if chunk > 1:
                    chunk = 1
                    continue
                raise
            nul = data.find(b"\x00")
            if nul >= 0:
                return out + data[:nul]
            out += data
        return out

    def _read_cstr_array(self, addr: int, cap: int = 1024) -> list[bytes]:
        out = []
        for i in range(cap):
            (ptr,) = struct.unpack(
                "<Q", self.handler.mem.read(addr + 8 * i, 8))
            if ptr == 0:
                return out
            out.append(self._read_cstr(ptr))
        return out

    def _read_exec_request(self, thread: ManagedThread, args):
        """Read and validate an execve request from process memory.
        Returns (path, argv, app_env) on success, or an int errno; the
        validation happens fully BEFORE any image teardown — after a
        kill there is no process left to return an errno to."""
        import errno as _errno

        try:
            path = self._read_cstr(args[0]).decode("utf-8", "surrogateescape")
            # NULL argv/envp are legal (empty vectors, `execve(2)`)
            argv = [a.decode("utf-8", "surrogateescape")
                    for a in self._read_cstr_array(args[1])] \
                if args[1] else []
            envp = [e.decode("utf-8", "surrogateescape")
                    for e in self._read_cstr_array(args[2])] \
                if args[2] else []
        except OSError:
            return _errno.EFAULT
        if os.path.isdir(path):
            return _errno.EISDIR
        if not os.path.exists(path):
            return _errno.ENOENT
        if not os.access(path, os.X_OK):
            return _errno.EACCES
        try:
            with open(path, "rb") as fh:
                magic = fh.read(4)
        except OSError:
            return _errno.EACCES
        if not (magic.startswith(b"\x7fELF") or magic.startswith(b"#!")):
            return _errno.ENOEXEC
        app_env = {}
        for entry in envp:
            key, _, value = entry.partition("=")
            if key:
                app_env[key] = value
        return path, argv, app_env

    def _begin_exec(self, thread: ManagedThread, args) -> bool:
        """execve(2): replace this process's native image while keeping
        its simulator identity — pid/pgid/sid, descriptor table (minus
        CLOEXEC), itimers, and the blocked-signal mask survive; caught
        signal dispositions reset to default; sibling threads die
        (`handler/unistd.rs:777` execve_common). Returns True when the
        old incarnation is retired (exec never returns on success).

        Known limitation (exec-as-respawn): NATIVE fd state that only
        lives in the old image's fd table does not survive. A
        posix_spawn file_actions dup2 of a native regular-file fd (e.g.
        subprocess stdout=open('out.txt')) is performed in the vfork
        helper, which is SIGKILLed here; the respawned image gets fresh
        stdio wired to the sim's .stdout/.stderr logs, so the
        redirection silently vanishes. Virtual-table fds and the low-fd
        shadows are re-established; other non-CLOEXEC native fds are
        not. Real execve(2) preserves all of these — fixing it would
        mean snapshotting the helper's /proc/<pid>/fd and re-dup'ing
        into the new incarnation at spawn."""
        req = self._read_exec_request(thread, args)
        if isinstance(req, int):
            self._strace(thread, SYS_execve, args, -req)
            self._reply_complete(thread, -req)
            return False
        path, argv, app_env = req
        self._strace(thread, SYS_execve, args, "<noreturn>")
        saved_mask = thread.sig_blocked  # the exec'ing thread's mask

        # retire the old native incarnation: no more death callbacks for
        # the old pid, no replies to its shim — just kill and reap it
        old_pid = self.server.native_pid
        # cwd survives execve(2): snapshot the live incarnation's before
        # it dies so a chdir()-then-exec sequence respawns in the right
        # directory (exec-as-respawn would otherwise reset to the
        # initial per-host dir)
        try:
            self._cwd = os.readlink(f"/proc/{old_pid}/cwd")
        except OSError:
            pass  # already gone: keep the previous cwd
        # stdio survives execve(2) too: a shell's `cmd > file` opens the
        # redirect in the parent and the exec'd child INHERITS fd 1.
        # The respawn would rewire stdio to the .stdout/.stderr logs and
        # silently swallow the redirect (this exact bug shipped rounds
        # 2-4). Steal the dying image's stdio via pidfd_getfd and hand
        # any NON-default fd to the new incarnation.
        self._inherit_stdio = self._steal_stdio(old_pid)
        old_proc, self.proc = self.proc, None
        from .pidwatcher import get_watcher

        if old_pid:
            get_watcher().unwatch(old_pid)
        self._abort_pending_clone()  # a mid-handshake clone dies with us
        self._cancel_all_parks()
        with self._ipc_lock:
            for t in self.threads:
                t.dead = True
                if t.ipc is not None:
                    # the shim is about to be SIGKILLed and no worker is
                    # mid-recv on these mappings: free, don't just close
                    t.ipc.close()
                    t.ipc.block.free()
                    t.ipc = None
        old_clock, self.proc_clock = self.proc_clock, None
        if old_clock is not None:
            old_clock.free()
        if old_proc is not None:
            old_proc.kill()
            try:
                old_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        elif old_pid:  # forked child: not our direct native child
            try:
                os.kill(old_pid, 9)
            except ProcessLookupError:
                pass

        # exec-time kernel state transitions
        self.handler._table.close_cloexec()
        self.handler.sig_actions = {
            sig: act for sig, act in self.handler.sig_actions.items()
            if act[0] == "ignore"  # ignores survive; handlers reset
        }
        self.handler.futexes = kfutex.FutexTable()  # fresh address space

        try:
            self._launch_native(argv or [path], app_env=app_env,
                                executable=path)
        except OSError as e:
            # residual exec failure past the preflight (e.g. wrong-arch
            # ELF): the old image is already gone, so the process dies
            # like a child whose exec failed post-fork
            log.warning("%s: execve(%s) failed at spawn: %s",
                        self.name, path, e)
            self._exit_code = 127
            self.exit_status = 127
            self.state = ProcessState.EXITED
            for t in self.threads:
                t.dead = True
            self._close_descriptors()
            self._cleanup()
            self._notify_parent()
            return True
        self.threads[0].sig_blocked = saved_mask  # mask survives exec
        self._resume(self.threads[0])
        return True

    def _exec_vfork_child(self, thread: ManagedThread, args) -> bool:
        """execve from a posix_spawn/system(3) helper: the placeholder
        child process materializes with the new image; the parent is
        untouched. Readers go through the PARENT's memory (the helper
        shares our VM)."""
        req = self._read_exec_request(thread, args)
        if isinstance(req, int):
            self._strace(thread, SYS_execve, args, -req)
            self._reply_complete(thread, -req)
            return False
        path, argv, app_env = req
        self._strace(thread, SYS_execve, args, "<noreturn>")

        child, thread.vfork_child = thread.vfork_child, None
        # the child's handler exists since clone (its own fd-table copy,
        # already mutated by any file_actions the helper ran); exec-time
        # transitions: CLOEXEC drop, handler-dispositions reset, and a
        # fresh futex namespace (the VM stops being shared now)
        child.handler._table.close_cloexec()
        child.handler.sig_actions = {
            sig: act for sig, act in child.handler.sig_actions.items()
            if act[0] == "ignore"
        }
        child.handler.futexes = kfutex.FutexTable()

        # retire the native helper (its own native process, shared VM)
        helper_tid = thread.native_tid
        thread.dead = True
        with self._ipc_lock:
            if thread.ipc is not None and thread.ipc is not self.ipc:
                thread.ipc.close()
                thread.ipc.block.free()
                thread.ipc = None
            if not thread.is_main and thread in self.threads:
                self.threads.remove(thread)
        if helper_tid:
            from .pidwatcher import get_watcher

            get_watcher().unwatch(helper_tid)
            try:
                os.kill(helper_tid, 9)
            except ProcessLookupError:
                pass

        try:
            child._launch_native(argv or [path], app_env=app_env,
                                 executable=path)
        except OSError as e:
            log.warning("%s: spawn exec(%s) failed: %s",
                        child.name, path, e)
            child._exit_code = 127
            child.exit_status = 127
            child.state = ProcessState.EXITED
            # drop the snapshot's file refs and any partially-created
            # IPC/clock blocks — same teardown _begin_exec's twin does
            child._close_descriptors()
            child._cleanup()
            child._notify_parent()
            self._release_vfork_parent(child)
            return True
        child.threads[0].sig_blocked = thread.sig_blocked
        self._release_vfork_parent(child)  # exec happened: parent resumes
        self.host.schedule_task_with_delay(
            TaskRef(lambda h: child._resume(child.threads[0]),
                    "vfork-exec-start"), 0)
        return True

    # -- clone / fork handshakes ----------------------------------------

    def _begin_clone_thread(self, thread: ManagedThread, args) -> None:
        """Reply ADD_THREAD_REQ with a fresh channel; the shim runs the
        native clone + trampoline (`managed_thread.rs:349-428`)."""
        child_ipc = IpcChannel.create()
        child = ManagedThread(self, child_ipc)
        child.sig_blocked = thread.sig_blocked  # mask inherits at clone
        if args[0] & CLONE_VFORK:
            # posix_spawn/system: natively a VM-sharing helper process
            # (the shim strips VFORK and runs it like a thread); in the
            # simulation it is a child PROCESS whose image arrives at its
            # execve. Allocate its virtual pid now — that's the value the
            # app's clone returns and later waitpid()s on.
            child.vfork_child = ManagedSimProcess.vfork_placeholder(self)
        if args[0] & CLONE_CHILD_CLEARTID:
            child.ctid_addr = args[3]
        with self._ipc_lock:  # threads is read by the death watcher
            self.threads.append(child)
        self._pending_clone = child
        self._pending_clone_call = (SYS_clone, tuple(args))
        reply = ShimEvent()
        reply.kind = EVENT_ADD_THREAD_REQ
        reply.u.add_thread_req.ipc_handle = child_ipc.block.serialize().encode()
        self._publish_clock()
        try:
            thread.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _begin_fork(self, thread: ManagedThread, nr: int, args) -> None:
        child = ManagedSimProcess.forked(self)
        child.threads[0].sig_blocked = thread.sig_blocked  # fork inherits
        self._pending_clone = child
        self._pending_clone_call = (nr, tuple(args))
        reply = ShimEvent()
        reply.kind = EVENT_ADD_THREAD_REQ
        reply.u.add_thread_req.ipc_handle = child.ipc.block.serialize().encode()
        self._publish_clock()
        try:
            thread.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _finish_clone(self, thread: ManagedThread, native_tid: int) -> bool:
        """Returns True when the CALLER (the cloning thread) must park:
        vfork semantics suspend the parent until the child execs or
        exits — glibc's posix_spawn keeps the spawn args on the parent's
        stack frame and relies on that suspension."""
        pending, self._pending_clone = self._pending_clone, None
        call, self._pending_clone_call = (
            getattr(self, "_pending_clone_call", None), None)
        if call is not None:
            retval = native_tid
            if native_tid >= 0 and pending is not None:
                if not isinstance(pending, ManagedThread):
                    retval = pending.pid  # app sees the virtual child pid
                elif pending.vfork_child is not None:
                    retval = pending.vfork_child.pid  # vfork child's vpid
            self._strace(thread, call[0], call[1], retval)
        if pending is None:
            self._reply_complete(thread, -kerrors.EINVAL)
            return False
        if isinstance(pending, ManagedThread):
            if native_tid < 0:  # native clone failed
                with self._ipc_lock:  # vs the death watcher's close sweep
                    self.threads.remove(pending)
                    pending.ipc.close()
                    pending.ipc.block.free()
                    pending.ipc = None
                if pending.vfork_child is not None:
                    pending.vfork_child._erase_placeholder()
                self._reply_complete(thread, native_tid)
                return False
            pending.native_tid = native_tid
            self.host.schedule_task_with_delay(
                TaskRef(lambda h, c=pending: self._start_thread(c),
                        "thread-start"), 0,
            )
            if pending.vfork_child is not None:
                # the vfork helper is natively its own process; the
                # PARENT thread stays suspended (no reply) until the
                # child execs or exits — true vfork semantics. Watch the
                # helper's native process: a silent death (segfault,
                # external kill) must not wedge the recv loop.
                pending.vfork_child.server.native_pid = native_tid
                pending.vfork_child._vfork_parent_wait = (
                    thread, pending.vfork_child.pid)
                from .pidwatcher import get_watcher

                get_watcher().watch(
                    native_tid,
                    lambda t=pending: self._on_vfork_helper_death(t))
                return True
            # native tids stay visible to the app (glibc already
            # stored this value via CLONE_PARENT_SETTID)
            self._reply_complete(thread, native_tid)
            return False
        else:  # forked child process
            if native_tid < 0:
                pending._abort_fork()
                self._reply_complete(thread, native_tid)
                return False
            pending._finish_fork(native_tid)
            # the app sees the VIRTUAL child pid (wait4/kill use it)
            self._reply_complete(thread, pending.pid)
            return False

    def _release_vfork_parent(self, child: "ManagedSimProcess") -> None:
        """The vfork child exec'd or exited: wake the suspended parent
        thread with the child's pid as the clone retval."""
        waiter = getattr(child, "_vfork_parent_wait", None)
        child._vfork_parent_wait = None
        if waiter is None:
            return
        parent_thread, retval = waiter
        if parent_thread.dead or self.state != ProcessState.RUNNING:
            return
        self._reply_complete(parent_thread, retval)
        self.host.schedule_task_with_delay(
            TaskRef(lambda h: self._resume(parent_thread),
                    "vfork-parent-resume"), 0)

    def _start_thread(self, child: ManagedThread) -> None:
        """Host task: first resume of a cloned thread (or forked child's
        main thread) — consume its START_RES, send the go-ahead, serve.

        The cloned thread may die before the rendezvous (`shim_clone_child`
        exits if attach fails) — only the THREAD dies, so the process
        watcher never closes this channel and a plain blocking recv would
        hang the whole simulation. Recv in bounded slices and check the
        native task's liveness on each timeout."""
        if child.dead or self.state != ProcessState.RUNNING:
            return
        while True:
            try:
                ev = child.ipc.recv_from_shim_timed(50_000_000)  # START_RES
                break
            except TimeoutError:
                if self._native_task_running(child.native_tid):
                    continue
                log.warning("cloned thread %s of %r died before rendezvous",
                            child.native_tid, self.name)
                with self._ipc_lock:
                    child.dead = True
                    if child.ipc is not None:
                        child.ipc.close()
                        child.ipc.block.free()
                        child.ipc = None
                return
        if ev is None:
            self._reap()
            return
        self._reply_complete(child, 0)  # the go-ahead
        self._resume(child)

    # -- exits -----------------------------------------------------------

    def _finalize_vfork_helper(self, thread: ManagedThread,
                               exit_code: Optional[int],
                               kill_signal: Optional[int] = None) -> None:
        """A posix_spawn helper left WITHOUT exec (its _exit(127) after a
        failed exec arrives as exit_group, or it died natively): only the
        vfork CHILD dies; the parent process is untouched."""
        child, thread.vfork_child = thread.vfork_child, None
        if kill_signal is not None:
            child.kill_signal = kill_signal
            child.state = ProcessState.KILLED
        else:
            child._exit_code = _i32_exit(exit_code or 0)
            child.exit_status = child._exit_code
            child.state = ProcessState.EXITED
        if child.handler is not None:
            child.handler.close_all()  # drop the copied table's refs
        child._notify_parent()
        self._release_vfork_parent(child)
        thread.dead = True
        if thread.native_tid:
            from .pidwatcher import get_watcher

            get_watcher().unwatch(thread.native_tid)
        with self._ipc_lock:
            if thread.ipc is not None and thread.ipc is not self.ipc:
                thread.ipc.close()
                thread.ipc.block.free()
                thread.ipc = None
            if not thread.is_main and thread in self.threads:
                self.threads.remove(thread)

    def _on_vfork_helper_death(self, thread: ManagedThread) -> None:
        """Watcher-thread callback: the helper's native process died
        without an event (segfault/external kill). Close its channel so
        a blocked recv returns, and finalize from a worker task."""
        with self._ipc_lock:
            if thread.ipc is not None:
                thread.ipc.close()
        self.host.post_cross_thread_task(TaskRef(
            lambda h: (self._finalize_vfork_helper(thread, None,
                                                   kill_signal=9)
                       if thread.vfork_child is not None else None),
            "vfork-helper-reap"))

    def _handle_exit_group(self, thread: ManagedThread, args) -> None:
        """exit_group: close simulated descriptors (FINs go out, ports
        free), record the exit code, and let the native exit run."""
        if thread.vfork_child is not None:
            # a spawn helper's _exit (exec failed in __spawni_child):
            # the vfork CHILD exits; the parent lives on. Reply BEFORE
            # finalize — finalize frees the thread's channel.
            self._reply_native(thread)  # its native exit tears down only
            self._finalize_vfork_helper(thread, args[0])
            return  # the helper's own process
        self._exit_code = _i32_exit(args[0])
        for t in self.threads:
            if t is not thread:
                self._thread_cleartid(t)
            t.dead = True
        self._cancel_all_parks()
        self._close_descriptors()
        self._reply_native(thread)
        self._reap()

    def _handle_thread_exit(self, thread: ManagedThread, args) -> bool:
        """SYS_exit: one thread leaves. Returns True when the caller's
        resume loop should stop (always — the thread is gone; if it was the
        last one the process is reaped)."""
        if thread.vfork_child is not None:
            # a posix_spawn helper that exits WITHOUT exec (exec failed
            # in __spawni_child): only the vfork child dies. Reply first;
            # finalize frees the channel.
            self._reply_native(thread)
            self._finalize_vfork_helper(thread, args[0])
            return True
        thread.dead = True
        self._reply_native(thread)
        # The emulated cleartid wake must not fire before the native thread
        # is really gone: a woken joiner may free the dying thread's stack
        # (glibc __nptl_free_tcb) while it is still running. Zombie-wait on
        # /proc like the reference (`managed_thread.rs:481-531`); exited
        # non-leader threads are auto-reaped, so the task dir vanishing is
        # the all-clear.
        self._wait_native_thread_gone(thread)
        self._thread_cleartid(thread)
        # Release the dead thread's channel NOW, not at process teardown: a
        # server cloning one thread per request would otherwise accumulate
        # one shmem block + one ManagedThread record per request for the
        # whole simulation.
        with self._ipc_lock:
            if thread.ipc is not None and thread.ipc is not self.ipc:
                thread.ipc.close()
                thread.ipc.block.free()
                thread.ipc = None
            if not thread.is_main:
                self.threads.remove(thread)
        if all(t.dead for t in self.threads):
            self._exit_code = _i32_exit(args[0])
            self._close_descriptors()
            self._reap()
        return True

    def _wait_native_thread_gone(self, thread: ManagedThread,
                                 timeout_s: float = 2.0) -> None:
        tid = thread.native_tid
        if not self.server.native_pid or not tid:
            return
        import time as _time

        deadline = _time.monotonic() + timeout_s  # shadowlint: disable=SL101 -- real-OS thread reaping
        while self._native_task_running(tid):
            # shadowlint: disable=SL101 -- real-OS thread reaping, outside the sim clock
            if _time.monotonic() > deadline:
                log.warning("thread %d of %r did not exit within %ss",
                            tid, self.name, timeout_s)
                return
            _time.sleep(0.00005)

    @staticmethod
    def _proc_stat_fields(pid: int, tid: Optional[int] = None) \
            -> Optional[list[bytes]]:
        """/proc/<pid>[/task/<tid>]/stat fields AFTER the parenthesized
        comm (i.e. index 0 = state, stat field 3), or None when the entry
        is gone/unreadable. rsplit on ')' survives a comm containing
        parentheses."""
        path = (f"/proc/{pid}/task/{tid}/stat" if tid is not None
                else f"/proc/{pid}/stat")
        try:
            with open(path, "rb") as f:
                fields = f.read().rsplit(b")", 1)[1].split()
            return fields or None
        except (OSError, IndexError):
            return None

    def _native_task_running(self, tid: Optional[int]) -> bool:
        """Whether the native task may still be executing user code. Gone =
        its /proc task entry vanished (exited non-leader threads are
        auto-reaped) OR it parks as a zombie — a thread-group leader's
        entry lingers in Z state until the whole group exits, and a zombie
        runs no more user code, so waiting on the entry itself would spin
        out the full timeout on every leader pthread_exit."""
        pid = self.server.native_pid
        if not pid or not tid:
            return False
        fields = self._proc_stat_fields(pid, tid)
        return fields is not None and fields[0] not in (b"Z", b"X")

    def _thread_cleartid(self, thread: ManagedThread) -> None:
        """CLONE_CHILD_CLEARTID contract against the EMULATED futex: write
        0 to the ctid word and wake its waiters (pthread_join blocks
        there). The kernel's native clear/wake happens too, but only our
        wake reaches simulated waiters (`thread.rs` handle_child_cleartid).
        """
        if not thread.ctid_addr:
            return
        try:
            self.server.mem.write(thread.ctid_addr, struct.pack("<i", 0))
        except OSError:
            pass  # address space already gone
        self.handler.futexes.wake(thread.ctid_addr, 2**31)
        thread.ctid_addr = 0

    # -- syscall dispatch ------------------------------------------------

    def _handle_syscall_event(self, thread: ManagedThread, nr: int, args,
                              wake=None) -> bool:
        """Dispatch one trapped syscall. Returns True when the thread
        parked (the shim gets its reply when the condition fires)."""
        ctx = DispatchCtx(wake, thread.park_deadline if wake else None,
                          thread)
        if nr in MAPPING_SYSCALLS and self.regions is not None:
            # the mapping mutates natively; re-parse the region table on
            # its next query (`memory_manager/mod.rs:616-709`)
            self.regions.mark_dirty()
        # a vfork helper's syscalls act on ITS copied fd table (and its
        # own process identity: getppid, wait, kill-from), not ours
        handler = self.handler if thread.vfork_child is None \
            else thread.vfork_child.handler
        try:
            ret = handler.dispatch(nr, args, ctx)
        except NativeSyscallRewrite as rw:
            self._strace(thread, nr, args, "<native>",
                         argstr=rw.strace_args)
            self._reply_native_rewrite(thread, args, rw.path_args)
            return False
        except NativeSyscall as ns:
            # not simulated-kernel territory: time/identity emulation, then
            # native passthrough
            try:
                ret2 = self.server.handle(nr, args)
            except OSError:
                ret2 = None  # memory gone (racing exit): run it natively
            if ret2 is None:
                self._strace(thread, nr, args, "<native>",
                             argstr=getattr(ns, "strace_args", None))
                self._reply_native(thread)
            else:
                self._strace(thread, nr, args, ret2)
                self._reply_complete(thread, ret2)
            return False
        except kerrors.SyscallError as e:
            self._strace(thread, nr, args, -e.errno)
            self._reply_complete(thread, -e.errno)
            return False
        except kerrors.Blocked as b:
            # logged at completion, when the re-dispatch returns a result
            self._park(thread, nr, args, b)
            return True
        except OSError as e:
            # A process_vm read/write failed mid-handler. For a live
            # process that's a bad pointer: report EFAULT (never re-run a
            # simulated-kernel syscall natively — simulated side effects
            # may already have happened). For a dying process the shim is
            # gone and the reply lands nowhere anyway.
            import errno as _errno

            if self.regions is not None and e.filename:
                try:
                    where = self.regions.describe(int(e.filename, 16))
                    log.debug("%s: syscall %d EFAULT at %s",
                              self.name, nr, where)
                except (ValueError, OSError):
                    pass
            self._strace(thread, nr, args, -_errno.EFAULT)
            self._reply_complete(thread, -_errno.EFAULT)
            return False
        self._strace(thread, nr, args, ret)
        self._reply_complete(thread, ret)
        return False

    def _strace(self, thread: ManagedThread, nr: int, args, result,
                argstr: Optional[str] = None) -> None:
        if self.strace is not None:
            self.strace.log(self.host.now(), thread.tindex, nr, args, result,
                            argstr=argstr)

    def _park(self, thread: ManagedThread, nr: int, args, blocked) -> None:
        """Arm a SysCallCondition for a blocked syscall; the shim stays in
        recv until the wakeup re-dispatches and replies."""
        timeout_at = None
        if blocked.timeout_ns is not None:
            timeout_at = self.host.now() + blocked.timeout_ns
        thread.park_deadline = timeout_at
        thread.park_call = (nr, tuple(args))
        # SA_RESTART eligibility of THIS park (e.g. pause() and a
        # connect() past its first block are never restartable even when
        # the interrupting handler sets SA_RESTART)
        thread.park_restartable = blocked.restartable

        def wakeup(reason, thread=thread, nr=nr, args=tuple(args)):
            self._unpark(thread, nr, list(args), reason)

        cond = SysCallCondition(
            self.host,
            file=blocked.file,
            state_mask=blocked.state_mask,
            timeout_at_ns=timeout_at,
            wakeup=wakeup,
            allow_forever=blocked.forever,
        )
        thread.parked_condition = cond
        cond.arm()

    def _unpark(self, thread: ManagedThread, nr: int, args,
                reason: str) -> None:
        thread.parked_condition = None
        # the park is over either way; a timeout re-dispatch of
        # rt_sigtimedwait answers EAGAIN without re-reading these
        thread.sigwait_set = 0
        thread.sigwait_info_ptr = 0
        if self.state != ProcessState.RUNNING or thread.dead \
                or reason == "cancel":
            return
        # a parked poll/select holds a transient wait-epoll; release it
        self.handler._drop_wait_epoll(thread)
        if not self._handle_syscall_event(thread, nr, args, wake=reason):
            self._resume(thread)

    def _close_descriptors(self) -> None:
        try:
            self.handler.close_all()
        except Exception:
            log.warning("error closing %r descriptors at exit", self.name,
                        exc_info=True)

    def _clock_ns(self) -> int:
        """The process's observable clock: the host clock, or the shim's
        locally-advanced time when it ran ahead within the runahead bound
        (keeps slow-path time answers monotonic with fast-path ones)."""
        now = self.host.now()
        if self.proc_clock is not None:
            return max(now, self.proc_clock.sim_time_ns)
        return now

    def _publish_clock(self) -> None:
        """Refresh the shared clock before handing control to the shim
        (`continue_plugin` writing max_runahead_time, `managed_thread.rs:
        431-467`): runahead bound = current round end."""
        if self.proc_clock is None:
            return
        worker = getattr(self.host, "_worker", None)
        round_end = getattr(worker, "round_end_time", 0) or self.host.now()
        self.proc_clock.publish(self.host.now(), round_end)

    def _reply_complete(self, thread: ManagedThread, retval: int) -> None:
        self._publish_clock()
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_COMPLETE
        reply.u.complete.retval = retval
        reply.u.complete.restartable = 1
        try:
            thread.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _reply_native(self, thread: ManagedThread) -> None:
        self._publish_clock()
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_DO_NATIVE
        try:
            thread.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _reply_native_rewrite(self, thread: ManagedThread, args,
                              path_args: dict) -> None:
        """Execute natively with substituted path arguments (the per-host
        filesystem view): the shim stages each replacement string on its
        own stack and runs the raw syscall."""
        from ..interpose import EVENT_SYSCALL_DO_NATIVE_REWRITE

        self._publish_clock()
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_DO_NATIVE_REWRITE
        for i in range(6):
            reply.u.rewrite.args[i] = int(args[i]) & (2**64 - 1)
        reply.u.rewrite.path_arg[0] = -1
        reply.u.rewrite.path_arg[1] = -1
        for slot, (idx, path) in enumerate(sorted(path_args.items())):
            reply.u.rewrite.path_arg[slot] = idx
            reply.u.rewrite.path[slot].value = path  # NUL-terminated
        try:
            thread.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _on_child_death(self) -> None:
        """Watcher-thread callback: the native process died. Close every
        thread channel's writer (never free — the worker thread may be
        mid-recv on the mapping) so any blocked recv_from_shim returns
        None, and post a reap task for the case where nobody is in recv at
        all: a thread parked on an untimed condition (blocking recv/accept)
        would otherwise stay RUNNING forever, its sockets never sending
        FIN."""
        with self._ipc_lock:
            for t in self.threads:
                if t.ipc is not None:
                    t.ipc.close()
        self.host.post_cross_thread_task(
            TaskRef(lambda h: self._reap_if_parked(), "managed-death-reap")
        )

    def _reap_if_parked(self) -> None:
        """Worker-thread task: reap a process that died while its threads
        were parked. If the death was already observed (via recv returning
        None), this is a no-op."""
        if self.state != ProcessState.RUNNING:
            return
        for t in self.threads:
            t.parked_condition = None
        self._reap()

    def reap_if_native_dead(self) -> None:
        """End-of-run sweep (Manager, single-threaded): a child that died
        so close to simulation end that the watcher's posted reap task
        never got a round boundary to drain into must still be reaped, or
        the final-state check would report a dead process as running."""
        if self.state != ProcessState.RUNNING:
            return
        if self.proc is not None and self.proc.poll() is not None:
            self._reap_if_parked()
        elif self.proc is None and self._death_seen_natively():
            self._reap_if_parked()

    def _native_term_signal(self) -> Optional[int]:
        """Forked child killed by a signal: no exit_group was trapped and
        it is not waitpid-able from here (its native parent is the managed
        parent process), but the zombie's waitpid-style exit code is
        /proc/<pid>/stat field 52 — readable since the simulator has
        ptrace access to its descendants."""
        pid = self.server.native_pid
        if pid is None:
            return None
        fields = self._proc_stat_fields(pid)
        if fields is None or len(fields) < 50:
            return None
        try:
            code = int(fields[49])  # stat field 52: waitpid-style exit code
        except ValueError:
            return None
        return os.WTERMSIG(code) if os.WIFSIGNALED(code) else None

    def _death_seen_natively(self) -> bool:
        """Forked children are not our native children (their native
        parent is the managed parent process, which never native-waits),
        so a dead one lingers as a ZOMBIE — kill(pid, 0) still succeeds on
        those. Read the /proc state instead."""
        pid = self.server.native_pid
        if pid is None:
            return False
        fields = self._proc_stat_fields(pid)
        return fields is None or fields[0] in (b"Z", b"X")

    def _abort_pending_clone(self) -> None:
        """The process died between ADD_THREAD_REQ and ADD_THREAD_RES: the
        pending half-born thread (or forked-child process object) must not
        outlive it — a phantom forked child would sit RUNNING forever (it
        has no native pid for liveness sweeps to notice) and leak its IPC
        shmem block."""
        pending, self._pending_clone = self._pending_clone, None
        if pending is None:
            return
        if isinstance(pending, ManagedThread):
            with self._ipc_lock:
                if pending in self.threads:
                    self.threads.remove(pending)
                pending.dead = True
                if pending.ipc is not None:
                    pending.ipc.close()
                    pending.ipc.block.free()
                    pending.ipc = None
        else:
            pending._abort_fork()

    def _reap(self) -> None:
        if self.state not in (ProcessState.PENDING, ProcessState.RUNNING):
            return  # already reaped
        self._abort_pending_clone()
        if self.proc is not None:
            try:
                self.exit_status = self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.exit_status = self.proc.wait(timeout=5)
        else:
            # forked child: not waitpid-able from the simulator (its native
            # parent is the managed parent); the exit code was captured at
            # exit_group, signal deaths surface as None
            self.exit_status = self._exit_code
        if self.exit_status is not None and self.exit_status < 0:
            # died to an unhandled signal (SIGKILL, SIGSEGV, ...)
            self.state = ProcessState.KILLED
            self.kill_signal = -self.exit_status
        elif self.proc is None and self._exit_code is None:
            self.state = ProcessState.KILLED
            self.kill_signal = self._native_term_signal() or 9
        else:
            self.state = ProcessState.EXITED
        for t in self.threads:
            t.dead = True
        self._close_descriptors()
        self._cleanup()
        self._notify_parent()

    def _notify_parent(self) -> None:
        """Wake the parent's wait4 (`handler/wait.rs`): pulse the
        CHILD_EVENTS bit so parked conditions fire OFF_TO_ON."""
        p = self.parent
        if p is None or not p.is_alive:
            return
        p.child_waiter.update_state(FileState.CHILD_EVENTS,
                                    FileState.CHILD_EVENTS)
        p.child_waiter.update_state(FileState.CHILD_EVENTS, FileState.NONE)

    def _cleanup(self) -> None:
        if self.proc is not None:
            from .pidwatcher import get_watcher

            get_watcher().unwatch(self.proc.pid)
        elif self.server.native_pid is not None:
            from .pidwatcher import get_watcher

            get_watcher().unwatch(self.server.native_pid)
        with self._ipc_lock:
            for t in self.threads:
                if t.ipc is not None:
                    t.ipc.close()
                    t.ipc.block.free()
                    t.ipc = None
            self.ipc = None
        if self.proc_clock is not None:
            self.proc_clock.free()
            self.proc_clock = None
        for fh in (self._stdout, self._stderr):
            if fh is not None:
                fh.close()
        self._stdout = self._stderr = None
        if self.strace is not None:
            self.strace.close()
