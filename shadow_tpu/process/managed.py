"""Managed native processes: real Linux binaries under interposition.

Parity: reference `src/main/host/managed_thread.rs` + `process.rs` — spawn
the binary with the shim preloaded (`inject_preloads`,
`managed_thread.rs:546-640`), then service its syscalls over the
shared-memory IPC channel: each trapped syscall arrives as a `ShimEvent`,
and the simulator answers with an emulated result (`SyscallComplete`) or
tells the shim to execute it natively (`SyscallDoNative`) — the dispatch
split in `syscall/handler/mod.rs`.

Round-1 scope: the syscall server virtualizes *time* (clock_gettime /
gettimeofday / time / nanosleep / clock_nanosleep answered from the
simulation clock, sleeps advancing it with zero wall-time) and identity
(getpid), passes everything else through natively, and reads/writes the
managed process's memory with process_vm_readv/writev — the
`MemoryCopier` half of the reference's memory manager
(`memory_copier.rs:185,246`). Full event-loop integration (one Host task
per resume, blocking syscalls parking on conditions) is the next layer.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
import struct
import subprocess
import threading
from typing import Callable, Optional

from ..core import simtime
from ..core.event import TaskRef
from ..kernel import errors as kerrors
from .condition import SysCallCondition
from .process import ProcessState
from .syscall_handler import DispatchCtx, NativeSyscall, SyscallHandler

log = logging.getLogger("shadow_tpu.process")
from ..interpose import (
    EVENT_PROCESS_DEATH,
    EVENT_START_RES,
    EVENT_SYSCALL,
    EVENT_SYSCALL_COMPLETE,
    EVENT_SYSCALL_DO_NATIVE,
    IpcChannel,
    ShimEvent,
)

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "interpose")
SHIM_PATH = os.path.join(_DIR, "libshadow_shim.so")
PRELOAD_LIBC_PATH = os.path.join(_DIR, "libshadow_preload_libc.so")


def _preload_chain() -> str:
    """LD_PRELOAD value: libc wrappers first (so application symbol lookups
    hit them before libc), then the shim they call into
    (`inject_preloads`, `managed_thread.rs:546-640`)."""
    if os.path.exists(PRELOAD_LIBC_PATH):
        return PRELOAD_LIBC_PATH + " " + SHIM_PATH
    return SHIM_PATH

# x86_64 syscall numbers the server emulates
SYS_write = 1
SYS_getpid = 39
SYS_nanosleep = 35
SYS_kill = 62
SYS_gettimeofday = 96
SYS_time = 201
SYS_clock_gettime = 228
SYS_clock_nanosleep = 230
SYS_exit_group = 231

_libc = ctypes.CDLL(None, use_errno=True)


class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


class MemoryCopier:
    """Read/write another process's memory (`memory_copier.rs`)."""

    def __init__(self, pid: int):
        self.pid = pid

    def read(self, remote_addr: int, n: int) -> bytes:
        buf = ctypes.create_string_buffer(n)
        local = _IoVec(ctypes.cast(buf, ctypes.c_void_p), n)
        remote = _IoVec(ctypes.c_void_p(remote_addr), n)
        got = _libc.process_vm_readv(
            self.pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
        )
        if got != n:
            raise OSError(ctypes.get_errno(), "process_vm_readv failed")
        return buf.raw

    def write(self, remote_addr: int, data: bytes) -> None:
        buf = ctypes.create_string_buffer(data, len(data))
        local = _IoVec(ctypes.cast(buf, ctypes.c_void_p), len(data))
        remote = _IoVec(ctypes.c_void_p(remote_addr), len(data))
        got = _libc.process_vm_writev(
            self.pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0
        )
        if got != len(data):
            raise OSError(ctypes.get_errno(), "process_vm_writev failed")


class SyscallServer:
    """Answers one managed process's syscall stream with virtual time.

    `clock` returns the simulation time in ns; `advance` moves it forward
    (standalone use drives a plain counter; event-loop integration hands
    these to the Host)."""

    def __init__(self, *, virtual_pid: int = 1000,
                 clock: Optional[Callable[[], int]] = None,
                 advance: Optional[Callable[[int], None]] = None):
        self._vtime = 0
        self.clock = clock or (lambda: self._vtime)
        self.advance = advance or self._advance_own
        self.virtual_pid = virtual_pid
        self.native_pid: Optional[int] = None  # set once the child is spawned
        self.syscall_counts: dict[int, int] = {}
        self.mem: Optional[MemoryCopier] = None

    def _advance_own(self, delta_ns: int) -> None:
        self._vtime += delta_ns

    # -- dispatch -------------------------------------------------------

    def handle(self, nr: int, args) -> Optional[int]:
        """Returns an emulated retval, or None for native passthrough."""
        self.syscall_counts[nr] = self.syscall_counts.get(nr, 0) + 1
        if nr == SYS_getpid:
            return self.virtual_pid
        if nr == SYS_clock_gettime:
            return self._clock_gettime(args[0], args[1])
        if nr == SYS_gettimeofday:
            return self._gettimeofday(args[0])
        if nr == SYS_time:
            t = simtime.emulated_from_sim(self.clock()) // simtime.SECOND
            if args[0]:
                self.mem.write(args[0], struct.pack("<q", t))
            return t
        if nr in (SYS_nanosleep, SYS_clock_nanosleep):
            return self._nanosleep(nr, args)
        if nr == SYS_kill:
            return self._kill(args[0], args[1])
        return None  # DO_NATIVE

    def _kill(self, target: int, sig: int) -> Optional[int]:
        """kill(2) with pid translation: the process only knows virtual
        pids (getpid returns one), so a native passthrough would target an
        unrelated — or nonexistent — real process. Translate the pids we
        know; fail with ESRCH for ones we don't rather than leak a signal
        outside the simulation (`process.rs:1309` signal dispatch)."""
        import errno as _errno

        target = ctypes.c_int64(target).value  # sign-extend from u64
        if target in (self.virtual_pid, 0, -self.virtual_pid) and self.native_pid:
            try:
                os.kill(self.native_pid, sig)
            except ProcessLookupError:
                return -_errno.ESRCH
            except PermissionError:
                return -_errno.EPERM
            return 0
        return -_errno.ESRCH

    def _clock_gettime(self, clockid: int, ts_addr: int) -> int:
        now = self.clock()
        if clockid in simtime.MONOTONIC_CLOCK_IDS:
            ns = now
        else:  # REALTIME & friends observe the emulated epoch
            ns = simtime.emulated_from_sim(now)
        if ts_addr:
            self.mem.write(ts_addr, struct.pack("<qq", ns // 10**9, ns % 10**9))
        return 0

    def _gettimeofday(self, tv_addr: int) -> int:
        ns = simtime.emulated_from_sim(self.clock())
        if tv_addr:
            self.mem.write(tv_addr, struct.pack("<qq", ns // 10**9,
                                                (ns % 10**9) // 1000))
        return 0

    def _nanosleep(self, nr: int, args) -> int:
        TIMER_ABSTIME = 1
        req_addr = args[2] if nr == SYS_clock_nanosleep else args[0]
        raw = self.mem.read(req_addr, 16)
        sec, nsec = struct.unpack("<qq", raw)
        t = sec * simtime.SECOND + nsec
        if nr == SYS_clock_nanosleep and args[1] & TIMER_ABSTIME:
            # absolute deadline on the given clock; REALTIME deadlines are
            # relative to the emulated epoch
            clockid = args[0]
            now = (self.clock() if clockid in simtime.MONOTONIC_CLOCK_IDS
                   else simtime.emulated_from_sim(self.clock()))
            t -= now
        if t > 0:
            self.advance(t)
        return 0


class ManagedProcess:
    """Spawn + serve one native binary under the shim."""

    def __init__(self, argv: list[str], server: Optional[SyscallServer] = None,
                 capture_output: bool = True, env: Optional[dict] = None):
        if not os.path.exists(SHIM_PATH):
            from .. import interpose

            interpose.build()
        self.server = server or SyscallServer()
        self.ipc = IpcChannel.create()
        full_env = dict(env if env is not None else os.environ)
        # preload injection (`managed_thread.rs` inject_preloads)
        preload = full_env.get("LD_PRELOAD", "")
        full_env["LD_PRELOAD"] = (
            _preload_chain() + (" " + preload if preload else "")
        )
        full_env["SHADOW_TPU_IPC_HANDLE"] = self.ipc.block.serialize()
        self.proc = subprocess.Popen(
            argv,
            env=full_env,
            stdout=subprocess.PIPE if capture_output else None,
            stderr=subprocess.PIPE if capture_output else None,
        )
        self.server.mem = MemoryCopier(self.proc.pid)
        self.server.native_pid = self.proc.pid
        self.native_pid: Optional[int] = None
        self.death_seen = threading.Event()
        self._serve_thread = threading.Thread(target=self._serve, daemon=True)
        self._serve_thread.start()

    def _serve(self) -> None:
        while True:
            ev = self.ipc.recv_from_shim()
            if ev is None:
                return  # channel closed
            if ev.kind == EVENT_START_RES:
                self.native_pid = int(ev.u.add_thread_res.child_native_tid)
                continue
            if ev.kind == EVENT_PROCESS_DEATH:
                self.death_seen.set()
                continue
            if ev.kind != EVENT_SYSCALL:
                continue
            nr = int(ev.u.syscall.number)
            args = [int(ev.u.syscall.args[i]) for i in range(6)]
            try:
                ret = self.server.handle(nr, args)
            except OSError:
                ret = None  # memory gone (racing exit): let it run natively
            reply = ShimEvent()
            if ret is None:
                reply.kind = EVENT_SYSCALL_DO_NATIVE
            else:
                reply.kind = EVENT_SYSCALL_COMPLETE
                reply.u.complete.retval = ret
                reply.u.complete.restartable = 1
            try:
                self.ipc.send_to_shim(reply)
            except OSError:
                return

    def wait(self, timeout: Optional[float] = None):
        """Wait for exit; returns (exit_code, stdout, stderr)."""
        out, err = self.proc.communicate(timeout=timeout)
        self.ipc.close()  # unblock the server thread
        self._serve_thread.join(timeout=5)
        self.ipc.block.free()  # unlink the /dev/shm object
        return self.proc.returncode, out, err


class ManagedSimProcess:
    """A native binary coordinated by the simulation event loop.

    Parity: the reference's resume model (`managed_thread.rs:185-322`,
    `Host::resume` `host.rs:474-501`): the worker thread executing this
    host hands control to the plugin (which runs natively, sim time frozen)
    and services its syscalls inline until one *blocks*; blocking sleeps
    become scheduled host tasks that deliver the completion later, so
    emulated time advances only through the event loop.

    Round-1 syscall surface: time/identity virtualized from the host
    clock, sleeps event-scheduled, everything else native passthrough
    (network syscalls join in the next round's handler table).
    """

    def __init__(self, host, name: str, argv: list[str],
                 output_dir: Optional[str] = None):
        self.host = host
        self.name = name
        self.argv = argv
        self.pid = host.next_pid()
        self.state = ProcessState.PENDING
        self.exit_status: Optional[int] = None
        self.kill_signal: Optional[int] = None
        self.server = SyscallServer(virtual_pid=self.pid,
                                    clock=self._clock_ns)
        # the simulated-kernel dispatch table (network, readiness, sleep)
        self.handler = SyscallHandler(self)
        # the shared clock powering the in-shim time fast path
        self.proc_clock = None
        self.ipc: Optional[IpcChannel] = None
        self.proc = None
        self._death_seen = False
        self._output_dir = output_dir
        self._stdout = self._stderr = None
        # park state for a blocked syscall (`SysCallCondition` trigger)
        self._parked_condition = None
        self._park_deadline: Optional[int] = None
        # Serializes IPC close/free between the worker thread (cleanup) and
        # the ChildPidWatcher thread (death callback): the callback must
        # never touch a freed shmem mapping.
        self._ipc_lock = threading.Lock()
        host.processes.append(self)

    @property
    def is_alive(self) -> bool:
        return self.state in (ProcessState.PENDING, ProcessState.RUNNING)

    # -- lifecycle ------------------------------------------------------

    def spawn(self) -> None:
        assert self.state == ProcessState.PENDING
        if not os.path.exists(SHIM_PATH):
            from .. import interpose

            interpose.build()
        self.ipc = IpcChannel.create()
        env = dict(os.environ)
        preload = env.get("LD_PRELOAD", "")
        env["LD_PRELOAD"] = _preload_chain() + (" " + preload if preload else "")
        env["SHADOW_TPU_IPC_HANDLE"] = self.ipc.block.serialize()
        # shared clock block: the shim answers clock_gettime/gettimeofday/
        # time locally from it, zero IPC round trips (`shim_sys.c:25-80`)
        from ..interpose import ProcessClock

        self.proc_clock = ProcessClock()
        latency = 0
        if getattr(self.host, "model_unblocked_syscall_latency", False):
            exp = getattr(self.host, "config_experimental", None)
            latency = getattr(exp, "unblocked_syscall_latency", 1000) or 0
        self.proc_clock.configure(
            simtime.EMUTIME_SIMULATION_START_UNIX_NS, latency
        )
        env["SHADOW_TPU_SHMEM_HANDLE"] = self.proc_clock.serialize()
        if self._output_dir:
            os.makedirs(self._output_dir, exist_ok=True)
            self._stdout = open(os.path.join(self._output_dir,
                                             f"{self.name}.stdout"), "wb")
            self._stderr = open(os.path.join(self._output_dir,
                                             f"{self.name}.stderr"), "wb")
        self.proc = subprocess.Popen(
            self.argv, env=env,
            stdout=self._stdout or subprocess.DEVNULL,
            stderr=self._stderr or subprocess.DEVNULL,
        )
        self.server.mem = MemoryCopier(self.proc.pid)
        self.server.native_pid = self.proc.pid
        self.state = ProcessState.RUNNING
        # Liveness guarantee (`childpid_watcher.rs`): if the child dies
        # without the shim destructor running (SIGKILL, segfault), close
        # the IPC writer so a recv_from_shim blocked on the worker thread
        # returns instead of deadlocking the simulation.
        from .pidwatcher import get_watcher

        get_watcher().watch(self.proc.pid, self._on_child_death)
        self._resume()

    def stop(self, signal_nr: int = 15) -> None:
        if self.state != ProcessState.RUNNING or self.proc is None:
            return
        self.proc.send_signal(signal_nr)
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)
        self.state = ProcessState.KILLED
        self.kill_signal = signal_nr
        if self._parked_condition is not None:
            cond, self._parked_condition = self._parked_condition, None
            cond.cancel()
        self._close_descriptors()
        self._cleanup()

    # -- the inline resume loop ----------------------------------------

    def _resume(self) -> None:
        """Service the plugin until it blocks or dies (runs on the worker
        thread currently executing this host, like the reference
        `managed_thread.rs:185-322` resume loop)."""
        while True:
            ev = self.ipc.recv_from_shim()
            if ev is None:
                self._reap()
                return
            if ev.kind == EVENT_START_RES:
                continue
            if ev.kind == EVENT_PROCESS_DEATH:
                self._death_seen = True
                continue
            if ev.kind != EVENT_SYSCALL:
                continue
            nr = int(ev.u.syscall.number)
            args = [int(ev.u.syscall.args[i]) for i in range(6)]

            if nr == SYS_exit_group:
                # close simulated descriptors (FINs go out, ports free) and
                # let the exit run natively
                self._close_descriptors()
                self._reply_native()
                self._reap()
                return

            if self._handle_syscall_event(nr, args):
                return  # parked on a condition; no reply yet

    def _handle_syscall_event(self, nr: int, args, wake=None) -> bool:
        """Dispatch one trapped syscall. Returns True when the process
        parked (the shim gets its reply when the condition fires)."""
        ctx = DispatchCtx(wake, self._park_deadline if wake else None)
        try:
            ret = self.handler.dispatch(nr, args, ctx)
        except NativeSyscall:
            # not simulated-kernel territory: time/identity emulation, then
            # native passthrough
            try:
                ret2 = self.server.handle(nr, args)
            except OSError:
                ret2 = None  # memory gone (racing exit): run it natively
            if ret2 is None:
                self._reply_native()
            else:
                self._reply_complete(ret2)
            return False
        except kerrors.SyscallError as e:
            self._reply_complete(-e.errno)
            return False
        except kerrors.Blocked as b:
            self._park(nr, args, b)
            return True
        except OSError:
            # A process_vm read/write failed mid-handler. For a live
            # process that's a bad pointer: report EFAULT (never re-run a
            # simulated-kernel syscall natively — simulated side effects
            # may already have happened). For a dying process the shim is
            # gone and the reply lands nowhere anyway.
            import errno as _errno

            self._reply_complete(-_errno.EFAULT)
            return False
        self._reply_complete(ret)
        return False

    def _park(self, nr: int, args, blocked) -> None:
        """Arm a SysCallCondition for a blocked syscall; the shim stays in
        recv until the wakeup re-dispatches and replies."""
        timeout_at = None
        if blocked.timeout_ns is not None:
            timeout_at = self.host.now() + blocked.timeout_ns
        self._park_deadline = timeout_at

        def wakeup(reason, nr=nr, args=tuple(args)):
            self._unpark(nr, list(args), reason)

        cond = SysCallCondition(
            self.host,
            file=blocked.file,
            state_mask=blocked.state_mask,
            timeout_at_ns=timeout_at,
            wakeup=wakeup,
        )
        self._parked_condition = cond
        cond.arm()

    def _unpark(self, nr: int, args, reason: str) -> None:
        self._parked_condition = None
        if self.state != ProcessState.RUNNING or reason == "cancel":
            return
        # a parked poll/select holds a transient wait-epoll; release it
        self.handler._drop_wait_epoll()
        if not self._handle_syscall_event(nr, args, wake=reason):
            self._resume()

    def _close_descriptors(self) -> None:
        try:
            self.handler.close_all()
        except Exception:
            log.warning("error closing %r descriptors at exit", self.name,
                        exc_info=True)

    def _clock_ns(self) -> int:
        """The process's observable clock: the host clock, or the shim's
        locally-advanced time when it ran ahead within the runahead bound
        (keeps slow-path time answers monotonic with fast-path ones)."""
        now = self.host.now()
        if self.proc_clock is not None:
            return max(now, self.proc_clock.sim_time_ns)
        return now

    def _publish_clock(self) -> None:
        """Refresh the shared clock before handing control to the shim
        (`continue_plugin` writing max_runahead_time, `managed_thread.rs:
        431-467`): runahead bound = current round end."""
        if self.proc_clock is None:
            return
        worker = getattr(self.host, "_worker", None)
        round_end = getattr(worker, "round_end_time", 0) or self.host.now()
        self.proc_clock.publish(self.host.now(), round_end)

    def _reply_complete(self, retval: int) -> None:
        self._publish_clock()
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_COMPLETE
        reply.u.complete.retval = retval
        reply.u.complete.restartable = 1
        try:
            self.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _reply_native(self) -> None:
        self._publish_clock()
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_DO_NATIVE
        try:
            self.ipc.send_to_shim(reply)
        except OSError:
            pass

    def _on_child_death(self) -> None:
        """Watcher-thread callback: the child died. Close the channel
        writers (never free — the worker thread may be mid-recv on the
        mapping) so any blocked recv_from_shim returns None, and post a
        reap task for the case where nobody is in recv at all: a process
        parked on an untimed condition (blocking recv/accept) would
        otherwise stay RUNNING forever, its sockets never sending FIN."""
        with self._ipc_lock:
            if self.ipc is not None:
                self.ipc.close()
        self.host.post_cross_thread_task(
            TaskRef(lambda h: self._reap_if_parked(), "managed-death-reap")
        )

    def _reap_if_parked(self) -> None:
        """Worker-thread task: reap a child that died while parked. If the
        death was already observed (via recv returning None), this is a
        no-op."""
        if self.state != ProcessState.RUNNING:
            return
        if self._parked_condition is not None:
            # drop the condition; if it fires later, _unpark's state check
            # discards the wakeup
            self._parked_condition = None
        self._reap()

    def reap_if_native_dead(self) -> None:
        """End-of-run sweep (Manager, single-threaded): a child that died
        so close to simulation end that the watcher's posted reap task
        never got a round boundary to drain into must still be reaped, or
        the final-state check would report a dead process as running."""
        if self.state == ProcessState.RUNNING and self.proc is not None \
                and self.proc.poll() is not None:
            self._reap_if_parked()

    def _reap(self) -> None:
        try:
            self.exit_status = self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.exit_status = self.proc.wait(timeout=5)
        if self.exit_status is not None and self.exit_status < 0:
            # died to an unhandled signal (SIGKILL, SIGSEGV, ...)
            self.state = ProcessState.KILLED
            self.kill_signal = -self.exit_status
        else:
            self.state = ProcessState.EXITED
        self._close_descriptors()
        self._cleanup()

    def _cleanup(self) -> None:
        if self.proc is not None:
            from .pidwatcher import get_watcher

            get_watcher().unwatch(self.proc.pid)
        with self._ipc_lock:
            if self.ipc is not None:
                self.ipc.close()
                self.ipc.block.free()
                self.ipc = None
        if self.proc_clock is not None:
            self.proc_clock.free()
            self.proc_clock = None
        for fh in (self._stdout, self._stderr):
            if fh is not None:
                fh.close()
        self._stdout = self._stderr = None
