"""Managed-process memory-region bookkeeping.

Parity: reference `src/main/host/memory_manager/mod.rs:616-709` — the
memory manager tracks every mapping (heap, stack, anonymous, file-backed)
in an interval map, updated on the brk/mmap/munmap/mprotect/mremap
syscalls, as the foundation for pointer validation and the zero-copy
MemoryMapper (`memory_mapper.rs`).

This rebuild keeps syscall argument access on process_vm_readv/writev
(`MemoryCopier`), so exact mutation-by-mutation replay of the reference's
bookkeeping isn't load-bearing; instead the region table is parsed from
/proc/<pid>/maps (the kernel's own authoritative interval map, the same
source the reference seeds from — `proc_maps.rs`) and invalidated when a
mapping syscall passes through the dispatch path. Queries re-parse at most
once per invalidation.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Optional

# mapping-mutating syscalls (x86_64) that invalidate the table
SYS_mmap = 9
SYS_mprotect = 10
SYS_munmap = 11
SYS_brk = 12
SYS_mremap = 25
SYS_shmat = 30
SYS_shmdt = 67

MAPPING_SYSCALLS = frozenset((
    SYS_mmap, SYS_mprotect, SYS_munmap, SYS_brk, SYS_mremap,
    SYS_shmat, SYS_shmdt,
))


@dataclass(frozen=True)
class Region:
    """One mapping, `[start, end)` (`memory_manager/mod.rs` Region)."""

    start: int
    end: int
    read: bool
    write: bool
    execute: bool
    private: bool
    path: str  # "", "[heap]", "[stack]", "/lib/...", ...

    @property
    def kind(self) -> str:
        if self.path == "[heap]":
            return "heap"
        if self.path.startswith("[stack"):
            return "stack"
        if self.path.startswith("["):
            return "special"  # vdso/vvar/vsyscall
        return "file" if self.path else "anonymous"

    def __len__(self) -> int:
        return self.end - self.start


class MemoryRegions:
    """Interval map over a live process's mappings, lazily refreshed."""

    def __init__(self, pid: int):
        self.pid = pid
        self._regions: list[Region] = []
        self._starts: list[int] = []
        self._dirty = True
        self.invalidations = 0  # observed mapping syscalls (stats/tests)

    # -- lifecycle -------------------------------------------------------

    def mark_dirty(self) -> None:
        """A mapping syscall passed through dispatch; re-parse on the
        next query (`mod.rs:616-709` handle_brk/mmap/... analogue)."""
        self._dirty = True
        self.invalidations += 1

    def refresh(self) -> None:
        regions = []
        try:
            with open(f"/proc/{self.pid}/maps") as fh:
                for line in fh:
                    parts = line.split(maxsplit=5)
                    if len(parts) < 5:
                        continue
                    span, perms = parts[0], parts[1]
                    lo, _, hi = span.partition("-")
                    regions.append(Region(
                        start=int(lo, 16),
                        end=int(hi, 16),
                        read=perms[0] == "r",
                        write=perms[1] == "w",
                        execute=perms[2] == "x",
                        private=perms[3] == "p",
                        path=parts[5].strip() if len(parts) > 5 else "",
                    ))
        except OSError:
            regions = []  # process gone; empty table
        self._regions = regions
        self._starts = [r.start for r in regions]
        self._dirty = False

    def _table(self) -> list[Region]:
        if self._dirty:
            self.refresh()
        return self._regions

    # -- queries ---------------------------------------------------------

    def region_at(self, addr: int) -> Optional[Region]:
        table = self._table()
        i = bisect.bisect_right(self._starts, addr) - 1
        if i >= 0 and table[i].start <= addr < table[i].end:
            return table[i]
        return None

    def regions(self) -> list[Region]:
        return list(self._table())

    def heap(self) -> Optional[Region]:
        return next((r for r in self._table() if r.kind == "heap"), None)

    def stack(self) -> Optional[Region]:
        return next((r for r in self._table() if r.kind == "stack"), None)

    def _span_ok(self, addr: int, n: int, need_write: bool) -> bool:
        """True when [addr, addr+n) is fully covered by mappings with the
        required permission (contiguous regions compose)."""
        if n <= 0:
            return n == 0
        end = addr + n
        pos = addr
        while pos < end:
            r = self.region_at(pos)
            if r is None or not r.read or (need_write and not r.write):
                return False
            pos = r.end
        return True

    def is_readable(self, addr: int, n: int) -> bool:
        return self._span_ok(addr, n, need_write=False)

    def is_writable(self, addr: int, n: int) -> bool:
        return self._span_ok(addr, n, need_write=True)

    def describe(self, addr: int) -> str:
        """Human-readable locator for fault diagnostics."""
        r = self.region_at(addr)
        if r is None:
            return f"0x{addr:x} (unmapped)"
        perms = "".join((
            "r" if r.read else "-", "w" if r.write else "-",
            "x" if r.execute else "-"))
        where = r.path or r.kind
        return f"0x{addr:x} ({perms} {where} 0x{r.start:x}-0x{r.end:x})"
