"""ChildPidWatcher: detect managed-process death and unblock the simulator.

Parity: reference `src/main/utility/childpid_watcher.rs` — a dedicated
thread epoll-waits on one pidfd per watched child; when a pidfd becomes
readable (the process died), registered callbacks run, whose job is to
close the IPC channel writer so a simulator thread blocked in
`recv_from_shim` wakes with WriterIsClosed instead of hanging forever
(`managed_thread.rs:444-447`). This is the only liveness mechanism that
covers SIGKILL and crashes, where the shim's destructor (which normally
announces PROCESS_DEATH) never runs.
"""

from __future__ import annotations

import logging
import os
import select
import threading
from typing import Callable, Optional

log = logging.getLogger("shadow_tpu.process")

# os.pidfd_open exists on Linux 5.3+ / Python 3.9+; fall back to a
# waitpid-polling thread per child if unavailable.
_HAVE_PIDFD = hasattr(os, "pidfd_open")


class ChildPidWatcher:
    """One epoll thread watching every managed child's pidfd."""

    def __init__(self):
        self._lock = threading.Lock()
        self._callbacks: dict[int, tuple[int, Callable[[], None]]] = {}  # pid -> (pidfd, cb)
        self._epoll: Optional[select.epoll] = None
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_w, False)
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._epoll = select.epoll()
            self._epoll.register(self._wake_r, select.EPOLLIN)
            self._shutdown = False
            self._thread = threading.Thread(
                target=self._run, name="child-pid-watcher", daemon=True
            )
            self._thread.start()

    def watch(self, pid: int, callback: Callable[[], None]) -> None:
        """Invoke `callback` (on the watcher thread) when `pid` dies.

        The callback must be safe to call while another thread is blocked
        on the resource it releases (it closes an IPC channel writer)."""
        if not _HAVE_PIDFD:
            t = threading.Thread(
                target=self._poll_fallback, args=(pid, callback), daemon=True
            )
            t.start()
            return
        with self._lock:
            self._ensure_thread()
            try:
                pidfd = os.pidfd_open(pid)
            except ProcessLookupError:
                # already dead: fire immediately (off-thread, like the
                # reference's register-after-death path)
                threading.Thread(target=callback, daemon=True).start()
                return
            self._callbacks[pid] = (pidfd, callback)
            self._epoll.register(pidfd, select.EPOLLIN)
        self._wake()

    def watched_pids(self) -> list[int]:
        """Pids with a live death-watch — i.e. children the watcher has
        NOT yet seen die. The round watchdog's blame collector reads
        this to mark which of a hung host's processes were still alive
        when the watchdog fired (faults/watchdog.py)."""
        with self._lock:
            return sorted(self._callbacks)

    def unwatch(self, pid: int) -> None:
        with self._lock:
            entry = self._callbacks.pop(pid, None)
            if entry is None:
                return
            pidfd, _ = entry
            try:
                self._epoll.unregister(pidfd)
            except (OSError, ValueError):
                pass
            os.close(pidfd)
        self._wake()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except BlockingIOError:
            pass

    def _run(self) -> None:
        while True:
            try:
                events = self._epoll.poll()
            except (OSError, ValueError):
                return
            fired: list[Callable[[], None]] = []
            with self._lock:
                if self._shutdown:
                    return
                for fd, _mask in events:
                    if fd == self._wake_r:
                        try:
                            os.read(self._wake_r, 4096)
                        except OSError:
                            pass
                        continue
                    for pid, (pidfd, cb) in list(self._callbacks.items()):
                        if pidfd == fd:
                            fired.append(cb)
                            del self._callbacks[pid]
                            try:
                                self._epoll.unregister(pidfd)
                            except (OSError, ValueError):
                                pass
                            os.close(pidfd)
            for cb in fired:
                try:
                    cb()
                except Exception:
                    # a failing death-callback must not kill the watcher
                    # thread (other children still need their wakeups),
                    # but it may leave a worker blocked forever — say so
                    log.error(
                        "child-death callback raised; a simulator thread "
                        "may stay blocked on this child's IPC channel",
                        exc_info=True)

    def _poll_fallback(self, pid: int, callback: Callable[[], None]) -> None:
        """No pidfd support: block in waitid(WNOWAIT) — it returns as soon
        as the child exits but leaves the zombie for subprocess.Popen's own
        waitpid to reap. (A kill(pid, 0) poll would NOT work: it succeeds
        on zombies, and the reaping wait() only runs after this callback
        unblocks the worker thread — a circular wait.)"""
        try:
            os.waitid(os.P_PID, pid, os.WEXITED | os.WNOWAIT)
        except (ChildProcessError, OSError):
            pass  # already reaped or not our child: treat as dead
        callback()


_watcher: Optional[ChildPidWatcher] = None
_watcher_lock = threading.Lock()


def get_watcher() -> ChildPidWatcher:
    """The process-wide watcher (the reference keeps one in WorkerShared)."""
    global _watcher
    with _watcher_lock:
        if _watcher is None:
            _watcher = ChildPidWatcher()
        return _watcher
