"""Emulated processes: Python coroutines scheduled on the host event loop.

Parity: reference `src/main/host/process.rs` — virtual PIDs from 1000,
process lifecycle (spawn → running → zombie/exited), exit status, signal
stop, `expected_final_state` checking (`configuration.rs:614`) — with the
execution model adapted: where Shadow resumes a *native* thread over IPC
until its next syscall (`managed_thread.rs:185-322`), this plane resumes a
*generator* until it yields its next blocking point. The blocking contract
is identical: a syscall either completes, fails with errno, or parks the
process on a `SysCallCondition` (file-state × timeout), and a fired
condition schedules the resume task (`syscall_condition.c`).

Applications are generator functions `app(api, *args)` written against the
`Syscalls` facade, e.g.::

    def client(api):
        s = api.tcp_socket()
        yield from api.connect(s, ("server", 80))
        yield from api.send_all(s, b"GET /")
        data = yield from api.recv(s)
        api.close(s)

`yield from` marks every potential block point; everything else is plain
Python running to completion inside one host event (the discrete-event
abstraction: emulated time does not advance during a burst of user code).
"""

from __future__ import annotations

import enum
import ipaddress
import logging
from typing import Callable, Generator, Optional

from ..kernel import errors
from ..kernel.socket.tcp import TcpSocket
from ..kernel.socket.udp import UdpSocket
from ..kernel.status import FileState
from .condition import SysCallCondition

log = logging.getLogger("shadow_tpu.process")


class ProcessState(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    EXITED = "exited"
    KILLED = "killed"


class SimProcess:
    """One emulated process = one driver around an application generator."""

    def __init__(self, host, name: str, app: Callable, args: tuple = (),
                 pid: Optional[int] = None):
        self.host = host
        self.name = name
        self.pid = pid if pid is not None else host.next_pid()
        self.pgid = 1  # init's group/session (`process.rs:1092-1094`)
        self.sid = 1
        self.state = ProcessState.PENDING
        self.exit_status: Optional[int] = None
        self.kill_signal: Optional[int] = None
        self._app = app
        self._args = args
        self._gen: Optional[Generator] = None
        self._condition: Optional[SysCallCondition] = None
        self.api = Syscalls(self)
        host.processes.append(self)

    # -- lifecycle -----------------------------------------------------

    def spawn(self) -> None:
        """Start running (called from the start-time task)."""
        assert self.state == ProcessState.PENDING
        self.state = ProcessState.RUNNING
        self._gen = self._app(self.api, *self._args)
        if self._gen is None or not hasattr(self._gen, "send"):
            # plain function: ran to completion synchronously
            self._finish(0)
            return
        self._advance(None)

    def stop(self, signal: int = 15) -> None:
        """Deliver a terminating signal (SIGTERM default, like the
        config's shutdown_signal)."""
        if self.state != ProcessState.RUNNING:
            return
        self.state = ProcessState.KILLED
        self.kill_signal = signal
        if self._condition is not None:
            cond, self._condition = self._condition, None
            cond.cancel()
        gen, self._gen = self._gen, None
        if gen is not None:
            gen.close()

    @property
    def is_alive(self) -> bool:
        return self.state in (ProcessState.PENDING, ProcessState.RUNNING)

    def _finish(self, status: int) -> None:
        self.state = ProcessState.EXITED
        self.exit_status = status
        self._gen = None
        self._condition = None

    # -- the resume loop -----------------------------------------------

    def _advance(self, wake_reason: Optional[str]) -> None:
        """Resume the generator until its next block point.

        Mirrors `Thread::resume` returning Blocked(condition) vs exited:
        the generator yields `errors.Blocked` values; StopIteration is
        process exit."""
        if self.state != ProcessState.RUNNING:
            return
        self._condition = None
        try:
            blocked = self._gen.send(wake_reason)
        except StopIteration as stop:
            self._finish(stop.value if isinstance(stop.value, int) else 0)
            return
        except errors.Blocked:
            # A blocking op was *raised* instead of yielded — an app bug the
            # generator contract can't express; surface it loudly.
            log.warning(
                "process %r raised Blocked instead of yielding it; blocking "
                "ops must be driven with `yield from api....`",
                self.name, exc_info=True,
            )
            self._finish(1)
            return
        except Exception:
            # Any uncaught app error (errno, assertion, bug) is an abnormal
            # exit of THIS process, never a simulator crash — the analogue
            # of a plugin error, which the reference logs (`worker.rs:589-604`).
            log.warning(
                "process %r exited abnormally with an uncaught exception",
                self.name, exc_info=True,
            )
            self._finish(1)
            return
        if not isinstance(blocked, errors.Blocked):
            raise TypeError(
                f"process {self.name!r} yielded {blocked!r}; apps must yield "
                "errors.Blocked (use the Syscalls api helpers)"
            )
        timeout_at = None
        if blocked.timeout_ns is not None:
            timeout_at = self.host.now() + blocked.timeout_ns
        self._condition = SysCallCondition(
            self.host,
            file=blocked.file,
            state_mask=blocked.state_mask,
            timeout_at_ns=timeout_at,
            wakeup=self._advance,
        )
        self._condition.arm()


class Syscalls:
    """The simulated-syscall facade handed to applications.

    Non-blocking operations return plain values; potentially-blocking ones
    are generators used with `yield from`. Retry loops mirror the
    reference's restart semantics (`SyscallError::new_blocked` + resume
    re-dispatching the syscall)."""

    def __init__(self, process: SimProcess):
        self.process = process
        self.host = process.host

    # -- non-blocking --------------------------------------------------

    def tcp_socket(self) -> TcpSocket:
        return TcpSocket(self.host)

    def udp_socket(self) -> UdpSocket:
        return UdpSocket(self.host)

    def pipe(self):
        from ..kernel.pipe import make_pipe

        return make_pipe()

    def eventfd(self, initval: int = 0, semaphore: bool = False):
        from ..kernel.eventfd import EventFd

        return EventFd(initval, semaphore)

    def timerfd(self):
        from ..kernel.timerfd import TimerFd

        return TimerFd(self.host)

    def epoll(self):
        from ..kernel.epoll import Epoll

        return Epoll()

    def epoll_wait(self, ep, max_events: int = 64):
        """Blocking epoll_wait (generator)."""
        return ep.wait(max_events)

    def close(self, f) -> None:
        f.close()

    def now(self) -> int:
        return self.host.now()

    def gethostbyname(self, name: str) -> str:
        ip = self.host.dns_lookup(name)
        if ip is None:
            raise errors.SyscallError(errors.ENOENT, f"unknown host {name}")
        return ip

    def getpid(self) -> int:
        return self.process.pid

    # -- blocking ------------------------------------------------------

    def sleep(self, duration_ns: int):
        yield errors.Blocked(None, FileState.NONE, timeout_ns=duration_ns)

    def _resolve(self, name_or_ip: str) -> str:
        """Hostname or IPv4 literal -> IPv4 literal, via simulated DNS."""
        try:
            return str(ipaddress.IPv4Address(name_or_ip))
        except ValueError:
            return self.gethostbyname(name_or_ip)

    def connect(self, sock: TcpSocket, addr: tuple[str, int]):
        """Blocking TCP connect; resolves hostnames through simulated DNS."""
        ip = self._resolve(addr[0])
        try:
            sock.connect((ip, addr[1]))
        except errors.Blocked as b:
            yield b
        except errors.SyscallError as e:
            if e.errno != errors.EINPROGRESS:
                raise
            yield errors.Blocked(sock, FileState.SOCKET_ALLOWING_CONNECT)
        if sock.conn is not None and sock.conn.error is not None:
            raise errors.SyscallError(sock.conn.error)

    def accept(self, listener: TcpSocket):
        while True:
            try:
                return listener.accept()
            except errors.Blocked as b:
                yield b
            except errors.SyscallError as e:
                if e.errno != errors.EWOULDBLOCK:
                    raise
                yield errors.Blocked(listener, FileState.READABLE)

    def recv(self, sock, max_bytes: int = 65536):
        while True:
            try:
                return sock.recv(max_bytes)
            except errors.Blocked as b:
                yield b
            except errors.SyscallError as e:
                if e.errno != errors.EWOULDBLOCK:
                    raise
                yield errors.Blocked(sock, FileState.READABLE)

    def recvfrom(self, sock: UdpSocket):
        while True:
            try:
                return sock.recvfrom()
            except errors.Blocked as b:
                yield b
            except errors.SyscallError as e:
                if e.errno != errors.EWOULDBLOCK:
                    raise
                yield errors.Blocked(sock, FileState.READABLE)

    def send(self, sock, data: bytes):
        while True:
            try:
                return sock.send(data)
            except errors.Blocked as b:
                yield b
            except errors.SyscallError as e:
                if e.errno != errors.EWOULDBLOCK:
                    raise
                yield errors.Blocked(sock, FileState.WRITABLE)

    def send_all(self, sock, data: bytes):
        sent = 0
        while sent < len(data):
            sent += yield from self.send(sock, data[sent:])
        return sent

    def sendto(self, sock: UdpSocket, data: bytes, addr: tuple[str, int]):
        ip = self._resolve(addr[0])
        while True:
            try:
                return sock.sendto(data, (ip, addr[1]))
            except errors.Blocked as b:
                yield b
            except errors.SyscallError as e:
                if e.errno != errors.EWOULDBLOCK:
                    raise
                yield errors.Blocked(sock, FileState.WRITABLE)

    def recv_exact(self, sock, n: int):
        chunks, got = [], 0
        while got < n:
            data = yield from self.recv(sock, n - got)
            if not data:
                break  # EOF
            chunks.append(data)
            got += len(data)
        return b"".join(chunks)
