"""Per-process strace logging for managed processes.

Parity: reference `src/lib/syscall-logger/src/lib.rs` (the `#[log_syscall]`
attribute on every handler) with the `strace_logging_mode` knob from
`configuration.rs:1163`: `off` | `standard` | `deterministic`.

`deterministic` exists so two runs of the same seed produce byte-identical
.strace files (the reference's determinism CI diffs them): pointer-valued
arguments come from the managed process's ASLR'd address space and differ
run to run, so they are masked as `<ptr>`; everything else — simulated
timestamps, stable per-process thread ordinals, syscall numbers, fds,
lengths, return values — is deterministic under the simulator. The
pointer heuristic is the 2^32 line: x86_64 PIE/mmap/stack addresses all
live far above it, while fds, lengths, flags, and counts live below.
KNOWN LIMIT: a -no-pie binary's brk heap sits below 4 GiB with a
randomized base, so its heap pointers evade the mask — build managed
binaries as PIE (the default everywhere current) for deterministic
traces.

`standard` additionally prints raw pointer values (useful for debugging a
single run, diffable only with itself).
"""

from __future__ import annotations

from typing import Optional

from ..core import simtime
from . import syscall_handler as sh

# reverse map of the SYS_* constants the handler module declares, plus the
# process-family syscalls managed.py intercepts before dispatch
SYSCALL_NAMES = {
    v: k[4:]
    for k, v in vars(sh).items()
    if k.startswith("SYS_") and isinstance(v, int)
}
SYSCALL_NAMES.update({35: "nanosleep", 39: "getpid", 56: "clone",
                      57: "fork", 58: "vfork", 60: "exit", 62: "kill",
                      96: "gettimeofday", 201: "time", 228: "clock_gettime",
                      230: "clock_nanosleep", 231: "exit_group"})

_PTR_FLOOR = 1 << 32


class StraceLogger:
    """One .strace file per managed process."""

    def __init__(self, path: str, mode: str):
        if mode not in ("standard", "deterministic"):
            raise ValueError(
                f"strace_logging_mode must be off|standard|deterministic, "
                f"got {mode!r}")
        self.path = path
        self.mode = mode
        self._fh = None

    def _arg(self, v: int) -> str:
        if self.mode == "deterministic" and v >= _PTR_FLOOR:
            return "<ptr>"
        return hex(v) if v >= _PTR_FLOOR else str(v)

    def log(self, now_ns: int, tindex: int, nr: int, args, result) -> None:
        if self._fh is None:
            self._fh = open(self.path, "w", buffering=1 << 16)
        name = SYSCALL_NAMES.get(nr, f"syscall_{nr}")
        sec, rem = divmod(now_ns, simtime.SECOND)
        h, s = divmod(sec, 3600)
        m, s = divmod(s, 60)
        rendered = ", ".join(self._arg(int(a) & (2**64 - 1)) for a in args)
        if isinstance(result, str):
            res = result
        elif self.mode == "deterministic" and result >= _PTR_FLOOR:
            res = "<ptr>"
        else:
            res = str(result)
        self._fh.write(
            f"{h:02d}:{m:02d}:{s:02d}.{rem:09d} [t{tindex}] "
            f"{name}({rendered}) = {res}\n"
        )

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def make_logger(output_dir: Optional[str], proc_name: str,
                mode: str) -> Optional[StraceLogger]:
    if mode not in (None, "", "off", "standard", "deterministic"):
        raise ValueError(
            f"strace_logging_mode must be off|standard|deterministic, "
            f"got {mode!r}")
    if mode in (None, "", "off") or output_dir is None:
        return None
    import os

    os.makedirs(output_dir, exist_ok=True)
    return StraceLogger(os.path.join(output_dir, f"{proc_name}.strace"),
                        mode)
