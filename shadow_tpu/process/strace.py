"""Per-process strace logging for managed processes.

Parity: reference `src/lib/syscall-logger/src/lib.rs` (the `#[log_syscall]`
attribute on every handler) with the `strace_logging_mode` knob from
`configuration.rs:1163`: `off` | `standard` | `deterministic`.

`deterministic` exists so two runs of the same seed produce byte-identical
.strace files (the reference's determinism CI diffs them): pointer-valued
arguments come from the managed process's ASLR'd address space and differ
run to run, so they are masked as `<ptr>`; everything else — simulated
timestamps, stable per-process thread ordinals, syscall numbers, fds,
lengths, return values — is deterministic under the simulator. The
pointer heuristic is the 2^32 line: x86_64 PIE/mmap/stack addresses all
live far above it, while fds, lengths, flags, and counts live below.
KNOWN LIMIT: a -no-pie binary's brk heap sits below 4 GiB with a
randomized base, so its heap pointers evade the mask — build managed
binaries as PIE (the default everywhere current) for deterministic
traces.

`standard` additionally prints raw pointer values (useful for debugging a
single run, diffable only with itself).
"""

from __future__ import annotations

from typing import Optional

from ..core import simtime
from . import syscall_handler as sh

# reverse map of the SYS_* constants the handler module declares, plus the
# process-family syscalls managed.py intercepts before dispatch
SYSCALL_NAMES = {
    v: k[4:]
    for k, v in vars(sh).items()
    if k.startswith("SYS_") and isinstance(v, int)
}
SYSCALL_NAMES.update({35: "nanosleep", 39: "getpid", 56: "clone",
                      57: "fork", 58: "vfork", 60: "exit", 62: "kill",
                      96: "gettimeofday", 201: "time", 228: "clock_gettime",
                      230: "clock_nanosleep", 231: "exit_group"})

_PTR_FLOOR = 1 << 32
_TID_RESULTS = frozenset({56, 435})  # clone, clone3: native tids
# mapping-family syscalls: lengths/sizes are ASLR-DERIVED (glibc trims
# thread stacks and arenas to boundaries computed from randomized
# bases), so even sub-2^32 arguments differ run to run; deterministic
# mode renders the whole argument list as <mem>
_MEM_SYSCALLS = frozenset({9, 10, 11, 12, 25, 26, 28})  # mmap..brk..madvise
# x86_64 argument counts: the trap delivers all six registers, but slots
# past a syscall's real arity carry STALE CALLER REGISTERS — run-to-run
# noise. Deterministic mode prints only the real arguments, and elides
# the argument list entirely ("...") for syscalls whose arity it
# doesn't know.
_ARG_COUNTS = {
    0: 3, 1: 3, 2: 3, 3: 1, 4: 2, 5: 2, 6: 2, 7: 3, 8: 3, 12: 1,
    13: 4, 14: 4, 16: 3, 17: 4, 18: 4, 19: 3, 20: 3, 21: 2, 22: 1,
    23: 5, 24: 0, 32: 1, 33: 2, 34: 0, 35: 2, 36: 2, 37: 1, 38: 3,
    39: 0, 40: 4, 41: 3, 42: 3, 43: 3, 44: 6, 45: 6, 46: 3, 47: 3,
    48: 2, 49: 3, 50: 2, 51: 3, 52: 3, 53: 4, 54: 5, 55: 5, 56: 5,
    57: 0, 58: 0, 59: 3, 60: 1, 61: 4, 62: 2, 63: 1, 72: 3, 73: 2,
    74: 1, 75: 1, 76: 2, 77: 2, 79: 2, 80: 1, 81: 1, 82: 2, 83: 2,
    84: 1, 86: 2, 87: 1, 88: 2, 89: 3, 90: 2, 91: 2, 92: 3, 93: 3,
    94: 3, 95: 1, 96: 2, 97: 2, 98: 2, 99: 1, 100: 1, 102: 0, 104: 0,
    105: 1, 106: 1, 107: 0, 108: 0, 109: 2, 110: 0, 111: 0, 112: 0,
    115: 2, 116: 2, 117: 3, 118: 3, 119: 3, 120: 3, 121: 1, 124: 1,
    128: 4, 130: 2, 131: 2, 137: 2, 138: 2, 140: 2, 141: 3, 143: 2,
    144: 3, 145: 1, 149: 2, 150: 2, 151: 1, 152: 0, 160: 2, 161: 1,
    164: 2, 165: 5, 166: 2, 170: 2, 186: 0, 200: 2, 201: 1, 202: 6,
    203: 3, 204: 3, 213: 1, 217: 3, 218: 1, 227: 2, 228: 2, 229: 2,
    230: 4, 231: 1, 232: 4, 233: 4, 234: 3, 247: 5, 253: 0, 254: 3,
    255: 2, 257: 4, 258: 3, 262: 4, 263: 3, 264: 4, 269: 3, 271: 5,
    273: 2, 281: 6, 283: 2, 286: 4, 287: 2, 288: 4, 290: 2, 291: 1,
    292: 3, 293: 2, 294: 1, 295: 4, 296: 4, 299: 5, 302: 4, 307: 4,
    318: 3, 326: 6, 435: 2,
}
# pointer POSITIONS per syscall (bitmask, bit i = arg i is an address):
# the value heuristic alone misses sub-4GiB pointers (non-PIE binaries
# — /usr/bin/python3 on this image — keep their brk heap below 2^32),
# so deterministic mode masks by POSITION for known syscalls.
_PTR_ARGS = {
    0: 0b010, 1: 0b010, 2: 0b001, 4: 0b011, 5: 0b010, 6: 0b011,
    7: 0b001, 13: 0b0110, 14: 0b0110, 16: 0b100, 17: 0b010, 18: 0b010,
    19: 0b010, 20: 0b010, 21: 0b001, 22: 0b001, 23: 0b11110,
    35: 0b11, 36: 0b10, 37: 0, 38: 0b110, 40: 0b100, 42: 0b010,
    43: 0b110, 44: 0b010010, 45: 0b110010, 46: 0b010, 47: 0b010,
    49: 0b010, 51: 0b110, 52: 0b110, 53: 0b1000, 54: 0b01000,
    55: 0b11000, 56: 0b11110, 59: 0b111, 61: 0b1010, 63: 0b001,
    72: 0, 76: 0b01, 79: 0b01, 80: 0b1, 82: 0b11, 83: 0b01, 84: 0b1,
    86: 0b11, 87: 0b1, 88: 0b11, 89: 0b011, 90: 0b01, 92: 0b001,
    94: 0b001, 96: 0b11, 97: 0b10, 98: 0b10, 99: 0b1, 100: 0b1,
    115: 0b10, 116: 0b10, 117: 0, 118: 0b111, 119: 0, 120: 0b111,
    128: 0b0111, 130: 0b01, 131: 0b01, 137: 0b11, 138: 0b10,
    143: 0b10, 144: 0b100, 149: 0b01, 150: 0b01, 160: 0b10, 161: 0b1,
    164: 0b11, 165: 0b10111, 166: 0b01, 170: 0b01, 200: 0, 201: 0b1,
    202: 0b101001, 203: 0b100, 204: 0b100, 217: 0b010, 218: 0b1,
    227: 0b10, 228: 0b10, 229: 0b10, 230: 0b1100, 232: 0b0010,
    233: 0b1000, 234: 0, 247: 0b10100, 254: 0b010, 257: 0b0010,
    258: 0b010, 262: 0b0110, 263: 0b010, 264: 0b1010, 269: 0b010,
    271: 0b01101, 273: 0b01, 281: 0b010010, 286: 0b1100, 287: 0b10,
    288: 0b0110, 293: 0b01, 295: 0b0010, 296: 0b0010, 299: 0b10010,
    302: 0b1100, 307: 0b0010, 318: 0b001, 326: 0b001010, 435: 0b01,
}


class StraceLogger:
    """One .strace file per managed process."""

    def __init__(self, path: str, mode: str):
        if mode not in ("standard", "deterministic"):
            raise ValueError(
                f"strace_logging_mode must be off|standard|deterministic, "
                f"got {mode!r}")
        self.path = path
        self.mode = mode
        self._fh = None

    def _arg(self, v: int) -> str:
        if self.mode == "deterministic" and v >= _PTR_FLOOR:
            return "<ptr>"
        return hex(v) if v >= _PTR_FLOOR else str(v)

    def log(self, now_ns: int, tindex: int, nr: int, args, result,
            argstr=None) -> None:
        if self._fh is None:
            self._fh = open(self.path, "w", buffering=1 << 16)
        name = SYSCALL_NAMES.get(nr, f"syscall_{nr}")
        sec, rem = divmod(now_ns, simtime.SECOND)
        h, s = divmod(sec, 3600)
        m, s = divmod(s, 60)
        if argstr is not None:
            # a handler supplied the guest-visible rendering (file-family
            # syscalls print their PATH STRINGS — sim-deterministic —
            # where the raw pointer args would be masked)
            rendered = argstr
        elif self.mode == "deterministic":
            if nr in _MEM_SYSCALLS:
                rendered = "<mem>"
            else:
                arity = _ARG_COUNTS.get(nr)
                if arity is None:
                    rendered = "..."
                else:
                    ptrs = _PTR_ARGS.get(nr, 0)
                    rendered = ", ".join(
                        "<ptr>" if (ptrs >> i) & 1 and a
                        else self._arg(int(a) & (2**64 - 1))
                        for i, a in enumerate(args[:arity]))
        else:
            rendered = ", ".join(self._arg(int(a) & (2**64 - 1))
                                 for a in args)
        if isinstance(result, str):
            res = result
        elif self.mode == "deterministic" and result >= _PTR_FLOOR:
            res = "<ptr>"
        elif self.mode == "deterministic" and nr in _TID_RESULTS \
                and result > 0:
            # clone-family retvals are NATIVE thread ids (the guest
            # needs the real value; tids are not virtualized) and differ
            # run to run — mask them to keep the diffable contract
            res = "<tid>"
        else:
            res = str(result)
        self._fh.write(
            f"{h:02d}:{m:02d}:{s:02d}.{rem:09d} [t{tindex}] "
            f"{name}({rendered}) = {res}\n"
        )

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def make_logger(output_dir: Optional[str], proc_name: str,
                mode: str) -> Optional[StraceLogger]:
    if mode not in (None, "", "off", "standard", "deterministic"):
        raise ValueError(
            f"strace_logging_mode must be off|standard|deterministic, "
            f"got {mode!r}")
    if mode in (None, "", "off") or output_dir is None:
        return None
    import os

    os.makedirs(output_dir, exist_ok=True)
    return StraceLogger(os.path.join(output_dir, f"{proc_name}.strace"),
                        mode)
