"""The syscall-handler table for managed native processes.

Parity: reference `src/main/host/syscall/handler/mod.rs` (dispatch table at
`mod.rs:357-496`) — this is the layer that makes real binaries use the
*simulated* network: socket-family syscalls are emulated against the
simulated kernel objects (`shadow_tpu.kernel`), readiness syscalls
(poll/select/epoll) wait on simulated file state, and blocking syscalls
park the managed thread on a `SysCallCondition` until a file-status or
timeout trigger fires (`syscall_condition.c`). Anything not emulated is
executed natively by the shim (`SyscallDoNative`), and anything fd-based is
routed by descriptor: virtual fds (>= VFD_BASE) belong to the simulated
kernel, low fds belong to the real one.

The reference virtualizes *every* fd; this rebuild keeps native files
native and gives simulated descriptors a disjoint range — chosen below
FD_SETSIZE so select() bitmaps still work, above anything a real process
plausibly allocates.

Blocking protocol: a handler raises `errors.Blocked(file, state_mask,
timeout_ns)`; the ManagedSimProcess parks the shim (no IPC reply) and arms
a condition; when it fires, the same syscall is re-dispatched with
`ctx.wake` set ("file" | "timeout") and `ctx.deadline` carrying the
original absolute timeout, so timed waits (poll/select/epoll_wait) expire
correctly across spurious wakeups.

Multi-file waits (poll/select) are implemented over a transient kernel
`Epoll` instance, the same trick as the reference's handler-internal epoll
(`handler/mod.rs:80-107`).
"""

from __future__ import annotations

import ctypes
import logging
import struct
from time import perf_counter_ns as _perf_ns
from typing import Optional

from ..core import simtime
from ..kernel import errors
from ..kernel import futex as kfutex
from ..kernel.descriptor import (VFD_BASE as _VFD_BASE,
                                 VISIBLE_FD_LIMIT,
                                 DescriptorTable)
from ..kernel.epoll import Epoll, EpollEvents
from ..kernel.eventfd import EventFd
from ..kernel.pipe import PipeReader, PipeWriter, make_pipe
from ..kernel.socket.tcp import TcpSocket
from ..kernel.socket.udp import UdpSocket
from ..kernel.socket.netlink import NetlinkSocket
from ..kernel.socket.unix import UnixSocket, make_socketpair
from ..kernel.status import FileState
from ..kernel.timerfd import TimerFd

_LOG = logging.getLogger("shadow.vfs")

# ---------------------------------------------------------------------------
# x86_64 syscall numbers (the emulated subset)

SYS_read = 0
SYS_write = 1
SYS_close = 3
SYS_fstat = 5
SYS_poll = 7
SYS_lseek = 8
SYS_newfstatat = 262
SYS_pipe = 22
SYS_sched_yield = 24
SYS_wait4 = 61
SYS_kill = 62
SYS_uname = 63
SYS_sysinfo = 99
SYS_getppid = 110
SYS_tkill = 200
SYS_futex = 202
SYS_sched_getaffinity = 204
SYS_set_tid_address = 218
SYS_tgkill = 234
SYS_waitid = 247
SYS_set_robust_list = 273
SYS_rt_sigprocmask = 14
SYS_rt_sigtimedwait = 128
SYS_rt_sigsuspend = 130
SYS_pause = 34
SYS_getitimer = 36
SYS_alarm = 37
SYS_setitimer = 38
SYS_times = 100
SYS_setpgid = 109
SYS_getpgrp = 111
SYS_setsid = 112
SYS_getpgid = 121
SYS_getsid = 124
SYS_sched_setaffinity = 203
# memory-mapping family (region bookkeeping + validated passthrough)
SYS_mmap = 9
SYS_mprotect = 10
SYS_munmap = 11
SYS_brk = 12
SYS_mremap = 25
SYS_msync = 26
SYS_madvise = 28
SYS_mlock = 149
SYS_munlock = 150
SYS_mlockall = 151
SYS_munlockall = 152
# credentials (virtualized: deterministic simulated identity)
SYS_getuid = 102
SYS_getgid = 104
SYS_setuid = 105
SYS_setgid = 106
SYS_geteuid = 107
SYS_getegid = 108
SYS_getgroups = 115
SYS_setgroups = 116
SYS_setresuid = 117
SYS_getresuid = 118
SYS_setresgid = 119
SYS_getresgid = 120
# resource limits / accounting (virtualized: deterministic)
SYS_getrlimit = 97
SYS_getrusage = 98
SYS_setrlimit = 160
SYS_prlimit64 = 302
# scheduling / priority (virtualized: single deterministic CPU model)
SYS_getpriority = 140
SYS_setpriority = 141
SYS_sched_getparam = 143
SYS_sched_setscheduler = 144
SYS_sched_getscheduler = 145
# privileged operations (deterministic unprivileged denial)
SYS_chroot = 161
SYS_settimeofday = 164
SYS_mount = 165
SYS_umount2 = 166
SYS_clock_settime = 227
# zero-copy file->socket
SYS_sendfile = 40
SYS_clock_getres = 229
SYS_timerfd_create = 283
SYS_eventfd = 284
SYS_timerfd_settime = 286
SYS_timerfd_gettime = 287
SYS_eventfd2 = 290
SYS_pipe2 = 293
SYS_getcpu = 309
SYS_membarrier = 324
SYS_clone3 = 435
SYS_rt_sigaction = 13
SYS_ioctl = 16
SYS_readv = 19
SYS_writev = 20
SYS_select = 23
SYS_dup = 32
SYS_dup2 = 33
SYS_nanosleep = 35
SYS_socket = 41
SYS_socketpair = 53
SYS_connect = 42
SYS_accept = 43
SYS_sendto = 44
SYS_recvfrom = 45
SYS_sendmsg = 46
SYS_recvmsg = 47
SYS_shutdown = 48
SYS_bind = 49
SYS_listen = 50
SYS_getsockname = 51
SYS_getpeername = 52
SYS_setsockopt = 54
SYS_getsockopt = 55
SYS_fcntl = 72
SYS_gettimeofday = 96
SYS_time = 201
SYS_epoll_create = 213
SYS_clock_gettime = 228
SYS_clock_nanosleep = 230
SYS_epoll_wait = 232
SYS_epoll_ctl = 233
SYS_pselect6 = 270
SYS_ppoll = 271
SYS_epoll_pwait = 281
SYS_accept4 = 288
SYS_recvmmsg = 299
SYS_sendmmsg = 307
SYS_statx = 332
# file family (handler/file.c + fileat.c in the reference; here: path
# virtualization + strace visibility, execution stays native)
SYS_stat = 4
SYS_open = 2
SYS_creat = 85
SYS_lstat = 6
SYS_access = 21
SYS_rename = 82
SYS_mkdir = 83
SYS_rmdir = 84
SYS_link = 86
SYS_unlink = 87
SYS_symlink = 88
SYS_readlink = 89
SYS_chmod = 90
SYS_chown = 92
SYS_lchown = 94
SYS_truncate = 76
SYS_ftruncate = 77
SYS_fsync = 74
SYS_fdatasync = 75
SYS_flock = 73
SYS_getdents = 78
SYS_getdents64 = 217
SYS_getcwd = 79
SYS_chdir = 80
SYS_fchdir = 81
SYS_fchmod = 91
SYS_statfs = 137
SYS_utime = 132
SYS_utimes = 235
SYS_openat = 257
SYS_mkdirat = 258
SYS_fchownat = 260
SYS_unlinkat = 263
SYS_renameat = 264
SYS_linkat = 265
SYS_symlinkat = 266
SYS_readlinkat = 267
SYS_fchmodat = 268
SYS_faccessat = 269
SYS_utimensat = 280
SYS_fallocate = 285
SYS_renameat2 = 316
SYS_faccessat2 = 439
SYS_mknod = 133
SYS_mknodat = 259

AT_FDCWD = -100
O_WRONLY, O_RDWR, O_CREAT, O_TRUNC, O_APPEND = 0o1, 0o2, 0o100, 0o1000, 0o2000
O_TMPFILE = 0o20200000
# absolute prefixes served by the REAL filesystem: read-only system
# resources every process legitimately shares. Everything else absolute
# is per-host (redirected under host.vfs_root with read-through to the
# real path for base files — a create/write-oriented overlay; deletions
# of base-layer files are not whiteout-tracked, documented in BASELINE)
VFS_SYSTEM_PREFIXES = (
    b"/etc/", b"/usr/", b"/lib/", b"/lib64/", b"/bin/", b"/sbin/",
    b"/proc/", b"/sys/", b"/dev/", b"/run/", b"/opt/", b"/nix/",
)
VFS_PATH_MAX = 399  # SHIM_REWRITE_PATH_MAX - NUL
SYS_epoll_create1 = 291
SYS_dup3 = 292
SYS_getrandom = 318

# socket constants
AF_UNIX = 1
AF_INET = 2
AF_INET6 = 10
AF_NETLINK = 16
SOCK_STREAM = 1
SOCK_DGRAM = 2
SOCK_RAW = 3
SOCK_TYPE_MASK = 0xF
SOCK_SEQPACKET = 5
SOCK_NONBLOCK = 0o4000
SOCK_CLOEXEC = 0o2000000

SOL_SOCKET = 1
IPPROTO_TCP = 6
SO_REUSEADDR = 2
SO_ERROR = 4
SO_SNDBUF = 7
SO_RCVBUF = 8

MSG_PEEK = 0x02
MSG_TRUNC = 0x20
MSG_DONTWAIT = 0x40

O_NONBLOCK = 0o4000
F_GETFD = 1
F_SETFD = 2
F_GETFL = 3
F_SETFL = 4
F_DUPFD = 0
F_DUPFD_CLOEXEC = 1030

FIONREAD = 0x541B
FIONBIO = 0x5421

SHUT_RD, SHUT_WR, SHUT_RDWR = 0, 1, 2

ITIMER_REAL = 0
SIGALRM = 14

O_CLOEXEC = 0o2000000
EFD_SEMAPHORE = 1
TFD_TIMER_ABSTIME = 1
WNOHANG = 1

# poll events
POLLIN = 0x001
POLLPRI = 0x002
POLLOUT = 0x004
POLLERR = 0x008
POLLHUP = 0x010
POLLNVAL = 0x020
POLLRDHUP = 0x2000

EPOLL_CTL_ADD = 1
EPOLL_CTL_DEL = 2
EPOLL_CTL_MOD = 3

UNSPECIFIED = "0.0.0.0"

MS = 1_000_000  # ns per millisecond


class NativeSyscall(Exception):
    """Handler verdict: execute this syscall natively in the shim. An
    optional `strace_args` carries a handler-rendered argument string
    (file-family handlers print guest-visible paths where deterministic
    strace would mask the raw pointers)."""

    def __init__(self, strace_args=None):
        super().__init__()
        self.strace_args = strace_args


class NativeSyscallRewrite(Exception):
    """Handler verdict: execute natively with substituted pointer args —
    the per-host filesystem view (`core/manager` assigns `host.vfs_root`;
    reference parity: the file family of `handler/file.c:1-429` /
    `fileat.c:1-508`, re-designed as path REDIRECTION because this
    rebuild's managed fds are real kernel fds, not virtual file objects).
    `path_args` maps arg index -> replacement path bytes (max 2)."""

    def __init__(self, path_args: dict, strace_args=None):
        super().__init__()
        self.path_args = path_args
        self.strace_args = strace_args


class DispatchCtx:
    """Per-dispatch context threaded through handlers.

    `wake` is None on first dispatch, else the condition-fire reason
    ("file" | "timeout"); `deadline` is the absolute sim-time the original
    call's timeout expires (None = untimed), fixed at first block so timed
    waits don't restart their clock on every spurious wakeup. `thread` is
    the managed thread issuing the call (None for single-context callers).
    """

    __slots__ = ("wake", "deadline", "thread")

    def __init__(self, wake: Optional[str] = None,
                 deadline: Optional[int] = None, thread=None):
        self.wake = wake
        self.deadline = deadline
        self.thread = thread


_libc = ctypes.CDLL(None, use_errno=True)


def _libc_syscall(nr: int, *args: int) -> int:
    rc = _libc.syscall(ctypes.c_long(nr), *(ctypes.c_long(a) for a in args))
    if rc < 0:
        return -ctypes.get_errno()
    return rc


def _i32(v: int) -> int:
    return ctypes.c_int32(v & 0xFFFFFFFF).value


def _i64(v: int) -> int:
    return ctypes.c_int64(v).value


class SyscallHandler:
    """One per managed process (`SyscallHandler` in `handler/mod.rs`)."""

    VFD_BASE = _VFD_BASE  # above real fds, below FD_SETSIZE

    def __init__(self, process, table: Optional[DescriptorTable] = None):
        self.process = process
        self.host = process.host
        # fd -> simulated file; offset table keeps vfds in our range.
        # fork passes the parent table's fork_into() clone.
        self._table = table if table is not None else DescriptorTable()
        # low fd -> table slot: virtual files dup2'd onto stdio-range
        # descriptors (subprocess pipe redirection); consulted by _file
        self._low_overrides: dict[int, int] = {}
        # the one transient wait-epoll a parked poll/select holds (fallback
        # slot for single-context callers; threads park on their own)
        self._wait_epoll: Optional[Epoll] = None
        # emulated futexes, shared by all threads of the process
        self.futexes = kfutex.FutexTable()
        # signal dispositions recorded from rt_sigaction: sig -> (kind,
        # sa_restart) with kind in {'default','ignore','handler'}
        # (`process.rs:1309` signal virtualization)
        self.sig_actions: dict[int, tuple[str, bool]] = {}
        # ITIMER_REAL state (`handler/time.rs`): per-process, generation-
        # guarded so disarm/rearm invalidates in-flight expiry tasks
        self._itimer_deadline: Optional[int] = None
        self._itimer_interval = 0
        self._itimer_gen = 0
        # stable st_ino assignment for virtual descriptors
        self._ino_counter = 0
        # guest-set resource limits and nice value — fork(2) inherits
        # both (copied by managed.forked()/vfork_placeholder())
        self._rlimits: dict[int, tuple[int, int]] = {}
        self._nice = 0
        # per-syscall dispatch tally for sim-stats (first dispatches only;
        # condition-wakeup re-dispatches of the same call don't re-count)
        self.syscall_counts: dict[int, int] = {}
        # perf timers (`handler/mod.rs:84-89`): wall ns per syscall number,
        # only accumulated when experimental.use_perf_timers is on
        self._perf_enabled = bool(getattr(
            self.host.config_experimental, "use_perf_timers", False))
        self.syscall_ns: dict[int, int] = {}
        if self._perf_enabled:
            # host-level registry so aggregation sees every handler ever
            # created — including fork()ed children that exit (and are
            # unlinked from their parent) before stats are collected
            handlers = getattr(self.host, "perf_handlers", None)
            if handlers is None:
                handlers = self.host.perf_handlers = []
            handlers.append(self)

    # -- descriptor plumbing -------------------------------------------

    @property
    def mem(self):
        return self.process.server.mem

    def _vfd(self, file, cloexec: bool = False) -> int:
        return self._table.register(file, cloexec) + self.VFD_BASE

    def _file(self, fd: int):
        fd = _i32(fd)
        if fd < self.VFD_BASE:
            # a low fd may SHADOW a virtual file (dup2 of a simulated
            # pipe/socket onto stdio — subprocess/popen redirection)
            slot = self._low_overrides.get(fd)
            if slot is None:
                raise NativeSyscall()
            return self._table.get(slot)
        try:
            return self._table.get(fd - self.VFD_BASE)
        except errors.SyscallError:
            # in our range but not ours: report EBADF rather than letting
            # the kernel act on a fd the process never opened
            raise errors.SyscallError(errors.EBADF) from None

    def has_vfd(self, fd: int) -> bool:
        fd = _i32(fd)
        if fd < self.VFD_BASE:
            return fd in self._low_overrides
        try:
            self._table.get(fd - self.VFD_BASE)
            return True
        except errors.SyscallError:
            return False

    def close_all(self) -> None:
        self._table.close_all()
        self._low_overrides.clear()
        self._drop_wait_epoll()
        self._itimer_disarm()  # a dead process's timer must not re-arm
        if self._perf_enabled:
            # fold our durations into the host aggregate and drop the
            # registry reference so reaped fork children don't pin their
            # whole object graph until teardown
            agg = getattr(self.host, "perf_syscall_ns", None)
            if agg is None:
                agg = self.host.perf_syscall_ns = {}
            for nr, ns in self.syscall_ns.items():
                agg[nr] = agg.get(nr, 0) + ns
            self.syscall_ns = {}
            handlers = getattr(self.host, "perf_handlers", None)
            if handlers is not None and self in handlers:
                handlers.remove(self)

    def _drop_wait_epoll(self, thread=None) -> None:
        if thread is not None and getattr(thread, "wait_epoll", None) is not None:
            thread.wait_epoll.close()
            thread.wait_epoll = None
        if thread is None and self._wait_epoll is not None:
            self._wait_epoll.close()  # removes its listeners
            self._wait_epoll = None

    # -- sockaddr codec ------------------------------------------------

    def _read_sockaddr(self, addr: int, addrlen: int) -> tuple[str, int]:
        if addrlen < 2:
            raise errors.SyscallError(errors.EINVAL)
        raw = self.mem.read(addr, min(addrlen, 110))
        (family,) = struct.unpack_from("<H", raw, 0)
        if family == AF_UNIX:
            from ..kernel.socket.unix import UNIX_ADDR_FAMILY

            # sockaddr_un: sun_path is addrlen-2 bytes; pathname names end
            # at the first NUL, abstract names (leading NUL) keep their
            # full length (unix(7))
            path_bytes = raw[2:addrlen]
            if path_bytes[:1] == b"\x00":
                path = path_bytes.decode("latin-1")
            else:
                path = path_bytes.split(b"\x00", 1)[0].decode("latin-1")
            return UNIX_ADDR_FAMILY, path
        if family == AF_NETLINK:
            # sockaddr_nl: u16 family, u16 pad, u32 pid, u32 groups
            if addrlen < 12:
                raise errors.SyscallError(errors.EINVAL)
            pid, groups = struct.unpack_from("<II", raw, 4)
            return ("netlink", pid, groups)
        if family != AF_INET or addrlen < 8:
            raise errors.SyscallError(errors.EAFNOSUPPORT)
        port = struct.unpack_from(">H", raw, 2)[0]
        ip = ".".join(str(b) for b in raw[4:8])
        return ip, port

    @staticmethod
    def _pack_sockaddr(sockaddr: Optional[tuple[str, int]]) -> bytes:
        from ..kernel.socket.unix import UNIX_ADDR_FAMILY

        if sockaddr is not None and sockaddr[0] == UNIX_ADDR_FAMILY:
            path = sockaddr[1].encode("latin-1")
            return struct.pack("<H", AF_UNIX) + path + (
                b"" if path[:1] == b"\x00" else b"\x00")
        if sockaddr is not None and sockaddr[0] == "netlink":
            _fam, pid, groups = sockaddr
            return struct.pack("<HHII", AF_NETLINK, 0, pid, groups)
        ip, port = sockaddr if sockaddr is not None else (UNSPECIFIED, 0)
        return struct.pack("<H", AF_INET) + struct.pack(">H", port) + bytes(
            int(p) for p in ip.split(".")
        ) + b"\x00" * 8

    def _write_sockaddr(self, addr: int, addrlen_ptr: int,
                        sockaddr: Optional[tuple[str, int]]) -> None:
        if not addr or not addrlen_ptr:
            return
        if sockaddr is None:
            # no source address to report (e.g. recvfrom on a stream
            # socket whose peer is gone): report length 0, never an
            # AF_INET-shaped placeholder into an AF_UNIX buffer
            self.mem.write(addrlen_ptr, struct.pack("<I", 0))
            return
        raw = self._pack_sockaddr(sockaddr)
        (cap,) = struct.unpack("<I", self.mem.read(addrlen_ptr, 4))
        self.mem.write(addr, raw[: min(cap, len(raw))])
        self.mem.write(addrlen_ptr, struct.pack("<I", len(raw)))

    def _scatter(self, iovs: list[tuple[int, int]], data: bytes) -> None:
        """Write `data` across iovec buffers (readv/recvmsg gather side)."""
        off = 0
        for base, ln in iovs:
            chunk = data[off:off + ln]
            if not chunk:
                break
            self.mem.write(base, chunk)
            off += len(chunk)

    # -- dispatch ------------------------------------------------------

    def dispatch(self, nr: int, args, ctx: DispatchCtx) -> int:
        """Returns the syscall retval; raises NativeSyscall for
        passthrough, errors.SyscallError for -errno, errors.Blocked to
        park. Re-dispatched (ctx.wake set) calls must be idempotent up to
        their blocking point."""
        if ctx.wake is None:
            self.syscall_counts[nr] = self.syscall_counts.get(nr, 0) + 1
        handler = self._HANDLERS.get(nr)
        if handler is None:
            raise NativeSyscall()
        if self._perf_enabled:
            t0 = _perf_ns()  # shadowlint: disable=SL101 -- opt-in strace profiling stat
            try:
                return handler(self, args, ctx)
            finally:
                self.syscall_ns[nr] = (self.syscall_ns.get(nr, 0)
                                       # shadowlint: disable=SL101 -- strace profiling stat
                                       + _perf_ns() - t0)
        return handler(self, args, ctx)

    # -- socket family -------------------------------------------------

    def _sys_socket(self, args, ctx) -> int:
        domain, type_, _proto = _i32(args[0]), _i32(args[1]), _i32(args[2])
        if domain == AF_UNIX:
            kind = type_ & SOCK_TYPE_MASK
            if kind not in (SOCK_STREAM, SOCK_DGRAM, SOCK_SEQPACKET):
                raise errors.SyscallError(errors.EPROTONOSUPPORT)
            sock = UnixSocket(self.host, stream=kind != SOCK_DGRAM)
            sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
            return self._vfd(sock, cloexec=bool(type_ & SOCK_CLOEXEC))
        if domain == AF_NETLINK:
            kind = type_ & SOCK_TYPE_MASK
            if kind not in (SOCK_RAW, SOCK_DGRAM):
                raise errors.SyscallError(errors.EPROTONOSUPPORT)
            sock = NetlinkSocket(self.host, protocol=_i32(args[2]))
            sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
            return self._vfd(sock, cloexec=bool(type_ & SOCK_CLOEXEC))
        if domain == AF_INET6:
            # v4-only simulated internet; apps fall back (`inet/mod.rs`)
            raise errors.SyscallError(errors.EAFNOSUPPORT)
        if domain != AF_INET:
            raise errors.SyscallError(errors.EAFNOSUPPORT)
        kind = type_ & SOCK_TYPE_MASK
        if kind == SOCK_STREAM:
            sock = TcpSocket(self.host)
        elif kind == SOCK_DGRAM:
            sock = UdpSocket(self.host)
        else:
            raise errors.SyscallError(errors.EPROTONOSUPPORT)
        sock.nonblocking = bool(type_ & SOCK_NONBLOCK)
        return self._vfd(sock, cloexec=bool(type_ & SOCK_CLOEXEC))

    def _sys_bind(self, args, ctx) -> int:
        sock = self._file(args[0])
        addr = self._read_sockaddr(args[1], _i32(args[2]))
        sock.bind(addr)
        return 0

    def _sys_listen(self, args, ctx) -> int:
        sock = self._file(args[0])
        if not isinstance(sock, (TcpSocket, UnixSocket)):
            raise errors.SyscallError(errors.EOPNOTSUPP)
        backlog = _i32(args[1])
        sock.listen(backlog if backlog > 0 else 1)
        return 0

    def _sys_socketpair(self, args, ctx) -> int:
        domain, type_ = _i32(args[0]), _i32(args[1])
        if domain != AF_UNIX:
            raise errors.SyscallError(errors.EAFNOSUPPORT)
        kind = type_ & SOCK_TYPE_MASK
        if kind not in (SOCK_STREAM, SOCK_DGRAM, SOCK_SEQPACKET):
            raise errors.SyscallError(errors.EPROTONOSUPPORT)
        a, b = make_socketpair(self.host, stream=kind != SOCK_DGRAM)
        a.nonblocking = b.nonblocking = bool(type_ & SOCK_NONBLOCK)
        cloexec = bool(type_ & SOCK_CLOEXEC)
        fds = (self._vfd(a, cloexec), self._vfd(b, cloexec))
        self.mem.write(args[3], struct.pack("<ii", *fds))
        return 0

    def _sys_connect(self, args, ctx) -> int:
        sock = self._file(args[0])
        if isinstance(sock, (UdpSocket, UnixSocket)):
            # both connect without a handshake round trip (unix pairs
            # rendezvous instantly: same host, no network plane)
            addr = self._read_sockaddr(args[1], _i32(args[2]))
            sock.connect(addr)
            return 0
        if ctx.wake is not None:
            # resuming a blocked connect: report the handshake outcome
            if sock.conn is not None and sock.conn.error is not None:
                raise errors.SyscallError(sock.conn.error)
            if sock.is_connected():
                return 0
            raise errors.Blocked(
                sock, FileState.SOCKET_ALLOWING_CONNECT, restartable=False
            )
        addr = self._read_sockaddr(args[1], _i32(args[2]))
        sock.connect(addr)  # raises Blocked (blocking) or EINPROGRESS
        return 0

    def _sys_accept(self, args, ctx, flags: int = 0) -> int:
        listener = self._file(args[0])
        if not isinstance(listener, (TcpSocket, UnixSocket)):
            raise errors.SyscallError(errors.EOPNOTSUPP)
        child = listener.accept()  # raises Blocked when queue empty
        child.nonblocking = bool(flags & SOCK_NONBLOCK)
        fd = self._vfd(child, cloexec=bool(flags & SOCK_CLOEXEC))
        self._write_sockaddr(args[1], args[2], child.getpeername())
        return fd

    def _sys_accept4(self, args, ctx) -> int:
        return self._sys_accept(args, ctx, flags=_i32(args[3]))

    def _sys_shutdown(self, args, ctx) -> int:
        sock = self._file(args[0])
        how = _i32(args[1])
        if how not in (SHUT_RD, SHUT_WR, SHUT_RDWR):
            raise errors.SyscallError(errors.EINVAL)
        if isinstance(sock, TcpSocket):
            if sock.conn is None:
                raise errors.SyscallError(errors.ENOTCONN)
            if how in (SHUT_WR, SHUT_RDWR) and not sock.conn.fin_requested:
                sock.conn.close()
                sock._pump_out()
        else:
            if isinstance(sock, UnixSocket):
                sock.shutdown(rd=how in (SHUT_RD, SHUT_RDWR),
                              wr=how in (SHUT_WR, SHUT_RDWR))
        return 0

    def _sys_getsockname(self, args, ctx) -> int:
        sock = self._file(args[0])
        self._write_sockaddr(args[1], args[2], sock.getsockname())
        return 0

    def _sys_getpeername(self, args, ctx) -> int:
        sock = self._file(args[0])
        peer = sock.getpeername()
        if peer is None:
            raise errors.SyscallError(errors.ENOTCONN)
        self._write_sockaddr(args[1], args[2], peer)
        return 0

    def _sys_setsockopt(self, args, ctx) -> int:
        sock = self._file(args[0])  # EBADF check
        level, optname = _i32(args[1]), _i32(args[2])
        if level == SOL_SOCKET and optname in (SO_SNDBUF, SO_RCVBUF):
            # int-valued options: Linux rejects optlen < sizeof(int)
            # (including negative — optlen is an int) with EINVAL, then
            # faults on a NULL optval, instead of silently succeeding
            if _i32(args[4]) < 4:
                raise errors.SyscallError(errors.EINVAL)
            if not args[3]:
                raise errors.SyscallError(errors.EFAULT)
            # read as the kernel does (u32 comparison against the
            # ceiling): -1 is the "give me the max" idiom, not an error
            (value,) = struct.unpack("<I", self.mem.read(args[3], 4))
            setter = getattr(sock, "set_buffer_size", None)
            if setter is not None:  # TCP: pins size, disables autotune
                setter("send" if optname == SO_SNDBUF else "recv", value)
        # SO_REUSEADDR / TCP_NODELAY / the rest: accepted, not modeled
        return 0

    def _sys_getsockopt(self, args, ctx) -> int:
        sock = self._file(args[0])
        level, optname = _i32(args[1]), _i32(args[2])
        optval, optlen_ptr = args[3], args[4]
        if level == SOL_SOCKET and optname == SO_ERROR:
            err = 0
            if isinstance(sock, TcpSocket) and sock.conn is not None \
                    and sock.conn.error is not None:
                err = sock.conn.error
            self._write_int_opt(optval, optlen_ptr, err)
            return 0
        if level == SOL_SOCKET and optname in (SO_SNDBUF, SO_RCVBUF):
            value = 131072
            cfg = getattr(getattr(sock, "conn", None), "config", None) \
                or getattr(sock, "_config", None)
            if cfg is not None:
                value = (cfg.send_buffer if optname == SO_SNDBUF
                         else cfg.recv_buffer)
            self._write_int_opt(optval, optlen_ptr, value)
            return 0
        self._write_int_opt(optval, optlen_ptr, 0)
        return 0

    def _write_int_opt(self, optval: int, optlen_ptr: int, value: int) -> None:
        if not optval or not optlen_ptr:
            return
        (cap,) = struct.unpack("<I", self.mem.read(optlen_ptr, 4))
        raw = struct.pack("<i", value)[: max(0, cap)]
        if raw:
            self.mem.write(optval, raw)
        self.mem.write(optlen_ptr, struct.pack("<I", len(raw)))

    # -- data transfer -------------------------------------------------

    def _recv_common(self, sock, bufp: int, n: int, flags: int,
                     want_src: bool):
        dontwait = bool(flags & MSG_DONTWAIT)
        saved = sock.nonblocking
        if dontwait:
            sock.nonblocking = True
        try:
            if isinstance(sock, UdpSocket):
                data, src = sock.recvfrom(peek=bool(flags & MSG_PEEK))
                full = len(data)
                data = data[:n]  # datagram truncation
                if data:
                    self.mem.write(bufp, data)
                return (full if flags & MSG_TRUNC else len(data)), src
            elif isinstance(sock, NetlinkSocket):
                data, src, full = sock.recvfrom(
                    n, peek=bool(flags & MSG_PEEK))
                if data:
                    self.mem.write(bufp, data)
                return (full if flags & MSG_TRUNC else len(data)), src
            elif isinstance(sock, UnixSocket) and not sock.stream:
                # take the whole datagram so MSG_TRUNC can report its real
                # size (supported on AF_UNIX dgram since Linux 3.4)
                data, src = sock.recvfrom(1 << 20,
                                          peek=bool(flags & MSG_PEEK))
                full = len(data)
                data = data[:n]
                if data:
                    self.mem.write(bufp, data)
                return (full if flags & MSG_TRUNC else len(data)), src
            else:
                # TCP / unix-stream: MSG_PEEK honored; MSG_TRUNC on a
                # stream means read-and-discard (no buffer copy)
                data = sock.recv(n, peek=bool(flags & MSG_PEEK))
                src = sock.getpeername()
                if flags & MSG_TRUNC:
                    return len(data), src
        finally:
            sock.nonblocking = saved
        if data:
            self.mem.write(bufp, data)
        return len(data), src

    def _sys_recvfrom(self, args, ctx) -> int:
        sock = self._file(args[0])
        n = args[2]
        got, src = self._recv_common(sock, args[1], n, _i32(args[3]),
                                     want_src=True)
        self._write_sockaddr(args[4], args[5], src)
        return got

    def _sys_read(self, args, ctx) -> int:
        file = self._file(args[0])
        if isinstance(file, EventFd):
            if args[2] < 8:
                raise errors.SyscallError(errors.EINVAL)
            value = file.read_value()
            self.mem.write(args[1], struct.pack("<Q", value))
            return 8
        if isinstance(file, TimerFd):
            if args[2] < 8:
                raise errors.SyscallError(errors.EINVAL)
            n = file.read_expirations()
            self.mem.write(args[1], struct.pack("<Q", n))
            return 8
        if isinstance(file, PipeReader):
            data = file.recv(args[2])
            if data:
                self.mem.write(args[1], data)
            return len(data)
        if isinstance(file, (PipeWriter, Epoll)):
            raise errors.SyscallError(errors.EBADF)
        got, _src = self._recv_common(file, args[1], args[2], 0, False)
        return got

    def _sys_readv(self, args, ctx) -> int:
        sock = self._file(args[0])
        iovs = self._read_iovec(args[1], _i32(args[2]))
        total = sum(ln for _, ln in iovs)
        data = sock.recv(total) if not isinstance(sock, UdpSocket) \
            else sock.recvfrom()[0][:total]
        self._scatter(iovs, data)
        return len(data)

    def _sys_sendto(self, args, ctx) -> int:
        sock = self._file(args[0])
        bufp, n, flags = args[1], args[2], _i32(args[3])
        data = self.mem.read(bufp, n) if n else b""
        dontwait = bool(flags & MSG_DONTWAIT)
        saved = sock.nonblocking
        if dontwait:
            sock.nonblocking = True
        try:
            if isinstance(sock, UdpSocket) or (
                    isinstance(sock, UnixSocket) and not sock.stream):
                dst = None
                if args[4]:
                    dst = self._read_sockaddr(args[4], _i32(args[5]))
                return sock.sendto(data, dst)
            return sock.send(data)
        finally:
            sock.nonblocking = saved

    def _sys_write(self, args, ctx) -> int:
        file = self._file(args[0])
        if isinstance(file, EventFd):
            if args[2] < 8:
                raise errors.SyscallError(errors.EINVAL)
            (value,) = struct.unpack("<Q", self.mem.read(args[1], 8))
            file.write_value(value)
            return 8
        if isinstance(file, (TimerFd, PipeReader, Epoll)):
            raise errors.SyscallError(errors.EBADF)
        data = self.mem.read(args[1], args[2]) if args[2] else b""
        return file.send(data)

    def _sys_writev(self, args, ctx) -> int:
        sock = self._file(args[0])
        iovs = self._read_iovec(args[1], _i32(args[2]))
        data = b"".join(self.mem.read(base, ln) for base, ln in iovs if ln)
        return sock.send(data)

    def _read_iovec(self, iovp: int, iovcnt: int) -> list[tuple[int, int]]:
        if iovcnt < 0 or iovcnt > 1024:
            raise errors.SyscallError(errors.EINVAL)
        raw = self.mem.read(iovp, iovcnt * 16)
        return [struct.unpack_from("<QQ", raw, i * 16) for i in range(iovcnt)]

    def _parse_msghdr(self, msgp: int):
        # x86_64 struct msghdr: name(8) namelen(4+4pad) iov(8) iovlen(8)
        # control(8) controllen(8) flags(4+4pad) = 56 bytes
        raw = self.mem.read(msgp, 56)
        name, namelen, iovp, iovlen, _ctrl, _ctrllen, _flags = struct.unpack(
            "<QI4xQQQQi4x", raw
        )
        return name, namelen, self._read_iovec(iovp, iovlen)

    def _sys_sendmsg(self, args, ctx) -> int:
        sock = self._file(args[0])
        name, namelen, iovs = self._parse_msghdr(args[1])
        data = b"".join(self.mem.read(base, ln) for base, ln in iovs if ln)
        dontwait = bool(_i32(args[2]) & MSG_DONTWAIT)
        saved = sock.nonblocking
        if dontwait:
            sock.nonblocking = True
        try:
            if isinstance(sock, UdpSocket):
                dst = self._read_sockaddr(name, namelen) if name else None
                return sock.sendto(data, dst)
            return sock.send(data)
        finally:
            sock.nonblocking = saved

    def _sys_recvmsg(self, args, ctx) -> int:
        sock = self._file(args[0])
        name, namelen, iovs = self._parse_msghdr(args[1])
        total = sum(ln for _, ln in iovs)
        flags_ = _i32(args[2])
        dontwait = bool(flags_ & MSG_DONTWAIT)
        saved = sock.nonblocking
        if dontwait:
            sock.nonblocking = True
        ret = None
        msg_flags_out = 0
        try:
            if isinstance(sock, UdpSocket):
                data, src = sock.recvfrom(peek=bool(flags_ & MSG_PEEK))
                full = len(data)
                data = data[:total]
                if full > total:
                    msg_flags_out = MSG_TRUNC
                if flags_ & MSG_TRUNC:
                    ret = full
            elif isinstance(sock, NetlinkSocket):
                data, src, full = sock.recvfrom(
                    total, peek=bool(flags_ & MSG_PEEK))
                if full > total:
                    # datagram clipped: Linux flags MSG_TRUNC in msg_flags
                    # on ANY truncating read; the MSG_TRUNC input flag only
                    # switches the return value to the full length (glibc's
                    # PEEK|TRUNC length probe relies on both)
                    msg_flags_out = MSG_TRUNC
                if flags_ & MSG_TRUNC:
                    ret = full
            elif isinstance(sock, UnixSocket) and not sock.stream:
                data, src = sock.recvfrom(1 << 20,
                                          peek=bool(flags_ & MSG_PEEK))
                full = len(data)
                data = data[:total]
                if full > total:
                    msg_flags_out = MSG_TRUNC
                if flags_ & MSG_TRUNC:
                    ret = full
            else:
                data = sock.recv(total, peek=bool(flags_ & MSG_PEEK))
                src = sock.getpeername()
                if flags_ & MSG_TRUNC:
                    # stream MSG_TRUNC = read-and-discard, same as the
                    # recvfrom path (Linux tcp_recvmsg serves both)
                    ret = len(data)
                    data = b""
        finally:
            sock.nonblocking = saved
        self._scatter(iovs, data)
        # msg_flags writeback (offset 48 in msghdr)
        self.mem.write(args[1] + 48, struct.pack("<i", msg_flags_out))
        # msg_name writeback, capped at the caller's msg_namelen; the
        # written length lands in msg_namelen (offset 8 in msghdr)
        if name and src is not None:
            raw = self._pack_sockaddr(src)
            self.mem.write(name, raw[: min(namelen, len(raw))])
            self.mem.write(args[1] + 8, struct.pack("<I", len(raw)))
        return ret if ret is not None else len(data)

    # -- descriptor ops ------------------------------------------------

    def _sys_close(self, args, ctx) -> int:
        fd = _i32(args[0])
        if fd < self.VFD_BASE:
            slot = self._low_overrides.pop(fd, None)
            if slot is not None:
                try:
                    self._table.close(slot)
                except errors.SyscallError:
                    pass
            raise NativeSyscall()  # the kernel closes its side too
        try:
            self._table.close(fd - self.VFD_BASE)
        except errors.SyscallError:
            raise errors.SyscallError(errors.EBADF) from None
        return 0

    def _sys_dup(self, args, ctx) -> int:
        fd = _i32(args[0])
        if not self.has_vfd(fd):
            raise NativeSyscall()
        file = self._file(fd)  # resolves low-fd shadows too
        return self._table.register(file) + self.VFD_BASE

    def _sys_dup2(self, args, ctx, flags: int = 0) -> int:
        oldfd, newfd = _i32(args[0]), _i32(args[1])
        old_virtual = oldfd >= self.VFD_BASE \
            or oldfd in self._low_overrides
        if not old_virtual:
            if newfd >= self.VFD_BASE:
                # native source replacing a virtual slot: drop the
                # virtual file, then the kernel can't take over a fd in
                # our reserved range — reject like a bad target
                raise errors.SyscallError(errors.EBADF)
            # native->native (possibly clearing a low shadow first)
            slot = self._low_overrides.pop(newfd, None)
            if slot is not None:
                self._table.close(slot)
            raise NativeSyscall()
        file = self._file(oldfd)
        if oldfd == newfd:
            return newfd
        if newfd >= self.VFD_BASE:
            self._table.register_at(newfd - self.VFD_BASE, file)
            return newfd
        # virtual file onto a low fd (dup2(pipe, STDOUT_FILENO)): shadow
        # the native descriptor — subsequent ops on newfd route virtually
        slot = self._low_overrides.pop(newfd, None)
        if slot is not None:
            self._table.close(slot)
        self._low_overrides[newfd] = self._table.register(file)
        return newfd

    def _sys_dup3(self, args, ctx) -> int:
        if _i32(args[0]) == _i32(args[1]):
            raise errors.SyscallError(errors.EINVAL)
        return self._sys_dup2(args, ctx, flags=_i32(args[2]))

    def _vfd_stat_identity(self, file) -> tuple[int, int]:
        """(st_mode, st_ino) for a virtual descriptor — shared by fstat
        and statx so the two never disagree about the same fd. Inodes are
        stat-order ordinals stamped ON the file object (stable across
        dup()s, immune to id() reuse after GC, deterministic across
        runs)."""
        from ..kernel.pipe import PipeReader as _PR, PipeWriter as _PW

        ino = getattr(file, "st_ino", None)
        if ino is None:
            self._ino_counter += 1
            ino = file.st_ino = self._ino_counter
        if isinstance(file, (_PR, _PW)):
            return 0o010600, ino  # S_IFIFO
        return 0o140777, ino  # S_IFSOCK

    def write_siginfo(self, ptr: int, sig: int) -> None:
        """Minimal siginfo_t (si_signo; zero si_errno/si_code/payload) —
        the one serialization shared by every sigwait completion path."""
        if ptr:
            self.mem.write(ptr, struct.pack("<iii", sig, 0, 0)
                           + b"\x00" * 116)

    def _sys_fstat(self, args, ctx) -> int:
        file = self._file(args[0])  # EBADF check / native routing
        # minimal stat (layout: x86_64 struct stat; ino at 8, mode at 24)
        mode, ino = self._vfd_stat_identity(file)
        st = bytearray(144)
        struct.pack_into("<Q", st, 8, ino)
        struct.pack_into("<Q", st, 16, 1)  # st_nlink
        struct.pack_into("<I", st, 24, mode)
        self.mem.write(args[1], bytes(st))
        return 0

    def _sys_newfstatat(self, args, ctx) -> int:
        """newfstatat(2): glibc implements fstat() as
        newfstatat(fd, "", AT_EMPTY_PATH) — emulate that shape for
        virtual descriptors; path-based forms route through the per-host
        filesystem view like stat(2)."""
        dirfd, flags = _i32(args[0]), _i32(args[3])
        if flags & self.AT_EMPTY_PATH and self.has_vfd(dirfd):
            return self._sys_fstat([dirfd, args[2]], ctx)
        return self._vfs_one_path(args, "newfstatat", 1, False)

    def _sys_lseek(self, args, ctx) -> int:
        """lseek(2) on a virtual descriptor: pipes and sockets are not
        seekable — ESPIPE, which io layers (CPython's io.open) use to
        detect non-seekable streams. Native fds pass through."""
        self._file(args[0])  # NativeSyscall for real fds, EBADF check
        raise errors.SyscallError(errors.ESPIPE)

    def _sys_fcntl(self, args, ctx) -> int:
        fd = _i32(args[0])
        if not self.has_vfd(fd):
            raise NativeSyscall()
        file = self._file(fd)
        cmd, arg = _i32(args[1]), args[2]
        if cmd == F_GETFL:
            return O_NONBLOCK if getattr(file, "nonblocking", False) else 0
        if cmd == F_SETFL:
            file.nonblocking = bool(arg & O_NONBLOCK)
            return 0
        if cmd in (F_GETFD, F_SETFD):
            return 0
        if cmd in (F_DUPFD, F_DUPFD_CLOEXEC):
            # `file` already resolved through any low-fd shadow
            return self._table.register(file) + self.VFD_BASE
        raise errors.SyscallError(errors.EINVAL)

    def _sys_ioctl(self, args, ctx) -> int:
        fd = _i32(args[0])
        if not self.has_vfd(fd):
            raise NativeSyscall()
        file = self._file(fd)
        req = args[1]
        if req == FIONBIO:
            (val,) = struct.unpack("<i", self.mem.read(args[2], 4))
            file.nonblocking = bool(val)
            return 0
        if req == FIONREAD:
            n = 0
            if isinstance(file, TcpSocket) and file.conn is not None:
                n = file.conn.readable_bytes()
            elif isinstance(file, UdpSocket) and len(file._recv_buffer):
                n = file._recv_buffer.queue[0][2]
            self.mem.write(args[2], struct.pack("<i", n))
            return 0
        raise errors.SyscallError(errors.EINVAL)

    # -- readiness: poll/select/epoll ----------------------------------

    def _poll_revents(self, fd: int, events: int) -> int:
        """Readiness bits for one pollfd entry. Native fds report 0 (we
        cannot wait on them without breaking determinism); mixing native
        and simulated fds in one poll set is unsupported-but-harmless."""
        if not self.has_vfd(fd):
            return POLLNVAL if fd >= self.VFD_BASE else 0
        file = self._file(fd)
        state = file.state
        r = 0
        if state & FileState.READABLE:
            r |= POLLIN
        if state & FileState.WRITABLE:
            r |= POLLOUT
        if state & FileState.CLOSED:
            r |= POLLHUP
        if isinstance(file, TcpSocket) and file.conn is not None:
            if file.conn.error is not None:
                r |= POLLERR
            if file.conn.at_eof():
                r |= POLLRDHUP | POLLIN  # EOF: read returns 0
        return r & (events | POLLERR | POLLHUP | POLLNVAL)

    def _block_on_files(self, entries: list[tuple[int, int]],
                        timeout_ns: Optional[int], ctx=None):
        """Arm a transient epoll over (fd, poll-events) pairs and block on
        it (`handler/mod.rs:80-107` internal-epoll pattern)."""
        ep = Epoll()
        for fd, events in entries:
            if not self.has_vfd(fd):
                continue
            interest = EpollEvents(0)
            if events & (POLLIN | POLLPRI | POLLRDHUP):
                interest |= EpollEvents.IN
            if events & POLLOUT:
                interest |= EpollEvents.OUT
            try:
                ep.add(self._file(fd), interest)
            except errors.SyscallError:
                pass
        if ctx is not None and ctx.thread is not None:
            ctx.thread.wait_epoll = ep
        else:
            self._wait_epoll = ep
        raise errors.Blocked(ep, FileState.READABLE, timeout_ns=timeout_ns)

    def _remaining(self, ctx: DispatchCtx,
                   timeout_ns: Optional[int]) -> Optional[int]:
        """Remaining wait from the original deadline (set at first block)."""
        if ctx.deadline is not None:
            return max(0, ctx.deadline - self.host.now())
        return timeout_ns

    def _sys_poll(self, args, ctx, timeout_ns: Optional[int] = -1) -> int:
        fdsp, nfds = args[0], args[1]
        if timeout_ns == -1:  # plain poll: ms timeout in arg 2
            tmo = _i32(args[2])
            timeout_ns = None if tmo < 0 else tmo * MS
        if nfds > 4096:
            raise errors.SyscallError(errors.EINVAL)
        raw = self.mem.read(fdsp, nfds * 8) if nfds else b""
        entries = []
        for i in range(nfds):
            fd, events, _rev = struct.unpack_from("<ihh", raw, i * 8)
            entries.append((fd, events))
        ready = 0
        out = bytearray(raw)
        for i, (fd, events) in enumerate(entries):
            rev = self._poll_revents(fd, events) if fd >= 0 else 0
            struct.pack_into("<h", out, i * 8 + 6, rev)
            if rev:
                ready += 1
        if ready or timeout_ns == 0:
            self.mem.write(fdsp, bytes(out))
            return ready
        if ctx.wake == "timeout":
            self.mem.write(fdsp, bytes(out))
            return 0
        self._block_on_files(
            [(fd, ev) for fd, ev in entries if fd >= 0],
            self._remaining(ctx, timeout_ns), ctx,
        )

    def _sys_ppoll(self, args, ctx) -> int:
        tsp = args[2]
        if tsp:
            sec, nsec = struct.unpack("<qq", self.mem.read(tsp, 16))
            timeout_ns = sec * simtime.SECOND + nsec
        else:
            timeout_ns = None
        return self._sys_poll(args, ctx, timeout_ns=timeout_ns)

    def _sys_select(self, args, ctx, timeout_ns: Optional[int] = -1) -> int:
        nfds = _i32(args[0])
        if nfds < 0 or nfds > 1024:
            raise errors.SyscallError(errors.EINVAL)
        nbytes = (nfds + 7) // 8
        sets = []
        for argi, want in ((args[1], POLLIN), (args[2], POLLOUT),
                           (args[3], POLLPRI)):
            if argi and nbytes:
                sets.append((argi, want, bytearray(self.mem.read(argi, nbytes))))
            else:
                sets.append((argi, want, None))
        if timeout_ns == -1:  # plain select: struct timeval in arg 4
            if args[4]:
                sec, usec = struct.unpack("<qq", self.mem.read(args[4], 16))
                timeout_ns = sec * simtime.SECOND + usec * 1000
            else:
                timeout_ns = None

        entries: dict[int, int] = {}
        for _ptr, want, bits in sets:
            if bits is None:
                continue
            for fd in range(nfds):
                if bits[fd // 8] & (1 << (fd % 8)):
                    entries[fd] = entries.get(fd, 0) | want

        ready_fds = 0
        outs = []
        for ptr, want, bits in sets:
            if bits is None:
                outs.append((ptr, None))
                continue
            out = bytearray(nbytes)
            for fd in range(nfds):
                if bits[fd // 8] & (1 << (fd % 8)):
                    if self._poll_revents(fd, want) & (want | POLLERR | POLLHUP):
                        out[fd // 8] |= 1 << (fd % 8)
                        ready_fds += 1
            outs.append((ptr, out))

        if ready_fds or timeout_ns == 0 or ctx.wake == "timeout":
            for ptr, out in outs:
                if out is not None:
                    self.mem.write(ptr, bytes(out))
            return ready_fds
        self._block_on_files(list(entries.items()),
                             self._remaining(ctx, timeout_ns), ctx)

    def _sys_pselect6(self, args, ctx) -> int:
        tsp = args[4]
        if tsp:
            sec, nsec = struct.unpack("<qq", self.mem.read(tsp, 16))
            timeout_ns = sec * simtime.SECOND + nsec
        else:
            timeout_ns = None
        return self._sys_select(args, ctx, timeout_ns=timeout_ns)

    def _sys_epoll_create(self, args, ctx) -> int:
        return self._vfd(Epoll())

    def _sys_epoll_create1(self, args, ctx) -> int:
        return self._vfd(Epoll(), cloexec=bool(args[0] & SOCK_CLOEXEC))

    def _sys_epoll_ctl(self, args, ctx) -> int:
        ep = self._file(args[0])
        if not isinstance(ep, Epoll):
            raise errors.SyscallError(errors.EINVAL)
        op, fd = _i32(args[1]), _i32(args[2])
        if not self.has_vfd(fd):
            # native fds can't join a simulated interest list; Linux says
            # EPERM for files that don't support epoll
            raise errors.SyscallError(errors.EPERM)
        target = self._file(fd)
        if op == EPOLL_CTL_DEL:
            ep.remove(target)
            return 0
        raw = self.mem.read(args[3], 12)  # packed epoll_event
        events, data = struct.unpack("<IQ", raw)
        interest = EpollEvents(0)
        if events & POLLIN:
            interest |= EpollEvents.IN
        if events & POLLOUT:
            interest |= EpollEvents.OUT
        if events & (1 << 31):
            interest |= EpollEvents.ET
        if events & (1 << 30):
            interest |= EpollEvents.ONESHOT
        if op == EPOLL_CTL_ADD:
            ep.add(target, interest, data=(fd, data))
        elif op == EPOLL_CTL_MOD:
            ep.modify(target, interest, data=(fd, data))
        else:
            raise errors.SyscallError(errors.EINVAL)
        return 0

    def _sys_epoll_wait(self, args, ctx) -> int:
        ep = self._file(args[0])
        if not isinstance(ep, Epoll):
            raise errors.SyscallError(errors.EINVAL)
        evp, maxev, tmo_ms = args[1], _i32(args[2]), _i32(args[3])
        if maxev <= 0:
            raise errors.SyscallError(errors.EINVAL)
        got = ep.ready(maxev)
        if got:
            out = bytearray(12 * len(got))
            for i, (data, hits) in enumerate(got):
                fd, user_data = data if isinstance(data, tuple) else (0, 0)
                ev = 0
                if hits & EpollEvents.IN:
                    ev |= POLLIN
                if hits & EpollEvents.OUT:
                    ev |= POLLOUT
                if hits & EpollEvents.HUP:
                    ev |= POLLHUP
                if hits & EpollEvents.ERR:
                    ev |= POLLERR
                struct.pack_into("<IQ", out, i * 12, ev, user_data)
            self.mem.write(evp, bytes(out))
            return len(got)
        timeout_ns = None if tmo_ms < 0 else tmo_ms * MS
        if timeout_ns == 0 or ctx.wake == "timeout":
            return 0
        raise errors.Blocked(ep, FileState.READABLE,
                             timeout_ns=self._remaining(ctx, timeout_ns))

    def _sys_epoll_pwait(self, args, ctx) -> int:
        return self._sys_epoll_wait(args, ctx)

    # -- time / sleep / random -----------------------------------------

    def _sys_nanosleep(self, args, ctx) -> int:
        if ctx.wake == "timeout":
            return 0
        delay = self._sleep_ns(args[0], absolute=False, clockid=0)
        if delay <= 0:
            return 0
        raise errors.Blocked(None, FileState.NONE, timeout_ns=delay)

    def _sys_clock_nanosleep(self, args, ctx) -> int:
        if ctx.wake == "timeout":
            return 0
        TIMER_ABSTIME = 1
        delay = self._sleep_ns(args[2], absolute=bool(args[1] & TIMER_ABSTIME),
                               clockid=_i32(args[0]))
        if delay <= 0:
            return 0
        raise errors.Blocked(None, FileState.NONE, timeout_ns=delay)

    def _sleep_ns(self, req_addr: int, absolute: bool, clockid: int) -> int:
        sec, nsec = struct.unpack("<qq", self.mem.read(req_addr, 16))
        t = sec * simtime.SECOND + nsec
        if absolute:
            now = (self.host.now() if clockid in simtime.MONOTONIC_CLOCK_IDS
                   else simtime.emulated_from_sim(self.host.now()))
            t -= now
        return max(0, t)

    def _sys_time_read(self, args, ctx) -> int:
        """clock_gettime / gettimeofday / time arriving over IPC.

        Normally these are answered INSIDE the shim from the shared clock
        (`shim_sys.c:25-80`); they reach us only before the first clock
        publish or when the shim exhausted its runahead bound. In the
        latter case the shim's local clock is ahead of the host clock —
        park until simulated time catches up (the reference's
        SYS_shadow_yield barrier, `shim_sys.c:225`), then answer from the
        merged clock via the slow path."""
        pc = getattr(self.process, "proc_clock", None)
        if pc is not None and ctx.wake is None:
            ahead = pc.sim_time_ns - self.host.now()
            if ahead > 0:
                raise errors.Blocked(None, FileState.NONE, timeout_ns=ahead)
        raise NativeSyscall()  # SyscallServer answers from the merged clock

    # shim-owned signals: SIGSEGV carries the rdtsc trap-and-emulate
    # handler, SIGSYS the seccomp trampoline. An app install would clobber
    # interposition process-wide (reference: the shim interposes sigaction
    # to protect its signals, `shim/src/lib.rs`).
    _SHIM_OWNED_SIGNALS = (11, 31)  # SIGSEGV, SIGSYS

    SA_RESTART = 0x10000000
    _SIG_UNBLOCKABLE = (9, 19)  # SIGKILL, SIGSTOP

    def _sys_rt_sigaction(self, args, ctx) -> int:
        signum = _i32(args[0])
        if signum in self._SHIM_OWNED_SIGNALS and args[1]:
            # pretend success without replacing the shim's handler; reads
            # (act==NULL) still pass through natively
            return 0
        if args[1] and signum not in self._SIG_UNBLOCKABLE:
            # record the disposition for virtual delivery (the native
            # install still happens below, so the handler really runs in
            # the managed process when we forward the signal)
            handler_ptr, flags = struct.unpack(
                "<QQ", self.mem.read(args[1], 16))
            if handler_ptr == 0:
                kind = "default"
            elif handler_ptr == 1:
                kind = "ignore"
            else:
                kind = "handler"
            self.sig_actions[signum] = (kind,
                                        bool(flags & self.SA_RESTART))
        raise NativeSyscall()

    # default-ignore dispositions (signal(7)); stop/continue job control
    # (SIGSTOP/SIGTSTP/SIGTTIN/SIGTTOU/SIGCONT) is not modeled — treated
    # as ignore rather than terminate
    _SIG_DEFAULT_IGNORE = (17, 18, 19, 20, 21, 22, 23, 28)

    def signal_disposition(self, sig: int) -> tuple[str, bool]:
        rec = self.sig_actions.get(sig)
        if rec is not None:
            return rec
        if sig in self._SIG_DEFAULT_IGNORE:
            return "ignore", False
        return "default", False

    def _sys_getrandom(self, args, ctx) -> int:
        bufp, n = args[0], min(args[1], 1 << 20)
        # deterministic bytes from the host RNG stream (`random.rs` handler;
        # same role as preload-openssl's deterministic RNG)
        out = bytearray()
        while len(out) < n:
            out += struct.pack("<Q", self.host.rng.next_u64())
        self.mem.write(bufp, bytes(out[:n]))
        return n

    # -- pipes / eventfd / timerfd (`handler/{eventfd,timerfd}.rs`,
    #    `descriptor/pipe.rs`) -------------------------------------------

    def _sys_pipe(self, args, ctx, flags: int = 0) -> int:
        r, w = make_pipe()
        if flags & O_NONBLOCK:
            r.nonblocking = w.nonblocking = True
        cloexec = bool(flags & O_CLOEXEC)
        rfd = self._vfd(r, cloexec)
        wfd = self._vfd(w, cloexec)
        self.mem.write(args[0], struct.pack("<ii", rfd, wfd))
        return 0

    def _sys_pipe2(self, args, ctx) -> int:
        return self._sys_pipe(args, ctx, flags=_i32(args[1]))

    def _sys_eventfd(self, args, ctx, flags: int = 0) -> int:
        ev = EventFd(args[0] & 0xFFFFFFFF, semaphore=bool(flags & EFD_SEMAPHORE))
        ev.nonblocking = bool(flags & O_NONBLOCK)
        return self._vfd(ev, cloexec=bool(flags & O_CLOEXEC))

    def _sys_eventfd2(self, args, ctx) -> int:
        return self._sys_eventfd(args, ctx, flags=_i32(args[1]))

    def _sys_timerfd_create(self, args, ctx) -> int:
        clockid, flags = _i32(args[0]), _i32(args[1])
        if clockid not in (0, 1, 7):  # REALTIME, MONOTONIC, BOOTTIME
            raise errors.SyscallError(errors.EINVAL)
        tfd = TimerFd(self.host)
        tfd.clockid = clockid
        tfd.nonblocking = bool(flags & O_NONBLOCK)
        return self._vfd(tfd, cloexec=bool(flags & O_CLOEXEC))

    def _read_itimerspec(self, addr: int) -> tuple[int, int]:
        """(interval_ns, value_ns) from a struct itimerspec."""
        isec, insec, vsec, vnsec = struct.unpack("<qqqq", self.mem.read(addr, 32))
        return (isec * simtime.SECOND + insec, vsec * simtime.SECOND + vnsec)

    def _write_itimerspec(self, addr: int, interval_ns: int,
                          value_ns: Optional[int]) -> None:
        v = value_ns or 0
        self.mem.write(addr, struct.pack(
            "<qqqq", interval_ns // simtime.SECOND, interval_ns % simtime.SECOND,
            v // simtime.SECOND, v % simtime.SECOND))

    def _sys_timerfd_settime(self, args, ctx) -> int:
        tfd = self._file(args[0])
        if not isinstance(tfd, TimerFd):
            raise errors.SyscallError(errors.EINVAL)
        flags = _i32(args[1])
        interval_ns, value_ns = self._read_itimerspec(args[2])
        if args[3]:
            rem, old_int = tfd.gettime()
            self._write_itimerspec(args[3], old_int, rem)
        if value_ns and (flags & TFD_TIMER_ABSTIME):
            # absolute REALTIME deadlines are relative to the emulated epoch
            if getattr(tfd, "clockid", 1) == 0:
                value_ns -= simtime.EMUTIME_SIMULATION_START_UNIX_NS
            tfd.settime(max(1, value_ns), interval_ns, absolute=True)
        else:
            tfd.settime(value_ns, interval_ns, absolute=False)
        return 0

    def _sys_timerfd_gettime(self, args, ctx) -> int:
        tfd = self._file(args[0])
        if not isinstance(tfd, TimerFd):
            raise errors.SyscallError(errors.EINVAL)
        rem, interval = tfd.gettime()
        self._write_itimerspec(args[1], interval, rem)
        return 0

    # -- multi-message send/recv (`recvmmsg(2)`/`sendmmsg(2)`) -----------

    MMSGHDR_SIZE = 64  # msghdr (56) + u32 msg_len + 4 pad

    def _sys_recvmmsg(self, args, ctx) -> int:
        """Loop of recvmsg: the first message may block (honoring the
        timeout argument), later ones stop at EWOULDBLOCK with the
        partial count (Linux semantics)."""
        fd, vecp, vlen = args[0], args[1], args[2] & 0xFFFFFFFF
        flags = _i32(args[3])
        vlen = min(vlen, 1024)
        if vlen == 0:
            return 0
        if ctx.wake == "timeout":
            raise errors.SyscallError(errors.EWOULDBLOCK)
        timeout_ns = None
        if args[4]:
            sec, nsec = struct.unpack("<qq", self.mem.read(args[4], 16))
            timeout_ns = sec * simtime.SECOND + nsec
        done = 0
        sub_ctx = DispatchCtx(None, None, ctx.thread)
        while done < vlen:
            msgp = vecp + done * self.MMSGHDR_SIZE
            # only the FIRST datagram may block; later ones stop the loop
            sub_flags = flags if done == 0 else flags | MSG_DONTWAIT
            sub = [fd, msgp, sub_flags, 0, 0, 0]
            try:
                got = self._sys_recvmsg(sub, sub_ctx)
            except errors.Blocked as b:
                if done == 0:
                    if timeout_ns is not None:
                        raise errors.Blocked(
                            b.file, b.state_mask, timeout_ns=timeout_ns,
                            restartable=b.restartable) from None
                    raise
                break
            except errors.SyscallError:
                if done == 0:
                    raise
                break  # partial count now; the error surfaces next call
            self.mem.write(msgp + 56, struct.pack("<I", got & 0xFFFFFFFF))
            done += 1
        return done

    def _sys_sendmmsg(self, args, ctx) -> int:
        """Known divergence: Linux blocks inside EACH sendmsg on a
        blocking socket; re-dispatching a partially-sent batch after a
        park would duplicate the messages already sent, so only the first
        message may block here — later would-blocks return the partial
        count (the API contract callers must handle anyway). Persistent
        socket errors surface on the caller's next syscall from socket
        state, like sk_err."""
        fd, vecp, vlen = args[0], args[1], args[2] & 0xFFFFFFFF
        vlen = min(vlen, 1024)
        if vlen == 0:
            return 0
        done = 0
        while done < vlen:
            msgp = vecp + done * self.MMSGHDR_SIZE
            sub = [fd, msgp, args[3], 0, 0, 0]
            try:
                sent = self._sys_sendmsg(sub, ctx)
            except (errors.Blocked, errors.SyscallError):
                if done == 0:
                    raise
                break
            self.mem.write(msgp + 56, struct.pack("<I", sent & 0xFFFFFFFF))
            done += 1
        return done

    # -- statx on simulated descriptors ----------------------------------

    AT_EMPTY_PATH = 0x1000
    STATX_BASIC_STATS = 0x7FF

    def _sys_statx(self, args, ctx) -> int:
        """statx(2) for virtual fds via AT_EMPTY_PATH; path-based forms
        route through the per-host filesystem view (regular files are
        native in this design)."""
        dirfd, flags = _i32(args[0]), _i32(args[2])
        if not flags & self.AT_EMPTY_PATH or not self.has_vfd(dirfd):
            return self._vfs_one_path(args, "statx", 1, False)
        file = self._file(dirfd)
        mode, ino = self._vfd_stat_identity(file)
        # struct statx: mask(4) blksize(4) attributes(8) nlink(4) uid(4)
        # gid(4) mode(2) pad(2) ino(8) size(8) blocks(8) ...
        buf = bytearray(256)
        struct.pack_into("<IIQIIIHH", buf, 0, self.STATX_BASIC_STATS, 4096,
                         0, 1, 0, 0, mode, 0)
        struct.pack_into("<QQQ", buf, 32, ino, 0, 0)
        self.mem.write(args[4], bytes(buf))
        return 0

    # -- signal-mask virtualization (`handler/signal.rs` rt_sigprocmask) --

    def _sys_rt_sigprocmask(self, args, ctx) -> int:
        """Fully virtualized blocked-signal mask. A native execution would
        run inside the shim's SIGSYS handler, where the kernel restores
        uc_sigmask at sigreturn and silently undoes the change — so the
        simulator's per-thread mask is the single authority: it selects
        the delivery recipient and holds process-wide signals pending
        while every thread blocks them (reference: shim-shmem
        blocked_signals, `shim_shmem.rs:139-404`)."""
        SIG_BLOCK, SIG_UNBLOCK, SIG_SETMASK = 0, 1, 2
        how, setp, oldp = _i32(args[0]), args[1], args[2]
        if args[3] != 8:  # sigsetsize must be 64-bit
            raise errors.SyscallError(errors.EINVAL)
        # validate BEFORE any user-memory write: the kernel leaves oldset
        # untouched on EINVAL
        if setp and how not in (SIG_BLOCK, SIG_UNBLOCK, SIG_SETMASK):
            raise errors.SyscallError(errors.EINVAL)
        thread = ctx.thread
        if thread is None:
            raise NativeSyscall()
        old = getattr(thread, "sig_blocked", 0)
        if oldp:
            self.mem.write(oldp, struct.pack("<Q", old))
        if setp:
            (mask,) = struct.unpack("<Q", self.mem.read(setp, 8))
            if how == SIG_BLOCK:
                thread.sig_blocked = old | mask
            elif how == SIG_UNBLOCK:
                thread.sig_blocked = old & ~mask
            else:  # SIG_SETMASK
                thread.sig_blocked = mask
            unblocked = old & ~thread.sig_blocked
            if unblocked:
                self.process.signals_unblocked(unblocked)
        return 0

    def _sys_rt_sigsuspend(self, args, ctx) -> int:
        """sigsuspend(2): swap in the given mask, park until a signal
        delivery unparks us (always EINTR), restore the old mask on the
        way out (`_deliver_handled` handles the restore since delivery
        completes the park without a re-dispatch)."""
        thread = ctx.thread
        if thread is None:
            raise NativeSyscall()
        if args[1] != 8:
            raise errors.SyscallError(errors.EINVAL)
        (mask,) = struct.unpack("<Q", self.mem.read(args[0], 8))
        opened = thread.sig_blocked & ~mask
        thread.suspend_saved = thread.sig_blocked
        thread.sig_blocked = mask
        if opened:
            self.process.signals_unblocked(opened)
        raise errors.Blocked(None, FileState.NONE, restartable=False,
                             forever=True)

    def _sys_rt_sigtimedwait(self, args, ctx) -> int:
        """sigwait/sigtimedwait: consume a pending (or next-delivered)
        signal from the set without running its handler. Delivery
        completes the park via `_complete_sigwait`; this body only
        handles entry and timeout."""
        thread = ctx.thread
        if thread is None:
            raise NativeSyscall()
        if ctx.wake == "timeout":
            thread.sigwait_set = 0
            thread.sigwait_info_ptr = 0
            raise errors.SyscallError(errors.EAGAIN)
        (waitset,) = struct.unpack("<Q", self.mem.read(args[0], 8))
        # SIGKILL/SIGSTOP can't be waited for (Linux silently drops them)
        waitset &= ~((1 << 8) | (1 << 18))
        # already-pending process signal in the set: consume right away
        for sig in sorted(self.process._pending_signals):
            if waitset & (1 << (sig - 1)):
                self.process._pending_signals.discard(sig)
                self.write_siginfo(args[1], sig)
                return sig
        timeout_ns = None
        if args[2]:
            sec, nsec = struct.unpack("<qq", self.mem.read(args[2], 16))
            timeout_ns = sec * simtime.SECOND + nsec
            if timeout_ns == 0:
                raise errors.SyscallError(errors.EAGAIN)
        thread.sigwait_set = waitset
        thread.sigwait_info_ptr = args[1]
        raise errors.Blocked(None, FileState.NONE, timeout_ns=timeout_ns,
                             restartable=False, forever=timeout_ns is None)

    # -- itimers / alarm (`handler/time.rs:31-100`: ITIMER_REAL only,
    # SIGALRM in simulated time; per-process, not inherited on fork) -----

    def _itimer_arm(self, deadline_ns: int, interval_ns: int) -> None:
        from ..core.event import TaskRef

        self._itimer_gen += 1
        gen = self._itimer_gen
        self._itimer_deadline = deadline_ns
        self._itimer_interval = interval_ns
        self.host.schedule_task_at(
            TaskRef(lambda h: self._itimer_fire(gen), "itimer-real"),
            deadline_ns)

    def _itimer_fire(self, gen: int) -> None:
        if gen != self._itimer_gen:
            return  # disarmed or re-armed since
        from .process import ProcessState

        if self.process.state != ProcessState.RUNNING:
            # process gone: drop the timer instead of re-arming forever
            self._itimer_disarm()
            return
        if self._itimer_interval > 0:
            self._itimer_arm(self.host.now() + self._itimer_interval,
                             self._itimer_interval)
        else:
            self._itimer_deadline = None
        self.process.deliver_signal(SIGALRM)

    def _itimer_disarm(self) -> tuple[int, int]:
        """Returns (remaining_ns, interval_ns) of the old timer."""
        rem = 0
        if self._itimer_deadline is not None:
            rem = max(0, self._itimer_deadline - self.host.now())
        old_interval = self._itimer_interval
        self._itimer_gen += 1
        self._itimer_deadline = None
        self._itimer_interval = 0
        return rem, old_interval

    def _read_itimerval(self, addr: int) -> tuple[int, int]:
        """(interval_ns, value_ns) from struct itimerval (timevals)."""
        isec, iusec, vsec, vusec = struct.unpack(
            "<qqqq", self.mem.read(addr, 32))
        if min(isec, iusec, vsec, vusec) < 0 or max(iusec, vusec) >= 10**6:
            raise errors.SyscallError(errors.EINVAL)
        return (isec * simtime.SECOND + iusec * 1000,
                vsec * simtime.SECOND + vusec * 1000)

    def _write_itimerval(self, addr: int, interval_ns: int,
                         value_ns: int) -> None:
        self.mem.write(addr, struct.pack(
            "<qqqq",
            interval_ns // simtime.SECOND,
            (interval_ns % simtime.SECOND) // 1000,
            value_ns // simtime.SECOND,
            (value_ns % simtime.SECOND) // 1000))

    def _itimer_current(self) -> tuple[int, int]:
        rem = 0
        if self._itimer_deadline is not None:
            rem = max(0, self._itimer_deadline - self.host.now())
        return self._itimer_interval, rem

    def _sys_pause(self, args, ctx) -> int:
        """pause(2): park until a signal delivery unparks us; the EINTR
        completion after the handler runs IS the contract (never
        restartable, `signal(7)`)."""
        raise errors.Blocked(None, FileState.NONE, restartable=False,
                             forever=True)

    def _sys_getitimer(self, args, ctx) -> int:
        if _i32(args[0]) != ITIMER_REAL:
            raise errors.SyscallError(errors.EINVAL)
        interval, rem = self._itimer_current()
        self._write_itimerval(args[1], interval, rem)
        return 0

    def _sys_setitimer(self, args, ctx) -> int:
        if _i32(args[0]) != ITIMER_REAL:
            raise errors.SyscallError(errors.EINVAL)
        old_interval, old_rem = self._itimer_current()
        interval_ns, value_ns = self._read_itimerval(args[1])
        if args[2]:
            self._write_itimerval(args[2], old_interval, old_rem)
        if value_ns == 0:
            self._itimer_disarm()
        else:
            self._itimer_arm(self.host.now() + value_ns, interval_ns)
        return 0

    def _sys_alarm(self, args, ctx) -> int:
        """alarm(2): seconds-granular ITIMER_REAL; returns whole seconds
        remaining of the previous alarm (rounded up, like Linux)."""
        seconds = args[0] & 0xFFFFFFFF
        old_rem, _old_int = self._itimer_disarm()
        if seconds:
            self._itimer_arm(self.host.now() + seconds * simtime.SECOND, 0)
        return -(-old_rem // simtime.SECOND)  # ceil to seconds

    def _sys_times(self, args, ctx) -> int:
        """times(2): returns elapsed sim time in clock ticks (100/s);
        the tms CPU-time fields mirror the simulated-CPU charge."""
        ticks = self.host.now() * 100 // simtime.SECOND
        cpu_ticks = 0
        if self.host.cpu is not None:
            cpu_ticks = (self.host.cpu._time_cursor * 100) // simtime.SECOND
        if args[0]:
            self.mem.write(args[0], struct.pack(
                "<qqqq", cpu_ticks, 0, 0, 0))
        return ticks

    def _sys_clock_getres(self, args, ctx) -> int:
        clock_id = _i32(args[0])
        if clock_id < 0 or clock_id > 11:
            raise errors.SyscallError(errors.EINVAL)
        if args[1]:
            self.mem.write(args[1], struct.pack("<qq", 0, 1))  # 1 ns
        return 0

    def _sys_sched_setaffinity(self, args, ctx) -> int:
        # accepted and ignored: managed threads are pinned by the
        # scheduler, not the app (`sched.rs` does the same)
        return 0

    # -- futex (`futex.c`, `handler/futex.rs`) ---------------------------

    def _sys_futex(self, args, ctx) -> int:
        uaddr, op, val = args[0], _i32(args[1]), args[2] & 0xFFFFFFFF
        cmd = op & kfutex.FUTEX_CMD_MASK
        if cmd in (kfutex.FUTEX_WAIT, kfutex.FUTEX_WAIT_BITSET):
            thread = ctx.thread
            if ctx.wake == "file":
                if thread is not None:
                    thread.futex_waiter = None
                return 0
            if ctx.wake == "timeout":
                w = thread.futex_waiter if thread is not None else None
                if thread is not None:
                    thread.futex_waiter = None
                if w is not None:
                    # a wake may have popped this waiter at the same sim
                    # instant the timeout fired; the wake already counted
                    # it, so losing it here would strand another waiter
                    if w.state & FileState.FUTEX_WAKEUP:
                        return 0
                    self.futexes.remove_waiter(w)
                return -errors.ETIMEDOUT
            (cur,) = struct.unpack("<I", self.mem.read(uaddr, 4))
            if cur != val:
                return -errors.EAGAIN
            timeout_ns = None
            if args[3]:
                sec, nsec = struct.unpack("<qq", self.mem.read(args[3], 16))
                t = sec * simtime.SECOND + nsec
                if cmd == kfutex.FUTEX_WAIT_BITSET:
                    # absolute deadline; realtime clocks sit on the epoch
                    now = (simtime.emulated_from_sim(self.host.now())
                           if op & kfutex.FUTEX_CLOCK_REALTIME
                           else self.host.now())
                    t -= now
                timeout_ns = max(0, t)
            bitset = (args[5] & 0xFFFFFFFF
                      if cmd == kfutex.FUTEX_WAIT_BITSET else kfutex.MATCH_ANY)
            if bitset == 0:
                raise errors.SyscallError(errors.EINVAL)
            waiter = self.futexes.add_waiter(uaddr, bitset)
            if thread is not None:
                thread.futex_waiter = waiter
            raise errors.Blocked(waiter, FileState.FUTEX_WAKEUP,
                                 timeout_ns=timeout_ns)
        if cmd in (kfutex.FUTEX_WAKE, kfutex.FUTEX_WAKE_BITSET):
            bitset = (args[5] & 0xFFFFFFFF
                      if cmd == kfutex.FUTEX_WAKE_BITSET else kfutex.MATCH_ANY)
            if bitset == 0:
                raise errors.SyscallError(errors.EINVAL)
            return self.futexes.wake(uaddr, max(0, _i32(args[2])), bitset)
        if cmd in (kfutex.FUTEX_REQUEUE, kfutex.FUTEX_CMP_REQUEUE):
            if cmd == kfutex.FUTEX_CMP_REQUEUE:
                (cur,) = struct.unpack("<I", self.mem.read(uaddr, 4))
                if cur != (args[5] & 0xFFFFFFFF):
                    return -errors.EAGAIN
            woken, moved = self.futexes.requeue(
                uaddr, max(0, _i32(args[2])), args[4], max(0, _i32(args[3]))
            )
            # CMP_REQUEUE returns woken+requeued; plain REQUEUE only woken
            return woken + moved if cmd == kfutex.FUTEX_CMP_REQUEUE else woken
        raise errors.SyscallError(errors.ENOSYS)

    # -- process family (`handler/{wait,clone,unistd}.rs`) ---------------

    def _sys_wait4(self, args, ctx) -> int:
        # pid_t is 32-bit: the register may carry -1 zero-extended
        # (0xFFFFFFFF), which _i64 would misread as 4294967295
        pid, options = _i32(args[0]), _i32(args[2])
        proc = self.process
        children = getattr(proc, "children", [])

        def matches(c):
            return pid in (-1, 0) or pid == c.pid

        candidates = [c for c in children
                      if matches(c) and not getattr(c, "reaped", False)]
        if not candidates:
            raise errors.SyscallError(errors.ECHILD)
        for c in candidates:
            if not c.is_alive:
                c.reaped = True
                if c.kill_signal is not None:
                    status = c.kill_signal & 0x7F
                else:
                    status = ((c.exit_status or 0) & 0xFF) << 8
                if args[1]:
                    self.mem.write(args[1], struct.pack("<i", status))
                return c.pid
        if options & WNOHANG:
            return 0
        if ctx.wake == "timeout":
            return 0
        raise errors.Blocked(proc.child_waiter, FileState.CHILD_EVENTS)

    def _sys_getppid(self, args, ctx) -> int:
        parent = getattr(self.process, "parent", None)
        if parent is not None and parent.is_alive:
            return parent.pid
        return 1

    def _sys_kill_family(self, args, ctx, nr: int) -> int:
        """kill/tkill/tgkill with virtual-pid translation and VIRTUAL
        delivery (`process.rs:1309`): the signal's effect happens at
        simulated time under simulator control — a default-terminate
        signal kills the target deterministically through the process
        plane (no native-kill race with the death watcher), a handled
        signal is forwarded natively (so the app's handler really runs)
        after interrupting any parked syscalls per SA_RESTART.

        kill(2) group forms: 0 = the caller's process group, -pgid = that
        group, -1 = every process on the host (`kill(2)`)."""
        # pid_t is 32-bit: decode as i32 so a zero-extended -1/-pgid in
        # the register reads correctly (same hazard as wait4)
        if nr == SYS_kill:
            target, sig = _i32(args[0]), _i32(args[1])
        else:  # tgkill(tgid, tid, sig): process-granularity delivery
            target, sig = _i32(args[0]), _i32(args[2])
            if target <= 0:
                raise errors.SyscallError(errors.EINVAL)
        if nr == SYS_kill and target <= 0:
            # group forms — including -pid of a group leader, which
            # addresses the whole group (fork children included), not
            # just the leader
            victims = self._group_targets(target)
            if not victims:
                raise errors.SyscallError(errors.ESRCH)
            self._check_signum(sig)
            if sig == 0:
                return 0
            # deterministic order; the caller last so its own death (or
            # EINTR) doesn't cut the group delivery short
            victims.sort(key=lambda p: (p is self.process, p.pid))
            for v in victims:
                self._deliver_to(v, sig)
            return 0
        victim = self._target_process(target)
        if victim is None:
            raise errors.SyscallError(errors.ESRCH)
        self._check_signum(sig)
        if sig == 0:
            return 0  # existence probe
        self._deliver_to(victim, sig)
        return 0

    @staticmethod
    def _check_signum(sig: int) -> None:
        """valid_signal(): EINVAL for sig outside [0, 64]. Linux checks
        this AFTER the pid lookup (check_kill_permission runs on a found
        task), so ESRCH for a bogus pid wins over EINVAL for a bogus
        signal. Without this a guest kill(pid, -1) would reach
        deliver_signal's 1 << (sig-1) and crash the worker with a
        negative-shift ValueError."""
        if sig < 0 or sig > 64:
            raise errors.SyscallError(errors.EINVAL)

    def _deliver_to(self, victim, sig: int) -> None:
        deliver = getattr(victim, "deliver_signal", None)
        if deliver is not None:  # managed native process
            deliver(sig, self_directed=victim is self.process)
            return
        stop = getattr(victim, "stop", None)
        if stop is not None:  # coroutine SimProcess: no handlers to run
            if sig not in self._SIG_DEFAULT_IGNORE:
                stop(sig)
            return
        raise errors.SyscallError(errors.ESRCH)

    def _group_targets(self, target: int) -> list:
        """Alive processes matched by a kill(2) group form."""
        if target == 0:
            pgid = getattr(self.process, "pgid", self.process.pid)
        elif target == -1:
            pgid = None  # broadcast
        else:
            pgid = -target
        out = []
        for proc in getattr(self.host, "processes", []):
            if not getattr(proc, "is_alive", False):
                continue
            if pgid is None:
                # kill(-1) broadcasts to everyone EXCEPT the caller
                if proc is not self.process:
                    out.append(proc)
            elif getattr(proc, "pgid", proc.pid) == pgid:
                out.append(proc)
        return out

    def _target_process(self, vpid: int):
        """Positive-pid lookup (kill's <=0 group forms route through
        _group_targets; tgkill rejects tgid <= 0 before this)."""
        return None if vpid <= 0 else self._proc_by_vpid(vpid)

    def _sys_kill(self, args, ctx) -> int:
        return self._sys_kill_family(args, ctx, SYS_kill)

    def _sys_tgkill(self, args, ctx) -> int:
        return self._sys_kill_family(args, ctx, SYS_tgkill)

    # -- process groups / sessions (`process.rs` groups, `setpgid(2)`) ---

    def _proc_by_vpid(self, vpid: int):
        if vpid == 0 or vpid == self.process.pid:
            return self.process
        for other in getattr(self.host, "processes", []):
            if getattr(other, "pid", None) == vpid \
                    and getattr(other, "is_alive", False):
                return other
        return None

    def _sys_getpgrp(self, args, ctx) -> int:
        return getattr(self.process, "pgid", self.process.pid)

    def _sys_getpgid(self, args, ctx) -> int:
        proc = self._proc_by_vpid(_i32(args[0]))
        if proc is None:
            raise errors.SyscallError(errors.ESRCH)
        return getattr(proc, "pgid", proc.pid)

    def _sys_setpgid(self, args, ctx) -> int:
        pid, pgid = _i32(args[0]), _i32(args[1])
        if pgid < 0:
            raise errors.SyscallError(errors.EINVAL)
        proc = self._proc_by_vpid(pid)
        if proc is None:
            raise errors.SyscallError(errors.ESRCH)
        # POSIX: only self or our children may be moved (ESRCH for an
        # unrelated pid), and a session leader's group may never change
        if proc is not self.process \
                and getattr(proc, "parent", None) is not self.process:
            raise errors.SyscallError(errors.ESRCH)
        if getattr(proc, "sid", proc.pid) == proc.pid:
            raise errors.SyscallError(errors.EPERM)
        target_pgid = pgid or proc.pid
        if target_pgid != proc.pid:
            # joining a group: it must exist in the caller's session
            owner = next(
                (p for p in getattr(self.host, "processes", [])
                 if getattr(p, "pgid", p.pid) == target_pgid
                 and getattr(p, "is_alive", False)), None)
            if owner is None or getattr(owner, "sid", owner.pid) != \
                    getattr(proc, "sid", proc.pid):
                raise errors.SyscallError(errors.EPERM)
        proc.pgid = target_pgid
        return 0

    def _sys_setsid(self, args, ctx) -> int:
        proc = self.process
        if getattr(proc, "pgid", proc.pid) == proc.pid:
            # a group leader can't start a session (`setsid(2)`)
            raise errors.SyscallError(errors.EPERM)
        # ...nor may a group with our pid already exist elsewhere (groups
        # never span sessions)
        for other in getattr(self.host, "processes", []):
            if other is not proc and getattr(other, "is_alive", False) \
                    and getattr(other, "pgid", other.pid) == proc.pid:
                raise errors.SyscallError(errors.EPERM)
        proc.pgid = proc.pid
        proc.sid = proc.pid
        return proc.pid

    def _sys_getsid(self, args, ctx) -> int:
        proc = self._proc_by_vpid(_i32(args[0]))
        if proc is None:
            raise errors.SyscallError(errors.ESRCH)
        return getattr(proc, "sid", proc.pid)

    def _sys_set_tid_address(self, args, ctx) -> int:
        if ctx.thread is not None:
            ctx.thread.ctid_addr = args[0]
            return ctx.thread.native_tid or 0
        return 0

    def _sys_set_robust_list(self, args, ctx) -> int:
        return 0  # recorded nowhere: robust-futex death handling is native

    # -- identity / topology (`handler/{sched,sysinfo,prctl}.rs`) --------

    def _sys_uname(self, args, ctx) -> int:
        """Deterministic utsname with the SIMULATED hostname
        (`handler/uname` analogue; nodename comes from the host)."""

        def field(s: str) -> bytes:
            b = s.encode()[:64]
            return b + b"\x00" * (65 - len(b))

        name = getattr(self.host, "name", "shadow-host")
        buf = (field("Linux") + field(name) + field("5.15.0-shadow")
               + field("#1 SMP shadow_tpu") + field("x86_64") + field("(none)"))
        self.mem.write(args[0], buf)
        return 0

    def _sys_sysinfo(self, args, ctx) -> int:
        """Deterministic sysinfo: uptime = simulated seconds, fixed memory
        figures (16 GiB total / 8 GiB free), zero load."""
        buf = struct.pack(
            "<q3Q6QHH4x2QI",
            self.host.now() // simtime.SECOND,  # uptime
            0, 0, 0,  # loads
            16 << 30, 8 << 30, 0, 0, 0, 0,  # ram/swap
            len(getattr(self.host, "processes", [])) or 1, 0,  # procs, pad
            0, 0,  # high mem
            1,  # mem_unit
        ).ljust(112, b"\x00")
        self.mem.write(args[0], buf)
        return 0

    def _sys_sched_yield(self, args, ctx) -> int:
        return 0

    def _sys_sched_getaffinity(self, args, ctx) -> int:
        size = args[2]
        if size < 8:
            raise errors.SyscallError(errors.EINVAL)
        # one deterministic CPU: runtimes size their pools predictably
        self.mem.write(args[1], struct.pack("<Q", 1))
        return 8

    def _sys_getcpu(self, args, ctx) -> int:
        if args[0]:
            self.mem.write(args[0], struct.pack("<I", 0))
        if args[1]:
            self.mem.write(args[1], struct.pack("<I", 0))
        return 0

    def _sys_clone3(self, args, ctx) -> int:
        # force glibc's fallback to classic clone, which the shim traps
        raise errors.SyscallError(errors.ENOSYS)

    def _sys_waitid(self, args, ctx) -> int:
        raise errors.SyscallError(errors.ENOSYS)  # callers fall back to wait4

    # -- table ----------------------------------------------------------

    # -- simulated identity (`handler/uid.rs` moral equivalent) ----------
    # Every managed process runs as the same deterministic unprivileged
    # identity regardless of which real user runs the simulator — results
    # must not depend on the invoking machine's uid.

    SIM_UID = 1000
    SIM_GID = 1000

    def _sys_getuid(self, args, ctx) -> int:
        return self.SIM_UID

    _sys_geteuid = _sys_getuid

    def _sys_getgid(self, args, ctx) -> int:
        return self.SIM_GID

    _sys_getegid = _sys_getgid

    def _sys_setuid(self, args, ctx) -> int:
        if _i32(args[0]) != self.SIM_UID:
            raise errors.SyscallError(errors.EPERM)
        return 0

    def _sys_setgid(self, args, ctx) -> int:
        if _i32(args[0]) != self.SIM_GID:
            raise errors.SyscallError(errors.EPERM)
        return 0

    def _sys_setresuid(self, args, ctx) -> int:
        # each of ruid/euid/suid must be -1 (keep) or the current id
        for a in args[:3]:
            if _i32(a) not in (-1, self.SIM_UID):
                raise errors.SyscallError(errors.EPERM)
        return 0

    def _sys_setresgid(self, args, ctx) -> int:
        for a in args[:3]:
            if _i32(a) not in (-1, self.SIM_GID):
                raise errors.SyscallError(errors.EPERM)
        return 0

    def _sys_getresuid(self, args, ctx) -> int:
        for ptr in args[:3]:
            if not ptr:
                raise errors.SyscallError(errors.EFAULT)
            self.mem.write(ptr, struct.pack("<I", self.SIM_UID))
        return 0

    def _sys_getresgid(self, args, ctx) -> int:
        for ptr in args[:3]:
            if not ptr:
                raise errors.SyscallError(errors.EFAULT)
            self.mem.write(ptr, struct.pack("<I", self.SIM_GID))
        return 0

    def _sys_getgroups(self, args, ctx) -> int:
        size, ptr = _i32(args[0]), args[1]
        if size == 0:
            return 1
        if size < 1:
            raise errors.SyscallError(errors.EINVAL)
        self.mem.write(ptr, struct.pack("<I", self.SIM_GID))
        return 1

    def _sys_setgroups(self, args, ctx) -> int:
        raise errors.SyscallError(errors.EPERM)  # needs CAP_SETGID

    # -- resource limits / accounting (deterministic) --------------------
    # The VISIBLE fd limit (1024) deliberately exceeds the KERNEL limit
    # on the native table (700, set at spawn): virtual fds live in
    # [700, 1024) and glibc validates fds against sysconf(_SC_OPEN_MAX)
    # — with the kernel value visible, posix_spawn_file_actions_adddup2
    # would reject every virtual fd with EBADF at add time.

    RLIM_INFINITY = 0xFFFFFFFFFFFFFFFF
    RLIMIT_NOFILE = 7
    RLIM_NOFILE = VISIBLE_FD_LIMIT

    def _rlimit(self, resource_id: int) -> tuple[int, int]:
        custom = self._rlimits.get(resource_id)
        if custom is not None:
            return custom
        if resource_id == self.RLIMIT_NOFILE:
            return (self.RLIM_NOFILE, self.RLIM_NOFILE)
        return (self.RLIM_INFINITY, self.RLIM_INFINITY)

    def _set_rlimit(self, resource_id: int, soft: int, hard: int) -> None:
        if soft > hard:
            raise errors.SyscallError(errors.EINVAL)
        _old_soft, old_hard = self._rlimit(resource_id)
        if hard > old_hard:
            raise errors.SyscallError(errors.EPERM)  # raising needs CAP
        self._rlimits[resource_id] = (soft, hard)

    def _sys_getrlimit(self, args, ctx) -> int:
        if _i32(args[0]) < 0 or _i32(args[0]) > 15:
            raise errors.SyscallError(errors.EINVAL)
        soft, hard = self._rlimit(_i32(args[0]))
        self.mem.write(args[1], struct.pack("<QQ", soft, hard))
        return 0

    def _sys_setrlimit(self, args, ctx) -> int:
        if _i32(args[0]) < 0 or _i32(args[0]) > 15:
            raise errors.SyscallError(errors.EINVAL)
        soft, hard = struct.unpack("<QQ", self.mem.read(args[1], 16))
        self._set_rlimit(_i32(args[0]), soft, hard)
        return 0

    def _sys_prlimit64(self, args, ctx) -> int:
        pid, res, new_ptr, old_ptr = (_i32(args[0]), _i32(args[1]),
                                      args[2], args[3])
        if pid not in (0, self.process.pid):
            # cross-process limit surgery isn't modeled
            raise errors.SyscallError(
                errors.ESRCH if self._proc_by_vpid(pid) is None
                else errors.EPERM)
        if res < 0 or res > 15:
            raise errors.SyscallError(errors.EINVAL)
        old = self._rlimit(res)  # snapshot BEFORE applying the new value
        if new_ptr:
            soft, hard = struct.unpack("<QQ", self.mem.read(new_ptr, 16))
            self._set_rlimit(res, soft, hard)
        if old_ptr:
            self.mem.write(old_ptr, struct.pack("<QQ", *old))
        return 0

    def _sys_getrusage(self, args, ctx) -> int:
        who = _i32(args[0])
        if who not in (0, -1, 1):  # SELF, CHILDREN, THREAD
            raise errors.SyscallError(errors.EINVAL)
        # deterministic: a fresh process's accounting (the CPU model
        # charges simulated time, not rusage counters — reporting real
        # rusage would leak wall-clock nondeterminism into the guest)
        self.mem.write(args[1], bytes(144))
        return 0

    # -- scheduling / priority (single deterministic CPU model) ----------

    def _sys_getpriority(self, args, ctx) -> int:
        which, who = _i32(args[0]), _i32(args[1])
        if which not in (0, 1, 2):
            raise errors.SyscallError(errors.EINVAL)
        # kernel ABI: returns 20 - nice (1..40)
        return 20 - self._nice

    def _sys_setpriority(self, args, ctx) -> int:
        which, _who, prio = _i32(args[0]), _i32(args[1]), _i32(args[2])
        if which not in (0, 1, 2):
            raise errors.SyscallError(errors.EINVAL)
        nice = max(-20, min(19, prio))
        if nice < self._nice:
            raise errors.SyscallError(errors.EACCES)  # lowering needs CAP
        self._nice = nice
        return 0

    def _sys_sched_getscheduler(self, args, ctx) -> int:
        return 0  # SCHED_OTHER

    def _sys_sched_setscheduler(self, args, ctx) -> int:
        if _i32(args[1]) != 0:  # only SCHED_OTHER without privilege
            raise errors.SyscallError(errors.EPERM)
        return 0

    def _sys_sched_getparam(self, args, ctx) -> int:
        if not args[1]:
            raise errors.SyscallError(errors.EFAULT)
        self.mem.write(args[1], struct.pack("<i", 0))  # sched_priority 0
        return 0

    # -- memory-mapping family -------------------------------------------
    # The mappings THEMSELVES run natively (each managed process owns a
    # real address space); the simulated kernel's job is validation the
    # native kernel can't do — a virtual fd must never leak to a native
    # mmap (the raw number would map some unrelated simulator fd) — and
    # region-map bookkeeping (managed.py marks the region cache dirty on
    # every MAPPING_SYSCALLS member before dispatch).

    MAP_ANONYMOUS = 0x20

    def _sys_mmap(self, args, ctx) -> int:
        length, flags, fd = args[1], _i32(args[3]), _i32(args[4])
        if length == 0:
            raise errors.SyscallError(errors.EINVAL)
        if not flags & self.MAP_ANONYMOUS and fd >= 0:
            if fd >= self.VFD_BASE or fd in self._low_overrides:
                # sockets/pipes aren't mmap-able (Linux: ENODEV)
                self._file(fd)  # EBADF for a dead virtual fd
                raise errors.SyscallError(errors.ENODEV)
        raise NativeSyscall()

    def _sys_munmap(self, args, ctx) -> int:
        if args[0] & 0xFFF or args[1] == 0:
            raise errors.SyscallError(errors.EINVAL)
        raise NativeSyscall()

    def _sys_mprotect(self, args, ctx) -> int:
        if args[0] & 0xFFF:
            raise errors.SyscallError(errors.EINVAL)
        raise NativeSyscall()

    def _sys_mremap(self, args, ctx) -> int:
        if args[0] & 0xFFF or args[1] == 0:
            raise errors.SyscallError(errors.EINVAL)
        raise NativeSyscall()

    def _sys_brk(self, args, ctx) -> int:
        raise NativeSyscall()  # dispatched for the region-cache mark

    def _sys_msync(self, args, ctx) -> int:
        MS_ASYNC, MS_SYNC = 1, 4
        flags = _i32(args[2])
        if args[0] & 0xFFF or (flags & MS_ASYNC and flags & MS_SYNC):
            raise errors.SyscallError(errors.EINVAL)
        raise NativeSyscall()

    def _sys_madvise(self, args, ctx) -> int:
        if args[0] & 0xFFF:
            raise errors.SyscallError(errors.EINVAL)
        raise NativeSyscall()

    def _sys_mlock_family(self, args, ctx) -> int:
        # deterministic no-op success: real mlock can fail with ENOMEM
        # under RLIMIT_MEMLOCK depending on the invoking machine, and
        # pinning pages buys a simulated process nothing
        return 0

    _sys_mlock = _sys_mlock_family
    _sys_munlock = _sys_mlock_family
    _sys_mlockall = _sys_mlock_family
    _sys_munlockall = _sys_mlock_family

    # -- privileged operations: deterministic unprivileged denial --------

    def _sys_eperm(self, args, ctx) -> int:
        raise errors.SyscallError(errors.EPERM)

    _sys_chroot = _sys_eperm
    _sys_mount = _sys_eperm
    _sys_umount2 = _sys_eperm
    _sys_settimeofday = _sys_eperm
    _sys_clock_settime = _sys_eperm

    def _sys_sendfile(self, args, ctx) -> int:
        out_fd, in_fd = _i32(args[0]), _i32(args[1])
        out_virtual = out_fd >= self.VFD_BASE \
            or out_fd in self._low_overrides
        in_virtual = in_fd >= self.VFD_BASE or in_fd in self._low_overrides
        if not out_virtual and not in_virtual:
            raise NativeSyscall()  # file->file: the kernel handles it
        if in_virtual:
            self._file(in_fd)  # EBADF check
            # sockets/pipes aren't pread-able sources (Linux: EINVAL)
            raise errors.SyscallError(errors.EINVAL)
        # native file -> virtual socket: refuse with EINVAL so the app
        # takes its read/write fallback path (what nginx/libcurl do on
        # sendfile EINVAL/ENOSYS); emulating it would need pidfd_getfd
        # access to the guest's native fd
        self._file(out_fd)  # EBADF check
        raise errors.SyscallError(errors.EINVAL)


    # ==================================================================
    # file family: the per-host filesystem view (reference
    # `handler/file.c:1-429` + `fileat.c:1-508`, re-designed as path
    # REDIRECTION: managed fds are real kernel fds here, so execution
    # stays native and the simulator virtualizes the NAMESPACE instead —
    # absolute non-system paths land under `host.vfs_root`, with
    # read-through to the real path for base-layer files. Deterministic
    # strace prints the GUEST-visible path.)
    # ==================================================================

    def _read_path(self, addr) -> bytes:
        """NUL-terminated guest string (path-sized). Chunks never cross
        a page boundary: a string ending near the top of the last mapped
        page must not drag the read into the unmapped neighbor
        (process_vm_readv fails the WHOLE iovec on any fault)."""
        addr = int(addr) & (2**64 - 1)
        if addr == 0:
            raise errors.SyscallError(errors.EFAULT)
        out = b""
        while len(out) < 4096:
            pos = addr + len(out)
            span = min(256, 4096 - (pos & 0xFFF))
            chunk = self.mem.read(pos, span)
            nul = chunk.find(0)
            if nul >= 0:
                return out + chunk[:nul]
            out += chunk
        raise errors.SyscallError(errors.ENAMETOOLONG)

    def _vfs_root(self):
        if not getattr(self.host, "vfs_enabled", False):
            return None
        root = getattr(self.host, "vfs_root", None)
        if root is None:
            return None
        return root if isinstance(root, bytes) else root.encode()

    def _vfs_resolve(self, path: bytes, write: bool,
                     mirror_dir: bool = False):
        """None = leave the path alone (relative, system prefix, already
        host-local, or a base-layer read); else the redirected bytes."""
        import os as _os

        root = self._vfs_root()
        if root is None or not path.startswith(b"/"):
            return None
        # collapse ".." BEFORE any prefix decision: "/tmp/../usr/x" IS
        # /usr/x (system), and "/a/../../x" must not climb out of the
        # per-host root. (Escapes via guest-created symlinks inside the
        # virtual tree are not chased — documented limitation.)
        norm = _os.path.normpath(path)
        if norm.startswith(root):
            return None  # app echoed a virtualized path back to us
        if any(norm.startswith(p) or norm == p.rstrip(b"/")
               for p in VFS_SYSTEM_PREFIXES):
            return None
        host_dir = getattr(self.host, "vfs_host_dir", None)
        if host_dir and norm.startswith(
                host_dir if isinstance(host_dir, bytes)
                else host_dir.encode()):
            return None  # the host data dir itself (cwd outputs)
        virt = root + norm
        if len(virt) > VFS_PATH_MAX:
            # isolation would need a longer path than the rewrite event
            # carries. Failing the syscall with ENAMETOOLONG is the
            # only safe verdict: the old fall-through to the shared
            # real path silently BROKE per-host isolation for
            # deep-but-legal guest paths (two hosts writing the same
            # long absolute path would collide), and the guest sees
            # exactly what a real kernel with a shorter PATH_MAX would
            # return
            _LOG.warning(
                "guest path too long for per-host redirect "
                "(%d > %d incl. vfs root), failing with ENAMETOOLONG: "
                "%r", len(virt), VFS_PATH_MAX, path)
            raise errors.SyscallError(errors.ENAMETOOLONG)
        if write:
            parent = virt.rsplit(b"/", 1)[0]
            try:
                _os.makedirs(parent, exist_ok=True)
            except OSError:
                pass
            if not _os.path.lexists(virt):
                # copy-up: a write-class op on a BASE-layer file must see
                # the base content (append, read-modify-write, rename);
                # dirs mirror as empty nodes (chdir, O_TMPFILE targets)
                try:
                    if _os.path.isdir(norm):
                        if mirror_dir:
                            _os.makedirs(virt, exist_ok=True)
                    elif _os.path.isfile(norm):
                        import shutil as _shutil

                        _shutil.copy2(norm, virt)
                except OSError:
                    pass
            return virt
        return virt if _os.path.lexists(virt) else None

    @staticmethod
    def _render_path(p: bytes) -> str:
        return '"' + p.decode(errors="replace") + '"'

    @staticmethod
    def _render_small(v) -> str:
        """fds/flags render as ints; anything address-sized masks (the
        deterministic-strace contract: no ASLR-dependent values)."""
        u = int(v) & (2**64 - 1)
        s = u - 2**64 if u >= 2**63 else u
        return str(s) if -4096 <= s < (1 << 24) else "<ptr>"

    def _vfs_active(self) -> bool:
        return self._vfs_root() is not None \
            or getattr(self.process, "strace", None) is not None

    def _vfs_one_path(self, args, name: str, arg_idx: int, write: bool,
                      mirror_dir: bool = False, tail: str = ""):
        """Shared shape: resolve the single path argument, raise the
        native(-rewrite) verdict with a guest-visible strace line."""
        if not self._vfs_active():
            raise NativeSyscall()  # nothing to redirect, nobody to log to
        path = self._read_path(args[arg_idx])
        pre = ", ".join(self._render_small(args[i]) for i in range(arg_idx))
        disp = (pre + ", " if pre else "") + self._render_path(path) + tail
        red = self._vfs_resolve(path, write, mirror_dir=mirror_dir)
        if red is None:
            raise NativeSyscall(strace_args=disp)
        raise NativeSyscallRewrite({arg_idx: red}, strace_args=disp)

    def _vfs_two_paths(self, args, name: str, idx_a: int, idx_b: int):
        """rename/link shapes: both paths are write-class."""
        if not self._vfs_active():
            raise NativeSyscall()
        pa = self._read_path(args[idx_a])
        pb = self._read_path(args[idx_b])
        disp = f"{self._render_path(pa)}, {self._render_path(pb)}"
        ra = self._vfs_resolve(pa, write=True)
        rb = self._vfs_resolve(pb, write=True)
        path_args = {}
        if ra is not None:
            path_args[idx_a] = ra
        if rb is not None:
            path_args[idx_b] = rb
        if not path_args:
            raise NativeSyscall(strace_args=disp)
        raise NativeSyscallRewrite(path_args, strace_args=disp)

    @staticmethod
    def _open_is_write(flags: int) -> bool:
        return bool(flags & (O_WRONLY | O_RDWR | O_CREAT | O_TRUNC
                             | O_APPEND)) or \
            (flags & O_TMPFILE) == O_TMPFILE

    def _sys_open(self, args, ctx) -> int:
        flags = _i32(args[1])
        return self._vfs_one_path(
            args, "open", 0, self._open_is_write(flags),
            mirror_dir=(flags & O_TMPFILE) == O_TMPFILE,
            tail=f", {flags:#o}")

    def _sys_creat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "creat", 0, True)

    def _sys_openat(self, args, ctx) -> int:
        flags = _i32(args[2])
        return self._vfs_one_path(
            args, "openat", 1, self._open_is_write(flags),
            mirror_dir=(flags & O_TMPFILE) == O_TMPFILE,
            tail=f", {flags:#o}")

    def _sys_stat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "stat", 0, False)

    def _sys_lstat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "lstat", 0, False)

    def _sys_access(self, args, ctx) -> int:
        return self._vfs_one_path(args, "access", 0, False)

    def _sys_faccessat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "faccessat", 1, False)

    def _sys_statfs(self, args, ctx) -> int:
        return self._vfs_one_path(args, "statfs", 0, False)

    def _sys_readlink(self, args, ctx) -> int:
        return self._vfs_one_path(args, "readlink", 0, False)

    def _sys_readlinkat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "readlinkat", 1, False)

    def _sys_utime_like(self, args, ctx) -> int:
        return self._vfs_one_path(args, "utime", 0, True)

    def _sys_utimensat(self, args, ctx) -> int:
        if int(args[1]) == 0:
            raise NativeSyscall()  # NULL path: operates on dirfd itself
        return self._vfs_one_path(args, "utimensat", 1, True)

    def _sys_chdir(self, args, ctx) -> int:
        # write-class with dir mirroring: entering a base-layer dir
        # creates the per-host twin so later RELATIVE writes stay
        # host-local (the whole point of the redirect)
        return self._vfs_one_path(args, "chdir", 0, True,
                                  mirror_dir=True)

    def _sys_mkdir(self, args, ctx) -> int:
        return self._vfs_one_path(args, "mkdir", 0, True)

    def _sys_mkdirat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "mkdirat", 1, True)

    def _sys_rmdir(self, args, ctx) -> int:
        return self._vfs_one_path(args, "rmdir", 0, True)

    def _sys_unlink(self, args, ctx) -> int:
        return self._vfs_one_path(args, "unlink", 0, True)

    def _sys_unlinkat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "unlinkat", 1, True)

    def _sys_chmod(self, args, ctx) -> int:
        return self._vfs_one_path(args, "chmod", 0, True)

    def _sys_fchmodat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "fchmodat", 1, True)

    def _sys_chown_like(self, args, ctx) -> int:
        return self._vfs_one_path(args, "chown", 0, True)

    def _sys_fchownat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "fchownat", 1, True)

    def _sys_truncate(self, args, ctx) -> int:
        return self._vfs_one_path(args, "truncate", 0, True)

    def _sys_rename(self, args, ctx) -> int:
        return self._vfs_two_paths(args, "rename", 0, 1)

    def _sys_renameat(self, args, ctx) -> int:
        return self._vfs_two_paths(args, "renameat", 1, 3)

    def _sys_link(self, args, ctx) -> int:
        return self._vfs_two_paths(args, "link", 0, 1)

    def _sys_linkat(self, args, ctx) -> int:
        return self._vfs_two_paths(args, "linkat", 1, 3)

    def _sys_symlink(self, args, ctx) -> int:
        # arg0 is the link CONTENT (never resolved); arg1 is the link
        # path to create
        return self._vfs_one_path(args, "symlink", 1, True)

    def _sys_symlinkat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "symlinkat", 2, True)

    def _sys_mknod_like(self, args, ctx) -> int:
        return self._vfs_one_path(args, "mknod", 0, True)

    def _sys_mknodat(self, args, ctx) -> int:
        return self._vfs_one_path(args, "mknodat", 1, True)

    # fd-only disk ops: a VIRTUAL descriptor (socket/pipe/timer) is not
    # a disk file — EINVAL (ENOTDIR for the dents family, like Linux);
    # real fds stay native
    def _fd_only_native(self, args, errno_for_vfd: int) -> int:
        fd = _i32(args[0])
        if fd >= self.VFD_BASE or fd in self._low_overrides:
            raise errors.SyscallError(errno_for_vfd)
        raise NativeSyscall()

    def _sys_fsync_like(self, args, ctx) -> int:
        return self._fd_only_native(args, errors.EINVAL)

    def _sys_getdents_like(self, args, ctx) -> int:
        return self._fd_only_native(args, errors.ENOTDIR)

    def _sys_fchdir(self, args, ctx) -> int:
        return self._fd_only_native(args, errors.ENOTDIR)

    def _sys_flock(self, args, ctx) -> int:
        return self._fd_only_native(args, errors.EINVAL)

    def _sys_getcwd(self, args, ctx) -> int:
        raise NativeSyscall(strace_args="<buf>")

    _HANDLERS = {
        SYS_open: _sys_open,
        SYS_openat: _sys_openat,
        SYS_creat: _sys_creat,
        SYS_stat: _sys_stat,
        SYS_lstat: _sys_lstat,
        SYS_access: _sys_access,
        SYS_faccessat: _sys_faccessat,
        SYS_faccessat2: _sys_faccessat,
        SYS_statfs: _sys_statfs,
        SYS_readlink: _sys_readlink,
        SYS_readlinkat: _sys_readlinkat,
        SYS_chdir: _sys_chdir,
        SYS_fchdir: _sys_fchdir,
        SYS_getcwd: _sys_getcwd,
        SYS_mkdir: _sys_mkdir,
        SYS_mkdirat: _sys_mkdirat,
        SYS_rmdir: _sys_rmdir,
        SYS_unlink: _sys_unlink,
        SYS_unlinkat: _sys_unlinkat,
        SYS_rename: _sys_rename,
        SYS_renameat: _sys_renameat,
        SYS_renameat2: _sys_renameat,
        SYS_link: _sys_link,
        SYS_linkat: _sys_linkat,
        SYS_symlink: _sys_symlink,
        SYS_symlinkat: _sys_symlinkat,
        SYS_chmod: _sys_chmod,
        SYS_fchmod: _sys_fsync_like,
        SYS_fchmodat: _sys_fchmodat,
        SYS_chown: _sys_chown_like,
        SYS_lchown: _sys_chown_like,
        SYS_fchownat: _sys_fchownat,
        SYS_truncate: _sys_truncate,
        SYS_ftruncate: _sys_fsync_like,
        SYS_fsync: _sys_fsync_like,
        SYS_fdatasync: _sys_fsync_like,
        SYS_fallocate: _sys_fsync_like,
        SYS_flock: _sys_flock,
        SYS_getdents: _sys_getdents_like,
        SYS_getdents64: _sys_getdents_like,
        SYS_mknod: _sys_mknod_like,
        SYS_mknodat: _sys_mknodat,
        SYS_utime: _sys_utime_like,
        SYS_utimes: _sys_utime_like,
        SYS_utimensat: _sys_utimensat,
        SYS_socket: _sys_socket,
        SYS_socketpair: _sys_socketpair,
        SYS_bind: _sys_bind,
        SYS_listen: _sys_listen,
        SYS_connect: _sys_connect,
        SYS_accept: _sys_accept,
        SYS_accept4: _sys_accept4,
        SYS_shutdown: _sys_shutdown,
        SYS_getsockname: _sys_getsockname,
        SYS_getpeername: _sys_getpeername,
        SYS_setsockopt: _sys_setsockopt,
        SYS_getsockopt: _sys_getsockopt,
        SYS_sendto: _sys_sendto,
        SYS_recvfrom: _sys_recvfrom,
        SYS_sendmsg: _sys_sendmsg,
        SYS_recvmsg: _sys_recvmsg,
        SYS_read: _sys_read,
        SYS_write: _sys_write,
        SYS_readv: _sys_readv,
        SYS_writev: _sys_writev,
        SYS_close: _sys_close,
        SYS_dup: _sys_dup,
        SYS_dup2: _sys_dup2,
        SYS_dup3: _sys_dup3,
        SYS_fstat: _sys_fstat,
        SYS_fcntl: _sys_fcntl,
        SYS_ioctl: _sys_ioctl,
        SYS_poll: _sys_poll,
        SYS_ppoll: _sys_ppoll,
        SYS_select: _sys_select,
        SYS_pselect6: _sys_pselect6,
        SYS_epoll_create: _sys_epoll_create,
        SYS_epoll_create1: _sys_epoll_create1,
        SYS_epoll_ctl: _sys_epoll_ctl,
        SYS_epoll_wait: _sys_epoll_wait,
        SYS_epoll_pwait: _sys_epoll_pwait,
        SYS_nanosleep: _sys_nanosleep,
        SYS_clock_nanosleep: _sys_clock_nanosleep,
        SYS_clock_gettime: _sys_time_read,
        SYS_gettimeofday: _sys_time_read,
        SYS_time: _sys_time_read,
        SYS_rt_sigaction: _sys_rt_sigaction,
        SYS_getrandom: _sys_getrandom,
        SYS_pipe: _sys_pipe,
        SYS_pipe2: _sys_pipe2,
        SYS_eventfd: _sys_eventfd,
        SYS_eventfd2: _sys_eventfd2,
        SYS_timerfd_create: _sys_timerfd_create,
        SYS_timerfd_settime: _sys_timerfd_settime,
        SYS_timerfd_gettime: _sys_timerfd_gettime,
        SYS_lseek: _sys_lseek,
        SYS_newfstatat: _sys_newfstatat,
        SYS_pause: _sys_pause,
        SYS_rt_sigprocmask: _sys_rt_sigprocmask,
        SYS_rt_sigsuspend: _sys_rt_sigsuspend,
        SYS_rt_sigtimedwait: _sys_rt_sigtimedwait,
        SYS_recvmmsg: _sys_recvmmsg,
        SYS_sendmmsg: _sys_sendmmsg,
        SYS_statx: _sys_statx,
        SYS_getitimer: _sys_getitimer,
        SYS_alarm: _sys_alarm,
        SYS_setitimer: _sys_setitimer,
        SYS_times: _sys_times,
        SYS_setpgid: _sys_setpgid,
        SYS_getpgrp: _sys_getpgrp,
        SYS_setsid: _sys_setsid,
        SYS_getpgid: _sys_getpgid,
        SYS_getsid: _sys_getsid,
        SYS_clock_getres: _sys_clock_getres,
        SYS_sched_setaffinity: _sys_sched_setaffinity,
        SYS_futex: _sys_futex,
        SYS_wait4: _sys_wait4,
        SYS_waitid: _sys_waitid,
        SYS_getppid: _sys_getppid,
        SYS_kill: _sys_kill,
        SYS_tgkill: _sys_tgkill,
        SYS_set_tid_address: _sys_set_tid_address,
        SYS_set_robust_list: _sys_set_robust_list,
        SYS_uname: _sys_uname,
        SYS_sysinfo: _sys_sysinfo,
        SYS_sched_yield: _sys_sched_yield,
        SYS_sched_getaffinity: _sys_sched_getaffinity,
        SYS_getcpu: _sys_getcpu,
        SYS_clone3: _sys_clone3,
        # identity
        SYS_getuid: _sys_getuid,
        SYS_geteuid: _sys_geteuid,
        SYS_getgid: _sys_getgid,
        SYS_getegid: _sys_getegid,
        SYS_setuid: _sys_setuid,
        SYS_setgid: _sys_setgid,
        SYS_setresuid: _sys_setresuid,
        SYS_setresgid: _sys_setresgid,
        SYS_getresuid: _sys_getresuid,
        SYS_getresgid: _sys_getresgid,
        SYS_getgroups: _sys_getgroups,
        SYS_setgroups: _sys_setgroups,
        # limits / accounting
        SYS_getrlimit: _sys_getrlimit,
        SYS_setrlimit: _sys_setrlimit,
        SYS_prlimit64: _sys_prlimit64,
        SYS_getrusage: _sys_getrusage,
        # scheduling / priority
        SYS_getpriority: _sys_getpriority,
        SYS_setpriority: _sys_setpriority,
        SYS_sched_getscheduler: _sys_sched_getscheduler,
        SYS_sched_setscheduler: _sys_sched_setscheduler,
        SYS_sched_getparam: _sys_sched_getparam,
        # memory-mapping family
        SYS_mmap: _sys_mmap,
        SYS_munmap: _sys_munmap,
        SYS_mprotect: _sys_mprotect,
        SYS_mremap: _sys_mremap,
        SYS_brk: _sys_brk,
        SYS_msync: _sys_msync,
        SYS_madvise: _sys_madvise,
        SYS_mlock: _sys_mlock,
        SYS_munlock: _sys_munlock,
        SYS_mlockall: _sys_mlockall,
        SYS_munlockall: _sys_munlockall,
        # privileged-op denial
        SYS_chroot: _sys_chroot,
        SYS_mount: _sys_mount,
        SYS_umount2: _sys_umount2,
        SYS_settimeofday: _sys_settimeofday,
        SYS_clock_settime: _sys_clock_settime,
        SYS_sendfile: _sys_sendfile,
    }
