"""Pure, dependency-injected TCP.

Parity: reference `src/lib/tcp/` (clean-room Rust TCP driven by a
`Dependencies` trait — clock and timers injected, nothing Shadow-specific)
*plus* the congestion machinery the Rust crate lacks, modeled on the legacy
stack: Reno congestion control (`src/main/host/descriptor/tcp_cong_reno.c`),
RFC 6298 retransmission timing (`tcp.c:1137-1170`), retransmit queue and
fast retransmit/recovery (`tcp.c`, `tcp_retransmit_tally.cc`).

Deliberately structured as a *pull-model state machine over plain integer
state* — the shape that transplants to a vmapped JAX step function in the
TPU plane (SURVEY.md §7 phase C): no callbacks into the environment except
the injected `Dependencies`, segments built on demand, all window/sequence
state as scalars.
"""

from .connection import (
    Dependencies,
    TcpConfig,
    TcpConnection,
    TcpError,
    TcpFlags,
    TcpState,
)
from .cong import RenoCongestion
from .rtt import RttEstimator

__all__ = [
    "Dependencies",
    "RenoCongestion",
    "RttEstimator",
    "TcpConfig",
    "TcpConnection",
    "TcpError",
    "TcpFlags",
    "TcpState",
]
