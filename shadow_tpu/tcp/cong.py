"""Reno congestion control, counted in segments.

Parity: reference `src/main/host/descriptor/tcp_cong_reno.c` — the three
phases and their transitions:

- slow start: cwnd += n per n newly-acked segments; on reaching ssthresh,
  carry the leftover acks into congestion avoidance (`tcp_cong_reno.c:70-93`);
- congestion avoidance: cwnd += 1 per cwnd acked segments, via an
  accumulator (`:110-120`);
- three duplicate acks (from slow start or avoidance): ssthresh = cwnd/2+1,
  cwnd = ssthresh + 3, enter fast recovery (`:50-66`); every further dup ack
  inflates cwnd += 1 (`:97-99`); the next new ack deflates cwnd = ssthresh
  and re-enters avoidance (`:101-107`);
- RTO timeout: ssthresh = cwnd/2+1, restart slow start (`:152-163`) —
  the reference restarts at cwnd=10, its initial-window constant
  (`tcp.c:2856`).

The whole state is four small ints — trivially SoA-packable for the TPU
per-connection step kernel.
"""

from __future__ import annotations

INITIAL_WINDOW = 10  # segments (`tcp.c:2856`)
_SSTHRESH_INF = (1 << 31) - 1

_SLOW_START = 0
_AVOIDANCE = 1
_RECOVERY = 2


class RenoCongestion:
    __slots__ = ("cwnd", "ssthresh", "phase", "dup_acks", "_avoid_acked")

    def __init__(self, initial_window: int = INITIAL_WINDOW):
        self.cwnd = initial_window  # segments
        self.ssthresh = _SSTHRESH_INF
        self.phase = _SLOW_START
        self.dup_acks = 0
        self._avoid_acked = 0

    def on_new_ack(self, n_segments: int) -> None:
        """`n_segments` newly acknowledged (cumulative-ack advance / MSS)."""
        self.dup_acks = 0
        if self.phase == _RECOVERY:
            self.cwnd = self.ssthresh
            self._enter_avoidance(n_segments)
        elif self.phase == _SLOW_START:
            new_cwnd = self.cwnd + n_segments
            if new_cwnd >= self.ssthresh:
                leftover = new_cwnd - self.ssthresh
                self.cwnd = self.ssthresh
                self._enter_avoidance(leftover)
            else:
                self.cwnd = new_cwnd
        else:
            self._avoid_tick(n_segments)

    def on_duplicate_ack(self) -> bool:
        """Returns True exactly when fast retransmit should fire (3rd dup)."""
        if self.phase == _RECOVERY:
            self.cwnd += 1  # window inflation
            return False
        self.dup_acks += 1
        if self.dup_acks == 3:
            self.ssthresh = self.cwnd // 2 + 1
            self.cwnd = self.ssthresh + 3
            self.phase = _RECOVERY
            return True
        return False

    def on_partial_ack(self, n_segments: int) -> None:
        """NewReno partial ack during fast recovery (RFC 6582): deflate by
        the amount acked, add back one segment, stay in recovery."""
        self.cwnd = max(1, self.cwnd - n_segments + 1)

    def on_timeout(self) -> None:
        self.dup_acks = 0
        self.ssthresh = self.cwnd // 2 + 1
        self.cwnd = INITIAL_WINDOW
        self.phase = _SLOW_START

    @property
    def in_fast_recovery(self) -> bool:
        return self.phase == _RECOVERY

    def _enter_avoidance(self, carried_acks: int) -> None:
        self.phase = _AVOIDANCE
        self._avoid_acked = 0
        if carried_acks:
            self._avoid_tick(carried_acks)

    def _avoid_tick(self, n: int) -> None:
        self._avoid_acked += n
        while self._avoid_acked >= self.cwnd:
            self._avoid_acked -= self.cwnd
            self.cwnd += 1
