"""The TCP connection state machine.

Parity: reference `src/lib/tcp/src/lib.rs` (TcpState + Dependencies-driven
design, typestate FSM `states.rs:23-120`) and the legacy stack's congestion
machinery (`src/main/host/descriptor/tcp.c`): Reno (`tcp_cong_reno.c`),
RFC 6298 RTO (`tcp.c:1137-1170`), fast retransmit on the third duplicate
ack, TIME_WAIT expiry, window scaling (`src/lib/tcp/src/window_scaling.rs`),
RTT from timestamp options with Karn's rule (`tcp.c:2314-2316`).

Design notes (TPU-first, SURVEY.md §7 phase C):
- *Pull model*: the environment asks for the next segment
  (`next_segment()`); the connection never pushes. The NIC/relay layer paces
  transmission, so bandwidth and congestion limits compose correctly.
- *Unwrapped stream offsets* internally (plain ints), 32-bit wrapping only
  at the header boundary — the kernel-facing arithmetic stays branch-light
  and array-packable.
- All mutable state is scalars + two byte buffers; the planned JAX port
  carries the scalars as SoA arrays and fixed-capacity ring buffers.
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from . import seq as seqmod
from .cong import RenoCongestion
from .rtt import RttEstimator

MSS = 1460  # CONFIG_TCP_MAX_SEGMENT_SIZE (`definitions.h:129`)
TIME_WAIT_NS = 60 * 1_000_000_000  # 2*MSL; Linux's 60s TIME_WAIT
MAX_WSCALE = 14  # RFC 7323 limit
SYN_RETRIES = 6  # Linux tcp_syn_retries default
DATA_RETRIES = 15  # Linux tcp_retries2 default


class TcpFlags(enum.IntFlag):
    NONE = 0
    FIN = 1
    SYN = 2
    RST = 4
    PSH = 8
    ACK = 16
    URG = 32


# plain-int twins for hot-path flag arithmetic: IntFlag's __and__/__or__
# re-enter the enum machinery on every test (measured ~25% of rung-3
# wall in enum internals); segments carry int flags at runtime and
# IntFlag's int interop keeps every external `==`/`&` comparison working
_FIN = int(TcpFlags.FIN)
_SYN = int(TcpFlags.SYN)
_RST = int(TcpFlags.RST)
_PSH = int(TcpFlags.PSH)
_ACK = int(TcpFlags.ACK)


class TcpState(enum.IntEnum):
    """FSM states (`src/lib/tcp/src/states.rs:23-120`, `tcp.c:38-52`)."""

    CLOSED = 0
    LISTEN = 1
    SYN_SENT = 2
    SYN_RCVD = 3
    ESTABLISHED = 4
    FIN_WAIT_1 = 5
    FIN_WAIT_2 = 6
    CLOSING = 7
    TIME_WAIT = 8
    CLOSE_WAIT = 9
    LAST_ACK = 10


class TcpError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = err
        super().__init__(msg or str(err))


class Dependencies(Protocol):
    """Everything the state machine needs from its host environment
    (reference `lib/tcp/src/lib.rs` `Dependencies` trait)."""

    def now(self) -> int:
        """Emulated time, ns."""

    def set_timer(self, delay_ns: int, callback: Callable[[], None]) -> None:
        """Run `callback` after `delay_ns`; no cancellation (callbacks must
        self-validate, which the connection does with generation counters)."""

    def random_u32(self) -> int:
        """Deterministic per-host randomness for the ISS."""

    def notify(self) -> None:
        """State changed outside a caller's stack frame (timer fire, inbound
        segment): the wrapper should refresh file state and, if
        `has_outgoing()`, tell the NIC."""


@dataclass
class TcpConfig:
    mss: int = MSS
    send_buffer: int = 131072
    recv_buffer: int = 174760
    # wscale is fixed at SYN time; when buffers may grow later (socket
    # autotuning), this names the ceiling the scale should cover (None =
    # recv_buffer, the static-buffer behavior)
    wscale_buffer: Optional[int] = None
    window_scaling: bool = True
    nagle: bool = False  # reference disables Nagle's algorithm
    sack: bool = True  # RFC 2018 selective acknowledgment


SACK_SLOTS = 16  # sender scoreboard capacity (tcp_retransmit_tally.cc)
SACK_WIRE_BLOCKS = 3  # blocks carried per segment (RFC 2018 w/ timestamps)


@dataclass
class Segment:
    """One outbound segment, protocol-level only (no addresses — the socket
    wrapper owns addressing)."""

    flags: int  # TcpFlags bits (plain int on the hot path)
    seq: int  # 32-bit wire value
    ack: int
    window: int  # as advertised on the wire (already scaled down)
    payload: bytes = b""
    window_scale: Optional[int] = None  # SYN only
    timestamp: int = 0
    timestamp_echo: int = 0
    sack_permitted: bool = False  # SYN only (RFC 2018 option)
    sack: tuple = ()  # ((wire_start, wire_end), ...) end exclusive


class _Reassembly:
    """Out-of-order segment store keyed by unwrapped stream offset."""

    __slots__ = ("segments",)

    def __init__(self):
        self.segments: dict[int, bytes] = {}

    def insert(self, off: int, data: bytes) -> None:
        existing = self.segments.get(off)
        if existing is None or len(existing) < len(data):
            self.segments[off] = data

    def drain_from(self, off: int) -> tuple[int, list[bytes]]:
        """Pop every byte contiguous from `off`; returns (new_off, chunks)."""
        chunks = []
        while True:
            best = None
            for start, data in self.segments.items():
                if start <= off < start + len(data):
                    best = start
                    break
            if best is None:
                break
            data = self.segments.pop(best)
            skip = off - best
            chunks.append(data[skip:])
            off += len(data) - skip
        # drop fully-covered stale segments
        for start in [s for s, d in self.segments.items() if s + len(d) <= off]:
            del self.segments[start]
        return off, chunks

    def byte_count(self) -> int:
        return sum(len(d) for d in self.segments.values())


class _SackScoreboard:
    """Sender-side tally of peer-held (SACKed) ranges, unwrapped stream
    offsets (`tcp_retransmit_tally.cc`).

    A FIXED slot algorithm, deliberately branch-simple so the device
    kernel (`tpu/tcp.py`) mirrors it slot-for-slot: `insert` clips to the
    cumulative ack, skips contained duplicates, extends the FIRST
    overlapping-or-touching slot once (no cascade merging), else takes
    the first empty slot (all full = drop the block); `prune` clips every
    slot to the advancing ack; `next_unsacked` walks chained ranges to
    the first hole and reports the distance to the next range above."""

    __slots__ = ("s", "e")

    INF = 1 << 62

    def __init__(self):
        self.s = [0] * SACK_SLOTS
        self.e = [0] * SACK_SLOTS

    def insert(self, start: int, end: int, una: int) -> None:
        start = max(start, una)
        if start >= end:
            return
        for i in range(SACK_SLOTS):  # contained in an existing range?
            if self.e[i] > self.s[i] and self.s[i] <= start \
                    and end <= self.e[i]:
                return
        for i in range(SACK_SLOTS):  # extend the first overlap/touch
            if self.e[i] > self.s[i] and start <= self.e[i] \
                    and self.s[i] <= end:
                self.s[i] = min(self.s[i], start)
                self.e[i] = max(self.e[i], end)
                return
        for i in range(SACK_SLOTS):  # first empty slot
            if self.e[i] <= self.s[i]:
                self.s[i], self.e[i] = start, end
                return

    def prune(self, una: int) -> None:
        for i in range(SACK_SLOTS):
            if self.e[i] > self.s[i]:
                self.s[i] = max(self.s[i], una)
                if self.s[i] >= self.e[i]:
                    self.s[i] = self.e[i] = 0

    def next_unsacked(self, off: int) -> tuple[int, int]:
        """(off', cap): first unsacked offset >= off; bytes until the next
        range above (INF when none)."""
        for _ in range(SACK_SLOTS):
            moved = False
            for i in range(SACK_SLOTS):
                if self.e[i] > self.s[i] and self.s[i] <= off < self.e[i]:
                    off = self.e[i]
                    moved = True
            if not moved:
                break
        cap = self.INF
        for i in range(SACK_SLOTS):
            if self.e[i] > self.s[i] and self.s[i] > off:
                cap = min(cap, self.s[i] - off)
        return off, cap


class TcpConnection:
    def __init__(self, deps: Dependencies, config: Optional[TcpConfig] = None):
        self.deps = deps
        self.config = config or TcpConfig()
        self.state = TcpState.CLOSED
        self.error: Optional[int] = None

        # --- send side (unwrapped stream offsets; 0 = first payload byte) ---
        self.iss = 0  # initial send sequence number (wire value of our SYN)
        self.snd_una = 0  # lowest unacked stream offset
        self.snd_nxt = 0  # next offset to transmit
        self.snd_wnd = self.config.mss  # peer-advertised window, bytes
        self.snd_buf = bytearray()  # bytes [snd_una, stream_len)
        self.stream_len = 0  # total bytes accepted from the app
        self.fin_requested = False
        self.fin_sent = False
        self.fin_acked = False
        self._syn_outstanding = False  # our SYN/SYN-ACK is in flight
        self._syn_sends = 0  # builds of our SYN; >1 means handshake retransmit
        self.syn_acked = False
        self._retx_pending = False  # rebuild a segment at snd_una
        self._probe_pending = False  # zero-window probe: 1 byte past window
        self._recover = 0  # NewReno fast-recovery point (snd_nxt at entry)
        self._gbn_high = 0  # go-back-N: resends below this are retransmits
        self.snd_max = 0  # highest stream offset ever transmitted (+FIN slot)
        self._rst_pending = False

        # --- receive side -------------------------------------------------
        self.irs = 0  # peer's ISS
        self.rcv_nxt = 0  # next expected stream offset
        self._reassembly = _Reassembly()
        self._ordered: deque[bytes] = deque()  # in-order, app-readable chunks
        self._ordered_bytes = 0
        self._error_consumed = False  # reset reported to the app once
        self.fin_received = False
        self._fin_offset: Optional[int] = None
        self._ack_pending = False

        # --- options ------------------------------------------------------
        self.my_wscale = 0
        self.peer_wscale = 0
        self._wscale_ok = False  # both sides negotiated scaling
        if self.config.window_scaling:
            ws = 0
            cover = self.config.wscale_buffer or self.config.recv_buffer
            while (cover >> ws) > 0xFFFF and ws < MAX_WSCALE:
                ws += 1
            self.my_wscale = ws
        self._last_ts_recv = 0  # peer timestamp to echo

        # --- SACK (RFC 2018; `tcp_retransmit_tally.cc`) --------------------
        self._sack_ok = False  # negotiated on the handshake
        self._sacked = _SackScoreboard()
        self.retransmitted_bytes = 0

        # --- timers / control ---------------------------------------------
        self.rtt = RttEstimator()
        self.cong = RenoCongestion()
        self._rto_gen = 0
        self._rto_armed = False
        self._persist_gen = 0
        self._persist_armed = False
        self.retransmit_count = 0

    # ==================================================================
    # application-facing API
    # ==================================================================

    def open_active(self) -> None:
        assert self.state == TcpState.CLOSED
        self.iss = self.deps.random_u32() & 0xFFFFFFFF
        self.state = TcpState.SYN_SENT
        self._arm_rto()

    def open_passive(self, syn: Segment) -> None:
        """Become the server side of a connection from a received SYN
        (the listener socket calls this on a fresh connection)."""
        assert self.state == TcpState.CLOSED
        assert syn.flags & _SYN
        self.iss = self.deps.random_u32() & 0xFFFFFFFF
        self.irs = syn.seq
        self.rcv_nxt = 0  # offset 0 == wire seq irs+1
        if syn.window_scale is not None and self.config.window_scaling:
            self.peer_wscale = min(syn.window_scale, MAX_WSCALE)
            self._wscale_ok = True
        else:
            self.my_wscale = 0
        self._sack_ok = syn.sack_permitted and self.config.sack
        self.snd_wnd = syn.window  # unscaled on SYN
        self._last_ts_recv = syn.timestamp
        self.state = TcpState.SYN_RCVD
        self._arm_rto()

    def write(self, data: bytes) -> int:
        """Queue bytes for sending; returns how many were accepted (0 means
        the send buffer is full — caller blocks on WRITABLE)."""
        if self.error is not None:
            raise TcpError(self.error)
        if self.state in (TcpState.CLOSED, TcpState.LISTEN):
            raise TcpError(107, "ENOTCONN")
        if self.fin_requested:
            raise TcpError(32, "EPIPE")
        space = self.send_space()
        n = min(space, len(data))
        if n:
            self.snd_buf.extend(data[:n])
            self.stream_len += n
            # Zero-window deadlock guard: if the peer already closed its
            # window, only the persist timer can get this data moving.
            if self.snd_wnd == 0 and self.is_established():
                self._arm_persist()
        return n

    def _raise_pending_error(self) -> bool:
        """Shared by read/peek: surface a pending error once when the
        ordered queue is drained; afterwards reads see EOF, like Linux.
        Returns True when the caller should return b"" (post-consumption)."""
        if self.error is None or self._ordered:
            return False
        if self._error_consumed:
            return True
        self._error_consumed = True
        raise TcpError(self.error)

    def read(self, max_bytes: int) -> bytes:
        """Pop in-order received bytes; b"" at EOF. Raises when unreadable."""
        if self._raise_pending_error():
            return b""
        out = []
        need = max_bytes
        while need > 0 and self._ordered:
            chunk = self._ordered[0]
            if len(chunk) <= need:
                out.append(chunk)
                self._ordered.popleft()
                need -= len(chunk)
            else:
                out.append(chunk[:need])
                self._ordered[0] = chunk[need:]
                need = 0
        got = b"".join(out)
        self._ordered_bytes -= len(got)
        if got:
            # The window just opened; push an update if we'd gone quiet.
            self._ack_pending = True
            self.deps.notify()
        return got

    def peek(self, max_bytes: int) -> bytes:
        """Non-consuming read of in-order bytes (recv MSG_PEEK): no queue
        mutation, no window-update side effects. Pending errors are still
        consumed-once, like Linux sk_err under MSG_PEEK."""
        if self._raise_pending_error():
            return b""
        out = []
        need = max_bytes
        for chunk in self._ordered:
            if need <= 0:
                break
            take = chunk[:need]
            out.append(take)
            need -= len(take)
        return b"".join(out)

    def close(self) -> None:
        """Orderly close of the send direction (app close())."""
        if self.state in (TcpState.CLOSED, TcpState.LISTEN):
            self.state = TcpState.CLOSED
            return
        if self.state == TcpState.SYN_SENT:
            self._enter_closed(None)
            return
        if self.fin_requested:
            return
        self.fin_requested = True
        if self.state in (TcpState.ESTABLISHED, TcpState.SYN_RCVD):
            self.state = TcpState.FIN_WAIT_1
        elif self.state == TcpState.CLOSE_WAIT:
            self.state = TcpState.LAST_ACK
        self.deps.notify()

    def abort(self) -> None:
        """Hard reset (SO_LINGER 0 close / process death)."""
        if self.state in (TcpState.CLOSED, TcpState.LISTEN, TcpState.TIME_WAIT):
            self.state = TcpState.CLOSED
            return
        self._rst_pending = True
        self.deps.notify()

    # -- poll surface for the socket wrapper ---------------------------

    def readable_bytes(self) -> int:
        return self._ordered_bytes

    def at_eof(self) -> bool:
        if self._ordered_bytes:
            return False
        return self.fin_received or self._error_consumed

    def send_space(self) -> int:
        return max(0, self.config.send_buffer - (self.stream_len - self.snd_una))

    def is_established(self) -> bool:
        return self.state >= TcpState.ESTABLISHED and self.state != TcpState.CLOSED

    # ==================================================================
    # segment egress (pull model)
    # ==================================================================

    def has_outgoing(self) -> bool:
        return self._next_kind() is not None

    def next_segment(self) -> Optional[Segment]:
        kind = self._next_kind()
        if kind is None:
            return None
        builder = getattr(self, f"_build_{kind}")
        before_nxt = self.snd_nxt
        seg = builder()
        # visible to the socket wrapper so retransmissions can be stamped
        # with SND_TCP_RETRANSMITTED for the tracker (`tracker.c:24-41`);
        # covers handshake RTOs (kind 'syn' rebuilt after _on_rto_fire),
        # data retransmits, zero-window probes, and go-back-N resends of
        # previously-transmitted data after an RTO
        gbn_resend = kind in ("data", "fin") and before_nxt < self._gbn_high
        if gbn_resend:
            self.retransmit_count += 1
        if gbn_resend or kind == "retransmit":
            self.retransmitted_bytes += len(seg.payload)
        self.last_segment_retransmit = (
            kind in ("retransmit", "probe")
            or (kind == "syn" and self._syn_sends > 1)
            or gbn_resend
        )
        return seg

    def _next_kind(self) -> Optional[str]:
        if self._rst_pending:
            return "rst"
        if self.state in (TcpState.SYN_SENT, TcpState.SYN_RCVD) and not self._syn_outstanding:
            return "syn"
        if self.state == TcpState.SYN_SENT:
            return None  # nothing else goes out until the handshake answers
        if self._retx_pending and self.snd_nxt > self.snd_una:
            return "retransmit"
        if self._probe_pending and self.stream_len > self.snd_nxt:
            return "probe"
        if self._can_send_new_data():
            return "data"
        if self._should_send_fin():
            return "fin"
        if self._ack_pending and self.state not in (TcpState.CLOSED,):
            return "ack"
        return None

    def _can_send_new_data(self) -> bool:
        if self.state not in (
            TcpState.ESTABLISHED,
            TcpState.CLOSE_WAIT,
            TcpState.FIN_WAIT_1,  # data queued before close() drains first
            TcpState.CLOSING,  # ditto, after a simultaneous close
            TcpState.LAST_ACK,
        ):
            return False
        if self.snd_nxt >= self.stream_len:
            return False
        in_flight = self.snd_nxt - self.snd_una
        window = min(self.cong.cwnd * self.config.mss, self.snd_wnd)
        return in_flight < window

    def _should_send_fin(self) -> bool:
        return (
            self.fin_requested
            and not self.fin_sent
            and self.snd_nxt >= self.stream_len
            and self.state
            in (TcpState.FIN_WAIT_1, TcpState.LAST_ACK, TcpState.CLOSING)
        )

    # -- builders -------------------------------------------------------

    def _wire_seq(self, off: int) -> int:
        """Stream offset -> 32-bit wire sequence (offset 0 == iss+1)."""
        return seqmod.add(self.iss, 1 + off)

    def _wire_ack(self) -> int:
        off = self.rcv_nxt + (1 if self.fin_received else 0)
        return seqmod.add(self.irs, 1 + off)

    def _recv_space(self) -> int:
        used = self._ordered_bytes + self._reassembly.byte_count()
        return max(0, self.config.recv_buffer - used)

    def _advertised_window(self, for_syn: bool) -> int:
        space = self._recv_space()
        if for_syn or not self._wscale_ok:
            return min(space, 0xFFFF)
        return min(space >> self.my_wscale, 0xFFFF)

    def _now_ms(self) -> int:
        return self.deps.now() // 1_000_000

    def _stamp(self, seg: Segment) -> Segment:
        seg.timestamp = self._now_ms() & 0xFFFFFFFF
        seg.timestamp_echo = self._last_ts_recv
        return seg

    def _sack_blocks(self) -> tuple:
        """Receiver SACK blocks from the reassembly store: the ranges
        NEAREST the ack point first (lowest start), merged when touching.
        Deterministic (stable across schedulers) and maximally useful to
        the sender, whose retransmissions fill the lowest holes first —
        as they fill, the 3-block window slides up the held ranges."""
        if not self._sack_ok or not self._reassembly.segments:
            return ()
        ranges = sorted(
            (start, start + len(data))
            for start, data in self._reassembly.segments.items()
        )
        merged: list[list[int]] = []
        for s, e in ranges:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        base = seqmod.add(self.irs, 1)
        return tuple(
            (seqmod.add(base, s), seqmod.add(base, e))
            for s, e in merged[:SACK_WIRE_BLOCKS]
        )

    def _build_syn(self) -> Segment:
        self._syn_outstanding = True
        self._syn_sends += 1
        if self._syn_sends > 1:
            self.retransmit_count += 1
        if self.state == TcpState.SYN_SENT:
            flags, ack = _SYN, 0
        else:  # SYN_RCVD: SYN|ACK
            flags, ack = _SYN | _ACK, self._wire_ack()
        self._ack_pending = False
        return self._stamp(
            Segment(
                flags=flags,
                seq=self.iss,
                ack=ack,
                window=self._advertised_window(for_syn=True),
                window_scale=self.my_wscale if self.config.window_scaling else None,
                sack_permitted=self.config.sack,
            )
        )

    def _build_data(self) -> Segment:
        off = self.snd_nxt
        # never (re)send bytes the peer already SACKed: jump the send
        # cursor over held ranges, cap the chunk at the next held range
        off2, cap = self._sacked.next_unsacked(off)
        if off2 != off:
            self.snd_nxt = off2
            self.snd_max = max(self.snd_max, off2)
            off = off2
        in_flight = off - self.snd_una
        window = min(self.cong.cwnd * self.config.mss, self.snd_wnd)
        n = min(self.config.mss, self.stream_len - off, window - in_flight,
                cap)
        if n <= 0:
            # everything in reach is already held by the peer
            return self._build_ack()
        payload = bytes(self.snd_buf[off - self.snd_una : off - self.snd_una + n])
        self.snd_nxt = off + n
        self.snd_max = max(self.snd_max, self.snd_nxt)
        self._ack_pending = False
        if not self._rto_armed:
            self._arm_rto()
        flags = _ACK
        if self.snd_nxt >= self.stream_len:
            flags |= _PSH
        return self._stamp(
            Segment(
                flags=flags,
                seq=self._wire_seq(off),
                ack=self._wire_ack(),
                window=self._advertised_window(False),
                payload=payload,
                sack=self._sack_blocks(),
            )
        )

    def _build_retransmit(self) -> Segment:
        self._retx_pending = False
        self.retransmit_count += 1
        off = self.snd_una
        # only payload bytes live in the buffer; the FIN slot retransmits
        # as a FIN. SACK: the hole ends where the peer's held data starts.
        _, cap = self._sacked.next_unsacked(off)
        n = min(self.config.mss, self.stream_len - off, cap)
        if n <= 0:
            if self.fin_sent:
                return self._build_fin(retransmit=True)
            return self._build_ack()
        payload = bytes(self.snd_buf[:n])
        if not self._rto_armed:
            self._arm_rto()
        return self._stamp(
            Segment(
                flags=_ACK,
                seq=self._wire_seq(off),
                ack=self._wire_ack(),
                window=self._advertised_window(False),
                payload=payload,
                sack=self._sack_blocks(),
            )
        )

    def _build_probe(self) -> Segment:
        """Zero-window probe: one byte beyond the advertised window."""
        self._probe_pending = False
        off = self.snd_nxt
        payload = bytes(self.snd_buf[off - self.snd_una : off - self.snd_una + 1])
        self.snd_nxt = off + 1
        self.snd_max = max(self.snd_max, self.snd_nxt)
        if not self._rto_armed:
            self._arm_rto()
        return self._stamp(
            Segment(
                flags=_ACK,
                seq=self._wire_seq(off),
                ack=self._wire_ack(),
                window=self._advertised_window(False),
                payload=payload,
                sack=self._sack_blocks(),
            )
        )

    def _build_fin(self, retransmit: bool = False) -> Segment:
        if not retransmit:
            self.fin_sent = True
            self.snd_nxt = self.stream_len + 1  # FIN occupies one seq slot
            self.snd_max = max(self.snd_max, self.snd_nxt)
        self._ack_pending = False
        if not self._rto_armed:
            self._arm_rto()
        return self._stamp(
            Segment(
                flags=_FIN | _ACK,
                seq=self._wire_seq(self.stream_len),
                ack=self._wire_ack(),
                window=self._advertised_window(False),
                sack=self._sack_blocks(),
            )
        )

    def _build_ack(self) -> Segment:
        self._ack_pending = False
        return self._stamp(
            Segment(
                flags=_ACK,
                seq=self._wire_seq(min(self.snd_nxt, self.stream_len + (1 if self.fin_sent else 0))),
                ack=self._wire_ack(),
                window=self._advertised_window(False),
                sack=self._sack_blocks(),
            )
        )

    def _build_rst(self) -> Segment:
        self._rst_pending = False
        seg = Segment(
            flags=_RST | _ACK,
            seq=self._wire_seq(min(self.snd_nxt, self.stream_len)),
            ack=self._wire_ack(),
            window=0,
        )
        self._enter_closed(104)  # ECONNRESET locally too
        return seg

    # ==================================================================
    # segment ingress
    # ==================================================================

    def on_segment(self, seg: Segment) -> None:
        if self.state == TcpState.CLOSED:
            # RFC 793: a segment (other than RST) arriving at a closed
            # connection elicits a RESET — without it, a peer stuck in
            # CLOSING/LAST_ACK retransmits its FIN into a silent void
            # until retry exhaustion (reachable once the wire is lossy;
            # both twins fixed together round 5, tpu/tcp.py _ev_segment)
            if not seg.flags & _RST:
                self._rst_pending = True
                self.deps.notify()
            return
        if seg.timestamp:
            self._last_ts_recv = seg.timestamp

        if self.state == TcpState.SYN_SENT:
            self._on_segment_syn_sent(seg)
            self.deps.notify()
            return

        # --- RST (any synchronized state) ------------------------------
        if seg.flags & _RST:
            if self.state == TcpState.TIME_WAIT:
                self._enter_closed(None)
            else:
                self._enter_closed(104)  # ECONNRESET
            self.deps.notify()
            return

        # --- SYN handling outside handshake -----------------------------
        if seg.flags & _SYN:
            if self.state == TcpState.SYN_RCVD and seg.seq == self.irs:
                # duplicate of the original SYN: re-send SYN|ACK
                self._syn_outstanding = False
                self.deps.notify()
                return
            if self.state == TcpState.TIME_WAIT:
                return  # new-connection reuse unsupported; ignore
            if seqmod.lt(seg.seq, seqmod.add(self.irs, 1 + self.rcv_nxt)):
                # old duplicate SYN below the window — e.g. a
                # retransmitted SYN|ACK when our handshake-completing
                # ACK was lost. RFC 793 p.69 / RFC 5961: answer with an
                # ACK (which completes the peer's handshake), never RST.
                # Both twins fixed together round 5 (tpu/tcp.py
                # _ev_segment); a lossy wire made this reachable.
                self._ack_pending = True
                self.deps.notify()
                return
            self._rst_pending = True
            self.deps.notify()
            return

        if seg.flags & _ACK:
            self._process_ack(seg)

        if seg.payload:
            self._process_payload(seg)

        if seg.flags & _FIN:
            self._process_fin(seg)

        self.deps.notify()

    def _on_segment_syn_sent(self, seg: Segment) -> None:
        if seg.flags & _RST:
            if seg.flags & _ACK and seg.ack == seqmod.add(self.iss, 1):
                self._enter_closed(111)  # ECONNREFUSED
            return
        if seg.flags & _SYN and seg.flags & _ACK:
            if seg.ack != seqmod.add(self.iss, 1):
                self._rst_pending = True
                return
            self.irs = seg.seq
            self.rcv_nxt = 0
            self.syn_acked = True
            self._syn_outstanding = False
            if seg.window_scale is not None and self.config.window_scaling:
                self.peer_wscale = min(seg.window_scale, MAX_WSCALE)
                self._wscale_ok = True
            else:
                self.my_wscale = 0
            self._sack_ok = seg.sack_permitted and self.config.sack
            self.snd_wnd = seg.window  # unscaled on SYN
            self.state = TcpState.ESTABLISHED
            self._ack_pending = True
            self._disarm_rto()
            if seg.timestamp_echo and self.rtt.backoff_count == 0:
                self.rtt.update(self._now_ms() - seg.timestamp_echo)
        elif seg.flags & _SYN:
            # simultaneous open
            self.irs = seg.seq
            self.rcv_nxt = 0
            if seg.window_scale is not None and self.config.window_scaling:
                self.peer_wscale = min(seg.window_scale, MAX_WSCALE)
                self._wscale_ok = True
            self._sack_ok = seg.sack_permitted and self.config.sack
            self.snd_wnd = seg.window
            self.state = TcpState.SYN_RCVD
            self._syn_outstanding = False  # rebuild as SYN|ACK
            # that rebuild is a NEW segment (first SYN|ACK), not a
            # handshake retransmission — don't let it count as one
            self._syn_sends = 0

    def _process_ack(self, seg: Segment) -> None:
        ack_off = self._unwrap_ack(seg.ack)
        if ack_off is None:
            return

        # SYN_RCVD: the handshake-completing ACK
        if self.state == TcpState.SYN_RCVD and ack_off >= 0:
            self.syn_acked = True
            self.state = TcpState.ESTABLISHED
            self._disarm_rto()
            if seg.timestamp_echo and self.rtt.backoff_count == 0:
                self.rtt.update(self._now_ms() - seg.timestamp_echo)

        if self._sack_ok and seg.sack:
            base = self._wire_seq(0)  # wire value of stream offset 0
            limit = max(self.snd_nxt, self.snd_max)
            for ws, we in seg.sack[:SACK_WIRE_BLOCKS]:
                s_off = seqmod.sub(ws, base)
                e_off = seqmod.sub(we, base)
                if s_off < (1 << 31) and e_off < (1 << 31) \
                        and s_off < e_off and e_off <= limit:
                    self._sacked.insert(s_off, e_off, self.snd_una)

        sent_end = self.snd_nxt
        fin_off = self.stream_len + 1 if self.fin_sent else None
        new_window = seg.window << (self.peer_wscale if self._wscale_ok else 0)

        if ack_off > self.snd_una:
            acked_bytes = min(ack_off, self.stream_len) - self.snd_una
            del self.snd_buf[:acked_bytes]
            self.snd_una = min(ack_off, self.stream_len)
            if fin_off is not None and ack_off >= fin_off:
                self.fin_acked = True
                self.snd_una = self.stream_len
            if self.snd_nxt < self.snd_una:
                self.snd_nxt = self.snd_una
            self._sacked.prune(self.snd_una)
            if acked_bytes > 0:
                n_seg = (acked_bytes + self.config.mss - 1) // self.config.mss
                if self.cong.in_fast_recovery and ack_off < self._recover:
                    # NewReno (RFC 6582): a partial ack means the next hole
                    # is also lost — retransmit it NOW, stay in recovery
                    self.cong.on_partial_ack(n_seg)
                    self._retx_pending = True
                else:
                    self.cong.on_new_ack(n_seg)
                    self._retx_pending = False
            else:
                self._retx_pending = False
            if seg.timestamp_echo and self.rtt.backoff_count == 0:
                self.rtt.update(self._now_ms() - seg.timestamp_echo)
            self.rtt.reset_backoff()
            # RTO restarts while anything is in flight
            if self.snd_nxt > self.snd_una or (self.fin_sent and not self.fin_acked):
                self._arm_rto()
            else:
                self._disarm_rto()
            self._on_fin_acked_transitions()
        elif (
            ack_off == self.snd_una
            and not seg.payload
            and self.snd_nxt > self.snd_una
            and new_window == self.snd_wnd
            and new_window > 0  # probe-elicited acks aren't loss signals
        ):
            if self.cong.on_duplicate_ack():
                self._retx_pending = True  # fast retransmit
                self._recover = self.snd_nxt  # NewReno recovery point

        self.snd_wnd = new_window
        if self.snd_wnd == 0 and self.stream_len > self.snd_nxt:
            self._arm_persist()

    def _unwrap_ack(self, wire_ack: int) -> Optional[int]:
        """Wire ack -> stream offset; None for an ack of data never sent
        (RFC 793: such acks must be ignored, not applied).

        Offsets near snd_una disambiguate the wrap: old duplicate acks map
        below snd_una (harmless), valid ones into [snd_una, snd_nxt]."""
        base = self._wire_seq(self.snd_una)
        delta = seqmod.sub(wire_ack, base)
        if delta < (1 << 31):
            off = self.snd_una + delta
            # bound by the ever-sent high-water mark, not snd_nxt: after a
            # go-back-N rollback, in-flight acks legitimately cover data
            # above the rolled-back snd_nxt
            if off > max(self.snd_nxt, self.snd_max):
                return None  # acks bytes we never transmitted
            return off
        return self.snd_una - seqmod.sub(base, wire_ack)

    def _on_fin_acked_transitions(self) -> None:
        if not self.fin_acked:
            return
        if self.state == TcpState.FIN_WAIT_1:
            self.state = TcpState.FIN_WAIT_2
        elif self.state == TcpState.CLOSING:
            self._enter_time_wait()
        elif self.state == TcpState.LAST_ACK:
            self._enter_closed(None)

    def _process_payload(self, seg: Segment) -> None:
        if self.state in (TcpState.TIME_WAIT,):
            self._ack_pending = True
            return
        seg_off = self.rcv_nxt + seqmod.sub(seg.seq, self._wire_rcv_nxt())
        if seg_off > self.rcv_nxt + (1 << 31):
            seg_off = self.rcv_nxt - seqmod.sub(self._wire_rcv_nxt(), seg.seq)

        data = seg.payload
        # trim left of rcv_nxt
        if seg_off < self.rcv_nxt:
            skip = self.rcv_nxt - seg_off
            if skip >= len(data):
                self._ack_pending = True  # pure duplicate
                return
            data = data[skip:]
            seg_off = self.rcv_nxt
        # trim right of the receive window
        space_end = self.rcv_nxt + self._recv_space()
        if seg_off >= space_end:
            self._ack_pending = True
            return
        if seg_off + len(data) > space_end:
            data = data[: space_end - seg_off]
        if data:
            self._reassembly.insert(seg_off, data)
            new_nxt, chunks = self._reassembly.drain_from(self.rcv_nxt)
            self.rcv_nxt = new_nxt
            for c in chunks:
                self._ordered.append(c)
                self._ordered_bytes += len(c)
        self._ack_pending = True
        self._maybe_apply_pending_fin()

    def _wire_rcv_nxt(self) -> int:
        return seqmod.add(self.irs, 1 + self.rcv_nxt)

    def _process_fin(self, seg: Segment) -> None:
        fin_off = self.rcv_nxt + seqmod.sub(
            seqmod.add(seg.seq, len(seg.payload)), self._wire_rcv_nxt()
        )
        if fin_off > self.rcv_nxt + (1 << 31):  # stale retransmitted fin
            fin_off = self.rcv_nxt
        self._fin_offset = fin_off if self._fin_offset is None else self._fin_offset
        self._ack_pending = True
        self._maybe_apply_pending_fin()

    def _maybe_apply_pending_fin(self) -> None:
        if self.fin_received or self._fin_offset is None:
            return
        if self._fin_offset > self.rcv_nxt:
            return  # data before the FIN still missing
        self.fin_received = True
        if self.state == TcpState.ESTABLISHED:
            self.state = TcpState.CLOSE_WAIT
        elif self.state == TcpState.FIN_WAIT_1:
            if self.fin_acked:
                self._enter_time_wait()
            else:
                self.state = TcpState.CLOSING
        elif self.state == TcpState.FIN_WAIT_2:
            self._enter_time_wait()

    # ==================================================================
    # timers
    # ==================================================================

    def _arm_rto(self) -> None:
        self._rto_gen += 1
        self._rto_armed = True
        gen = self._rto_gen
        self.deps.set_timer(self.rtt.rto_ns, lambda: self._on_rto_fire(gen))

    def _disarm_rto(self) -> None:
        self._rto_gen += 1
        self._rto_armed = False

    def _on_rto_fire(self, gen: int) -> None:
        if gen != self._rto_gen or self.state == TcpState.CLOSED:
            return
        self._rto_armed = False
        in_flight = (
            self.snd_nxt > self.snd_una
            or (self.fin_sent and not self.fin_acked)
            or self.state in (TcpState.SYN_SENT, TcpState.SYN_RCVD)
        )
        if not in_flight:
            return
        # Retry limits (Linux tcp_syn_retries / tcp_retries2): give up and
        # surface ETIMEDOUT rather than retransmitting forever.
        limit = (
            SYN_RETRIES
            if self.state in (TcpState.SYN_SENT, TcpState.SYN_RCVD)
            else DATA_RETRIES
        )
        if self.rtt.backoff_count >= limit:
            self._enter_closed(110)  # ETIMEDOUT
            return
        self.rtt.backoff()
        self.cong.on_timeout()
        if self.state in (TcpState.SYN_SENT, TcpState.SYN_RCVD):
            self._syn_outstanding = False  # rebuild the SYN / SYN|ACK
        else:
            # Go-back-N (pre-SACK TCP timeout recovery): the receiver may
            # have discarded any or all of the in-flight tail, so resend
            # everything unacked through normal slow-start-paced
            # transmission instead of trickling one MSS per (backed-off)
            # RTO. Segments below the old snd_nxt are stamped as
            # retransmissions. A pure unacked FIN lands here too (its
            # sequence slot keeps snd_nxt above snd_una) and re-sends via
            # fin_sent=False once the data, if any, drains.
            self._gbn_high = max(self._gbn_high, self.snd_nxt)
            self.snd_nxt = self.snd_una
            self._retx_pending = False
            if self.fin_sent and not self.fin_acked:
                self.fin_sent = False
            if self.snd_wnd == 0 and self.stream_len > self.snd_nxt:
                self._arm_persist()  # window may never reopen via acks
        self._arm_rto()
        self.deps.notify()

    def _arm_persist(self) -> None:
        if self._persist_armed:
            return
        self._persist_gen += 1
        self._persist_armed = True
        gen = self._persist_gen
        self.deps.set_timer(self.rtt.rto_ns, lambda: self._on_persist_fire(gen))

    def _on_persist_fire(self, gen: int) -> None:
        if gen != self._persist_gen or self.state == TcpState.CLOSED:
            return
        self._persist_armed = False
        if self.snd_wnd == 0 and self.stream_len > self.snd_nxt:
            self._probe_pending = True
            self.rtt.backoff()
            self._arm_persist()
            self.deps.notify()

    def _enter_time_wait(self) -> None:
        self.state = TcpState.TIME_WAIT
        self._disarm_rto()
        gen = self._rto_gen
        self.deps.set_timer(
            TIME_WAIT_NS,
            lambda: self._enter_closed(None) if gen == self._rto_gen else None,
        )

    def _enter_closed(self, error: Optional[int]) -> None:
        notify = self.state != TcpState.CLOSED
        self.state = TcpState.CLOSED
        if error is not None:
            self.error = error
        self._disarm_rto()
        self._persist_gen += 1
        if notify:
            self.deps.notify()
