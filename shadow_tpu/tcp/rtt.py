"""RFC 6298 round-trip-time estimation and retransmission timeout.

Parity: reference `tcp.c:1128-1170` (`_tcp_updateRTTEstimate`,
`_tcp_setRetransmitTimeout`) and `definitions.h:46-48`: millisecond
granularity integer arithmetic, SRTT/RTTVAR with alpha=1/8 beta=1/4,
initial RTO 1s, exponential backoff on expiry, and Karn's rule (no
estimate updates from echoes while backed off, `tcp.c:2315-2316`).

DELIBERATE deviation from the reference's RTO = SRTT + 4*RTTVAR: the
deviation term is floored at RTO_MIN/4 like Linux's tcp_rtt_estimator
(net/ipv4/tcp_input.c, mdev floor), so RTO >= SRTT + RTO_MIN. See
`_rto_from_estimate` for why the unfloored formula spuriously times out
on deterministic constant-RTT paths. The clamp to [200ms, 120s] is
unchanged.

Integer milliseconds — not ns — deliberately: the estimator divides, and
keeping the reference's ms units makes the arithmetic exact and cheap for
the eventual int32 TPU port.
"""

from __future__ import annotations

RTO_INIT_MS = 1000  # CONFIG_TCP_RTO_INIT (NET_TCP_HZ = 1000 ms)
RTO_MIN_MS = 200  # CONFIG_TCP_RTO_MIN
RTO_MAX_MS = 120_000  # CONFIG_TCP_RTO_MAX


def _rto_from_estimate(srtt_ms: int, rttvar_ms: int) -> int:
    """RTO from the current estimate, with Linux's deviation floor
    (tcp_input.c tcp_rtt_estimator: mdev_max >= tcp_rto_min/4) so
    RTO >= srtt + RTO_MIN. Pure RFC 6298 lets rttvar decay to 0 under
    perfectly regular samples while the integer srtt EWMA settles a
    couple ms BELOW the true RTT (floor division) — rto < RTT,
    guaranteeing periodic spurious timeouts on any constant-RTT path
    with RTT > RTO_MIN. A deterministic simulator produces exactly such
    paths (the device flow engine hit this at RTT 234 ms: srtt settled
    at 232, rttvar at 0). The device twin (`tpu/tcp.py:_rtt_update`)
    mirrors this formula; change BOTH or the bitwise-parity contract
    breaks."""
    return srtt_ms + 4 * max(rttvar_ms, RTO_MIN_MS // 4)


class RttEstimator:
    __slots__ = ("srtt_ms", "rttvar_ms", "rto_ms", "backoff_count")

    def __init__(self):
        self.srtt_ms = 0  # 0 = no measurement yet
        self.rttvar_ms = 0
        self.rto_ms = RTO_INIT_MS
        self.backoff_count = 0

    def update(self, rtt_ms: int) -> None:
        """Fold one RTT sample in; recompute the RTO. Callers must not feed
        samples taken from retransmitted segments (Karn's rule) — gate on
        `backoff_count == 0` like the reference does."""
        rtt_ms = max(1, rtt_ms)
        if self.srtt_ms == 0:
            self.srtt_ms = rtt_ms
            self.rttvar_ms = rtt_ms // 2
        else:
            self.rttvar_ms = (3 * self.rttvar_ms) // 4 + abs(self.srtt_ms - rtt_ms) // 4
            self.srtt_ms = (7 * self.srtt_ms) // 8 + rtt_ms // 8
        self._set_rto(_rto_from_estimate(self.srtt_ms, self.rttvar_ms))
        self.backoff_count = 0

    def backoff(self) -> None:
        """RTO expiry: double the timeout (`tcp.c:1499`)."""
        self.backoff_count += 1
        self._set_rto(self.rto_ms * 2)

    def reset_backoff(self) -> None:
        """Forward progress after a timeout: restore the RTO from the
        estimator instead of keeping the exponentially-inflated value
        (otherwise every later loss doubles from the inflated base and
        recovery degenerates into minutes-long stalls)."""
        if self.backoff_count == 0:
            return
        self.backoff_count = 0
        if self.srtt_ms:
            self._set_rto(_rto_from_estimate(self.srtt_ms, self.rttvar_ms))
        else:
            self._set_rto(RTO_INIT_MS)

    def _set_rto(self, ms: int) -> None:
        self.rto_ms = min(max(ms, RTO_MIN_MS), RTO_MAX_MS)

    @property
    def rto_ns(self) -> int:
        return self.rto_ms * 1_000_000
