"""32-bit wrapping sequence-number arithmetic.

Parity: reference `src/lib/tcp/src/seq.rs` (wrapping `Seq` type). All
comparisons are modular: `a` is "before" `b` when the wrapped distance from
`a` to `b` is less than half the space.
"""

MOD = 1 << 32
_HALF = 1 << 31


def add(a: int, n: int) -> int:
    return (a + n) % MOD


def sub(a: int, b: int) -> int:
    """Distance from b to a (a - b), wrapped to [0, 2^32)."""
    return (a - b) % MOD


def lt(a: int, b: int) -> bool:
    return a != b and sub(b, a) < _HALF


def le(a: int, b: int) -> bool:
    return a == b or lt(a, b)


def gt(a: int, b: int) -> bool:
    return lt(b, a)


def ge(a: int, b: int) -> bool:
    return le(b, a)


def clamp(x: int, lo: int, hi: int) -> int:
    """Clamp x into the wrapped interval [lo, hi]."""
    if lt(x, lo):
        return lo
    if gt(x, hi):
        return hi
    return x
