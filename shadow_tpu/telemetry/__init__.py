"""Device + CPU plane telemetry: on-device counters, virtual-time
heartbeats, and trace/metrics exporters.

- `metrics` — the `PlaneMetrics` SoA pytree accumulated with pure jnp
  adds inside the jitted device kernels (zero host syncs, bitwise
  invisible to simulation state).
- `harvest` — the `TelemetryHarvester`: asynchronous snapshots every N
  virtual-time windows, merged with the CPU `host/tracker.py` counters
  under one host-id namespace, emitted as deterministic JSONL.
- `export` — Perfetto/Chrome trace on the virtual-time axis and the
  `stats.shadow.json` bridge into `tools/plot_shadow.py`.
- `histo` — on-device log2-bucketed latency/queue-depth histograms
  (`PlaneHistograms`), the distribution half of the counters.
- `flightrec` — the sampled per-packet flight recorder: a seeded
  deterministic 1/K sampling mask, a device-side hop trace ring, and
  the asynchronous host drain (`FlightRecorder`).
- `tracer` — shadowscope: the per-chain-span run ledger (`RunTracer`,
  JSONL, emitted at the driver's existing chain-boundary host sync)
  and the two-clock wall/virtual Chrome-trace exporter.

Design rule (docs/observability.md): telemetry may never add a device
sync to the per-window hot path — harvest happens OUTSIDE jitted code,
enforced statically by shadowlint SL301 (and SL405 for the float()/
.item() read side).
"""

from .flightrec import FlightRecArrays, FlightRecorder, make_flightrec
from .harvest import TelemetryHarvester, unwrap_u32
from .histo import HIST_BUCKETS, PlaneHistograms, make_histograms
from .metrics import PlaneMetrics, add_retransmits, make_metrics
from .tracer import RUNLEDGER_SCHEMA, RunTracer

__all__ = [
    "FlightRecArrays",
    "FlightRecorder",
    "HIST_BUCKETS",
    "PlaneHistograms",
    "PlaneMetrics",
    "RUNLEDGER_SCHEMA",
    "RunTracer",
    "TelemetryHarvester",
    "add_retransmits",
    "make_flightrec",
    "make_histograms",
    "make_metrics",
    "unwrap_u32",
]
