"""Device + CPU plane telemetry: on-device counters, virtual-time
heartbeats, and trace/metrics exporters.

- `metrics` — the `PlaneMetrics` SoA pytree accumulated with pure jnp
  adds inside the jitted device kernels (zero host syncs, bitwise
  invisible to simulation state).
- `harvest` — the `TelemetryHarvester`: asynchronous snapshots every N
  virtual-time windows, merged with the CPU `host/tracker.py` counters
  under one host-id namespace, emitted as deterministic JSONL.
- `export` — Perfetto/Chrome trace on the virtual-time axis and the
  `stats.shadow.json` bridge into `tools/plot_shadow.py`.

Design rule (docs/observability.md): telemetry may never add a device
sync to the per-window hot path — harvest happens OUTSIDE jitted code,
enforced statically by shadowlint SL301.
"""

from .harvest import TelemetryHarvester, unwrap_u32
from .metrics import PlaneMetrics, add_retransmits, make_metrics

__all__ = [
    "PlaneMetrics",
    "TelemetryHarvester",
    "add_retransmits",
    "make_metrics",
    "unwrap_u32",
]
