"""Telemetry exporters: Perfetto/Chrome trace + plot-pipeline stats.

`write_perfetto_trace` lays a run's heartbeat stream out on the
VIRTUAL-time axis in the Chrome trace-event JSON format (loadable in
Perfetto / chrome://tracing): one process row per host carrying
counter tracks (traffic rates and drop totals, computed as per-interval
deltas of the cumulative heartbeat counters) plus a simulation row
whose slices mark the harvest intervals and the windows/events each one
covered. `ts` is virtual nanoseconds divided by 1000 — a trace "µs" IS
a simulated µs, so two seeds' traces align perfectly for diffing.

`to_plot_stats` converts the same heartbeats into the
`stats.shadow.json` shape `tools/parse_shadow.py` produces, so
`tools/plot_shadow.py` plots telemetry runs unchanged.
"""

from __future__ import annotations

import json
from typing import Iterable

from .harvest import MAX_FIELDS

#: keys plotted as per-host counter tracks (cumulative in heartbeats;
#: traffic is emitted as per-interval rates, drops as running totals)
_RATE_KEYS = ("bytes_out", "bytes_in", "pkts_out", "pkts_in")
_TOTAL_KEYS = ("drop_ring_full", "drop_qdisc", "drop_loss",
               "retransmits", "packets_dropped", "retransmitted")


def read_heartbeats(lines: Iterable[str]) -> list[dict]:
    """Parse heartbeat JSONL. Lines may carry a log prefix (the
    shadowlog-formatted `telemetry time_ns=...` form): everything
    before the first '{' is ignored; non-JSON lines are skipped."""
    out = []
    for line in lines:
        brace = line.find("{")
        if brace < 0:
            continue
        try:
            rec = json.loads(line[brace:])
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("type") in ("sim", "host"):
            out.append(rec)
    return out


def _host_series(heartbeats: list[dict]) -> dict[str, list[dict]]:
    """Per-host heartbeat lines, keyed by host name, in time order."""
    series: dict[str, list[dict]] = {}
    for rec in heartbeats:
        if rec.get("type") == "host":
            series.setdefault(rec["host"], []).append(rec)
    for recs in series.values():
        recs.sort(key=lambda r: r["time_ns"])
    return series


def _merged_counters(rec: dict) -> dict[str, int]:
    """One flat counter dict per host line: device counters first, CPU
    tracker counters layered on top (distinct names, so no clobbering
    beyond the intentional shared namespace)."""
    out: dict[str, int] = {}
    out.update(rec.get("device") or {})
    for k, v in (rec.get("cpu") or {}).items():
        if isinstance(v, (int, float)):
            out[k] = v
    return out


def write_perfetto_trace(heartbeats: list[dict], path: str, *,
                         max_hosts: int = 256) -> dict:
    """Write a Chrome trace-event JSON file; returns a small summary
    dict (events written, hosts plotted/dropped). Hosts are capped at
    `max_hosts` counter rows (top talkers by total bytes) so a 4096-host
    run stays loadable; the cap is recorded in the trace's otherData —
    never silent."""
    events: list[dict] = [
        {"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
         "args": {"name": "simulation (virtual time)"}},
    ]
    # simulation row: one slice per harvest interval
    sims = sorted((r for r in heartbeats if r.get("type") == "sim"),
                  key=lambda r: r["time_ns"])
    prev_t = 0
    for rec in sims:
        t = rec["time_ns"]
        args = {k: rec[k] for k in ("windows", "events", "sort_occupancy")
                if k in rec}
        events.append({
            "ph": "X", "pid": 0, "tid": 0,
            "name": "harvest", "ts": prev_t / 1e3,
            "dur": max(t - prev_t, 1) / 1e3, "args": args,
        })
        for totals_key in ("device_totals", "cpu_totals"):
            if totals_key in rec:
                events.append({
                    "ph": "C", "pid": 0, "name": totals_key,
                    "ts": t / 1e3,
                    "args": {k: v for k, v in rec[totals_key].items()},
                })
        for ev in rec.get("annotations", ()):
            # run-lifecycle annotations (capacity-ring growth, ...) as
            # global trace instants at their own virtual instant
            events.append({
                "ph": "i", "pid": 0, "tid": 0, "s": "g",
                "name": ev.get("kind", "event"),
                "ts": ev.get("time_ns", t) / 1e3,
                "args": dict(ev),
            })
        prev_t = t

    series = _host_series(heartbeats)
    by_bytes = sorted(
        series.items(),
        key=lambda kv: (-sum(_merged_counters(r).get("bytes_out", 0)
                             + _merged_counters(r).get("bytes_in", 0)
                             for r in kv[1][-1:]), kv[0]),
    )
    plotted, dropped = by_bytes[:max_hosts], by_bytes[max_hosts:]
    for name, recs in sorted(plotted):
        pid = recs[0]["host_id"]
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": name}})
        prev: dict[str, int] = {}
        prev_t = 0
        for rec in recs:
            t = rec["time_ns"]
            c = _merged_counters(rec)
            dt_s = max(t - prev_t, 1) / 1e9
            rates = {k: round((c[k] - prev.get(k, 0)) / dt_s, 3)
                     for k in _RATE_KEYS if k in c}
            if rates:
                events.append({"ph": "C", "pid": pid, "name": "traffic/s",
                               "ts": t / 1e3, "args": rates})
            totals = {k: c[k] for k in _TOTAL_KEYS if k in c}
            if totals:
                events.append({"ph": "C", "pid": pid, "name": "drops",
                               "ts": t / 1e3, "args": totals})
            prev, prev_t = c, t

    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "virtual simulated time (1 trace us = 1 sim us)",
            "hosts_plotted": len(plotted),
            "hosts_dropped_by_cap": len(dropped),
        },
    }
    with open(path, "w") as fh:
        json.dump(trace, fh, sort_keys=True)
    return {"events": len(events), "hosts_plotted": len(plotted),
            "hosts_dropped_by_cap": len(dropped), "path": path}


def to_plot_stats(heartbeats: list[dict]) -> dict:
    """The `stats.shadow.json` shape `tools/plot_shadow.py` consumes:
    cumulative per-host counters sampled at heartbeat times. Drop
    reasons fold into the `packets_dropped` total when the CPU tracker
    didn't already provide one."""
    nodes: dict[str, dict] = {}
    for name, recs in sorted(_host_series(heartbeats).items()):
        entry = nodes.setdefault(name, {"time_ns": [], "counters": []})
        for rec in recs:
            c = _merged_counters(rec)
            if "packets_dropped" not in c:
                c["packets_dropped"] = (
                    c.get("drop_ring_full", 0) + c.get("drop_qdisc", 0)
                    + c.get("drop_loss", 0))
            entry["time_ns"].append(rec["time_ns"])
            entry["counters"].append(c)
    return {"nodes": nodes, "rusage": [], "meminfo": []}


def summarize(heartbeats: list[dict], *, top: int = 10) -> dict:
    """Run-level summary for the report CLI: final totals, drop
    breakdown, window stats, top talkers."""
    sims = sorted((r for r in heartbeats if r.get("type") == "sim"),
                  key=lambda r: r["time_ns"])
    series = _host_series(heartbeats)
    finals = {name: _merged_counters(recs[-1])
              for name, recs in series.items()}
    total = {}
    for c in finals.values():
        for k, v in c.items():
            if not isinstance(v, (int, float)):
                continue
            if k in MAX_FIELDS:  # high-water marks: fleet max, not sum
                total[k] = max(total.get(k, 0), v)
            else:
                total[k] = total.get(k, 0) + v
    talkers = sorted(
        finals.items(),
        key=lambda kv: (-(kv[1].get("bytes_out", 0)
                          + kv[1].get("bytes_in", 0)), kv[0]))[:top]
    out = {
        "heartbeats": len(heartbeats),
        "harvests": len(sims),
        "hosts": len(series),
        "last_time_ns": sims[-1]["time_ns"] if sims else 0,
        "totals": total,
        "top_talkers": [
            {"host": name,
             "bytes_out": c.get("bytes_out", 0),
             "bytes_in": c.get("bytes_in", 0)}
            for name, c in talkers],
    }
    if sims:
        last = sims[-1]
        for k in ("windows", "events", "sort_occupancy"):
            if k in last:
                out[k] = last[k]
    return out
