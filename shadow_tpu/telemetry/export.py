"""Telemetry exporters: Perfetto/Chrome trace + plot-pipeline stats.

`write_perfetto_trace` lays a run's heartbeat stream out on the
VIRTUAL-time axis in the Chrome trace-event JSON format (loadable in
Perfetto / chrome://tracing): one process row per host carrying
counter tracks (traffic rates and drop totals, computed as per-interval
deltas of the cumulative heartbeat counters) plus a simulation row
whose slices mark the harvest intervals and the windows/events each one
covered. `ts` is virtual nanoseconds divided by 1000 — a trace "µs" IS
a simulated µs, so two seeds' traces align perfectly for diffing.

`to_plot_stats` converts the same heartbeats into the
`stats.shadow.json` shape `tools/parse_shadow.py` produces, so
`tools/plot_shadow.py` plots telemetry runs unchanged.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

from . import histo
from .flightrec import hop_flows
from .harvest import MAX_FIELDS

#: keys plotted as per-host counter tracks (cumulative in heartbeats;
#: traffic is emitted as per-interval rates, drops as running totals)
_RATE_KEYS = ("bytes_out", "bytes_in", "pkts_out", "pkts_in")
_TOTAL_KEYS = ("drop_ring_full", "drop_qdisc", "drop_loss",
               "retransmits", "packets_dropped", "retransmitted")


def read_heartbeats(lines: Iterable[str]) -> list[dict]:
    """Parse heartbeat JSONL. Lines may carry a log prefix (the
    shadowlog-formatted `telemetry time_ns=...` form): everything
    before the first '{' is ignored; non-JSON lines are skipped."""
    out = []
    for line in lines:
        brace = line.find("{")
        if brace < 0:
            continue
        try:
            rec = json.loads(line[brace:])
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("type") in ("sim", "host"):
            out.append(rec)
    return out


def _host_series(heartbeats: list[dict]) -> dict[str, list[dict]]:
    """Per-host heartbeat lines, keyed by host name, in time order."""
    series: dict[str, list[dict]] = {}
    for rec in heartbeats:
        if rec.get("type") == "host":
            series.setdefault(rec["host"], []).append(rec)
    for recs in series.values():
        recs.sort(key=lambda r: r["time_ns"])
    return series


def _merged_counters(rec: dict) -> dict[str, int]:
    """One flat counter dict per host line: device counters first, CPU
    tracker counters layered on top (distinct names, so no clobbering
    beyond the intentional shared namespace)."""
    out: dict[str, int] = {}
    out.update(rec.get("device") or {})
    for k, v in (rec.get("cpu") or {}).items():
        if isinstance(v, (int, float)):
            out[k] = v
    return out


def build_sim_events(heartbeats: list[dict], *, max_hosts: int = 256,
                     hops: Optional[list[dict]] = None,
                     max_flows: int = 512) -> tuple[list[dict], dict]:
    """The virtual-time trace-event rows of `write_perfetto_trace`,
    as (events, caps-summary) — shared with the two-clock merged
    exporter (telemetry/tracer.py `write_chrome_trace`), which lays
    these beside the wall-time driver row."""
    events: list[dict] = [
        {"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
         "args": {"name": "simulation (virtual time)"}},
    ]
    # simulation row: one slice per harvest interval
    sims = sorted((r for r in heartbeats if r.get("type") == "sim"),
                  key=lambda r: r["time_ns"])
    prev_t = 0
    prev_hist: dict[str, list] = {}
    for rec in sims:
        t = rec["time_ns"]
        args = {k: rec[k] for k in ("windows", "events", "sort_occupancy")
                if k in rec}
        events.append({
            "ph": "X", "pid": 0, "tid": 0,
            "name": "harvest", "ts": prev_t / 1e3,
            "dur": max(t - prev_t, 1) / 1e3, "args": args,
        })
        for hname, counts in sorted((rec.get("hist") or {}).items()):
            # interval percentiles from the cumulative bucket deltas:
            # counter tracks on the VIRTUAL-time axis, so an incast's
            # p99 blowup lands at its simulated instant
            prev = prev_hist.get(hname, [0] * len(counts))
            delta = [c - p for c, p in zip(counts, prev)]
            prev_hist[hname] = counts
            if sum(delta) <= 0:
                continue
            events.append({
                "ph": "C", "pid": 0,
                "name": hname.removeprefix(histo.HIST_PREFIX),
                "ts": t / 1e3, "args": histo.percentiles(delta),
            })
        for totals_key in ("device_totals", "cpu_totals"):
            if totals_key in rec:
                events.append({
                    "ph": "C", "pid": 0, "name": totals_key,
                    "ts": t / 1e3,
                    "args": {k: v for k, v in rec[totals_key].items()},
                })
        for ev in rec.get("annotations", ()):
            # run-lifecycle annotations (capacity-ring growth, ...) as
            # global trace instants at their own virtual instant
            events.append({
                "ph": "i", "pid": 0, "tid": 0, "s": "g",
                "name": ev.get("kind", "event"),
                "ts": ev.get("time_ns", t) / 1e3,
                "args": dict(ev),
            })
        prev_t = t

    series = _host_series(heartbeats)
    by_bytes = sorted(
        series.items(),
        key=lambda kv: (-sum(_merged_counters(r).get("bytes_out", 0)
                             + _merged_counters(r).get("bytes_in", 0)
                             for r in kv[1][-1:]), kv[0]),
    )
    plotted, dropped = by_bytes[:max_hosts], by_bytes[max_hosts:]
    for name, recs in sorted(plotted):
        pid = recs[0]["host_id"]
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": name}})
        prev: dict[str, int] = {}
        prev_t = 0
        for rec in recs:
            t = rec["time_ns"]
            c = _merged_counters(rec)
            dt_s = max(t - prev_t, 1) / 1e9
            rates = {k: round((c[k] - prev.get(k, 0)) / dt_s, 3)
                     for k in _RATE_KEYS if k in c}
            if rates:
                events.append({"ph": "C", "pid": pid, "name": "traffic/s",
                               "ts": t / 1e3, "args": rates})
            totals = {k: c[k] for k in _TOTAL_KEYS if k in c}
            if totals:
                events.append({"ph": "C", "pid": pid, "name": "drops",
                               "ts": t / 1e3, "args": totals})
            prev, prev_t = c, t

    flows_written = flows_dropped = 0
    if hops:
        flows_written, flows_dropped = _flow_events(
            events, hops, max_flows)

    return events, {"hosts_plotted": len(plotted),
                    "hosts_dropped_by_cap": len(dropped),
                    "flows_plotted": flows_written,
                    "flows_dropped_by_cap": flows_dropped}


def write_perfetto_trace(heartbeats: list[dict], path: str, *,
                         max_hosts: int = 256,
                         hops: Optional[list[dict]] = None,
                         max_flows: int = 512) -> dict:
    """Write a Chrome trace-event JSON file; returns a small summary
    dict (events written, hosts plotted/dropped). Hosts are capped at
    `max_hosts` counter rows (top talkers by total bytes) so a 4096-host
    run stays loadable; the cap is recorded in the trace's otherData —
    never silent.

    When the sim heartbeats carry `hist` bucket vectors
    (telemetry/histo.py), the simulation row gains per-interval
    percentile COUNTER tracks on the virtual-time axis (p50/p90/p99/
    p999 of each histogram's interval delta). When `hops` (flight-
    recorder hop records, telemetry/flightrec.py) are given, sampled
    packets become FLOW events: a send slice on the source host row
    bound by an `s` arrow to a deliver slice on the destination row —
    one packet's life, linked across hosts. Flows are capped at
    `max_flows` (recorded in otherData, never silent)."""
    events, caps = build_sim_events(heartbeats, max_hosts=max_hosts,
                                    hops=hops, max_flows=max_flows)
    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "virtual simulated time (1 trace us = 1 sim us)",
            **caps,
        },
    }
    with open(path, "w") as fh:
        json.dump(trace, fh, sort_keys=True)
    return {"events": len(events), "path": path, **caps}


def _flow_events(events: list[dict], hops: list[dict],
                 max_flows: int) -> tuple[int, int]:
    """Append flight-recorder packet flows to a trace-event list: for
    each sampled packet with a `routed` hop, a send slice on the
    source host's row, an `s` flow arrow, and (when the packet's
    terminal hop was recorded) a terminal slice on the destination row
    closing the arrow (`f`, bp="e"). An AQM drop is a terminal hop
    too, named `drop_aqm` — the trace says where and why the packet
    died. Loss/fault-dropped packets never entered the wire, so they
    have no flow; their hops still appear in the hops JSONL. Host rows
    use pid = host index + 1 (the heartbeat host_id), matching the
    counter-track rows. Returns (flows written, flows dropped by the
    cap)."""
    # only flows with a `routed` hop are plottable (e.g. an ingest-only
    # group has no wire span); the cap counts PLOTTABLE flows cut, so
    # flows_dropped_by_cap is the same number regardless of where the
    # unplottable groups fall in iteration order
    plottable = []
    for (src, seq), group in sorted(hop_flows(hops).items()):
        routed = next((h for h in group if h["kind"] == "routed"), None)
        if routed is not None:
            plottable.append(((src, seq), group, routed))
    written = 0
    for (src, seq), group, routed in plottable[:max_flows]:
        fid = f"pkt-{src}-{seq}"
        terminal = next(
            (h for h in group
             if h["kind"] in ("delivered", "drop_aqm")), None)
        end_t = terminal["t_ns"] if terminal else routed["t_ns"]
        events.append({
            "ph": "X", "pid": src + 1, "tid": 1,
            "name": f"send #{seq} -> host{routed['dst'] + 1}",
            "ts": routed["t_ns"] / 1e3,
            "dur": max(end_t - routed["t_ns"], 1) / 1e3,
            "args": dict(routed),
        })
        events.append({"ph": "s", "pid": src + 1, "tid": 1,
                       "id": fid, "name": "packet",
                       "ts": routed["t_ns"] / 1e3})
        if terminal is not None:
            events.append({
                "ph": "X", "pid": terminal["dst"] + 1, "tid": 1,
                "name": f"{terminal['kind']} #{seq} "
                        f"from host{src + 1}",
                "ts": terminal["t_ns"] / 1e3, "dur": 1.0,
                "args": dict(terminal),
            })
            events.append({"ph": "f", "bp": "e",
                           "pid": terminal["dst"] + 1, "tid": 1,
                           "id": fid, "name": "packet",
                           "ts": terminal["t_ns"] / 1e3})
        written += 1
    return written, len(plottable) - written


def to_plot_stats(heartbeats: list[dict]) -> dict:
    """The `stats.shadow.json` shape `tools/plot_shadow.py` consumes:
    cumulative per-host counters sampled at heartbeat times. Drop
    reasons fold into the `packets_dropped` total when the CPU tracker
    didn't already provide one."""
    nodes: dict[str, dict] = {}
    for name, recs in sorted(_host_series(heartbeats).items()):
        entry = nodes.setdefault(name, {"time_ns": [], "counters": []})
        for rec in recs:
            c = _merged_counters(rec)
            if "packets_dropped" not in c:
                c["packets_dropped"] = (
                    c.get("drop_ring_full", 0) + c.get("drop_qdisc", 0)
                    + c.get("drop_loss", 0))
            entry["time_ns"].append(rec["time_ns"])
            entry["counters"].append(c)
    return {"nodes": nodes, "rusage": [], "meminfo": []}


def summarize(heartbeats: list[dict], *, top: int = 10) -> dict:
    """Run-level summary for the report CLI: final totals, drop
    breakdown, window stats, top talkers."""
    sims = sorted((r for r in heartbeats if r.get("type") == "sim"),
                  key=lambda r: r["time_ns"])
    series = _host_series(heartbeats)
    finals = {name: _merged_counters(recs[-1])
              for name, recs in series.items()}
    total = {}
    for c in finals.values():
        for k, v in c.items():
            if not isinstance(v, (int, float)):
                continue
            if k in MAX_FIELDS:  # high-water marks: fleet max, not sum
                total[k] = max(total.get(k, 0), v)
            else:
                total[k] = total.get(k, 0) + v
    talkers = sorted(
        finals.items(),
        key=lambda kv: (-(kv[1].get("bytes_out", 0)
                          + kv[1].get("bytes_in", 0)), kv[0]))[:top]
    out = {
        "heartbeats": len(heartbeats),
        "harvests": len(sims),
        "hosts": len(series),
        "last_time_ns": sims[-1]["time_ns"] if sims else 0,
        "totals": total,
        "top_talkers": [
            {"host": name,
             "bytes_out": c.get("bytes_out", 0),
             "bytes_in": c.get("bytes_in", 0)}
            for name, c in talkers],
    }
    if sims:
        last = sims[-1]
        for k in ("windows", "events", "sort_occupancy"):
            if k in last:
                out[k] = last[k]
        if last.get("hist"):
            # run-level SLO percentiles from the final cumulative
            # fleet histograms (telemetry/histo.py bucket scheme)
            out["percentiles"] = {
                name.removeprefix(histo.HIST_PREFIX):
                    histo.percentiles(counts)
                for name, counts in sorted(last["hist"].items())}
    return out


def host_percentiles(heartbeats: list[dict]) -> dict[str, dict]:
    """Per-host percentile tables from each host's FINAL cumulative
    histogram line: {host_name: {hist_name: {p50: ..., ...}}} — the
    report CLI's per-host latency table."""
    out: dict[str, dict] = {}
    for name, recs in sorted(_host_series(heartbeats).items()):
        hist = next((r["hist"] for r in reversed(recs)
                     if r.get("hist")), None)
        if not hist:
            continue
        out[name] = {
            hname.removeprefix(histo.HIST_PREFIX):
                histo.percentiles(counts)
            for hname, counts in sorted(hist.items())}
    return out
