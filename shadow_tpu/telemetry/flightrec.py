"""Sampled per-packet hop tracing: the device-plane flight recorder.

Histograms (`telemetry/histo.py`) answer "how bad"; the flight recorder
answers "WHERE did this packet spend its time": a seeded deterministic
sampling mask tags ~1/K packets, and every tagged packet's hops —
ingested into an egress ring, routed onto the wire, AQM-judged,
delivered or dropped (with the reason) — land as fixed-shape SoA events
in a device-side trace ring, drained at harvest boundaries with zero
added syncs and exported as Perfetto flow events linking a packet's
life across hosts (docs/observability.md "Distributions and the flight
recorder").

Design rules, same as every observability plane:

1. **Static presence switch.** `window_step(..., flightrec=None)`
   compiles the recorder out; threading a `FlightRecArrays` pytree is
   bitwise-invisible to simulation state, metrics, AND guards
   (tests/test_flightrec.py parity matrix).
2. **Deterministic sampling.** The mask is a pure function of
   (seed, src, seq) — an independent counter-based threefry stream,
   exactly like the fault plane's corruption draws: it never touches
   the simulation RNG, and whether a packet is sampled does not depend
   on batch shape, queue occupancy, sharding, or ring capacity. Two
   identical runs record byte-identical hop streams.
3. **No silent truncation.** The ring keeps the LAST R events under a
   monotone (modular) write cursor; when more events land between two
   drains than the ring holds, the overwritten count is computed from
   the cursor delta and reported LOUDLY (log + summary + heartbeat
   annotation), never dropped silently. Under the elastic capacity
   policy the ring participates in growth: `grow_ring` repacks the
   ring into a larger power-of-two, entry-preserving and
   cursor-consistent, so a driver can double it instead of losing
   events (docs/robustness.md "Elastic capacity").

The host half (`FlightRecorder`) mirrors the `TelemetryHarvester`
double-buffer: `tick()` starts an asynchronous D2H copy of the ring
columns and materializes the PREVIOUS tick's copy — no
`block_until_ready`, no blocking pull on the driver loop.
"""

from __future__ import annotations

import json
import logging
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .harvest import unwrap_u32

log = logging.getLogger("shadow_tpu.telemetry")

# hop kinds (ev_kind values). DROP reasons are distinct kinds so the
# drop taxonomy (docs/robustness.md) survives into the hop stream: an
# injected fault never reads as wire loss, per-packet included. The
# flow plane's recovery kinds share the packet's identity (src, flow
# seq), so a sampled lost packet's trail reads
# drop_loss -> rto_fired -> retransmit -> delivered — it never
# silently vanishes (docs/observability.md attribution table).
HOP_INGEST = 0  # appended to its source's egress ring
HOP_ROUTED = 1  # cleared the egress gate and entered the wire
HOP_DELIVERED = 2  # released to the destination host
HOP_DROP_LOSS = 3  # Bernoulli path-loss sample
HOP_DROP_FAULT = 4  # injected fault (crash purge / corruption burst)
HOP_DROP_AQM = 5  # router CoDel verdict at the destination
HOP_RTO_FIRED = 6  # flow-plane RTO expiry: go-back-N rewind (seq =
# the snd_una segment the timer was guarding)
HOP_RETRANSMIT = 7  # flow-plane re-emission of an already-sent seq

HOP_NAMES = {
    HOP_INGEST: "ingest",
    HOP_ROUTED: "routed",
    HOP_DELIVERED: "delivered",
    HOP_DROP_LOSS: "drop_loss",
    HOP_DROP_FAULT: "drop_fault",
    HOP_DROP_AQM: "drop_aqm",
    HOP_RTO_FIRED: "rto_fired",
    HOP_RETRANSMIT: "retransmit",
}

I32_MAX = np.int32(2**31 - 1)


class FlightRecArrays(NamedTuple):
    """The device-side trace ring. Plain kernel arguments (never
    static), so advancing between windows never recompiles; the ring
    length R is the only static dimension."""

    key: jax.Array  # [2] uint32 — threefry key of the sampling stream
    sample_every: jax.Array  # scalar uint32 — tag ~1/K packets
    ev_kind: jax.Array  # [R] int32 HOP_* code
    ev_src: jax.Array  # [R] int32 source host index
    ev_seq: jax.Array  # [R] int32 per-source packet id
    ev_dst: jax.Array  # [R] int32 destination host index
    ev_t: jax.Array  # [R] int32 ns relative to the event's window start
    ev_win: jax.Array  # [R] int32 window counter at the event
    cursor: jax.Array  # scalar int32 — monotone (modular) write cursor
    win: jax.Array  # scalar int32 — windows recorded so far


def make_flightrec(seed: int, *, sample_every: int = 64,
                   ring: int = 4096) -> FlightRecArrays:
    """A fresh recorder. `seed` keys the sampling stream (a pure
    function of (seed, src, seq) — docs/determinism.md); `sample_every`
    = K tags ~1/K packets (1 = every packet); `ring` is the trace-ring
    capacity (static: changing it retraces the step)."""
    if sample_every < 1:
        raise ValueError("flight_recorder.sample_every must be >= 1")
    if ring < 1:
        raise ValueError("flight_recorder.ring must be >= 1")
    kd = jax.random.key_data(jax.random.key(seed)).astype(jnp.uint32)
    z = lambda: jnp.zeros((ring,), jnp.int32)
    return FlightRecArrays(
        key=kd.reshape(-1)[:2],
        sample_every=jnp.uint32(sample_every),
        ev_kind=z(), ev_src=z(), ev_seq=z(), ev_dst=z(),
        ev_t=z(), ev_win=z(),
        cursor=jnp.zeros((), jnp.int32),
        win=jnp.zeros((), jnp.int32),
    )


def ring_capacity(fr: FlightRecArrays) -> int:
    return int(fr.ev_kind.shape[0])


# -- device half (pure jnp; safe inside jit) ------------------------------


def sample_mask(fr: FlightRecArrays, src: jax.Array,
                seq: jax.Array) -> jax.Array:
    """The deterministic sampling mask: True for packets whose
    (src, seq) hashes to 0 mod K under the recorder's threefry key.
    One batched 2x32 block over all slots — the (src, seq) pair IS the
    cipher's counter block, so the mask depends only on
    (seed, src, seq): identical under any vectorization, sharding,
    batch shape, or ring capacity (the determinism contract), and
    INDEPENDENT of the simulation RNG streams (separate key)."""
    from jax.extend import random as jex_random

    shape = src.shape
    count = jnp.concatenate([
        src.reshape(-1).astype(jnp.uint32),
        seq.reshape(-1).astype(jnp.uint32),
    ])
    bits = jex_random.threefry_2x32(fr.key, count)[: src.size]
    return (bits % fr.sample_every == 0).reshape(shape)


def record_events(fr: FlightRecArrays, kind, src, seq, dst, t,
                  mask) -> FlightRecArrays:
    """Append this window's masked candidate events ([B] flat int32
    columns, bool mask) to the trace ring, in layout order (the
    deterministic candidate order window_step concatenates them in).

    The append is sort-free AND scatter-free (the same diet the
    routing stage is on, docs/performance.md): a masked event's ring
    position is (cursor + rank) % R with rank its layout-order
    counting rank (an inclusive cumsum), and because those positions
    are CONSECUTIVE modulo R, the update inverts into a per-ring-slot
    GATHER — each slot computes which rank (if any) lands on it this
    window and binary-searches the cumsum for that event's index. One
    cumsum over the candidates + O(R log B) searchsorted + 6 R-sized
    gathers, vs the 6 B-sized scatters (or worse, a B-sized sort)
    the naive formulations pay.

    When the window produces more events than the ring holds, only
    the LAST R survive (ring-overwrite semantics, uniform within a
    window and across windows) — the loss is visible in the cursor
    delta and reported loudly by the host drain, never silent."""
    R = fr.ev_kind.shape[0]
    B = mask.shape[0]
    csum = jnp.cumsum(mask.astype(jnp.int32))  # inclusive rank + 1
    count = csum[-1]
    # ring slot j receives the event whose rank r satisfies
    # (cursor + r) % R == j, taking the LARGEST such r < count (newest
    # wins); r < count - R means the slot keeps its previous entry
    r0 = (jnp.arange(R, dtype=jnp.int32) - fr.cursor) % R
    r = count - 1 - (count - 1 - r0) % R
    written = (r >= 0) & (r >= count - R)
    # first candidate index with inclusive cumsum == r + 1 IS the
    # masked event of rank r (the cumsum jumps exactly there)
    src_idx = jnp.clip(
        jnp.searchsorted(csum, r + 1).astype(jnp.int32), 0, B - 1)
    take = lambda col, old: jnp.where(
        written, col.reshape(-1)[src_idx], old)
    return fr._replace(
        ev_kind=take(kind, fr.ev_kind),
        ev_src=take(src, fr.ev_src),
        ev_seq=take(seq, fr.ev_seq),
        ev_dst=take(dst, fr.ev_dst),
        ev_t=take(t, fr.ev_t),
        ev_win=jnp.where(written, fr.win, fr.ev_win),
        cursor=fr.cursor + count,
    )


def advance_window(fr: FlightRecArrays) -> FlightRecArrays:
    """Bump the window counter (window_step calls this once, AFTER
    recording the window's events — events stamp the window they
    happened in)."""
    return fr._replace(win=fr.win + 1)


def grow_ring(fr: FlightRecArrays, new_ring: int) -> FlightRecArrays:
    """Repack the trace ring into `new_ring` slots (> R), preserving
    every live entry at its cursor-consistent position — index j of
    the new ring holds the event whose absolute cursor position p
    satisfies p % new_ring == j, exactly as if the run had started at
    the larger capacity with the same event stream. Pure device op
    (one stacked scatter); drivers call it between windows when a
    drain reports overwritten events under the elastic capacity
    policy (docs/robustness.md 'Elastic capacity'). Recompiles the
    step per ring shape — bounded at log2 by power-of-two growth."""
    R = fr.ev_kind.shape[0]
    if new_ring <= R:
        raise ValueError(
            f"flight-recorder ring can only grow ({R} -> {new_ring})")
    idx = jnp.arange(R, dtype=jnp.int32)
    # old slot j holds the latest absolute position p < cursor with
    # p % R == j (only the last min(cursor, R) slots are live)
    abs_pos = fr.cursor - 1 - (fr.cursor - 1 - idx) % R
    live = (abs_pos >= 0) & (abs_pos >= fr.cursor - R)
    pos = jnp.where(live, abs_pos % new_ring, new_ring)
    old = jnp.stack([fr.ev_kind, fr.ev_src, fr.ev_seq, fr.ev_dst,
                     fr.ev_t, fr.ev_win])
    ring = jnp.zeros((6, new_ring), jnp.int32).at[:, pos].set(
        old, mode="drop")
    return fr._replace(
        ev_kind=ring[0], ev_src=ring[1], ev_seq=ring[2],
        ev_dst=ring[3], ev_t=ring[4], ev_win=ring[5])


# -- host half: the asynchronous drain ------------------------------------

#: ring columns the drain copies (cursor rides along)
_COLS = ("ev_kind", "ev_src", "ev_seq", "ev_dst", "ev_t", "ev_win")


class FlightRecorder:
    """Host-side drain for the device trace ring, double-buffered like
    the `TelemetryHarvester`: `tick(fr)` drains the previous snapshot
    (whose asynchronous D2H copy has had a whole interval to land) and
    starts copying the current ring. Decoded hops accumulate in
    `self.hops` (and stream to `sink` as deterministic JSONL — sorted
    keys, virtual-time stamps, byte-stable across identical runs).

    `window_ns` maps the device (win, t_rel) stamp to absolute virtual
    ns for fixed-cadence window drivers (bench/chaos/scenario loops —
    the only drivers that thread the recorder). `overwritten` counts
    ring-overflow losses, computed from the cursor delta at every
    drain and reported loudly — no silent truncation."""

    def __init__(self, *, window_ns: int, sink=None,
                 retain: bool = True):
        self.window_ns = int(window_ns)
        self.hops: list[dict] = []
        self.recorded = 0  # hops decoded across all drains
        self.overwritten = 0  # events lost to ring overwrite
        self._retain = retain
        self._pending = None  # {col: array-ref} + cursor ref
        self._prev_cursor_raw = 0
        self._cursor_total = 0
        self._grown_at = 0  # overwritten count at the last grow_ring
        self._own_sink = isinstance(sink, str)
        self.sink_path = sink if self._own_sink else None
        self._sink = open(sink, "w") if self._own_sink else sink

    # -- the drain cycle -------------------------------------------------

    def tick(self, fr: FlightRecArrays) -> None:
        """Drain the previous snapshot, then start an asynchronous copy
        of the current ring columns + cursor. Nothing blocks."""
        self.drain()
        snap = {c: getattr(fr, c) for c in _COLS}
        snap["cursor"] = fr.cursor
        for arr in snap.values():
            copy = getattr(arr, "copy_to_host_async", None)
            if copy is not None:
                copy()
        self._pending = snap

    def seed_cursor(self, cursor_raw: int) -> None:
        """Start the drain window at an existing ring cursor (a
        checkpoint resume): hops before it were drained — and
        reported — by the run that wrote the checkpoint."""
        self._prev_cursor_raw = int(cursor_raw) & 0xFFFFFFFF
        self._cursor_total = int(cursor_raw)

    def drain(self) -> None:
        """Materialize and decode the pending snapshot, if any."""
        if self._pending is None:
            return
        snap, self._pending = self._pending, None
        cols = {c: np.asarray(snap[c]) for c in _COLS}
        cur_raw = int(np.asarray(snap["cursor"]))
        delta = int(unwrap_u32(self._prev_cursor_raw, cur_raw))
        self._prev_cursor_raw = cur_raw
        if delta == 0:
            return
        R = cols["ev_kind"].shape[0]
        lost = max(0, delta - R)
        if lost:
            # no silent truncation: the overwritten count is first-class
            self.overwritten += lost
            log.error(
                "flight-recorder trace ring overflowed: %d hop event(s) "
                "overwritten before the drain (ring=%d); shorten the "
                "harvest interval, raise flight_recorder.ring, or run "
                "capacity.mode=elastic to grow it", lost, R)
        start = self._cursor_total + lost
        end = self._cursor_total + delta
        self._cursor_total = end
        for p in range(start, end):
            j = p % R
            rec = {
                "kind": HOP_NAMES.get(int(cols["ev_kind"][j]),
                                      str(int(cols["ev_kind"][j]))),
                "src": int(cols["ev_src"][j]),
                "seq": int(cols["ev_seq"][j]),
                "dst": int(cols["ev_dst"][j]),
                "win": int(cols["ev_win"][j]),
                "t_ns": int(cols["ev_win"][j]) * self.window_ns
                + int(cols["ev_t"][j]),
            }
            self._write(rec)

    def finalize(self) -> None:
        """Drain the pending snapshot and flush/close the sink.
        Idempotent."""
        self.drain()
        if self._sink is not None:
            self._sink.flush()
            if self._own_sink:
                self._sink.close()
                self._sink = None

    # -- growth (elastic capacity participation) -------------------------

    def want_growth(self) -> bool:
        """True when a drain reported overwritten events since the last
        growth — the elastic driver's cue to `grow_ring` (and
        retrace)."""
        return self.overwritten > self._grown_at

    def note_grown(self) -> None:
        self._grown_at = self.overwritten

    # -- emission --------------------------------------------------------

    def _write(self, rec: dict) -> None:
        if self._sink is not None:
            self._sink.write(json.dumps(rec, sort_keys=True) + "\n")
        if self._retain:
            self.hops.append(rec)
        self.recorded += 1

    def summary(self) -> dict:
        """JSON-ready drain summary for driver records."""
        return {
            "recorded_hops": self.recorded,
            "overwritten": self.overwritten,
            "sink": self.sink_path,
        }


def read_hops(lines) -> list[dict]:
    """Parse a hops JSONL stream back into hop dicts (the report/export
    input path)."""
    out = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "kind" in rec:
            out.append(rec)
    return out


def hop_flows(hops: list[dict]) -> dict[tuple[int, int], list[dict]]:
    """Group hops by packet identity (src, seq), each group in hop
    order — the Perfetto flow-event builder's input."""
    flows: dict[tuple[int, int], list[dict]] = {}
    for h in hops:
        flows.setdefault((h["src"], h["seq"]), []).append(h)
    for group in flows.values():
        group.sort(key=lambda h: (h["t_ns"], h["kind"]))
    return flows


def flightrec_meta(fr: FlightRecArrays) -> dict:
    """Static recorder parameters for checkpoint meta / run records."""
    return {
        "sample_every": int(np.asarray(fr.sample_every)),
        "ring": ring_capacity(fr),
    }
