"""Asynchronous telemetry harvest: device counters -> JSONL heartbeats.

The harvest cycle is double-buffered so the device NEVER blocks for
telemetry:

- `tick(now_ns, ...)` first *drains* the previous tick's snapshot —
  whose D2H copy has had a whole harvest interval to complete — then
  starts an asynchronous copy of the current counter arrays
  (`Array.copy_to_host_async`, falling back to holding the reference
  when the backend has no async copy, e.g. plain numpy stand-ins in
  tests). Nothing here calls `block_until_ready`, and the only
  materialization (`np.asarray`) happens on buffers that are already
  host-resident by the time they are read.
- Heartbeats therefore trail the simulation by one harvest interval;
  `finalize()` drains the last snapshot at end of run.

Counters arrive as modular-2^32 int32 (the device dtype discipline,
`telemetry/metrics.py`); `unwrap_u32` reconstructs monotone int64
totals from uint32 deltas per interval. High-water-mark fields
(`max_*`) and the CPU-side tracker counters are plain values and pass
through unchanged.

Output is deterministic JSONL (sorted keys, virtual-time stamped — no
wall-clock anywhere): one ``sim`` summary line per harvest plus one
``host`` line per host (disable with per_host=False for huge fleets),
written to the configured sink and summarized through the
`core/shadowlog.py` logging tree.
"""

from __future__ import annotations

import json
import logging
from typing import Mapping, Optional

import numpy as np

log = logging.getLogger("shadow_tpu.telemetry")

#: PlaneMetrics fields that are high-water marks, not modular counters —
#: they aggregate across hosts with max, never sum (export.py shares this)
MAX_FIELDS = frozenset({"max_eg_depth", "max_in_depth"})
_MAX_FIELDS = MAX_FIELDS

_U32 = np.uint64(1 << 32)


def unwrap_u32(prev_raw, cur_raw):
    """Delta of a modular-2^32 counter between two raw snapshots.

    Exact as long as the true delta is < 2^32 (one harvest interval's
    worth of movement); returns int64 (array or scalar)."""
    p = np.asarray(prev_raw).astype(np.int64) & 0xFFFFFFFF
    c = np.asarray(cur_raw).astype(np.int64) & 0xFFFFFFFF
    return (c - p) % np.int64(_U32)


def counter_delta(prev_raw, cur_raw):
    """Modular uint32 delta between two int32 counter snapshots — the
    RECORD half of the memo plane's delta replay (`tpu/memo.py`).

    Same modular-2^32 reading of the device counters as
    :func:`unwrap_u32` (``int(counter_delta(p, c)) == unwrap_u32(p, c)``
    elementwise, pinned in tests/test_memo.py), kept in uint32 so
    :func:`apply_counter_delta` wrap-adds it exactly like the device's
    int32 accumulation does."""
    p = np.asarray(prev_raw)
    c = np.asarray(cur_raw)
    if p.dtype != np.int32 or c.dtype != np.int32:
        raise TypeError(
            f"counter_delta wants int32 modular counters, got "
            f"{p.dtype}/{c.dtype}")
    # signed->unsigned astype wraps mod 2^32 (C semantics), so the
    # subtraction is exact through both the 2^31 sign flip and the
    # 2^32 full wrap
    return c.astype(np.uint32) - p.astype(np.uint32)


def apply_counter_delta(base_raw, delta_u32):
    """Wrap-add a :func:`counter_delta` onto a live int32 counter — the
    REPLAY half of the memo plane's delta replay.

    Bitwise-equal to the device having executed the span itself: XLA
    int32 addition is two's-complement modular, which is exactly
    uint32 addition reinterpreted, so applying the recorded delta
    reproduces the cold run's counter through any wrap point (the
    2^31/2^32 boundary pins in tests/test_memo.py)."""
    b = np.asarray(base_raw)
    d = np.asarray(delta_u32)
    if b.dtype != np.int32 or d.dtype != np.uint32:
        raise TypeError(
            f"apply_counter_delta wants int32 base + uint32 delta, got "
            f"{b.dtype}/{d.dtype}")
    return (b.astype(np.uint32) + d).astype(np.int32)


def _leaves(device) -> dict:
    """Normalize a device-counter source to {name: array}: a
    PlaneMetrics-style NamedTuple, a mapping, or None."""
    if device is None:
        return {}
    if hasattr(device, "_asdict"):
        return dict(device._asdict())
    return dict(device)


class TelemetryHarvester:
    """Snapshots device counters every `interval_ns` of virtual time,
    merges them with CPU-plane per-host counters under one host-id
    namespace, and emits JSONL heartbeats.

    `sink` is a path (opened/closed by the harvester) or a file-like
    object (borrowed). `host_names[i]` names host_id i+1; device array
    row i and CPU counters for host_id i+1 merge onto the same line.
    `slot_capacity` is the static per-window sort-slot capacity
    (N*(CE+CI) for the general plane) used to turn the accumulated
    `sort_slots` into an occupancy ratio."""

    def __init__(self, *, interval_ns: int, sink=None,
                 host_names: Optional[list[str]] = None,
                 slot_capacity: Optional[int] = None,
                 per_host: bool = True, retain: bool = True,
                 on_drain=None):
        """`on_drain(time_ns, device_totals, cpu)` is invoked at the end
        of every drain, when the snapshot's asynchronous device copy has
        materialized — the guard plane's cross-plane reconciliation hook
        (guards/reconcile.py). `device_totals` maps counter name to the
        unwrapped int64 totals (per-host arrays / scalars); `cpu` is the
        tick-time CPU counter snapshot. The callback may raise (an abort
        guard policy): the pending snapshot was already consumed, so a
        later finalize() still flushes cleanly."""
        if interval_ns <= 0:
            raise ValueError("telemetry interval must be positive")
        self._on_drain = on_drain
        self.interval_ns = int(interval_ns)
        self._next_due = int(interval_ns)
        self._per_host = per_host
        self._retain = retain
        self._slot_capacity = slot_capacity
        self._host_names = host_names
        self._pending = None  # (time_ns, {name: array-ref}, cpu dict)
        self._events: list[dict] = []  # run-lifecycle events for the
        # next sim heartbeat line (capacity growth, ...; note_event)
        self._prev_raw: dict[str, np.ndarray] = {}
        self._totals: dict[str, np.ndarray] = {}
        self.heartbeats: list[dict] = []  # retained emitted records
        self.emitted = 0  # JSONL lines written
        self.harvests = 0  # completed (drained) snapshots
        self._own_sink = isinstance(sink, str)
        #: resolved sink path for callers reporting where heartbeats
        #: landed (None = borrowed file object or log-summary-only)
        self.sink_path = sink if self._own_sink else None
        self._sink = open(sink, "w") if self._own_sink else sink

    # -- cadence ---------------------------------------------------------

    def due(self, now_ns: int) -> bool:
        return now_ns >= self._next_due

    # -- run-lifecycle events --------------------------------------------

    def note_event(self, record: dict) -> None:
        """Queue a structured run-lifecycle event (a capacity-ring
        growth, a kernel fallback, ...) for the NEXT emitted sim
        heartbeat line (its ``annotations`` field) — and, through it, a
        trace instant in the Perfetto export. Records must be
        JSON-serializable and should carry a virtual ``time_ns``; they
        never touch the hot path (attached at drain time)."""
        self._events.append(dict(record))

    # -- the harvest cycle ----------------------------------------------

    def tick(self, now_ns: int, device=None,
             cpu: Optional[Mapping[int, dict]] = None) -> None:
        """One harvest: drain the previous snapshot (its async copy is
        long done), then start copying the current counters. `device`
        is a PlaneMetrics / {name: [N] array} source; `cpu` maps
        host_id -> plain counter dict (values copied immediately —
        they are host-side ints already)."""
        self.drain()
        leaves = _leaves(device)
        for arr in leaves.values():
            copy = getattr(arr, "copy_to_host_async", None)
            if copy is not None:
                copy()
        cpu_copy = (
            {int(hid): dict(counters) for hid, counters in cpu.items()}
            if cpu else None
        )
        self._pending = (int(now_ns), leaves, cpu_copy)
        while self._next_due <= now_ns:
            self._next_due += self.interval_ns

    def drain(self) -> None:
        """Materialize and emit the pending snapshot, if any."""
        if self._pending is None:
            return
        time_ns, leaves, cpu = self._pending
        self._pending = None
        device_now: dict[str, np.ndarray] = {}
        for name, arr in leaves.items():
            raw = np.asarray(arr)
            if name in _MAX_FIELDS:
                device_now[name] = raw.astype(np.int64)
                continue
            prev = self._prev_raw.get(name)
            delta = unwrap_u32(0 if prev is None else prev, raw)
            total = self._totals.get(name)
            self._totals[name] = delta if total is None else total + delta
            self._prev_raw[name] = raw
            device_now[name] = self._totals[name]
        self.harvests += 1
        self._emit(time_ns, device_now, cpu)
        if self._on_drain is not None:
            self._on_drain(time_ns, device_now, cpu)

    def finalize(self) -> None:
        """Drain the pending snapshot and flush/close the sink.
        Idempotent — the Manager also calls it on the crash path."""
        self.drain()
        if self._sink is not None:
            self._sink.flush()
            if self._own_sink:
                self._sink.close()
                self._sink = None

    # -- emission --------------------------------------------------------

    def _write(self, record: dict) -> None:
        if self._sink is not None:
            self._sink.write(json.dumps(record, sort_keys=True) + "\n")
        if self._retain:
            self.heartbeats.append(record)
        self.emitted += 1

    def _host_name(self, idx: int) -> str:
        if self._host_names and idx < len(self._host_names):
            return self._host_names[idx]
        return f"host{idx + 1}"

    def _emit(self, time_ns: int, device: dict[str, np.ndarray],
              cpu: Optional[dict[int, dict]]) -> None:
        per_host = {k: v for k, v in device.items() if np.ndim(v) == 1}
        scalars = {k: int(v) for k, v in device.items() if np.ndim(v) == 0}
        # [N, B] leaves are per-host log2 histograms (telemetry/histo.py,
        # conventionally `hist_`-prefixed): the sim line carries the
        # fleet-summed bucket vector per histogram, host lines each
        # host's own row — raw unwrapped counts, so percentile math
        # downstream (report/export) stays exact and byte-stable
        hists = {k: v for k, v in device.items() if np.ndim(v) == 2}
        sim: dict = {"type": "sim", "time_ns": time_ns}
        if hists:
            sim["hist"] = {
                k: [int(x) for x in v.sum(axis=0)]
                for k, v in sorted(hists.items())}
        if self._events:
            # resize & co. ride the heartbeat stream once, in order
            # ("annotations", not "events" — that name is the
            # PlaneMetrics per-window event counter)
            sim["annotations"], self._events = self._events, []
        sim.update(scalars)
        if "sort_slots" in scalars and self._slot_capacity and \
                scalars.get("windows"):
            sim["sort_occupancy"] = round(
                scalars["sort_slots"]
                / (scalars["windows"] * self._slot_capacity), 6)
        if per_host:
            # high-water marks aggregate with max (a fleet-summed "max
            # depth" would read as an impossible queue length); counters
            # aggregate with sum
            sim["device_totals"] = {
                k: int(v.max() if k in _MAX_FIELDS else v.sum())
                for k, v in sorted(per_host.items())}
        if cpu:
            agg: dict[str, int] = {}
            for counters in cpu.values():
                for k, v in counters.items():
                    if isinstance(v, (int, np.integer)):
                        agg[k] = agg.get(k, 0) + int(v)
            sim["cpu_totals"] = agg
        self._write(sim)
        log.info("telemetry time_ns=%d %s", time_ns,
                 json.dumps(sim, sort_keys=True))
        if not self._per_host:
            return
        n = max((v.shape[0] for v in per_host.values()), default=0)
        n = max(n, max((v.shape[0] for v in hists.values()), default=0))
        ids = set(range(1, n + 1)) | set(cpu.keys() if cpu else ())
        for hid in sorted(ids):
            rec: dict = {"type": "host", "time_ns": time_ns,
                         "host_id": hid, "host": self._host_name(hid - 1)}
            if per_host and hid - 1 < n:
                rec["device"] = {k: int(v[hid - 1])
                                 for k, v in sorted(per_host.items())
                                 if hid - 1 < v.shape[0]}
            if hists and hid - 1 < n:
                rec["hist"] = {
                    k: [int(x) for x in v[hid - 1]]
                    for k, v in sorted(hists.items())
                    if hid - 1 < v.shape[0]}
            if cpu and hid in cpu:
                rec["cpu"] = cpu[hid]
            self._write(rec)
