"""On-device log2-bucketed latency/depth histograms (`PlaneHistograms`).

The scalar counters in `telemetry/metrics.py` answer "how much" but not
"how bad": a p99 delivery latency under incast, or how often a queue ran
deep, are DISTRIBUTION questions the telemetry plane could not answer
without host-side replay. This module is the batched-SoA equivalent of
an HDR histogram: per-host `[N, B]` int32 bucket matrices where bucket
``b`` counts observations in ``[2^b, 2^(b+1))`` nanoseconds (or queue
slots), accumulated ON DEVICE with pure `jnp` one-hot sums and
scatter-adds inside the existing jitted kernels — under the exact rules
`PlaneMetrics` obeys (docs/observability.md):

1. **Zero host syncs on the hot path.** Histograms ride the kernel
   carry as a static presence switch (`window_step(..., hist=None)`
   compiles the section out) and are only pulled by the
   `TelemetryHarvester`'s asynchronous drain.
2. **Bitwise-invisible to the simulation.** Every bucket is computed
   from values the window step already materialized; nothing feeds
   back. tests/test_flightrec.py pins hist-on == hist-off state
   bitwise across the qdisc matrix (plus faults-on and workload-on
   worlds).
3. **Dtype discipline.** Buckets are int32 and wrap modulo 2^32 like
   every modular counter; the harvester delta-unwraps them per
   interval (`harvest.unwrap_u32`), so percentiles computed from the
   unwrapped totals are exact. The bucket index itself is pure integer
   arithmetic (`31 - clz(v)`), never a float log2 — a float32 log near
   a power-of-two boundary would misbucket and break bitwise replay.

Percentile extraction (`percentile`/`percentiles`) happens HOST-SIDE on
the unwrapped totals and reports the bucket's UPPER edge — a
conservative bound, exact to within the 2x bucket resolution, which is
what a log-bucketed histogram promises (the HDR trade: O(B) memory for
bounded relative error at any scale).

This module is dependency-light (jax/numpy only): `tpu/plane.py`
imports it, never the other way around.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

#: log2 buckets: bucket b counts values v with floor(log2(max(v, 1)))
#: == b, i.e. [2^b, 2^(b+1)) for v >= 1; bucket 0 also absorbs v <= 1
#: (sub-2ns latencies / empty-or-single-slot queues). 32 buckets cover
#: the whole int32 ns domain (2^31 ns ~ 2.1 s, the device window
#: budget) with no clipping ambiguity.
HIST_BUCKETS = 32

#: the standard SLO quantiles the report surfaces
QUANTILES = (0.5, 0.9, 0.99, 0.999)

#: harvester/export key prefix marking a [N, B] histogram leaf in a
#: device-counter dict (harvest.py splits on leaf rank, this prefix
#: keeps the JSONL namespace self-describing)
HIST_PREFIX = "hist_"


class PlaneHistograms(NamedTuple):
    """Accumulating device histograms; every leaf is [N, B] int32,
    modular 2^32 (delta-unwrapped by the harvester)."""

    #: delivery latency (deliver - send instant, i.e. wire latency plus
    #: the round-barrier clamp) per packet, attributed to the
    #: DESTINATION host — the consumer's view, the "p99 under incast"
    #: question
    hist_delivery_ns: jax.Array
    #: egress-queue sojourn: how long a packet waited in its source's
    #: egress ring (token-bucket backlog) before clearing the gate,
    #: attributed to the SOURCE host
    hist_sojourn_ns: jax.Array
    #: queue-depth samples: one observation per host per window (egress
    #: occupancy at entry + ingress occupancy after the arrival merge)
    #: plus one per ingest_rows append (post-append egress occupancy) —
    #: bucket b counts samples with depth in [2^b, 2^(b+1))
    hist_qdepth: jax.Array


def make_histograms(n_hosts: int) -> PlaneHistograms:
    """A zeroed histogram pytree for `n_hosts` hosts."""
    z = lambda: jnp.zeros((n_hosts, HIST_BUCKETS), jnp.int32)
    return PlaneHistograms(
        hist_delivery_ns=z(), hist_sojourn_ns=z(), hist_qdepth=z())


def hist_names() -> tuple[str, ...]:
    """Leaf names in pytree order (the harvester's histogram keys)."""
    return tuple(PlaneHistograms._fields)


# -- device-side accumulation (pure jnp; safe inside jit) -----------------


def bucket_index(values: jax.Array) -> jax.Array:
    """log2 bucket of int32 values: floor(log2(max(v, 1))), clipped to
    [0, HIST_BUCKETS). Pure integer arithmetic via count-leading-zeros —
    exact at every power-of-two boundary (a float32 log2 is not)."""
    v = jnp.maximum(values.astype(jnp.int32), 1)
    return jnp.clip(31 - jax.lax.clz(v), 0, HIST_BUCKETS - 1)


def accum_rows(h: jax.Array, bucket: jax.Array,
               mask: jax.Array) -> jax.Array:
    """Fold [N, C] per-slot observations into the [N, B] histogram of
    the ROW (source-attributed): a one-hot compare + sum, no scatter
    dispatch (shards cleanly along the host axis)."""
    onehot = (bucket[:, :, None]
              == jnp.arange(HIST_BUCKETS, dtype=jnp.int32)) \
        & mask[:, :, None]
    return h + onehot.sum(axis=1, dtype=jnp.int32)


def accum_scatter(h: jax.Array, rows: jax.Array, bucket: jax.Array,
                  mask: jax.Array) -> jax.Array:
    """Fold [N, C] per-slot observations into the [N, B] histogram of
    an arbitrary target row per slot (destination-attributed): one
    2-D scatter-add. int32 adds commute exactly, so the result is
    bitwise-identical under any sharding/execution order. Out-of-range
    rows must be pre-masked by the caller."""
    n = h.shape[0]
    r = jnp.clip(rows, 0, n - 1).reshape(-1)
    return h.at[r, bucket.reshape(-1)].add(
        mask.reshape(-1).astype(jnp.int32), mode="drop")


def accum_depth(h: jax.Array, depth: jax.Array) -> jax.Array:
    """One depth observation per host ([N] int32 occupancy) into the
    [N, B] histogram."""
    onehot = (bucket_index(depth)[:, None]
              == jnp.arange(HIST_BUCKETS, dtype=jnp.int32))
    return h + onehot.astype(jnp.int32)


# -- host-side percentile extraction (outside jit; unwrapped totals) ------


def bucket_edges(b: int) -> tuple[int, int]:
    """[lo, hi) value bounds of bucket ``b`` (lo of bucket 0 is 0: it
    absorbs sub-2 observations)."""
    return (0 if b == 0 else 1 << b, 1 << (b + 1))


def percentile(counts, q: float) -> int:
    """The q-quantile's conservative upper bound from a bucket-count
    vector ([B] ints): the UPPER edge of the first bucket whose
    cumulative count reaches ceil(q * total). 0 when the histogram is
    empty. Exact to within the 2x log-bucket resolution."""
    c = np.asarray(counts, np.int64)
    total = int(c.sum())
    if total <= 0:
        return 0
    need = int(np.ceil(q * total))
    need = max(need, 1)
    cum = np.cumsum(c)
    b = int(np.searchsorted(cum, need))
    return bucket_edges(min(b, HIST_BUCKETS - 1))[1]


def percentiles(counts, qs=QUANTILES) -> dict:
    """{"p50": ..., "p99": ..., ...} upper-bound values for the given
    quantiles; keys are the conventional percentile labels (0.5 -> p50,
    0.9 -> p90, 0.99 -> p99, 0.999 -> p999)."""
    out = {}
    for q in qs:
        digits = f"{q:g}".split(".")[1]
        key = "p" + (digits + "0" if len(digits) == 1 else digits)
        out[key] = percentile(counts, q)
    return out


def fleet_percentiles(hist_nb, qs=QUANTILES) -> dict:
    """`percentiles` over the fleet-summed [N, B] histogram (int64
    accumulation, so a saturated 2^31-count fleet cannot wrap the
    sum): the per-scenario SLO view the runner records — one p99/p999
    line per histogram, aggregated over every host."""
    return percentiles(np.asarray(hist_nb, np.int64).sum(axis=0), qs)


def ensemble_percentiles(world_counts, qs=QUANTILES) -> dict:
    """Percentile-of-percentiles across an ensemble of worlds
    (ROADMAP item 4's error bars): `world_counts` is one [B]
    bucket-count vector PER WORLD for the same histogram; each world's
    quantiles are extracted independently (`percentiles`), then each
    quantile's cross-world spread is reported as min/median/max —
    ``{"p99": {"min": ..., "median": ..., "max": ..., "worlds": W}}``.

    The median is `statistics.median` (the midpoint average for even
    W), so a 2-world ensemble reports exactly the two worlds' mean —
    the hand-computable case tests/test_tracer.py pins. Worlds whose
    histogram is empty still contribute (their percentiles are 0, a
    real "this world saw no observations" datum), and an empty world
    LIST raises — an ensemble of zero worlds has no percentiles."""
    import statistics

    if not world_counts:
        raise ValueError(
            "ensemble_percentiles needs >= 1 world bucket vector")
    per_world = [percentiles(c, qs) for c in world_counts]
    out = {}
    for key in per_world[0]:
        vals = sorted(p[key] for p in per_world)
        out[key] = {"min": vals[0],
                    "median": statistics.median(vals),
                    "max": vals[-1],
                    "worlds": len(vals)}
    return out
