"""On-device simulation counters for the TPU plane (`PlaneMetrics`).

The device plane's simulation state (`tpu/plane.py NetPlaneState`) keeps
only the counters the simulation itself needs (n_sent, drop totals).
Everything an operator needs to *debug* a run — per-host traffic, drops
broken down by reason, queue-depth high-water marks, per-window event
and sort-occupancy figures — used to exist only as intermediate traced
values that vanished after each `window_step`. `PlaneMetrics` is the SoA
pytree that accumulates them ON DEVICE with pure `jnp` adds inside the
existing jitted kernels, under three hard rules:

1. **Zero host syncs on the hot path.** Metrics ride the kernel carry
   and are only pulled by the `TelemetryHarvester` every N virtual-time
   windows, via an asynchronous D2H copy (`harvest.py`).
2. **Bitwise-invisible to the simulation.** Every metric is computed
   from values the window step already materialized; nothing feeds back
   into simulation state. `tests/test_telemetry.py` pins metrics-on ==
   metrics-off state across the qdisc matrix.
3. **Dtype discipline.** Counters are int32 like everything else on
   device (tpu/plane.py header) and wrap modulo 2^32 by design; the
   harvester reconstructs monotone 64-bit totals from uint32 deltas
   per harvest interval (`harvest.unwrap_u32`), so wraparound is safe
   as long as any single counter moves < 2^31 between harvests.

Counters answer "how much"; their DISTRIBUTION twins live in
`telemetry/histo.py` (log2-bucketed latency/queue-depth histograms,
threaded as the `hist=` presence switch under the same three rules)
and `telemetry/flightrec.py` (the sampled per-packet hop recorder) —
docs/observability.md "Distributions and the flight recorder".

This module is dependency-light (jax/numpy only): `tpu/plane.py`
imports it, never the other way around.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class PlaneMetrics(NamedTuple):
    """Accumulating device counters; per-host leaves are [N] int32,
    per-window leaves are scalar int32. All modular 2^32."""

    # per-host traffic
    pkts_out: jax.Array  # packets that left the egress gate (sent)
    bytes_out: jax.Array  # wire bytes of those packets
    pkts_in: jax.Array  # packets delivered to this host
    bytes_in: jax.Array  # wire bytes delivered
    # per-host drops, by reason
    drop_ring_full: jax.Array  # egress/ingress ring-capacity overflow
    drop_qdisc: jax.Array  # router AQM (CoDel) drops
    drop_loss: jax.Array  # Bernoulli path-loss samples
    drop_fault: jax.Array  # injected fault-plane drops (crashed-host
    # egress purge, burst corruption, routing toward a down host) —
    # kept apart from drop_loss so an injected outage is never
    # misread as wire loss (docs/robustness.md drop taxonomy)
    # per-host recovery activity (fed by the device TCP layer / callers;
    # the raw plane has no retransmit concept of its own)
    retransmits: jax.Array
    # per-host queue-depth high-water marks (NOT modular: maxima)
    max_eg_depth: jax.Array
    max_in_depth: jax.Array
    # per-window scalars
    windows: jax.Array  # window_step invocations accumulated
    events: jax.Array  # send + deliver events processed
    sort_slots: jax.Array  # occupied egress+ingress slots entering the
    # window's sorts (occupancy ratio = sort_slots / (windows * slot
    # capacity); the capacity is static and supplied by the harvester)


def make_metrics(n_hosts: int) -> PlaneMetrics:
    """A zeroed metrics pytree for `n_hosts` hosts."""
    z = lambda: jnp.zeros((n_hosts,), jnp.int32)
    s = lambda: jnp.zeros((), jnp.int32)
    return PlaneMetrics(
        pkts_out=z(), bytes_out=z(), pkts_in=z(), bytes_in=z(),
        drop_ring_full=z(), drop_qdisc=z(), drop_loss=z(),
        drop_fault=z(),
        retransmits=z(), max_eg_depth=z(), max_in_depth=z(),
        windows=s(), events=s(), sort_slots=s(),
    )


def add_retransmits(metrics: PlaneMetrics,
                    per_host: jax.Array) -> PlaneMetrics:
    """Fold per-host retransmission counts (e.g. from the device TCP
    layer's `retransmit_count`, reduced to hosts by the caller) into the
    metrics pytree. Pure add; safe inside jit."""
    return metrics._replace(
        retransmits=metrics.retransmits + per_host.astype(jnp.int32))


def metric_names() -> tuple[str, ...]:
    """Leaf names in pytree order (the harvester's column order)."""
    return tuple(PlaneMetrics._fields)
