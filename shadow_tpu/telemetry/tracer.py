"""shadowscope: the run ledger + the two-clock Chrome-trace export.

`RunTracer` is the driver-loop flight log (docs/observability.md "Run
ledger"): one structured JSONL record per chain span, emitted at the
chain-boundary host sync the driver already owns — SL603-compliant by
construction, because the tracer never touches a device value. Every
field it records is either host wall-clock (`time.monotonic`), a plain
python int the driver computed from the span bounds, or a dict some
boundary hook already materialized (memo stats, capacity-trajectory
events, harvest/guard annotations). Zero new in-loop syncs; this file
rides `costmodel.DRIVER_MODULES` so the AST fence re-proves that on
every CI run.

Presence-invisibility contract (the SL501 discipline, enforced here by
the trace-parity gate rather than a jaxpr taint proof — the tracer has
no device surface for the prover to walk): a traced run is
digest-identical to an untraced run across the full golden corpus.
Wall-clock fields (`WALL_FIELDS`) are excluded from every compare; the
ledger is a SEPARATE artifact from the golden records, which carry no
wall time at all.

The ledger schema is version-stamped (`RUNLEDGER_SCHEMA`) and
drift-pinned by tests/test_tracer.py: any field change to the span
record bumps the version or fails the pin.

Record kinds on the ledger:

- ``meta`` (first line): ``schema``, ``label``, ``backend`` fingerprint
  (platform + device kind — the cross-container MEANINGLESS-banner
  key), plus caller metadata (chain_len, n_rounds, scenario
  fingerprint, ...).
- ``span``: one per committed chain span — ``r0``/``r1``/``windows``,
  ``mode`` (execute | replay | ffwd | ensemble), the wall-time split
  (``wall_ms`` total, ``dispatch_ms`` device dispatch+readback,
  ``memo_ms`` snapshot/key/record, ``hook_ms`` on_chain), capacity
  ``growth`` events the span committed, and the memo/fault-span
  fingerprint (``span_salt``) when the driver has one.
- ``annotation`` records (caller kinds: ``harvest``, ``guards``,
  ``checkpoint``, ``tamper``, ``kill``, ...): boundary-hook events at
  their wall instant.
- ``memo``: the full `ChainMemo.report()` — the ONE artifact
  `--memo-report` is a filtered view of (tools/trace_report.py
  ``--memo-view``).
- ``end`` (last line): total wall, span/sync counts.

`write_chrome_trace` lays the ledger out as the "driver (wall time)"
process row of a Chrome trace-event JSON — spans as nested X slices
(span > dispatch/memo/hook), annotations as instants — and, when given
a heartbeat stream, merges the existing virtual-time simulation rows
(telemetry/export.py) beside it. Two clock tracks, one artifact: the
driver row's µs are wall µs since run start, the simulation rows' µs
are simulated µs; `otherData.clocks` names both.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterable, Optional

#: the ledger schema version: bump on ANY change to the span-record
#: field set (tests/test_tracer.py pins both).
RUNLEDGER_SCHEMA = "runledger-v1"

#: fields always present on a ``span`` record, in emission order —
#: the drift-pin surface.
SPAN_FIELDS = ("kind", "seq", "r0", "r1", "windows", "mode",
               "wall_t0_ms", "wall_ms", "dispatch_ms", "memo_ms",
               "hook_ms")

#: wall-clock fields — excluded from EVERY compare (trace-parity,
#: compare_runs ratios gate on aggregates, never on these raw values
#: matching across runs).
WALL_FIELDS = frozenset({"wall_t0_ms", "wall_ms", "dispatch_ms",
                         "memo_ms", "hook_ms"})

#: span execution modes the driver reports.
SPAN_MODES = ("execute", "replay", "ffwd", "ensemble")

#: the driver row's pid in the merged Chrome trace — far above any
#: heartbeat host_id row (those are host index + 1).
DRIVER_PID = 1_000_000


def backend_fingerprint() -> dict:
    """The same (platform, device_kind) fingerprint bench.py stamps on
    its records — computed lazily so importing the tracer never pulls
    jax. Cross-container ledger comparisons fail loudly on mismatch
    (compare_runs --trace MEANINGLESS banner)."""
    import jax

    dev = jax.devices()[0]
    return {"platform": dev.platform, "device_kind": dev.device_kind}


class RunTracer:
    """Accumulates the run ledger in host memory; `write` dumps JSONL.

    The driver calls `clock()`/`span()` at chain boundaries; boundary
    hooks call `annotate()`; the owner calls `memo_close()`/`close()`/
    `write()` once after the drive loop returns. Nothing here may read
    a device value — pass host scalars/dicts only.

    ``sink`` switches the ledger to STREAMING mode: every record is
    appended (and flushed + fsynced) to the file the instant it is
    recorded, so a SIGKILL preserves everything up to the last chain
    boundary — the crash-survivable ledger a checkpointed run needs.
    ``resume=True`` (requires ``sink``) APPENDS to an existing ledger
    instead of truncating it, and suppresses the duplicate head meta
    record (`read_ledger`'s first-line contract): the resumed run's
    records continue the killed run's stream, and the caller marks the
    seam with an ``annotate("resume", checkpoint=...)`` record that
    `stitch_ledger` / trace_report use to rebase the second segment's
    wall clocks (docs/observability.md "Ledger stitching")."""

    def __init__(self, label: str = "run", *, backend: dict | None = None,
                 meta: dict | None = None, sink: str | None = None,
                 resume: bool = False):
        if resume and sink is None:
            raise ValueError("RunTracer(resume=True) requires a sink "
                             "path — only a streamed ledger can be "
                             "appended across a resume")
        self.label = label
        self._origin = time.monotonic()  # shadowlint: disable=SL101 -- wall-clock ledger origin; never feeds sim time
        self._seq = 0
        self._sink = None
        self.sink_path = sink
        self.resumed = bool(resume)
        head = {"schema": RUNLEDGER_SCHEMA, "kind": "meta",
                "label": label,
                "backend": dict(backend) if backend is not None
                else backend_fingerprint()}
        if meta:
            head.update({k: v for k, v in meta.items()
                         if k not in ("schema", "kind")})
        self.records: list[dict] = [head]
        if sink is not None:
            self._sink = open(sink, "a" if resume else "w")
            if not resume:
                self._emit(head)

    def _emit(self, rec: dict) -> None:
        if self._sink is None:
            return
        self._sink.write(json.dumps(rec, sort_keys=True) + "\n")
        self._sink.flush()
        os.fsync(self._sink.fileno())

    # -- driver hooks ----------------------------------------------------

    def clock(self) -> float:
        """Host monotonic seconds — the only clock the ledger knows."""
        return time.monotonic()  # shadowlint: disable=SL101 -- the ledger IS the wall-clock artifact

    def span(self, r0: int, r1: int, *, mode: str, t0: float,
             dispatch_ms: float = 0.0, memo_ms: float = 0.0,
             hook_ms: float = 0.0, growth=None, span_salt=None,
             **extra) -> dict:
        """One committed chain span. `t0` is the `clock()` value taken
        at span start; total wall closes here. `growth` is the list of
        capacity-trajectory events this span committed; `span_salt` is
        the memo/fault-span fingerprint hex when the driver has one."""
        now = time.monotonic()  # shadowlint: disable=SL101 -- span wall close; parity-gated trace-invisible
        rec = {"kind": "span", "seq": self._seq, "r0": int(r0),
               "r1": int(r1), "windows": int(r1) - int(r0),
               "mode": mode,
               "wall_t0_ms": (t0 - self._origin) * 1e3,
               "wall_ms": (now - t0) * 1e3,
               "dispatch_ms": dispatch_ms, "memo_ms": memo_ms,
               "hook_ms": hook_ms}
        if growth:
            rec["growth"] = [dict(ev) for ev in growth]
        if span_salt is not None:
            rec["span_salt"] = span_salt
        rec.update(extra)
        self._seq += 1
        self.records.append(rec)
        self._emit(rec)
        return rec

    def annotate(self, kind: str, **fields) -> dict:
        """A boundary-hook event (harvest tick, guard deltas,
        checkpoint/tamper/kill, resume seam, fault-span fingerprint)
        at its wall instant. `fields` must be host values."""
        rec = {"kind": kind,
               "wall_t0_ms": (time.monotonic() - self._origin) * 1e3}  # shadowlint: disable=SL101 -- annotation wall instant
        rec.update(fields)
        self.records.append(rec)
        self._emit(rec)
        return rec

    # -- finalization ----------------------------------------------------

    def memo_close(self, memo) -> dict:
        """Fold the `ChainMemo.report()` into the ledger — ONE
        artifact; `--memo-report` stays a filtered view of this record
        (trace_report.py --memo-view, pinned by test)."""
        rec = {"kind": "memo", "report": memo.report()}
        self.records.append(rec)
        self._emit(rec)
        return rec

    def close(self, **fields) -> dict:
        """Terminal record: total wall + span/sync accounting (spans
        counted from THIS process — a resumed ledger's earlier
        segments live only in the sink file). Closes the sink."""
        spans = [r for r in self.records if r.get("kind") == "span"]
        rec = {"kind": "end",
               "wall_ms": (time.monotonic() - self._origin) * 1e3,  # shadowlint: disable=SL101 -- total run wall
               "spans": len(spans),
               "windows": sum(r["windows"] for r in spans)}
        rec.update(fields)
        self.records.append(rec)
        self._emit(rec)
        if self._sink is not None:
            self._sink.close()
            self._sink = None
        return rec

    def write(self, path: str) -> dict:
        """Dump the ledger as JSONL (meta first, end last when
        `close()` ran). In streaming-sink mode the file is already on
        disk record-by-record: writing to the sink path is a no-op
        (returns its summary); writing elsewhere copies the in-memory
        records (which on a resumed tracer are THIS segment only)."""
        if self.sink_path is not None and (
                os.path.abspath(path) == os.path.abspath(self.sink_path)):
            return {"path": path, "records": len(self.records),
                    "streamed": True}
        with open(path, "w") as fh:
            for rec in self.records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return {"path": path, "records": len(self.records)}


# --------------------------------------------------------------------------
# ledger readers (trace_report.py / compare_runs.py share these)
# --------------------------------------------------------------------------


def read_ledger(lines: Iterable[str]) -> list[dict]:
    """Parse a run-ledger JSONL stream, enforcing the schema stamp on
    the meta line — a ledger from a different schema version refuses to
    parse rather than mis-attributing fields."""
    records = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        records.append(json.loads(line))
    if not records or records[0].get("kind") != "meta":
        raise ValueError("run ledger must start with a meta record")
    schema = records[0].get("schema")
    if schema != RUNLEDGER_SCHEMA:
        raise ValueError(
            f"run-ledger schema mismatch: file says {schema!r}, this "
            f"tree reads {RUNLEDGER_SCHEMA!r} — regenerate the ledger "
            "or use the matching tools/trace_report.py")
    return records


def load_ledger(path: str) -> list[dict]:
    with open(path) as fh:
        return read_ledger(fh)


def stitch_ledger(records: list[dict]) -> tuple[list[dict], int]:
    """Rebase a resumed ledger's wall clocks onto one monotone
    timeline.

    A killed-and-resumed run's ledger is one stream with a ``resume``
    annotation at every seam (the resumed tracer appends; no duplicate
    head meta). Each segment's wall clocks restart at its own process
    origin, so raw ``wall_t0_ms`` values overlap; this shifts every
    post-seam record forward by the maximum wall extent seen so far —
    purely presentational (WALL_FIELDS are excluded from every
    compare), but it is what makes the Chrome export render segments
    side by side instead of stacked. Returns ``(rebased_records,
    n_resumes)``; untouched pass-through when no seam exists."""
    out: list[dict] = []
    offset = 0.0
    seg_max = 0.0
    resumes = 0
    for rec in records:
        if rec.get("kind") == "resume":
            resumes += 1
            offset = seg_max
        if "wall_t0_ms" in rec:
            rec = dict(rec)
            rec["wall_t0_ms"] += offset
            seg_max = max(seg_max,
                          rec["wall_t0_ms"] + rec.get("wall_ms", 0.0))
        out.append(rec)
    return out, resumes


def phase_totals(records: list[dict]) -> dict:
    """Aggregate wall attribution — the per-phase table compare_runs
    --trace and trace_report print: totals plus a per-mode breakdown.
    All values are wall-clock (WALL_FIELDS discipline: meaningful only
    within one backend fingerprint)."""
    spans = [r for r in records if r.get("kind") == "span"]
    out = {
        "spans": len(spans),
        "windows": sum(r["windows"] for r in spans),
        "wall_ms": sum(r["wall_ms"] for r in spans),
        "dispatch_ms": sum(r["dispatch_ms"] for r in spans),
        "memo_ms": sum(r["memo_ms"] for r in spans),
        "hook_ms": sum(r["hook_ms"] for r in spans),
        "growth_events": sum(len(r.get("growth", ())) for r in spans),
        "resumes": sum(1 for r in records if r.get("kind") == "resume"),
    }
    for mode in SPAN_MODES:
        picked = [r for r in spans if r["mode"] == mode]
        out[f"{mode}_spans"] = len(picked)
        out[f"{mode}_ms"] = sum(r["wall_ms"] for r in picked)
    end = next((r for r in records if r.get("kind") == "end"), None)
    if end is not None:
        out["run_wall_ms"] = end["wall_ms"]
    return out


def memo_view(records: list[dict]) -> Optional[dict]:
    """The memo filtered view: the folded `ChainMemo.report()` — what
    `run_scenarios --memo-report` publishes per scenario. None when the
    run was not memoized."""
    rec = next((r for r in records if r.get("kind") == "memo"), None)
    return rec["report"] if rec is not None else None


# --------------------------------------------------------------------------
# the two-clock Chrome-trace export
# --------------------------------------------------------------------------


def write_chrome_trace(records: list[dict], path: str, *,
                       heartbeats: Optional[list[dict]] = None,
                       max_hosts: int = 256, hops=None,
                       max_flows: int = 512) -> dict:
    """Merge the run ledger's wall-time driver spans with the
    virtual-time simulation rows into one Chrome trace-event JSON.

    Driver row (pid `DRIVER_PID`): each span is an X slice whose
    children nest the wall split — `dispatch` at the span start,
    `memo` directly after, `hook` closing the span — so Perfetto's
    slice nesting IS the attribution. Annotations render as instants.
    `ts`/`dur` on this row are wall µs since run start.

    Simulation rows (when `heartbeats` given): exactly the rows
    telemetry/export.py `write_perfetto_trace` draws — harvest slices,
    percentile counters, per-host traffic, flight-recorder flows — on
    the VIRTUAL axis (1 trace µs = 1 simulated µs). The two tracks
    share a timeline but not a clock; `otherData.clocks` names each."""
    # a resumed ledger's segments get their wall clocks rebased onto
    # one monotone axis first (no-op for single-segment ledgers)
    records, _resumes = stitch_ledger(records)
    meta = records[0] if records and records[0].get("kind") == "meta" \
        else {"label": "run"}
    events: list[dict] = [
        {"ph": "M", "pid": DRIVER_PID, "tid": 0, "name": "process_name",
         "args": {"name": "driver (wall time)"}},
        {"ph": "M", "pid": DRIVER_PID, "tid": 0, "name": "thread_name",
         "args": {"name": meta.get("label", "run")}},
    ]
    for rec in records:
        kind = rec.get("kind")
        if kind == "span":
            ts = rec["wall_t0_ms"] * 1e3  # ledger ms -> trace us
            dur = max(rec["wall_ms"], 1e-3) * 1e3
            args = {k: rec[k] for k in
                    ("r0", "r1", "windows", "mode", "span_salt")
                    if k in rec}
            if rec.get("growth"):
                args["growth"] = rec["growth"]
            events.append({
                "ph": "X", "pid": DRIVER_PID, "tid": 0,
                "name": f"{rec['mode']} [{rec['r0']},{rec['r1']})",
                "ts": ts, "dur": dur, "args": args})
            # nested children: measured sub-intervals in their real
            # order (dispatch, then memo bookkeeping, hook last)
            offset = 0.0
            for name, ms in (("dispatch", rec["dispatch_ms"]),
                             ("memo", rec["memo_ms"])):
                if ms > 0:
                    events.append({
                        "ph": "X", "pid": DRIVER_PID, "tid": 0,
                        "name": name, "ts": ts + offset * 1e3,
                        "dur": min(ms, rec["wall_ms"]) * 1e3,
                        "args": {}})
                    offset += ms
            if rec["hook_ms"] > 0:
                events.append({
                    "ph": "X", "pid": DRIVER_PID, "tid": 0,
                    "name": "hook",
                    "ts": ts + max(rec["wall_ms"] - rec["hook_ms"],
                                   offset) * 1e3,
                    "dur": rec["hook_ms"] * 1e3, "args": {}})
        elif kind not in ("meta", "end"):
            events.append({
                "ph": "i", "pid": DRIVER_PID, "tid": 0, "s": "p",
                "name": kind, "ts": rec.get("wall_t0_ms", 0.0) * 1e3,
                "args": {k: v for k, v in rec.items()
                         if k not in ("kind", "wall_t0_ms")}})

    sim_summary = {"hosts_plotted": 0, "hosts_dropped_by_cap": 0,
                   "flows_plotted": 0, "flows_dropped_by_cap": 0}
    if heartbeats:
        from .export import build_sim_events

        sim_events, sim_summary = build_sim_events(
            heartbeats, max_hosts=max_hosts, hops=hops,
            max_flows=max_flows)
        events.extend(sim_events)

    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": RUNLEDGER_SCHEMA,
            "clocks": {
                "driver (wall time)":
                    "wall us since run start (host monotonic)",
                "simulation (virtual time)":
                    "virtual simulated time (1 trace us = 1 sim us)",
            },
            **sim_summary,
        },
    }
    with open(path, "w") as fh:
        json.dump(trace, fh, sort_keys=True)
    return {"path": path, "events": len(events), **sim_summary}
