"""The TPU network plane.

Everything from "socket emits packet" down — rate limiting, latency/loss
sampling, cross-host delivery, queueing — runs as batched JAX ops over
hosts-as-SoA arrays (SURVEY.md §7). The CPU planes (sockets, syscalls,
processes) stay object-level; this plane carries packet *metadata* at scale.
Payload bytes never leave the host: the (src, seq) pair correlates delivered
metadata back to payloads buffered CPU-side.
"""

import os as _os

from .plane import (NetPlaneParams, NetPlaneState, ingest, ingest_rows,
                    make_params, make_state, window_step)
from .mesh import host_sharding, make_mesh, shard_state


def enable_compilation_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent). On a
    tunneled/disaggregated TPU a single window-step compile costs 10-20 s
    of wall time; the cache makes every run after the first pay ~nothing
    for unchanged kernels. Safe no-op if the config knob is missing."""
    import jax

    try:
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update(
                "jax_compilation_cache_dir",
                _os.path.expanduser("~/.cache/shadow_tpu_xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
    except Exception:  # pragma: no cover - knob renamed/removed upstream
        pass

__all__ = [
    "NetPlaneParams",
    "NetPlaneState",
    "ingest",
    "ingest_rows",
    "make_params",
    "make_state",
    "window_step",
    "make_mesh",
    "host_sharding",
    "shard_state",
]
