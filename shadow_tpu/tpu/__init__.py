"""The TPU network plane.

Everything from "socket emits packet" down — rate limiting, latency/loss
sampling, cross-host delivery, queueing — runs as batched JAX ops over
hosts-as-SoA arrays (SURVEY.md §7). The CPU planes (sockets, syscalls,
processes) stay object-level; this plane carries packet *metadata* at scale.
Payload bytes never leave the host: the (src, seq) pair correlates delivered
metadata back to payloads buffered CPU-side.
"""

import os as _os

from .flows import (FlowState, FlowTables, make_flow_state,
                    make_flow_tables)
from .mesh import host_sharding, make_mesh, shard_state
from .plane import (NetPlaneParams, NetPlaneState, chain_windows, ingest,
                    ingest_rows, make_params, make_state, unpack_planes,
                    window_step)


def enable_compilation_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent). On a
    tunneled/disaggregated TPU a single window-step compile costs 10-20 s
    of wall time; the cache makes every run after the first pay ~nothing
    for unchanged kernels. Safe no-op if the config knob is missing."""
    import jax

    try:
        if not jax.config.jax_compilation_cache_dir:
            jax.config.update(
                "jax_compilation_cache_dir",
                _os.path.expanduser("~/.cache/shadow_tpu_xla"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
    except Exception as e:  # pragma: no cover - knob renamed/removed upstream
        # losing the persistent cache is a perf regression, not an
        # error; say so once instead of swallowing (SL401 discipline)
        import logging

        logging.getLogger("shadow_tpu.tpu").debug(
            "persistent compilation cache unavailable: %s", e)

def donating_jit(fun=None, donate_argnums=(0,), **jit_kwargs):
    """`jax.jit` that donates the state-pytree argument(s) so XLA aliases
    the ~20 [N, C] state buffers in place across window dispatches instead
    of re-materializing them. On the CPU backend (tests) donation is a
    warning-only no-op upstream, so it is skipped there to keep test
    output clean. DONATION CONTRACT: callers must treat the donated
    argument as consumed — rebind the returned state and never touch the
    input again (see docs/performance.md)."""
    import functools

    import jax

    if fun is None:
        return functools.partial(donating_jit,
                                 donate_argnums=donate_argnums, **jit_kwargs)
    if jax.default_backend() == "cpu":
        return jax.jit(fun, **jit_kwargs)
    return jax.jit(fun, donate_argnums=donate_argnums, **jit_kwargs)


__all__ = [
    "FlowState",
    "FlowTables",
    "NetPlaneParams",
    "NetPlaneState",
    "chain_windows",
    "donating_jit",
    "make_flow_state",
    "make_flow_tables",
    "ingest",
    "ingest_rows",
    "make_params",
    "make_state",
    "unpack_planes",
    "window_step",
    "make_mesh",
    "host_sharding",
    "shard_state",
]
