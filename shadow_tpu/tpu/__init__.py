"""The TPU network plane.

Everything from "socket emits packet" down — rate limiting, latency/loss
sampling, cross-host delivery, queueing — runs as batched JAX ops over
hosts-as-SoA arrays (SURVEY.md §7). The CPU planes (sockets, syscalls,
processes) stay object-level; this plane carries packet *metadata* at scale.
Payload bytes never leave the host: the (src, seq) pair correlates delivered
metadata back to payloads buffered CPU-side.
"""

from .plane import (NetPlaneParams, NetPlaneState, ingest, ingest_rows,
                    make_params, make_state, window_step)
from .mesh import host_sharding, make_mesh, shard_state

__all__ = [
    "NetPlaneParams",
    "NetPlaneState",
    "ingest",
    "ingest_rows",
    "make_params",
    "make_state",
    "window_step",
    "make_mesh",
    "host_sharding",
    "shard_state",
]
