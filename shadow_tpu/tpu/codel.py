"""Batched CoDel AQM: the per-host inbound router queue as a device kernel.

Parity: reference `src/main/network/router/codel_queue.rs:23-33` (RFC 8289
with Shadow's TARGET = 10ms, INTERVAL = 100ms, unbounded limit) — the same
state machine as the CPU plane's `shadow_tpu.net.router.CoDelQueue`, which
this kernel must match drop-for-drop on any trace (tests/test_tpu_codel.py
replays random traces through both).

Design (TPU-first):
- One window's drain is a bounded `lax.fori_loop` of "micro-steps", each of
  which consumes at most one queue entry or completes one empty pop — the
  CPU implementation's nested pop loops linearized so every host advances
  in lock-step; `vmap` batches hosts.
- All times int32, relative to the window start; the two "None" sentinels
  of the scalar state (`interval_end`, `drop_next`) become explicit bool
  flags so rebasing across windows stays branch-free.
- The control law `now + INTERVAL/sqrt(count)` is served from a
  precomputed int32 table so device results match the CPU plane's
  float64 `round()` bitwise. Counts beyond the table (4096 consecutive
  drops — far above any sane queue) clamp to the last entry.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import simtime
from ..net.packet import CONFIG_MTU

TARGET = np.int32(10 * simtime.MILLISECOND)
INTERVAL = np.int32(100 * simtime.MILLISECOND)
I32_MAX = np.int32(2**31 - 1)

_MODE_STORE = np.int32(0)
_MODE_DROP = np.int32(1)

# control_law(t, c) = t + CTRL_TABLE[min(c, len)-1]; CTRL_TABLE[0] unused
# spare (count=0 never queried by the state machine, kept for safe indexing)
_MAX_COUNT = 4096
CTRL_TABLE = jnp.asarray(
    [round(float(INTERVAL))]
    + [round(float(INTERVAL) / float(np.sqrt(np.float64(c))))
       for c in range(1, _MAX_COUNT + 1)],
    jnp.int32,
)

# entry status codes produced by codel_drain
STATUS_QUEUED = 0  # not consumed this window (still in queue)
STATUS_DELIVERED = 1
STATUS_DROPPED = 2


class CodelState(NamedTuple):
    """Per-host scalar CoDel state, axis 0 = host."""

    mode: jax.Array  # int32: 0 store / 1 drop
    has_interval_end: jax.Array  # bool
    interval_end: jax.Array  # int32 rel ns (valid iff flag)
    has_drop_next: jax.Array  # bool
    drop_next: jax.Array  # int32 rel ns (valid iff flag)
    cur_count: jax.Array  # int32 current drop count
    prev_count: jax.Array  # int32 drop count at last store->drop switch
    entry_idx: jax.Array  # int32 entries consumed from the trace
    consumed_bytes: jax.Array  # int32 bytes consumed from the trace
    dropped: jax.Array  # int32 total drops (router-drop counter)


def make_codel_state(n_hosts: int) -> CodelState:
    z = lambda: jnp.zeros((n_hosts,), jnp.int32)
    f = lambda: jnp.zeros((n_hosts,), bool)
    return CodelState(
        mode=z(), has_interval_end=f(), interval_end=z(),
        has_drop_next=f(), drop_next=z(), cur_count=z(), prev_count=z(),
        entry_idx=z(), consumed_bytes=z(), dropped=z(),
    )


def rebase_codel_state(state: CodelState, shift_ns) -> CodelState:
    """Rebase the stored absolute-ish times when the window start moves."""
    shift = jnp.int32(shift_ns)
    return state._replace(
        interval_end=jnp.where(
            state.has_interval_end, state.interval_end - shift,
            state.interval_end,
        ),
        drop_next=jnp.where(
            state.has_drop_next, state.drop_next - shift, state.drop_next
        ),
    )


# phases of the linearized pop state machine (shared by both kernels)
_PH_START = 0  # at the top of pop(now)
_PH_AFTER_STORE_DROP = 1  # store-mode drop done; pop-and-return next
_PH_DROP_LOOP = 2  # inside drop-mode while; front entry just dropped


def _codel_pop_step(phase, mode, has_ie, ie, has_dn, dn, cur, prev,
                    now, empty, e_arr, e_size, total_after):
    """One micro-step of the CoDel pop state machine: the CPU
    `CoDelQueue.pop` nested drop loops, unrolled one
    queue-entry-or-empty-pop at a time. Pure function of the codel scalars
    and the front-entry view; shared by the trace-replay kernel
    (`_drain_one_host`) and the integrated router (`_route_one_host`) so
    the parity-critical logic exists exactly once.

    Returns (scalars', outcome) where scalars' =
    (mode, has_ie, ie, has_dn, dn, cur, prev, phase_mid) — phase_mid
    reflects drop transitions only; the CALLER resolves the final phase of
    a completed pop (trace replay restarts at _PH_START; the integrated
    router goes idle on empty or token-block) — and outcome =
    (consume, rec_status, pop_done, any_empty, deliver).
    """
    # --- _codel_pop(now): standing-delay check on the front entry -------
    standing = now - e_arr
    below = (standing < TARGET) | (total_after <= CONFIG_MTU)
    entered_bad = ~below & ~has_ie
    # ok_to_drop per _process_standing_delay
    ok = ~below & has_ie & (now >= ie)
    n_ie = jnp.where(below, ie, jnp.where(entered_bad, now + INTERVAL, ie))
    n_has_ie = jnp.where(below, False, True)

    # control law via table (count >= 1 always when queried)
    def ctrl(t, c):
        return t + CTRL_TABLE[jnp.clip(c, 1, _MAX_COUNT)]

    consume = jnp.bool_(False)
    rec_status = jnp.int32(STATUS_QUEUED)
    n_mode, n_has_dn, n_dn = mode, has_dn, dn
    n_cur, n_prev, n_phase = cur, prev, phase

    is_start = phase == _PH_START
    is_after_sd = phase == _PH_AFTER_STORE_DROP
    is_drop_loop = phase == _PH_DROP_LOOP

    # ---- _PH_START -----------------------------------------------------
    # empty queue: pop returns None; mode=store; interval_end=None
    c_empty = is_start & empty
    # not ok_to_drop: deliver; mode=store
    c_deliver = is_start & ~empty & ~ok
    # ok & store mode: drop entry, switch to drop mode (store-mode drop)
    c_store_drop = is_start & ~empty & ok & (mode == _MODE_STORE)
    # ok & drop mode: should_drop(now)?
    should = has_dn & (now >= dn)
    c_drop_again = is_start & ~empty & ok & (mode == _MODE_DROP) & should
    c_drop_deliver = is_start & ~empty & ok & (mode == _MODE_DROP) & ~should

    # ---- _PH_AFTER_STORE_DROP ------------------------------------------
    a_empty = is_after_sd & empty
    a_deliver = is_after_sd & ~empty  # delivered regardless of its ok flag

    # ---- _PH_DROP_LOOP --------------------------------------------------
    # front entry state machine: _codel_pop; if empty -> return None
    d_empty = is_drop_loop & empty
    # non-empty: if ok -> drop_next=ctrl(drop_next, cur) else mode=store;
    # then re-check the while condition with the NEW drop_next/mode
    d_nonempty = is_drop_loop & ~empty
    dn_upd = jnp.where(d_nonempty & ok, ctrl(dn, cur), dn)
    mode_upd = jnp.where(d_nonempty & ~ok, _MODE_STORE, mode)
    should2 = has_dn & (now >= dn_upd)
    d_drop = d_nonempty & ok & should2  # mode still drop, keep dropping
    d_deliver = d_nonempty & ~d_drop

    # ----- merge transitions --------------------------------------------
    # empty-queue outcomes (all phases): pop completes, nothing consumed.
    # CPU: _PH_START empty -> mode=store (pop()'s None branch); phases 1/2
    # leave mode alone; _codel_pop cleared interval_end in every case.
    any_empty = c_empty | a_empty | d_empty
    n_mode = jnp.where(c_empty, _MODE_STORE, n_mode)
    n_has_ie = jnp.where(any_empty, False, n_has_ie)

    # deliver outcomes
    deliver = c_deliver | a_deliver | c_drop_deliver | d_deliver
    consume = consume | deliver
    rec_status = jnp.where(deliver, STATUS_DELIVERED, rec_status)
    n_mode = jnp.where(c_deliver, _MODE_STORE, n_mode)
    n_mode = jnp.where(d_deliver, mode_upd, n_mode)
    n_dn = jnp.where(d_deliver, dn_upd, n_dn)

    # store-mode drop: drop entry now; count bookkeeping; enter phase 1
    consume = consume | c_store_drop
    rec_status = jnp.where(c_store_drop, STATUS_DROPPED, rec_status)
    recently = has_dn & ((jnp.maximum(0, now - dn)) < INTERVAL * 16)
    delta = cur - prev
    new_cur = jnp.where(recently & (delta > 1), delta, 1)
    n_cur = jnp.where(c_store_drop, new_cur, n_cur)
    n_prev = jnp.where(c_store_drop, new_cur, n_prev)
    n_dn = jnp.where(c_store_drop, ctrl(now, new_cur), n_dn)
    n_has_dn = jnp.where(c_store_drop, True, n_has_dn)
    n_mode = jnp.where(c_store_drop, _MODE_DROP, n_mode)
    n_phase = jnp.where(c_store_drop, _PH_AFTER_STORE_DROP, n_phase)

    # drop-mode drop (from _PH_START): drop entry, count++, enter loop
    consume = consume | c_drop_again
    rec_status = jnp.where(c_drop_again, STATUS_DROPPED, rec_status)
    n_cur = jnp.where(c_drop_again, cur + 1, n_cur)
    n_phase = jnp.where(c_drop_again, _PH_DROP_LOOP, n_phase)

    # drop-loop continued drop: entry dropped, count++, stay in loop
    consume = consume | d_drop
    rec_status = jnp.where(d_drop, STATUS_DROPPED, rec_status)
    n_cur = jnp.where(d_drop, cur + 1, n_cur)
    n_dn = jnp.where(d_drop, dn_upd, n_dn)

    pop_done = any_empty | deliver
    scalars = (n_mode, n_has_ie, n_ie, n_has_dn, n_dn, n_cur, n_prev,
               n_phase)
    return scalars, (consume, rec_status, pop_done, any_empty, deliver)


def _drain_one_host(arrival, size, pops, n_pops, st: CodelState):
    """Drain one host's queue through its pop trace.

    arrival [K] int32 ascending (I32_MAX padding), size [K] int32,
    pops [P] int32 ascending pop-invocation times, of which the first
    `n_pops` are real. `st` holds scalars for THIS host (already indexed).
    Returns (st', status [K], deliver_t [K]).
    """
    K = arrival.shape[0]
    P = pops.shape[0]
    pushed_bytes = jnp.cumsum(size * (arrival < I32_MAX))  # [K] prefix sums

    def micro_step(_, carry):
        (mode, has_ie, ie, has_dn, dn, cur, prev, eidx, cbytes, dropped,
         pidx, phase, status, deliver_t) = carry

        active = pidx < n_pops
        now = jnp.where(active, pops[jnp.minimum(pidx, P - 1)], 0)

        # queue contents at `now`: entries pushed (arrival <= now) and not
        # yet consumed. arrival is sorted so pushed count = searchsorted.
        n_pushed = jnp.searchsorted(arrival, now, side="right").astype(jnp.int32)
        empty = eidx >= n_pushed
        e = jnp.minimum(eidx, K - 1)  # front entry index (clamped for gather)
        # total_bytes AFTER removing this entry (the CPU code decrements
        # before _process_standing_delay reads it)
        total_after = pushed_bytes[jnp.minimum(n_pushed - 1, K - 1)] * (
            n_pushed > 0
        ) - cbytes - size[e]

        scalars, (consume, rec_status, pop_done, _any_empty, _deliver) = \
            _codel_pop_step(phase, mode, has_ie, ie, has_dn, dn, cur, prev,
                            now, empty, arrival[e], size[e], total_after)
        (n_mode, n_has_ie, n_ie, n_has_dn, n_dn, n_cur, n_prev,
         n_phase) = scalars
        # trace replay: completing any pop restarts at the next pop time
        n_phase = jnp.where(pop_done, _PH_START, n_phase)

        # gate everything on `active` (pops exhausted = this host is done)
        consume = consume & active
        pop_done = pop_done & active

        def sel(new, old):
            return jnp.where(active, new, old)

        status = status.at[e].set(
            jnp.where(consume, rec_status, status[e]), mode="drop"
        )
        deliver_t = deliver_t.at[e].set(
            jnp.where(consume & (rec_status == STATUS_DELIVERED), now,
                      deliver_t[e]),
            mode="drop",
        )
        return (
            sel(n_mode, mode), sel(n_has_ie, has_ie), sel(n_ie, ie),
            sel(n_has_dn, has_dn), sel(n_dn, dn), sel(n_cur, cur),
            sel(n_prev, prev),
            jnp.where(consume, eidx + 1, eidx),
            jnp.where(consume, cbytes + size[e], cbytes),
            jnp.where(consume & (rec_status == STATUS_DROPPED),
                      dropped + 1, dropped),
            jnp.where(pop_done, pidx + 1, pidx),
            sel(n_phase, phase),
            status, deliver_t,
        )

    status0 = jnp.zeros((K,), jnp.int32)
    deliver0 = jnp.full((K,), I32_MAX, jnp.int32)
    carry = (
        st.mode, st.has_interval_end, st.interval_end, st.has_drop_next,
        st.drop_next, st.cur_count, st.prev_count, st.entry_idx,
        st.consumed_bytes, st.dropped, jnp.int32(0), jnp.int32(_PH_START),
        status0, deliver0,
    )
    # bound: every micro-step consumes an entry or completes a pop
    carry = jax.lax.fori_loop(0, K + P, micro_step, carry)
    (mode, has_ie, ie, has_dn, dn, cur, prev, eidx, cbytes, dropped,
     _pidx, _phase, status, deliver_t) = carry
    st_out = CodelState(
        mode=mode, has_interval_end=has_ie, interval_end=ie,
        has_drop_next=has_dn, drop_next=dn, cur_count=cur, prev_count=prev,
        entry_idx=eidx, consumed_bytes=cbytes, dropped=dropped,
    )
    return st_out, status, deliver_t


# -- integrated router: CoDel + down-bandwidth relay ----------------------
#
# The window_step ingress pipeline (`host.rs:810-865`: router CoDel ->
# inet-in relay -> interface). Unlike `codel_drain`, pop times are DERIVED,
# not given: every arrival starts a pop chain at its arrival time (the CPU
# plane's route_incoming_packet -> relay.notify -> delay-0 task), the chain
# pops until the queue empties or the down-bandwidth token bucket runs dry,
# and a non-conforming packet is CACHED in the relay (already consumed from
# the CoDel queue, `relay/mod.rs` Forwarding->Idle with _next_packet) with a
# resume scheduled exactly at the refill boundary that affords it.

STATUS_TAKEN = 3  # consumed from the queue, cached in the relay at window end

_PH_IDLE = 3  # no active pop chain (extends the PH_* codes in _drain_one_host)


class RouterDownState(NamedTuple):
    """Per-host scalar state of the integrated router+relay, axis 0 = host."""

    # CoDel scalars (same meaning as CodelState)
    mode: jax.Array
    has_interval_end: jax.Array
    interval_end: jax.Array
    has_drop_next: jax.Array
    drop_next: jax.Array
    cur_count: jax.Array
    prev_count: jax.Array
    # down-bandwidth token bucket (`relay/token_bucket.rs`)
    dn_balance: jax.Array  # int32 token bytes
    dn_last_refill: jax.Array  # int32 rel ns of the last refill boundary
    # relay-cached packet (popped from CoDel, waiting for tokens)
    has_cached: jax.Array  # bool
    cached_src: jax.Array  # int32 identity carried across windows
    cached_seq: jax.Array
    cached_sock: jax.Array  # int32 payload tag (delivered["sock"])
    cached_bytes: jax.Array
    resume: jax.Array  # int32 rel ns the relay resumes (valid iff has_cached)
    dropped: jax.Array  # int32 cumulative router drops


def make_router_state(n_hosts: int,
                      dn_cap: jax.Array | None = None) -> RouterDownState:
    z = lambda: jnp.zeros((n_hosts,), jnp.int32)
    f = lambda: jnp.zeros((n_hosts,), bool)
    return RouterDownState(
        mode=z(), has_interval_end=f(), interval_end=z(),
        has_drop_next=f(), drop_next=z(), cur_count=z(), prev_count=z(),
        dn_balance=(jnp.asarray(dn_cap, jnp.int32) if dn_cap is not None
                    else z()),
        dn_last_refill=z(), has_cached=f(), cached_src=z(), cached_seq=z(),
        cached_sock=z(), cached_bytes=z(), resume=z(), dropped=z(),
    )


def rebase_router_state(st: RouterDownState, shift_ns, dn_rate,
                        dn_cap) -> RouterDownState:
    """Rebase stored times by the window shift AND re-anchor the token
    bucket: apply every refill boundary that has passed up to the new
    window start (elapsed clamped before multiplying, as everywhere).
    Without the re-anchoring, dn_last_refill only ever decreases and wraps
    int32 after ~2.1 s of inbound-idle sim time, corrupting all later
    bucket math for the host."""
    shift = jnp.int32(shift_ns)
    interval_ms = jnp.int32(simtime.MILLISECOND)
    lref = st.dn_last_refill - shift
    span = jnp.maximum(-lref, 0)  # ns from last refill to the new t=0
    num = span // interval_ms
    headroom = jnp.maximum(dn_cap - st.dn_balance, 0)
    need = (headroom + dn_rate - 1) // dn_rate
    # == min(balance + refund, cap) for refund >= 0 (min(u, c) is
    # c - max(c - u, 0)); the headroom form keeps every intermediate
    # interval-bounded even at the 2^30 - MTU rate clamp — the SL506
    # range proof closes it without the relational
    # "refund < headroom + rate" argument
    balance = dn_cap - jnp.maximum(
        headroom - dn_rate * jnp.minimum(num, need), 0
    )
    # re-anchor into (-1 ms, 0] (or keep a small positive value):
    # algebraically identical to `lref + num * interval_ms` (lref +
    # span == max(lref, 0) and num * interval_ms == span - span %
    # interval_ms), but every term is interval-bounded — the SL506
    # range proof (analysis/ranges.py `state.router.dn_last_refill`)
    # needs no relational reasoning to close it
    lref = jnp.maximum(lref, 0) - span % interval_ms
    return st._replace(
        interval_end=jnp.where(st.has_interval_end,
                               st.interval_end - shift, st.interval_end),
        drop_next=jnp.where(st.has_drop_next, st.drop_next - shift,
                            st.drop_next),
        dn_balance=balance,
        dn_last_refill=lref,
        resume=jnp.where(st.has_cached, st.resume - shift, st.resume),
    )


def _route_one_host(arrival, size, window_ns, dn_rate, dn_cap, st):
    """Run one host's router (CoDel + down relay) over one window.

    arrival [K] int32 ascending (I32_MAX padding), size [K]. `st` holds this
    host's scalars. Returns (scalars', status [K], deliver_t [K], co_mask,
    co_t, cached_idx) where co_* report the delivery of a packet cached in a
    PREVIOUS window (identity lives in the state scalars) and cached_idx >= 0
    names the row entry left cached at window end (-1: none, or the cached
    packet is the carried-over one)."""
    K = arrival.shape[0]
    interval_ms = jnp.int32(simtime.MILLISECOND)
    pushed_bytes = jnp.cumsum(size * (arrival < I32_MAX))
    n_valid = (arrival < I32_MAX).sum().astype(jnp.int32)

    PH_START = 0
    PH_AFTER_STORE_DROP = 1
    PH_DROP_LOOP = 2

    def refill(bal, lref, now):
        """Lazy 1ms refill, elapsed clamped BEFORE multiplying so the
        arithmetic stays inside int32 for any rate (cf. window_step's
        token-bucket refill)."""
        span = jnp.maximum(now - lref, 0)
        num = span // interval_ms
        headroom = jnp.maximum(dn_cap - bal, 0)
        need = (headroom + dn_rate - 1) // dn_rate
        # == min(bal + refund, cap); headroom form for the SL506 range
        # proof, like rebase_router_state
        bal2 = dn_cap - jnp.maximum(
            headroom - dn_rate * jnp.minimum(num, need), 0)
        # == lref + num * interval_ms (lref + span == max(now, lref));
        # the max form keeps the anchor interval-bounded by the window
        # horizon for the SL506 range proof (analysis/ranges.py)
        return bal2, jnp.maximum(now, lref) - span % interval_ms

    def micro_step(_, carry):
        (mode, has_ie, ie, has_dn, dn, cur, prev, bal, lref, has_c, c_size,
         c_idx, resume, dropped, eidx, cbytes, T, phase, halted, co_mask,
         co_t, status, deliver_t) = carry

        # ---- event selection while no pop chain is active ----------------
        idle = (phase == _PH_IDLE) & ~halted
        resume_ok = idle & has_c & (resume < window_ns)
        head_arr = arrival[jnp.minimum(eidx, K - 1)]
        head_ok = (idle & ~has_c & (eidx < n_valid)
                   & (head_arr < window_ns))
        halt_now = idle & ~resume_ok & ~head_ok
        halted = halted | halt_now

        def wait_until(now, required, lref_now):
            """Resume time of a token-blocked packet: the refill boundary
            that affords `required` more bytes. Saturates just below
            I32_MAX on int32 overflow; rebasing brings it down across
            windows and the resume-time conformance RE-CHECK below turns a
            too-early (saturated) firing into a recomputation instead of a
            premature delivery."""
            n_refills = (required + dn_rate - 1) // dn_rate
            w = (interval_ms - (now - lref_now)
                 + (n_refills - 1) * interval_ms)
            r = now + w
            return jnp.where(r < now, I32_MAX - interval_ms, r)

        # cached resume: refill + conformance re-check. A wait computed
        # exactly conforms at its boundary; a saturated one fires early,
        # fails the check, and re-blocks with the remaining wait.
        rT = resume
        r_bal, r_lref = refill(bal, lref, rT)
        r_conform = c_size <= r_bal
        r_fwd = resume_ok & r_conform
        r_again = resume_ok & ~r_conform
        bal = jnp.where(r_fwd, r_bal - c_size,
                        jnp.where(r_again, r_bal, bal))
        lref = jnp.where(resume_ok, r_lref, lref)
        row_cached = c_idx >= 0
        ci = jnp.minimum(jnp.maximum(c_idx, 0), K - 1)
        status = status.at[ci].set(
            jnp.where(r_fwd & row_cached, STATUS_DELIVERED, status[ci]),
            mode="drop")
        deliver_t = deliver_t.at[ci].set(
            jnp.where(r_fwd & row_cached, rT, deliver_t[ci]), mode="drop")
        co_mask = co_mask | (r_fwd & ~row_cached)
        co_t = jnp.where(r_fwd & ~row_cached, rT, co_t)
        has_c = jnp.where(r_fwd, False, has_c)
        c_idx = jnp.where(r_fwd, -1, c_idx)
        resume = jnp.where(r_again, wait_until(rT, c_size - r_bal, r_lref),
                           resume)
        T = jnp.where(r_fwd, rT, T)
        phase = jnp.where(r_fwd, _PH_START, phase)

        # idle chain start at the head entry's arrival (notify -> delay-0
        # relay task)
        T = jnp.where(head_ok, head_arr, T)
        phase = jnp.where(head_ok, _PH_START, phase)

        # ---- one CoDel pop micro-step at chain time T --------------------
        in_chain = ((phase != _PH_IDLE) & ~halted & ~resume_ok & ~head_ok)
        now = T
        n_pushed = jnp.searchsorted(arrival, now,
                                    side="right").astype(jnp.int32)
        empty = eidx >= n_pushed
        e = jnp.minimum(eidx, K - 1)
        e_size = size[e]
        total_after = pushed_bytes[jnp.minimum(n_pushed - 1, K - 1)] * (
            n_pushed > 0
        ) - cbytes - e_size

        scalars, (consume, rec_status, _pop_done, any_empty, deliver) = \
            _codel_pop_step(phase, mode, has_ie, ie, has_dn, dn, cur, prev,
                            now, empty, arrival[e], e_size, total_after)
        (n_mode, n_has_ie, n_ie, n_has_dn, n_dn, n_cur, n_prev,
         n_phase) = scalars

        # deliver candidate -> relay token gate (the one divergence from
        # the trace-replay kernel: a candidate the bucket can't afford is
        # TAKEN into the relay cache instead of delivered)
        g_bal, g_lref = refill(bal, lref, now)
        conform = e_size <= g_bal
        fwd = deliver & conform
        blocked = deliver & ~conform
        rec_status = jnp.where(blocked, STATUS_TAKEN, rec_status)
        bal = jnp.where(in_chain & deliver,
                        jnp.where(conform, g_bal - e_size, g_bal), bal)
        lref = jnp.where(in_chain & deliver, g_lref, lref)
        has_c = jnp.where(in_chain & blocked, True, has_c)
        c_size = jnp.where(in_chain & blocked, e_size, c_size)
        c_idx = jnp.where(in_chain & blocked, e, c_idx)
        resume = jnp.where(in_chain & blocked,
                           wait_until(now, e_size - g_bal, g_lref), resume)

        # chain control: empty queue or token block idles the relay; a
        # forwarded pop restarts the chain at the same instant
        n_phase = jnp.where(any_empty | blocked, _PH_IDLE, n_phase)
        n_phase = jnp.where(fwd, _PH_START, n_phase)

        gate = in_chain
        status = status.at[e].set(
            jnp.where(gate & consume, rec_status, status[e]), mode="drop")
        deliver_t = deliver_t.at[e].set(
            jnp.where(gate & consume & (rec_status == STATUS_DELIVERED), now,
                      deliver_t[e]), mode="drop")

        def sel(new, old):
            return jnp.where(gate, new, old)

        return (
            sel(n_mode, mode), sel(n_has_ie, has_ie), sel(n_ie, ie),
            sel(n_has_dn, has_dn), sel(n_dn, dn), sel(n_cur, cur),
            sel(n_prev, prev), bal, lref, has_c, c_size, c_idx, resume,
            jnp.where(gate & consume & (rec_status == STATUS_DROPPED),
                      dropped + 1, dropped),
            jnp.where(gate & consume, eidx + 1, eidx),
            jnp.where(gate & consume, cbytes + e_size, cbytes),
            T, sel(n_phase, phase), halted, co_mask, co_t, status, deliver_t,
        )

    status0 = jnp.zeros((K,), jnp.int32)
    deliver0 = jnp.full((K,), I32_MAX, jnp.int32)
    carry = (
        st.mode, st.has_interval_end, st.interval_end, st.has_drop_next,
        st.drop_next, st.cur_count, st.prev_count, st.dn_balance,
        st.dn_last_refill, st.has_cached, st.cached_bytes, jnp.int32(-1),
        st.resume, st.dropped, jnp.int32(0), jnp.int32(0), jnp.int32(0),
        jnp.int32(_PH_IDLE), jnp.bool_(False), jnp.bool_(False),
        jnp.int32(0), status0, deliver0,
    )
    # bound: every micro-step consumes an entry, completes an empty pop,
    # delivers (or re-blocks) a cached packet, starts a chain, or halts
    carry = jax.lax.fori_loop(0, 4 * K + 16, micro_step, carry)
    (mode, has_ie, ie, has_dn, dn, cur, prev, bal, lref, has_c, c_size,
     c_idx, resume, dropped, _eidx, _cbytes, _T, _phase, _halted, co_mask,
     co_t, status, deliver_t) = carry
    st_out = RouterDownState(
        mode=mode, has_interval_end=has_ie, interval_end=ie,
        has_drop_next=has_dn, drop_next=dn, cur_count=cur, prev_count=prev,
        dn_balance=bal, dn_last_refill=lref, has_cached=has_c,
        cached_src=st.cached_src, cached_seq=st.cached_seq,
        cached_sock=st.cached_sock, cached_bytes=c_size, resume=resume,
        dropped=dropped,
    )
    return st_out, status, deliver_t, co_mask, co_t, c_idx


def router_drain(arrival: jax.Array, size: jax.Array, window_ns,
                 dn_rate: jax.Array, dn_cap: jax.Array,
                 state: RouterDownState):
    """Vmapped integrated router step: per-host CoDel + down-bw relay.

    arrival/size: [N, K], arrival ascending per row with I32_MAX padding.
    Returns (state', status [N, K], deliver_t [N, K], co_mask [N],
    co_t [N], cached_idx [N]). The caller owns identity bookkeeping:
    cached_idx >= 0 means row entry cached at window end (gather its
    src/seq into the state scalars); co_mask means the PREVIOUS window's
    cached packet (identity in the pre-step state scalars) was delivered
    at co_t."""
    return jax.vmap(
        _route_one_host, in_axes=(0, 0, None, 0, 0, 0)
    )(arrival, size, jnp.int32(window_ns), dn_rate, dn_cap, state)


def codel_drain(arrival: jax.Array, size: jax.Array, pops: jax.Array,
                state: CodelState):
    """Replay pop invocations against per-host entry traces.

    arrival/size: [N, K] entries per host, arrival ascending with I32_MAX
    padding; pops: [N, P] pop times ascending with I32_MAX padding (a
    padded pop is ignored). state: per-host CodelState ([N] arrays).
    Returns (state', status [N, K], deliver_t [N, K]) where status uses
    STATUS_QUEUED / STATUS_DELIVERED / STATUS_DROPPED and deliver_t is the
    pop time for delivered entries (I32_MAX otherwise).
    """
    # padded pops (I32_MAX) are inert: the per-host machine stops once its
    # real pop count is exhausted
    n_real_pops = (pops < I32_MAX).sum(axis=1).astype(jnp.int32)
    return jax.vmap(_drain_one_host, in_axes=(0, 0, 0, 0, 0))(
        arrival, size, pops, n_real_pops, state
    )
