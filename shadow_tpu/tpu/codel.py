"""Batched CoDel AQM: the per-host inbound router queue as a device kernel.

Parity: reference `src/main/network/router/codel_queue.rs:23-33` (RFC 8289
with Shadow's TARGET = 10ms, INTERVAL = 100ms, unbounded limit) — the same
state machine as the CPU plane's `shadow_tpu.net.router.CoDelQueue`, which
this kernel must match drop-for-drop on any trace (tests/test_tpu_codel.py
replays random traces through both).

Design (TPU-first):
- One window's drain is a bounded `lax.fori_loop` of "micro-steps", each of
  which consumes at most one queue entry or completes one empty pop — the
  CPU implementation's nested pop loops linearized so every host advances
  in lock-step; `vmap` batches hosts.
- All times int32, relative to the window start; the two "None" sentinels
  of the scalar state (`interval_end`, `drop_next`) become explicit bool
  flags so rebasing across windows stays branch-free.
- The control law `now + INTERVAL/sqrt(count)` is served from a
  precomputed int32 table so device results match the CPU plane's
  float64 `round()` bitwise. Counts beyond the table (4096 consecutive
  drops — far above any sane queue) clamp to the last entry.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import simtime
from ..net.packet import CONFIG_MTU

TARGET = np.int32(10 * simtime.MILLISECOND)
INTERVAL = np.int32(100 * simtime.MILLISECOND)
I32_MAX = np.int32(2**31 - 1)

_MODE_STORE = np.int32(0)
_MODE_DROP = np.int32(1)

# control_law(t, c) = t + CTRL_TABLE[min(c, len)-1]; CTRL_TABLE[0] unused
# spare (count=0 never queried by the state machine, kept for safe indexing)
_MAX_COUNT = 4096
CTRL_TABLE = jnp.asarray(
    [round(float(INTERVAL))]
    + [round(float(INTERVAL) / float(np.sqrt(np.float64(c))))
       for c in range(1, _MAX_COUNT + 1)],
    jnp.int32,
)

# entry status codes produced by codel_drain
STATUS_QUEUED = 0  # not consumed this window (still in queue)
STATUS_DELIVERED = 1
STATUS_DROPPED = 2


class CodelState(NamedTuple):
    """Per-host scalar CoDel state, axis 0 = host."""

    mode: jax.Array  # int32: 0 store / 1 drop
    has_interval_end: jax.Array  # bool
    interval_end: jax.Array  # int32 rel ns (valid iff flag)
    has_drop_next: jax.Array  # bool
    drop_next: jax.Array  # int32 rel ns (valid iff flag)
    cur_count: jax.Array  # int32 current drop count
    prev_count: jax.Array  # int32 drop count at last store->drop switch
    entry_idx: jax.Array  # int32 entries consumed from the trace
    consumed_bytes: jax.Array  # int32 bytes consumed from the trace
    dropped: jax.Array  # int32 total drops (router-drop counter)


def make_codel_state(n_hosts: int) -> CodelState:
    z = lambda: jnp.zeros((n_hosts,), jnp.int32)
    f = lambda: jnp.zeros((n_hosts,), bool)
    return CodelState(
        mode=z(), has_interval_end=f(), interval_end=z(),
        has_drop_next=f(), drop_next=z(), cur_count=z(), prev_count=z(),
        entry_idx=z(), consumed_bytes=z(), dropped=z(),
    )


def rebase_codel_state(state: CodelState, shift_ns) -> CodelState:
    """Rebase the stored absolute-ish times when the window start moves."""
    shift = jnp.int32(shift_ns)
    return state._replace(
        interval_end=jnp.where(
            state.has_interval_end, state.interval_end - shift,
            state.interval_end,
        ),
        drop_next=jnp.where(
            state.has_drop_next, state.drop_next - shift, state.drop_next
        ),
    )


def _drain_one_host(arrival, size, pops, n_pops, st: CodelState):
    """Drain one host's queue through its pop trace.

    arrival [K] int32 ascending (I32_MAX padding), size [K] int32,
    pops [P] int32 ascending pop-invocation times, of which the first
    `n_pops` are real. `st` holds scalars for THIS host (already indexed).
    Returns (st', status [K], deliver_t [K]).
    """
    K = arrival.shape[0]
    P = pops.shape[0]
    pushed_bytes = jnp.cumsum(size * (arrival < I32_MAX))  # [K] prefix sums

    # phases of the linearized pop state machine
    PH_START = 0  # at the top of pop(now)
    PH_AFTER_STORE_DROP = 1  # store-mode drop done; pop-and-return next
    PH_DROP_LOOP = 2  # inside drop-mode while; front entry just dropped

    def micro_step(_, carry):
        (mode, has_ie, ie, has_dn, dn, cur, prev, eidx, cbytes, dropped,
         pidx, phase, status, deliver_t) = carry

        active = pidx < n_pops
        now = jnp.where(active, pops[jnp.minimum(pidx, P - 1)], 0)

        # queue contents at `now`: entries pushed (arrival <= now) and not
        # yet consumed. arrival is sorted so pushed count = searchsorted.
        n_pushed = jnp.searchsorted(arrival, now, side="right").astype(jnp.int32)
        empty = eidx >= n_pushed
        e = jnp.minimum(eidx, K - 1)  # front entry index (clamped for gather)
        e_arr = arrival[e]
        e_size = size[e]

        # --- _codel_pop(now): consume front entry, standing-delay check ---
        # total_bytes AFTER removing this entry (the CPU code decrements
        # before _process_standing_delay reads it)
        total_after = pushed_bytes[jnp.minimum(n_pushed - 1, K - 1)] * (
            n_pushed > 0
        ) - cbytes - e_size
        standing = now - e_arr
        below = (standing < TARGET) | (total_after <= CONFIG_MTU)
        entered_bad = ~below & ~has_ie
        # ok_to_drop per _process_standing_delay
        ok = ~below & has_ie & (now >= ie)
        ie_new = jnp.where(below, ie, jnp.where(entered_bad, now + INTERVAL, ie))
        has_ie_new = jnp.where(below, False, True)

        # helper: control law via table (count >= 1 always when queried)
        def ctrl(t, c):
            return t + CTRL_TABLE[jnp.clip(c, 1, _MAX_COUNT)]

        # ----- dispatch on phase -----------------------------------------
        # Defaults: no entry consumed, nothing recorded, pop not finished.
        consume = jnp.bool_(False)
        rec_status = jnp.int32(STATUS_QUEUED)
        pop_done = jnp.bool_(False)
        n_mode, n_has_ie, n_ie = mode, has_ie_new, ie_new
        n_has_dn, n_dn, n_cur, n_prev = has_dn, dn, cur, prev
        n_phase = phase

        is_start = phase == PH_START
        is_after_sd = phase == PH_AFTER_STORE_DROP
        is_drop_loop = phase == PH_DROP_LOOP

        # ---- PH_START -----------------------------------------------------
        # empty queue: pop returns None; mode=store; interval_end=None
        c_empty = is_start & empty
        # (CPU _codel_pop clears interval_end when empty)
        # not ok_to_drop: deliver; mode=store
        c_deliver = is_start & ~empty & ~ok
        # ok & store mode: drop entry, switch to drop mode (store-mode drop)
        c_store_drop = is_start & ~empty & ok & (mode == _MODE_STORE)
        # ok & drop mode: should_drop(now)?
        should = has_dn & (now >= dn)
        c_drop_again = is_start & ~empty & ok & (mode == _MODE_DROP) & should
        c_drop_deliver = is_start & ~empty & ok & (mode == _MODE_DROP) & ~should

        # ---- PH_AFTER_STORE_DROP -------------------------------------------
        a_empty = is_after_sd & empty
        a_deliver = is_after_sd & ~empty  # delivered regardless of its ok flag

        # ---- PH_DROP_LOOP ---------------------------------------------------
        # front entry state machine: _codel_pop; if empty → return None
        d_empty = is_drop_loop & empty
        # non-empty: if ok → drop_next=ctrl(drop_next, cur) else mode=store;
        # then re-check while condition with the NEW drop_next/mode
        d_nonempty = is_drop_loop & ~empty
        dn_upd = jnp.where(d_nonempty & ok, ctrl(dn, cur), dn)
        mode_upd = jnp.where(d_nonempty & ~ok, _MODE_STORE, mode)
        should2 = has_dn & (now >= dn_upd)
        d_drop = d_nonempty & ok & should2  # mode still drop, keep dropping
        d_deliver = d_nonempty & ~d_drop

        # ----- merge transitions ------------------------------------------
        # empty-queue outcomes (all phases): pop completes, nothing consumed
        any_empty = c_empty | a_empty | d_empty
        pop_done = pop_done | any_empty
        # CPU: PH_START empty → mode=store (pop()'s None branch). Phase 1 /
        # phase 2 empty: _codel_pop cleared interval_end; mode untouched in
        # phase 2; phase 1 returns None from _drop_from_store_mode (mode
        # was already set to DROP before the nested pop)
        n_mode = jnp.where(c_empty, _MODE_STORE, n_mode)
        n_has_ie = jnp.where(any_empty, False, n_has_ie)

        # deliver outcomes
        deliver = c_deliver | a_deliver | c_drop_deliver | d_deliver
        consume = consume | deliver
        rec_status = jnp.where(deliver, STATUS_DELIVERED, rec_status)
        pop_done = pop_done | deliver
        n_mode = jnp.where(c_deliver, _MODE_STORE, n_mode)
        n_mode = jnp.where(d_deliver, mode_upd, n_mode)
        n_dn = jnp.where(d_deliver, dn_upd, n_dn)

        # store-mode drop: drop entry now; count bookkeeping; enter phase 1
        consume = consume | c_store_drop
        rec_status = jnp.where(c_store_drop, STATUS_DROPPED, rec_status)
        recently = has_dn & ((jnp.maximum(0, now - dn)) < INTERVAL * 16)
        delta = cur - prev
        new_cur = jnp.where(recently & (delta > 1), delta, 1)
        n_cur = jnp.where(c_store_drop, new_cur, n_cur)
        n_prev = jnp.where(c_store_drop, new_cur, n_prev)
        n_dn = jnp.where(c_store_drop, ctrl(now, new_cur), n_dn)
        n_has_dn = jnp.where(c_store_drop, True, n_has_dn)
        n_mode = jnp.where(c_store_drop, _MODE_DROP, n_mode)
        n_phase = jnp.where(c_store_drop, PH_AFTER_STORE_DROP, n_phase)

        # drop-mode drop (from PH_START): drop entry, count++, enter loop
        consume = consume | c_drop_again
        rec_status = jnp.where(c_drop_again, STATUS_DROPPED, rec_status)
        n_cur = jnp.where(c_drop_again, cur + 1, n_cur)
        n_phase = jnp.where(c_drop_again, PH_DROP_LOOP, n_phase)

        # drop-loop continued drop: entry dropped, count++, stay in loop
        consume = consume | d_drop
        rec_status = jnp.where(d_drop, STATUS_DROPPED, rec_status)
        n_cur = jnp.where(d_drop, cur + 1, n_cur)
        n_dn = jnp.where(d_drop, dn_upd, n_dn)

        # completing any pop resets the phase
        n_phase = jnp.where(pop_done, PH_START, n_phase)

        # gate everything on `active` (pops exhausted = this host is done)
        consume = consume & active
        pop_done = pop_done & active

        def sel(new, old):
            return jnp.where(active, new, old)

        status = status.at[e].set(
            jnp.where(consume, rec_status, status[e]), mode="drop"
        )
        deliver_t = deliver_t.at[e].set(
            jnp.where(consume & (rec_status == STATUS_DELIVERED), now,
                      deliver_t[e]),
            mode="drop",
        )
        return (
            sel(n_mode, mode), sel(n_has_ie, has_ie), sel(n_ie, ie),
            sel(n_has_dn, has_dn), sel(n_dn, dn), sel(n_cur, cur),
            sel(n_prev, prev),
            jnp.where(consume, eidx + 1, eidx),
            jnp.where(consume, cbytes + e_size, cbytes),
            jnp.where(consume & (rec_status == STATUS_DROPPED),
                      dropped + 1, dropped),
            jnp.where(pop_done, pidx + 1, pidx),
            sel(n_phase, phase),
            status, deliver_t,
        )

    status0 = jnp.zeros((K,), jnp.int32)
    deliver0 = jnp.full((K,), I32_MAX, jnp.int32)
    carry = (
        st.mode, st.has_interval_end, st.interval_end, st.has_drop_next,
        st.drop_next, st.cur_count, st.prev_count, st.entry_idx,
        st.consumed_bytes, st.dropped, jnp.int32(0), jnp.int32(PH_START),
        status0, deliver0,
    )
    # bound: every micro-step consumes an entry or completes a pop
    carry = jax.lax.fori_loop(0, K + P, micro_step, carry)
    (mode, has_ie, ie, has_dn, dn, cur, prev, eidx, cbytes, dropped,
     _pidx, _phase, status, deliver_t) = carry
    st_out = CodelState(
        mode=mode, has_interval_end=has_ie, interval_end=ie,
        has_drop_next=has_dn, drop_next=dn, cur_count=cur, prev_count=prev,
        entry_idx=eidx, consumed_bytes=cbytes, dropped=dropped,
    )
    return st_out, status, deliver_t


def codel_drain(arrival: jax.Array, size: jax.Array, pops: jax.Array,
                state: CodelState):
    """Replay pop invocations against per-host entry traces.

    arrival/size: [N, K] entries per host, arrival ascending with I32_MAX
    padding; pops: [N, P] pop times ascending with I32_MAX padding (a
    padded pop is ignored). state: per-host CodelState ([N] arrays).
    Returns (state', status [N, K], deliver_t [N, K]) where status uses
    STATUS_QUEUED / STATUS_DELIVERED / STATUS_DROPPED and deliver_t is the
    pop time for delivered entries (I32_MAX otherwise).
    """
    # padded pops (I32_MAX) are inert: the per-host machine stops once its
    # real pop count is exhausted
    n_real_pops = (pops < I32_MAX).sum(axis=1).astype(jnp.int32)
    return jax.vmap(_drain_one_host, in_axes=(0, 0, 0, 0, 0))(
        arrival, size, pops, n_real_pops, state
    )
