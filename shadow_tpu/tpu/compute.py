"""The device compute plane: per-host service occupancy (`compute_step`).

The network planes answer "when does the packet arrive"; this module
answers the other half of the serving question — "when has the host
actually *processed* it". Each host is modeled as a single FIFO
service station in the SCALE-Sim/DCSim tradition (PAPERS.md: arxiv
2603.22535 supplies validated per-op TPU timings, arxiv 2411.13809 the
integrated compute+network host model): a busy-until clock, a bounded
FIFO queue with a depth counter, and a per-request service cost
``svc_ns`` drawn from the checked-in op-timing table
(`workloads/op_timings.json`) lowered at compile time into the traffic
program's per-(host, phase) ``compute_service_ns`` table.

Like every presence plane (docs/observability.md, docs/robustness.md),
the compute plane is a static compile-out switch on `window_step`
(``compute=None`` removes the section entirely; pallas kernels refuse
it like faults/guards/flows) and is **bitwise-invisible to the
simulation state**: `compute_step` reads the delivered dict the step
already materialized and writes ONLY its own `ComputeState` — the
SL501 full-invisibility obligation ``window_step[compute]``
(analysis/proofs.py) proves no compute taint can reach the lead
outputs. The *coupling* — "a phase completes only when network
delivery AND host service time are both done" — lives in the scenario
runner's credit path (`gate_credits`), never inside the step.

Determinism + dtype discipline (docs/determinism.md):

- everything is int32 with I32_MAX-free closed-form arithmetic; the
  spec compiler bounds ``svc_ns * (ingress_cap + queue_cap + 1)``
  inside the int32 quarter-budget so no completion time can overflow;
- the FIFO is solved in closed form per window, no per-request scan:
  with constant per-host service cost ``s`` inside a window,
  completions obey ``c_j = max(c_{j-1}, a_j) + s``, and substituting
  ``d_j = c_j - s*j`` turns the recurrence into a running cummax —
  one `lax.cummax` over the delivered row (already in deterministic
  (deliver_t, src, seq) order, front-packed ascending);
- arrivals the bounded queue cannot hold are REFUSED from the tail of
  the window (the latest arrivals are exactly the ones still
  incomplete at window end, so trimming the suffix keeps the closed
  form exact): refused requests never complete, never credit a phase,
  and count in ``n_overflow`` — load shedding, not a silent clamp;
- queueing delay and request sojourn accumulate into the same
  log2-bucket histograms the PR-10 latency plane uses
  (`telemetry/histo.py`), kept INSIDE `ComputeState` so the existing
  `PlaneHistograms` record keys (and every golden byte) are untouched.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import histo
from .plane import I32_MAX


class ComputeTables(NamedTuple):
    """The lowered service-cost tables (read-only on device).

    ``service_ns[h, p]`` is host h's per-request service cost while in
    phase p — nonzero only for dep-bearing phases (a phase that waits
    on deliveries services them; emission-only phases cost nothing),
    lowered by `workloads/serve.lower_service_table` from the
    checked-in op-timing table. ``queue_cap`` is the static bound on
    requests still owed service at a window boundary."""

    service_ns: jnp.ndarray  # [N, P] int32
    queue_cap: int  # static


class ComputeState(NamedTuple):
    """Mutable per-host service-station state, axis 0 = host.

    Clocks are window-relative like the net plane's (`busy_rel` is the
    backlog-end instant relative to the current window start, rebased
    by ``shift_ns`` each step). Counters are modular int32 like every
    telemetry counter; the [N, B] histograms follow the
    `telemetry/histo.py` bucket scheme."""

    busy_rel: jnp.ndarray  # [N] int32 backlog end, rel to window start
    svc_ns: jnp.ndarray  # [N] int32 current phase's service cost
    q_depth: jnp.ndarray  # [N] int32 admitted-not-complete at window end
    served_win: jnp.ndarray  # [N] int32 completions within last window
    n_served: jnp.ndarray  # [N] int32 cumulative completions
    n_queued: jnp.ndarray  # [N] int32 cumulative arrivals that waited
    n_overflow: jnp.ndarray  # [N] int32 cumulative refused (queue full)
    n_credit_raw: jnp.ndarray  # [N] int32 raw credits offered (gate)
    n_granted: jnp.ndarray  # [N] int32 credits granted (gate)
    hist_wait_ns: jnp.ndarray  # [N, B] int32 queueing delay
    hist_sojourn_ns: jnp.ndarray  # [N, B] int32 wait + service


def make_compute_tables(service_ns, queue_cap: int) -> ComputeTables:
    """Upload the [N, P] service table (copies, like
    `workloads/device.to_device`). ``queue_cap`` must be >= 1: a
    zero-capacity queue would refuse every arrival that cannot start
    inside its own window, which is a config error, not a model."""
    if queue_cap < 1:
        raise ValueError(
            f"compute queue_cap={queue_cap} must be >= 1 (a bounded "
            "FIFO needs at least one waiting slot)")
    return ComputeTables(
        service_ns=jnp.array(np.asarray(service_ns), jnp.int32),
        queue_cap=int(queue_cap))


def make_compute_state(ct: ComputeTables) -> ComputeState:
    """Zeroed state; ``svc_ns`` pre-armed from phase 0's costs (hosts
    start IN phase 0, `workloads/device.make_workload_state`)."""
    n = ct.service_ns.shape[0]
    z = lambda: jnp.zeros((n,), jnp.int32)
    zb = lambda: jnp.zeros((n, histo.HIST_BUCKETS), jnp.int32)
    return ComputeState(
        busy_rel=z(), svc_ns=ct.service_ns[:, 0], q_depth=z(),
        served_win=z(), n_served=z(), n_queued=z(), n_overflow=z(),
        n_credit_raw=z(), n_granted=z(),
        hist_wait_ns=zb(), hist_sojourn_ns=zb())


def _ceil_div(x, y):
    """ceil(x / y) for non-negative int32 x, guarded for y == 0 (a
    zero-cost host has no backlog by construction)."""
    return jnp.where(y > 0, (x + jnp.maximum(y, 1) - 1)
                     // jnp.maximum(y, 1), 0)


def compute_step(ct: ComputeTables, cs: ComputeState, delivered,
                 shift_ns, window_ns) -> ComputeState:
    """Service one window's deliveries through each host's FIFO.

    `delivered` is `window_step`'s released dict for THIS window
    (front-packed per host in ascending (deliver_t, src, seq) order —
    the FIFO arrival order). Pure reads of the dict; writes only the
    returned `ComputeState`. Semantics per window:

    1. rebase the backlog clock by ``shift_ns`` (like every stored
       relative time);
    2. closed-form FIFO: completion ``c_j = s*(j+1) + max(busy,
       cummax_j(a_j - s*j))`` over the row's arrivals;
    3. bounded queue: if more than ``queue_cap`` admitted requests
       would still be incomplete at window end, the LAST excess
       arrivals of the window are refused (counted in ``n_overflow``,
       their service cancelled — they are exactly the tail of the
       completion order, so earlier completions are untouched);
    4. ``served_win`` = carried-backlog completions falling in this
       window + this window's arrivals completing in it — the count
       `gate_credits` meters phase credits against;
    5. queueing delay (service start - arrival) and sojourn
       (completion - arrival) of every ADMITTED arrival accumulate
       into the log2 histograms at admission (completion is already
       determined — the FIFO is deterministic).
    """
    mask = delivered["mask"]
    s = cs.svc_ns
    sN = s[:, None]
    cap = jnp.int32(ct.queue_cap)
    win = jnp.int32(window_ns)
    busy0 = jnp.maximum(cs.busy_rel - jnp.int32(shift_ns), 0)

    # -- carried backlog: the q_depth requests admitted earlier finish
    # at busy0, busy0 - s, ... (the last q service slots); those past
    # window end remain, the rest complete this window
    backlog = jnp.maximum(busy0 - win, 0)
    carried_rem = jnp.minimum(cs.q_depth, _ceil_div(backlog, s))
    carried_done = cs.q_depth - carried_rem

    # -- closed-form FIFO over this window's arrivals ------------------
    a = jnp.where(mask, delivered["deliver_rel"], 0)
    k = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1  # service rank
    base = jnp.where(mask, a - sN * k, -I32_MAX)
    d = jnp.maximum(busy0[:, None], jax.lax.cummax(base, axis=1))
    c = d + sN * (k + 1)  # completion time (valid where mask)

    # -- bounded queue: refuse the tail the depth bound cannot hold ----
    n_arr = mask.sum(axis=1, dtype=jnp.int32)
    incomplete = mask & (c > win)
    depth_all = carried_rem + incomplete.sum(axis=1, dtype=jnp.int32)
    over = jnp.maximum(depth_all - cap, 0)
    kept = mask & (k < (n_arr - over)[:, None])
    done_now = (kept & (c <= win)).sum(axis=1, dtype=jnp.int32)
    busy_end = jnp.maximum(
        busy0, jnp.max(jnp.where(kept, c, -I32_MAX), axis=1))

    wait = jnp.where(kept, c - sN - a, 0)
    sojourn = jnp.where(kept, c - a, 0)

    return cs._replace(
        busy_rel=busy_end,
        q_depth=depth_all - over,
        served_win=carried_done + done_now,
        n_served=cs.n_served + carried_done + done_now,
        n_queued=cs.n_queued
        + (kept & (wait > 0)).sum(axis=1, dtype=jnp.int32),
        n_overflow=cs.n_overflow + over,
        hist_wait_ns=histo.accum_rows(
            cs.hist_wait_ns, histo.bucket_index(wait), kept),
        hist_sojourn_ns=histo.accum_rows(
            cs.hist_sojourn_ns, histo.bucket_index(sojourn), kept))


def phase_service(ct: ComputeTables, cs: ComputeState,
                  phase) -> ComputeState:
    """Re-arm each host's per-request cost from its CURRENT phase's
    table entry (the runner calls this after `workload_step` advances
    the phase machine — `window_step` itself never sees phases)."""
    P = ct.service_ns.shape[1]
    idx = jnp.clip(phase, 0, P - 1)[:, None]
    return cs._replace(
        svc_ns=jnp.take_along_axis(ct.service_ns, idx, axis=1)[:, 0])


def gate_credits(cs: ComputeState, raw_credits):
    """Meter phase credits through service completion: the k-th credit
    is granted only when BOTH the k-th network credit (raw delivery
    count on the direct transport, ACKED in-order segment under
    ``transport: flows``) AND the k-th service completion have
    happened — ``granted = min(cum_raw, cum_served)``, delta'd against
    what was already granted. Hosts with ``svc_ns == 0`` serve
    instantly (``cum_served`` tracks raw arrivals), so the gate passes
    their credits through bitwise-unchanged. Returns (cs', got)."""
    cum_raw = cs.n_credit_raw + raw_credits
    granted = jnp.minimum(cum_raw, cs.n_served)
    got = granted - cs.n_granted
    return cs._replace(n_credit_raw=cum_raw, n_granted=granted), got
