"""Elastic ring growth for the device plane: the bitwise repack kernels.

The device half of the capacity policy plane (`core/capacity.py`,
docs/robustness.md "Elastic capacity"): pure, donation-friendly repack
functions that migrate a SoA world into larger power-of-two rings —
every live ring column, every I32_MAX/NO_CLAMP idle sentinel, every
counter moves bitwise; the new trailing columns carry exactly the
`make_state` defaults. Growth is therefore invisible to the step
kernels (docs/determinism.md "Growth is bitwise-invisible"):

- live lanes are front-packed, so they occupy the same columns before
  and after a grow;
- every sort in `window_step` is stable with invalid-last keys, so the
  extra all-invalid columns sort behind the live lanes and never
  change their order;
- every consumer masks by validity, so the dead-lane payload
  ("compaction garbage", `plane._routing_place`) can never feed back
  into live state.

The one thing growth does NOT preserve is that garbage itself: a run
grown mid-flight carries different dead-lane payload than a run
pre-provisioned at the final capacity (each permuted its own history's
garbage). `canonical_state` normalizes those don't-care lanes to the
`make_state` defaults so two such runs compare bitwise — the contract
the elastic parity matrix in tests/test_elastic.py pins is
``canonical_state(elastic) == canonical_state(pre-provisioned)`` plus
identical delivered streams, counters, RNG, and metrics.

`drive_chained_windows` is THE driver loop (bench.py,
tools/chaos_smoke.py, workloads/runner.py — pinned by the
inspect-source gate in tests/test_chain_driver.py): K consecutive
windows execute device-resident per dispatch, the host syncs only at
chain ends, and under the capacity policy the chain is the
growth-decision unit — `run_elastic_window` underneath attempts the
chain, reads the per-ring overflow it reported, and grows + re-executes
from the chain-start snapshot (`jax.jit` retraces per ring shape, so
recompiles are bounded at log2 by the power-of-two growth; the PR-1
recompile harness asserts it).

jax imports are lazy (function-local) so `core/` consumers of the
re-exported :class:`CapacityError` never pull the device stack.
"""

from __future__ import annotations

import numpy as np

from ..core.capacity import (CAPACITY_MODES, CapacityError,  # noqa: F401
                             CapacityTrajectory, RingPolicy, next_pow2)

__all__ = [
    "CAPACITY_MODES", "CapacityError", "CapacityTrajectory", "RingPolicy",
    "canonical_state", "chain_spans", "drive_chained_windows",
    "drive_ensemble", "grow_state", "grow_transport_state", "next_pow2",
    "ring_dims", "run_elastic_window", "world_key", "world_keys",
]


def ring_dims(state) -> tuple[int, int]:
    """(egress_cap, ingress_cap) of a `plane.NetPlaneState`."""
    return int(state.eg_dst.shape[1]), int(state.in_src.shape[1])


def _pad_cols(arr, width: int, fill):
    """Widen a [N, C] ring to [N, width] with `fill` in the new lanes."""
    import jax.numpy as jnp

    n, c = arr.shape
    if width == c:
        return arr
    block = jnp.full((n, width - c), fill, arr.dtype)
    return jnp.concatenate([arr, block], axis=1)


def grow_state(state, new_egress_cap: int, new_ingress_cap: int):
    """Repack a `plane.NetPlaneState` into larger rings, bitwise.

    Pure and donation-friendly (jnp concatenations only — wrap in
    `tpu.donating_jit` to repack in place on device). Every existing
    column migrates unchanged; new trailing lanes carry exactly the
    `make_state` defaults (-1 dst/src, I32_MAX priority/deliver
    sentinels, NO_CLAMP clamps, zeros elsewhere, invalid), so the next
    `window_step` sees a state indistinguishable from one that was
    front-packed at the larger capacity all along. Scalars, RR
    counters, router state, and the per-host counters pass through
    untouched. Shrinking is refused — dropping lanes could drop live
    packets, the exact silent divergence this plane exists to prevent.
    """
    from .plane import I32_MAX, NO_CLAMP

    ce, ci = ring_dims(state)
    if new_egress_cap < ce or new_ingress_cap < ci:
        raise ValueError(
            f"grow_state cannot shrink rings: have (CE={ce}, CI={ci}), "
            f"asked for (CE={new_egress_cap}, CI={new_ingress_cap})")
    if (new_egress_cap, new_ingress_cap) == (ce, ci):
        return state
    return state._replace(
        eg_dst=_pad_cols(state.eg_dst, new_egress_cap, -1),
        eg_bytes=_pad_cols(state.eg_bytes, new_egress_cap, 0),
        eg_prio=_pad_cols(state.eg_prio, new_egress_cap, I32_MAX),
        eg_seq=_pad_cols(state.eg_seq, new_egress_cap, 0),
        eg_ctrl=_pad_cols(state.eg_ctrl, new_egress_cap, False),
        eg_tsend=_pad_cols(state.eg_tsend, new_egress_cap, 0),
        eg_clamp=_pad_cols(state.eg_clamp, new_egress_cap, NO_CLAMP),
        eg_sock=_pad_cols(state.eg_sock, new_egress_cap, 0),
        eg_valid=_pad_cols(state.eg_valid, new_egress_cap, False),
        in_src=_pad_cols(state.in_src, new_ingress_cap, -1),
        in_bytes=_pad_cols(state.in_bytes, new_ingress_cap, 0),
        in_seq=_pad_cols(state.in_seq, new_ingress_cap, 0),
        in_sock=_pad_cols(state.in_sock, new_ingress_cap, 0),
        in_deliver_rel=_pad_cols(state.in_deliver_rel, new_ingress_cap,
                                 I32_MAX),
        in_valid=_pad_cols(state.in_valid, new_ingress_cap, False),
    )


def grow_transport_state(state, new_ingress_cap: int):
    """Repack a `transport.TransportState` into larger per-destination
    in-flight rings. Transport slots are sparse (never compacted) and
    the ingest kernel fills the LOWEST free columns first, so as long
    as no packet was ever overflow-dropped the grown state is bitwise
    identical — including dead-lane payload — to a run pre-provisioned
    at the larger capacity: lanes < CI carry the identical history,
    lanes >= CI carry the construction defaults in both."""
    ci = int(state.in_src.shape[1])
    if new_ingress_cap < ci:
        raise ValueError(
            f"grow_transport_state cannot shrink: have CI={ci}, asked "
            f"for {new_ingress_cap}")
    if new_ingress_cap == ci:
        return state
    I32_MAX = np.int32(2**31 - 1)
    return state._replace(
        in_src=_pad_cols(state.in_src, new_ingress_cap, 0),
        in_seq=_pad_cols(state.in_seq, new_ingress_cap, 0),
        in_tag=_pad_cols(state.in_tag, new_ingress_cap, 0),
        in_deliver=_pad_cols(state.in_deliver, new_ingress_cap, I32_MAX),
        in_valid=_pad_cols(state.in_valid, new_ingress_cap, False),
    )


def canonical_state(state):
    """Normalize a `NetPlaneState`'s dead lanes to `make_state`
    defaults, leaving live lanes and every scalar/counter untouched.

    Dead-lane payload is outside the determinism contract (every
    consumer masks by validity; the stable sorts only shuffle it), and
    it is the ONE thing a mid-run grow cannot reproduce bitwise — so
    the elastic-vs-pre-provisioned parity gate compares canonical
    states. Two runs whose canonical states AND delivered streams match
    are behaviorally identical forever after (live content determines
    every future output)."""
    import jax.numpy as jnp

    from .plane import I32_MAX, NO_CLAMP

    ev, iv = state.eg_valid, state.in_valid
    w = lambda mask, arr, fill: jnp.where(
        mask, arr, jnp.asarray(fill, dtype=arr.dtype))
    return state._replace(
        eg_dst=w(ev, state.eg_dst, -1),
        eg_bytes=w(ev, state.eg_bytes, 0),
        eg_prio=w(ev, state.eg_prio, I32_MAX),
        eg_seq=w(ev, state.eg_seq, 0),
        eg_ctrl=state.eg_ctrl & ev,
        eg_tsend=w(ev, state.eg_tsend, 0),
        eg_clamp=w(ev, state.eg_clamp, NO_CLAMP),
        eg_sock=w(ev, state.eg_sock, 0),
        in_src=w(iv, state.in_src, -1),
        in_bytes=w(iv, state.in_bytes, 0),
        in_seq=w(iv, state.in_seq, 0),
        in_sock=w(iv, state.in_sock, 0),
        in_deliver_rel=w(iv, state.in_deliver_rel, I32_MAX),
    )


def chain_spans(n_rounds: int, chain_len: int, *, start_round: int = 0,
                boundaries=()) -> list[tuple[int, int]]:
    """The driver's chain partition: [start_round, n_rounds) split at
    every ABSOLUTE `chain_len` multiple and at every explicit boundary
    round. Boundaries are where the host MUST regain control between
    windows — checkpoint instants, tamper/kill points — on top of the
    regular sync cadence. Empty spans collapse; spans are returned as
    [r0, r1) pairs.

    Cuts are aligned to round 0 (not to `start_round`) on purpose:
    under the elastic capacity policy the chain IS the growth-decision
    unit (one snapshot + one overflow read per span), so a run resumed
    from a checkpoint must partition the remaining rounds exactly like
    the uninterrupted run did or the two could grow different ring
    trajectories — the kill/resume bitwise-parity contract
    (docs/determinism.md "Chain length is bitwise-invisible" covers the
    state stream; the ABSOLUTE alignment covers the capacity
    trajectory). Chain lengths stay as regular as the boundary set
    allows, which is what bounds the per-length scan retraces (one
    compile per distinct span length)."""
    if chain_len < 1:
        raise ValueError(f"chain_len must be >= 1, got {chain_len}")
    if start_round >= n_rounds:
        # nothing left to run (a resume at or past the horizon) — the
        # unguarded cut set would invert into a phantom
        # (n_rounds, start_round) span and drive windows PAST the
        # requested end
        return []
    cuts = {start_round, n_rounds}
    first = ((start_round // chain_len) + 1) * chain_len
    cuts.update(range(first, n_rounds, chain_len))
    cuts.update(b for b in boundaries if start_round < b < n_rounds)
    edges = sorted(cuts)
    return [(a, b) for a, b in zip(edges[:-1], edges[1:]) if b > a]


def drive_chained_windows(state, extras, chain_fn, *, n_rounds: int,
                          chain_len: int, start_round: int = 0,
                          boundaries=(), per_round=None,
                          policy: RingPolicy | None = None,
                          window_ns: int = 0, host_names=None,
                          on_chain=None, memo=None, memo_span_salt=None,
                          tracer=None, checkpointer=None):
    """THE driver loop. bench.py, tools/chaos_smoke.py, and the
    scenario corpus runner (workloads/runner.py) all drive their
    windows through this one function (pinned by the inspect-source
    test in tests/test_chain_driver.py so the three loops cannot
    silently fork again): K consecutive windows execute device-resident
    per dispatch, and the host regains control only at chain ends —
    once per harvest/checkpoint/growth boundary instead of once per
    window.

    `chain_fn(state, extras, round_ids, per_round_slice)` is the
    caller's compiled chain — typically one `jax.lax.scan` of its
    window body (window_step + respawn/workload emission, with
    metrics/guards/hist/flight-recorder presence switches riding the
    carry exactly as they ride `plane.chain_windows`' while_loop) —
    and returns ``(state', extras', eg_overflow, in_overflow)`` with
    the per-ring overflow the capacity policy reads ([N] arrays or
    scalars; zeros when untracked). `round_ids` is the span's
    jnp.int32 round-index vector; `per_round_slice` is
    ``per_round(r0, r1)`` (None when per_round is None) — the hook
    time-varying per-window inputs (the fault schedule's mask stack)
    ride in on, as scan inputs rather than per-window host dispatches.

    Under ``policy`` (elastic/strict capacity, docs/robustness.md),
    every chain runs through :func:`run_elastic_window`: the snapshot
    the policy re-executes from is the CHAIN-start state — one
    snapshot per chain, not per window — and a chain that overflows is
    discarded and re-executed against grown rings, so the committed
    stream stays bitwise-identical to a pre-provisioned run. The
    caller's `chain_fn` must then be a pure non-donating function of
    its inputs.

    ``on_chain(r1, state, extras)`` fires after every committed chain
    (the host-sync point: harvester ticks, checkpoints, kill/tamper
    hooks); returning a (state, extras) pair replaces the carried
    values (how chaos_smoke's tamper writes corrupted device state),
    returning None keeps them. Returns the final ``(state, extras)``.

    The flow plane (docs/robustness.md "Flow plane") rides `extras`
    like every other non-NetPlaneState pytree: the scenario runner's
    chain carries its FlowState next to the workload/metrics/guards
    states, so under ``policy`` a discarded overflowing chain replays
    the flow machine from the chain-start snapshot too — retransmit
    schedules stay bitwise-reproducible through elastic growth.

    ``memo`` (a `tpu/memo.ChainMemo`, docs/performance.md
    "Steady-state memoization") makes the chain span the memo unit: at
    every span boundary the carry is snapshotted to host and keyed; a
    hit REPLAYS the recorded post-chain carry (keyed substitution +
    modular counter deltas, bitwise-equal to execution) instead of
    dispatching, and consecutive hits with no `on_chain` hook
    fast-forward entirely on host — no device round-trip at all. A
    miss executes normally (including under ``policy``) and records.
    ``memo_span_salt(r0, r1) -> bytes`` folds per-span external inputs
    into the key — the fault schedule's span fingerprint — and is
    REQUIRED whenever ``per_round`` is set: time-varying inputs the
    key cannot see would otherwise replay across non-equivalent spans,
    so that combination raises instead of guessing.

    ``tracer`` (a `telemetry/tracer.RunTracer`, docs/observability.md
    "Run ledger") records one ledger record per committed span AT the
    chain-boundary sync the loop already owns: the wall-time split
    (dispatch / memo bookkeeping / on_chain hook), the span mode
    (execute, or a memo `replay`/host-only `ffwd`), the capacity
    trajectory events the span committed, and the span-salt
    fingerprint when one exists. The tracer reads host wall clocks and
    values this loop already materialized — zero new device syncs
    (`costmodel.DRIVER_MODULES` re-proves that statically), and
    presence-invisible: tracer-on and tracer-off runs are
    digest-identical (the trace-parity CI gate).

    ``checkpointer`` (a `faults/runstate.RunCheckpointer`,
    docs/robustness.md "Resumable runs") spills the FULL carry to an
    atomic file at its own cadence: its checkpoint instants join the
    boundary set (extra cuts are bitwise-invisible — the chain-length
    theorem), and the save fires AFTER the span's on_chain hook so a
    resume replays nothing the hook already observed. On the memo
    fast-forward path the checkpoint is written straight from the host
    mirror — a crash-survivable run costs zero extra device syncs.
    A checkpointed run SIGKILLed at any boundary and resumed is
    byte-identical to its uninterrupted twin (the kill/resume CI
    gate).
    """
    import jax.numpy as jnp

    if memo is not None and per_round is not None and memo_span_salt is None:
        raise ValueError(
            "drive_chained_windows: memo with per_round inputs needs a "
            "memo_span_salt folding them into the key (e.g. the fault "
            "schedule's span_fingerprint) — refusing to memoize spans "
            "whose external inputs the key cannot see")
    if checkpointer is not None:
        boundaries = tuple(boundaries) + checkpointer.cut_rounds(n_rounds)

    host_carry = None  # memo's host mirror of (state, extras)
    stale = False      # device carry behind host_carry (hits pending)

    def _upload():
        nonlocal state, extras, stale
        state, extras = memo.to_device(host_carry)
        stale = False

    def _maybe_checkpoint(r1):
        # fires at the span end, after on_chain: the carry saved is
        # exactly the carry the next span starts from. The memo host
        # mirror, when authoritative, is saved as-is (no device sync).
        if checkpointer is None or not checkpointer.due(r1, n_rounds):
            return
        carry = host_carry if host_carry is not None else (state, extras)
        checkpointer.save(r1, carry, host=host_carry is not None,
                          tracer=tracer)

    for r0, r1 in chain_spans(n_rounds, chain_len,
                              start_round=start_round,
                              boundaries=boundaries):
        t0 = tracer.clock() if tracer is not None else 0.0
        salt_hex = None
        pre_walk = None
        salt = b""
        if memo_span_salt is not None \
                and (memo is not None or tracer is not None):
            salt = memo_span_salt(r0, r1)
            if tracer is not None:
                # the span's external-input fingerprint (the fault
                # schedule's) — host bytes, hashed before the loop ran
                salt_hex = salt.hex()
        if memo is not None:
            if host_carry is None:
                host_carry = memo.snapshot(state, extras)
            key, pre_walk = memo.key(host_carry, r0, r1, span_salt=salt)
            entry = memo.lookup(key)
            if entry is not None:
                host_carry = memo.replay(entry, host_carry)
                stale = True
                mode, hook_ms = "ffwd", 0.0
                if on_chain is not None:
                    mode = "replay"
                    _upload()
                    th = tracer.clock() if tracer is not None else 0.0
                    replaced = on_chain(r1, state, extras)
                    if tracer is not None:
                        hook_ms = (tracer.clock() - th) * 1e3
                    if replaced is not None:
                        state, extras = replaced
                        host_carry = None  # device is authoritative
                if tracer is not None:
                    tracer.span(r0, r1, mode=mode, t0=t0,
                                hook_ms=hook_ms, span_salt=salt_hex)
                _maybe_checkpoint(r1)
                continue
            if stale:
                _upload()
        rids = jnp.arange(r0, r1, dtype=jnp.int32)
        pr = per_round(r0, r1) if per_round is not None else None
        growth = None
        if policy is None:
            state, extras, _eg, _in = chain_fn(state, extras, rids, pr)
        else:
            def attempt(st, _ex=extras, _rids=rids, _pr=pr):
                st2, ex2, eg, inn = chain_fn(st, _ex, _rids, _pr)
                return (st2, ex2), eg, inn

            n_events = len(policy.trajectory.events)
            try:
                out, _used = run_elastic_window(
                    state, attempt, policy, time_ns=r0 * int(window_ns),
                    host_names=host_names)
            except CapacityError as e:
                # under chained execution the overflow is observed per
                # CHAIN, so the span is the precise blame unit — attach
                # it here so every driver's error report names it
                # without a side channel
                e.chain_span = (r0, r1)
                raise
            state, extras = out
            # the span's committed capacity decisions (growth / drop /
            # exhaustion) — already-host trajectory dicts, by slice
            growth = policy.trajectory.events[n_events:]
        dispatch_ms = ((tracer.clock() - t0) * 1e3
                       if tracer is not None else 0.0)
        memo_ms = 0.0
        if memo is not None:
            tm = tracer.clock() if tracer is not None else 0.0
            host_carry = memo.snapshot(state, extras)
            memo.record(key, pre_walk, host_carry, span_len=r1 - r0)
            if tracer is not None:
                memo_ms = (tracer.clock() - tm) * 1e3
        hook_ms = 0.0
        if on_chain is not None:
            th = tracer.clock() if tracer is not None else 0.0
            replaced = on_chain(r1, state, extras)
            if tracer is not None:
                hook_ms = (tracer.clock() - th) * 1e3
            if replaced is not None:
                state, extras = replaced
                host_carry = None
        if tracer is not None:
            tracer.span(r0, r1, mode="execute", t0=t0,
                        dispatch_ms=dispatch_ms, memo_ms=memo_ms,
                        hook_ms=hook_ms, growth=growth,
                        span_salt=salt_hex)
        _maybe_checkpoint(r1)
    if stale:
        _upload()
    return state, extras


def world_key(rng_root, seed):
    """THE per-world RNG key derivation — and the registered SL702
    obligation (``analysis/batchdim.rng_obligations``).

    ``fold_in(root, seed)`` is one threefry invocation with the ROOT
    key fixed: a block cipher keyed by a constant is a bijection of
    its counter block, so distinct 32-bit seeds yield distinct derived
    key blocks, and every subsequent device draw is
    ``threefry(derived_key, counter)`` — two worlds with distinct
    derived keys can never issue the same cipher invocation. That
    chain (seed -> bijective widen -> fold_in under a fixed key) is
    exactly what the SL702 prover walks symbolically; changing this
    derivation to anything non-injective (``seed % k``, ``seed * 2``)
    fails the proof gate, not a 2x-run parity sweep."""
    import jax

    return jax.random.fold_in(rng_root, seed)


def world_keys(rng_root, seeds):
    """Vector of per-world keys for :func:`drive_ensemble` — the
    vmapped :func:`world_key` chain over a batch of world seeds."""
    import jax

    return jax.vmap(lambda s: world_key(rng_root, s))(seeds)


def drive_ensemble(states, extras, chain_fn, *, n_rounds: int,
                   chain_len: int, start_round: int = 0,
                   boundaries=(), per_round=None, per_round_axis=None,
                   on_chain=None, tracer=None, checkpointer=None):
    """The PROVEN vmap ensemble driver (ROADMAP item 4): W independent
    worlds execute the same chained-window schedule as ONE batched
    program, with one host sync per chain for the whole ensemble.

    ``chain_fn`` is the identical per-world step
    :func:`drive_chained_windows` drives solo —
    ``chain_fn(state, extras, round_ids, per_round_slice) ->
    (state', extras', eg_overflow, in_overflow)`` — vmapped ONCE over
    the leading world axis of ``states``/``extras``. Per-world inputs
    (the :func:`world_keys` RNG keys, fault schedules, workload
    parameters) ride ``extras`` (or ``per_round`` with
    ``per_round_axis=0``) as batched leaves; ``round_ids`` is shared
    (in_axes=None), so every world sees the same round schedule and
    the chain partition is bitwise-identical to the solo run's
    (:func:`chain_spans` ABSOLUTE alignment).

    Why this is trustworthy without running every world twice: the
    SL701 world-isolation proofs (analysis/batchdim.py) show the
    batched step's jaxpr has NO primitive that reduces, gathers,
    scatters, or concatenates across the world axis, and SL702 proves
    the per-world RNG streams disjoint — so world b of a W-world run
    is the solo run of world b by theorem, and the worlds-parity test
    (tests/test_ensemble.py) pins the canonical digests as the
    runtime witness.

    Deliberately NOT supported: a capacity ``policy``. Ring growth is
    per-world (one world's overflow would re-shape every world's
    arrays), so ensemble runs must be provisioned at fixed capacity —
    the per-chain overflow totals are surfaced to ``on_chain`` via
    ``extras`` untouched instead. ``on_chain(r1, states, extras)`` is
    the ONE host-sync point per chain (harvest/checkpoint cadence for
    the whole ensemble); returning a (states, extras) pair replaces
    the carried values, returning None keeps them. ``tracer`` records
    one ``mode="ensemble"`` run-ledger span per batched chain (same
    zero-sync contract as :func:`drive_chained_windows`).
    ``checkpointer`` spills the batched per-world carries into ONE
    runstate file per cadence (docs/robustness.md "Resumable runs" —
    ensemble kill/resume parity is the solo theorem applied
    worldwise). Returns the final batched ``(states, extras)``.
    """
    import jax
    import jax.numpy as jnp

    # jit OUTSIDE the vmap: one compiled batched program per chain
    # length (the final partial chain retraces once), dispatched W
    # worlds at a time — the amortization BENCH_WORLDS measures
    vchain = jax.jit(jax.vmap(chain_fn,
                              in_axes=(0, 0, None, per_round_axis)))
    if checkpointer is not None:
        # per-world batched carries spill to ONE file: the leading
        # world axis is just another array dimension to the flattener,
        # and chain_spans' absolute alignment makes the resumed
        # ensemble partition identical (the solo parity argument,
        # batched)
        boundaries = tuple(boundaries) + checkpointer.cut_rounds(n_rounds)
    for r0, r1 in chain_spans(n_rounds, chain_len,
                              start_round=start_round,
                              boundaries=boundaries):
        t0 = tracer.clock() if tracer is not None else 0.0
        rids = jnp.arange(r0, r1, dtype=jnp.int32)
        pr = per_round(r0, r1) if per_round is not None else None
        states, extras, _eg, _in = vchain(states, extras, rids, pr)
        dispatch_ms = ((tracer.clock() - t0) * 1e3
                       if tracer is not None else 0.0)
        hook_ms = 0.0
        if on_chain is not None:
            th = tracer.clock() if tracer is not None else 0.0
            replaced = on_chain(r1, states, extras)
            if tracer is not None:
                hook_ms = (tracer.clock() - th) * 1e3
            if replaced is not None:
                states, extras = replaced
        if tracer is not None:
            tracer.span(r0, r1, mode="ensemble", t0=t0,
                        dispatch_ms=dispatch_ms, hook_ms=hook_ms)
        if checkpointer is not None and checkpointer.due(r1, n_rounds):
            checkpointer.save(r1, (states, extras), tracer=tracer)
    return states, extras


def run_elastic_window(state, attempt_fn, policy: RingPolicy, *,
                       time_ns: int, host_names=None):
    """One window (or chunk of windows) under the capacity policy.

    `attempt_fn(state)` runs the window against `state` and returns
    ``(out, eg_overflow, in_overflow)`` where `out` is whatever the
    driver commits (its first element being the post-window state is
    conventional but not required here) and the overflow values are
    per-host [N] arrays (or scalars) of ring-full drops the attempt
    incurred — egress-ring (ingest-side) and ingress-ring
    (routing-side) respectively. The attempt must be a pure function of
    `state` plus snapshots the closure holds (metrics, guards, fault
    masks, respawn counters): under the elastic policy an overflowing
    attempt is DISCARDED, the offending ring dimension doubles
    (`grow_state` on the pre-attempt snapshot), and the window
    re-executes — so the committed stream is bitwise identical to a
    run pre-provisioned at the final capacity, and the discarded
    attempt's drops never happened.

    fixed: commit the attempt; a first drop lands a structured
    trajectory event. strict: raise :class:`CapacityError` with
    per-host blame. elastic: grow + re-execute, bounded by the
    policy's ``max_doublings`` per dimension (exhaustion commits the
    overflowing attempt, recorded loudly).

    Returns ``(out, state_used)`` — `state_used` is the (possibly
    grown) pre-window state the committed attempt consumed, which is
    what drivers must snapshot/checkpoint against."""
    while True:
        out, eg_ovf, in_ovf = attempt_fn(state)
        eg_arr = np.atleast_1d(np.asarray(eg_ovf))
        in_arr = np.atleast_1d(np.asarray(in_ovf))
        eg_total, in_total = int(eg_arr.sum()), int(in_arr.sum())
        if eg_total == 0 and in_total == 0:
            return out, state
        if policy.mode == "strict":
            blame = sorted(set(np.nonzero(eg_arr)[0].tolist())
                           | set(np.nonzero(in_arr)[0].tolist()))
            if host_names:
                blame = [host_names[i] if i < len(host_names) else i
                         for i in blame]
            ring = ("egress" if eg_total and not in_total else
                    "ingress" if in_total and not eg_total else
                    "egress+ingress")
            raise CapacityError(
                f"ring-full overflow under capacity.mode=strict: "
                f"{eg_total} egress + {in_total} ingress drop(s) in the "
                f"window at t={time_ns} ns (caps CE={policy.egress_cap}, "
                f"CI={policy.ingress_cap}); raise the ring capacities or "
                f"run capacity.mode=elastic", ring=ring, blame=blame)
        if policy.mode != "elastic":
            if eg_total:
                policy.note_drop(ring="egress", overflow=eg_total,
                                 time_ns=time_ns)
            if in_total:
                policy.note_drop(ring="ingress", overflow=in_total,
                                 time_ns=time_ns)
            return out, state
        target = policy.plan_growth(eg_overflow=eg_total,
                                    in_overflow=in_total,
                                    time_ns=time_ns)
        if target is None:  # growth budget exhausted: the drops are real
            return out, state
        state = grow_state(state, *target)
