"""Device-resident TCP flow engine: live tgen-shaped simulations that
never leave the TPU (phase C of SURVEY.md §7 — the role of the
reference's `src/lib/tcp` + tgen driving it, `src/test/tgen/`).

The transport bridge (`tpu.transport`) keeps hosts on the CPU and moves
packet metadata; this module goes the rest of the way for the workload
class that dominates the benchmark ladder — bulk TCP transfers between
host pairs (tgen mesh, rungs 2-3): BOTH endpoints' TCP machines
(`tpu.tcp`, the bitwise twin of `shadow_tpu.tcp.connection`), the wire,
the timers, and the application (write N bytes, drain, close) advance
entirely on device inside one `lax.scan`. The host dispatches once and
reads back per-flow completion times and counters.

Execution model (conservative PDES, same invariant as the network
plane): windows of width <= the minimum wire latency. Within a window
every connection processes ITS OWN local events — queued segment
arrivals, armed timer deadlines, and immediate app/egress work — in
local-time order, independently of every other connection (vmapped);
nothing a connection emits can affect its peer within the same window
because the wire latency spans the window. At the window barrier,
emitted segments sit in per-destination FIFO rings with their arrival
times; the next window's steps consume them.

Time is int32 MICROSECONDS (the TCP machine's own clocks are integer
milliseconds — RFC 6298 granularity — so microsecond wire precision is
strictly finer than anything the state machine observes; int32 us spans
~35 simulated minutes, far beyond any ladder rung).

What this is NOT: a bitwise replay of the CPU object plane. The CPU
rungs route through NIC relays + CoDel + per-host event queues whose
interleaving this engine does not model (the wire here is the same
fixed-latency pipe the TCP parity harness uses,
`tests/test_tpu_tcp.py::Wire`). The contract is flow-level: same TCP
decisions (the machine is the proven-bitwise kernel), same bytes, same
handshake/teardown structure, deterministic across runs and devices —
validated in tests/test_floweng.py against the CPU `TcpConnection` pair
driver flow-for-flow.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import tcp as dtcp

I32_MAX = np.int32(2**31 - 1)
MS_US = 1000  # microseconds per millisecond

WRITE_CHUNK = 65536


class FlowWorld(NamedTuple):
    """2F connections (even = active opener / writer "a", odd = passive
    "b"); peer(i) = i ^ 1. All times int32 microseconds."""

    plane: dtcp.TcpPlane  # [C]
    # inbound segment FIFO ring per connection (fixed per-flow latency =>
    # arrival order == emission order)
    q_time: jax.Array  # [C, Q] int32 arrival us
    q_fields: jax.Array  # [C, Q, 16] int32 EV_SEG fields
    q_head: jax.Array  # [C]
    q_count: jax.Array  # [C]
    q_dropped: jax.Array  # [C] ring-overflow drops (recovered by retx)
    # app model
    opened: jax.Array  # [C] bool — OPEN_* issued
    close_sent: jax.Array  # [C] bool
    written: jax.Array  # [C] bytes accepted into the stream so far
    read_bytes: jax.Array  # [C] bytes drained by the app
    total: jax.Array  # [C] bytes this side must WRITE (reader: 0)
    t_start: jax.Array  # [C] us — active opener's start time
    latency_us: jax.Array  # [C] one-way wire latency toward PEER
    iss: jax.Array  # [C] int32 — initial send sequence (u32 bits)
    # progress
    conn_t: jax.Array  # [C] us — local clock (last processed event)
    complete_us: jax.Array  # [C] — reader: time the full payload was read
    n_segments: jax.Array  # [C] segments emitted
    clock_us: jax.Array  # [] — window start
    # windows whose inner loop hit max_events_per_window with events
    # still pending: their leftovers process a window late at distorted
    # local times — nonzero means raise the cap
    n_saturated: jax.Array  # []


def make_flow_world(latency_us: np.ndarray, size_bytes: np.ndarray,
                    start_us: np.ndarray | None = None,
                    queue_slots: int = 192, seed: int = 1) -> FlowWorld:
    """F flows; flow f is connection pair (2f, 2f+1): `a`=2f actively
    opens at start_us[f] and writes size_bytes[f]; `b`=2f+1 passively
    accepts, drains, and closes at EOF."""
    F = len(latency_us)
    C = F * 2
    if start_us is None:
        start_us = np.zeros(F, np.int64)
    lat = np.repeat(np.asarray(latency_us, np.int64), 2)
    total = np.zeros(C, np.int64)
    total[0::2] = np.asarray(size_bytes, np.int64)
    t_start = np.full(C, I32_MAX, np.int64)
    t_start[0::2] = np.asarray(start_us, np.int64)
    # deterministic per-connection ISS (splitmix32 of the index)
    idx = np.arange(C, dtype=np.uint32)
    z = (idx + np.uint32(seed) * np.uint32(0x9E3779B9))
    z = (z ^ (z >> 16)) * np.uint32(0x85EBCA6B)
    z = (z ^ (z >> 13)) * np.uint32(0xC2B2AE35)
    iss = (z ^ (z >> 16)).astype(np.int32)
    Q = queue_slots
    zc = lambda: jnp.zeros((C,), jnp.int32)
    return FlowWorld(
        plane=dtcp.make_tcp_plane(C),
        q_time=jnp.full((C, Q), I32_MAX, jnp.int32),
        q_fields=jnp.zeros((C, Q, dtcp.N_FIELDS), jnp.int32),
        q_head=zc(), q_count=zc(), q_dropped=zc(),
        opened=jnp.zeros((C,), bool), close_sent=jnp.zeros((C,), bool),
        written=zc(), read_bytes=zc(),
        total=jnp.asarray(total, jnp.int32),
        t_start=jnp.asarray(t_start, jnp.int32),
        latency_us=jnp.asarray(lat, jnp.int32),
        iss=jnp.asarray(iss),
        conn_t=zc(),
        complete_us=jnp.full((C,), I32_MAX, jnp.int32),
        n_segments=zc(),
        clock_us=jnp.int32(0),
        n_saturated=jnp.int32(0),
    )


def _select_event(w: FlowWorld, window_end):
    """Per-connection next local event (vmapped axes: everything [C]).

    Returns (kind [C], fields [C, 16], t [C], active [C]) — the event each
    connection processes this inner step, at its own local time t.
    Priority at the current local time: OPEN > READ > WRITE > CLOSE >
    PULL (app acts before the stack emits, mirroring the CPU pair
    driver); otherwise the earliest of queued arrival / armed timers
    within the window."""
    p = w.plane
    C = w.conn_t.shape[0]
    now = w.conn_t
    zero_f = jnp.zeros((C, dtcp.N_FIELDS), jnp.int32)

    # ---- immediate app work at the local clock ----
    healthy = p.error == 0  # an errored connection stops app activity
    ev_open = ~w.opened & (now >= w.t_start)
    can_read = p.ordered_bytes > 0
    state_ok = (p.state == dtcp.ESTABLISHED) | (p.state == dtcp.CLOSE_WAIT)
    ev_write = (state_ok & healthy & (w.written < w.total)
                & (dtcp._send_space(p) > 0) & w.opened)
    writer_done = w.written >= w.total
    # writer closes once everything is accepted; reader closes at EOF
    # (FIN seen and every byte drained)
    at_eof = (p.fin_received & (p.ordered_bytes == 0)
              & (p.reass_bytes == 0))
    is_writer = w.total > 0
    ev_close = (~w.close_sent & w.opened & healthy
                & jnp.where(is_writer,
                            writer_done & (p.state == dtcp.ESTABLISHED),
                            at_eof & state_ok))
    ev_pull = dtcp._next_kind(p) != dtcp.K_NONE

    # ---- scheduled events ----
    q_slot = w.q_head % w.q_time.shape[1]
    arr_t = jnp.where(w.q_count > 0,
                      jnp.take_along_axis(w.q_time, q_slot[:, None],
                                          axis=1)[:, 0], I32_MAX)
    rto_t = jnp.where(p.rto_armed, p.rto_deadline_ms * MS_US, I32_MAX)
    tw_t = jnp.where(p.state == dtcp.TIME_WAIT,
                     p.rto_deadline_ms * MS_US, I32_MAX)
    ps_t = jnp.where(p.persist_armed, p.persist_deadline_ms * MS_US,
                     I32_MAX)
    # the active opener's start is also a scheduled event
    open_t = jnp.where(w.opened, I32_MAX, w.t_start)

    imm = ev_open & (now >= w.t_start) | ((ev_write | can_read | ev_close
                                           | ev_pull) & w.opened)
    sched_t = jnp.minimum(jnp.minimum(arr_t, rto_t),
                          jnp.minimum(jnp.minimum(tw_t, ps_t), open_t))
    t = jnp.where(imm, now, jnp.maximum(sched_t, now))
    active = jnp.where(imm, True, sched_t < window_end)

    # choose the kind (priority order)
    is_arr = ~imm & (sched_t == arr_t)
    is_rto = ~imm & ~is_arr & (sched_t == rto_t)
    is_tw = ~imm & ~is_arr & ~is_rto & (sched_t == tw_t)
    is_ps = ~imm & ~is_arr & ~is_rto & ~is_tw & (sched_t == ps_t)
    is_open_sched = ~imm & ~is_arr & ~is_rto & ~is_tw & ~is_ps \
        & (sched_t == open_t)

    arr_f = jnp.take_along_axis(
        w.q_fields, q_slot[:, None, None], axis=1)[:, 0]
    # a SYN arriving at an unopened passive side becomes OPEN_PASSIVE:
    # fields [iss, syn_seq, syn_window, wscale, ts, ts_echo, sack_perm]
    syn_arrival = is_arr & ~w.opened & ((arr_f[:, 0] & dtcp.SYN) != 0)
    passive_f = jnp.stack([
        w.iss, arr_f[:, 1], arr_f[:, 3], arr_f[:, 5], arr_f[:, 6],
        arr_f[:, 7], arr_f[:, 8],
        *(jnp.zeros((dtcp.N_FIELDS - 7, C), jnp.int32)),
    ], axis=1)
    open_f = zero_f.at[:, 0].set(w.iss)
    write_f = zero_f.at[:, 0].set(
        jnp.minimum(jnp.int32(WRITE_CHUNK), w.total - w.written))
    read_f = zero_f.at[:, 0].set(jnp.int32(1 << 24))
    rto_f = zero_f.at[:, 0].set(p.rto_gen)
    tw_f = zero_f.at[:, 0].set(p.rto_gen)
    ps_f = zero_f.at[:, 0].set(p.persist_gen)

    kind = jnp.full((C,), dtcp.EV_NONE, jnp.int32)
    fields = zero_f

    def put(cond, k, f):
        nonlocal kind, fields
        sel = cond & (kind == dtcp.EV_NONE) & active
        kind = jnp.where(sel, k, kind)
        fields = jnp.where(sel[:, None], f, fields)

    # immediate priority chain
    put(imm & ev_open, dtcp.EV_OPEN_ACTIVE, open_f)
    put(imm & can_read & w.opened, dtcp.EV_READ, read_f)
    put(imm & ev_write, dtcp.EV_WRITE, write_f)
    put(imm & ev_close, dtcp.EV_CLOSE, zero_f)
    put(imm & ev_pull, dtcp.EV_PULL, zero_f)
    # scheduled (a non-SYN arrival at an unopened side keeps kind
    # EV_NONE: it is consumed by the pop below and dropped, like a
    # segment to a closed port)
    put(is_open_sched, dtcp.EV_OPEN_ACTIVE, open_f)
    put(syn_arrival, dtcp.EV_OPEN_PASSIVE, passive_f)
    put(is_arr & ~syn_arrival & w.opened, dtcp.EV_SEG, arr_f)
    put(is_rto, dtcp.EV_TIMER_RTO, rto_f)
    put(is_tw, dtcp.EV_TIMER_TW, tw_f)
    put(is_ps, dtcp.EV_TIMER_PERSIST, ps_f)

    pop = is_arr & active  # every consumed arrival leaves the ring
    return kind, fields, t, (active & (kind != dtcp.EV_NONE)) | pop, pop


def _seg_to_fields(out):
    """PULL output [C, 18] -> EV_SEG fields [C, 16] (drop `has` and the
    retransmit flag; the wire carries exactly what the CPU Wire does)."""
    return jnp.concatenate([out[:, 1:9], out[:, 10:]], axis=1)


def _inner_step(w: FlowWorld, window_end):
    kind, fields, t, active, pop = _select_event(w, window_end)
    C = t.shape[0]
    Q = w.q_time.shape[1]
    plane, out, ret = dtcp.tcp_event_step(w.plane, kind, fields,
                                          t // MS_US)
    conn_t = jnp.where(active, jnp.maximum(w.conn_t, t), w.conn_t)

    # pop consumed arrivals
    q_head = jnp.where(pop, w.q_head + 1, w.q_head)
    q_count = jnp.where(pop, w.q_count - 1, w.q_count)

    # app bookkeeping
    opened = w.opened | (kind == dtcp.EV_OPEN_ACTIVE) \
        | (kind == dtcp.EV_OPEN_PASSIVE)
    close_sent = w.close_sent | (kind == dtcp.EV_CLOSE)
    written = w.written + jnp.where(
        (kind == dtcp.EV_WRITE) & (ret > 0), ret, 0)
    got = jnp.where((kind == dtcp.EV_READ) & (ret > 0), ret, 0)
    read_bytes = w.read_bytes + got
    peer_total = w.total[jnp.arange(C) ^ 1]
    complete_us = jnp.where(
        (w.complete_us == I32_MAX) & (read_bytes >= peer_total)
        & (peer_total > 0) & (got > 0),
        conn_t, w.complete_us)

    # emitted segments enter the PEER's ring at t + latency (2D scatter,
    # no reshape: flattening the ring buffers defeated XLA's in-place
    # aliasing inside the scan and copied the whole 20+ MB ring per step
    # — the dominant cost of the round-4 first cut)
    emitted = (kind == dtcp.EV_PULL) & (out[:, 0] != 0)
    seg_f = _seg_to_fields(out)
    peer = jnp.arange(C, dtype=jnp.int32) ^ 1
    p_count = q_count[peer]
    p_head = q_head[peer]
    room = p_count < Q
    slot = (p_head + p_count) % Q
    dst = jnp.where(emitted & room, peer, C)  # C = dropped
    q_time = w.q_time.at[dst, slot].set(
        jnp.where(emitted, conn_t + w.latency_us, 0), mode="drop")
    q_fields = w.q_fields.at[dst, slot].set(seg_f, mode="drop")
    add = jnp.zeros((C,), jnp.int32).at[dst].add(1, mode="drop")
    q_count = q_count + add
    q_dropped = w.q_dropped + jnp.where(emitted & ~room, 1, 0)
    n_segments = w.n_segments + emitted

    return FlowWorld(
        plane=plane, q_time=q_time, q_fields=q_fields, q_head=q_head,
        q_count=q_count, q_dropped=q_dropped, opened=opened,
        close_sent=close_sent, written=written, read_bytes=read_bytes,
        total=w.total, t_start=w.t_start, latency_us=w.latency_us,
        iss=w.iss, conn_t=conn_t, complete_us=complete_us,
        n_segments=n_segments, clock_us=w.clock_us,
        n_saturated=w.n_saturated,
    ), active.any()


def run_windows(world: FlowWorld, n_windows: int, window_us: int,
                max_events_per_window: int = 512):
    """Advance `n_windows` windows of `window_us` each, entirely on
    device. Within each window, inner steps run until no connection has
    an event left before the boundary (bounded by
    max_events_per_window). `window_us` must be <= the minimum one-way
    latency (the PDES lookahead invariant)."""

    def window(w, _):
        end = w.clock_us + window_us

        def cond(c):
            w, progressed, n = c
            return progressed & (n < max_events_per_window)

        def body(c):
            w, _, n = c
            w, progressed = _inner_step(w, end)
            return (w, progressed, n + 1)

        w, progressed, n_events = jax.lax.while_loop(
            cond, body, (w, jnp.bool_(True), jnp.int32(0)))
        # exit with work remaining = the cap truncated this window
        w = w._replace(clock_us=end,
                       conn_t=jnp.maximum(w.conn_t, end),
                       n_saturated=w.n_saturated + progressed)
        return w, n_events

    world, events_per_window = jax.lax.scan(window, world, None,
                                            length=n_windows)
    return world, events_per_window


def flow_results(world: FlowWorld) -> dict:
    """Pull the per-flow outcome to the host — only the small per-flow
    columns, never the segment rings (tens of MB that cost seconds over
    a tunneled link)."""
    complete, read, total, segs, retx, drops, sat, states = \
        jax.device_get((
            world.complete_us, world.read_bytes, world.total,
            world.n_segments.sum(), world.plane.retransmit_count.sum(),
            world.q_dropped.sum(), world.n_saturated, world.plane.state,
        ))
    C = len(complete)
    reader = np.arange(1, C, 2)
    writer = np.arange(0, C, 2)
    return {
        "complete_us": np.asarray(complete)[reader],
        "bytes_read": np.asarray(read)[reader],
        "bytes_expected": np.asarray(total)[writer],
        "segments": int(segs),
        "retransmits": int(retx),
        "queue_drops": int(drops),
        "saturated_windows": int(sat),
        "states": np.asarray(states),
    }


def all_complete(world: FlowWorld) -> bool:
    """Cheap completion probe: one scalar D2H."""
    peer_total = world.total[jnp.arange(world.total.shape[0]) ^ 1]
    return bool(jax.device_get(
        (world.read_bytes >= peer_total).all()))
