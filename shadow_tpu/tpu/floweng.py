"""Device-resident TCP flow engine: live tgen-shaped simulations that
never leave the TPU (phase C of SURVEY.md §7 — the role of the
reference's `src/lib/tcp` + tgen driving it, `src/test/tgen/`).

The transport bridge (`tpu.transport`) keeps hosts on the CPU and moves
packet metadata; this module goes the rest of the way for the workload
class that dominates the benchmark ladder — bulk TCP transfers between
host pairs (tgen mesh, rungs 2-3): BOTH endpoints' TCP machines
(`tpu.tcp`, the bitwise twin of `shadow_tpu.tcp.connection`), the wire,
the timers, and the application (write N bytes, drain, close) advance
entirely on device inside one `lax.scan`. The host dispatches once and
reads back per-flow completion times and counters. Manager integration:
`experimental.use_flow_engine` (core/flowplan.py) compiles a YAML tgen
workload into a flow plan and reconciles results into sim stats.

Execution model (conservative PDES, same invariant as the network
plane): windows of width <= the minimum wire latency. Within a window
every connection processes ITS OWN local events — queued segment
arrivals, armed timer deadlines, and immediate app/egress work — in
local-time order, independently of every other connection (vmapped);
nothing a connection emits can affect its peer within the same window
because the wire latency spans the window (connections only ever talk
to their pair, so the lookahead bound is the minimum over FLOWS). At
the window barrier, emitted segments sit in per-destination FIFO rings
with their arrival times; the next window's steps consume them.

FUSED STEPPING (round 5 — the change that made this engine win the
rung-3 shape): the round-4 driver spent one `while_loop` iteration per
micro-event (arrival, read, write, close, each individual segment
pull), so a window in which some connection handled a 45-segment burst
cost everyone 100+ iterations of the full 11-way event kernel
(~6 ms each on v5e). One fused step now:
  1. processes scheduled events (arrivals, timers, opens) through a
     6-way kernel (`tcp_sched_step`) — a convergent inner loop, up to
     `sched_batch` per connection, skipped entirely when no connection
     has one;
  2. applies app work inline and batched — greedy read, buffer-refill
     write, EOF/done close — as pure [C] array updates (no per-kind
     kernel passes);
  3. drains egress with a convergent pull loop (`tcp_pull_step`),
     scattering emitted segments into the peer rings.
Pure ACKs are coalesced RFC-1122 style: a lone data segment's ACK is
held (up to `ack_every` segments or the window barrier, whichever
first) while out-of-order, FIN, handshake, and window-update ACKs still
go out immediately — receivers in the reference's target workloads
(Linux delayed acks + GRO) batch harder than this. The flush at the
window barrier bounds added latency at one window (<= min latency).
The window's step loop is gated on a cheap "any work before the
barrier" predicate, so event-free windows cost one predicate
evaluation — which is what makes narrow windows (low-latency flows)
and long quiet tails affordable.

WIRE LOSS: per-connection Bernoulli loss at emission, drawn from a
counter-based splitmix hash of (connection, segment ordinal) — fully
deterministic, no RNG state. Dropped segments never enter the peer
ring; the TCP machines recover through the normal dup-ack/SACK/RTO
paths. This mirrors the composed path-loss model of the CPU plane
(`net/graph.py` loss composition), segment-granular rather than
packet-granular.

Time is int32 MICROSECONDS (the TCP machine's own clocks are integer
milliseconds — RFC 6298 granularity — so microsecond wire precision is
strictly finer than anything the state machine observes; int32 us spans
~35 simulated minutes, far beyond any ladder rung).

What this is NOT: a bitwise replay of the CPU object plane. The CPU
rungs route through NIC relays + CoDel + per-host event queues whose
interleaving this engine does not model (the wire here is a
fixed-latency lossy pipe; NIC serialization at ladder sizes is ~two
orders of magnitude below path RTTs — quantified in BASELINE.md). The
contract is flow-level: same TCP decisions (the machine is the
proven-bitwise kernel), same bytes, same handshake/teardown structure,
deterministic across runs and devices — validated in
tests/test_floweng.py against the CPU `TcpConnection` pair driver
flow-for-flow.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import tcp as dtcp

I32_MAX = np.int32(2**31 - 1)
MS_US = 1000  # microseconds per millisecond


class FlowWorld(NamedTuple):
    """2F connections (even = active opener, odd = passive accepter);
    peer(i) = i ^ 1. The WRITER of flow f is whichever lane has
    total > 0 — the active opener for client-upload flows, the passive
    side for tgen's fetch direction (server streams to the connecting
    client). All times int32 microseconds."""

    plane: dtcp.TcpPlane  # [C]
    # inbound segment FIFO ring per connection (fixed per-flow latency =>
    # arrival order == emission order)
    q_time: jax.Array  # [C, Q] int32 arrival us
    q_fields: jax.Array  # [C, Q, 16] int32 EV_SEG fields
    q_head: jax.Array  # [C]
    q_count: jax.Array  # [C]
    q_dropped: jax.Array  # [C] ring-overflow drops (recovered by retx)
    # app model
    opened: jax.Array  # [C] bool — OPEN_* issued
    close_sent: jax.Array  # [C] bool
    written: jax.Array  # [C] bytes accepted into the stream so far
    read_bytes: jax.Array  # [C] bytes drained by the app
    total: jax.Array  # [C] bytes this side must WRITE (reader: 0)
    t_start: jax.Array  # [C] us — active opener's start time
    latency_us: jax.Array  # [C] one-way wire latency toward PEER
    loss_u32: jax.Array  # [C] uint32 Bernoulli threshold toward PEER
    lane_id: jax.Array  # [C] GLOBAL lane index — keys the wire-loss
    # hash, so a device shard draws the same losses as the unsharded
    # world (local arange would diverge under pmap)
    iss: jax.Array  # [C] int32 — initial send sequence (u32 bits)
    # progress
    conn_t: jax.Array  # [C] us — local clock (last processed event)
    complete_us: jax.Array  # [C] — reader: time the full payload was read
    n_segments: jax.Array  # [C] wire units emitted (macro-segments;
    # drives the loss-hash counter)
    seg_units: jax.Array  # [C] MSS-equivalent segments emitted (the
    # stat comparable to the CPU plane's packet count)
    wire_drops: jax.Array  # [C] segments lost to Bernoulli wire loss
    unacked: jax.Array  # [C] in-order data segments not yet covered by
    # an emitted ACK (drives RFC-1122-style ack coalescing)
    clock_us: jax.Array  # [] — window start
    # windows whose inner loop hit the step cap with events still
    # pending: their leftovers process a window late at distorted local
    # times — callers MUST re-run with a doubled cap (run_to_completion
    # does this automatically); results from a saturated run are wrong
    n_saturated: jax.Array  # []


def make_flow_world(latency_us: np.ndarray, size_bytes: np.ndarray,
                    start_us: np.ndarray | None = None,
                    queue_slots: int = 192, seed: int = 1,
                    loss: np.ndarray | float = 0.0,
                    server_writes: bool = False,
                    latency_back_us: np.ndarray | None = None,
                    loss_back: np.ndarray | None = None) -> FlowWorld:
    """F flows; flow f is connection pair (2f, 2f+1): `a`=2f actively
    opens at start_us[f]. With server_writes=False, `a` writes
    size_bytes[f] and `b` drains (upload shape); with True, `b` writes
    once the handshake completes and `a` drains (tgen's fetch shape —
    the 8-byte size request rides the handshake tail and is not
    byte-modeled). `loss` is per-flow one-way segment loss probability,
    applied independently per direction. latency_back_us / loss_back
    give the passive->active direction its own path (asymmetric directed
    graphs); they default to the forward values."""
    F = len(latency_us)
    C = F * 2
    if start_us is None:
        start_us = np.zeros(F, np.int64)
    if latency_back_us is None:
        latency_back_us = latency_us
    lat = np.empty(C, np.int64)
    lat[0::2] = np.asarray(latency_us, np.int64)  # active -> passive
    lat[1::2] = np.asarray(latency_back_us, np.int64)
    total = np.zeros(C, np.int64)
    writer_off = 1 if server_writes else 0
    total[writer_off::2] = np.asarray(size_bytes, np.int64)
    t_start = np.full(C, I32_MAX, np.int64)
    t_start[0::2] = np.asarray(start_us, np.int64)
    if loss_back is None:
        loss_back = loss
    loss_fwd = np.broadcast_to(np.asarray(loss, np.float64), (F,))
    loss_bck = np.broadcast_to(np.asarray(loss_back, np.float64), (F,))
    loss_pair = np.empty(C, np.float64)
    loss_pair[0::2] = loss_fwd
    loss_pair[1::2] = loss_bck
    loss_u32 = np.clip(loss_pair * 2.0**32,
                       0, 2**32 - 1).astype(np.uint32)
    # deterministic per-connection ISS (splitmix32 of the index)
    idx = np.arange(C, dtype=np.uint32)
    z = (idx + np.uint32(seed) * np.uint32(0x9E3779B9))
    z = (z ^ (z >> 16)) * np.uint32(0x85EBCA6B)
    z = (z ^ (z >> 13)) * np.uint32(0xC2B2AE35)
    iss = (z ^ (z >> 16)).astype(np.int32)
    Q = queue_slots
    zc = lambda: jnp.zeros((C,), jnp.int32)
    return FlowWorld(
        # GSO macro-segment wires produce few disjoint OOO ranges: 32
        # slots (vs the per-MSS default 128) cover bursts while the
        # SACK-block sort — the kernel's heaviest op — scans 4x less
        plane=dtcp.make_tcp_plane(C, reass_slots=32),
        q_time=jnp.full((C, Q), I32_MAX, jnp.int32),
        q_fields=jnp.zeros((C, Q, dtcp.N_FIELDS), jnp.int32),
        q_head=zc(), q_count=zc(), q_dropped=zc(),
        opened=jnp.zeros((C,), bool), close_sent=jnp.zeros((C,), bool),
        written=zc(), read_bytes=zc(),
        total=jnp.asarray(total, jnp.int32),
        t_start=jnp.asarray(t_start, jnp.int32),
        latency_us=jnp.asarray(lat, jnp.int32),
        loss_u32=jnp.asarray(loss_u32),
        lane_id=jnp.arange(C, dtype=jnp.int32),
        iss=jnp.asarray(iss),
        conn_t=zc(),
        complete_us=jnp.full((C,), I32_MAX, jnp.int32),
        n_segments=zc(),
        seg_units=zc(),
        wire_drops=zc(),
        unacked=zc(),
        clock_us=jnp.int32(0),
        n_saturated=jnp.int32(0),
    )


def _seg_to_fields(out):
    """PULL output [C, 18] -> EV_SEG fields [C, 16] (drop `has` and the
    retransmit flag; the wire carries exactly what the CPU Wire does)."""
    return jnp.concatenate([out[:, 1:9], out[:, 10:]], axis=1)


def _sched_times(w: FlowWorld):
    """Per-connection earliest scheduled event time [C]: head-of-ring
    arrival, armed timer deadlines, the active opener's start."""
    p = w.plane
    Q = w.q_time.shape[1]
    q_slot = w.q_head % Q
    arr_t = jnp.where(w.q_count > 0,
                      jnp.take_along_axis(w.q_time, q_slot[:, None],
                                          axis=1)[:, 0], I32_MAX)
    rto_t = jnp.where(p.rto_armed, p.rto_deadline_ms * MS_US, I32_MAX)
    tw_t = jnp.where(p.state == dtcp.TIME_WAIT,
                     p.rto_deadline_ms * MS_US, I32_MAX)
    ps_t = jnp.where(p.persist_armed, p.persist_deadline_ms * MS_US,
                     I32_MAX)
    open_t = jnp.where(w.opened, I32_MAX, w.t_start)
    sched_t = jnp.minimum(jnp.minimum(arr_t, rto_t),
                          jnp.minimum(jnp.minimum(tw_t, ps_t), open_t))
    return sched_t, arr_t, rto_t, tw_t, ps_t


def _ack_delayed(w: FlowWorld, kind, ack_every: int):
    """Which connections may HOLD a pure ACK: in-order established-state
    data acks below the coalescing threshold. OOO (dup-ack), FIN,
    handshake, error, and window-update acks (unacked == 0) all emit
    immediately."""
    p = w.plane
    return ((kind == dtcp.K_ACK) & (p.state == dtcp.ESTABLISHED)
            & ~p.fin_received & (p.reass_bytes == 0) & (p.error == 0)
            & (w.unacked >= 1) & (w.unacked < ack_every))


def _pull_wanted(w: FlowWorld, ack_every: int):
    kind = dtcp._next_kind(w.plane)  # elementwise: batched as-is
    return (kind != dtcp.K_NONE) & w.opened \
        & ~_ack_delayed(w, kind, ack_every)


def _any_work(w: FlowWorld, window_end, ack_every: int):
    """Cheap predicate: does ANY connection have a scheduled event
    before the barrier, or unsuppressed egress? Evaluated as the window
    while_loop condition, so event-free windows run zero steps."""
    sched_t, *_ = _sched_times(w)
    return ((sched_t < window_end) | _pull_wanted(w, ack_every)).any()


def _sched_event(w: FlowWorld, window_end):
    """Process ONE scheduled event per connection (arrival / timer /
    open), each at its own local time. Returns (w', any_active)."""
    p = w.plane
    C = w.conn_t.shape[0]
    Q = w.q_time.shape[1]
    sched_t, arr_t, rto_t, tw_t, ps_t = _sched_times(w)
    active = sched_t < window_end
    t = jnp.where(active, jnp.maximum(sched_t, w.conn_t), w.conn_t)
    now_ms = t // MS_US

    # priority at equal times: arrival > rto > time-wait > persist > open
    is_arr = active & (sched_t == arr_t)
    is_rto = active & ~is_arr & (sched_t == rto_t)
    is_tw = active & ~is_arr & ~is_rto & (sched_t == tw_t)
    is_ps = active & ~is_arr & ~is_rto & ~is_tw & (sched_t == ps_t)
    is_open = active & ~is_arr & ~is_rto & ~is_tw & ~is_ps

    q_slot = w.q_head % Q
    arr_f = jnp.take_along_axis(
        w.q_fields, q_slot[:, None, None], axis=1)[:, 0]
    # a SYN arriving at an unopened passive side becomes OPEN_PASSIVE:
    # fields [iss, syn_seq, syn_window, wscale, ts, ts_echo, sack_perm]
    syn_arrival = is_arr & ~w.opened & ((arr_f[:, 0] & dtcp.SYN) != 0)
    seg_arrival = is_arr & w.opened
    # (a non-SYN arrival at an unopened side keeps kind EV_NONE: popped
    # and dropped, like a segment to a closed port)

    zero_f = jnp.zeros((C, dtcp.N_FIELDS), jnp.int32)
    passive_f = jnp.stack([
        w.iss, arr_f[:, 1], arr_f[:, 3], arr_f[:, 5], arr_f[:, 6],
        arr_f[:, 7], arr_f[:, 8],
        *(jnp.zeros((dtcp.N_FIELDS - 7, C), jnp.int32)),
    ], axis=1)
    open_f = zero_f.at[:, 0].set(w.iss)
    gen_f = zero_f.at[:, 0].set(
        jnp.where(is_ps, p.persist_gen, p.rto_gen))

    kind = jnp.full((C,), dtcp.EV_NONE, jnp.int32)
    kind = jnp.where(seg_arrival, dtcp.EV_SEG, kind)
    kind = jnp.where(syn_arrival, dtcp.EV_OPEN_PASSIVE, kind)
    kind = jnp.where(is_rto, dtcp.EV_TIMER_RTO, kind)
    kind = jnp.where(is_tw, dtcp.EV_TIMER_TW, kind)
    kind = jnp.where(is_ps, dtcp.EV_TIMER_PERSIST, kind)
    kind = jnp.where(is_open, dtcp.EV_OPEN_ACTIVE, kind)
    fields = jnp.where(seg_arrival[:, None], arr_f, zero_f)
    fields = jnp.where(syn_arrival[:, None], passive_f, fields)
    fields = jnp.where((is_rto | is_tw | is_ps)[:, None], gen_f, fields)
    fields = jnp.where(is_open[:, None], open_f, fields)

    plane = dtcp.tcp_sched_step(p, kind, fields, now_ms)

    q_head = jnp.where(is_arr, w.q_head + 1, w.q_head)
    q_count = jnp.where(is_arr, w.q_count - 1, w.q_count)
    opened = w.opened | (kind == dtcp.EV_OPEN_ACTIVE) \
        | (kind == dtcp.EV_OPEN_PASSIVE)
    unacked = w.unacked + (seg_arrival & (arr_f[:, 4] > 0))
    return w._replace(
        plane=plane, q_head=q_head, q_count=q_count, opened=opened,
        unacked=unacked, conn_t=t,
    ), active.any()


def _app_phase(w: FlowWorld) -> FlowWorld:
    """Inline batched app model at the current local clocks: greedy
    read, buffer-refill write, EOF/done close. Pure [C] array updates —
    mirrors what the round-4 driver issued as separate EV_READ /
    EV_WRITE / EV_CLOSE kernel passes (tcp.py:_ev_read/_ev_write/
    _ev_close), restricted to the paths the driver actually took."""
    p = w.plane
    now_ms = w.conn_t // MS_US
    healthy = p.error == 0
    state_ok = (p.state == dtcp.ESTABLISHED) | (p.state == dtcp.CLOSE_WAIT)

    # greedy read (EV_READ drain path; the driver never reads on the
    # error path — can_read gated on ordered_bytes > 0, as in round 4)
    got = jnp.where(w.opened, p.ordered_bytes, 0)
    drain = got > 0
    p = p._replace(ordered_bytes=jnp.where(drain, 0, p.ordered_bytes),
                   ack_pending=p.ack_pending | drain)
    read_bytes = w.read_bytes + got
    C = w.conn_t.shape[0]
    peer_total = w.total[jnp.arange(C) ^ 1]
    complete_us = jnp.where(
        (w.complete_us == I32_MAX) & (read_bytes >= peer_total)
        & (peer_total > 0) & drain,
        w.conn_t, w.complete_us)

    # buffer-refill write (EV_WRITE accept path, un-chunked: accepting
    # min(space, remaining) in one update admits the same stream bytes
    # as round 4's 64 KiB-chunk loop)
    space = dtcp._send_space(p)  # elementwise: batched as-is
    n = jnp.minimum(space, w.total - w.written)
    do_write = state_ok & healthy & w.opened & (n > 0)
    n = jnp.where(do_write, n, 0)
    p = p._replace(stream_len=p.stream_len + n)
    written = w.written + n
    # batched arm-persist (dtcp._arm_persist's update under a [C]
    # mask; the helper's scalar _sel cannot broadcast over 2D slot
    # fields, hence sel_batched around the same field updates)
    need_persist = (do_write & (p.snd_wnd == 0)
                    & (p.state >= dtcp.ESTABLISHED) & ~p.persist_armed)
    armed = p._replace(persist_gen=p.persist_gen + 1,
                       persist_armed=jnp.ones_like(p.persist_armed),
                       persist_deadline_ms=now_ms + p.rto_ms)
    p = dtcp.sel_batched(need_persist, armed, p)

    # close: writer once everything is accepted; reader at EOF (FIN seen
    # and every byte drained). Only the ESTABLISHED->FIN_WAIT_1 and
    # CLOSE_WAIT->LAST_ACK arms of _ev_close are reachable here.
    writer_done = written >= w.total
    at_eof = (p.fin_received & (p.ordered_bytes == 0)
              & (p.reass_bytes == 0))
    is_writer = w.total > 0
    do_close = (~w.close_sent & w.opened & healthy
                & jnp.where(is_writer,
                            writer_done & (p.state == dtcp.ESTABLISHED),
                            at_eof & state_ok))
    nxt = jnp.where(p.state == dtcp.ESTABLISHED, dtcp.FIN_WAIT_1,
                    jnp.where(p.state == dtcp.CLOSE_WAIT, dtcp.LAST_ACK,
                              p.state))
    p = p._replace(
        state=jnp.where(do_close, nxt, p.state).astype(jnp.int32),
        fin_requested=p.fin_requested | do_close)

    return w._replace(plane=p, read_bytes=read_bytes, written=written,
                      complete_us=complete_us,
                      close_sent=w.close_sent | do_close)


def _wire_draw(idx, counter):
    """Counter-based uniform u32: splitmix-style hash of (connection,
    per-connection emission ordinal). Deterministic, stateless."""
    z = idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B9) \
        + counter.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B) \
        + jnp.uint32(0x6A09E667)
    z = (z ^ (z >> 16)) * jnp.uint32(0x21F0AAAD)
    z = (z ^ (z >> 15)) * jnp.uint32(0x735A2D97)
    return z ^ (z >> 15)


def _pull_phase(w: FlowWorld, ack_every: int, pull_cap: int,
                gso_segs: int = 1) -> FlowWorld:
    """Drain egress: pull segments (data, acks, SYN/FIN/RST, probes)
    until every connection reports K_NONE or holds only a delayed ACK,
    bounded by pull_cap. With gso_segs > 1 a data pull emits one
    TSO-style macro-segment of up to gso_segs*MSS and the peer ingests
    it as one arrival (GRO) — the tpu-native batching of the hot path;
    sequence arithmetic is byte-based so the TCP machines are oblivious.
    Wire loss is still drawn PER MSS UNIT: the macro-segment truncates
    at the first lost unit (the in-flight tail is charged to the same
    burst), so per-byte loss probability matches the CPU plane's
    per-packet draw. Emitted segments that survive enter the PEER's
    ring at conn_t + latency (2D scatter, no reshape: flattening the
    ring buffers defeated XLA's in-place aliasing inside the scan and
    copied the whole ring per step — the dominant cost of the round-4
    first cut)."""
    C = w.conn_t.shape[0]
    Q = w.q_time.shape[1]
    peer = jnp.arange(C, dtype=jnp.int32) ^ 1
    kk = jnp.arange(gso_segs, dtype=jnp.int32)

    def cond(c):
        w, i, pending = c
        return pending & (i < pull_cap)

    def body(c):
        w, i, _ = c
        do = _pull_wanted(w, ack_every)
        now_ms = w.conn_t // MS_US
        p2, out = dtcp.tcp_pull_step(w.plane, now_ms, gso_segs)
        plane = dtcp.sel_batched(do, p2, w.plane)
        emitted = do & (out[:, 0] != 0)
        paylen = out[:, 5]
        units = jnp.maximum((paylen + dtcp.MSS - 1) // dtcp.MSS, 1)
        draws = _wire_draw(
            w.lane_id[:, None],
            w.n_segments[:, None] * gso_segs + kk[None, :])
        unit_lost = ((w.loss_u32 > 0)[:, None]
                     & (draws < w.loss_u32[:, None])
                     & (kk[None, :] < units[:, None]))
        any_lost = unit_lost.any(axis=1)
        f0 = jnp.argmax(unit_lost, axis=1)  # first lost unit
        after0 = unit_lost & (kk[None, :] > f0[:, None])
        any2 = after0.any(axis=1)
        f1 = jnp.where(any2, jnp.argmax(after0, axis=1), units)
        # the surviving RUNS of the burst ship as (up to) two wire
        # segments: A = units [0, f0), B = units (f0, f1). Units from
        # the second loss on are charged to the wire (a >=2-losses-per-
        # burst event, O(p^2) rare), so per-unit delivery probability
        # stays ~= (1 - p) like the CPU plane's per-packet draw.
        lenA_units = jnp.where(any_lost, f0, units)
        lenA = jnp.minimum(lenA_units * dtcp.MSS, paylen)
        startB = (f0 + 1) * dtcp.MSS
        lenB = jnp.where(any_lost,
                         jnp.clip(jnp.minimum(f1 * dtcp.MSS, paylen)
                                  - startB, 0, None), 0)
        lenB_units = (lenB + dtcp.MSS - 1) // dtcp.MSS
        pure = paylen == 0  # ack/SYN/FIN carrier: one all-or-nothing unit
        hasA = emitted & jnp.where(pure, ~any_lost, lenA > 0)
        hasB = emitted & (lenB > 0)
        delivered = jnp.where(pure, hasA.astype(jnp.int32),
                              lenA_units + lenB_units)
        seg_f = _seg_to_fields(out)
        segA = seg_f.at[:, 4].set(jnp.minimum(seg_f[:, 4], lenA))
        segB = seg_f.at[:, 1].set(
            (seg_f[:, 1].astype(jnp.uint32)
             + startB.astype(jnp.uint32)).astype(jnp.int32))
        segB = segB.at[:, 4].set(lenB)
        p_count = w.q_count[peer]
        p_head = w.q_head[peer]
        roomA = p_count < Q
        slotA = (p_head + p_count) % Q
        dstA = jnp.where(hasA & roomA, peer, C)  # C = dropped
        q_time = w.q_time.at[dstA, slotA].set(
            jnp.where(hasA, w.conn_t + w.latency_us, 0), mode="drop")
        q_fields = w.q_fields.at[dstA, slotA].set(segA, mode="drop")
        occA = (hasA & roomA).astype(jnp.int32)
        roomB = p_count + occA < Q
        slotB = (p_head + p_count + occA) % Q
        dstB = jnp.where(hasB & roomB, peer, C)
        q_time = q_time.at[dstB, slotB].set(
            jnp.where(hasB, w.conn_t + w.latency_us, 0), mode="drop")
        q_fields = q_fields.at[dstB, slotB].set(segB, mode="drop")
        add = jnp.zeros((C,), jnp.int32).at[dstA].add(1, mode="drop") \
            .at[dstB].add(1, mode="drop")
        w = w._replace(
            plane=plane, q_time=q_time, q_fields=q_fields,
            q_count=w.q_count + add,
            q_dropped=w.q_dropped + (hasA & ~roomA) + (hasB & ~roomB),
            wire_drops=w.wire_drops
            + jnp.where(emitted, units - delivered, 0),
            n_segments=w.n_segments + emitted,
            seg_units=w.seg_units + jnp.where(emitted, units, 0),
            # every emitted segment carries the current cumulative ack
            # (whether the wire then eats it is the sender's problem)
            unacked=jnp.where(emitted, 0, w.unacked),
        )
        return w, i + 1, _pull_wanted(w, ack_every).any()

    w, _, _ = jax.lax.while_loop(
        cond, body, (w, jnp.int32(0), jnp.bool_(True)))
    return w


def _fused_step(w: FlowWorld, window_end, ack_every: int,
                sched_batch: int, pull_cap: int,
                gso_segs: int) -> FlowWorld:
    """One fused driver step: up to sched_batch scheduled events per
    connection (stopping early when none are left), inline app work,
    then the egress pull loop."""
    def sched_cond(c):
        w, i, alive = c
        return alive & (i < sched_batch)

    def sched_body(c):
        w, i, _ = c
        w, any_active = _sched_event(w, window_end)
        sched_t, *_ = _sched_times(w)
        return w, i + 1, any_active & (sched_t < window_end).any()

    w, _, _ = jax.lax.while_loop(
        sched_cond, sched_body, (w, jnp.int32(0), jnp.bool_(True)))
    w = _app_phase(w)
    return _pull_phase(w, ack_every, pull_cap, gso_segs)


def run_windows(world: FlowWorld, n_windows: int, window_us: int,
                max_events_per_window: int = 512, ack_every: int = 2,
                sched_batch: int = 8, pull_cap: int = 8,
                gso_segs: int = 16):
    """Advance `n_windows` windows of `window_us` each, entirely on
    device. Within each window, fused steps run until no connection has
    an event left before the boundary (bounded by max_events_per_window
    fused steps — each step is up to sched_batch scheduled events plus
    a pull loop per connection). `window_us` must be <= the minimum
    one-way FLOW latency (the PDES lookahead invariant — pairs are
    independent, so only a pair's own latency bounds its windows).
    Check `n_saturated` on the result — nonzero means the cap truncated
    a window and results are distorted; use run_to_completion for the
    re-run-with-doubled-cap discipline."""

    def window(w, _):
        end = w.clock_us + window_us

        def cond(c):
            w, n = c
            return _any_work(w, end, ack_every) \
                & (n < max_events_per_window)

        def body(c):
            w, n = c
            w = _fused_step(w, end, ack_every, sched_batch, pull_cap,
                            gso_segs)
            return (w, n + 1)

        w, n_steps = jax.lax.while_loop(cond, body, (w, jnp.int32(0)))
        # cond still true at the cap = the cap truncated this window
        saturated = _any_work(w, end, ack_every) \
            & (n_steps >= max_events_per_window)
        # flush delayed acks at the barrier (nothing to flush when the
        # window ran no steps — skip the pull pass, it is the whole cost
        # of an idle window)
        w = jax.lax.cond(
            n_steps > 0,
            lambda w: _pull_phase(w, ack_every=1, pull_cap=pull_cap,
                                  gso_segs=gso_segs),
            lambda w: w, w)
        w = w._replace(clock_us=end,
                       conn_t=jnp.maximum(w.conn_t, end),
                       n_saturated=w.n_saturated + saturated)
        return w, n_steps

    world, steps_per_window = jax.lax.scan(window, world, None,
                                           length=n_windows)
    return world, steps_per_window


def flow_results(world: FlowWorld) -> dict:
    """Pull the per-flow outcome to the host — only the small per-flow
    columns, never the segment rings (tens of MB that cost seconds over
    a tunneled link)."""
    complete, read, total, segs, retx, drops, wire, sat, states = \
        jax.device_get((
            world.complete_us, world.read_bytes, world.total,
            world.seg_units.sum(), world.plane.retransmit_count.sum(),
            world.q_dropped.sum(), world.wire_drops.sum(),
            world.n_saturated, world.plane.state,
        ))
    complete, read, total = map(np.asarray, (complete, read, total))
    C = len(complete)
    even, odd = np.arange(0, C, 2), np.arange(1, C, 2)
    # the reader of flow f is the lane whose PEER carries the payload
    writer_is_even = total[even] > 0
    reader = np.where(writer_is_even, odd, even)
    writer = reader ^ 1
    return {
        "complete_us": complete[reader],
        "bytes_read": read[reader],
        "bytes_expected": total[writer],
        "segments": int(segs),
        "retransmits": int(retx),
        "queue_drops": int(drops),
        "wire_drops": int(wire),
        "saturated_windows": int(sat),
        "states": np.asarray(states),
    }


def _status_flags(world: FlowWorld):
    """(all_complete, quiescent) as one tiny device value. Quiescent =
    nothing in flight and nothing armed except TIME_WAIT expiries (which
    finalize_to applies analytically)."""
    peer_total = world.total[jnp.arange(world.total.shape[0]) ^ 1]
    complete = (world.read_bytes >= peer_total).all()
    p = world.plane
    settled = (p.state == dtcp.CLOSED) | (p.state == dtcp.TIME_WAIT)
    # ack_pending on a CLOSED lane can never drain (K_ACK requires a
    # live state) and owes no event — only live-state acks block
    quiescent = ((world.q_count == 0).all() & (~p.rto_armed).all()
                 & (~p.persist_armed).all() & settled.all()
                 & (~p.ack_pending | (p.state == dtcp.CLOSED)).all())
    return jnp.stack([complete, quiescent])


def all_complete(world: FlowWorld) -> bool:
    """Cheap completion probe: one tiny D2H."""
    return bool(jax.device_get(_status_flags(world))[0])


def finalize_to(world: FlowWorld, stop_us: int) -> FlowWorld:
    """Fast-forward a quiescent world to the configured stop time:
    TIME_WAIT lanes whose 2MSL deadline falls before the stop close
    analytically (the only events a quiescent world still owes), clocks
    jump to the stop. Mirrors the CPU controller skipping straight to
    the next event horizon over quiet spans."""
    p = world.plane
    expire = (p.state == dtcp.TIME_WAIT) \
        & (p.rto_deadline_ms * MS_US <= stop_us)
    plane = p._replace(
        state=jnp.where(expire, dtcp.CLOSED, p.state).astype(jnp.int32))
    stop = jnp.int32(stop_us)
    return world._replace(
        plane=plane, clock_us=stop,
        conn_t=jnp.maximum(world.conn_t, stop))


def run_to_completion(world: FlowWorld, window_us: int,
                      max_sim_s: float = 40.0, chunk_windows: int = 50,
                      probe_every: int = 2, jit_run=None,
                      max_events_per_window: int = 512,
                      **step_opts):
    """Host driver with the saturation discipline (VERDICT r4 #9): run
    chunked window dispatches until all flows complete and the world is
    quiescent; if ANY window saturated its step cap (results would be
    distorted — leftovers processed a window late), restart the whole
    run from the initial world with a DOUBLED cap. Deterministic: the
    retried run is a fresh simulation, not a patch-up. Returns
    (world, sim_seconds, retries)."""
    world0 = world
    cap = max_events_per_window
    n_chunks = int(max_sim_s * 1e6 / (window_us * chunk_windows)) + 1
    for _retry in range(6):
        run = jit_run
        if run is None:
            run = jax.jit(functools.partial(
                run_windows, n_windows=chunk_windows, window_us=window_us,
                max_events_per_window=cap, **step_opts))
        w = world0
        windows = 0
        for i in range(n_chunks):
            w, _ev = run(w)
            windows += chunk_windows
            if (i + 1) % probe_every == 0:
                complete, quiescent = jax.device_get(_status_flags(w))
                if complete and quiescent:
                    break
        sat = int(jax.device_get(w.n_saturated))
        if sat == 0:
            return w, windows * window_us / 1e6, _retry
        cap *= 2
        jit_run = None  # recompile with the doubled cap
    raise RuntimeError(
        f"flow engine still saturating after 6 cap doublings (cap={cap})")


# ---------------------------------------------------------------------------
# multichip: flow pairs never interact, so the world is EMBARRASSINGLY
# parallel over the pair axis — each device runs its slice of flows with
# the identical window kernel and zero collectives (the sharded analogue
# of the reference scaling tgen load across worker threads). split/merge
# preserve per-lane identity (iss, loss counters hash by ORIGINAL lane
# index), so a sharded run is BITWISE-identical to the single-device run
# on the same world — asserted by __graft_entry__.dryrun_multichip.
# ---------------------------------------------------------------------------

def split_flow_world(world: FlowWorld, n_shards: int):
    """[C]-leaved world -> [n_shards, C/n_shards]-leaved world, split on
    whole pairs (C must be divisible by 2*n_shards). Pure device-side
    reshapes — the multi-MB segment rings never round-trip through host
    memory. The accumulated saturation counter rides on shard 0 only,
    so a split -> run -> merge cycle adds per-shard contributions
    without multiplying the prior total by n_shards."""
    C = world.conn_t.shape[0]
    if C % (2 * n_shards):
        raise ValueError(f"{C} lanes not divisible into {n_shards} "
                         f"pair-aligned shards")

    def split(x):
        x = jnp.asarray(x)
        if x.ndim == 0:  # clock scalar replicates
            return jnp.full((n_shards,), x)
        return x.reshape((n_shards, C // n_shards) + x.shape[1:])

    out = jax.tree.map(split, world)
    sat0 = jnp.zeros((n_shards,), jnp.int32).at[0].set(world.n_saturated)
    return out._replace(n_saturated=sat0)


def merge_flow_world(sharded: FlowWorld) -> FlowWorld:
    """Inverse of split_flow_world; scalar leaves take shard 0 except
    n_saturated, which sums (any shard's saturation poisons the run)."""

    def merge(x):
        x = jnp.asarray(x)
        if x.ndim == 1:  # replicated scalar
            return x[0]
        return x.reshape((-1,) + x.shape[2:])

    out = jax.tree.map(merge, sharded)
    return out._replace(n_saturated=jnp.asarray(sharded.n_saturated).sum())


_sharded_run_cache: dict = {}


def run_windows_sharded(world: FlowWorld, n_windows: int, window_us: int,
                        n_shards: int | None = None, **opts):
    """run_windows over every visible device via pmap (one world shard
    per device, no cross-device communication — pairs are independent).
    Returns (merged world, [n_shards, n_windows] step counts). The
    pmapped callable caches per parameter set (mirroring
    run_to_completion's jit_run) so repeated calls don't retrace."""
    if n_shards is None:
        n_shards = jax.local_device_count()
    sharded = split_flow_world(world, n_shards)
    key = (n_windows, window_us, n_shards, tuple(sorted(opts.items())))
    run = _sharded_run_cache.get(key)
    if run is None:
        run = _sharded_run_cache[key] = jax.pmap(
            functools.partial(run_windows, n_windows=n_windows,
                              window_us=window_us, **opts))
    sharded, steps = run(sharded)
    return merge_flow_world(sharded), steps
