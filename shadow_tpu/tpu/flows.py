"""Device-resident flow plane: RTO retransmit + congestion backpressure.

The robustness layer the scenario corpus was explicitly missing
(`workloads/runner.py` used to declare its worlds "lossless — the phase
machine has no retransmit layer"; ROADMAP item 3): a per-flow SoA state
machine — cwnd/ssthresh Reno congestion, RFC 6298 RTO in integer
milliseconds, go-back-N timeout recovery — batched over every flow in
the fleet and threaded through the window drivers like the other device
planes. With it, a scenario runs under a non-zero loss matrix and
*completes*: lost data leaves the unacked range open, the RTO deadline
expires, and the range re-emits through the normal `plane.ingest`
egress path with exponential backoff — so retransmissions are ordinary
packets, visible to routing, AQM, faults, metrics, histograms, and the
flight recorder (`rto_fired` / `retransmit` hop kinds).

The congestion/RTT math is NOT re-derived here: the per-flow handlers
reuse `tpu/tcp.py`'s helpers verbatim (`_rtt_update` / `_rtt_backoff` /
`_rtt_reset_backoff` / `_set_rto` / `_cong_new_ack` / `_cong_timeout` /
`_arm_rto` / `_disarm_rto`) — `FlowState` carries the same field names
those helpers `_replace`, so the device TCP twin and the flow plane can
never drift apart on the estimator or Reno transitions (the same
one-copy rule as `_rto_from_estimate`'s twin comment).

Model (window-quantized, bitwise-deterministic):

- a *flow* is a directed (src host -> dst host) stream of fixed-size
  segments; one workload message = one segment (`pkt_bytes` is the
  message size), so the workload plane's dependency counts carry over
  unchanged — under ``transport: flows`` a phase credit is an IN-ORDER
  segment arrival (`rcv_nxt` advance), never a raw delivery, so
  duplicates from spurious retransmits can never double-credit a phase;
- flow packets ride the existing plane payload columns: ``sock`` is the
  flow tag (``(flow+1)*2 + kind``; kind 0 = data, 1 = ack — sock 0/1
  stay free so untagged traffic can never alias flow state), ``seq``
  is the flow-local segment index (data) or the cumulative ack value
  (acks). Identity is therefore stable across retransmissions: a
  sampled lost packet's flight-recorder trail reads
  drop_loss -> rto_fired -> retransmit -> delivered;
- the receiver keeps a ``recv_wnd``-segment bitmap (`rcv_bits`) of
  out-of-order arrivals — the unacked-range queue, SACK-shaped but
  cumulative-acked: in-order arrivals (and the buffered run behind a
  filled hole) advance `rcv_nxt`, arrivals past the window are
  discarded (the sender retransmits), duplicates re-arm the delayed
  ack. One cumulative ack per flow per window (window-quantized
  delayed ack), sent as a REAL 64-byte packet — acks face the same
  loss/AQM/faults as data; cumulative acking makes that safe;
- time is the window cadence: `clock_ms` advances by the window length
  each step, RTO deadlines are absolute virtual milliseconds against
  it (scenarios with flows must use windows >= 1 ms — validated at
  spec parse), and RTT samples are classic one-segment-at-a-time
  probes (`rtt_seq`) under Karn's rule (no samples while backed off;
  the probe is abandoned on timeout).

Presence contract: ``flows=None`` in `window_step` / `chain_windows`
compiles the plane out; threading tables whose flows are all inactive
(src == -1) is bitwise-invisible to simulation state, metrics, the
RNG stream, and every guard VIOLATION bit (tests/test_flows.py
parity; the SL501 obligation `window_step[flows]` proves the plane's
writes confine to the egress append columns + the overflow counter —
the same append-only theorem the workload generator carries). The one
deliberate guard-side delta: `flow_emit` counts its append into
`guards.checks` every window like any producer, so the
checks-evaluated TALLY grows with flows threaded — violations stay
identically zero, which is the load-bearing half. Like the workload plane, this rides
the WINDOW DRIVERS only (`tools/run_scenarios.py`); Manager-driven
runs warn (`flows:` config block, ConfigError under ``strict: true``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..guards import plane as guards_plane
from ..telemetry import flightrec as flightrec_mod
from ..telemetry.metrics import add_retransmits
from . import tcp as tcp_mod
from .plane import ingest as plane_ingest

#: ack/control segment wire size (matches the workload plane's
#: `compile.ACK_BYTES` closed-loop control messages)
ACK_BYTES = 64
#: per-flow data segments emitted per window (static lane cap; cwnd
#: beyond it carries to the next window — window-quantized self-pacing)
EMIT_CAP = 8
#: go-back-N receive window in segments: out-of-order arrivals past it
#: are discarded and recovered by retransmit; the sender clamps its
#: effective window to min(cwnd, recv_wnd)
RECV_WND = 64
#: sock values 0 and 1 are reserved (never a flow tag), so untagged
#: producers (PHOLD, direct-mode workloads) can never alias flow 0
SOCK_RESERVED = 2

I32_MAX = np.int32(2**31 - 1)


class FlowTables(NamedTuple):
    """Static per-flow tables (read-only on device), axis 0 = flow.

    ``lane_flow`` is the workload bridge: the [N, P, K] flow id of each
    send lane (compile.py fills it under ``transport: flows``), so the
    generator's emissions become `enqueue` stream extensions instead of
    raw `ingest_rows` appends. None for non-workload flow worlds."""

    src: jax.Array  # [F] int32 sending host (-1 = inactive slot)
    dst: jax.Array  # [F] int32 receiving host
    pkt_bytes: jax.Array  # [F] int32 wire bytes per data segment
    lane_flow: jax.Array | None = None  # [N, P, K] int32 (-1 = none)


class FlowState(NamedTuple):
    """Mutable per-flow SoA state, axis 0 = flow; every leaf [F] int32
    (bool where noted). Field names deliberately match `tpu/tcp.py`'s
    TcpPlane where the semantics match, so its RTT/Reno/timer helpers
    apply verbatim (`_replace`-compatible — do not rename)."""

    # sender: segment-index stream offsets
    snd_una: jax.Array  # lowest unacked segment
    snd_nxt: jax.Array  # next segment to transmit
    snd_max: jax.Array  # highest segment ever sent (+1): retx classifier
    stream_len: jax.Array  # segments enqueued on the flow
    # receiver
    rcv_nxt: jax.Array  # next in-order segment expected
    rcv_bits: jax.Array  # [F, recv_wnd] bool — OOO arrivals buffered
    # relative to rcv_nxt (bit 0 == rcv_nxt, always False after the
    # post-advance shift): the unacked-range queue
    ack_pending: jax.Array  # bool — delayed ack armed for this window
    # Reno congestion (tcp._cong_new_ack / _cong_timeout field set)
    cwnd: jax.Array
    ssthresh: jax.Array
    phase: jax.Array
    dup_acks: jax.Array
    avoid_acked: jax.Array
    # RFC 6298 estimator (tcp._rtt_update / _rtt_backoff field set)
    srtt_ms: jax.Array
    rttvar_ms: jax.Array
    rto_ms: jax.Array
    backoff_count: jax.Array
    # RTO timer (tcp._arm_rto / _disarm_rto field set)
    rto_gen: jax.Array
    rto_armed: jax.Array  # bool
    rto_deadline_ms: jax.Array  # absolute virtual ms
    # one-segment RTT probe (classic pre-timestamp TCP timing)
    rtt_seq: jax.Array  # segment being timed (-1 = none)
    rtt_sent_ms: jax.Array
    # counters (cumulative, int32 modular like every device counter)
    retransmit_count: jax.Array
    retransmitted_bytes: jax.Array
    rto_fired: jax.Array
    # virtual clock: absolute ms at the END of the last processed
    # window ([F]-replicated so the whole pytree stays flow-major for
    # the vmapped scalar handlers), plus the sub-millisecond carry so
    # variable-length windows (the chain_windows event-skipping
    # driver) never freeze the deadline clock — the _refill_tokens
    # remainder discipline; zero forever under ms-multiple cadences,
    # so fixed-cadence digests are untouched
    clock_ms: jax.Array
    clock_rem_ns: jax.Array


def make_flow_tables(src, dst, pkt_bytes, lane_flow=None) -> FlowTables:
    """Upload flow tables; copies (`jnp.array`) so a mutated numpy
    program can never alias device state (the workload/fault-schedule
    zero-copy rule)."""
    return FlowTables(
        src=jnp.array(src, jnp.int32),
        dst=jnp.array(dst, jnp.int32),
        pkt_bytes=jnp.array(pkt_bytes, jnp.int32),
        lane_flow=(jnp.array(lane_flow, jnp.int32)
                   if lane_flow is not None else None),
    )


def make_flow_state(n_flows: int, recv_wnd: int = RECV_WND) -> FlowState:
    """Fresh per-flow state: empty streams, initial cwnd/RTO.
    `recv_wnd` (static) sizes the receive bitmap — and therefore the
    sender's effective window clamp."""
    z = lambda: jnp.zeros((n_flows,), jnp.int32)
    f = lambda: jnp.zeros((n_flows,), bool)
    return FlowState(
        snd_una=z(), snd_nxt=z(), snd_max=z(), stream_len=z(),
        rcv_nxt=z(),
        rcv_bits=jnp.zeros((n_flows, recv_wnd), bool),
        ack_pending=f(),
        cwnd=jnp.full((n_flows,), tcp_mod.INITIAL_CWND, jnp.int32),
        ssthresh=jnp.full((n_flows,), tcp_mod.SSTHRESH_INF, jnp.int32),
        phase=z(), dup_acks=z(), avoid_acked=z(),
        srtt_ms=z(), rttvar_ms=z(),
        rto_ms=jnp.full((n_flows,), tcp_mod.RTO_INIT_MS, jnp.int32),
        backoff_count=z(),
        rto_gen=z(), rto_armed=f(), rto_deadline_ms=z(),
        rtt_seq=jnp.full((n_flows,), -1, jnp.int32), rtt_sent_ms=z(),
        retransmit_count=z(), retransmitted_bytes=z(), rto_fired=z(),
        clock_ms=z(), clock_rem_ns=z(),
    )


def n_flows(ft: FlowTables) -> int:
    return int(ft.src.shape[0])


def data_tag(flow_idx):
    """The `sock` tag of flow `flow_idx`'s data segments."""
    return (flow_idx + 1) * 2


def ack_tag(flow_idx):
    """The `sock` tag of flow `flow_idx`'s cumulative acks."""
    return (flow_idx + 1) * 2 + 1


def enqueue(ft: FlowTables, fs: FlowState, flow_ids, valid) -> FlowState:
    """Extend flow streams by one segment per valid lane (the workload
    generator's emission path under ``transport: flows``): `flow_ids`
    is any-shaped int32 flow indices (< 0 = no flow), `valid` the
    matching mask. Pure scatter-add of lane counts into `stream_len` —
    the segments go out through `flow_emit`'s cwnd-gated window."""
    F = ft.src.shape[0]
    ids = jnp.where(valid & (flow_ids >= 0), flow_ids, F).reshape(-1)
    counts = jnp.zeros((F,), jnp.int32).at[ids].add(1, mode="drop")
    return fs._replace(stream_len=fs.stream_len + counts)


# -- per-flow scalar handlers (vmapped; tcp.py helper reuse) ---------------


def _ack_one(s: FlowState, ack_val) -> FlowState:
    """Process one cumulative ack for one flow (mirrors the new-data-
    acked path of `tcp._process_ack`, minus the FSM): Reno advance via
    `_cong_new_ack`, Karn-gated RTT sample from the one-segment probe,
    backoff reset on forward progress, RTO re-arm/disarm."""
    now_ms = s.clock_ms
    has = ack_val > s.snd_una
    n_seg = jnp.maximum(ack_val - s.snd_una, 0)
    a = tcp_mod._cong_new_ack(s, n_seg)
    a = a._replace(snd_una=jnp.minimum(ack_val, a.stream_len))
    a = a._replace(snd_nxt=jnp.maximum(a.snd_nxt, a.snd_una))
    take_rtt = (a.rtt_seq >= 0) & (ack_val > a.rtt_seq)
    sampled = tcp_mod._rtt_update(a, now_ms - a.rtt_sent_ms)
    a = tcp_mod._sel(take_rtt & (a.backoff_count == 0), sampled, a)
    a = a._replace(rtt_seq=jnp.where(take_rtt, -1, a.rtt_seq))
    a = tcp_mod._rtt_reset_backoff(a)
    in_flight = a.snd_nxt > a.snd_una
    a = tcp_mod._sel(in_flight, tcp_mod._arm_rto(a, now_ms),
                     tcp_mod._disarm_rto(a))
    return tcp_mod._sel(has, a, s)


def _rto_one(s: FlowState) -> FlowState:
    """One expired RTO: exponential backoff + Reno timeout via the tcp
    twins, go-back-N rewind, probe abandoned (Karn), timer re-armed.
    Callers select with the `fired` mask."""
    b = tcp_mod._rtt_backoff(s)
    b = tcp_mod._cong_timeout(b)
    b = b._replace(snd_nxt=b.snd_una, rtt_seq=jnp.int32(-1),
                   rto_fired=b.rto_fired + 1)
    return tcp_mod._arm_rto(b, s.clock_ms)


# -- the window halves -----------------------------------------------------


def flow_recv(ft: FlowTables, fs: FlowState, delivered, window_ns):
    """Consume one window's `delivered` dict: advance the virtual flow
    clock by the window, credit in-order data arrivals, arm delayed
    acks, and fold cumulative acks into the sender state. Returns
    (fs', credits) — `credits[N]` is the per-receiving-host count of
    NEW in-order segments this window, the workload plane's
    acked-bytes phase credit under ``transport: flows``.

    Pure reads of `delivered` + `fs`: simulation state is untouched
    (the emission half lives in `flow_emit`)."""
    F = ft.src.shape[0]
    recv_wnd = fs.rcv_bits.shape[1]
    N, _CI = delivered["mask"].shape
    total_ns = fs.clock_rem_ns + jnp.int32(window_ns)
    fs = fs._replace(clock_ms=fs.clock_ms + total_ns // 1_000_000,
                     clock_rem_ns=total_ns % 1_000_000)

    mask = delivered["mask"]
    sock = delivered["sock"]
    seq = delivered["seq"]
    psrc = delivered["src"]
    rows = jnp.arange(N, dtype=jnp.int32)[:, None]
    f_id = (sock >> 1) - 1
    kind_ack = (sock & 1) == 1
    tagged = mask & (sock >= SOCK_RESERVED) & (f_id < F)
    f_safe = jnp.clip(f_id, 0, F - 1)
    # a tag only counts when the packet's (row, src) matches the
    # flow's endpoints — untagged/foreign traffic can never mutate a
    # flow, which is what makes all-inactive presence bitwise-inert
    is_data = (tagged & ~kind_ack & (ft.dst[f_safe] == rows)
               & (ft.src[f_safe] == psrc))
    is_ackp = (tagged & kind_ack & (ft.src[f_safe] == rows)
               & (ft.dst[f_safe] == psrc))

    def do_recv(fs):
        # receiver: fold this window's arrivals into the persistent
        # receive bitmap (duplicates are idempotent True-sets,
        # out-of-window arrivals drop — the sender retransmits them),
        # advance rcv_nxt through the leading contiguous run — a
        # filled hole releases everything buffered behind it — then
        # shift the bitmap left so bit 0 tracks the new rcv_nxt
        off = seq - fs.rcv_nxt[f_safe]
        in_wnd = is_data & (off >= 0) & (off < recv_wnd)
        flat_idx = jnp.where(in_wnd, f_safe * recv_wnd + off,
                             F * recv_wnd)
        present = jnp.zeros((F * recv_wnd,), jnp.int32).at[
            flat_idx.reshape(-1)].max(
            1, mode="drop").reshape(F, recv_wnd)
        bits = fs.rcv_bits | (present != 0)
        adv = jnp.cumprod(bits.astype(jnp.int32), axis=1) \
            .sum(axis=1).astype(jnp.int32)
        shift_idx = jnp.arange(recv_wnd, dtype=jnp.int32)[None, :] \
            + adv[:, None]
        bits_shifted = jnp.take_along_axis(
            bits, jnp.clip(shift_idx, 0, recv_wnd - 1), axis=1) \
            & (shift_idx < recv_wnd)
        # ANY data arrival (in-order, dup, or out-of-window) re-arms
        # the delayed ack — dup data after a lost ack must re-elicit
        # it
        any_data = jnp.zeros((F,), jnp.int32).at[
            jnp.where(is_data, f_safe, F).reshape(-1)].add(
            1, mode="drop") > 0
        fs = fs._replace(rcv_nxt=fs.rcv_nxt + adv,
                         rcv_bits=bits_shifted,
                         ack_pending=fs.ack_pending | any_data)
        active = ft.src >= 0
        credits = jnp.zeros((N,), jnp.int32).at[
            jnp.where(active, ft.dst, N)].add(adv, mode="drop")

        # sender: cumulative ack = max delivered ack value per flow
        ack_val = jnp.full((F,), -1, jnp.int32).at[
            jnp.where(is_ackp, f_safe, F).reshape(-1)].max(
            jnp.where(is_ackp, seq, -1).reshape(-1), mode="drop")
        fs = jax.vmap(_ack_one)(fs, ack_val)
        return fs, credits

    # idle gate (the ingest_rows gate_idle contract): a window with no
    # tagged deliveries leaves every flow field untouched — bit 0 of
    # rcv_bits is False by the shift invariant, so adv is zero, the
    # shift is the identity, ack_val stays -1, and every _ack_one is
    # the identity select — so both branches are bitwise-equal for
    # every input and the gate only skips the scatter/vmap cost of
    # quiet (or flow-free) windows. PROVEN per build: the SL505
    # obligation `flow_recv[idle]` (analysis/condeq.py) evaluates both
    # branches over a boundary-value lattice (incl. untagged and
    # endpoint-mismatched tagged traffic) on every CI run
    return jax.lax.cond(
        (is_data | is_ackp).any(), do_recv,
        lambda fs: (fs, jnp.zeros((N,), jnp.int32)), fs)


def flow_emit(ft: FlowTables, fs: FlowState, state, *,
              emit_cap: int = EMIT_CAP,
              metrics=None, guards=None, flightrec=None):
    """Fire expired RTO deadlines (go-back-N + backoff), then emit this
    window's sends — up to `emit_cap` cwnd-gated data segments plus one
    cumulative delayed ack per flow — through ONE `plane.ingest` append
    (the normal egress path: the packets face routing, loss, AQM,
    faults, and every observability plane like any other traffic).

    `metrics` / `guards` thread the ingest append exactly as every
    producer does; `metrics` additionally folds this window's
    retransmitted-segment counts into the per-host `retransmits`
    field (the counter `telemetry.add_retransmits` owns). `flightrec`
    records `rto_fired` / `retransmit` hops for sampled flows/segments
    (identity = (src host, flow seq) — the SAME identity the lost
    original carried, so its trail links). Returns
    (state', fs'[, metrics'][, guards'][, flightrec'])."""
    F = ft.src.shape[0]
    recv_wnd = fs.rcv_bits.shape[1]
    N = state.eg_dst.shape[0]
    active = ft.src >= 0
    now_ms = fs.clock_ms

    una_before = fs.snd_una
    fired = (fs.rto_armed & active & (fs.snd_nxt > fs.snd_una)
             & (now_ms >= fs.rto_deadline_ms))
    fs = tcp_mod.sel_batched(fired, jax.vmap(_rto_one)(fs), fs)

    # emission lanes: [F, emit_cap] data + [F] acks
    wnd = jnp.minimum(fs.cwnd, jnp.int32(recv_wnd))
    limit = jnp.minimum(fs.stream_len, fs.snd_una + wnd)
    n_emit = jnp.where(active,
                       jnp.clip(limit - fs.snd_nxt, 0, emit_cap), 0)
    lane = jnp.arange(emit_cap, dtype=jnp.int32)[None, :]
    emit_seq = fs.snd_nxt[:, None] + lane
    data_valid = lane < n_emit[:, None]
    retx_lane = data_valid & (emit_seq < fs.snd_max[:, None])
    retx_n = retx_lane.sum(axis=1, dtype=jnp.int32)
    retx_b = jnp.where(retx_lane, ft.pkt_bytes[:, None], 0) \
        .sum(axis=1, dtype=jnp.int32)
    new_nxt = fs.snd_nxt + n_emit
    # RTT probe: time the first never-before-sent segment of the batch
    # (Karn: never while backed off, never a retransmission)
    probe = ((fs.rtt_seq < 0) & (n_emit > 0) & (fs.backoff_count == 0)
             & (fs.snd_nxt >= fs.snd_max))
    arm = (n_emit > 0) & ~fs.rto_armed
    ack_valid = fs.ack_pending & active
    fs = fs._replace(
        snd_nxt=new_nxt,
        snd_max=jnp.maximum(fs.snd_max, new_nxt),
        rtt_seq=jnp.where(probe, fs.snd_nxt, fs.rtt_seq),
        rtt_sent_ms=jnp.where(probe, now_ms, fs.rtt_sent_ms),
        retransmit_count=fs.retransmit_count + retx_n,
        retransmitted_bytes=fs.retransmitted_bytes + retx_b,
        rto_gen=fs.rto_gen + arm.astype(jnp.int32),
        rto_armed=fs.rto_armed | arm,
        rto_deadline_ms=jnp.where(arm, now_ms + fs.rto_ms,
                                  fs.rto_deadline_ms),
        ack_pending=fs.ack_pending & ~ack_valid,
    )

    flow_idx = jnp.arange(F, dtype=jnp.int32)
    rep = lambda a: jnp.repeat(a, emit_cap)
    src_b = jnp.concatenate([rep(ft.src), ft.dst])
    dst_b = jnp.concatenate([rep(ft.dst), ft.src])
    bytes_b = jnp.concatenate([rep(ft.pkt_bytes),
                               jnp.full((F,), ACK_BYTES, jnp.int32)])
    seq_b = jnp.concatenate([emit_seq.reshape(-1), fs.rcv_nxt])
    sock_b = jnp.concatenate([rep(data_tag(flow_idx)),
                              ack_tag(flow_idx)])
    valid_b = jnp.concatenate([data_valid.reshape(-1), ack_valid])

    # idle gate, same contract as ingest_rows' gate_idle: an append
    # with zero valid lanes is the bitwise identity (rows keep their
    # front-packed content, overflow delta is zero), so the branches
    # are equal for every input and the gate only trades the dominant
    # flat-merge cost on quiet windows — which is also what makes the
    # all-inactive presence probe (window_step_flows) cheap. Metrics
    # and guards apply OUTSIDE the gate from the state's own overflow
    # counter delta (the ingest_rows discipline), so the guard checks
    # counter advances identically through both branches. PROVEN per
    # build: the SL505 obligation `flow_emit[idle]`
    # (analysis/condeq.py), with full-ring lattice points pinning the
    # zero-overflow edge.
    pre_occ = state.eg_valid.sum(axis=1, dtype=jnp.int32)
    pre_ovf = state.n_overflow_dropped
    state = jax.lax.cond(
        valid_b.any(),
        lambda st: plane_ingest(
            st, src_b, dst_b, bytes_b, seq_b, seq_b,
            jnp.zeros_like(valid_b), valid=valid_b, sock=sock_b),
        lambda st: st, state)
    ovf_delta = state.n_overflow_dropped - pre_ovf
    if guards is not None:
        incoming = jnp.zeros((N,), jnp.int32).at[
            jnp.where(valid_b, jnp.clip(src_b, 0, N - 1), N)].add(
            1, mode="drop")
        guards = guards_plane.check_ingest(
            guards, occ_before=pre_occ,
            occ_after=state.eg_valid.sum(axis=1, dtype=jnp.int32),
            incoming=incoming, overflow=ovf_delta)
    if metrics is not None:
        per_host = jnp.zeros((N,), jnp.int32).at[
            jnp.where(active, ft.src, N)].add(retx_n, mode="drop")
        metrics = add_retransmits(
            metrics._replace(
                drop_ring_full=metrics.drop_ring_full + ovf_delta),
            per_host)
    if flightrec is not None:
        samp_f = flightrec_mod.sample_mask(flightrec, ft.src, una_before)
        samp_d = flightrec_mod.sample_mask(
            flightrec, rep(ft.src), emit_seq.reshape(-1))
        kinds = jnp.concatenate([
            jnp.full((F,), flightrec_mod.HOP_RTO_FIRED, jnp.int32),
            jnp.full((F * emit_cap,), flightrec_mod.HOP_RETRANSMIT,
                     jnp.int32)])
        flightrec = flightrec_mod.record_events(
            flightrec, kinds,
            jnp.concatenate([ft.src, rep(ft.src)]),
            jnp.concatenate([una_before, emit_seq.reshape(-1)]),
            jnp.concatenate([ft.dst, rep(ft.dst)]),
            jnp.zeros((F + F * emit_cap,), jnp.int32),
            jnp.concatenate([fired & samp_f,
                             retx_lane.reshape(-1) & samp_d]))
    out = (state, fs)
    if metrics is not None:
        out += (metrics,)
    if guards is not None:
        out += (guards,)
    if flightrec is not None:
        out += (flightrec,)
    return out


def flow_step(ft: FlowTables, fs: FlowState, state, delivered,
              window_ns, *, emit_cap: int = EMIT_CAP,
              metrics=None, guards=None, flightrec=None):
    """The one-call form `window_step(flows=...)` / `chain_windows`
    compose: `flow_recv` + `flow_emit` back to back. Drivers that
    interleave the workload generator between the halves (the scenario
    runner: recv -> credit the phase machine -> enqueue -> emit) call
    the halves directly. Returns
    (state', fs', credits[, metrics'][, guards'][, flightrec'])."""
    fs, credits = flow_recv(ft, fs, delivered, window_ns)
    out = flow_emit(ft, fs, state, emit_cap=emit_cap,
                    metrics=metrics, guards=guards,
                    flightrec=flightrec)
    return (out[0], out[1], credits, *out[2:])


def next_deadline_rel_ns(ft: FlowTables, fs: FlowState) -> jax.Array:
    """Earliest pending RTO deadline in ns RELATIVE to the flow clock
    (= the end of the last processed window), I32_MAX when no armed
    timer guards outstanding data. The event-skipping chain driver
    (`plane.chain_windows`) folds this into its next-event reduction
    so an idle chain wakes AT the deadline instead of sleeping through
    a pending retransmission. Already-due deadlines report 0 (fire in
    the next window); the ms->ns conversion clamps to the int32
    window budget (a far-off deadline just reads 'beyond the chain
    horizon', which is all the reduction needs — the clamp is part of
    the SL506 range proof of the chain wake arithmetic,
    analysis/ranges.py `chain_windows[flows]`)."""
    active = (ft.src >= 0) & fs.rto_armed & (fs.snd_nxt > fs.snd_una)
    rel_ms = jnp.clip(fs.rto_deadline_ms - fs.clock_ms, 0,
                      (I32_MAX // 2) // 1_000_000)
    rel = jnp.where(active, rel_ms * 1_000_000 - fs.clock_rem_ns,
                    I32_MAX)
    return jnp.maximum(rel.min(), 0).astype(jnp.int32)


# -- host-side report helpers ----------------------------------------------


def retransmits_by_host(ft: FlowTables, fs: FlowState,
                        n_hosts: int) -> jax.Array:
    """[N] per-sending-host cumulative retransmitted segments (the
    `tpu/tcp.retransmits_by_host` twin for the flow plane)."""
    active = ft.src >= 0
    return jnp.zeros((n_hosts,), jnp.int32).at[
        jnp.where(active, ft.src, n_hosts)].add(
        fs.retransmit_count, mode="drop")


def flow_totals(ft: FlowTables, fs: FlowState) -> dict:
    """JSON-ready fleet totals for run records (host-side pull)."""
    active = np.asarray(ft.src) >= 0
    g = lambda a: int(np.asarray(a)[active].astype(np.int64).sum())
    return {
        "flows": int(active.sum()),
        "segments_enqueued": g(fs.stream_len),
        "segments_acked": g(fs.snd_una),
        "retransmits": g(fs.retransmit_count),
        "retransmitted_bytes": g(fs.retransmitted_bytes),
        "rto_fired": g(fs.rto_fired),
    }
