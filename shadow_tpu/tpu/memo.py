"""Steady-state memoization + fast-forward for the chained drivers.

ROADMAP item 3, grounded in PAPERS.md "Supercharging Packet-level
Network Simulation of Large Model Training via Memoization and
Fast-Forwarding" (arxiv 2602.10615): periodic traffic revisits the
same simulation state, and re-executing a window chain whose inputs
are bitwise-identical to one already executed is pure waste. This
module gives `elastic.drive_chained_windows` a chain-granular memo
table:

- at every chain boundary the FULL carry (net-plane state + every
  extras plane: workload, metrics, guards, histograms, flight
  recorder, flows) is snapshotted to host and digested into a memo
  key, together with the span length/alignment, the caller's static
  salt (phase-program digest, world fingerprint, knob settings) and
  the per-span salt (the fault-schedule span fingerprint);
- a key hit replays the recorded post-chain state instead of
  executing: keyed leaves are substituted byte-for-byte, declared
  modular-counter leaves (`COUNTER_LEAVES`) get the recorded uint32
  delta wrap-added onto the live value (`telemetry/harvest.py`
  `counter_delta`/`apply_counter_delta` — the same modular discipline
  the harvester's `unwrap_u32` relies on);
- a miss executes normally and records the (post snapshot, counter
  deltas) pair, bounded by an LRU byte budget.

Soundness contract (tests/test_memo.py pins every clause):

- **Every leaf is covered.** The carry walk visits every array leaf
  and classifies it keyed-by-default; ONLY leaves explicitly declared
  in `COUNTER_LEAVES` — observability accumulators proven
  presence-invisible by the SL501 taint proofs, plus the flow plane's
  virtual clock — are excluded from the digest and delta-replayed. A
  new plane leaf therefore lands IN the key (fewer hits, never a
  stale replay) — the drift-guard discipline.
- **Replay is bitwise.** A hit requires the canonicalized pre-carry,
  span shape, and salts to match, so the recorded execution IS this
  execution: keyed substitution and modular delta-apply reproduce the
  cold run's post-carry exactly (canonical-digest parity across the
  golden corpus is the gating witness; dead net-plane lanes are
  outside the contract, exactly as for elastic growth).
- **Unstable spans are never recorded.** Spans that stamp
  non-counter accumulators from excluded inputs — a guard's first
  violation window (stamps `GuardState.windows`), a flight-recorder
  event append (stamps `FlightRecArrays.win` into the ring) — are
  refused at record time (`STABILITY_FIELDS`), so replayed spans are
  always event-free with respect to those planes.
- **Round-index sensitivity is declared by the caller.** The default
  `key_extra` folds the absolute start round into every key (safe: no
  cross-span hits); callers that can PROVE round-translation
  invariance (the corpus runner: no live workload host means nothing
  stamps `done_win`) override it with their predicate.

Host-sync note (SL603): `snapshot()` is ONE `jax.device_get` per
chain boundary — the same sanctioned cadence as the telemetry
harvester and the elastic overflow readback. Between consecutive
hits the driver never touches the device at all (the fast-forward
fast path): replay is host-side numpy, uploaded lazily only when a
miss must execute or an `on_chain` hook needs device values.

The cache OUTLIVES a driver invocation: `ChainMemo.save/load`
round-trip the recorded entries through the single-file atomic
checkpoint format (`faults/checkpoint.write_npz_checkpoint` — tmp +
fsync + rename, per-array sha256, schema stamp), and `spill/absorb`
embed the same payload inside a full-run checkpoint
(`faults/runstate.py`). Soundness across runs is the same argument as
within a run: every key digests the full canonical carry plus the
caller's static salt, so a persisted entry can only hit when the
world, knobs, and carry bytes are identical — and `absorb` refuses a
cache whose salt fingerprint disagrees (the closures the entries
summarize — params, program tables, RNG root — are exactly what the
salt names). Entries restored from disk are flagged `persisted`;
`stats()["persisted_hits"]` counts hits served by them (the CI
cross-run witness).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ..telemetry.harvest import apply_counter_delta, counter_delta

__all__ = [
    "COUNTER_LEAVES", "MEMO_SCHEMA", "STABILITY_FIELDS", "ChainMemo",
    "walk_carry",
]

#: schema stamp for persisted caches (`write_npz_checkpoint` refuses a
#: mismatch before any entry is trusted)
MEMO_SCHEMA = "chainmemo-v1"

#: instance counters `spill` serializes and `absorb(restore=True)`
#: reproduces verbatim — the memoized kill/resume parity surface
_COUNTER_ATTRS = (
    "lookups", "hits", "misses", "records", "evictions",
    "unstable_skips", "oversize_skips", "fast_forwarded_windows",
    "peak_bytes", "loaded_entries", "persisted_hits",
)

#: (NamedTuple class name) -> field names excluded from the memo key
#: and replayed as modular uint32 deltas. Declaration rules:
#: observability accumulators that are presence-invisible to the
#: simulation (the SL501 taint-proof set: metrics, histograms, guard
#: tallies, flight-recorder cursors) plus counters the step only ever
#: wrap-adds (net-plane totals) and the flow plane's virtual clock
#: (translation-covariant; folded raw into the key by the caller's
#: `key_extra` whenever any flow could read it). EVERYTHING else is
#: keyed byte-for-byte — the safe default a new plane leaf gets.
COUNTER_LEAVES: dict[str, frozenset[str]] = {
    "NetPlaneState": frozenset({
        "n_sent", "n_loss_dropped", "n_overflow_dropped",
        "n_delivered", "n_fault_dropped",
    }),
    # all of PlaneMetrics EXCEPT the high-water marks: maxima are not
    # delta-applicable (harvest.MAX_FIELDS aggregates them with max),
    # and in steady state they are constant — so they stay keyed and
    # replay by substitution
    "PlaneMetrics": frozenset({
        "pkts_out", "bytes_out", "pkts_in", "bytes_in",
        "drop_ring_full", "drop_qdisc", "drop_loss", "drop_fault",
        "retransmits", "windows", "events", "sort_slots",
    }),
    "PlaneHistograms": frozenset({
        "hist_delivery_ns", "hist_sojourn_ns", "hist_qdepth",
    }),
    # violations/first_window/flags stay KEYED (latches, constant in
    # steady state) and double as the record-stability witness below
    "GuardState": frozenset({"windows", "checks"}),
    # the ring contents (ev_*) stay keyed; an event append moves the
    # cursor, which refuses the record (STABILITY_FIELDS)
    "FlightRecArrays": frozenset({"cursor", "win"}),
    "FlowState": frozenset({
        "retransmit_count", "retransmitted_bytes", "rto_fired",
        "clock_ms",
    }),
}

#: (NamedTuple class name) -> fields that must be byte-identical
#: between a span's pre and post snapshots for the span to be
#: RECORDED. These are keyed leaves whose in-span writes embed values
#: of excluded leaves (GuardState.first_window stamps .windows; the
#: flight recorder's ev_win stamps .win at the .cursor position) — a
#: span that moved them is not translation-stable and must never be
#: replayed elsewhere.
STABILITY_FIELDS: dict[str, frozenset[str]] = {
    "GuardState": frozenset({"violations", "first_window", "flags"}),
    "FlightRecArrays": frozenset({"cursor"}),
}

_I32_MAX = np.int32(2**31 - 1)
_NO_CLAMP = np.int32(-(2**30))  # tpu.plane.NO_CLAMP


def _canonical_netplane_np(state):
    """Host-side mirror of `elastic.canonical_state`: normalize dead
    ring lanes to the `make_state` defaults so two carries differing
    only in compaction garbage digest equal (tests/test_memo.py pins
    byte-parity against the device canonicalizer)."""
    ev = np.asarray(state.eg_valid)
    iv = np.asarray(state.in_valid)
    w = lambda mask, arr, fill: np.where(
        mask, arr, np.asarray(fill, dtype=np.asarray(arr).dtype))
    return state._replace(
        eg_dst=w(ev, state.eg_dst, -1),
        eg_bytes=w(ev, state.eg_bytes, 0),
        eg_prio=w(ev, state.eg_prio, _I32_MAX),
        eg_seq=w(ev, state.eg_seq, 0),
        eg_ctrl=np.asarray(state.eg_ctrl) & ev,
        eg_tsend=w(ev, state.eg_tsend, 0),
        eg_clamp=w(ev, state.eg_clamp, _NO_CLAMP),
        eg_sock=w(ev, state.eg_sock, 0),
        in_src=w(iv, state.in_src, -1),
        in_bytes=w(iv, state.in_bytes, 0),
        in_seq=w(iv, state.in_seq, 0),
        in_sock=w(iv, state.in_sock, 0),
        in_deliver_rel=w(iv, state.in_deliver_rel, _I32_MAX),
    )


#: class name -> host-side canonicalizer applied before DIGESTING (the
#: recorded post snapshots stay raw — replay substitutes real bytes)
_CANONICALIZERS: dict[str, Callable] = {
    "NetPlaneState": _canonical_netplane_np,
}


def _is_namedtuple(node) -> bool:
    return isinstance(node, tuple) and hasattr(node, "_fields")


def walk_carry(carry, *, canonical: bool = False):
    """Flatten a chain carry into ``[(owner, field, np.ndarray)]`` in
    deterministic traversal order. `owner` is the immediate NamedTuple
    class name ("" for anonymous tuple positions — always keyed).
    With ``canonical=True``, registered canonicalizers rewrite their
    node before its leaves are emitted (digest view only). None
    subtrees (disabled presence planes) vanish, exactly as they do in
    `jax.tree` flattening."""
    out: list[tuple[str, str, np.ndarray]] = []

    def rec(node, owner: str, name: str):
        if node is None:
            return
        if _is_namedtuple(node):
            cls = type(node).__name__
            if canonical and cls in _CANONICALIZERS:
                node = _CANONICALIZERS[cls](node)
            for fname, val in zip(node._fields, node):
                rec(val, cls, fname)
            return
        if isinstance(node, (tuple, list)):
            for i, val in enumerate(node):
                rec(val, owner, f"{name}[{i}]")
            return
        if isinstance(node, dict):
            for k in sorted(node):
                rec(node[k], owner, f"{name}.{k}")
            return
        out.append((owner, name, np.asarray(node)))

    rec(carry, "", "")
    return out


def classify(owner: str, field: str) -> str:
    """'counter' for declared modular leaves, 'keyed' for everything
    else (the safe default a new plane leaf gets)."""
    if field in COUNTER_LEAVES.get(owner, ()):  # pragma: no branch
        return "counter"
    return "keyed"


class _Entry:
    __slots__ = ("post_keyed", "deltas", "nbytes", "span_len", "hits",
                 "persisted")

    def __init__(self, post_keyed, deltas, nbytes, span_len,
                 persisted=False):
        self.post_keyed = post_keyed
        self.deltas = deltas
        self.nbytes = nbytes
        self.span_len = span_len
        self.hits = 0
        self.persisted = persisted


class ChainMemo:
    """Chain-boundary memo table for `drive_chained_windows`.

    ``salt`` folds the caller's static world identity into every key
    (scenario fingerprint, program digest, knob settings — everything
    the chain closure captures that the carry does not show).
    ``key_extra(carry_host, r0)`` returns extra key bytes computed
    from the live carry: the default folds the absolute start round
    (safe — no cross-span hits); callers with a proven
    round-translation-invariance predicate override it.
    ``min_repeat`` is how many times a key must MISS before its span
    is recorded (1 = record on first sight). ``max_bytes`` bounds the
    recorded bytes, LRU-evicted."""

    def __init__(self, *, max_bytes: int = 64 << 20,
                 min_repeat: int = 1, salt: bytes = b"",
                 key_extra: Optional[Callable] = None):
        if max_bytes < 1:
            raise ValueError("memo max_bytes must be >= 1")
        if min_repeat < 1:
            raise ValueError("memo min_repeat must be >= 1")
        self.max_bytes = int(max_bytes)
        self.min_repeat = int(min_repeat)
        self.salt = bytes(salt)
        self.key_extra = (key_extra if key_extra is not None
                          else (lambda carry, r0: b"r0:%d" % r0))
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._seen: OrderedDict[str, int] = OrderedDict()
        self.bytes_cached = 0
        self.lookups = 0
        self.hits = 0
        self.misses = 0
        self.records = 0
        self.evictions = 0
        self.unstable_skips = 0
        self.oversize_skips = 0
        self.fast_forwarded_windows = 0
        self.peak_bytes = 0
        self.loaded_entries = 0
        self.persisted_hits = 0

    # -- snapshot / key ---------------------------------------------------

    def snapshot(self, state, extras):
        """Pull the full carry to host: ONE `jax.device_get` per chain
        boundary — the sanctioned harvest-cadence sync (SL603)."""
        import jax

        return jax.device_get((state, extras))

    def key(self, carry_host, r0: int, r1: int,
            span_salt: bytes = b""):
        """Digest the canonicalized carry + span shape + salts.
        Returns ``(hexdigest, raw_walk)`` — the raw (uncanonicalized)
        walk is what `record`/`replay` consume, so the pre-walk rides
        along for free."""
        h = hashlib.sha256()
        h.update(self.salt)
        h.update(b"|span:%d" % (r1 - r0))
        h.update(b"|first:%d" % int(r0 == 0))
        h.update(b"|" + bytes(span_salt))
        h.update(b"|" + bytes(self.key_extra(carry_host, r0)))
        for owner, field, leaf in walk_carry(carry_host,
                                             canonical=True):
            h.update(b"|%s.%s:%s:%s:" % (
                owner.encode(), field.encode(),
                str(leaf.dtype).encode(), repr(leaf.shape).encode()))
            if classify(owner, field) == "keyed":
                h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest(), walk_carry(carry_host)

    # -- lookup / record / replay ----------------------------------------

    def lookup(self, key: str):
        self.lookups += 1
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            self._seen[key] = self._seen.get(key, 0) + 1
            self._seen.move_to_end(key)
            while len(self._seen) > 65536:
                self._seen.popitem(last=False)
            return None
        self.hits += 1
        entry.hits += 1
        if entry.persisted:
            self.persisted_hits += 1
        self.fast_forwarded_windows += entry.span_len
        self._entries.move_to_end(key)
        return entry

    def record(self, key: str, pre_walk, post_carry_host, *,
               span_len: int) -> bool:
        """Store the span's replay data unless (a) the key hasn't
        missed `min_repeat` times yet, (b) the span moved a stability
        witness (never replayable), or (c) the entry alone exceeds
        the byte budget."""
        if key in self._entries or self._seen.get(key, 0) < self.min_repeat:
            return False
        post_walk = walk_carry(post_carry_host)
        if len(post_walk) != len(pre_walk):
            # an elastic growth changed the carry's shape mid-span;
            # keys include shapes, so the entry is still sound — but
            # delta alignment needs matched walks, so pair by name
            pre_by = {(o, f): a for o, f, a in pre_walk}
        else:
            pre_by = None
        for owner, field, post in post_walk:
            if field in STABILITY_FIELDS.get(owner, ()):
                pre = (pre_by[(owner, field)] if pre_by is not None
                       else pre_walk[[i for i, (o, f, _a) in
                                      enumerate(post_walk)
                                      if (o, f) == (owner, field)][0]][2])
                if not np.array_equal(pre, post):
                    self.unstable_skips += 1
                    return False
        post_keyed = []
        deltas = []
        nbytes = 0
        for i, (owner, field, post) in enumerate(post_walk):
            if classify(owner, field) == "counter":
                if pre_by is not None:
                    pre = pre_by[(owner, field)]
                else:
                    pre = pre_walk[i][2]
                d = counter_delta(pre, post)
                post_keyed.append(None)
                deltas.append(d)
                nbytes += d.nbytes
            else:
                arr = np.ascontiguousarray(post)
                post_keyed.append(arr)
                deltas.append(None)
                nbytes += arr.nbytes
        if nbytes > self.max_bytes:
            self.oversize_skips += 1
            return False
        while self.bytes_cached + nbytes > self.max_bytes and self._entries:
            _k, old = self._entries.popitem(last=False)
            self.bytes_cached -= old.nbytes
            self.evictions += 1
        self._entries[key] = _Entry(post_keyed, deltas, nbytes, span_len)
        self.bytes_cached += nbytes
        self.peak_bytes = max(self.peak_bytes, self.bytes_cached)
        self.records += 1
        self._seen.pop(key, None)
        return True

    def replay(self, entry: _Entry, pre_carry_host):
        """Rebuild the post-chain carry on host: keyed leaves from the
        recorded snapshot, counter leaves wrap-added (bitwise-equal to
        re-execution — the golden-corpus parity gate's contract)."""
        it = iter(range(len(entry.post_keyed)))

        def rec(node):
            if node is None:
                return None
            if _is_namedtuple(node):
                return type(node)(*(rec(v) for v in node))
            if isinstance(node, tuple):
                return tuple(rec(v) for v in node)
            if isinstance(node, list):
                return [rec(v) for v in node]
            if isinstance(node, dict):
                return {k: rec(node[k]) for k in sorted(node)}
            i = next(it)
            post = entry.post_keyed[i]
            if post is not None:
                return post
            return apply_counter_delta(node, entry.deltas[i])

        return rec(pre_carry_host)

    def to_device(self, carry_host):
        """Upload a host carry back to device arrays (lazy: only when
        a miss must execute or an on_chain hook needs device values)."""
        import jax
        import jax.numpy as jnp

        return jax.tree.map(jnp.asarray, carry_host)

    # -- persistence ------------------------------------------------------

    def _salt_sha(self) -> str:
        return hashlib.sha256(self.salt).hexdigest()

    def spill(self, prefix: str = "") -> tuple[dict, dict]:
        """Serialize the cache: ``(meta_fragment, arrays)``.

        Each entry's leaves become arrays named
        ``{prefix}e{j}.post.{i}`` (keyed snapshot) or
        ``{prefix}e{j}.delta.{i}`` (modular counter delta); the meta
        fragment records insertion order, keys, span lengths, and a
        sha256 of the salt (the world identity the keys were minted
        under). Used standalone by `save` and embedded by
        `faults/runstate.py` full-run checkpoints."""
        arrays: dict[str, np.ndarray] = {}
        entries_meta = []
        for j, (key, e) in enumerate(self._entries.items()):
            leaves = []
            for i, post in enumerate(e.post_keyed):
                if post is not None:
                    arrays[f"{prefix}e{j}.post.{i}"] = post
                    leaves.append("post")
                else:
                    arrays[f"{prefix}e{j}.delta.{i}"] = e.deltas[i]
                    leaves.append("delta")
            entries_meta.append({"key": key, "span_len": int(e.span_len),
                                 "hits": int(e.hits), "leaves": leaves,
                                 "persisted": bool(e.persisted)})
        meta = {
            "salt_sha256": self._salt_sha(),
            "entries": entries_meta,
            "max_bytes": int(self.max_bytes),
            "min_repeat": int(self.min_repeat),
            # the full counter census + pre-record miss counts: what
            # `absorb(restore=True)` needs to reproduce this instance
            # EXACTLY (the memoized kill/resume byte-parity contract —
            # a resumed run's memo report matches the uninterrupted
            # twin's, entry hits and all)
            "counters": {f: int(getattr(self, f))
                         for f in _COUNTER_ATTRS},
            "seen": {k: int(v) for k, v in self._seen.items()},
        }
        return meta, arrays

    def absorb(self, meta: dict, arrays: dict, prefix: str = "",
               source: str = "<memo>", restore: bool = False) -> int:
        """Inverse of `spill`. Two modes:

        - cross-run import (default): re-admit entries flagged
          ``persisted`` with hit counts restarting at 0 — a later hit
          counts toward `persisted_hits`, the ROADMAP-3 proof surface.
        - ``restore=True`` (full-run checkpoint resume): reproduce the
          spilled instance EXACTLY — per-entry hits and persisted
          flags, every counter, and the pre-record miss census — so a
          resumed run's memo report is byte-identical to the
          uninterrupted twin's.

        Refuses — as `CheckpointError` — a cache minted under a
        different salt (different world/knobs: its keys could never
        soundly hit) or one missing a serialized leaf. The caller must
        also keep its ``key_extra`` policy consistent across runs;
        that closure is not serializable, so it is a documented
        contract, not a check. Returns the number of entries admitted
        (LRU budget applies)."""
        from ..faults.checkpoint import CheckpointError

        want_salt = meta.get("salt_sha256")
        if want_salt != self._salt_sha():
            raise CheckpointError(
                f"{source}: memo cache salt_sha256 {str(want_salt)[:12]}... "
                f"does not match this run's salt {self._salt_sha()[:12]}... "
                f"— the cache was recorded for a different world/knob "
                f"configuration; refusing to replay it")
        loaded = 0
        for j, em in enumerate(meta.get("entries", ())):
            key = em["key"]
            if key in self._entries:
                continue
            post_keyed, deltas, nbytes = [], [], 0
            for i, kind in enumerate(em["leaves"]):
                name = f"{prefix}e{j}.{kind}.{i}"
                if name not in arrays:
                    raise CheckpointError(
                        f"{source}: memo entry {j} is missing serialized "
                        f"leaf {name!r}")
                arr = np.asarray(arrays[name])
                if kind == "post":
                    post_keyed.append(arr)
                    deltas.append(None)
                else:
                    post_keyed.append(None)
                    deltas.append(arr)
                nbytes += arr.nbytes
            if nbytes > self.max_bytes:
                self.oversize_skips += 1
                continue
            while (self.bytes_cached + nbytes > self.max_bytes
                   and self._entries):
                _k, old = self._entries.popitem(last=False)
                self.bytes_cached -= old.nbytes
                self.evictions += 1
            entry = _Entry(post_keyed, deltas, nbytes,
                           int(em["span_len"]),
                           persisted=(bool(em.get("persisted"))
                                      if restore else True))
            if restore:
                entry.hits = int(em.get("hits", 0))
            self._entries[key] = entry
            self.bytes_cached += nbytes
            loaded += 1
        if restore:
            for f in _COUNTER_ATTRS:
                if f in meta.get("counters", {}):
                    setattr(self, f, int(meta["counters"][f]))
            self._seen = OrderedDict(
                (k, int(v)) for k, v in meta.get("seen", {}).items())
        else:
            self.peak_bytes = max(self.peak_bytes, self.bytes_cached)
            self.loaded_entries += loaded
        return loaded

    def save(self, path: str) -> dict:
        """Persist the cache to one atomic self-verifying ``.npz``
        (ROADMAP-3 "cross-run cache persistence"). Returns the written
        meta."""
        from ..faults import checkpoint as ckpt

        meta, arrays = self.spill()
        meta["kind"] = "chainmemo"
        return ckpt.write_npz_checkpoint(path, schema=MEMO_SCHEMA,
                                         meta=meta, arrays=arrays)

    def load(self, path: str) -> int:
        """Load a `save`d cache file; returns entries admitted."""
        from ..faults import checkpoint as ckpt

        meta, arrays = ckpt.load_npz_checkpoint(path, schema=MEMO_SCHEMA)
        return self.absorb(meta, arrays, source=path)

    # -- reporting --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "misses": self.misses,
            "records": self.records,
            "evictions": self.evictions,
            "unstable_skips": self.unstable_skips,
            "oversize_skips": self.oversize_skips,
            "fast_forwarded_windows": self.fast_forwarded_windows,
            "loaded_entries": self.loaded_entries,
            "persisted_hits": self.persisted_hits,
            "entries": len(self._entries),
            "bytes_cached": self.bytes_cached,
            "peak_bytes": self.peak_bytes,
            "max_bytes": self.max_bytes,
            "min_repeat": self.min_repeat,
        }

    def report(self) -> dict:
        """The `--memo-report` artifact body: stats plus per-entry
        sizes (keys truncated — they are content digests, not
        secrets, but full hex is noise)."""
        return {
            **self.stats(),
            "entry_sizes": [
                {"key": k[:16], "bytes": e.nbytes,
                 "span_len": e.span_len, "hits": e.hits}
                for k, e in self._entries.items()],
        }
