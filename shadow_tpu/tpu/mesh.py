"""Mesh construction and host-axis sharding for the network plane.

Parity concept: Shadow parallelizes over hosts (SURVEY.md §2.2 — hosts are
the unit of parallelism; work stealing balances them across cores). The TPU
mapping shards the host axis of every SoA array over the device mesh; the
cross-host routing scatter inside `window_step` is then lowered by the SPMD
partitioner to on-mesh collectives — the moral equivalent of the reference's
cross-thread `push_packet_to_host` (`worker.rs:629-639`) riding ICI instead
of a mutex.

Path tables are node-level ([M, M], M = graph nodes) and small, so they are
replicated to every device along with the [N] host->node map (destination
lookups index any host's node); per-host scalar/stat arrays shard on their
only axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .plane import NetPlaneParams

HOST_AXIS = "hosts"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (HOST_AXIS,))


def host_sharding(mesh: Mesh) -> NamedSharding:
    """Axis-0-sharded layout for [N, ...] per-host arrays."""
    return NamedSharding(mesh, P(HOST_AXIS))


def param_shardings(mesh: Mesh) -> NetPlaneParams:
    # node-level path tables are small ([M, M], M = graph nodes) and every
    # shard gathers arbitrary (src, dst) pairs from them: replicate — and
    # host_node too, since destination lookups index ANY host's node; the
    # per-host vectors shard with the host axis
    rep = NamedSharding(mesh, P())
    vec = NamedSharding(mesh, P(HOST_AXIS))
    return NetPlaneParams(latency_ns=rep, loss=rep, host_node=rep,
                          tb_rate=vec, tb_cap=vec, qdisc_rr=vec,
                          dn_rate=vec, dn_cap=vec)


def shard_state(state: NetPlaneState, params: NetPlaneParams, mesh: Mesh):
    """Place state/params onto the mesh with host-axis sharding."""
    state = jax.device_put(state, host_sharding(mesh))
    params = jax.device_put(params, param_shardings(mesh))
    return state, params
