"""Fused Pallas egress kernel: rebase -> packed-key row sort -> prefix-sum
token gate in ONE VMEM-resident pass per host tile.

The XLA egress path (plane.window_step sections 2a-2c) round-trips the
egress columns through HBM between the rebase, the qdisc sort, and the
token-bucket cumsum. This kernel keeps a tile of host rows resident in
VMEM and does all three in place:

- clock rebase of send times / barrier clamps (elementwise);
- the FIFO qdisc order as a BITONIC network over each row's
  (packed key, column index) pairs — the index tiebreak makes the
  network's output exactly the stable sort the XLA path computes, and
  the compare-exchange swaps carry the bytes/tsend/clamp columns along
  so no in-kernel gather is needed;
- the token gate as a Hillis-Steele inclusive prefix sum over the
  sorted byte column.

Scope: the FIFO qdisc only (`rr_enabled=False` — the integrated
transport and the bench shape; the RR fairness tensors stay on the XLA
path). Selected via `experimental.plane_kernel = "pallas"` /
`window_step(kernel="pallas")`; default remains "xla". The kernel runs
in interpreter mode on non-TPU backends (JAX_PLATFORMS=cpu tests), and
`tests/test_plane_sortdiet.py` pins bitwise parity of the full window
step against the XLA path.

Mosaic note: the bitonic partner exchange is written as a static
column-permutation gather (`a[:, cols ^ stride]`). On TPU hardware
Mosaic may prefer this rewritten with `pltpu.roll`-based shuffles; the
interpret path (and the parity contract) is the part this module
guarantees.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .plane import NO_CLAMP

_SIGN32 = np.uint32(0x80000000)

# host rows per kernel tile: large enough to amortize dispatch, small
# enough that the ~10 [TILE, CE] int32 buffers stay far inside VMEM
# (~16 MB/core): 256 rows x 256 slots x 10 cols x 4 B = 2.6 MB worst case
_TILE_ROWS = 256


def _partner_swap(a, stride: int):
    """a[..., i ^ stride] as pure reshapes + a static reverse — each
    contiguous block of 2*stride columns swaps its halves. No gather, no
    captured index constants (Mosaic/pallas-friendly)."""
    n = a.shape[-1]
    r = a.reshape(a.shape[:-1] + (n // (2 * stride), 2, stride))
    return r[..., ::-1, :].reshape(a.shape)


def _bitonic_rows(key, idx, cols, carried):
    """Ascending bitonic sort of each row by (key, idx); the `carried`
    arrays ride the compare-exchange swaps. Row width must be a power of
    two; `cols` is the broadcast column iota. (key, idx) pairs are
    distinct, so the network's output equals the STABLE sort by key —
    bitwise the permutation the XLA diet path's `lax.sort((packed, col))`
    produces."""
    n = key.shape[-1]
    assert n & (n - 1) == 0, "bitonic row sort needs a power-of-two width"
    arrs = [key, idx, *carried]
    size = 2
    while size <= n:
        stride = size // 2
        while stride >= 1:
            # ascending block iff bit `size` of the column index is clear;
            # the lower-indexed element of each pair keeps the min there
            is_left = (cols & stride) == 0
            up = (cols & size) == 0
            take_min = is_left == up
            partners = [_partner_swap(a, stride) for a in arrs]
            less = (arrs[0] < partners[0]) | (
                (arrs[0] == partners[0]) & (arrs[1] < partners[1]))
            keep_self = less == take_min
            arrs = [jnp.where(keep_self, a, p)
                    for a, p in zip(arrs, partners)]
            stride //= 2
        size *= 2
    return arrs[0], arrs[1], arrs[2:]


def _egress_kernel(shift_ref, valid_ref, prio_ref, bytes_ref, tsend_ref,
                   clamp_ref, balance_ref, perm_ref, bytes_out_ref,
                   tsend_out_ref, clamp_out_ref, valid_out_ref,
                   sendable_ref, spent_ref):
    shift = shift_ref[0]
    valid = valid_ref[...] != 0
    prio = prio_ref[...]

    # rebase send times / clamps to this window's start
    tsend_rb = jnp.where(valid, tsend_ref[...] - shift, 0)
    clamp = clamp_ref[...]
    clamp_rb = jnp.where(valid & (clamp != NO_CLAMP), clamp - shift, clamp)

    # packed FIFO key: validity bit 31, priority bits 0..30 (the same
    # _pack_valid_key layout the XLA diet path sorts by)
    key = jnp.where(valid, jnp.uint32(0), _SIGN32) | prio.astype(jnp.uint32)
    n = key.shape[-1]
    col = jax.lax.broadcasted_iota(jnp.int32, key.shape, dimension=1)

    key_s, perm, (bytes_s, tsend_s, clamp_s) = _bitonic_rows(
        key, col, col, (bytes_ref[...], tsend_rb, clamp_rb))
    valid_s = (key_s & _SIGN32) == 0

    # Hillis-Steele inclusive prefix sum of the sendable byte column
    cum = jnp.where(valid_s, bytes_s, 0)
    shift_w = 1
    while shift_w < n:
        prev = jnp.concatenate(
            [jnp.zeros_like(cum[:, :shift_w]), cum[:, :-shift_w]], axis=1)
        cum = cum + prev
        shift_w *= 2
    sendable = valid_s & (cum <= balance_ref[...])
    spent = jnp.sum(jnp.where(sendable, bytes_s, 0), axis=1, keepdims=True)

    perm_ref[...] = perm
    bytes_out_ref[...] = bytes_s
    tsend_out_ref[...] = tsend_s
    clamp_out_ref[...] = clamp_s
    valid_out_ref[...] = valid_s.astype(jnp.int32)
    sendable_ref[...] = sendable.astype(jnp.int32)
    spent_ref[...] = spent


def _pick_tile(n: int) -> int:
    """Largest divisor of the host count <= _TILE_ROWS (single tile for
    small worlds; the bench shapes are multiples of 256)."""
    if n <= _TILE_ROWS:
        return n
    for t in range(_TILE_ROWS, 0, -1):
        if n % t == 0:
            return t
    return n


@functools.partial(jax.jit, static_argnames=("interpret",))
def _egress_call(valid, prio, nbytes, tsend, clamp, balance, shift_ns,
                 interpret: bool):
    N, CE = valid.shape
    T = _pick_tile(N)
    row_spec = pl.BlockSpec((T, CE), lambda i: (i, 0))
    col_spec = pl.BlockSpec((T, 1), lambda i: (i, 0))
    out = pl.pallas_call(
        _egress_kernel,
        grid=(N // T,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,)),  # shift scalar
            row_spec, row_spec, row_spec, row_spec, row_spec,  # egress cols
            col_spec,  # balance [N, 1]
        ],
        out_specs=[row_spec] * 6 + [col_spec],
        out_shape=[
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # perm
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # bytes sorted
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # tsend rebased+sorted
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # clamp rebased+sorted
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # valid sorted
            jax.ShapeDtypeStruct((N, CE), jnp.int32),  # sendable
            jax.ShapeDtypeStruct((N, 1), jnp.int32),  # spent per host
        ],
        interpret=interpret,
    )(shift_ns.reshape(1), valid.astype(jnp.int32), prio, nbytes, tsend,
      clamp, balance.reshape(N, 1))
    return out


def egress_order_gate(valid, prio, nbytes, tsend, clamp, balance, shift_ns):
    """The fused egress stage: returns (perm, bytes_s, tsend_s, clamp_s,
    valid_s, sendable, spent) — the sorted byte/time columns plus the
    permutation to apply to the remaining payload columns, bitwise equal
    to the XLA diet path's `_egress_order` + `_token_gate` outputs for
    FIFO rows.

    The fusion covers the FIFO qdisc stage ONLY: neither the fault gate
    (`faults=`) nor the guard plane (`guards=`) is part of the fused
    pipeline, and `window_step` refuses both combinations at trace time
    — the self-healing `KernelFallback` (faults/healing.py) demotes
    such drivers to the bitwise-identical XLA path automatically."""
    if (valid.shape[1] & (valid.shape[1] - 1)) != 0:
        raise ValueError(
            f"plane_kernel='pallas' needs a power-of-two egress capacity, "
            f"got {valid.shape[1]}; use the XLA kernel or pad egress_cap")
    interpret = jax.default_backend() != "tpu"
    shift_arr = jnp.asarray(shift_ns, jnp.int32)
    (perm, bytes_s, tsend_s, clamp_s, valid_s, sendable,
     spent) = _egress_call(valid, prio, jnp.asarray(nbytes, jnp.int32),
                           jnp.asarray(tsend, jnp.int32),
                           jnp.asarray(clamp, jnp.int32),
                           jnp.asarray(balance, jnp.int32), shift_arr,
                           interpret)
    return (perm, bytes_s, tsend_s, clamp_s, valid_s != 0, sendable != 0,
            spent[:, 0])
