"""The fused rank→place→egress Pallas pipeline: one VMEM-resident pass
per host tile around the irreducible cross-host exchange.

`plane_kernel: pallas` (tpu/pallas_egress.py + tpu/pallas_route.py)
fuses the egress stage and the routing placement as TWO separate
dispatches with XLA glue between them — the payload-column gathers
behind the egress permutation, the routing seq-rank tensors, and the
per-row placement loop all round-trip work through HBM or per-row
control flow. `plane_kernel: pallas_fused` (this module) collapses
that glue into the kernels, so a host tile's window work stays in
VMEM end-to-end:

- **egress_rank_stage** (kernel A): clock rebase → packed-key FIFO
  bitonic sort → ALL payload columns permuted in-tile → Hillis-Steele
  token gate → the routing stage's row-local seq order (phase A of the
  bucketed exchange) as ONE more bitonic over the already-sorted
  (seq, column) pairs, whose index column IS the `row_perm` the XLA
  path materializes via an [N, CE, CE] pairwise rank + scatter
  inversion. One dispatch where the two-dispatch path pays the egress
  kernel plus five XLA gathers plus the rank tensors.
- **route_place** (kernel B): the per-destination bucketed append with
  the arrival-sorted stream resident in VMEM next to the destination
  tile — rank arithmetic and scatter-append collapse into one
  whole-tile masked select, with no per-row windowed-load loop (the
  `pallas_route` formulation, whose row loop dominated the kernel's
  cost) and no per-column placement dispatches.

What stays in XLA is exactly the cross-host exchange: the flat diet
sort establishing the global (dst, deliver) arrival order and its
binary-searched bucket bounds (`plane._routing_rank` with the kernel-A
`row_perm` passed through) — sorting across the host axis is what
XLA's comparator networks are for, and under a sharded mesh that sort
IS the all-to-all — plus the steady-state-gated ingress compaction
(`plane._compact_ingress`) and due-release split, whose already-
ordered fast path and wrapped-key diet make them cheaper than any
in-kernel re-sort.

Scope mirrors the split kernels: FIFO only (`rr_enabled=False`),
power-of-two egress AND ingress capacities (the bitonic widths),
refused at trace time when faults/guards/hist/flightrec are threaded
(`window_step` enforces it; the self-healing `KernelFallback` demotes
to the bitwise-identical XLA path). Off-TPU the kernels run in Pallas
interpret mode — the interpret path and the bitwise-parity contract
(tests/test_plane_sortdiet.py, tests/test_chain_driver.py) are what
this module pins, like its two-dispatch siblings.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_egress import _bitonic_rows, _pick_tile
from .plane import NO_CLAMP, _routing_rank

_SIGN32 = np.uint32(0x80000000)
I32_MAX = np.int32(2**31 - 1)


def _require_pow2(cap: int, what: str):
    if cap & (cap - 1):
        raise ValueError(
            f"plane_kernel='pallas_fused' needs a power-of-two {what} "
            f"(the bitonic network width), got {cap}; pad the ring or "
            f"use the xla/pallas kernels")


# ---------------------------------------------------------------------------
# kernel A: egress sort + token gate + routing row-perm
# ---------------------------------------------------------------------------


def _egress_rank_kernel(shift_ref, valid_ref, prio_ref, bytes_ref,
                        tsend_ref, clamp_ref, dst_ref, seq_ref, sock_ref,
                        ctrl_ref, balance_ref,
                        prio_o, sock_o, dst_o, bytes_o, seq_o, ctrl_o,
                        tsend_o, clamp_o, valid_o, sendable_o, spent_o,
                        row_perm_o):
    shift = shift_ref[0]
    valid = valid_ref[...] != 0
    prio = prio_ref[...]

    # rebase send times / barrier clamps to this window's start
    tsend_rb = jnp.where(valid, tsend_ref[...] - shift, 0)
    clamp = clamp_ref[...]
    clamp_rb = jnp.where(valid & (clamp != NO_CLAMP), clamp - shift, clamp)

    # packed FIFO key (the `_pack_valid_key` layout) sorted by a bitonic
    # over (key, column) pairs — bitwise the stable sort the XLA diet
    # path computes; the permutation then lands EVERY payload column
    # with in-VMEM row gathers (no HBM round trip, no separate dispatch)
    key = jnp.where(valid, jnp.uint32(0), _SIGN32) | prio.astype(jnp.uint32)
    n = key.shape[-1]
    col = jax.lax.broadcasted_iota(jnp.int32, key.shape, dimension=1)
    key_s, perm, _ = _bitonic_rows(key, col, col, ())
    valid_s = (key_s & _SIGN32) == 0
    take = lambda a: jnp.take_along_axis(a, perm, axis=1)
    bytes_s = take(bytes_ref[...])
    seq_s = take(seq_ref[...])

    # Hillis-Steele inclusive prefix sum -> the token-bucket gate
    cum = jnp.where(valid_s, bytes_s, 0)
    shift_w = 1
    while shift_w < n:
        prev = jnp.concatenate(
            [jnp.zeros_like(cum[:, :shift_w]), cum[:, :-shift_w]], axis=1)
        cum = cum + prev
        shift_w *= 2
    sendable = valid_s & (cum <= balance_ref[...])
    spent = jnp.sum(jnp.where(sendable, bytes_s, 0), axis=1, keepdims=True)

    # routing phase A, fused: the XLA path ranks the SORTED rows by
    # (seq, column) with an [N, CE, CE] pairwise tensor and inverts the
    # rank by scatter; the inverse permutation is exactly "columns in
    # (seq, column) order", i.e. the index output of ONE more bitonic
    # over the sign-biased sorted seq — distinct (seq, col) pairs make
    # the network's output the stable sort, bitwise the same perm
    _, row_perm, _ = _bitonic_rows(seq_s.astype(jnp.uint32) ^ _SIGN32,
                                   col, col, ())

    prio_o[...] = take(prio)
    sock_o[...] = take(sock_ref[...])
    dst_o[...] = take(dst_ref[...])
    bytes_o[...] = bytes_s
    seq_o[...] = seq_s
    ctrl_o[...] = take(ctrl_ref[...])
    tsend_o[...] = take(tsend_rb)
    clamp_o[...] = take(clamp_rb)
    valid_o[...] = valid_s.astype(jnp.int32)
    sendable_o[...] = sendable.astype(jnp.int32)
    spent_o[...] = spent
    row_perm_o[...] = row_perm


@functools.partial(jax.jit, static_argnames=("interpret",))
def _egress_rank_call(valid, prio, nbytes, tsend, clamp, dst, seq, sock,
                      ctrl, balance, shift_ns, interpret: bool):
    N, CE = valid.shape
    T = _pick_tile(N)
    row_spec = pl.BlockSpec((T, CE), lambda i: (i, 0))
    col_spec = pl.BlockSpec((T, 1), lambda i: (i, 0))
    out = pl.pallas_call(
        _egress_rank_kernel,
        grid=(N // T,),
        in_specs=[pl.BlockSpec((1,), lambda i: (0,))]  # shift scalar
        + [row_spec] * 9 + [col_spec],
        out_specs=[row_spec] * 10 + [col_spec] + [row_spec],
        out_shape=[jax.ShapeDtypeStruct((N, CE), jnp.int32)] * 10
        + [jax.ShapeDtypeStruct((N, 1), jnp.int32),
           jax.ShapeDtypeStruct((N, CE), jnp.int32)],
        interpret=interpret,
    )(shift_ns.reshape(1), valid.astype(jnp.int32), prio, nbytes, tsend,
      clamp, dst, seq, sock, ctrl.astype(jnp.int32),
      balance.reshape(N, 1))
    return out


def egress_rank_stage(valid, prio, nbytes, tsend, clamp, dst, seq, sock,
                      ctrl, balance, shift_ns):
    """Kernel A of the fused pipeline: returns the 9 sorted egress
    columns (prio, sock, dst, bytes, seq, ctrl, tsend, clamp, valid)
    plus (sendable, spent, row_perm) — bitwise equal to the XLA diet
    path's `_egress_order` + `_token_gate` + the `_routing_order`
    seq-rank inverse for FIFO rows, in ONE dispatch."""
    _require_pow2(valid.shape[1], "egress capacity")
    interpret = jax.default_backend() != "tpu"
    (prio_s, sock_s, dst_s, bytes_s, seq_s, ctrl_s, tsend_s, clamp_s,
     valid_s, sendable, spent, row_perm) = _egress_rank_call(
        valid, prio, jnp.asarray(nbytes, jnp.int32),
        jnp.asarray(tsend, jnp.int32), jnp.asarray(clamp, jnp.int32),
        jnp.asarray(dst, jnp.int32), jnp.asarray(seq, jnp.int32),
        jnp.asarray(sock, jnp.int32), ctrl,
        jnp.asarray(balance, jnp.int32),
        jnp.asarray(shift_ns, jnp.int32), interpret)
    return (prio_s, sock_s, dst_s, bytes_s, seq_s, ctrl_s != 0, tsend_s,
            clamp_s, valid_s != 0, sendable != 0, spent[:, 0], row_perm)


# ---------------------------------------------------------------------------
# kernel B: bucketed placement + due-release split
# ---------------------------------------------------------------------------


def _place_kernel(nv_ref, lo_ref, take_ref,
                  s_src, s_seq, s_sock, s_bytes, s_del,
                  b_src, b_seq, b_sock, b_bytes, b_del, b_valid,
                  o_src, o_seq, o_sock, o_bytes, o_del, o_valid):
    T, CI = b_src.shape
    nv = nv_ref[...][:, None]
    lo = lo_ref[...][:, None]
    take_n = take_ref[...][:, None]
    ccol = jax.lax.broadcasted_iota(jnp.int32, (T, CI), 1)
    # append mask: slots [nv, nv + take_n) of each destination row
    # receive the bucket's contiguous segment of the arrival-sorted
    # stream; the segment window starts at (bucket offset - nv), so
    # window column c IS the item for row slot c — the `pallas_route`
    # collapse of rank + scatter-append, here as ONE whole-tile masked
    # gather from the VMEM-resident stream instead of a per-row
    # windowed-load loop (the loop emulation dominated interpret-mode
    # cost; Mosaic may want the per-row `pl.ds` form back on hardware)
    mask = (ccol >= nv) & (ccol < nv + take_n)
    B2 = s_src.shape[0]
    idx = jnp.clip(lo + ccol + CI, 0, B2 - 1)  # CI-left-padded stream
    sel = lambda s_ref, base: jnp.where(mask, s_ref[...][idx], base)
    o_src[...] = sel(s_src, b_src[...])
    o_seq[...] = sel(s_seq, b_seq[...])
    o_sock[...] = sel(s_sock, b_sock[...])
    o_bytes[...] = sel(s_bytes, b_bytes[...])
    o_del[...] = sel(s_del, b_del[...])
    o_valid[...] = jnp.where(mask, 1, b_valid[...])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _place_call(nv, lo, take, s_src, s_seq, s_sock, s_bytes, s_del,
                b_src, b_seq, b_sock, b_bytes, b_del, b_valid,
                interpret: bool):
    N, CI = b_src.shape
    B2 = s_src.shape[0]
    T = _pick_tile(N)
    tile1 = pl.BlockSpec((T,), lambda i: (i,))
    row_spec = pl.BlockSpec((T, CI), lambda i: (i, 0))
    full = pl.BlockSpec((B2,), lambda i: (0,))
    return pl.pallas_call(
        _place_kernel,
        grid=(N // T,),
        in_specs=[tile1] * 3 + [full] * 5 + [row_spec] * 6,
        out_specs=[row_spec] * 6,
        out_shape=[jax.ShapeDtypeStruct((N, CI), jnp.int32)] * 6,
        interpret=interpret,
    )(nv, lo, take, s_src, s_seq, s_sock, s_bytes, s_del,
      b_src, b_seq, b_sock, b_bytes, b_del, b_valid)


def route_place(sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
                in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
                in_valid_c, n_valid_in, row_perm):
    """Kernel B of the fused pipeline (+ the XLA exchange): land the
    routed arrivals into the destination tiles, bitwise equal to the
    XLA path's `_routing_rank` + `_routing_place` composition over the
    compacted ingress columns. `row_perm` is kernel A's fused seq-order
    inverse. Returns the merged ingress columns + per-host overflow,
    like `plane._route_scatter`."""
    N, CE = eg_dst.shape
    CI = in_src_c.shape[1]
    _require_pow2(CI, "ingress capacity")
    # the irreducible cross-host exchange: ONE diet flat sort over the
    # (bucket, deliver, slot) keys + binary-searched bucket bounds —
    # phase A's row_perm arrives precomputed from kernel A
    row_perm, o_pos, offsets, take_n, overflow = _routing_rank(
        sent, eg_dst, eg_seq, deliver_rel, n_valid_in, CI,
        row_perm=row_perm)
    lo = offsets - n_valid_in

    # arrival-sorted payload streams, addressed through the composed
    # permutation and padded CI on both sides so every masked stream
    # index is in bounds (padding is never selected — masked lanes only
    # cover the bucket's own segment)
    flat = lambda a: a.reshape(-1)
    g = (o_pos // CE) * CE + flat(row_perm)[o_pos]
    pad = lambda a: jnp.pad(a, (CI, CI))
    stream = lambda a: pad(flat(a)[g])
    s_src = pad((o_pos // CE).astype(jnp.int32))
    s_seq, s_sock = stream(eg_seq), stream(eg_sock)
    s_bytes = stream(eg_bytes)
    s_del = stream(deliver_rel)

    interpret = jax.default_backend() != "tpu"
    (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
     in_valid_m) = _place_call(
        n_valid_in, lo, take_n, s_src, s_seq, s_sock, s_bytes, s_del,
        in_src_c, in_seq_c, in_sock_c, in_bytes_c,
        jnp.where(in_valid_c, in_deliver_c, I32_MAX),
        in_valid_c.astype(jnp.int32), interpret)
    return (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
            in_valid_m != 0, overflow)
