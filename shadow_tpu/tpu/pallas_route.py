"""Fused Pallas routing stage: per-destination-tile rank + scatter-append
in ONE VMEM-resident pass.

The XLA routing path (plane.window_step section 5) computes the bucketed
arrival order (`plane._routing_order`), derives every item's destination
slot, and then lands each payload column with a separate flat scatter —
six scatter dispatches round-tripping the ingress columns through HBM.
This kernel fuses the per-destination placement: a tile of destination
rows stays resident in VMEM while, for each row, the bucket's segment of
the arrival-sorted stream is appended after the row's existing entries
in one masked select — rank computation (bucket offset - current
occupancy) and scatter-append collapse into a windowed dynamic load plus
a compare mask, with no per-column scatter dispatches.

The arrival order itself still comes from the XLA diet sort (sorting is
what XLA's comparator networks are for); the sorted payload streams are
materialized once and consumed by every destination tile.

Scope mirrors `pallas_egress`: selected via `experimental.plane_kernel =
"pallas"` / `window_step(kernel="pallas")` (FIFO worlds — the flag
already requires `rr_enabled=False`); `window_step` refuses the
combination with threaded faults or guards at trace time, and the
self-healing `KernelFallback` (faults/healing.py) demotes failing
drivers to the bitwise-identical XLA path. Off-TPU the kernel runs in
Pallas interpret mode — correct and parity-tested
(tests/test_plane_routing.py), not fast; the interpret path is the part
this module guarantees.

Mosaic note: the per-row windowed loads use dynamic-start `pl.ds`
slices; on TPU hardware Mosaic may want the stream blocks routed through
scalar-prefetched block indices instead. As with `pallas_egress`, the
interpret path and the bitwise-parity contract are what this module
pins.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_egress import _pick_tile
from .plane import I32_MAX, _routing_rank


def _route_kernel(nv_ref, lo_ref, take_ref, s_src, s_seq, s_sock, s_bytes,
                  s_del, b_src, b_seq, b_sock, b_bytes, b_del, b_valid,
                  o_src, o_seq, o_sock, o_bytes, o_del, o_valid):
    T, CI = b_src.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (1, CI), 1)

    def row(r, carry):
        nv = pl.load(nv_ref, (pl.ds(r, 1),))[0]
        lo = pl.load(lo_ref, (pl.ds(r, 1),))[0]
        take = pl.load(take_ref, (pl.ds(r, 1),))[0]
        # append mask: slots [nv, nv + take) receive the bucket segment;
        # the stream window is loaded at (bucket offset - nv) so window
        # column c IS the item destined for row slot c — the rank
        # computation and the scatter-append collapse into this select
        mask = (col >= nv) & (col < nv + take)
        start = lo + CI  # into the CI-left-padded stream
        for s_ref, b_ref, o_ref in ((s_src, b_src, o_src),
                                    (s_seq, b_seq, o_seq),
                                    (s_sock, b_sock, o_sock),
                                    (s_bytes, b_bytes, o_bytes),
                                    (s_del, b_del, o_del)):
            win = pl.load(s_ref, (pl.ds(start, CI),)).reshape(1, CI)
            base = pl.load(b_ref, (pl.ds(r, 1), pl.ds(0, CI)))
            pl.store(o_ref, (pl.ds(r, 1), pl.ds(0, CI)),
                     jnp.where(mask, win, base))
        basev = pl.load(b_valid, (pl.ds(r, 1), pl.ds(0, CI)))
        pl.store(o_valid, (pl.ds(r, 1), pl.ds(0, CI)),
                 jnp.where(mask, 1, basev))
        return carry

    jax.lax.fori_loop(0, T, row, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _route_call(nv, lo, take, s_src, s_seq, s_sock, s_bytes, s_del,
                b_src, b_seq, b_sock, b_bytes, b_del, b_valid,
                interpret: bool):
    N, CI = b_src.shape
    B2 = s_src.shape[0]
    T = _pick_tile(N)
    tile1 = pl.BlockSpec((T,), lambda i: (i,))
    row_spec = pl.BlockSpec((T, CI), lambda i: (i, 0))
    full = pl.BlockSpec((B2,), lambda i: (0,))
    return pl.pallas_call(
        _route_kernel,
        grid=(N // T,),
        in_specs=[tile1] * 3 + [full] * 5 + [row_spec] * 6,
        out_specs=[row_spec] * 6,
        out_shape=[jax.ShapeDtypeStruct((N, CI), jnp.int32)] * 6,
        interpret=interpret,
    )(nv, lo, take, s_src, s_seq, s_sock, s_bytes, s_del,
      b_src, b_seq, b_sock, b_bytes, b_del, b_valid)


def route_scatter(sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
                  in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
                  in_valid_c, n_valid_in):
    """The fused routing stage: bitwise equal to the XLA diet path's
    `_routing_rank` + `_routing_place` (plane.py section 5). Returns the
    merged ingress columns + per-host overflow, like `_route_scatter`."""
    N, CE = eg_dst.shape
    CI = in_src_c.shape[1]
    # ONE source of truth for the bucketed order and the placement-
    # capacity arithmetic: the same phase-A the XLA path composes
    row_perm, o_pos, offsets, take_n, overflow = _routing_rank(
        sent, eg_dst, eg_seq, deliver_rel, n_valid_in, CI)
    lo = offsets - n_valid_in

    # arrival-sorted payload streams (the cross-host exchange the tiles
    # consume) addressed through the composed permutation (sorted
    # position -> original slot), padded CI on both sides so every
    # windowed load is in bounds; padding is never selected (masked
    # lanes only cover the bucket's own segment)
    flat = lambda a: a.reshape(-1)
    g = (o_pos // CE) * CE + flat(row_perm)[o_pos]
    pad = lambda a: jnp.pad(a, (CI, CI))
    stream = lambda a: pad(flat(a)[g])
    s_src = pad((o_pos // CE).astype(jnp.int32))
    s_seq, s_sock = stream(eg_seq), stream(eg_sock)
    s_bytes = stream(eg_bytes)
    s_del = stream(deliver_rel)

    b_del = jnp.where(in_valid_c, in_deliver_c, I32_MAX)
    interpret = jax.default_backend() != "tpu"
    (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
     in_valid_m) = _route_call(
        n_valid_in, lo, take_n, s_src, s_seq, s_sock, s_bytes, s_del,
        in_src_c, in_seq_c, in_sock_c, in_bytes_c, b_del,
        in_valid_c.astype(jnp.int32), interpret)
    return (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
            in_valid_m != 0, overflow)
