"""Batched network-plane state and the per-window step function.

This is the TPU-native re-design of Shadow's per-packet hot path
(`src/main/core/worker.rs:326-410` send_packet, `src/main/network/relay/`
token buckets, per-host event queues) as dense array ops:

- `RoutingInfo` becomes the `[N, N]` latency/loss matrices already produced
  by `shadow_tpu.net.graph` (SURVEY.md §2.5 "this is the table that becomes
  a dense HBM array").
- Per-host rate limiting (`relay/token_bucket.rs`) becomes a vectorized
  token-bucket refill + prefix-sum spend over each host's egress queue.
- Bernoulli path loss from the *source host's* RNG stream
  (`worker.rs:359-375`) becomes counter-based threefry: every egress slot
  derives its key from (root_key, per-host monotone counter), so draws are
  identical under any vectorization or sharding.
- The deliver-time clamp to the round end (`worker.rs:396-399`) is what
  makes window-batched exchange legal; it is applied on-device.
- Cross-host "push to destination queue under mutex" (`worker.rs:629-639`)
  becomes a deterministic sorted scatter into fixed-capacity ingress
  queues; with the host axis sharded over a mesh the scatter is the
  all-to-all the SPMD partitioner lowers to ICI collectives.

Dtype discipline (TPU-first):
- Everything is int32/float32; no x64 dependence.
- Times on-device are *relative to the current window start* and rebased by
  `shift` each round, so int32 ns never overflows (constraint: path
  latency + window length < ~2.1 s, amply true for network sims).
- Invalid/empty slots use INT32_MAX sentinels so min-reductions are clean.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..faults.plane import FaultArrays
from ..guards.plane import GuardState
from ..guards import plane as guards_plane
from ..telemetry import flightrec as flightrec_mod
from ..telemetry import histo
from ..telemetry.flightrec import FlightRecArrays
from ..telemetry.histo import PlaneHistograms
from ..telemetry.metrics import PlaneMetrics
from . import codel

I32_MAX = np.int32(2**31 - 1)
# Bounded per-host socket-slot space for the round-robin qdisc's fairness
# counters; socket ids hash in with `% RR_SOCK_SLOTS` (collisions merge
# flows, as in classic stochastic fair queuing — determinism is unaffected).
RR_SOCK_SLOTS = 16
# eg_clamp sentinel: "clamp this packet's delivery to the end of whatever
# window processes it" (the pure-device mode, where ingest and step share a
# window). Integrated transport passes the send-round end instead, since the
# processing step runs one round later (`worker.rs:396-399` semantics).
NO_CLAMP = np.int32(-(2**30))


class NetPlaneParams(NamedTuple):
    """Static per-simulation data.

    Path properties are NODE-level ([M, M] with a [N] host→node map), the
    shape the GML graph actually has (`net/graph.py` RoutingInfo): real
    topologies have far fewer graph nodes than hosts, so the latency/loss
    tables stay small enough for VMEM residency — a [N, N] host-pair
    gather at 16k hosts would be a 1 GiB HBM table with ~30 ns per random
    lookup dominating the window step. Host-pair matrices still work:
    pass host_node=arange(N) (the make_params default)."""

    latency_ns: jax.Array  # [M, M] int32 — path latency between nodes
    loss: jax.Array  # [M, M] float32 — path loss probability
    host_node: jax.Array  # [N] int32 — graph node index of each host
    tb_rate: jax.Array  # [N] int32 — egress bytes per millisecond (up-bw)
    tb_cap: jax.Array  # [N] int32 — bucket capacity (rate/ms + 1 MTU burst)
    qdisc_rr: jax.Array  # [N] bool — per-host qdisc: round-robin vs FIFO
    dn_rate: jax.Array  # [N] int32 — ingress bytes per millisecond (down-bw)
    dn_cap: jax.Array  # [N] int32 — down bucket capacity (rate/ms + 1 MTU)


class NetPlaneState(NamedTuple):
    """Mutable SoA state, axis 0 = host, sharded over the mesh."""

    # egress queues (outbound, awaiting bandwidth): [N, CE]
    eg_dst: jax.Array  # int32 dest host index (-1 invalid)
    eg_bytes: jax.Array  # int32 total wire size
    eg_prio: jax.Array  # int32 host-assigned FIFO priority
    eg_seq: jax.Array  # int32 per-source packet id (payload correlation)
    eg_ctrl: jax.Array  # bool — control packets are never loss-dropped
    eg_tsend: jax.Array  # int32 ns send time relative to window start
    eg_clamp: jax.Array  # int32 barrier clamp (NO_CLAMP = current window end)
    eg_sock: jax.Array  # int32 emitting-socket id (round-robin qdisc key)
    eg_valid: jax.Array  # bool
    # ingress queues (in flight toward this host): [N, CI]
    in_src: jax.Array  # int32 source host index
    in_bytes: jax.Array  # int32
    in_seq: jax.Array  # int32
    in_sock: jax.Array  # int32 payload tag (socket id / pool slot)
    in_deliver_rel: jax.Array  # int32 ns relative to current window start
    in_valid: jax.Array  # bool
    # scalars per host: [N]
    tb_balance: jax.Array  # int32 token bytes available
    tb_rem_ns: jax.Array  # int32 sub-millisecond refill remainder
    rng_counter: jax.Array  # int32 draws consumed (determinism contract)
    # RR qdisc fairness: [N, RR_SOCK_SLOTS] int32 — virtual finish counter
    # per socket slot (packets this socket has pushed through the qdisc,
    # floored to the active minimum so idle sockets re-join at the current
    # virtual time instead of monopolizing on return)
    rr_sent: jax.Array
    # destination-side router (CoDel AQM + down-bw relay) scalars; active
    # only when window_step compiles with router_aqm=True
    router: codel.RouterDownState
    # counters (per host, int32)
    n_sent: jax.Array
    n_loss_dropped: jax.Array
    n_overflow_dropped: jax.Array
    n_delivered: jax.Array
    # fault-plane drops (injected failures: dead-host egress purge,
    # burst corruption, routing toward a crashed/link-down host) —
    # distinct from n_loss_dropped so injected losses are never
    # misattributed to the Bernoulli loss sample (docs/robustness.md);
    # stays zero when window_step compiles with faults=None
    n_fault_dropped: jax.Array


def make_params(latency_ns: np.ndarray, loss: np.ndarray, up_bw_bps: np.ndarray,
                mtu: int = 1500,
                qdisc_rr: np.ndarray | None = None,
                down_bw_bps: np.ndarray | None = None,
                host_node: np.ndarray | None = None) -> NetPlaneParams:
    """Build params from the routing matrices (`RoutingInfo.latency_ns/loss`,
    node-level [M, M]) and per-host up-bandwidths in bits/sec.

    `host_node` [N] maps each host to its graph-node index; None means the
    matrices are host-pair ([N, N]) and the identity map is used.

    `qdisc_rr` [N] bool selects the per-host queuing discipline
    (`QDiscMode` in `configuration.rs:961`): False = FIFO by packet
    priority, True = round-robin across emitting sockets. Default FIFO.

    `down_bw_bps` [N] feeds the destination-side router's down-bandwidth
    relay bucket (active only when window_step runs with router_aqm=True);
    None = transparent (max rate)."""
    # the path-latency budget (SL506 input-domain registry,
    # analysis/ranges.py `state.in_deliver_rel`): deliver = max(tsend +
    # latency, clamp) with tsend <= window <= I32_MAX//4 stays inside
    # int32 only while latency <= I32_MAX//2 (~1.07 s — beyond any
    # modeled path; the fault plane's lat_mult clamps to the same
    # budget). Was a docstring sentence ("path latency + window length
    # < ~2.1 s"); now refused at construction.
    lat = np.asarray(latency_ns)
    if lat.size and (lat.min() < 0 or lat.max() > (2**31 - 1) // 2):
        raise ValueError(
            f"latency_ns out of the device budget [0, I32_MAX//2 ns]: "
            f"min={lat.min()}, max={lat.max()} — the int32-ns deliver "
            "arithmetic (SL506 range proof, docs/determinism.md) "
            "admits wraparound beyond ~1.07 s of path latency")
    # cap the per-ms rate at 2^30 - mtu so the refill arithmetic in
    # window_step (rate * elapsed_eff <= headroom + rate <= cap + rate)
    # can never overflow int32; 2^30 B/ms ~ 8.6 Tbit/s, beyond any modeled NIC
    rate = np.minimum(
        np.maximum(1, (np.asarray(up_bw_bps) // 8) // 1000), 2**30 - mtu
    ).astype(np.int32)  # B/ms
    if host_node is None:
        host_node = np.arange(np.asarray(latency_ns).shape[0], dtype=np.int32)
    # host count: the host->node map defines it; a scalar bandwidth must
    # broadcast to N (not M — the node tables can be smaller than the fleet)
    n = np.asarray(host_node).shape[0]
    rate = np.broadcast_to(rate, (n,))
    if down_bw_bps is None:
        dn_rate = np.full(n, 2**30 - mtu, np.int32)
    else:
        dn_rate = np.broadcast_to(np.minimum(
            np.maximum(1, (np.asarray(down_bw_bps) // 8) // 1000),
            2**30 - mtu,
        ).astype(np.int32), (n,))
    return NetPlaneParams(
        latency_ns=jnp.asarray(latency_ns, jnp.int32),
        loss=jnp.asarray(loss, jnp.float32),
        host_node=jnp.asarray(host_node, jnp.int32),
        tb_rate=jnp.asarray(rate),
        tb_cap=jnp.asarray(rate + mtu, jnp.int32),
        qdisc_rr=(jnp.asarray(qdisc_rr, bool) if qdisc_rr is not None
                  else jnp.zeros(n, bool)),
        dn_rate=jnp.asarray(dn_rate),
        dn_cap=jnp.asarray(dn_rate + mtu, jnp.int32),
    )


def make_state(n_hosts: int, egress_cap: int = 32, ingress_cap: int = 64,
               initial_tokens: np.ndarray | None = None,
               initial_dn_tokens: np.ndarray | None = None,
               params: NetPlaneParams | None = None) -> NetPlaneState:
    """`params` (or an explicit `initial_dn_tokens`) starts the down-bw
    bucket at full capacity like the CPU TokenBucket — REQUIRED for parity
    whenever window_step runs with router_aqm=True (a zero-token start
    would delay every host's first inbound delivery to the 1 ms refill).

    `egress_cap`/`ingress_cap` need not be guessed right: under the
    elastic capacity policy (`capacity: {mode: elastic}` /
    `tpu/elastic.grow_state`, docs/robustness.md "Elastic capacity")
    drivers double a ring that overflows and re-execute the window from
    the pre-window snapshot, bitwise-identical to a run pre-provisioned
    at the final size. The invalid-lane fills below (-1 dst, I32_MAX
    priority/deliver sentinels, NO_CLAMP) are the canonical dead-lane
    values `elastic.grow_state`/`elastic.canonical_state` reproduce —
    keep the three in sync."""
    if initial_dn_tokens is None and params is not None:
        initial_dn_tokens = np.asarray(params.dn_cap)
    N, CE, CI = n_hosts, egress_cap, ingress_cap
    z = lambda shape: jnp.zeros(shape, jnp.int32)
    return NetPlaneState(
        eg_dst=jnp.full((N, CE), -1, jnp.int32),
        eg_bytes=z((N, CE)),
        eg_prio=jnp.full((N, CE), I32_MAX, jnp.int32),
        eg_seq=z((N, CE)),
        eg_ctrl=jnp.zeros((N, CE), bool),
        eg_tsend=z((N, CE)),
        eg_clamp=jnp.full((N, CE), NO_CLAMP, jnp.int32),
        eg_sock=z((N, CE)),
        eg_valid=jnp.zeros((N, CE), bool),
        in_src=jnp.full((N, CI), -1, jnp.int32),
        in_bytes=z((N, CI)),
        in_seq=z((N, CI)),
        in_sock=z((N, CI)),
        in_deliver_rel=jnp.full((N, CI), I32_MAX, jnp.int32),
        in_valid=jnp.zeros((N, CI), bool),
        tb_balance=(jnp.asarray(initial_tokens, jnp.int32)
                    if initial_tokens is not None else z((N,))),
        tb_rem_ns=z((N,)),
        rng_counter=z((N,)),
        rr_sent=z((N, RR_SOCK_SLOTS)),
        # CPU TokenBucket starts at full capacity; callers running with
        # router_aqm should pass the dn_cap array here for parity
        router=codel.make_router_state(N, initial_dn_tokens),
        n_sent=z((N,)),
        n_loss_dropped=z((N,)),
        n_overflow_dropped=z((N,)),
        n_delivered=z((N,)),
        n_fault_dropped=z((N,)),
    )


def _row_sort(*arrays, keys: int):
    """Sort each row of the given [N, C] arrays lexicographically by the
    first `keys` arrays. Returns the arrays reordered."""
    return jax.lax.sort(arrays, dimension=1, is_stable=True, num_keys=keys)


# --- packed sort keys (the "sort diet") ------------------------------------
# The window step's row sorts used to push every payload column through the
# lax.sort comparator network (12 arrays for the egress qdisc sort). The
# packed-key forms below fuse the (validity, key) pair into ONE uint32 key,
# sort (key, column-index), and apply the resulting permutation to the
# payload columns with take_along_axis — the sorting network then carries 2-3
# arrays instead of 7-12. Flat cross-host sorts (routing, flat ingest) get
# the BUCKETED diet instead (`_routing_rank`, `ingest`): the group key is a
# bounded bucket id, so the comparator network carries only (bucket, order
# key, slot index) and the payload columns land via one fused scatter each —
# never a standalone flat-permutation gather, which is DMA-bound on TPU
# (~0.5 ms per column at 65k slots on a v5e); a row-sort permutation, by
# contrast, only moves values within a C-wide row.

_SIGN32 = np.uint32(0x80000000)
_U32_MAX = np.uint32(0xFFFFFFFF)


def _assert_bit_budget(*fields):
    """Trace-time guard: the named (bits, what) fields must fit a single
    32-bit packed sort key. Raises at trace time (shapes and capacities
    are static), never at runtime."""
    total = sum(bits for bits, _ in fields)
    if total > 32:
        raise ValueError(
            "packed sort key bit-budget overflow: "
            + " + ".join(f"{what}={bits}b" for bits, what in fields)
            + f" = {total} bits > 32")


def _pack_valid_key(valid, key, *, what="qdisc key"):
    """Fuse (invalid-last, key) into one uint32 sort key: validity in bit
    31, the int32 key in bits 0..30. Exactly order-isomorphic to sorting
    by the (~valid, key) pair as long as keys are non-negative — the
    plane's priority / RR-key domain (monotone counters from 0, I32_MAX
    sentinels). Bit budget (1 validity + 31 key) asserted at trace time."""
    _assert_bit_budget((1, "validity"), (31, what))
    return jnp.where(valid, jnp.uint32(0), _SIGN32) | key.astype(jnp.uint32)


def _pack_time_key(valid, t):
    """Fuse (invalid-last, time) into one uint32 key for FULL-RANGE int32
    times (deliver offsets can be legitimately negative after a window
    rebase): sign-bias the time into unsigned order, invalid slots take
    the all-ones key. Exact for any valid time < I32_MAX — the invalid
    sentinel, unreachable for real deliveries under the int32-ns window
    budget (path latency + window < ~2.1 s)."""
    return jnp.where(valid, t.astype(jnp.uint32) ^ _SIGN32, _U32_MAX)


def _pack_rank_key(valid, rank, width: int):
    """Fuse (invalid-last, column-rank) into one uint32 key; `width` is
    the static column count, so the rank field's bit budget is checked at
    trace time against the capacities that determine it. Used where the
    ONLY ordering requirement is valid-first-in-original-order (the
    ingest_rows merge): the sort then carries a single array and the
    permutation is recovered from the key's low bits."""
    rank_bits = max(int(width - 1).bit_length(), 1)
    _assert_bit_budget((1, "validity"), (rank_bits, f"rank[{width}]"))
    return jnp.where(valid, jnp.uint32(0), _SIGN32) | rank.astype(jnp.uint32)


def _row_perm_sort(packed, *extra_keys):
    """Stable row sort of (packed uint32 key [, extra keys]); returns the
    permutation [N, C] to apply to payload columns via take_along_axis.
    Stability makes the carried column index break ties in original
    order, exactly like the variadic stable sort it replaces."""
    N, C = packed.shape
    col = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (N, C))
    out = jax.lax.sort((packed, *extra_keys, col), dimension=1,
                       is_stable=True, num_keys=1 + len(extra_keys))
    return out[-1]


def _pkt_uniform(rng_root: jax.Array, host: jax.Array,
                 counter: jax.Array) -> jax.Array:
    """Counter-based uniform [0,1) draw per (host, counter) slot.

    One batched threefry_2x32 block cipher over all slots: the (host,
    counter) pair IS the cipher's counter block, so the stream depends
    only on (root_key, host, counter) — identical under any
    vectorization, sharding, or queue occupancy (the determinism
    contract) — while lowering to a single fused elementwise kernel.
    (The per-slot `fold_in` formulation computed 2 full hashes per slot
    through vmap and dominated the whole window step: 40 ms vs 0.1 ms
    for this at 65k slots on a v5e.)
    """
    from jax.extend import random as jex_random

    shape = host.shape
    kd = jax.random.key_data(rng_root).astype(jnp.uint32)
    count = jnp.concatenate([
        host.reshape(-1).astype(jnp.uint32),
        counter.reshape(-1).astype(jnp.uint32),
    ])
    bits = jex_random.threefry_2x32(kd, count)[: host.size].reshape(shape)
    # 24 high-entropy bits -> float32 [0,1) (loss thresholds don't need
    # more resolution than the CPU plane's Python float comparison)
    return (bits >> 8).astype(jnp.float32) * np.float32(1.0 / (1 << 24))


def _scatter_append(group, in_order_rank_src, n_valid, cap, n_groups):
    """Deterministic append-slot allocation for grouped scatter.

    `group` [B]: destination row per item, already SORTED ascending (items
    for the same row in their deterministic order); values >= n_groups mean
    "drop". `n_valid` [n_groups]: current occupancy per row. Returns
    (flat_idx [B] into a [n_groups, cap] buffer with out-of-bounds for
    dropped/overflowed items, ok mask, overflow count per group).
    """
    # rank within group = i - first-occurrence(group[i]); group is sorted,
    # so first-occurrence is a running cummax over segment starts (O(B),
    # vs the O(B log B) searchsorted(group, group) that cost 9.5 ms at 65k)
    idx = jnp.arange(group.shape[0], dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), group[1:] != group[:-1]])
    first = jax.lax.cummax(jnp.where(is_start, idx, 0))
    rank = idx - first
    in_range = group < n_groups
    slot = jnp.where(
        in_range, n_valid[jnp.clip(group, 0, n_groups - 1)] + rank, cap
    )
    ok = in_order_rank_src & (slot < cap) & in_range
    flat_idx = jnp.where(ok, group * cap + slot, n_groups * cap)
    overflow = jax.ops.segment_sum(
        (in_order_rank_src & in_range & (slot >= cap)).astype(jnp.int32),
        jnp.clip(group, 0, n_groups - 1),
        num_segments=n_groups,
    )
    return flat_idx, ok, overflow


def ingest(state: NetPlaneState, src: jax.Array, dst: jax.Array,
           nbytes: jax.Array, prio: jax.Array, seq: jax.Array,
           ctrl: jax.Array, valid: jax.Array | None = None,
           send_rel: jax.Array | None = None,
           clamp_rel: jax.Array | None = None,
           sock: jax.Array | None = None, *,
           packed_sort: bool = True,
           metrics: PlaneMetrics | None = None,
           guards: GuardState | None = None):
    """Append a batch of outbound packets ([B] arrays; src = emitting host
    index) to the egress queues. Slots are allocated after the current valid
    entries per row; overflow beyond capacity is counted and dropped.
    `valid` masks out dead batch slots (fixed-shape on-device producers).
    `send_rel` is each packet's emission time relative to the current
    window start (defaults to 0 = window start), giving per-packet deliver
    times that bitwise-match the CPU plane's now + latency.

    `metrics` (static presence) threads the telemetry counters: ring
    overflow drops accumulate into `drop_ring_full` and the call returns
    (state', metrics') instead of state' — the simulation state itself is
    bitwise-unchanged (the drop delta is read off the state's own
    n_overflow_dropped counter).

    `guards` (static presence, docs/robustness.md) threads the runtime
    invariant checks: append conservation (each row gains exactly
    incoming - overflow entries) accumulates into the violation bitmask
    and guards' is appended to the return. Pure reads — the simulation
    state is untouched.

    `packed_sort` (static) selects the bucketed flat-append diet: src is
    a bounded bucket id, so the deterministic (src, seq) append order
    needs only ONE diet sort carrying (bucket, sign-biased seq, batch
    index) plus binary-searched bucket bounds for the counting
    placement, and the payload columns land via one fused stacked
    gather straight from the batch layout — vs the 9-array 2-key
    variadic sort it replaces (kept as the parity-test reference under
    `packed_sort=False`, bitwise-identical for in-domain src).

    The CPU syscall plane calls this once per round with everything the
    sockets emitted (double-buffered host arrays in the full system)."""
    N, CE = state.eg_dst.shape
    if valid is not None:
        # dead slots route to src N (out of range) and never place
        src = jnp.where(valid, src, N)
    if send_rel is None:
        send_rel = jnp.zeros_like(seq)
    if clamp_rel is None:
        clamp_rel = jnp.full_like(seq, NO_CLAMP)
    if sock is None:
        sock = jnp.zeros_like(seq)

    n_valid = state.eg_valid.sum(axis=1).astype(jnp.int32)  # [N]
    # rows are front-compacted (window_step re-sorts), so slot placement is
    # append; overflowing packets get an out-of-bounds index and drop
    incoming = None
    if packed_sort:
        # bucketed counting placement (same shape as the routing stage,
        # `_routing_rank`/`_routing_place`): ONE diet sort establishes
        # the (src, seq) append order, binary search bounds each row's
        # segment, and every payload column lands via one fused stacked
        # gather — base entries where the row already had them, the
        # segment's stream items in the appended slots
        B = src.shape[0]
        src_b = jnp.where((src >= 0) & (src < N), src, N)
        pos = jnp.arange(B, dtype=jnp.int32)
        o_src, _, o_pos = jax.lax.sort(
            (src_b, seq.astype(jnp.uint32) ^ _SIGN32, pos),
            dimension=0, is_stable=True, num_keys=2)
        bounds = jnp.searchsorted(
            o_src, jnp.arange(N + 1, dtype=jnp.int32)).astype(jnp.int32)
        offsets, counts = bounds[:-1], bounds[1:] - bounds[:-1]
        take_n = jnp.minimum(counts, jnp.int32(CE) - n_valid)
        overflow = jnp.maximum(counts + n_valid - CE, 0)
        incoming = counts
        flat = lambda a: a.reshape(-1)
        streams = jnp.stack([
            dst[o_pos], nbytes[o_pos], prio[o_pos], seq[o_pos],
            ctrl[o_pos].astype(jnp.int32), send_rel[o_pos],
            clamp_rel[o_pos], sock[o_pos], jnp.ones((B,), jnp.int32)])
        bases = jnp.stack([
            flat(state.eg_dst), flat(state.eg_bytes), flat(state.eg_prio),
            flat(state.eg_seq), flat(state.eg_ctrl.astype(jnp.int32)),
            flat(state.eg_tsend), flat(state.eg_clamp),
            flat(state.eg_sock), flat(state.eg_valid.astype(jnp.int32))])
        combined = jnp.concatenate([bases, streams], axis=1)
        ce_col = jnp.arange(CE, dtype=jnp.int32)[None, :]
        nv = n_valid[:, None]
        append = (ce_col >= nv) & (ce_col < nv + take_n[:, None])
        stream_idx = jnp.clip(offsets[:, None] + ce_col - nv, 0, B - 1)
        rows_i = jnp.arange(N, dtype=jnp.int32)[:, None]
        gidx = jnp.where(append, N * CE + stream_idx,
                         rows_i * CE + ce_col)
        merged = combined[:, gidx]  # one [9, N, CE] gather
        (eg_dst, eg_bytes, eg_prio, eg_seq, eg_ctrl_i, eg_tsend,
         eg_clamp, eg_sock, eg_valid_i) = merged
        eg_ctrl, eg_valid = eg_ctrl_i != 0, eg_valid_i != 0
    else:
        # the pre-diet reference: rank within each src group via one
        # variadic sort carrying every payload column
        (src_s, seq_s, dst_s, bytes_s, prio_s, ctrl_s, tsend_s, clamp_s,
         # shadowlint: disable=SL403 -- pre-diet variadic reference path
         sock_s) = jax.lax.sort(
            (src, seq, dst, nbytes, prio, ctrl, send_rel, clamp_rel, sock),
            dimension=0, is_stable=True, num_keys=2,
        )
        live = jnp.ones_like(src_s, bool)
        flat, ok, overflow = _scatter_append(src_s, live, n_valid, CE, N)
        if guards is not None:
            # incoming per row: live batch slots routed to in-range rows
            # (dead slots went to src N and fall off the segment sum)
            incoming = jax.ops.segment_sum(
                (src_s < N).astype(jnp.int32),
                jnp.clip(src_s, 0, N - 1), num_segments=N)

        def put(buf, vals):
            return buf.reshape(-1).at[flat].set(
                vals, mode="drop").reshape(N, CE)

        eg_dst = put(state.eg_dst, dst_s)
        eg_bytes = put(state.eg_bytes, bytes_s)
        eg_prio = put(state.eg_prio, prio_s)
        eg_seq = put(state.eg_seq, seq_s)
        eg_ctrl = put(state.eg_ctrl, ctrl_s)
        eg_tsend = put(state.eg_tsend, tsend_s)
        eg_clamp = put(state.eg_clamp, clamp_s)
        eg_sock = put(state.eg_sock, sock_s)
        eg_valid = put(state.eg_valid, jnp.ones_like(ok))
    new_state = state._replace(
        eg_dst=eg_dst, eg_bytes=eg_bytes, eg_prio=eg_prio, eg_seq=eg_seq,
        eg_ctrl=eg_ctrl, eg_tsend=eg_tsend, eg_clamp=eg_clamp,
        eg_sock=eg_sock, eg_valid=eg_valid,
        n_overflow_dropped=state.n_overflow_dropped + overflow,
    )
    if guards is not None:
        guards = guards_plane.check_ingest(
            guards,
            occ_before=n_valid,
            occ_after=eg_valid.sum(axis=1, dtype=jnp.int32),
            incoming=incoming, overflow=overflow)
    out = (new_state,)
    if metrics is not None:
        out += (metrics._replace(
            drop_ring_full=metrics.drop_ring_full + overflow),)
    if guards is not None:
        out += (guards,)
    return out if len(out) > 1 else new_state


def chain_windows(state: NetPlaneState, params: NetPlaneParams,
                  rng_root: jax.Array, shift0, window0_ns, runahead_ns,
                  horizon_rel, stop_rel, max_windows: int = 64, *,
                  rr_enabled: bool = True, router_aqm: bool = False,
                  no_loss: bool = False, kernel: str = "xla",
                  faults: FaultArrays | None = None,
                  metrics: PlaneMetrics | None = None,
                  guards: GuardState | None = None,
                  hist: PlaneHistograms | None = None,
                  flightrec: FlightRecArrays | None = None,
                  workload=None, flows=None, compute=None, round0=0):
    """Advance consecutive scheduling windows ON DEVICE until one delivers.

    The device-resident analogue of the controller's window chain
    (`controller.rs:87-113`): the first window ([shift0-rebased start,
    +window0_ns)) runs unconditionally; afterwards, while a window
    delivered nothing and the device's next event stays below both
    `horizon_rel` (the earliest CPU-side event) and `stop_rel` (simulation
    end), the next window opens at that next event with length
    min(runahead_ns, stop_rel - start) — exactly the boundaries the CPU
    controller would pick, since runahead only changes at capture time and
    nothing is captured during an idle chain. One `lax.while_loop`, zero
    host round trips for delivery-free windows.

    `horizon_rel`/`stop_rel` are relative to the first window's start and
    must be pre-clamped to <= I32_MAX // 2 by the caller (the chain simply
    stops at the clamp and Python takes over).

    Every `window_step` presence switch threads through the while_loop
    carry with the same static-presence discipline as the step itself
    (docs/observability.md, docs/robustness.md): `metrics`, `guards`,
    `hist`, and `flightrec` pytrees accumulate across every chained
    window with zero added host syncs — the chain is audited per
    variant (`analysis/jaxpr_audit.py` `chain_windows[metrics]` /
    `[guards]` / `[workload]`) so a sync smuggled into the carry fails
    the build. `workload=(wl, ws)` additionally runs the traffic
    generator's `workload_step` after each chained window (its
    emission re-arms the next-event reduction, so a chain never sleeps
    through traffic the generator just queued); `round0` is the
    driver's window counter for `done_win` stamping. `flows=(ft, fs0)`
    threads the device flow plane (docs/robustness.md "Flow plane")
    through the carry the same way — its emission (retransmissions,
    delayed acks) re-arms the next-event reduction too, so an idle
    chain can never sleep through a pending retransmission; mutually
    exclusive with `workload` here (the scenario runner interleaves
    the two through `flow_recv`/`flow_emit` around the phase credits
    instead — workloads/runner.py). `compute=(ct, cs0)` threads the
    device compute plane (`tpu/compute.py`) through the carry the
    same way; it emits no traffic (service completions only gate
    phase credits in the runner's split-form loop), so it never
    re-arms the next-event reduction — an idle chain may sleep
    through a backlog draining, which is fine because nothing
    observes the backlog until the next delivery wakes the chain.
    `kernel` selects the plane kernel like `window_step`
    ("xla" | "pallas" | "pallas_fused").

    Returns (state, delivered, off, next_rel, n_windows[, metrics']
    [, guards'][, hist'][, flightrec'][, ws'][, fs'][, cs']) —
    presence outputs appended in `window_step` order, the workload /
    flow / compute state last. `off` is the LAST window's start relative to the
    first window's start — `delivered` times and `next_rel` are
    relative to that last window's start.
    """
    if workload is not None and flows is not None:
        raise ValueError(
            "chain_windows composes workload= or flows=, not both: a "
            "workload riding a flow transport must interleave the "
            "phase credits between flow_recv and flow_emit, which is "
            "the scenario runner's split-form loop "
            "(workloads/runner.py)")
    if workload is not None:
        from ..workloads import device as _wdevice

        wl, ws0 = workload
    else:
        wl = ws0 = None
    if flows is not None:
        ft, fs0 = flows
    else:
        ft = fs0 = None
    if compute is not None:
        ctab, cs0 = compute
    else:
        ctab = cs0 = None

    def step(st, planes, shift, window_ns, ridx):
        m, g, h, fr, ws, fstate, cstate = planes
        out = window_step(st, params, rng_root, shift, window_ns,
                          rr_enabled=rr_enabled, router_aqm=router_aqm,
                          no_loss=no_loss, kernel=kernel, faults=faults,
                          metrics=m, guards=g, hist=h, flightrec=fr,
                          flows=(ft, fstate) if fstate is not None
                          else None,
                          compute=(ctab, cstate) if cstate is not None
                          else None)
        (st, delivered, next_ev), m, g, h, fr, fstate, cstate = \
            unpack_planes(out, metrics=m, guards=g, hist=h,
                          flightrec=fr, flows=fstate, compute=cstate)
        if fstate is not None:
            from . import flows as _flows_mod

            # the flow emission (retransmits / delayed acks) may have
            # re-armed an empty egress ring, exactly like the workload
            # emission below — and a pending RTO deadline must wake
            # the chain even when NOTHING is in flight (every packet
            # of a window lost): the deadline is a real future event,
            # relative to this window's end = window_ns + rel
            next_ev = jnp.minimum(
                next_ev, jnp.where(st.eg_valid.any(), window_ns,
                                   I32_MAX))
            rto_rel = _flows_mod.next_deadline_rel_ns(ft, fstate)
            # guard the add against the no-deadline sentinel: rel is
            # clamped <= I32_MAX//2 when a timer pends (window_ns <=
            # I32_MAX//4 by the spec budget), and the min below keeps
            # the sentinel lane's add in-range too — its sum is
            # discarded by the where, but the SL506 range proof
            # (analysis/ranges.py `chain_windows[flows]`) covers every
            # computed lane, not just the selected ones
            wake = jnp.where(rto_rel > I32_MAX // 2, I32_MAX,
                             jnp.int32(window_ns)
                             + jnp.minimum(rto_rel, I32_MAX // 2))
            next_ev = jnp.minimum(next_ev, wake)
        if ws is not None:
            wout = _wdevice.workload_step(wl, ws, st, delivered, ridx,
                                          window_ns, metrics=m, guards=g)
            if m is not None and g is not None:
                st, ws, m, g = wout
            elif m is not None:
                st, ws, m = wout
            elif g is not None:
                st, ws, g = wout
            else:
                st, ws = wout
            # the emission may have re-armed an empty egress ring: the
            # next pending event is then this window's end, exactly as
            # window_step would have reported had the packets been
            # queued before the step
            next_ev = jnp.minimum(
                next_ev, jnp.where(st.eg_valid.any(), window_ns,
                                   I32_MAX))
        return st, delivered, next_ev, (m, g, h, fr, ws, fstate, cstate)

    hs = jnp.minimum(jnp.int32(horizon_rel), jnp.int32(stop_rel))

    planes = (metrics, guards, hist, flightrec, ws0, fs0, cs0)
    state, delivered, next_ev, planes = step(
        state, planes, jnp.int32(shift0), jnp.int32(window0_ns),
        jnp.int32(round0))

    def keep_going(delivered, off, next_ev):
        # hs - off > 0 and both < I32_MAX//2, so no overflow anywhere —
        # no longer hand-reasoned: the SL506 range proof
        # (analysis/ranges.py `chain_windows`) closes the whole chain
        # loop's arithmetic by refining the carry intervals with THIS
        # predicate (`next_ev < hs - off` bounds off + next_ev below
        # I32_MAX inside the body, for all inputs in the registered
        # domains)
        return (~delivered["mask"].any()) & (next_ev < hs - off)

    def cond(c):
        _state, delivered, off, next_ev, n, _planes = c
        return keep_going(delivered, off, next_ev) & (n < max_windows)

    def body(c):
        st, _delivered, off, next_ev, n, planes = c
        off2 = off + next_ev
        window = jnp.minimum(jnp.int32(runahead_ns),
                             jnp.int32(stop_rel) - off2)
        st, delivered, next2, planes = step(st, planes, next_ev, window,
                                            jnp.int32(round0) + n)
        return (st, delivered, off2, next2, n + 1, planes)

    state, delivered, off, next_ev, n, planes = jax.lax.while_loop(
        cond, body,
        (state, delivered, jnp.int32(0), next_ev, jnp.int32(1), planes),
    )
    m, g, h, fr, ws, fstate, cstate = planes
    out = (state, delivered, off, next_ev, n)
    out += tuple(p for p in (m, g, h, fr) if p is not None)
    if workload is not None:
        out += (ws,)
    if flows is not None:
        out += (fstate,)
    if compute is not None:
        out += (cstate,)
    return out


_UNSET = object()


def unpack_planes(out, *, metrics=None, guards=None, hist=None,
                  flightrec=None, flows=_UNSET, compute=_UNSET,
                  n_lead=3):
    """Split a `window_step` (n_lead=3) or `ingest_rows` (n_lead=1)
    output into its lead values plus the presence-switch outputs, in
    the ONE declaration order both kernels append them — metrics,
    guards, hist, flightrec[, flows]. Pass the same presence pytrees
    the kernel call received: each non-None plane comes back as its
    output, each None stays None, so a driver writes

        (st, delivered, nxt), m, g, h, fr = unpack_planes(
            out, metrics=m, guards=g, hist=h, flightrec=fr)

    instead of hand-maintaining a per-site pop sequence (a mis-ordered
    pop swaps two pytrees silently until trace time — every window
    driver shares this one unpacker for the same reason they share
    `elastic.drive_chained_windows`).

    `flows` is the FlowState the kernel's ``flows=(ft, fs)`` pair
    carried (the tables are static). Passing it — even as None — adds
    a sixth slot to the return, so flow-plane drivers unpack
    ``(lead), m, g, h, fr, fs = unpack_planes(..., flows=fs)``;
    omitting it keeps the legacy five-slot shape. `compute` is the
    ComputeState of the kernel's ``compute=(ct, cs)`` pair and adds a
    further trailing slot the same way (kernel output order: flows
    then compute, both last)."""
    if type(out) is not tuple:
        # bare state: ingest_rows with no planes threaded returns the
        # NetPlaneState itself — which IS a (named)tuple, so the check
        # must be on the exact type, never isinstance
        out = (out,)
    lead, rest = out[:n_lead], list(out[n_lead:])
    want = [metrics, guards, hist, flightrec]
    if flows is not _UNSET:
        want.append(flows)
    if compute is not _UNSET:
        want.append(compute)
    planes = tuple(rest.pop(0) if p is not None else None
                   for p in want)
    if rest:
        raise TypeError(
            f"unpack_planes: {len(rest)} unclaimed kernel output(s) — "
            f"the presence arguments do not match the kernel call's")
    return (lead, *planes)


def compact_delivered(delivered: dict, cap: int):
    """Compress a [N, CI] delivered dict into fixed-[cap] columns for cheap
    device->host transfer: (count, dst, src, seq, sock, deliver_rel).

    The full delivered arrays are N*CI slots of which only a handful are
    usually due per window; pulling them raw costs a whole-array D2H
    transfer per round (the round-3 rung-3 timeout). A stable argsort on
    ~mask front-packs the due slots in row-major order — dst recovered from
    the flat index — so the host reads 5 short columns and a count. If
    count > cap the tail was truncated: callers must detect that and fall
    back to pulling the full arrays (it means ingress_cap-scale bursts;
    raise the compact cap)."""
    mask = delivered["mask"]
    N, CI = mask.shape
    flat = mask.reshape(-1)
    n = flat.sum(dtype=jnp.int32)
    idx = jnp.argsort(~flat, stable=True)[:cap]
    take = lambda a: a.reshape(-1)[idx]
    dst = (idx // CI).astype(jnp.int32)
    dst = jnp.where(take(mask), dst, -1)  # mark dead slots
    return (n, dst, take(delivered["src"]), take(delivered["seq"]),
            take(delivered["sock"]), take(delivered["deliver_rel"]))


def ingest_rows(state: NetPlaneState, dst: jax.Array, nbytes: jax.Array,
                prio: jax.Array, seq: jax.Array, ctrl: jax.Array,
                valid: jax.Array, send_rel: jax.Array | None = None,
                clamp_rel: jax.Array | None = None,
                sock: jax.Array | None = None, *,
                packed_sort: bool = True,
                gate_idle: bool = True,
                metrics: PlaneMetrics | None = None,
                guards: GuardState | None = None,
                hist: PlaneHistograms | None = None,
                flightrec: FlightRecArrays | None = None):
    """Append per-host batches ([N, K] arrays, row = emitting host) to the
    egress queues. The row-shaped twin of `ingest` for producers that are
    already host-major (on-device respawn loops, per-host socket emitters):
    no flat cross-host sort is needed — one row-wise merge sort appends
    each row's valid entries after the existing ones, in column order.

    `packed_sort` (static) selects the single-key merge: validity + column
    rank packed into one uint32, ONE array through the sorting network, and
    the payload columns permuted by the recovered rank — vs the 10-array
    variadic merge it replaces (kept as the reference path for the parity
    tests). `gate_idle` wraps the merge in a `lax.cond` on "any new valid
    entries", so windows that produce nothing pay one reduction instead of
    a full merge sort; both are bitwise no-ops on the result (rows are
    front-packed, so an entry-free merge is the identity — proven per
    build by the SL505 obligation `ingest_rows[gate_idle]`,
    analysis/condeq.py, docs/determinism.md "Branch gates are
    theorems").

    `metrics` (static presence) accumulates ring-overflow drops into
    `drop_ring_full` and switches the return to (state', metrics'); the
    drop delta is read off the state's own n_overflow_dropped counter, so
    the merge itself — and the simulation state — is untouched.

    `guards` (static presence, docs/robustness.md) appends append-
    conservation checking to the return, exactly like `ingest`: each
    row must gain (incoming valid - overflow) entries. Pure reads.

    `hist` (static presence, docs/observability.md "Distributions and
    the flight recorder") samples the post-append egress occupancy
    into the queue-depth histogram; `flightrec` records an `ingest`
    hop for every sampled appended packet. Both are pure reads over
    values the merge already materialized and append to the return
    after metrics/guards: (state'[, metrics'][, guards'][, hist']
    [, flightrec'])."""
    N, CE = state.eg_dst.shape
    if send_rel is None:
        send_rel = jnp.zeros_like(seq)
    if clamp_rel is None:
        clamp_rel = jnp.full_like(seq, NO_CLAMP)
    if sock is None:
        sock = jnp.zeros_like(seq)

    cat = lambda a, b: jnp.concatenate([a, b], axis=1)

    def merge(state: NetPlaneState) -> NetPlaneState:
        valid_all = cat(state.eg_valid, valid)
        W = valid_all.shape[1]
        if packed_sort:
            # stable valid-first order == sort by (validity, column rank);
            # the rank rides in the key's low bits, so the single sorted
            # array IS the permutation
            rank = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), (N, W))
            key = jax.lax.sort(_pack_rank_key(valid_all, rank, W),
                               dimension=1, is_stable=True)
            perm = (key & jnp.uint32(0x7FFFFFFF)).astype(jnp.int32)[:, :CE]
            take = lambda a, b: jnp.take_along_axis(cat(a, b), perm, axis=1)
            dst_m = take(state.eg_dst, dst)
            bytes_m = take(state.eg_bytes, nbytes)
            prio_m = take(state.eg_prio, prio)
            seq_m = take(state.eg_seq, seq)
            ctrl_m = take(state.eg_ctrl, ctrl)
            tsend_m = take(state.eg_tsend, send_rel)
            clamp_m = take(state.eg_clamp, clamp_rel)
            sock_m = take(state.eg_sock, sock)
            valid_m = take(state.eg_valid, valid)
            overflow = jnp.maximum(
                valid_all.sum(axis=1, dtype=jnp.int32) - CE, 0)
        else:
            inv = (~valid_all).astype(jnp.int32)
            # stable sort by validity alone: existing entries (columns
            # < CE, front-packed) stay ahead of the new ones, new entries
            # keep column order
            (_, dst_f, bytes_f, prio_f, seq_f, ctrl_f, tsend_f, clamp_f,
             # shadowlint: disable=SL403 -- pre-diet variadic reference
             sock_f, valid_f) = _row_sort(
                inv, cat(state.eg_dst, dst), cat(state.eg_bytes, nbytes),
                cat(state.eg_prio, prio), cat(state.eg_seq, seq),
                cat(state.eg_ctrl, ctrl), cat(state.eg_tsend, send_rel),
                cat(state.eg_clamp, clamp_rel), cat(state.eg_sock, sock),
                valid_all, keys=1,
            )
            overflow = jnp.maximum(
                valid_f.sum(axis=1, dtype=jnp.int32) - CE, 0)
            dst_m, bytes_m, prio_m = dst_f[:, :CE], bytes_f[:, :CE], \
                prio_f[:, :CE]
            seq_m, ctrl_m, tsend_m = seq_f[:, :CE], ctrl_f[:, :CE], \
                tsend_f[:, :CE]
            clamp_m, sock_m, valid_m = clamp_f[:, :CE], sock_f[:, :CE], \
                valid_f[:, :CE]
        return state._replace(
            eg_dst=dst_m, eg_bytes=bytes_m, eg_prio=prio_m, eg_seq=seq_m,
            eg_ctrl=ctrl_m, eg_tsend=tsend_m, eg_clamp=clamp_m,
            eg_sock=sock_m, eg_valid=valid_m,
            n_overflow_dropped=state.n_overflow_dropped + overflow,
        )

    if not gate_idle:
        new_state = merge(state)
    else:
        new_state = jax.lax.cond(valid.any(), merge, lambda st: st, state)
    overflow_delta = new_state.n_overflow_dropped - state.n_overflow_dropped
    if guards is not None:
        guards = guards_plane.check_ingest(
            guards,
            occ_before=state.eg_valid.sum(axis=1, dtype=jnp.int32),
            occ_after=new_state.eg_valid.sum(axis=1, dtype=jnp.int32),
            incoming=valid.sum(axis=1, dtype=jnp.int32),
            overflow=overflow_delta)
    out = (new_state,)
    if metrics is not None:
        # overflow delta via the state counter: identical through both
        # gate branches (the idle branch's delta is zero by construction)
        out += (metrics._replace(
            drop_ring_full=metrics.drop_ring_full + overflow_delta),)
    if guards is not None:
        out += (guards,)
    if hist is not None:
        # queue-depth sample at the append point (post-merge egress
        # occupancy) — pure read, nothing feeds back
        out += (hist._replace(hist_qdepth=histo.accum_depth(
            hist.hist_qdepth,
            new_state.eg_valid.sum(axis=1, dtype=jnp.int32))),)
    if flightrec is not None:
        # `ingest` hop per sampled ACCEPTED packet, stamped with the
        # UPCOMING window's counter (appends ride between windows) and
        # the emission offset relative to its start. Overflow-dropped
        # batch entries never entered the ring, so they record no hop
        # — their loss is the aggregate drop_ring_full counter, and a
        # phantom `ingest` would read as "queued" to a trace reader.
        # Accepted = the first (CE - occupancy) valid entries per row,
        # exactly the prefix the merge keeps (new entries append after
        # the existing ones in column order).
        rows = jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.int32)[:, None], valid.shape)
        samp = flightrec_mod.sample_mask(flightrec, rows, seq)
        valid_i = valid.astype(jnp.int32)
        new_rank = jnp.cumsum(valid_i, axis=1) - valid_i
        free = (jnp.int32(CE)
                - state.eg_valid.sum(axis=1, dtype=jnp.int32))
        accepted = valid & (new_rank < free[:, None])
        flat = lambda a: a.reshape(-1)
        flightrec = flightrec_mod.record_events(
            flightrec,
            jnp.full((valid.size,), flightrec_mod.HOP_INGEST, jnp.int32),
            flat(rows), flat(seq), flat(dst), flat(send_rel),
            flat(accepted & samp))
        out += (flightrec,)
    return out if len(out) > 1 else new_state


# ---------------------------------------------------------------------------
# window_step sections. Each stage of the per-window pipeline is a named
# helper so (a) the profiler (`tpu/profiling.py`) can time every section in
# isolation with realistic inputs and (b) alternative kernels (the packed
# sort diet, the Pallas egress fusion) swap in per-section without touching
# the rest of the pipeline. window_step composes them; the section
# boundaries are exactly the numbered comments the monolithic body used.
# ---------------------------------------------------------------------------


def _refill_tokens(state: NetPlaneState, params: NetPlaneParams, shift_ns,
                   *, faults: FaultArrays | None = None):
    """Section 1b: lazy 1ms-interval token refill (`relay/token_bucket.rs`);
    the sub-ms remainder carries across rounds so short windows don't leak
    bandwidth. Returns (balance, tb_rem_ns).

    `faults` (static presence) applies per-host bandwidth degradation:
    the refill rate is divided by `bw_div` (the rate-proportional part
    of the capacity scales with it, the MTU burst term does not).
    `bw_div=1` is bitwise-identity with faults=None."""
    rate, cap = params.tb_rate, params.tb_cap
    if faults is not None:
        rate = jnp.maximum(rate // jnp.maximum(faults.bw_div, 1), 1)
        cap = rate + (params.tb_cap - params.tb_rate)
    rem_total = state.tb_rem_ns + (shift_ns % 1_000_000)
    elapsed_ms = (shift_ns // 1_000_000) + (rem_total // 1_000_000)
    tb_rem_ns = rem_total % 1_000_000
    # refill only up to the headroom, clamping elapsed BEFORE multiplying:
    # rate * elapsed_eff <= headroom + rate <= cap + rate, inside int32 for
    # any rate <= 2^30 - MTU (make_params guarantees it) — the naive
    # balance + rate*fill_ms wrapped negative for rates near 1e9 B/ms and
    # stalled every egress queue for one round. The headroom form of the
    # final clamp (min(u, c) == c - max(c - u, 0) for a non-negative
    # refund) keeps every intermediate interval-bounded: the SL506 range
    # proof (analysis/ranges.py, window_step entries) closes this whole
    # section as a theorem instead of this comment's relational argument.
    headroom = jnp.maximum(cap - state.tb_balance, 0)
    need_ms = (headroom + rate - 1) // rate
    elapsed_eff = jnp.minimum(elapsed_ms, need_ms)
    balance = cap - jnp.maximum(headroom - rate * elapsed_eff, 0)
    return balance, tb_rem_ns


def _qdisc_keys(state: NetPlaneState, params: NetPlaneParams, *,
                rr_enabled: bool):
    """Section 2a: per-slot qdisc sort keys. FIFO = packet priority;
    round-robin = virtual-finish counter per socket slot (the [N, CE, CE]
    pairwise rank tensors — the dominant per-window cost when N < CE^2,
    which is why all-FIFO callers compile with rr_enabled=False). Returns
    (qkey1, qkey2, rr_aux) with rr_aux = (rr_base, vtime) bookkeeping the
    RR advance needs later (None when rr_enabled=False)."""
    if not rr_enabled:
        return state.eg_prio, jnp.zeros_like(state.eg_sock), None
    S = RR_SOCK_SLOTS
    sock_slot = jnp.where(state.eg_valid, state.eg_sock % S, S - 1)
    # active sockets re-join at the current virtual time (start-time
    # fair queuing floor) so a returning socket gets its fair turn, not
    # a burst; rows with nothing queued reset to 0 (counters only mean
    # anything relative to each other, and the rebase below keeps every
    # value within ~CE of zero, so int32 never wraps)
    slot_onehot = sock_slot[:, :, None] == jnp.arange(S, dtype=jnp.int32)
    active = (slot_onehot & state.eg_valid[:, :, None]).any(axis=1)
    vtime = jnp.where(active, state.rr_sent, I32_MAX).min(axis=1)  # [N]
    vtime = jnp.where(active.any(axis=1), vtime, 0)
    rr_base = jnp.maximum(state.rr_sent, vtime[:, None])  # [N, S]
    same_sock = sock_slot[:, :, None] == sock_slot[:, None, :]
    both_valid = state.eg_valid[:, :, None] & state.eg_valid[:, None, :]
    earlier = state.eg_seq[:, None, :] < state.eg_seq[:, :, None]
    rr_rank = jnp.sum(same_sock & both_valid & earlier, axis=2,
                      dtype=jnp.int32)
    rr_key = jnp.take_along_axis(rr_base, sock_slot, axis=1) + rr_rank
    rr_mode = params.qdisc_rr[:, None]
    qkey1 = jnp.where(rr_mode, rr_key, state.eg_prio)
    qkey2 = jnp.where(rr_mode, state.eg_sock, 0)
    return qkey1, qkey2, (rr_base, vtime)


def _egress_order(state: NetPlaneState, qkey1, qkey2, eg_tsend_rb,
                  eg_clamp_rb, *, rr_enabled: bool, packed_sort: bool):
    """Section 2b: the qdisc sort. Orders each egress row valid-first by
    (qkey1, qkey2). Packed form: ONE uint32 (validity | qkey1) key plus —
    only under RR, where socket ids break rr-key ties — qkey2, with the
    payload columns permuted afterwards; vs the 12-array variadic sort
    (kept as the parity-reference path). Returns the 9 sorted columns
    (prio, sock, dst, bytes, seq, ctrl, tsend, clamp, valid).

    FIFO packed rows additionally gate the sort on a cheap
    already-ordered check (the steady-state fast path): the leftover
    prefix left by `_compact_egress` is in (validity | priority) order
    already, and monotone-priority producers (the PHOLD respawn, the
    workload emitters — seq-derived priorities) append in order too,
    so most windows' rows arrive with a non-decreasing packed key. A
    stable sort of a non-decreasing key with the column-index tiebreak
    IS the identity, so both branches are bitwise-equal always — the
    gate can only change speed, never a bit (same contract as
    `ingest_rows`' gate_idle; proven structurally per build by SL505
    `_egress_order[fifo-ordered]`, analysis/condeq.py)."""
    if packed_sort:
        packed = _pack_valid_key(state.eg_valid, qkey1)
        if not rr_enabled:
            ordered = (packed[:, :-1] <= packed[:, 1:]).all()

            def ident(_):
                return (state.eg_prio, state.eg_sock, state.eg_dst,
                        state.eg_bytes, state.eg_seq, state.eg_ctrl,
                        eg_tsend_rb, eg_clamp_rb, state.eg_valid)

            def do_sort(packed):
                perm = _row_perm_sort(packed)
                take = lambda a: jnp.take_along_axis(a, perm, axis=1)
                return (take(state.eg_prio), take(state.eg_sock),
                        take(state.eg_dst), take(state.eg_bytes),
                        take(state.eg_seq), take(state.eg_ctrl),
                        take(eg_tsend_rb), take(eg_clamp_rb),
                        take(state.eg_valid))

            return jax.lax.cond(ordered, ident, do_sort, packed)
        extra = (qkey2,) if rr_enabled else ()
        perm = _row_perm_sort(packed, *extra)
        take = lambda a: jnp.take_along_axis(a, perm, axis=1)
        return (take(state.eg_prio), take(state.eg_sock),
                take(state.eg_dst), take(state.eg_bytes),
                take(state.eg_seq), take(state.eg_ctrl),
                take(eg_tsend_rb), take(eg_clamp_rb), take(state.eg_valid))
    inv = (~state.eg_valid).astype(jnp.int32)
    (_, _, _, eg_prio, eg_sock, eg_dst, eg_bytes, eg_seq, eg_ctrl,
     # shadowlint: disable=SL403 -- pre-diet variadic reference path
     eg_tsend, eg_clamp, eg_valid) = _row_sort(
        inv, qkey1, qkey2, state.eg_prio, state.eg_sock, state.eg_dst,
        state.eg_bytes, state.eg_seq, state.eg_ctrl, eg_tsend_rb,
        eg_clamp_rb, state.eg_valid, keys=3,
    )
    return (eg_prio, eg_sock, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
            eg_clamp, eg_valid)


def _token_gate(eg_valid, eg_bytes, balance):
    """Section 2c: prefix-sum token-bucket gate over the sorted egress.
    Returns (sendable, balance_after)."""
    cum = jnp.cumsum(jnp.where(eg_valid, eg_bytes, 0), axis=1)
    sendable = eg_valid & (cum <= balance[:, None])
    spent = jnp.where(sendable, eg_bytes, 0).sum(axis=1)
    return sendable, balance - spent


def _rr_advance(eg_sock, eg_valid, sendable, rr_aux):
    """Section 2d: advance the RR virtual-finish counters by packets
    pushed through, then rebase to the floor so counters stay bounded
    (per the dtype discipline)."""
    S = RR_SOCK_SLOTS
    rr_base, vtime = rr_aux
    sent_slot = jnp.where(eg_valid, eg_sock % S, S - 1)
    sent_per_sock = jnp.sum(
        (sent_slot[:, :, None] == jnp.arange(S, dtype=jnp.int32))
        & sendable[:, :, None], axis=1, dtype=jnp.int32)
    return rr_base - vtime[:, None] + sent_per_sock


def _loss_latency(state: NetPlaneState, params: NetPlaneParams, rng_root,
                  eg_dst, eg_ctrl, eg_tsend, eg_clamp, sendable, window_ns,
                  *, no_loss: bool, faults: FaultArrays | None = None):
    """Section 3: Bernoulli path-loss draw + latency lookup through the
    node-level tables (host -> node, then the [M, M] path matrices — vs a
    [N, N] host-pair gather whose per-element HBM cost dominated the step
    at 4k+ hosts). Returns (sent, lost, rng_counter', deliver_rel); with
    `faults` threaded (static presence) the return gains a `corrupt`
    mask after `lost`: (sent, lost, corrupt, rng_counter', deliver_rel).

    Fault handling here: (a) burst corruption — an extra Bernoulli drawn
    from an INDEPENDENT counter-based stream (host index offset by N, so
    the loss stream is untouched and a corruption schedule never changes
    which packets the base world loss-drops); control packets exempt,
    like path loss. (b) per-link latency degradation — `lat_mult` as an
    integer multiplier with the latency pre-clamped to the int32 window
    budget so the multiply can never wrap; `mult=1` is bitwise identity.
    """
    N, CE = eg_dst.shape
    host_idx = jnp.arange(N, dtype=jnp.int32)[:, None]
    dst_clipped = jnp.clip(eg_dst, 0, N - 1)
    node_src = params.host_node[:, None]  # [N, 1]
    node_dst = params.host_node[dst_clipped]  # [N, CE]
    if no_loss:
        # transport mode: the loss draw happened on the CPU at capture
        # (loss matrix is all zero) — skip the gather and the RNG entirely
        lost = jnp.zeros_like(sendable)
        sent = sendable
    else:
        counter = state.rng_counter[:, None] + jnp.arange(CE, dtype=jnp.int32)
        u = _pkt_uniform(rng_root, jnp.broadcast_to(host_idx, (N, CE)),
                         counter)
        p_loss = params.loss[jnp.broadcast_to(node_src, (N, CE)), node_dst]
        lost = sendable & (u < p_loss) & ~eg_ctrl
        sent = sendable & ~lost
    corrupt = None
    if faults is not None:
        counter2 = state.rng_counter[:, None] + jnp.arange(CE,
                                                           dtype=jnp.int32)
        u2 = _pkt_uniform(rng_root,
                          jnp.broadcast_to(host_idx + N, (N, CE)), counter2)
        corrupt = (sendable & ~lost & ~eg_ctrl
                   & (u2 < faults.corrupt_p[:, None]))
        sent = sent & ~corrupt
    # draws consumed only for slots that attempted transmission, keeping the
    # stream independent of queue occupancy beyond the sendable prefix
    rng_counter = state.rng_counter + sendable.sum(axis=1, dtype=jnp.int32)

    latency = params.latency_ns[jnp.broadcast_to(node_src, (N, CE)), node_dst]
    if faults is not None:
        mult = jnp.maximum(faults.lat_mult[
            jnp.broadcast_to(node_src, (N, CE)), node_dst], 1)
        degraded = jnp.minimum(latency, (I32_MAX // 2) // mult) * mult
        latency = jnp.where(mult > 1, degraded, latency)
    # send time + latency, but no earlier than the round barrier the packet
    # was sent under (`worker.rs:396-399`); NO_CLAMP means "this window's
    # end" (pure-device mode, where ingest and step share the window)
    clamp_eff = jnp.where(eg_clamp == NO_CLAMP, window_ns, eg_clamp)
    deliver_rel = jnp.maximum(eg_tsend + latency, clamp_eff)
    if faults is not None:
        return sent, lost, corrupt, rng_counter, deliver_rel
    return sent, lost, rng_counter, deliver_rel


def _compact_ingress(state: NetPlaneState, in_deliver, *, packed_sort: bool):
    """Section 4: compact surviving ingress, front-packed by deliver time
    for the scatter. Packed form: one uint32 (validity | sign-biased
    deliver) key + permutation; reference form: the 7-array variadic sort.
    Returns (deliver_c, src_c, seq_c, sock_c, bytes_c, valid_c,
    n_valid_in).

    The packed form gates the sort on an already-ordered check: after
    the first window, the surviving ingress is EXACTLY what
    `_release_due` (or the AQM keep-compaction) left — front-packed
    ascending by deliver, garbage lanes behind — and the window rebase
    is monotone, so the packed key arrives non-decreasing and the sort
    is the identity. A stable 1-key sort of a non-decreasing key with
    the column tiebreak IS the identity (equal keys keep column
    order), so the branches are bitwise-equal for every input — the
    gate trades a [N, CI] compare for the dominant steady-state row
    sort. Proven structurally per build (SL505
    `_compact_ingress[ordered]`: the sort-of-sorted rewrite + a
    selection witness, analysis/condeq.py)."""
    key_deliver = jnp.where(state.in_valid, in_deliver, I32_MAX)
    if packed_sort:
        packed = _pack_time_key(state.in_valid, key_deliver)
        ordered = (packed[:, :-1] <= packed[:, 1:]).all()

        def ident(_):
            return (key_deliver, state.in_src, state.in_seq,
                    state.in_sock, state.in_bytes, state.in_valid)

        def do_sort(packed):
            perm = _row_perm_sort(packed)
            take = lambda a: jnp.take_along_axis(a, perm, axis=1)
            return (take(key_deliver), take(state.in_src),
                    take(state.in_seq), take(state.in_sock),
                    take(state.in_bytes), take(state.in_valid))

        (in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
         in_valid_c) = jax.lax.cond(ordered, ident, do_sort, packed)
    else:
        inv_in = (~state.in_valid).astype(jnp.int32)
        (_, in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
         # shadowlint: disable=SL403 -- pre-diet variadic reference path
         in_valid_c) = _row_sort(
            inv_in, key_deliver, state.in_src, state.in_seq, state.in_sock,
            state.in_bytes, state.in_valid, keys=2,
        )
    n_valid_in = in_valid_c.sum(axis=1).astype(jnp.int32)  # [N]
    return (in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
            in_valid_c, n_valid_in)


def _routing_order(sent, eg_dst, eg_seq, deliver_rel, row_perm=None):
    """Bucketed routing, phase A: establish the deterministic global
    arrival order WITHOUT pushing payload through the flat comparator
    network. The order the CPU plane's event queue imposes per
    destination is (deliver, src, seq); the legacy path realizes it as
    one flat 4-key variadic sort over [N*CE] slots. Here:

    - a row-local stable seq RANK (an [N, CE, CE] pairwise compare —
      CE is small, so this beats a row sort the same way the RR qdisc's
      rank tensors do) permutes each source row into seq order, so the
      flat slot index itself encodes the (src, seq) tiebreak;
    - ONE flat sort then carries just the routing key pair — destination
      bucket + sign-biased deliver time, the 64-bit (dst | deliver) key
      expressed as two uint32/int32 words under the plane's 32-bit dtype
      discipline — plus the flat slot index as the only payload;
    - each bucket's [start, count) segment of the sorted sequence comes
      from a binary search of the bucket ids over the sorted keys
      (O(N log B) — vs an O(B) histogram scatter-add).

    Non-sent slots (and any out-of-domain dst) route to the sentinel
    bucket N, which sorts last and is never placed. Returns
    (row_perm [N, CE] — seq-rank position -> original column,
    o_pos [B] — sorted order -> seq-permuted flat slot,
    offsets/counts [N] — each bucket's segment of the sorted order).

    `row_perm` may be passed in precomputed (the fused Pallas pipeline
    derives it inside the egress kernel while the sorted rows are still
    VMEM-resident); None computes it here via the pairwise rank."""
    N, CE = eg_dst.shape
    B = N * CE
    col = jnp.arange(CE, dtype=jnp.int32)
    if row_perm is None:
        # stable rank of each slot within its row by (seq, column): the
        # qdisc sort left rows in priority order, not seq order, and equal
        # (dst, deliver) arrivals from one source must land by seq
        earlier = ((eg_seq[:, None, :] < eg_seq[:, :, None])
                   | ((eg_seq[:, None, :] == eg_seq[:, :, None])
                      & (col[None, None, :] < col[None, :, None])))
        rank = jnp.sum(earlier, axis=2, dtype=jnp.int32)  # [N, CE]
        rows = jnp.arange(N, dtype=jnp.int32)[:, None]
        # rank is a permutation per row ((seq, col) pairs are distinct),
        # so the scatter inverts it: row_perm[n, rank[n, c]] = c
        row_perm = jnp.zeros((N, CE), jnp.int32).at[rows, rank].set(
            jnp.broadcast_to(col, (N, CE)))
    take_row = lambda a: jnp.take_along_axis(a, row_perm, axis=1)
    sent_p, dst_p = take_row(sent), take_row(eg_dst)
    flat_dst = jnp.where(sent_p & (dst_p >= 0) & (dst_p < N),
                         dst_p, N).reshape(-1)
    deliver_key = take_row(deliver_rel).reshape(-1) \
        .astype(jnp.uint32) ^ _SIGN32
    pos = jnp.arange(B, dtype=jnp.int32)
    # (dst, deliver, pos) is a TOTAL order (pos is distinct), so the
    # unstable sort with pos promoted to a key returns exactly the
    # stable 2-key permutation — and skips the stable-sort machinery,
    # measurably cheaper through XLA:CPU's comparator path
    o_dst, _, o_pos = jax.lax.sort((flat_dst, deliver_key, pos),
                                   dimension=0, is_stable=False,
                                   num_keys=3)
    bounds = jnp.searchsorted(
        o_dst, jnp.arange(N + 1, dtype=jnp.int32)).astype(jnp.int32)
    offsets, counts = bounds[:-1], bounds[1:] - bounds[:-1]
    return row_perm, o_pos, offsets, counts


def _routing_rank(sent, eg_dst, eg_seq, deliver_rel, n_valid_in,
                  ingress_cap: int, row_perm=None):
    """Section 5a (packed): counting placement over the bucketed order.
    Each destination row accepts the first `take` items of its bucket's
    sorted segment — exactly the items whose in-bucket rank fits the
    row's free slots — so placement reduces to per-bucket [N] arithmetic
    over the segment bounds; no per-item destination indices are ever
    materialized. Returns (row_perm, o_pos, offsets, take [N], overflow
    [N])."""
    row_perm, o_pos, offsets, counts = _routing_order(
        sent, eg_dst, eg_seq, deliver_rel, row_perm)
    # per-bucket arithmetic is exact: occupancy never exceeds capacity,
    # so free = CI - n_valid >= 0; arrivals past the free slots drop
    take_n = jnp.minimum(counts, jnp.int32(ingress_cap) - n_valid_in)
    overflow = jnp.maximum(counts + n_valid_in - ingress_cap, 0)
    return row_perm, o_pos, offsets, take_n, overflow


def _routing_place(row_perm, o_pos, offsets, take_n, n_valid_in, eg_seq,
                   eg_bytes, eg_sock, deliver_rel, in_deliver_c, in_src_c,
                   in_seq_c, in_sock_c, in_bytes_c, in_valid_c):
    """Section 5b (packed): land the payload columns with ONE fused
    gather per column (stacked into a single [6, ...] gather) — no flat
    scatters at all. Each merged ingress row is a select between its
    existing entries and its bucket's contiguous segment of the
    arrival-sorted stream; the stream itself is addressed through the
    composed permutation (sorted position -> seq-permuted slot ->
    original slot), so the payload columns are read straight from their
    original layout and never materialize any intermediate."""
    N, CI = in_src_c.shape
    CE = row_perm.shape[1]
    B = N * CE
    flat = lambda a: a.reshape(-1)
    # sorted position -> original flat slot (row-major)
    g = (o_pos // CE) * CE + flat(row_perm)[o_pos]
    streams = jnp.stack([
        (o_pos // CE).astype(jnp.int32),  # src == source row
        flat(eg_seq)[g], flat(eg_sock)[g], flat(eg_bytes)[g],
        flat(deliver_rel)[g],
        jnp.ones((B,), jnp.int32),  # arrivals are valid
    ])
    bases = jnp.stack([
        flat(in_src_c), flat(in_seq_c), flat(in_sock_c), flat(in_bytes_c),
        flat(jnp.where(in_valid_c, in_deliver_c, I32_MAX)),
        flat(in_valid_c.astype(jnp.int32)),
    ])
    combined = jnp.concatenate([bases, streams], axis=1)  # [6, N*CI + B]
    ci_col = jnp.arange(CI, dtype=jnp.int32)[None, :]
    nv = n_valid_in[:, None]
    append = (ci_col >= nv) & (ci_col < nv + take_n[:, None])
    # append lane c of row d reads stream slot offsets[d] + (c - nv[d]);
    # non-append lanes keep the base value (compaction garbage included,
    # exactly like the reference scatters, which never touch them)
    stream_idx = jnp.clip(offsets[:, None] + ci_col - nv, 0, B - 1)
    rows = jnp.arange(N, dtype=jnp.int32)[:, None]
    idx = jnp.where(append, N * CI + stream_idx, rows * CI + ci_col)
    merged = combined[:, idx]  # one [6, N, CI] gather
    return (merged[0], merged[1], merged[2], merged[3], merged[4],
            merged[5] != 0)


def _routing_rank_legacy(sent, eg_dst, eg_seq, eg_bytes, eg_sock,
                         deliver_rel, n_valid_in, ingress_cap: int):
    """Section 5a (reference): the pre-diet flat variadic sort — every
    payload column rides the 4-key comparator network — plus the grouped
    scatter-append ranks. Kept compiled-in under `packed_sort=False` as
    the bitwise parity reference for the bucketed path."""
    N, CE = eg_dst.shape
    host_idx = jnp.arange(N, dtype=jnp.int32)[:, None]
    flat_sent = sent.reshape(-1)
    flat_dst = jnp.where(flat_sent, eg_dst.reshape(-1), N)  # N = "nowhere"
    (o_dst, o_deliver, o_src, o_seq, o_bytes, o_sock,
     # shadowlint: disable=SL403 -- pre-diet variadic reference path
     o_sent) = jax.lax.sort(
        (flat_dst, deliver_rel.reshape(-1),
         jnp.broadcast_to(host_idx, (N, CE)).reshape(-1),
         eg_seq.reshape(-1), eg_bytes.reshape(-1), eg_sock.reshape(-1),
         flat_sent),
        dimension=0, is_stable=True, num_keys=4,
    )
    flat_idx, ok, overflowed = _scatter_append(o_dst, o_sent, n_valid_in,
                                               ingress_cap, N)
    return (flat_idx, ok, o_deliver, o_src, o_seq, o_bytes, o_sock,
            overflowed)


def _routing_place_legacy(flat_idx, ok, o_deliver, o_src, o_seq, o_bytes,
                          o_sock, in_deliver_c, in_src_c, in_seq_c,
                          in_sock_c, in_bytes_c, in_valid_c):
    """Section 5b (reference): per-column scatters from the sorted
    payload of `_routing_rank_legacy`."""
    N, CI = in_src_c.shape

    def scatter(buf, vals):
        return buf.reshape(-1).at[flat_idx].set(
            vals, mode="drop").reshape(N, CI)

    in_src_m = scatter(in_src_c, o_src)
    in_seq_m = scatter(in_seq_c, o_seq)
    in_sock_m = scatter(in_sock_c, o_sock)
    in_bytes_m = scatter(in_bytes_c, o_bytes)
    in_deliver_m = scatter(
        jnp.where(in_valid_c, in_deliver_c, I32_MAX), o_deliver
    )
    # non-ok slots carry an out-of-bounds flat_idx, so only accepted
    # arrivals flip their slot valid
    in_valid_m = scatter(in_valid_c, jnp.ones_like(ok))
    return (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
            in_valid_m)


def _route_scatter(sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
                   in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
                   in_valid_c, n_valid_in, *, packed_sort: bool = True,
                   kernel: str = "xla"):
    """Section 5: route sent packets into destination ingress queues,
    in the deterministic per-destination (deliver, src, seq) insertion
    order the CPU plane's event queue imposes.

    Three implementations, all bitwise-identical for in-domain inputs
    (dst in [0, N), the only thing callers produce):

    - `packed_sort=True` (default): BUCKETED counting placement — dst is
      a bounded key, so the rank computation is one diet flat sort over
      (bucket, deliver, slot-index) plus binary-searched bucket bounds,
      and the payload columns land via one fused stacked gather
      (`_routing_rank` / `_routing_place`). A hypothetical sent packet
      with an out-of-range dst lands in the not-placeable bucket here;
      the reference path drops it through an out-of-bounds scatter
      index instead (same state, the overflow counter may differ for
      that impossible input).
    - `packed_sort=False`: the pre-diet flat 4-key variadic sort, the
      parity-test reference.
    - `kernel="pallas"`: the rank computation feeds the fused
      per-destination-tile append kernel (`tpu.pallas_route`) instead of
      the XLA scatters; interpret mode off-TPU, refused when faults or
      guards are threaded (window_step enforces this at trace time).

    Returns the merged ingress columns + per-host overflow."""
    if kernel == "pallas":
        if not packed_sort:
            raise ValueError(
                "kernel='pallas' implements the packed/bucketed ordering "
                "only; use kernel='xla' for the packed_sort=False parity "
                "reference")
        from . import pallas_route

        return pallas_route.route_scatter(
            sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
            in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
            in_valid_c, n_valid_in)
    CI = in_src_c.shape[1]
    if packed_sort:
        row_perm, o_pos, offsets, take_n, overflowed = _routing_rank(
            sent, eg_dst, eg_seq, deliver_rel, n_valid_in, CI)
        merged = _routing_place(
            row_perm, o_pos, offsets, take_n, n_valid_in, eg_seq,
            eg_bytes, eg_sock, deliver_rel, in_deliver_c, in_src_c,
            in_seq_c, in_sock_c, in_bytes_c, in_valid_c)
        return (*merged, overflowed)
    (flat_idx, ok, o_deliver, o_src, o_seq, o_bytes, o_sock,
     overflowed) = _routing_rank_legacy(
        sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel, n_valid_in,
        CI)
    merged = _routing_place_legacy(
        flat_idx, ok, o_deliver, o_src, o_seq, o_bytes, o_sock,
        in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
        in_valid_c)
    return (*merged, overflowed)


def _release_due(in_deliver_m, in_src_m, in_seq_m, in_sock_m, in_bytes_m,
                 in_valid_m, window_ns, *, packed_sort: bool = True):
    """Section 5b (direct mode): split the merged ingress into this
    window's due deliveries and the surviving queue. One sort serves both:
    not-due first keyed by deliver time keeps the survivors front-packed;
    the due block lands at the row tail in deterministic (deliver_t, src,
    seq) presentation order. The packed form fuses the (is_due, deliver)
    key pair into ONE uint32 via modular subtraction — is_due is exactly
    `deliver < window_ns`, so `biased(deliver) - biased(window_ns)` in
    wrapping uint32 arithmetic sends not-due entries to [0, ..) and due
    entries to the wrapped top of the range, each ascending in deliver:
    precisely the (is_due, deliver) composite order — and carries the
    column index through the now-total-order unstable sort instead of
    the payload columns (the deliver column itself is recovered from
    the wrapped key by adding the bias back). Returns (delivered dict,
    due, surviving ingress columns)."""
    in_deliver_key = jnp.where(in_valid_m, in_deliver_m, I32_MAX)
    due = in_valid_m & (in_deliver_key < window_ns)
    is_due = due.astype(jnp.int32)
    if packed_sort:
        N, CI = due.shape
        col = jnp.broadcast_to(jnp.arange(CI, dtype=jnp.int32), (N, CI))
        w_bias = jnp.int32(window_ns).astype(jnp.uint32) ^ _SIGN32
        wkey = (in_deliver_key.astype(jnp.uint32) ^ _SIGN32) - w_bias
        # (wkey, src, seq, col) is total (col distinct), so the
        # unstable 4-key sort equals the stable (is_due, deliver, src,
        # seq) sort the reference path computes
        (wkey_s, d_src, d_seq, perm) = jax.lax.sort(
            (wkey, in_src_m, in_seq_m, col),
            dimension=1, is_stable=False, num_keys=4,
        )
        d_t = ((wkey_s + w_bias) ^ _SIGN32).astype(jnp.int32)
        take = lambda a: jnp.take_along_axis(a, perm, axis=1)
        d_sock, d_bytes = take(in_sock_m), take(in_bytes_m)
        d_due, d_valid = take(due), take(in_valid_m)
    else:
        (_, d_t, d_src, d_seq, d_sock, d_bytes, d_due,
         # shadowlint: disable=SL403 -- pre-diet variadic reference path
         d_valid) = _row_sort(
            is_due, in_deliver_key, in_src_m,
            in_seq_m, in_sock_m, in_bytes_m, due, in_valid_m, keys=4,
        )
    delivered = {
        "mask": d_due, "src": d_src, "seq": d_seq, "sock": d_sock,
        "bytes": d_bytes, "deliver_rel": d_t,
    }
    in_valid_new = d_valid & ~d_due
    in_deliver_new = jnp.where(in_valid_new, d_t, I32_MAX)
    return (delivered, due, in_deliver_new, d_src, d_seq, d_sock, d_bytes,
            in_valid_new)


def _compact_egress(eg_prio, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
                    eg_clamp, eg_sock, eg_valid_left, *, packed_sort: bool):
    """Section 6: compact leftover egress so rows stay front-packed for
    ingest. Packed form: one uint32 (validity | priority-sentinel) key +
    permutation; reference form: the 10-array variadic sort."""
    eg_prio_left = jnp.where(eg_valid_left, eg_prio, I32_MAX)
    if packed_sort:
        perm = _row_perm_sort(_pack_time_key(eg_valid_left, eg_prio_left))
        take = lambda a: jnp.take_along_axis(a, perm, axis=1)
        return (take(eg_prio_left), take(eg_dst), take(eg_bytes),
                take(eg_seq), take(eg_ctrl), take(eg_tsend),
                take(eg_clamp), take(eg_sock), take(eg_valid_left))
    (_, eg_prio_c, eg_dst_c, eg_bytes_c, eg_seq_c, eg_ctrl_c, eg_tsend_c,
     # shadowlint: disable=SL403 -- pre-diet variadic reference path
     eg_clamp_c, eg_sock_c, eg_valid_c) = _row_sort(
        (~eg_valid_left).astype(jnp.int32), eg_prio_left, eg_dst, eg_bytes,
        eg_seq, eg_ctrl, eg_tsend, eg_clamp, eg_sock, eg_valid_left, keys=2,
    )
    return (eg_prio_c, eg_dst_c, eg_bytes_c, eg_seq_c, eg_ctrl_c,
            eg_tsend_c, eg_clamp_c, eg_sock_c, eg_valid_c)


def _accumulate_metrics(metrics: PlaneMetrics, state: NetPlaneState,
                        sent, lost, due, overflowed, delivered,
                        in_valid_m, router_dropped_delta,
                        fault_dropped_delta, eg_bytes) -> PlaneMetrics:
    """Section 8 (telemetry, compiled in only when a metrics pytree is
    threaded): pure jnp adds over values the step already materialized.
    Nothing here feeds back into simulation state — the parity tests in
    tests/test_telemetry.py pin that metrics-on == metrics-off bitwise —
    and nothing reads back to the host (the no-host-sync rule,
    docs/observability.md)."""
    sent_n = sent.sum(axis=1, dtype=jnp.int32)
    due_n = due.sum(axis=1, dtype=jnp.int32)
    return PlaneMetrics(
        pkts_out=metrics.pkts_out + sent_n,
        bytes_out=metrics.bytes_out
        + jnp.where(sent, eg_bytes, 0).sum(axis=1, dtype=jnp.int32),
        pkts_in=metrics.pkts_in + due_n,
        bytes_in=metrics.bytes_in
        + jnp.where(delivered["mask"], delivered["bytes"], 0)
        .sum(axis=1, dtype=jnp.int32),
        drop_ring_full=metrics.drop_ring_full + overflowed,
        drop_qdisc=metrics.drop_qdisc + router_dropped_delta,
        drop_loss=metrics.drop_loss
        + lost.sum(axis=1, dtype=jnp.int32),
        drop_fault=metrics.drop_fault + fault_dropped_delta,
        retransmits=metrics.retransmits,
        # high-water marks at the PEAK points: egress occupancy entering
        # the window (ingest already ran), ingress after this window's
        # arrivals merged but before the due release
        max_eg_depth=jnp.maximum(
            metrics.max_eg_depth,
            state.eg_valid.sum(axis=1, dtype=jnp.int32)),
        max_in_depth=jnp.maximum(
            metrics.max_in_depth,
            in_valid_m.sum(axis=1, dtype=jnp.int32)),
        windows=metrics.windows + 1,
        events=metrics.events + sent_n.sum() + due_n.sum(),
        sort_slots=metrics.sort_slots
        + state.eg_valid.sum(dtype=jnp.int32)
        + state.in_valid.sum(dtype=jnp.int32),
    )


def window_step(state: NetPlaneState, params: NetPlaneParams, rng_root: jax.Array,
                shift_ns: jax.Array, window_ns: jax.Array, *,
                rr_enabled: bool = True, router_aqm: bool = False,
                no_loss: bool = False, packed_sort: bool = True,
                kernel: str = "xla",
                faults: FaultArrays | None = None,
                metrics: PlaneMetrics | None = None,
                guards: GuardState | None = None,
                hist: PlaneHistograms | None = None,
                flightrec: FlightRecArrays | None = None,
                flows=None, compute=None):
    """Advance one scheduling round [t, t + window_ns).

    `rr_enabled` is a static (trace-time) switch: False compiles the
    FIFO-only qdisc without the RR rank/one-hot tensors — use it when no
    host configures round-robin (e.g. the integrated DeviceTransport,
    where the CPU NIC owns qdisc ordering). The RR path materializes
    [N, CE, CE] pairwise tensors, which DOMINATE the per-window cost
    whenever N < CE^2; callers with all-FIFO configs should pass False.

    `router_aqm` (static) switches the destination side from direct
    due-release to the full inbound pipeline (`host.rs:810-865`): router
    CoDel -> down-bandwidth relay -> delivery, via the fused micro-step
    kernel in `tpu.codel.router_drain`. In this mode a packet's stored
    time is its ARRIVAL at the destination router; delivery happens when
    the relay forwards it (same instant when tokens allow, later when the
    down-bw bucket or CoDel interferes), and CoDel may drop it instead
    (counted in state.router.dropped). The CPU relay's bootstrap-period
    rate-limit bypass is not modeled on device.

    `no_loss` (static) compiles out the loss draw + loss-table gather for
    callers whose loss matrix is all zero (the integrated DeviceTransport,
    where the CPU drew loss at capture). rng_counter still advances so
    state stays bitwise-comparable with a loss-enabled run.

    `packed_sort` (static) selects the packed-key sort diet for the row
    sorts (sections 2b, 4, 5b-AQM, 6) AND the bucketed counting
    placement for the flat routing stage (section 5, `_routing_rank` /
    `_routing_place`) — bitwise-identical ordering, far fewer arrays
    through the comparator networks; False compiles the original
    variadic sorts (the parity-test reference). `kernel` (static) picks
    the fused-kernel implementation: "xla" (default) or "pallas" — the
    fused VMEM-resident Pallas kernels for the egress stage
    (`tpu.pallas_egress`) and the routing scatter-append
    (`tpu.pallas_route`), FIFO-only (requires rr_enabled=False),
    bitwise-identical to the XLA path.

    `metrics` (static presence switch) threads the telemetry counters
    (`telemetry/metrics.PlaneMetrics`) through the step: per-host
    traffic/drop/depth counters and per-window scalars accumulate with
    pure jnp adds over values the step already materialized — zero extra
    host syncs, donation-compatible, and bitwise-invisible to the
    simulation state (tests/test_telemetry.py). With metrics=None
    (default) the telemetry section is compiled out entirely.

    `faults` (static presence switch) threads the fault plane
    (`faults/plane.FaultArrays`, docs/robustness.md): crashed /
    link-down hosts stop transmitting (their queued egress drops) and
    stop accepting new routing (packets toward them drop), per-link
    latency multiplies, per-host egress bandwidth divides, and burst
    corruption applies an extra Bernoulli drop from an independent
    counter stream. All fault drops accumulate in `n_fault_dropped`
    (and the telemetry `drop_fault` bucket), never in the loss-sample
    counter. With faults=None (default) every fault branch is compiled
    out — bitwise-identical to the pre-fault plane — and neutral masks
    (`neutral_faults`) are bitwise-identity too (tests/test_faults.py).
    XLA kernel only (the pallas egress fusion predates the fault gate).

    `guards` (static presence switch, docs/robustness.md) threads the
    runtime invariant plane (`guards/plane.GuardState`): conservation
    laws, ring structure, packed-key bit budget, RNG monotonicity, and
    the virtual-clock check accumulate per-host violation bitmasks with
    pure jnp compares over values the step already materialized —
    nothing raises inside jit, nothing feeds back into simulation
    state, and guards=None compiles the section out entirely (bitwise-
    identical; pinned by tests/test_guards.py). XLA kernel only, like
    faults.

    `hist` (static presence switch, docs/observability.md
    "Distributions and the flight recorder") threads the log2-bucketed
    `PlaneHistograms`: delivery latency (deliver - send, attributed to
    the destination), egress-queue sojourn (attributed to the source),
    and a per-window queue-depth sample accumulate with pure jnp
    one-hot sums / int32 scatter-adds over values the step already
    materialized — bitwise-invisible to simulation state, metrics, and
    guards (tests/test_flightrec.py). hist=None compiles the section
    out. XLA kernel only, like faults and guards.

    `flightrec` (static presence switch, same doc) threads the sampled
    flight recorder (`telemetry/flightrec.py`): packets whose
    (src, seq) hashes into the seeded 1/K sampling stream record their
    per-hop events (routed, delivered, dropped-with-reason, AQM
    verdict) into the device-side trace ring, drained asynchronously
    at harvest boundaries. The sampling draw is an independent
    counter-based stream (like fault corruption), so recording never
    perturbs the simulation. XLA kernel only.

    `flows` (static presence switch, docs/robustness.md "Flow plane")
    threads the device flow plane as a ``(FlowTables, FlowState)``
    pair (`tpu/flows.py`): this window's deliveries feed per-flow
    cumulative-ack / in-order-credit processing, expired RTO deadlines
    rewind go-back-N with exponential backoff, and the window's
    emissions (retransmissions + delayed acks) append through the
    normal ingest path — ordinary packets, visible to every other
    plane. Unlike the observability planes this one legitimately
    WRITES sim state, but only the egress append columns + the
    overflow counter (the SL501 append-only obligation
    `window_step[flows]`, same theorem as the workload generator);
    threading tables whose flows are all inactive is bitwise-inert
    (tests/test_flows.py). flows=None compiles the section out. XLA
    kernel only, like faults. The returned state's next_event was
    reduced BEFORE the flow emission; chained callers re-arm it like
    the workload emission (`chain_windows`).

    `compute` (static presence switch, docs/workloads.md "Serving
    load & the compute plane") threads the device compute plane as a
    ``(ComputeTables, ComputeState)`` pair (`tpu/compute.py`): this
    window's deliveries feed each host's bounded-FIFO service station
    (busy-until clock, closed-form completion times, queueing-delay /
    sojourn histograms). Pure reads over the delivered dict the step
    already materialized; writes ONLY the ComputeState' appended last
    — the SL501 full-invisibility obligation `window_step[compute]`
    proves no compute taint reaches the lead outputs. The
    delivery-AND-service phase coupling lives in the scenario runner
    (`compute.gate_credits`), never here. compute=None compiles the
    section out. XLA kernel only, like faults.

    `shift_ns` = this window's start minus the previous window's start;
    stored relative times are rebased by it. Returns
    (state', delivered, next_event_rel) — plus metrics', guards',
    hist', and/or flightrec' appended in that order when the
    respective pytrees were passed (the flow plane's FlowState', when
    threaded, appends next; the compute plane's ComputeState'
    appends last) — where `delivered` is a dict of
    [N, CI] arrays masked by delivered['mask'] (packets that arrived
    within this window, in deterministic (deliver_t, src, seq) order
    per host) and `next_event_rel` is the min pending delivery time
    relative to the new window start (INT32_MAX when idle).
    """
    if kernel not in ("xla", "pallas", "pallas_fused"):
        raise ValueError(f"unknown plane kernel {kernel!r}: "
                         "expected 'xla', 'pallas', or 'pallas_fused'")
    pallas_kernel = kernel != "xla"
    if pallas_kernel and rr_enabled:
        raise ValueError(
            f"plane_kernel={kernel!r} fuses the FIFO qdisc only; compile "
            "with rr_enabled=False (all-FIFO configs) or use the XLA path")
    if pallas_kernel and not packed_sort:
        raise ValueError(
            f"plane_kernel={kernel!r} implements the packed/bucketed "
            "ordering only; the packed_sort=False parity reference is an "
            "XLA-path concept — compile with kernel='xla' to measure or "
            "compare against the legacy variadic sorts")
    if pallas_kernel and faults is not None:
        raise ValueError(
            f"plane_kernel={kernel!r} does not fuse the fault plane; "
            "compile with kernel='xla' when a FaultArrays pytree is "
            "threaded (the self-healing kernel fallback in "
            "faults/healing.py does this automatically)")
    if pallas_kernel and guards is not None:
        raise ValueError(
            f"plane_kernel={kernel!r} does not fuse the guard plane; "
            "compile with kernel='xla' when a GuardState pytree is "
            "threaded (the self-healing kernel fallback in "
            "faults/healing.py does this automatically)")
    if pallas_kernel and (hist is not None or flightrec is not None):
        raise ValueError(
            f"plane_kernel={kernel!r} does not fuse the histogram/flight-"
            "recorder observability plane; compile with kernel='xla' "
            "when a PlaneHistograms or FlightRecArrays pytree is "
            "threaded (the self-healing kernel fallback in "
            "faults/healing.py does this automatically)")
    if pallas_kernel and flows is not None:
        raise ValueError(
            f"plane_kernel={kernel!r} does not fuse the flow plane; "
            "compile with kernel='xla' when a (FlowTables, FlowState) "
            "pair is threaded (the self-healing kernel fallback in "
            "faults/healing.py does this automatically)")
    if pallas_kernel and compute is not None:
        raise ValueError(
            f"plane_kernel={kernel!r} does not fuse the compute plane; "
            "compile with kernel='xla' when a (ComputeTables, "
            "ComputeState) pair is threaded (the self-healing kernel "
            "fallback in faults/healing.py does this automatically)")
    N, CE = state.eg_dst.shape

    # --- 1. rebase clocks + refill token buckets -----------------------
    in_deliver = jnp.where(state.in_valid, state.in_deliver_rel - shift_ns,
                           I32_MAX)
    balance, tb_rem_ns = _refill_tokens(state, params, shift_ns,
                                        faults=faults)
    rt = codel.rebase_router_state(state.router, shift_ns, params.dn_rate,
                                   params.dn_cap)

    # --- 2. egress: qdisc order, token-bucket gate ----------------------
    # Two qdiscs (`network_interface.c:205-303`, `QDiscMode`): FIFO sends
    # valid-first by ascending packet priority; round-robin interleaves
    # emitting sockets, taking one packet from each in turn (FIFO within a
    # socket by per-source seq, which is monotone in emission order).
    # Send times / clamps of leftover packets were taken relative to the
    # window they were ingested in; rebase them too.
    row_perm_fused = None
    if kernel == "pallas_fused":
        from . import pallas_pipeline

        (eg_prio, eg_sock, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
         eg_clamp, eg_valid, sendable, spent,
         row_perm_fused) = pallas_pipeline.egress_rank_stage(
            state.eg_valid, state.eg_prio, state.eg_bytes,
            state.eg_tsend, state.eg_clamp, state.eg_dst, state.eg_seq,
            state.eg_sock, state.eg_ctrl, balance, shift_ns)
        balance = balance - spent
        rr_sent = state.rr_sent
    elif kernel == "pallas":
        from . import pallas_egress

        (perm, eg_bytes, eg_tsend, eg_clamp, eg_valid,
         sendable, spent) = pallas_egress.egress_order_gate(
            state.eg_valid, state.eg_prio, state.eg_bytes, state.eg_tsend,
            state.eg_clamp, balance, shift_ns)
        take = lambda a: jnp.take_along_axis(a, perm, axis=1)
        eg_prio, eg_sock, eg_dst = (take(state.eg_prio),
                                    take(state.eg_sock),
                                    take(state.eg_dst))
        eg_seq, eg_ctrl = take(state.eg_seq), take(state.eg_ctrl)
        balance = balance - spent
        rr_sent = state.rr_sent
    else:
        eg_tsend_rb = jnp.where(state.eg_valid, state.eg_tsend - shift_ns, 0)
        eg_clamp_rb = jnp.where(
            state.eg_valid & (state.eg_clamp != NO_CLAMP),
            state.eg_clamp - shift_ns, state.eg_clamp,
        )
        qkey1, qkey2, rr_aux = _qdisc_keys(state, params,
                                           rr_enabled=rr_enabled)
        (eg_prio, eg_sock, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
         eg_clamp, eg_valid) = _egress_order(
            state, qkey1, qkey2, eg_tsend_rb, eg_clamp_rb,
            rr_enabled=rr_enabled, packed_sort=packed_sort)
        if faults is not None:
            # 2f. a crashed / link-down host transmits nothing: its queued
            # egress drops HERE, before the token gate (dead hosts spend
            # no bandwidth), counted once per slot — the slots leave the
            # queue, so a multi-window outage never double-counts
            up_src = (faults.host_alive & faults.link_up)[:, None]
            fault_purged = eg_valid & ~up_src
            eg_valid = eg_valid & up_src
        sendable, balance = _token_gate(eg_valid, eg_bytes, balance)
        rr_sent = (_rr_advance(eg_sock, eg_valid, sendable, rr_aux)
                   if rr_enabled else state.rr_sent)

    # --- 3. loss sampling + latency lookup ------------------------------
    if faults is not None:
        sent, lost, corrupt, rng_counter, deliver_rel = _loss_latency(
            state, params, rng_root, eg_dst, eg_ctrl, eg_tsend, eg_clamp,
            sendable, window_ns, no_loss=no_loss, faults=faults)
        # 3f. routing toward a crashed / link-down destination drops (the
        # fault withdraws the route); packets already in the dst's ingress
        # ring are untouched — the crash does not reach into the wire
        up = faults.host_alive & faults.link_up
        dst_ok = up[jnp.clip(eg_dst, 0, N - 1)] & (eg_dst >= 0) \
            & (eg_dst < N)
        blocked_dst = sent & ~dst_ok & (eg_dst >= 0) & (eg_dst < N)
        sent = sent & dst_ok
        # per-host fault-drop attribution: purge + corruption to the
        # SOURCE (its packets died on its own NIC), routing blocks to
        # the DESTINATION (the crash that ate them is the dst's)
        fault_drops = (
            fault_purged.sum(axis=1, dtype=jnp.int32)
            + corrupt.sum(axis=1, dtype=jnp.int32)
            + jnp.zeros((N,), jnp.int32).at[
                jnp.clip(eg_dst, 0, N - 1).reshape(-1)].add(
                blocked_dst.reshape(-1), mode="drop")
        )
    else:
        sent, lost, rng_counter, deliver_rel = _loss_latency(
            state, params, rng_root, eg_dst, eg_ctrl, eg_tsend, eg_clamp,
            sendable, window_ns, no_loss=no_loss)

    # egress queue keeps only what didn't go out (compacted after routing,
    # which still indexes this ordering)
    eg_valid_left = eg_valid & ~sendable

    # --- 4 + 5. compact surviving ingress, then route sent packets into
    # destination ingress queues. Routing happens BEFORE the due check
    # so a packet whose deliver time falls inside this window
    # (integrated transport: sent last round, clamped to this window's
    # start) is released THIS round, matching the CPU plane's
    # push-then-execute ordering.
    (in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
     in_valid_c, n_valid_in) = _compact_ingress(
        state, in_deliver, packed_sort=packed_sort)
    if kernel == "pallas_fused":
        from . import pallas_pipeline

        (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
         in_valid_m, overflowed) = pallas_pipeline.route_place(
            sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
            in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
            in_valid_c, n_valid_in, row_perm_fused)
    else:
        (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m,
         in_valid_m, overflowed) = _route_scatter(
            sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
            in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
            in_valid_c, n_valid_in,
            packed_sort=packed_sort, kernel=kernel)
    CI = in_src_m.shape[1]

    # --- 5b. destination side: release what this window hands the hosts --
    if router_aqm:
        # Full inbound pipeline: stored times are router-arrival times.
        # FIFO order at the router = (arrival, src, seq), the same order
        # the CPU plane's event queue feeds route_incoming_packet.
        inv_m = (~in_valid_m).astype(jnp.int32)
        arr_key = jnp.where(in_valid_m, in_deliver_m, I32_MAX)
        (_, arr_s, src_s2, seq_s2, sock_s2, bytes_s2, valid_s2) = _row_sort(
            inv_m, arr_key, in_src_m, in_seq_m, in_sock_m, in_bytes_m,
            in_valid_m, keys=4,
        )
        rt2, rstatus, r_dt, co_mask, co_t, c_idx = codel.router_drain(
            arr_s, bytes_s2, window_ns, params.dn_rate, params.dn_cap, rt,
        )
        # a row entry cached at window end leaves the queue: its identity
        # moves into the router scalars until the relay resumes
        new_cached = c_idx >= 0
        ci = jnp.clip(c_idx, 0, CI - 1)[:, None]
        take = lambda a: jnp.take_along_axis(a, ci, axis=1)[:, 0]
        rt2 = rt2._replace(
            cached_src=jnp.where(new_cached, take(src_s2), rt.cached_src),
            cached_seq=jnp.where(new_cached, take(seq_s2), rt.cached_seq),
            cached_sock=jnp.where(new_cached, take(sock_s2), rt.cached_sock),
        )
        # delivered = forwarded row entries + (maybe) the prior window's
        # relay-cached packet, presented in (deliver_t, src, seq) order
        fwd_rows = rstatus == codel.STATUS_DELIVERED
        d_mask0 = jnp.concatenate([fwd_rows, co_mask[:, None]], axis=1)
        d_src0 = jnp.concatenate([src_s2, rt.cached_src[:, None]], axis=1)
        d_seq0 = jnp.concatenate([seq_s2, rt.cached_seq[:, None]], axis=1)
        d_sock0 = jnp.concatenate([sock_s2, rt.cached_sock[:, None]], axis=1)
        d_bytes0 = jnp.concatenate([bytes_s2, rt.cached_bytes[:, None]],
                                   axis=1)
        d_t0 = jnp.concatenate(
            [jnp.where(fwd_rows, r_dt, I32_MAX),
             jnp.where(co_mask, co_t, I32_MAX)[:, None]], axis=1)
        (_, d_t, d_src, d_seq, d_sock, d_bytes, d_due) = _row_sort(
            (~d_mask0).astype(jnp.int32), d_t0, d_src0, d_seq0, d_sock0,
            d_bytes0, d_mask0, keys=4,
        )
        delivered = {
            "mask": d_due, "src": d_src, "seq": d_seq, "sock": d_sock,
            "bytes": d_bytes, "deliver_rel": d_t,
        }
        due = d_due  # for the n_delivered counter
        # surviving queue = the untouched FIFO suffix, re-front-packed
        keep = valid_s2 & (rstatus == codel.STATUS_QUEUED)
        if packed_sort:
            # sort-diet form: ONE (validity | arrival) packed key +
            # permutation (kept arrivals are real times < I32_MAX, so
            # the pack is exactly the (~keep, key) order)
            perm_keep = _row_perm_sort(_pack_time_key(keep, arr_s))
            take_keep = lambda a: jnp.take_along_axis(a, perm_keep, axis=1)
            in_deliver_new = take_keep(jnp.where(keep, arr_s, I32_MAX))
            in_src_new, in_seq_new = take_keep(src_s2), take_keep(seq_s2)
            in_sock_new, in_bytes_new = (take_keep(sock_s2),
                                         take_keep(bytes_s2))
            in_valid_new = take_keep(keep)
        else:
            (_, in_deliver_new, in_src_new, in_seq_new, in_sock_new,
             # shadowlint: disable=SL403 -- pre-diet variadic reference
             in_bytes_new, in_valid_new) = _row_sort(
                (~keep).astype(jnp.int32), jnp.where(keep, arr_s, I32_MAX),
                src_s2, seq_s2, sock_s2, bytes_s2, keep, keys=2,
            )
        rt_out = rt2
    else:
        (delivered, due, in_deliver_new, in_src_new, in_seq_new,
         in_sock_new, in_bytes_new, in_valid_new) = _release_due(
            in_deliver_m, in_src_m, in_seq_m, in_sock_m, in_bytes_m,
            in_valid_m, window_ns, packed_sort=packed_sort)
        rt_out = rt

    # --- 6. compact leftover egress so rows stay front-packed for ingest
    (eg_prio_c, eg_dst_c, eg_bytes_c, eg_seq_c, eg_ctrl_c, eg_tsend_c,
     eg_clamp_c, eg_sock_c, eg_valid_c) = _compact_egress(
        eg_prio, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend, eg_clamp,
        eg_sock, eg_valid_left, packed_sort=packed_sort)

    # --- 7. stats + next-event reduction --------------------------------
    per_host_in_next = jnp.where(in_valid_new, in_deliver_new,
                                 I32_MAX).min(axis=1)
    if router_aqm:
        # a relay-cached packet blocks its whole row until the resume fires
        per_host_in_next = jnp.where(rt_out.has_cached, rt_out.resume,
                                     per_host_in_next)
    next_event = jnp.minimum(
        per_host_in_next.min(),
        jnp.where(eg_valid_c.any(), window_ns, I32_MAX),
    )

    new_state = state._replace(
        eg_dst=eg_dst_c, eg_bytes=eg_bytes_c, eg_prio=eg_prio_c,
        eg_seq=eg_seq_c, eg_ctrl=eg_ctrl_c, eg_tsend=eg_tsend_c,
        eg_clamp=eg_clamp_c, eg_sock=eg_sock_c, eg_valid=eg_valid_c,
        in_src=in_src_new, in_bytes=in_bytes_new, in_seq=in_seq_new,
        in_sock=in_sock_new, in_deliver_rel=in_deliver_new,
        in_valid=in_valid_new,
        tb_balance=balance, tb_rem_ns=tb_rem_ns, rng_counter=rng_counter,
        rr_sent=rr_sent, router=rt_out,
        n_sent=state.n_sent + sent.sum(axis=1, dtype=jnp.int32),
        n_loss_dropped=state.n_loss_dropped + lost.sum(axis=1, dtype=jnp.int32),
        n_overflow_dropped=state.n_overflow_dropped + overflowed,
        n_delivered=state.n_delivered + due.sum(axis=1, dtype=jnp.int32),
        **({"n_fault_dropped": state.n_fault_dropped + fault_drops}
           if faults is not None else {}),
    )
    if metrics is not None:
        # --- 8. telemetry accumulation (static; compiled out when off) --
        metrics = _accumulate_metrics(
            metrics, state, sent, lost, due, overflowed, delivered,
            in_valid_m, rt_out.dropped - state.router.dropped,
            fault_drops if faults is not None
            else jnp.zeros((N,), jnp.int32), eg_bytes)
    if guards is not None:
        # --- 9. guard plane (static; compiled out when off) -------------
        # pure reads over values the step already materialized; nothing
        # here can perturb the simulation stream (docs/determinism.md)
        arrivals = jnp.zeros((N,), jnp.int32).at[
            jnp.clip(eg_dst, 0, N - 1).reshape(-1)].add(
            sent.reshape(-1), mode="drop")
        eg_left = sendable.sum(axis=1, dtype=jnp.int32)
        if faults is not None:
            eg_left = eg_left + fault_purged.sum(axis=1, dtype=jnp.int32)
        cached_zero = jnp.zeros((N,), jnp.int32)
        guards = guards_plane.check_window(
            guards,
            state=state,
            eg_occ_in=state.eg_valid.sum(axis=1, dtype=jnp.int32),
            eg_left_this_window=eg_left,
            in_occ_in=state.in_valid.sum(axis=1, dtype=jnp.int32),
            arrivals=arrivals,
            overflowed=overflowed,
            delivered=due.sum(axis=1, dtype=jnp.int32),
            qdisc_delta=(rt_out.dropped - state.router.dropped
                         if router_aqm else cached_zero),
            cached_in=(state.router.has_cached.astype(jnp.int32)
                       if router_aqm else cached_zero),
            cached_out=(rt_out.has_cached.astype(jnp.int32)
                        if router_aqm else cached_zero),
            new_state=new_state,
            rng_delta=rng_counter - state.rng_counter,
            egress_cap=CE, shift_ns=shift_ns, window_ns=window_ns)
    if hist is not None:
        # --- 10. latency/depth histograms (static; compiled out when
        # off) — pure reads over already-materialized values, like the
        # metrics section (docs/observability.md "Distributions and
        # the flight recorder")
        hist = PlaneHistograms(
            # deliver - send: wire latency + the round-barrier clamp,
            # attributed to the DESTINATION (the consumer's view —
            # "p99 delivery latency under incast" is a per-receiver
            # question); int32 scatter-adds commute exactly
            hist_delivery_ns=histo.accum_scatter(
                hist.hist_delivery_ns, eg_dst,
                histo.bucket_index(deliver_rel - eg_tsend), sent),
            # egress sojourn: a packet carried from k windows back has
            # a negative rebased send time; -tsend is exactly how long
            # it waited for bandwidth (fresh sends land in bucket 0)
            hist_sojourn_ns=histo.accum_rows(
                hist.hist_sojourn_ns,
                histo.bucket_index(-eg_tsend), sent),
            hist_qdepth=histo.accum_depth(
                hist.hist_qdepth,
                state.eg_valid.sum(axis=1, dtype=jnp.int32)
                + in_valid_m.sum(axis=1, dtype=jnp.int32)),
        )
    if flightrec is not None:
        # --- 11. sampled flight recorder (static; compiled out when
        # off): per-hop events for the ~1/K packets whose (src, seq)
        # hashes into the seeded sampling stream — an independent
        # counter-based draw, so recording never perturbs the
        # simulation (docs/determinism.md). Candidate classes
        # concatenate in a fixed layout order (routed, loss-drop,
        # fault-drop, delivered, AQM-drop), so the ring content is a
        # pure function of the event stream. Ring-overflow drops at
        # routing are aggregate-counted only (metrics drop_ring_full);
        # a per-slot overflow identity is not materialized.
        rows_e = jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.int32)[:, None], eg_dst.shape)
        samp_eg = flightrec_mod.sample_mask(flightrec, rows_e, eg_seq)
        flat = lambda a: a.reshape(-1)
        kind_of = lambda k, ref: jnp.full((ref.size,), k, jnp.int32)
        ev_kind = [kind_of(flightrec_mod.HOP_ROUTED, eg_dst),
                   kind_of(flightrec_mod.HOP_DROP_LOSS, eg_dst)]
        ev_src = [flat(rows_e), flat(rows_e)]
        ev_seq = [flat(eg_seq), flat(eg_seq)]
        ev_dst = [flat(eg_dst), flat(eg_dst)]
        ev_t = [flat(eg_tsend), flat(eg_tsend)]
        ev_mask = [flat(sent & samp_eg), flat(lost & samp_eg)]
        if faults is not None:
            # every fault-drop class the step distinguishes: source
            # purge (crashed/link-down sender), burst corruption, AND
            # the destination-blocked route withdrawal — a sampled
            # packet eaten by its destination's crash must record a
            # drop_fault hop, not silently vanish from the hop stream
            # while metrics.drop_fault counts it
            ev_kind.append(kind_of(flightrec_mod.HOP_DROP_FAULT, eg_dst))
            ev_src.append(flat(rows_e))
            ev_seq.append(flat(eg_seq))
            ev_dst.append(flat(eg_dst))
            ev_t.append(flat(eg_tsend))
            ev_mask.append(flat(
                (fault_purged | corrupt | blocked_dst) & samp_eg))
        d_rows = jnp.broadcast_to(
            jnp.arange(N, dtype=jnp.int32)[:, None],
            delivered["mask"].shape)
        samp_d = flightrec_mod.sample_mask(
            flightrec, delivered["src"], delivered["seq"])
        ev_kind.append(kind_of(flightrec_mod.HOP_DELIVERED,
                               delivered["mask"]))
        ev_src.append(flat(delivered["src"]))
        ev_seq.append(flat(delivered["seq"]))
        ev_dst.append(flat(d_rows))
        ev_t.append(flat(delivered["deliver_rel"]))
        ev_mask.append(flat(delivered["mask"] & samp_d))
        if router_aqm:
            a_rows = jnp.broadcast_to(
                jnp.arange(N, dtype=jnp.int32)[:, None], src_s2.shape)
            samp_a = flightrec_mod.sample_mask(flightrec, src_s2, seq_s2)
            ev_kind.append(kind_of(flightrec_mod.HOP_DROP_AQM, src_s2))
            ev_src.append(flat(src_s2))
            ev_seq.append(flat(seq_s2))
            ev_dst.append(flat(a_rows))
            ev_t.append(flat(arr_s))
            ev_mask.append(flat(valid_s2
                                & (rstatus == codel.STATUS_DROPPED)
                                & samp_a))
        flightrec = flightrec_mod.record_events(
            flightrec,
            jnp.concatenate(ev_kind), jnp.concatenate(ev_src),
            jnp.concatenate(ev_seq), jnp.concatenate(ev_dst),
            jnp.concatenate(ev_t), jnp.concatenate(ev_mask))
        flightrec = flightrec_mod.advance_window(flightrec)
    fs_out = None
    if flows is not None:
        # --- 12. device flow plane (static; compiled out when off):
        # RTO retransmit + congestion backpressure, docs/robustness.md
        # "Flow plane". Ack/credit processing reads the delivered dict
        # the step just released; emission (retransmissions, delayed
        # acks) appends through the normal ingest path AFTER every
        # observability section, so the guards' window conservation
        # checked the pre-append state and the append itself threads
        # check_ingest like any producer. The flow plane's writes
        # confine to the egress columns + the overflow counter (the
        # SL501 append-only obligation `window_step[flows]`); NOTE
        # next_event was reduced before the append — chained callers
        # re-arm it exactly like the workload emission (the min with
        # window_ns in `chain_windows`).
        from . import flows as flows_mod  # lazy: flows.py imports plane

        ft, fs = flows
        fout = flows_mod.flow_step(
            ft, fs, new_state, delivered, window_ns,
            metrics=metrics, guards=guards, flightrec=flightrec)
        new_state, fs_out = fout[0], fout[1]
        rest = list(fout[3:])
        if metrics is not None:
            metrics = rest.pop(0)
        if guards is not None:
            guards = rest.pop(0)
        if flightrec is not None:
            flightrec = rest.pop(0)
    cs_out = None
    if compute is not None:
        # --- 13. device compute plane (static; compiled out when
        # off): bounded-FIFO service occupancy over this window's
        # deliveries, docs/workloads.md "Serving load & the compute
        # plane". Pure reads of the delivered dict; writes only the
        # ComputeState appended last — the SL501 full-invisibility
        # obligation `window_step[compute]` (analysis/proofs.py).
        from . import compute as compute_mod  # lazy: compute imports plane

        ctab, cstate = compute
        cs_out = compute_mod.compute_step(ctab, cstate, delivered,
                                          shift_ns, window_ns)
    out = (new_state, delivered, next_event)
    for plane_out in (metrics, guards, hist, flightrec):
        if plane_out is not None:
            out += (plane_out,)
    if flows is not None:
        out += (fs_out,)
    if compute is not None:
        out += (cs_out,)
    return out
