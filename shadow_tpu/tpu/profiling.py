"""Per-section cost profiler for the device-plane window step.

The r5 verdict's core complaint was that the general device plane had "no
live win" and nobody could say WHERE the per-window budget goes. This
module answers that: it rebuilds the PHOLD bench world (`bench.py`) at a
given shape, warms it to steady-state occupancy, then times every section
of `plane.window_step` as an ISOLATED jitted micro-kernel — the same
section helpers `window_step` itself composes (`plane._refill_tokens`,
`plane._egress_order`, ...), called with realistic intermediates and timed
with `block_until_ready` around every repetition. The output is a JSON
cost breakdown per section, so every optimization claim against the
window step is a measured before/after, not a guess.

Sections (superset of the window step's numbered stages):

- ``rebase_refill``   — clock rebase + token refill (section 1)
- ``rr_tensors``      — the RR qdisc's [N, CE, CE] rank tensors (2a)
- ``qdisc_sort``      — the egress qdisc row sort (2b)
- ``token_gate``      — prefix-sum bandwidth gate (2c)
- ``loss_latency``    — loss draw + latency table gathers (3)
- ``ingress_compact`` — surviving-ingress compaction sort (4)
- ``routing_scatter`` — the full routing stage (5): rank + placement
- ``routing_rank``    — routing sub-section 5a: the bucketed order
  (row seq-rank + diet flat sort + histogram/prefix offsets; on the
  legacy path, the variadic flat sort + grouped ranks)
- ``routing_place``   — routing sub-section 5b: landing the payload
  columns into the destination ingress rows (the fused gather-scatters;
  on the legacy path, the per-column scatters)
- ``release_due``     — due split/presentation sort (5b, direct mode)
- ``codel_drain``     — the router CoDel/relay micro-step (5b, AQM mode)
- ``egress_compact``  — leftover-egress compaction sort (6)
- ``ingest_rows``     — the bench/respawn row-merge append
- ``window_step``     — the full composed step (sanity anchor: section
  times should roughly sum to it; XLA fusion makes the sum an upper
  bound)
- ``window_step_telemetry`` — the full step with the PlaneMetrics
  telemetry counters threaded (docs/observability.md). The CI
  perf-smoke job fails when this drifts past the no-host-sync budget
  relative to ``window_step`` — the harvester may never add a device
  sync (or material compute) to the hot path.
- ``window_step_faults`` — the full step with NEUTRAL FaultArrays
  masks threaded (docs/robustness.md). The CI chaos-smoke job gates on
  its ratio against ``window_step`` the same way (local bar: 5%): the
  fault plane's presence switch must stay cheap when nothing fails.
- ``window_step_guards`` — the full step with a clean GuardState
  threaded (the runtime invariant plane, docs/robustness.md). Gated in
  CI chaos-smoke against ``window_step`` like telemetry and faults:
  self-verification may never cost the hot path more than the presence
  switches before it.
- ``window_step_elastic`` — the full step plus the per-ring overflow
  deltas the elastic capacity driver reads back every window
  (`tpu/elastic.run_elastic_window`, docs/robustness.md "Elastic
  capacity"). Gated in CI chaos-smoke against ``window_step`` at the
  same 1.35x budget: an idle elastic run (nothing overflows) must cost
  essentially nothing over the plain step.
- ``window_step_trace`` — the full step with BOTH halves of the
  distribution/flight-recorder observability plane threaded
  (`telemetry/histo.PlaneHistograms` + `telemetry/flightrec.
  FlightRecArrays` at sample_every=64, docs/observability.md
  "Distributions and the flight recorder"). The CI perf-smoke job
  GATES on its ratio against ``window_step`` (<= 1.35) like the
  telemetry section: histogram one-hot sums, the sampling threefry,
  and the trace-ring compaction may never cost the hot path a sync or
  material compute.
- ``fused_stage`` — the span of the window step the fused Pallas
  pipeline covers (egress order + token gate + loss/latency + ingress
  compaction + routing), timed under whatever ``kernel`` the profile
  runs: the number the CI perf-smoke gate compares between
  ``--kernel pallas`` (two dispatches + XLA glue) and ``--kernel
  pallas_fused`` (kernel A → flat exchange → kernel B,
  tpu/pallas_pipeline.py).
- ``window_chain8`` — EIGHT window steps as one compiled
  `lax.scan` chain (the shared driver's device-resident unit,
  `tpu/elastic.drive_chained_windows`): divide by 8×``window_step``
  for the chain amortization ratio — what a host sync per window was
  costing. bench.py surfaces the companion ``windows_per_sync`` ratio
  in its JSON `sections`.
- ``window_step_workload`` — the full step plus the workload plane's
  `workload_step` (`shadow_tpu/workloads/device.py`, an onoff traffic
  program at the bench shape): phase-pointer advance + table-driven
  emission + the ingest_rows append, i.e. the per-window cost a
  scenario driver pays over the bare step. Gated in CI like the
  other plane sections (ratio vs ``window_step`` <= 1.35,
  docs/workloads.md).

- ``window_step_flows`` — the full step with the device flow plane
  threaded (`tpu/flows.py`: ack/credit classification over the
  delivered dict, the vmapped Reno/RTO handlers, and the masked
  emission append) over one IDLE flow per host — the neutral
  presence cost, exactly how the faults/guards sections price their
  planes. Gated in CI (ratio vs ``window_step`` <= 1.35,
  docs/robustness.md "Flow plane").

- ``window_step_compute`` — the full step with the compute plane
  threaded (`tpu/compute.py`: the closed-form FIFO cummax over the
  delivered dict + the bounded-queue tail trim + the wait/sojourn
  histogram folds) over an IDLE zero-backlog ComputeState — the
  neutral presence cost, priced exactly like the flows/faults/guards
  sections. Gated in CI (ratio vs ``window_step`` <= 1.35,
  docs/workloads.md "Serving load & the compute plane").

Drive it from the CLI: ``python tools/profile_plane.py --hosts 1024,32768``.
"""

from __future__ import annotations

import time as _walltime

import numpy as np

from ..workloads.phold import respawn_batch  # noqa: F401 — back-compat
# re-export: PHOLD moved to the workload plane (workloads/phold.py);
# bench.py / chaos_smoke import the new home, older callers keep
# finding `profiling.respawn_batch` here.

MS = 1_000_000

#: sections timed by default (codel_drain is representative of AQM mode
#: even though the bench's direct mode never runs it)
DEFAULT_SECTIONS = (
    "rebase_refill", "rr_tensors", "qdisc_sort", "token_gate",
    "loss_latency", "ingress_compact", "routing_scatter", "routing_rank",
    "routing_place", "release_due", "codel_drain", "egress_compact",
    "ingest_rows", "fused_stage", "window_step", "window_chain8",
    "window_step_telemetry",
    "window_step_faults", "window_step_guards", "window_step_elastic",
    "window_step_trace", "window_step_workload", "window_step_flows",
    "window_step_compute",
)

#: the cheap per-section subset bench.py records in its JSON `sections`
#: field (one profiled rep; the window_step_* presence-switch variants
#: are gated separately in CI and cost full extra compiles, and
#: rr_tensors/codel_drain never run in the bench's FIFO direct mode)
BENCH_SECTIONS = (
    "rebase_refill", "qdisc_sort", "token_gate", "loss_latency",
    "ingress_compact", "routing_scatter", "routing_rank", "routing_place",
    "release_due", "egress_compact", "ingest_rows", "window_step",
    "window_chain8",
)


def _time_call(fn, args, reps: int) -> dict:
    """Median/min wall time of a jitted section, blocking every rep.

    Wall-clock here is pure measurement output (the profiler never feeds
    sim state), hence the SL101 suppressions."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + first run outside the timing
    times = []
    for _ in range(reps):
        t0 = _walltime.perf_counter()  # shadowlint: disable=SL101 -- profiler measurement
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(_walltime.perf_counter() - t0)  # shadowlint: disable=SL101 -- profiler measurement
    times.sort()
    return {
        "min_ms": round(times[0] * 1e3, 4),
        "median_ms": round(times[len(times) // 2] * 1e3, 4),
        "reps": reps,
    }


def build_world(n_hosts: int, *, n_nodes: int = 64, egress_cap: int = 16,
                ingress_cap: int = 32, seed: int = 0,
                warmup_windows: int = 3):
    """The bench.py PHOLD world at steady state: node-level path tables,
    4 seed packets per host, `warmup_windows` full windows executed so
    egress/ingress occupancy matches what the bench's scan body sees."""
    import jax
    import jax.numpy as jnp

    from . import ingest, make_params, make_state
    from .plane import window_step

    N, M = n_hosts, n_nodes
    rng = np.random.default_rng(seed)
    lat = rng.integers(1 * MS, 50 * MS, size=(M, M), dtype=np.int32)
    lat = np.minimum(lat, lat.T)
    loss = np.full((M, M), 0.01, np.float32)
    host_node = (np.arange(N) % M).astype(np.int32)
    bw = np.full((N,), 10_000_000_000, np.int64)
    params = make_params(lat, loss, bw, host_node=host_node)
    state = make_state(N, egress_cap=egress_cap, ingress_cap=ingress_cap,
                       initial_tokens=np.asarray(params.tb_cap))
    k = 4
    src0 = jnp.repeat(jnp.arange(N, dtype=jnp.int32), k)
    dst0 = (src0 * 1566083941
            + jnp.tile(jnp.arange(k, dtype=jnp.int32), N) * 40503 + 1) % N
    b0 = src0.shape[0]
    state = ingest(
        state, src0, dst0,
        jnp.full((b0,), 1400, jnp.int32),
        jnp.arange(b0, dtype=jnp.int32),
        jnp.arange(b0, dtype=jnp.int32),
        jnp.zeros((b0,), bool),
    )
    rng_root = jax.random.key(1)
    window = jnp.int32(10 * MS)
    step = jax.jit(lambda st, sh: window_step(
        st, params, rng_root, sh, window, rr_enabled=False))
    shift = jnp.int32(0)
    delivered = None
    for _ in range(warmup_windows):
        state, delivered, _next = step(state, shift)
        shift = window
    jax.block_until_ready(state)
    return {
        "state": state, "params": params, "rng_root": rng_root,
        "shift": window, "window": window, "delivered": delivered,
        "egress_cap": egress_cap, "ingress_cap": ingress_cap,
    }


def profile_sections(n_hosts: int, *, reps: int = 20,
                     sections=None, rr_enabled: bool = False,
                     packed_sort: bool = True, kernel: str = "xla",
                     n_nodes: int = 64, egress_cap: int = 16,
                     ingress_cap: int = 32, seed: int = 0) -> dict:
    """Time each window-step section at the given bench shape. Returns a
    JSON-ready dict. `packed_sort=False` times the pre-diet variadic
    sorts (the before/after comparison the PR-level claims quote)."""
    import jax
    import jax.numpy as jnp

    from . import codel
    from .plane import (I32_MAX, NO_CLAMP, _compact_egress,
                        _compact_ingress, _egress_order, _loss_latency,
                        _qdisc_keys, _refill_tokens, _release_due,
                        _route_scatter, _routing_place,
                        _routing_place_legacy, _routing_rank,
                        _routing_rank_legacy, _row_sort, _token_gate,
                        ingest_rows, window_step)

    from ..faults.plane import neutral_faults as _neutral_faults
    from ..guards.plane import make_guards as _clean_guards
    from ..telemetry import make_flightrec as _fresh_flightrec
    from ..telemetry import make_histograms as _zero_hist
    from ..telemetry import make_metrics as _zero_metrics

    wanted = tuple(sections) if sections is not None else DEFAULT_SECTIONS
    world = build_world(n_hosts, n_nodes=n_nodes, egress_cap=egress_cap,
                        ingress_cap=ingress_cap, seed=seed)
    state, params = world["state"], world["params"]
    rng_root, shift, window = world["rng_root"], world["shift"], \
        world["window"]
    N = n_hosts
    CI = ingress_cap

    # precompute each section's inputs ONCE (jitted, materialized) so the
    # timed call measures exactly one section
    def rebase_refill(state, shift):
        in_deliver = jnp.where(state.in_valid,
                               state.in_deliver_rel - shift, I32_MAX)
        balance, rem = _refill_tokens(state, params, shift)
        eg_tsend_rb = jnp.where(state.eg_valid, state.eg_tsend - shift, 0)
        eg_clamp_rb = jnp.where(
            state.eg_valid & (state.eg_clamp != NO_CLAMP),
            state.eg_clamp - shift, state.eg_clamp)
        return in_deliver, balance, rem, eg_tsend_rb, eg_clamp_rb

    pre = jax.jit(rebase_refill)(state, shift)
    in_deliver, balance, _rem, eg_tsend_rb, eg_clamp_rb = \
        jax.block_until_ready(pre)
    qk1, qk2, _aux = jax.jit(
        lambda st: _qdisc_keys(st, params, rr_enabled=rr_enabled))(state)
    order = jax.jit(lambda st, a, b, c, d: _egress_order(
        st, a, b, c, d, rr_enabled=rr_enabled, packed_sort=packed_sort))
    (eg_prio, eg_sock, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
     eg_clamp, eg_valid) = jax.block_until_ready(
        order(state, qk1, qk2, eg_tsend_rb, eg_clamp_rb))
    sendable, _bal2 = jax.jit(_token_gate)(eg_valid, eg_bytes, balance)
    loss_fn = jax.jit(lambda st, dsts, ctrl, ts, cl, snd: _loss_latency(
        st, params, rng_root, dsts, ctrl, ts, cl, snd, window,
        no_loss=False))
    sent, _lost, _rc, deliver_rel = jax.block_until_ready(
        loss_fn(state, eg_dst, eg_ctrl, eg_tsend, eg_clamp, sendable))
    compact = jax.jit(lambda st, ind: _compact_ingress(
        st, ind, packed_sort=packed_sort))
    (in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c, in_valid_c,
     n_valid_in) = jax.block_until_ready(compact(state, in_deliver))
    route = jax.jit(lambda *a: _route_scatter(*a, packed_sort=packed_sort,
                                              kernel=kernel))
    (in_src_m, in_seq_m, in_sock_m, in_bytes_m, in_deliver_m, in_valid_m,
     _ovf) = jax.block_until_ready(route(
        sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel, in_deliver_c,
        in_src_c, in_seq_c, in_sock_c, in_bytes_c, in_valid_c, n_valid_in))

    # the routing sub-sections (5a rank / 5b place) per sort mode; the
    # place inputs are the rank outputs, precomputed untimed
    if packed_sort:
        route_rank = jax.jit(lambda s, d, q, dl, nv: _routing_rank(
            s, d, q, dl, nv, CI))
        rank_out = jax.block_until_ready(route_rank(
            sent, eg_dst, eg_seq, deliver_rel, n_valid_in))
        route_place = jax.jit(_routing_place)
        place_args = (*rank_out[:4], n_valid_in, eg_seq, eg_bytes,
                      eg_sock, deliver_rel, in_deliver_c, in_src_c,
                      in_seq_c, in_sock_c, in_bytes_c, in_valid_c)
        rank_args = (sent, eg_dst, eg_seq, deliver_rel, n_valid_in)
    else:
        route_rank = jax.jit(lambda s, d, q, b, k, dl, nv:
                             _routing_rank_legacy(s, d, q, b, k, dl, nv,
                                                  CI))
        rank_out = jax.block_until_ready(route_rank(
            sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
            n_valid_in))
        route_place = jax.jit(_routing_place_legacy)
        place_args = (*rank_out[:7], in_deliver_c, in_src_c, in_seq_c,
                      in_sock_c, in_bytes_c, in_valid_c)
        rank_args = (sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
                     n_valid_in)
    eg_valid_left = jax.block_until_ready(
        jax.jit(lambda v, s: v & ~s)(eg_valid, sendable))

    # AQM-mode inputs for the codel micro-step: arrival-ordered ingress +
    # a rebased router state (built once, untimed)
    def aqm_presort(valid_m, deliver_m, src_m, seq_m, sock_m, bytes_m):
        inv_m = (~valid_m).astype(jnp.int32)
        arr_key = jnp.where(valid_m, deliver_m, I32_MAX)
        return _row_sort(inv_m, arr_key, src_m, seq_m, sock_m, bytes_m,
                         valid_m, keys=4)
    (_, arr_s, _src_s, _seq_s, _sock_s, bytes_s, _valid_s) = \
        jax.block_until_ready(jax.jit(aqm_presort)(
            in_valid_m, in_deliver_m, in_src_m, in_seq_m, in_sock_m,
            in_bytes_m))
    rt = jax.block_until_ready(jax.jit(
        lambda st, sh: codel.rebase_router_state(
            st.router, sh, params.dn_rate, params.dn_cap))(state, shift))

    # the bench's respawn batch for ingest_rows, shaped from the warmup
    # window's delivered set (spawn_seq/round_idx pinned to the bench's
    # first respawning round)
    deliv = world["delivered"]
    spawn_seq = jnp.full((N,), 10_000, jnp.int32)
    mask, new_dst, row_bytes, seq_vals, row_ctrl = jax.block_until_ready(
        jax.jit(lambda d: respawn_batch(d, spawn_seq, jnp.int32(1), N, CI))(
            deliv))

    def _elastic_probe(st, sh):
        out = window_step(st, params, rng_root, sh, window,
                          rr_enabled=rr_enabled, packed_sort=packed_sort,
                          kernel=kernel)
        ovf = out[0].n_overflow_dropped - st.n_overflow_dropped
        return (*out, ovf, ovf.sum())

    def _make_workload_probe():
        # the workload plane's per-window cost: the step + a
        # table-driven workload_step (an onoff program over the full
        # fleet at the bench shape — phase advance, emission gathers,
        # ingest_rows append). Built only when the section is wanted:
        # bench.py's BENCH_SECTIONS subset skips it, so bench runs
        # never pay the program compile.
        from ..workloads import compile_program, parse_scenario
        from ..workloads import device as _wdevice

        prog = compile_program(parse_scenario({
            "name": "profile-onoff", "hosts": n_hosts,
            "egress_cap": egress_cap, "ingress_cap": ingress_cap,
            "patterns": [{"kind": "onoff", "burst": 2, "rounds": 4,
                          "gap_ns": 200_000, "off_mean_ns": 2_000_000}],
        }))
        wl = _wdevice.to_device(prog)

        def probe(st, ws, sh):
            st, delivered, nxt = window_step(
                st, params, rng_root, sh, window,
                rr_enabled=rr_enabled, packed_sort=packed_sort,
                kernel=kernel)
            st, ws = _wdevice.workload_step(wl, ws, st, delivered,
                                            jnp.int32(1), window)
            return st, ws, nxt

        return jax.jit(probe), _wdevice.make_workload_state(prog)

    def _fused_stage(st, sh):
        """The span the fused pipeline covers (sections 2 + 3 + 4 + 5),
        composed for the profiled `kernel` — the apples-to-apples
        number behind the CI fused-vs-two-dispatch gate."""
        in_dl = jnp.where(st.in_valid, st.in_deliver_rel - sh, I32_MAX)
        balance2, _rem2 = _refill_tokens(st, params, sh)
        if kernel == "pallas_fused":
            from . import pallas_pipeline

            (_p, f_sock, f_dst, f_bytes, f_seq, f_ctrl, f_tsend,
             f_clamp, _v, f_send, _spent,
             f_perm) = pallas_pipeline.egress_rank_stage(
                st.eg_valid, st.eg_prio, st.eg_bytes, st.eg_tsend,
                st.eg_clamp, st.eg_dst, st.eg_seq, st.eg_sock,
                st.eg_ctrl, balance2, sh)
            f_sent, _l, _rc, f_dr = _loss_latency(
                st, params, rng_root, f_dst, f_ctrl, f_tsend, f_clamp,
                f_send, window, no_loss=False)
            comp = _compact_ingress(st, in_dl, packed_sort=True)
            (m_src, m_seq, m_sock, m_bytes, m_del, m_valid,
             f_ovf) = pallas_pipeline.route_place(
                f_sent, f_dst, f_seq, f_bytes, f_sock, f_dr, *comp,
                f_perm)
            return f_ovf, _release_due(m_del, m_src, m_seq, m_sock,
                                       m_bytes, m_valid, window,
                                       packed_sort=True)
        tsr = jnp.where(st.eg_valid, st.eg_tsend - sh, 0)
        clr = jnp.where(st.eg_valid & (st.eg_clamp != NO_CLAMP),
                        st.eg_clamp - sh, st.eg_clamp)
        qk1f, qk2f, _af = _qdisc_keys(st, params, rr_enabled=rr_enabled)
        if kernel == "pallas":
            from . import pallas_egress

            (f_permE, f_bytes, f_tsend, f_clamp, _v, f_send,
             f_spent) = pallas_egress.egress_order_gate(
                st.eg_valid, st.eg_prio, st.eg_bytes, st.eg_tsend,
                st.eg_clamp, balance2, sh)
            takeE = lambda a: jnp.take_along_axis(a, f_permE, axis=1)
            f_dst, f_seq = takeE(st.eg_dst), takeE(st.eg_seq)
            f_sock, f_ctrl = takeE(st.eg_sock), takeE(st.eg_ctrl)
        else:
            (_p, f_sock, f_dst, f_bytes, f_seq, f_ctrl, f_tsend,
             f_clamp, f_valid) = _egress_order(
                st, qk1f, qk2f, tsr, clr, rr_enabled=rr_enabled,
                packed_sort=packed_sort)
            f_send, _bal2f = _token_gate(f_valid, f_bytes, balance2)
        f_sent, _l, _rc, f_dr = _loss_latency(
            st, params, rng_root, f_dst, f_ctrl, f_tsend, f_clamp,
            f_send, window, no_loss=False)
        comp = _compact_ingress(st, in_dl, packed_sort=packed_sort)
        (m_src, m_seq, m_sock, m_bytes, m_del, m_valid,
         f_ovf) = _route_scatter(
            f_sent, f_dst, f_seq, f_bytes, f_sock, f_dr, *comp,
            packed_sort=packed_sort, kernel=kernel)
        # the fused pipeline's span ends at the due split, so the
        # non-fused variants time it too (apples-to-apples)
        return f_ovf, _release_due(m_del, m_src, m_seq, m_sock,
                                   m_bytes, m_valid, window,
                                   packed_sort=packed_sort)

    def _chain8(st, sh):
        """Eight windows as one compiled scan — the shared driver's
        device-resident chain unit at its smallest realistic length."""
        def body(carry, _ridx):
            st, sh = carry
            st, delivered, _nxt = window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel=kernel)
            return (st, window), delivered["mask"].sum(dtype=jnp.int32)
        (st, _sh), outs = jax.lax.scan(
            body, (st, sh), jnp.arange(8, dtype=jnp.int32))
        return st, outs.sum()

    section_calls = {
        "fused_stage": (jax.jit(_fused_stage), (state, shift)),
        "window_chain8": (jax.jit(_chain8), (state, shift)),
        "rebase_refill": (jax.jit(rebase_refill), (state, shift)),
        "rr_tensors": (
            jax.jit(lambda st: _qdisc_keys(st, params, rr_enabled=True)),
            (state,)),
        "qdisc_sort": (order, (state, qk1, qk2, eg_tsend_rb, eg_clamp_rb)),
        "token_gate": (jax.jit(_token_gate), (eg_valid, eg_bytes, balance)),
        "loss_latency": (
            loss_fn, (state, eg_dst, eg_ctrl, eg_tsend, eg_clamp, sendable)),
        "ingress_compact": (compact, (state, in_deliver)),
        "routing_scatter": (route, (
            sent, eg_dst, eg_seq, eg_bytes, eg_sock, deliver_rel,
            in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c,
            in_valid_c, n_valid_in)),
        "routing_rank": (route_rank, rank_args),
        "routing_place": (route_place, place_args),
        "release_due": (
            jax.jit(lambda *a: _release_due(
                *a, window, packed_sort=packed_sort)),
            (in_deliver_m, in_src_m, in_seq_m, in_sock_m, in_bytes_m,
             in_valid_m)),
        "codel_drain": (
            jax.jit(lambda a, b, r: codel.router_drain(
                a, b, window, params.dn_rate, params.dn_cap, r)),
            (arr_s, bytes_s, rt)),
        "egress_compact": (
            jax.jit(lambda *a: _compact_egress(
                *a, packed_sort=packed_sort)),
            (eg_prio, eg_dst, eg_bytes, eg_seq, eg_ctrl, eg_tsend,
             eg_clamp, eg_sock, eg_valid_left)),
        "ingest_rows": (
            jax.jit(lambda st, d, b, p, s, c, v: ingest_rows(
                st, d, b, p, s, c, v, packed_sort=packed_sort)),
            (state, new_dst, row_bytes, seq_vals, seq_vals, row_ctrl,
             mask)),
        "window_step": (
            jax.jit(lambda st, sh: window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel=kernel)),
            (state, shift)),
        "window_step_telemetry": (
            jax.jit(lambda st, m, sh: window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel=kernel, metrics=m)),
            (state, _zero_metrics(n_hosts), shift)),
        "window_step_faults": (
            # faults require the XLA step (the pallas fusion predates
            # the fault gate), so this section pins kernel="xla"
            jax.jit(lambda st, f, sh: window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel="xla", faults=f)),
            (state, _neutral_faults(n_hosts, n_nodes), shift)),
        "window_step_guards": (
            # guards, like faults, refuse the pallas fusion: pin xla
            jax.jit(lambda st, g, sh: window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel="xla", guards=g)),
            (state, _clean_guards(n_hosts), shift)),
        "window_step_trace": (
            # the flight recorder + histograms (docs/observability.md
            # "Distributions and the flight recorder"); like faults/
            # guards, the observability plane refuses the pallas
            # fusion — pin xla
            jax.jit(lambda st, h, f, sh: window_step(
                st, params, rng_root, sh, window, rr_enabled=rr_enabled,
                packed_sort=packed_sort, kernel="xla", hist=h,
                flightrec=f)),
            (state, _zero_hist(n_hosts),
             _fresh_flightrec(0, sample_every=64, ring=4096), shift)),
        "window_step_elastic": (
            # the elastic driver's per-window cost: the step + the
            # per-ring overflow deltas it reads back to decide growth
            # (the read-back itself is the same tiny D2H every timed
            # rep already pays in block_until_ready)
            jax.jit(lambda st, sh: _elastic_probe(st, sh)),
            (state, shift)),
    }
    if "window_step_workload" in wanted:
        _probe, _wstate = _make_workload_probe()
        section_calls["window_step_workload"] = (
            _probe, (state, _wstate, shift))
    if "window_step_flows" in wanted:
        # the flow plane's presence cost: one idle flow per host
        # (active endpoints, nothing left to send) — the recv
        # classification, the vmapped ack/RTO handlers, and the
        # masked emission all run at fleet width, like the neutral
        # fault masks / clean guards the sibling sections thread
        from . import flows as _flows

        _ftab = _flows.make_flow_tables(
            np.arange(n_hosts, dtype=np.int32),
            (np.arange(n_hosts, dtype=np.int32) + 1) % n_hosts,
            np.full(n_hosts, 1400, np.int32))
        _fstate = _flows.make_flow_state(n_hosts)
        section_calls["window_step_flows"] = (
            jax.jit(lambda st, fst, sh: window_step(
                st, params, rng_root, sh, window,
                rr_enabled=rr_enabled, packed_sort=packed_sort,
                kernel="xla", flows=(_ftab, fst))),
            (state, _fstate, shift))
    if "window_step_compute" in wanted:
        # the compute plane's presence cost: a one-phase uniform
        # service table, zero backlog — the closed-form FIFO and the
        # histogram folds run at full delivered width, like the idle
        # flow / neutral fault sections above (compute, like every
        # presence plane, refuses the pallas fusion — pin xla)
        from . import compute as _compute

        _ctab = _compute.make_compute_tables(
            np.full((n_hosts, 1), 25_000, np.int32), 64)
        _cstate = _compute.make_compute_state(_ctab)
        section_calls["window_step_compute"] = (
            jax.jit(lambda st, cst, sh: window_step(
                st, params, rng_root, sh, window,
                rr_enabled=rr_enabled, packed_sort=packed_sort,
                kernel="xla", compute=(_ctab, cst))),
            (state, _cstate, shift))

    out_sections = {}
    for name in wanted:
        fn, args = section_calls[name]
        out_sections[name] = _time_call(fn, args, reps)

    return {
        "hosts": n_hosts,
        "egress_cap": egress_cap,
        "ingress_cap": ingress_cap,
        "nodes": n_nodes,
        "backend": jax.default_backend(),
        "rr_enabled": rr_enabled,
        "packed_sort": packed_sort,
        "kernel": kernel,
        "sections": out_sections,
    }
