"""TCP-on-TPU: the connection state machine as SoA arrays (phase C).

Parity: `shadow_tpu/tcp/connection.py` (itself modeled on the reference's
dependency-injected `TcpState`, `src/lib/tcp/src/lib.rs:238`) — every
scalar of the CPU machine becomes a [C] array and one vmapped kernel steps
C connections per event tick. Payload BYTES never live here: like the
network plane, this is a metadata machine (offsets, lengths, windows,
deadlines); the byte buffers stay host-side keyed by connection id.

What is modeled bitwise-identically to the CPU machine (asserted by
tests/test_tpu_tcp.py on recorded traces):
- wire-sequence arithmetic (uint32 wrap), unwrapped int32 stream offsets
- the full FSM: handshake (active/passive/simultaneous), ESTABLISHED,
  FIN/CLOSE states, TIME_WAIT, RST paths, error codes
- Reno congestion (slow start / avoidance / NewReno fast recovery with
  partial-ack retransmits), RFC 6298 RTT/RTO in integer milliseconds
- RTO/persist timers as per-connection generation counters + absolute
  millisecond DEADLINE arrays (`rto_deadline_ms`), go-back-N timeout
  recovery, zero-window probing
- out-of-order reassembly as fixed-capacity (offset, len) range slots
  (REASS_SLOTS per connection; coverage math only, no bytes)

Event model (the CPU machine's API surface, one event per connection per
step): OPEN_ACTIVE/OPEN_PASSIVE, WRITE(n)/READ(n), CLOSE/ABORT, SEG(hdr),
PULL (= next_segment: emits segment metadata or none), TIMER_*(gen).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tcp.cong import INITIAL_WINDOW as INITIAL_CWND
from ..tcp.cong import _SSTHRESH_INF as SSTHRESH_INF
from ..tcp.connection import (DATA_RETRIES, MAX_WSCALE, MSS, SYN_RETRIES,
                              TIME_WAIT_NS, TcpConfig)
from ..tcp.rtt import RTO_INIT_MS, RTO_MAX_MS, RTO_MIN_MS

# shared with the CPU machine so the bitwise-parity contract can't drift
TIME_WAIT_MS = TIME_WAIT_NS // 1_000_000
_CFG = TcpConfig()
SEND_BUFFER = _CFG.send_buffer
RECV_BUFFER = _CFG.recv_buffer

REASS_SLOTS = 128  # >= recv_buffer/MSS: as many ranges as the window admits
SACK_SLOTS = 16  # sender scoreboard capacity (mirrors _SackScoreboard)
SACK_WIRE_BLOCKS = 3
SB_INF = np.int32(1 << 30)  # scoreboard hole-cap sentinel (> any chunk)

# TcpFlags (bit-identical to the CPU enum)
FIN, SYN, RST, PSH, ACK, URG = 1, 2, 4, 8, 16, 32

# TcpState (bit-identical)
(CLOSED, LISTEN, SYN_SENT, SYN_RCVD, ESTABLISHED, FIN_WAIT_1, FIN_WAIT_2,
 CLOSING, TIME_WAIT, CLOSE_WAIT, LAST_ACK) = range(11)

# congestion phases
PH_SLOW_START, PH_AVOIDANCE, PH_RECOVERY = 0, 1, 2

# event kinds
(EV_NONE, EV_OPEN_ACTIVE, EV_OPEN_PASSIVE, EV_WRITE, EV_READ, EV_CLOSE,
 EV_ABORT, EV_SEG, EV_PULL, EV_TIMER_RTO, EV_TIMER_PERSIST,
 EV_TIMER_TW) = range(12)

N_FIELDS = 16  # per-event int32 args (8 base + SACK)

I32_MAX = np.int32(2**31 - 1)


class TcpPlane(NamedTuple):
    """Per-connection scalars, axis 0 = connection. u32 = wire values."""

    state: jax.Array  # int32 TcpState
    error: jax.Array  # int32 errno, 0 = none
    error_consumed: jax.Array  # bool
    # send side (int32 stream offsets; 0 = first payload byte)
    iss: jax.Array  # uint32
    snd_una: jax.Array
    snd_nxt: jax.Array
    snd_wnd: jax.Array
    stream_len: jax.Array
    snd_max: jax.Array
    fin_requested: jax.Array  # bool
    fin_sent: jax.Array  # bool
    fin_acked: jax.Array  # bool
    syn_outstanding: jax.Array  # bool
    syn_sends: jax.Array
    syn_acked: jax.Array  # bool
    retx_pending: jax.Array  # bool
    probe_pending: jax.Array  # bool
    recover: jax.Array
    gbn_high: jax.Array
    rst_pending: jax.Array  # bool
    # receive side
    irs: jax.Array  # uint32
    rcv_nxt: jax.Array
    ordered_bytes: jax.Array
    reass_bytes: jax.Array
    fin_received: jax.Array  # bool
    has_fin_offset: jax.Array  # bool
    fin_offset: jax.Array
    ack_pending: jax.Array  # bool
    # options
    my_wscale: jax.Array
    peer_wscale: jax.Array
    wscale_ok: jax.Array  # bool
    last_ts_recv: jax.Array  # uint32
    # RTT (integer ms, RFC 6298)
    srtt_ms: jax.Array
    rttvar_ms: jax.Array
    rto_ms: jax.Array
    backoff_count: jax.Array
    # Reno
    cwnd: jax.Array
    ssthresh: jax.Array
    phase: jax.Array
    dup_acks: jax.Array
    avoid_acked: jax.Array
    # timers: generation counters + absolute-ms deadline arrays
    rto_gen: jax.Array
    rto_armed: jax.Array  # bool
    rto_deadline_ms: jax.Array
    persist_gen: jax.Array
    persist_armed: jax.Array  # bool
    persist_deadline_ms: jax.Array
    retransmit_count: jax.Array
    retransmitted_bytes: jax.Array
    last_retx: jax.Array  # bool — last pulled segment was a retransmission
    # SACK (RFC 2018): config gate (mirrors TcpConfig.sack per
    # connection), negotiated flag, and the sender scoreboard — the
    # slot-for-slot mirror of connection.py's _SackScoreboard
    sack_on: jax.Array  # bool — config.sack for this connection
    sack_ok: jax.Array  # bool
    sacked_s: jax.Array  # [C, SACK_SLOTS]
    sacked_e: jax.Array
    # reassembly ranges [C, REASS_SLOTS] (len 0 = free slot)
    reass_off: jax.Array
    reass_len: jax.Array


def make_tcp_plane(n_conns: int, sack: bool = _CFG.sack,
                   reass_slots: int = REASS_SLOTS) -> TcpPlane:
    """reass_slots sizes the out-of-order range store. The default
    (recv_buffer/MSS) admits every window byte arriving as its own
    disjoint range — the worst case per-MSS wires can produce. Wires
    that deliver GSO macro-segments (the flow engine) produce FEW
    disjoint ranges, and the [C, reass_slots] arrays are the heaviest
    per-step operands in the event kernel (the SACK-block sort scans
    them every pull), so those callers pass a small capacity; slot
    exhaustion degrades to a dropped range recovered by retransmit,
    never to corruption."""
    z = lambda: jnp.zeros((n_conns,), jnp.int32)
    u = lambda: jnp.zeros((n_conns,), jnp.uint32)
    f = lambda: jnp.zeros((n_conns,), bool)
    # my_wscale from recv_buffer like TcpConnection.__init__ (scaling on)
    ws = 0
    while (RECV_BUFFER >> ws) > 0xFFFF and ws < MAX_WSCALE:
        ws += 1
    return TcpPlane(
        state=z(), error=z(), error_consumed=f(),
        iss=u(), snd_una=z(), snd_nxt=z(),
        snd_wnd=jnp.full((n_conns,), MSS, jnp.int32),
        stream_len=z(), snd_max=z(), fin_requested=f(), fin_sent=f(),
        fin_acked=f(), syn_outstanding=f(), syn_sends=z(), syn_acked=f(),
        retx_pending=f(), probe_pending=f(), recover=z(), gbn_high=z(),
        rst_pending=f(),
        irs=u(), rcv_nxt=z(), ordered_bytes=z(), reass_bytes=z(),
        fin_received=f(), has_fin_offset=f(), fin_offset=z(),
        ack_pending=f(),
        my_wscale=jnp.full((n_conns,), ws, jnp.int32), peer_wscale=z(),
        wscale_ok=f(), last_ts_recv=u(),
        srtt_ms=z(), rttvar_ms=z(),
        rto_ms=jnp.full((n_conns,), RTO_INIT_MS, jnp.int32),
        backoff_count=z(),
        cwnd=jnp.full((n_conns,), INITIAL_CWND, jnp.int32),
        ssthresh=jnp.full((n_conns,), SSTHRESH_INF, jnp.int32),
        phase=z(), dup_acks=z(), avoid_acked=z(),
        rto_gen=z(), rto_armed=f(), rto_deadline_ms=z(),
        persist_gen=z(), persist_armed=f(), persist_deadline_ms=z(),
        retransmit_count=z(), retransmitted_bytes=z(), last_retx=f(),
        sack_on=jnp.full((n_conns,), bool(sack)),
        sack_ok=f(),
        sacked_s=jnp.zeros((n_conns, SACK_SLOTS), jnp.int32),
        sacked_e=jnp.zeros((n_conns, SACK_SLOTS), jnp.int32),
        reass_off=jnp.zeros((n_conns, reass_slots), jnp.int32),
        reass_len=jnp.zeros((n_conns, reass_slots), jnp.int32),
    )


def retransmits_by_host(plane: TcpPlane, conn_host: jax.Array,
                        n_hosts: int) -> jax.Array:
    """Per-host retransmission totals [N] from the per-connection
    counters [C], for folding into the telemetry pytree
    (`telemetry.add_retransmits`; the plane itself has no host axis —
    `conn_host` maps each connection to its SENDING host index). Pure
    segment-sum, safe inside jit; note the counters are CUMULATIVE, so
    callers fold the DELTA between harvests (or fold once at the end of
    a run, the flow-engine pattern)."""
    return jax.ops.segment_sum(
        plane.retransmit_count, conn_host.astype(jnp.int32),
        num_segments=n_hosts).astype(jnp.int32)


# ---------------------------------------------------------------------------
# scalar helpers (everything below runs per-connection under vmap)
# ---------------------------------------------------------------------------

def _u32(x):
    return x.astype(jnp.uint32) if hasattr(x, "astype") else jnp.uint32(x)


def _wire_seq(s, off):
    return s.iss + _u32(1 + off)


def _wire_ack(s):
    off = s.rcv_nxt + jnp.where(s.fin_received, 1, 0)
    return s.irs + _u32(1 + off)


def _wire_rcv_nxt(s):
    return s.irs + _u32(1 + s.rcv_nxt)


def _recv_space(s):
    used = s.ordered_bytes + s.reass_bytes
    return jnp.maximum(0, RECV_BUFFER - used)


def _advertised_window(s, for_syn):
    space = _recv_space(s)
    shift = jnp.where(for_syn | ~s.wscale_ok, 0, s.my_wscale)
    return jnp.minimum(space >> shift, 0xFFFF)


def _send_space(s):
    return jnp.maximum(0, SEND_BUFFER - (s.stream_len - s.snd_una))


def _set_rto(s, ms):
    return s._replace(rto_ms=jnp.clip(ms, RTO_MIN_MS, RTO_MAX_MS))


def _rto_from_estimate(srtt_ms, rttvar_ms):
    """Device twin of rtt.py:_rto_from_estimate (Linux mdev floor);
    change BOTH or the bitwise-parity contract breaks."""
    return srtt_ms + 4 * jnp.maximum(rttvar_ms, RTO_MIN_MS // 4)


def _rtt_update(s, rtt_ms):
    """RttEstimator.update (callers gate on backoff_count == 0)."""
    rtt_ms = jnp.maximum(1, rtt_ms)
    first = s.srtt_ms == 0
    rttvar = jnp.where(
        first, rtt_ms // 2,
        (3 * s.rttvar_ms) // 4 + jnp.abs(s.srtt_ms - rtt_ms) // 4)
    srtt = jnp.where(first, rtt_ms, (7 * s.srtt_ms) // 8 + rtt_ms // 8)
    s = s._replace(srtt_ms=srtt, rttvar_ms=rttvar, backoff_count=jnp.int32(0))
    return _set_rto(s, _rto_from_estimate(srtt, rttvar))


def _rtt_backoff(s):
    s = s._replace(backoff_count=s.backoff_count + 1)
    return _set_rto(s, s.rto_ms * 2)


def _rtt_reset_backoff(s):
    had = s.backoff_count > 0
    s2 = s._replace(backoff_count=jnp.int32(0))
    s2 = _set_rto(s2, jnp.where(s.srtt_ms > 0,
                                _rto_from_estimate(s.srtt_ms, s.rttvar_ms),
                                RTO_INIT_MS))
    return _sel(had, s2, s)


def _sel(pred, a: TcpPlane, b: TcpPlane) -> TcpPlane:
    """Per-field select: pred ? a : b (pred is a scalar bool here)."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


# -- Reno ------------------------------------------------------------------

def _avoid_tick(cwnd, acked, n):
    acked = acked + n

    def cond(c):
        a, w = c
        return a >= w

    def body(c):
        a, w = c
        return a - w, w + 1

    acked, cwnd = jax.lax.while_loop(cond, body, (acked, cwnd))
    return cwnd, acked


def _cong_new_ack(s, n):
    s0 = s._replace(dup_acks=jnp.int32(0))
    # recovery: deflate to ssthresh, enter avoidance carrying n
    cw_r, aa_r = _avoid_tick(s0.ssthresh, jnp.int32(0), n)
    rec = s0._replace(cwnd=cw_r, phase=jnp.int32(PH_AVOIDANCE),
                      avoid_acked=aa_r)
    # slow start
    new_cwnd = s0.cwnd + n
    reach = new_cwnd >= s0.ssthresh
    cw_s, aa_s = _avoid_tick(s0.ssthresh, jnp.int32(0),
                             jnp.maximum(new_cwnd - s0.ssthresh, 0))
    ss_reach = s0._replace(cwnd=cw_s, phase=jnp.int32(PH_AVOIDANCE),
                           avoid_acked=aa_s)
    ss_stay = s0._replace(cwnd=new_cwnd)
    ss = _sel(reach, ss_reach, ss_stay)
    # avoidance
    cw_a, aa_a = _avoid_tick(s0.cwnd, s0.avoid_acked, n)
    av = s0._replace(cwnd=cw_a, avoid_acked=aa_a)
    return _sel(s.phase == PH_RECOVERY, rec,
                _sel(s.phase == PH_SLOW_START, ss, av))


def _cong_dup_ack(s):
    """Returns (state', fast_retransmit_now)."""
    in_rec = s.phase == PH_RECOVERY
    inflated = s._replace(cwnd=s.cwnd + 1)
    bumped = s._replace(dup_acks=s.dup_acks + 1)
    third = bumped.dup_acks == 3
    ssthresh = s.cwnd // 2 + 1
    entered = bumped._replace(ssthresh=ssthresh, cwnd=ssthresh + 3,
                              phase=jnp.int32(PH_RECOVERY))
    out = _sel(in_rec, inflated, _sel(third, entered, bumped))
    return out, (~in_rec) & third


def _cong_partial_ack(s, n):
    return s._replace(cwnd=jnp.maximum(1, s.cwnd - n + 1))


def _cong_timeout(s):
    return s._replace(dup_acks=jnp.int32(0), ssthresh=s.cwnd // 2 + 1,
                      cwnd=jnp.int32(INITIAL_CWND),
                      phase=jnp.int32(PH_SLOW_START))


# -- timers ----------------------------------------------------------------

def _arm_rto(s, now_ms):
    return s._replace(rto_gen=s.rto_gen + 1, rto_armed=jnp.bool_(True),
                      rto_deadline_ms=now_ms + s.rto_ms)


def _disarm_rto(s):
    return s._replace(rto_gen=s.rto_gen + 1, rto_armed=jnp.bool_(False))


def _arm_persist(s, now_ms):
    armed = s._replace(persist_gen=s.persist_gen + 1,
                       persist_armed=jnp.bool_(True),
                       persist_deadline_ms=now_ms + s.rto_ms)
    return _sel(s.persist_armed, s, armed)


# -- reassembly (coverage math over fixed (off, len) slots) ----------------

def _reass_insert(s, off, length):
    """_Reassembly.insert, with extend-on-touch coalescing: a range that
    overlaps or touches an existing slot EXTENDS it (union), so live
    slots are bounded by the number of HOLES in the receive window (one
    per in-flight loss), not by delivered segment count — which is what
    makes the flow engine's small reass_slots capacity safe. Coverage
    semantics are identical to per-segment storage (the drain walks
    coverage, and both twins' SACK blocks merge touching ranges before
    reporting); same-offset-keep-longer remains a special case of
    extend. Slot exhaustion (now only reachable with more holes than
    slots) drops the range — the peer retransmits."""
    end = off + length
    live = s.reass_len > 0
    touch = live & (s.reass_off <= end) & (off <= s.reass_off + s.reass_len)
    has_touch = touch.any()
    first_touch = jnp.argmax(touch)
    # union ALL touching slots into first_touch: a segment that BRIDGES
    # two existing ranges merges them in one pass, and every other
    # touching slot is cleared — so reass_bytes never transiently
    # double-counts the bridged span (it feeds the OOO / ack-coalescing
    # signals) and live slots stay pairwise disjoint. Coverage semantics
    # are unchanged: the drain walks coverage, and SACK blocks merge
    # touching ranges before reporting anyway.
    new_off = jnp.minimum(jnp.where(touch, s.reass_off, off).min(), off)
    new_end = jnp.maximum(
        jnp.where(touch, s.reass_off + s.reass_len, end).max(), end)
    ext_off = s.reass_off.at[first_touch].set(new_off)
    ext_len = s.reass_len.at[first_touch].set(new_end - new_off)
    cleared = touch & (jnp.arange(ext_len.shape[0]) != first_touch)
    ext_len = jnp.where(cleared, 0, ext_len)
    # free slot: first with len == 0
    free = s.reass_len == 0
    first_free = jnp.argmax(free)
    any_free = free.any()
    do_ins = ~has_touch & any_free
    ins_off = s.reass_off.at[first_free].set(
        jnp.where(do_ins, off, s.reass_off[first_free]))
    ins_len = s.reass_len.at[first_free].set(
        jnp.where(do_ins, length, s.reass_len[first_free]))
    off_out = jnp.where(has_touch, ext_off, ins_off)
    len_out = jnp.where(has_touch, ext_len, ins_len)
    bytes_out = (jnp.where(len_out > 0, len_out, 0).sum()
                 .astype(jnp.int32))
    return s._replace(reass_off=off_out, reass_len=len_out,
                      reass_bytes=bytes_out)


def _reass_drain(s):
    """_Reassembly.drain_from(rcv_nxt): advance through contiguous
    coverage, drop consumed/stale slots. Returns (state', advanced).

    The advance is a monotone fixpoint (each pass extends through every
    slot covering the current offset); a convergent while_loop reaches
    the same offset as a fixed REASS_SLOTS-iteration sweep — a chain of
    k covering ranges converges in <= k passes and k <= REASS_SLOTS —
    but typically in ONE pass, where the fixed sweep burned 128
    sequential iterations per event step (measured ~1 ms/step on v5e,
    the second-largest kernel cost behind _recv_sack_blocks)."""
    off0 = s.rcv_nxt

    def cond(c):
        _, advanced = c
        return advanced

    def body(c):
        off, _ = c
        covering = (s.reass_len > 0) & (s.reass_off <= off) \
            & (off < s.reass_off + s.reass_len)
        end = jnp.where(covering, s.reass_off + s.reass_len, off).max()
        new = jnp.maximum(off, end)
        return new, new > off

    off, _ = jax.lax.while_loop(cond, body, (off0, jnp.bool_(True)))
    keep = (s.reass_len > 0) & (s.reass_off + s.reass_len > off)
    new_len = jnp.where(keep, s.reass_len, 0)
    new_bytes = new_len.sum().astype(jnp.int32)
    adv = off - off0
    return s._replace(
        rcv_nxt=off, reass_len=new_len, reass_bytes=new_bytes,
        ordered_bytes=s.ordered_bytes + adv,
    ), adv


# -- SACK scoreboard (slot-for-slot mirror of _SackScoreboard) -------------

def _sb_insert(ss, se, start, end, una):
    start = jnp.maximum(start, una)
    valid = start < end
    live = se > ss
    contained = (live & (ss <= start) & (end <= se)).any()
    overlap = live & (start <= se) & (ss <= end)
    has_ov = overlap.any()
    first_ov = jnp.argmax(overlap)
    ext_s = ss.at[first_ov].set(jnp.minimum(ss[first_ov], start))
    ext_e = se.at[first_ov].set(jnp.maximum(se[first_ov], end))
    empty = ~live
    has_empty = empty.any()
    first_empty = jnp.argmax(empty)
    ins_s = ss.at[first_empty].set(start)
    ins_e = se.at[first_empty].set(end)
    do_ext = valid & ~contained & has_ov
    do_ins = valid & ~contained & ~has_ov & has_empty
    out_s = jnp.where(do_ext, ext_s, jnp.where(do_ins, ins_s, ss))
    out_e = jnp.where(do_ext, ext_e, jnp.where(do_ins, ins_e, se))
    return out_s, out_e


def _sb_prune(ss, se, una):
    live = se > ss
    s2 = jnp.where(live, jnp.maximum(ss, una), ss)
    dead = live & (s2 >= se)
    return (jnp.where(dead, 0, s2), jnp.where(dead, 0, se))


def _sb_next(ss, se, off):
    """(off', cap): first unsacked offset >= off; bytes to the next range
    above (SB_INF when none). Convergent while_loop: same fixpoint as a
    fixed SACK_SLOTS sweep (see _reass_drain), typically one pass."""
    def cond(c):
        _, advanced = c
        return advanced

    def body(c):
        o, _ = c
        covering = (se > ss) & (ss <= o) & (o < se)
        new = jnp.maximum(o, jnp.where(covering, se, o).max())
        return new, new > o

    off, _ = jax.lax.while_loop(cond, body, (off, jnp.bool_(True)))
    above = (se > ss) & (ss > off)
    cap = jnp.where(above, ss - off, SB_INF).min()
    return off, cap


def _recv_sack_blocks(s):
    """Receiver SACK blocks (mirror of _sack_blocks): reassembly ranges
    sorted ascending, touching ranges merged, lowest 3 reported. Returns
    (nsack, [3] wire starts, [3] wire ends) as int32 wire-bit values.

    Parallel interval merge. The round-4 form swept a sequential
    fori_loop over all REASS_SLOTS entries per event step — measured
    ~3 ms/step on v5e, the single largest kernel cost (~half the whole
    event kernel). Same math, log-depth: after the stable sort by start,
    a range opens a NEW merged block iff its start lies past the running
    maximum of all earlier ends (the running max always belongs to the
    current block: ranges are start-sorted, so once a block opens, every
    earlier end is below its start). Prefix-max via associative_scan,
    block ids via cumsum, then three masked reductions pick the lowest
    SACK_WIRE_BLOCKS blocks — identical output to the sequential merge."""
    live = s.reass_len > 0
    starts = jnp.where(live, s.reass_off, I32_MAX)
    ends = jnp.where(live, s.reass_off + s.reass_len, 0)
    starts, ends = jax.lax.sort((starts, ends), dimension=0, is_stable=True,
                                num_keys=1)
    valid = starts < I32_MAX
    incl_max = jax.lax.associative_scan(jnp.maximum, ends)
    prev_max = jnp.concatenate(
        [jnp.full((1,), -1, jnp.int32), incl_max[:-1]])
    # merge condition in the sequential form: st <= end-of-current-block;
    # new block iff st > max of ALL previous ends (equivalent, see above)
    is_new = valid & (starts > prev_max)
    block = jnp.cumsum(is_new.astype(jnp.int32)) - 1  # id per entry
    cnt = is_new.sum().astype(jnp.int32)

    idx3 = jnp.arange(SACK_WIRE_BLOCKS)
    in_blk = valid[None, :] & (block[None, :] == idx3[:, None])
    m_s = jnp.where(in_blk, starts[None, :], I32_MAX).min(axis=1)
    m_s = jnp.where(idx3 < cnt, m_s, 0)
    m_e = jnp.where(in_blk, ends[None, :], 0).max(axis=1)
    n = jnp.minimum(cnt, SACK_WIRE_BLOCKS)
    base = s.irs + jnp.uint32(1)
    idx = jnp.arange(SACK_WIRE_BLOCKS)
    sel = idx < n
    ws = jnp.where(sel, (base + m_s[idx].astype(jnp.uint32))
                   .astype(jnp.int32), 0)
    we = jnp.where(sel, (base + m_e[idx].astype(jnp.uint32))
                   .astype(jnp.int32), 0)
    has = s.sack_ok & (cnt > 0)
    return jnp.where(has, n, 0), jnp.where(has, ws, 0), jnp.where(has, we, 0)


# ---------------------------------------------------------------------------
# event handlers (scalar; mirror TcpConnection method-for-method)
# ---------------------------------------------------------------------------

def _enter_closed(s, errno):
    """errno 0 = none."""
    s2 = _disarm_rto(s._replace(state=jnp.int32(CLOSED)))
    s2 = s2._replace(
        error=jnp.where((errno != 0) & (s.error == 0), errno, s.error),
        persist_gen=s2.persist_gen + 1,
    )
    return s2


def _enter_time_wait(s, now_ms):
    s2 = _disarm_rto(s._replace(state=jnp.int32(TIME_WAIT)))
    # the TIME_WAIT timer rides the rto generation (connection.py:867-874)
    return s2._replace(rto_deadline_ms=now_ms + TIME_WAIT_MS)


def _ev_open_active(s, f, now_ms):
    s = s._replace(iss=f[0].astype(jnp.uint32),
                   state=jnp.int32(SYN_SENT))
    return _arm_rto(s, now_ms)


def _ev_open_passive(s, f, now_ms):
    # f: iss, syn_seq, syn_window, wscale(-1 none), ts, ts_echo
    has_ws = f[3] >= 0
    s = s._replace(
        iss=f[0].astype(jnp.uint32), irs=f[1].astype(jnp.uint32),
        rcv_nxt=jnp.int32(0),
        peer_wscale=jnp.where(has_ws, jnp.minimum(f[3], MAX_WSCALE),
                              s.peer_wscale),
        wscale_ok=has_ws,
        my_wscale=jnp.where(has_ws, s.my_wscale, 0),
        snd_wnd=f[2],
        last_ts_recv=jnp.where(f[4] != 0, f[4].astype(jnp.uint32),
                               s.last_ts_recv),
        sack_ok=(f[6] != 0) & s.sack_on,  # peer offered AND config.sack
        state=jnp.int32(SYN_RCVD),
    )
    return _arm_rto(s, now_ms)


def _ev_write(s, f, now_ms):
    """Returns (state', accepted-or-negative-errno)."""
    err = s.error != 0
    notconn = (s.state == CLOSED) | (s.state == LISTEN)
    pipe = s.fin_requested
    n = jnp.minimum(_send_space(s), f[0])
    accepted = s._replace(stream_len=s.stream_len + n)
    accepted = _sel(
        (n > 0) & (s.snd_wnd == 0) & (s.state >= ESTABLISHED),
        _arm_persist(accepted, now_ms), accepted)
    bad = err | notconn | pipe
    ret = jnp.where(err, -s.error,
                    jnp.where(notconn, -107, jnp.where(pipe, -32, n)))
    return _sel(bad, s, accepted), ret


def _ev_read(s, f):
    """Returns (state', got-or-negative-errno)."""
    err_path = (s.error != 0) & (s.ordered_bytes == 0)
    eof = s.error_consumed
    raise_now = err_path & ~eof
    got = jnp.minimum(f[0], s.ordered_bytes)
    drained = s._replace(
        ordered_bytes=s.ordered_bytes - got,
        ack_pending=s.ack_pending | (got > 0),
    )
    out = _sel(err_path, s._replace(error_consumed=jnp.bool_(True)), drained)
    ret = jnp.where(raise_now, -s.error, jnp.where(err_path, 0, got))
    return out, ret


def _ev_close(s):
    st = s.state
    trivially = (st == CLOSED) | (st == LISTEN)
    syn_sent = st == SYN_SENT
    already = s.fin_requested
    nxt = jnp.where(
        (st == ESTABLISHED) | (st == SYN_RCVD), FIN_WAIT_1,
        jnp.where(st == CLOSE_WAIT, LAST_ACK, st))
    closed = s._replace(state=jnp.int32(CLOSED))
    requested = s._replace(fin_requested=jnp.bool_(True),
                           state=nxt.astype(jnp.int32))
    return _sel(trivially, closed,
                _sel(syn_sent, _enter_closed(s, jnp.int32(0)),
                     _sel(already, s, requested)))


def _ev_abort(s):
    st = s.state
    trivially = (st == CLOSED) | (st == LISTEN) | (st == TIME_WAIT)
    return _sel(trivially, s._replace(state=jnp.int32(CLOSED)),
                s._replace(rst_pending=jnp.bool_(True)))


# -- segment ingress -------------------------------------------------------

def _unwrap_ack(s, wire_ack_u):
    """Returns (ignore, adv, is_eq): ignore = RFC 793 never-sent ack;
    adv = forward stream-bytes acked (0 when backward/equal); is_eq =
    ack sits exactly at snd_una."""
    base = _wire_seq(s, s.snd_una)
    delta = (wire_ack_u - base).astype(jnp.uint32)
    is_fwd = delta < jnp.uint32(1 << 31)
    limit = (jnp.maximum(s.snd_nxt, s.snd_max) - s.snd_una).astype(jnp.uint32)
    fwd_valid = is_fwd & (delta <= limit)
    ignore = is_fwd & ~fwd_valid
    adv = jnp.where(fwd_valid, delta.astype(jnp.int32), 0)
    is_eq = fwd_valid & (delta == 0)
    return ignore, adv, is_eq


def _process_ack(s, f, now_ms):
    wire_ack = f[2].astype(jnp.uint32)
    paylen, wnd = f[4], f[3]
    ts_echo = f[7]
    ignore, adv, is_eq = _unwrap_ack(s, wire_ack)
    ack_off = s.snd_una + adv

    # SYN_RCVD completing ACK: any FORWARD-valid ack (ack_off >= 0,
    # connection.py:644) — a stale backward ack must NOT complete it
    fwd_valid = is_eq | (adv > 0)
    complete = (s.state == SYN_RCVD) & fwd_valid
    s_hs = s._replace(syn_acked=jnp.bool_(True),
                      state=jnp.int32(ESTABLISHED))
    s_hs = _disarm_rto(s_hs)
    s_hs = _sel((ts_echo != 0) & (s_hs.backoff_count == 0),
                _rtt_update(s_hs, now_ms - ts_echo), s_hs)
    s = _sel(complete, s_hs, s)

    # SACK blocks -> scoreboard (connection.py inserts before the ack
    # advance, with the PRE-advance snd_una as the clip)
    base0 = _wire_seq(s, jnp.int32(0))
    limit = jnp.maximum(s.snd_nxt, s.snd_max)
    ss, se = s.sacked_s, s.sacked_e
    nsack = f[9]
    for _k in range(SACK_WIRE_BLOCKS):
        bws = f[10 + 2 * _k].astype(jnp.uint32)
        bwe = f[11 + 2 * _k].astype(jnp.uint32)
        s_off = (bws - base0).astype(jnp.int32)
        e_off = (bwe - base0).astype(jnp.int32)
        bval = (s.sack_ok & ~ignore & (_k < nsack) & (s_off >= 0)
                & (e_off >= 0) & (s_off < e_off) & (e_off <= limit))
        ns, ne = _sb_insert(ss, se, s_off, e_off, s.snd_una)
        ss = jnp.where(bval, ns, ss)
        se = jnp.where(bval, ne, se)
    s = s._replace(sacked_s=ss, sacked_e=se)

    fin_off = s.stream_len + 1
    new_window = (wnd << jnp.where(s.wscale_ok, s.peer_wscale, 0)) \
        .astype(jnp.int32)

    # --- new data acked -------------------------------------------------
    newly = adv > 0
    acked_bytes = jnp.minimum(ack_off, s.stream_len) - s.snd_una
    a = s._replace(snd_una=jnp.minimum(ack_off, s.stream_len))
    ack_covers_fin = s.fin_sent & (ack_off >= fin_off)
    a = a._replace(
        fin_acked=a.fin_acked | ack_covers_fin,
        snd_una=jnp.where(ack_covers_fin, a.stream_len, a.snd_una))
    a = a._replace(snd_nxt=jnp.maximum(a.snd_nxt, a.snd_una))
    pr_s, pr_e = _sb_prune(a.sacked_s, a.sacked_e, a.snd_una)
    a = a._replace(sacked_s=pr_s, sacked_e=pr_e)
    n_seg = (acked_bytes + MSS - 1) // MSS
    partial = (a.phase == PH_RECOVERY) & (ack_off < a.recover)
    a_partial = _cong_partial_ack(a, n_seg)._replace(
        retx_pending=jnp.bool_(True))
    a_full = _cong_new_ack(a, n_seg)._replace(retx_pending=jnp.bool_(False))
    a = _sel(acked_bytes > 0, _sel(partial, a_partial, a_full),
             a._replace(retx_pending=jnp.bool_(False)))
    a = _sel((ts_echo != 0) & (a.backoff_count == 0),
             _rtt_update(a, now_ms - ts_echo), a)
    a = _rtt_reset_backoff(a)
    in_flight = (a.snd_nxt > a.snd_una) | (a.fin_sent & ~a.fin_acked)
    a = _sel(in_flight, _arm_rto(a, now_ms), _disarm_rto(a))
    # FIN-acked transitions
    fw1 = a.state == FIN_WAIT_1
    closing = a.state == CLOSING
    last = a.state == LAST_ACK
    a = _sel(a.fin_acked & fw1, a._replace(state=jnp.int32(FIN_WAIT_2)),
             _sel(a.fin_acked & closing, _enter_time_wait(a, now_ms),
                  _sel(a.fin_acked & last, _enter_closed(a, jnp.int32(0)),
                       a)))

    # --- duplicate ack --------------------------------------------------
    dup = (is_eq & (paylen == 0) & (s.snd_nxt > s.snd_una)
           & (new_window == s.snd_wnd) & (new_window > 0))
    d, fast = _cong_dup_ack(s)
    d = _sel(fast, d._replace(retx_pending=jnp.bool_(True),
                              recover=d.snd_nxt), d)

    out = _sel(newly, a, _sel(dup, d, s))
    out = out._replace(snd_wnd=new_window)
    out = _sel((out.snd_wnd == 0) & (out.stream_len > out.snd_nxt),
               _arm_persist(out, now_ms), out)
    return _sel(ignore, s, out)


def _process_payload(s, f, now_ms):
    seq_u, paylen = f[1].astype(jnp.uint32), f[4]
    tw = s.state == TIME_WAIT
    base = _wire_rcv_nxt(s)
    delta = (seq_u - base).astype(jnp.uint32)
    is_fwd = delta < jnp.uint32(1 << 31)
    back = (base - seq_u).astype(jnp.int32)  # valid when ~is_fwd

    # backward: trim left by `back`; forward: starts `delta` into the space
    pure_dup = ~is_fwd & (back >= paylen)
    space = _recv_space(s)
    beyond = is_fwd & (delta.astype(jnp.int32) >= space)
    eff_off = jnp.where(is_fwd, s.rcv_nxt + delta.astype(jnp.int32),
                        s.rcv_nxt)
    raw_len = jnp.where(is_fwd, paylen, paylen - back)
    # right-trim to the receive window in both cases
    avail = space - (eff_off - s.rcv_nxt)
    eff_len = jnp.minimum(raw_len, avail)
    ok = ~tw & ~pure_dup & ~beyond & (eff_len > 0)

    ins = _reass_insert(s, eff_off, eff_len)
    ins, _adv = _reass_drain(ins)
    out = _sel(ok, ins, s)
    out = out._replace(ack_pending=jnp.bool_(True))
    return _sel(tw | pure_dup | beyond, out,
                _maybe_apply_fin_t(out, now_ms))


def _maybe_apply_fin_t(s, now_ms):
    """_maybe_apply_pending_fin (clock only feeds TIME_WAIT's deadline)."""
    applies = (~s.fin_received & s.has_fin_offset
               & (s.fin_offset <= s.rcv_nxt))
    a = s._replace(fin_received=jnp.bool_(True))
    est = a.state == ESTABLISHED
    fw1 = a.state == FIN_WAIT_1
    fw2 = a.state == FIN_WAIT_2
    a = _sel(est, a._replace(state=jnp.int32(CLOSE_WAIT)),
             _sel(fw1 & a.fin_acked, _enter_time_wait(a, now_ms),
                  _sel(fw1, a._replace(state=jnp.int32(CLOSING)),
                       _sel(fw2, _enter_time_wait(a, now_ms), a))))
    return _sel(applies, a, s)


def _process_fin(s, f, now_ms):
    seq_u, paylen = f[1].astype(jnp.uint32), f[4]
    end = seq_u + _u32(paylen)
    base = _wire_rcv_nxt(s)
    delta = (end - base).astype(jnp.uint32)
    is_fwd = delta < jnp.uint32(1 << 31)
    # clamp bogus-huge forward offsets below int32 overflow; they can
    # never apply (fin_offset > rcv_nxt forever), matching the CPU
    dd = jnp.minimum(delta.astype(jnp.int32) & 0x7FFFFFFF, 1 << 30)
    fin_off = jnp.where(is_fwd, s.rcv_nxt + dd, s.rcv_nxt)
    s = s._replace(
        fin_offset=jnp.where(s.has_fin_offset, s.fin_offset, fin_off),
        has_fin_offset=jnp.bool_(True),
        ack_pending=jnp.bool_(True),
    )
    return _maybe_apply_fin_t(s, now_ms)


def _on_segment_syn_sent(s, f, now_ms):
    flags = f[0]
    is_rst = (flags & RST) != 0
    is_syn = (flags & SYN) != 0
    is_ack = (flags & ACK) != 0
    ack_u = f[2].astype(jnp.uint32)
    expect = s.iss + jnp.uint32(1)
    refused = is_rst & is_ack & (ack_u == expect)
    r = _sel(refused, _enter_closed(s, jnp.int32(111)), s)

    has_ws = f[5] >= 0
    # SYN|ACK
    bad_ack = ack_u != expect
    sa = s._replace(
        irs=f[1].astype(jnp.uint32), rcv_nxt=jnp.int32(0),
        syn_acked=jnp.bool_(True), syn_outstanding=jnp.bool_(False),
        peer_wscale=jnp.where(has_ws, jnp.minimum(f[5], MAX_WSCALE),
                              s.peer_wscale),
        wscale_ok=has_ws,
        my_wscale=jnp.where(has_ws, s.my_wscale, 0),
        sack_ok=(f[8] != 0) & s.sack_on,
        snd_wnd=f[3], state=jnp.int32(ESTABLISHED),
        ack_pending=jnp.bool_(True),
    )
    sa = _disarm_rto(sa)
    sa = _sel((f[7] != 0) & (sa.backoff_count == 0),
              _rtt_update(sa, now_ms - f[7]), sa)
    sa = _sel(bad_ack, s._replace(rst_pending=jnp.bool_(True)), sa)
    # simultaneous open (SYN, no ACK)
    so = s._replace(
        irs=f[1].astype(jnp.uint32), rcv_nxt=jnp.int32(0),
        peer_wscale=jnp.where(has_ws, jnp.minimum(f[5], MAX_WSCALE),
                              s.peer_wscale),
        wscale_ok=has_ws, sack_ok=(f[8] != 0) & s.sack_on, snd_wnd=f[3],
        state=jnp.int32(SYN_RCVD),
        syn_outstanding=jnp.bool_(False), syn_sends=jnp.int32(0),
    )
    return _sel(is_rst, r,
                _sel(is_syn & is_ack, sa, _sel(is_syn, so, s)))


def _ev_segment(s, f, now_ms):
    closed = s.state == CLOSED
    # record peer timestamp to echo (f[6] = ts)
    s1 = s._replace(last_ts_recv=jnp.where(
        f[6] != 0, f[6].astype(jnp.uint32), s.last_ts_recv))

    syn_sent = s1.state == SYN_SENT
    ss = _on_segment_syn_sent(s1, f, now_ms)

    flags = f[0]
    # RST in any synchronized state
    is_rst = (flags & RST) != 0
    tw = s1.state == TIME_WAIT
    r = _sel(tw, _enter_closed(s1, jnp.int32(0)),
             _enter_closed(s1, jnp.int32(104)))

    # SYN outside handshake
    is_syn = (flags & SYN) != 0
    dup_syn = (s1.state == SYN_RCVD) & (f[1].astype(jnp.uint32) == s1.irs)
    syn_dup = s1._replace(syn_outstanding=jnp.bool_(False))
    # old duplicate SYN below the window (e.g. a retransmitted SYN|ACK
    # after our handshake-completing ACK was lost): RFC 793 p.69 /
    # RFC 5961 — answer with an ACK, never RST (connection.py twin,
    # fixed together round 5; reachable once the wire is lossy)
    syn_delta = (_wire_rcv_nxt(s1) - f[1].astype(jnp.uint32)) \
        .astype(jnp.uint32)
    syn_is_old = (syn_delta != 0) & (syn_delta < jnp.uint32(1 << 31))
    syn_old = s1._replace(ack_pending=jnp.bool_(True))
    syn_other = _sel(tw, s1,
                     _sel(syn_is_old, syn_old,
                          s1._replace(rst_pending=jnp.bool_(True))))
    sy = _sel(dup_syn, syn_dup, syn_other)

    # normal path
    n = s1
    n = _sel((flags & ACK) != 0, _process_ack(n, f, now_ms), n)
    n = _sel(f[4] > 0, _process_payload(n, f, now_ms), n)
    n = _sel((flags & FIN) != 0, _process_fin(n, f, now_ms), n)

    out = _sel(syn_sent, ss,
               _sel(is_rst, r, _sel(is_syn, sy, n)))
    # RFC 793: non-RST segment at a CLOSED connection elicits a RESET
    # (connection.py twin fixed together round 5) — note the CPU twin
    # returns before recording the timestamp, hence `s` not `s1`
    closed_rst = s._replace(rst_pending=s.rst_pending | ~is_rst)
    return _sel(closed, closed_rst, out)


# -- timers ----------------------------------------------------------------

def _ev_timer_rto(s, f, now_ms):
    gen = f[0]
    stale = (gen != s.rto_gen) | (s.state == CLOSED)

    a = s._replace(rto_armed=jnp.bool_(False))
    in_flight = ((a.snd_nxt > a.snd_una) | (a.fin_sent & ~a.fin_acked)
                 | (a.state == SYN_SENT) | (a.state == SYN_RCVD))
    handshake = (a.state == SYN_SENT) | (a.state == SYN_RCVD)
    limit = jnp.where(handshake, SYN_RETRIES, DATA_RETRIES)
    give_up = a.backoff_count >= limit
    gu = _enter_closed(a, jnp.int32(110))

    b = _rtt_backoff(a)
    b = _cong_timeout(b)
    hs = b._replace(syn_outstanding=jnp.bool_(False))
    gbn = b._replace(
        gbn_high=jnp.maximum(b.gbn_high, b.snd_nxt),
        snd_nxt=b.snd_una, retx_pending=jnp.bool_(False),
        fin_sent=b.fin_sent & b.fin_acked,
    )
    gbn = _sel((gbn.snd_wnd == 0) & (gbn.stream_len > gbn.snd_nxt),
               _arm_persist(gbn, now_ms), gbn)
    b = _sel(handshake, hs, gbn)
    b = _arm_rto(b, now_ms)
    fired = _sel(give_up, gu, b)
    return _sel(stale, s, _sel(in_flight, fired, a))


def _ev_timer_tw(s, f, now_ms):
    gen = f[0]
    ok = gen == s.rto_gen
    return _sel(ok, _enter_closed(s, jnp.int32(0)), s)


def _ev_timer_persist(s, f, now_ms):
    gen = f[0]
    stale = (gen != s.persist_gen) | (s.state == CLOSED)
    a = s._replace(persist_armed=jnp.bool_(False))
    due = (a.snd_wnd == 0) & (a.stream_len > a.snd_nxt)
    b = a._replace(probe_pending=jnp.bool_(True))
    b = _rtt_backoff(b)
    b = b._replace(persist_gen=b.persist_gen + 1,
                   persist_armed=jnp.bool_(True),
                   persist_deadline_ms=now_ms + b.rto_ms)
    return _sel(stale, s, _sel(due, b, a))


# -- egress (PULL = next_segment) ------------------------------------------

K_NONE, K_RST, K_SYN, K_RETX, K_PROBE, K_DATA, K_FIN, K_ACK = range(8)


def _next_kind(s):
    hs = (s.state == SYN_SENT) | (s.state == SYN_RCVD)
    can_data = (
        ((s.state == ESTABLISHED) | (s.state == CLOSE_WAIT)
         | (s.state == FIN_WAIT_1) | (s.state == CLOSING)
         | (s.state == LAST_ACK))
        & (s.snd_nxt < s.stream_len)
        & (s.snd_nxt - s.snd_una
           < jnp.minimum(s.cwnd * MSS, s.snd_wnd))
    )
    should_fin = (
        s.fin_requested & ~s.fin_sent & (s.snd_nxt >= s.stream_len)
        & ((s.state == FIN_WAIT_1) | (s.state == LAST_ACK)
           | (s.state == CLOSING))
    )
    return jnp.where(
        s.rst_pending, K_RST,
        jnp.where(hs & ~s.syn_outstanding, K_SYN,
        jnp.where(s.state == SYN_SENT, K_NONE,
        jnp.where(s.retx_pending & (s.snd_nxt > s.snd_una), K_RETX,
        jnp.where(s.probe_pending & (s.stream_len > s.snd_nxt), K_PROBE,
        jnp.where(can_data, K_DATA,
        jnp.where(should_fin, K_FIN,
        jnp.where(s.ack_pending & (s.state != CLOSED), K_ACK,
                  K_NONE)))))))).astype(jnp.int32)


def _ev_pull(s, now_ms, gso_segs: int = 1):
    """next_segment(): returns (state', out[18]):
    out = (has, flags, seq(u32 bits), ack, window, paylen, wscale(-1),
           ts, ts_echo, retransmit, sack_permitted, nsack, s1, e1, s2,
           e2, s3, e3).

    gso_segs > 1 emits one TSO/GSO-style macro-segment of up to
    gso_segs*MSS contiguous payload per pull (the flow engine's wire
    draws loss per MSS unit and truncates — floweng._pull_phase). The
    CPU twin and the trace-replay contract always use gso_segs=1;
    retransmissions stay single-MSS in both."""
    kind = _next_kind(s)
    before_nxt = s.snd_nxt
    zero = jnp.int32(0)

    def stamp(ts_out):
        return now_ms & 0x7FFFFFFF, s.last_ts_recv.astype(jnp.int32)

    nb_blk, ws_blk, we_blk = _recv_sack_blocks(s)
    sack_tail = (zero, nb_blk, ws_blk[0], we_blk[0], ws_blk[1], we_blk[1],
                 ws_blk[2], we_blk[2])
    no_sack_tail = (zero,) * 8

    # --- syn ---
    syn_state = s._replace(syn_outstanding=jnp.bool_(True),
                           syn_sends=s.syn_sends + 1)
    syn_retx = syn_state.syn_sends > 1
    syn_state = syn_state._replace(
        retransmit_count=syn_state.retransmit_count
        + jnp.where(syn_retx, 1, 0),
        ack_pending=jnp.bool_(False))
    syn_is_sent = s.state == SYN_SENT
    syn_flags = jnp.where(syn_is_sent, SYN, SYN | ACK)
    syn_ack = jnp.where(syn_is_sent, jnp.uint32(0), _wire_ack(s))
    syn_out = (jnp.int32(1), syn_flags, s.iss.astype(jnp.int32),
               syn_ack.astype(jnp.int32),
               _advertised_window(s, jnp.bool_(True)), zero,
               s.my_wscale, *stamp(0), syn_retx.astype(jnp.int32),
               s.sack_on.astype(jnp.int32), *((zero,) * 7))

    # --- data ---
    off0 = s.snd_nxt
    # never (re)send SACKed bytes: jump over held ranges, cap at the next
    off, d_cap = _sb_next(s.sacked_s, s.sacked_e, off0)
    in_flight = off - s.snd_una
    window = jnp.minimum(s.cwnd * MSS, s.snd_wnd)
    n_data = jnp.minimum(
        jnp.minimum(jnp.minimum(MSS * gso_segs, s.stream_len - off),
                    window - in_flight), d_cap)
    d_has = n_data > 0
    n_eff = jnp.maximum(n_data, 0)
    d_state = s._replace(
        snd_nxt=jnp.where(d_has, off + n_eff, jnp.maximum(off, off0)),
        snd_max=jnp.maximum(s.snd_max,
                            jnp.where(d_has, off + n_eff, off)),
        ack_pending=jnp.bool_(False))
    d_state = _sel(d_state.rto_armed | ~d_has, d_state,
                   _arm_rto(d_state, now_ms))
    d_flags = jnp.where(d_state.snd_nxt >= s.stream_len, ACK | PSH, ACK)
    data_gbn = before_nxt < s.gbn_high
    d_state = d_state._replace(
        retransmit_count=d_state.retransmit_count
        + jnp.where(data_gbn, 1, 0),
        retransmitted_bytes=d_state.retransmitted_bytes
        + jnp.where(data_gbn & d_has, n_eff, 0))
    # n <= 0 (everything in reach already held): _build_data falls back to
    # _build_ack, with the jumped snd_nxt already applied
    d_ack_seq = jnp.minimum(d_state.snd_nxt,
                            s.stream_len + jnp.where(s.fin_sent, 1, 0))
    d_out = (jnp.int32(1),
             jnp.where(d_has, d_flags, ACK),
             jnp.where(d_has, _wire_seq(s, off).astype(jnp.int32),
                       _wire_seq(s, d_ack_seq).astype(jnp.int32)),
             _wire_ack(s).astype(jnp.int32),
             _advertised_window(s, jnp.bool_(False)),
             jnp.where(d_has, n_data, 0),
             jnp.int32(-1), *stamp(0), data_gbn.astype(jnp.int32),
             *sack_tail)

    # --- retransmit (n>0 data at snd_una; else FIN-retx or bare ack) ---
    r_state0 = s._replace(retx_pending=jnp.bool_(False),
                          retransmit_count=s.retransmit_count + 1)
    _, r_cap = _sb_next(s.sacked_s, s.sacked_e, s.snd_una)
    r_n = jnp.minimum(jnp.minimum(MSS, s.stream_len - s.snd_una), r_cap)
    r_has_data = r_n > 0
    r_data = r_state0._replace(
        retransmitted_bytes=r_state0.retransmitted_bytes + r_n)
    r_data = _sel(r_data.rto_armed, r_data, _arm_rto(r_data, now_ms))
    r_data_out = (jnp.int32(1), jnp.int32(ACK),
                  _wire_seq(s, s.snd_una).astype(jnp.int32),
                  _wire_ack(s).astype(jnp.int32),
                  _advertised_window(s, jnp.bool_(False)), r_n,
                  jnp.int32(-1), *stamp(0), jnp.int32(1), *sack_tail)
    # FIN retransmit branch (fin_sent & no data)
    rf_state = r_state0._replace(ack_pending=jnp.bool_(False))
    rf_state = _sel(rf_state.rto_armed, rf_state, _arm_rto(rf_state, now_ms))
    rf_out = (jnp.int32(1), jnp.int32(FIN | ACK),
              _wire_seq(s, s.stream_len).astype(jnp.int32),
              _wire_ack(s).astype(jnp.int32),
              _advertised_window(s, jnp.bool_(False)), zero,
              jnp.int32(-1), *stamp(0), jnp.int32(1), *sack_tail)
    # bare-ack branch
    ra_state = r_state0._replace(ack_pending=jnp.bool_(False))
    ra_seq = jnp.minimum(s.snd_nxt,
                         s.stream_len + jnp.where(s.fin_sent, 1, 0))
    ra_out = (jnp.int32(1), jnp.int32(ACK),
              _wire_seq(s, ra_seq).astype(jnp.int32),
              _wire_ack(s).astype(jnp.int32),
              _advertised_window(s, jnp.bool_(False)), zero,
              jnp.int32(-1), *stamp(0), jnp.int32(1), *sack_tail)

    # --- probe (1 byte past the window) ---
    p_state = s._replace(probe_pending=jnp.bool_(False),
                         snd_nxt=s.snd_nxt + 1,
                         snd_max=jnp.maximum(s.snd_max, s.snd_nxt + 1))
    p_state = _sel(p_state.rto_armed, p_state, _arm_rto(p_state, now_ms))
    p_out = (jnp.int32(1), jnp.int32(ACK),
             _wire_seq(s, s.snd_nxt).astype(jnp.int32),
             _wire_ack(s).astype(jnp.int32),
             _advertised_window(s, jnp.bool_(False)), jnp.int32(1),
             jnp.int32(-1), *stamp(0), jnp.int32(1), *sack_tail)

    # --- fin ---
    f_state = s._replace(fin_sent=jnp.bool_(True),
                         snd_nxt=s.stream_len + 1,
                         snd_max=jnp.maximum(s.snd_max, s.stream_len + 1),
                         ack_pending=jnp.bool_(False))
    f_state = _sel(f_state.rto_armed, f_state, _arm_rto(f_state, now_ms))
    fin_gbn = before_nxt < s.gbn_high
    f_state = f_state._replace(
        retransmit_count=f_state.retransmit_count
        + jnp.where(fin_gbn, 1, 0))
    f_out = (jnp.int32(1), jnp.int32(FIN | ACK),
             _wire_seq(s, s.stream_len).astype(jnp.int32),
             _wire_ack(s).astype(jnp.int32),
             _advertised_window(s, jnp.bool_(False)), zero,
             jnp.int32(-1), *stamp(0), fin_gbn.astype(jnp.int32),
             *sack_tail)

    # --- ack ---
    a_state = s._replace(ack_pending=jnp.bool_(False))
    a_seq = jnp.minimum(s.snd_nxt,
                        s.stream_len + jnp.where(s.fin_sent, 1, 0))
    a_out = (jnp.int32(1), jnp.int32(ACK),
             _wire_seq(s, a_seq).astype(jnp.int32),
             _wire_ack(s).astype(jnp.int32),
             _advertised_window(s, jnp.bool_(False)), zero,
             jnp.int32(-1), *stamp(0), jnp.int32(0), *sack_tail)

    # --- rst ---
    rst_seq = jnp.minimum(s.snd_nxt, s.stream_len)
    # _build_rst is the one builder the CPU does NOT _stamp
    rst_out = (jnp.int32(1), jnp.int32(RST | ACK),
               _wire_seq(s, rst_seq).astype(jnp.int32),
               _wire_ack(s).astype(jnp.int32), zero, zero,
               jnp.int32(-1), zero, zero, jnp.int32(0), *no_sack_tail)
    rst_state = _enter_closed(s._replace(rst_pending=jnp.bool_(False)),
                              jnp.int32(104))

    none_out = tuple(jnp.int32(0) for _ in range(18))

    # merge: the retransmit kind has three sub-shapes
    retx_state = _sel(r_has_data, r_data,
                      _sel(s.fin_sent, rf_state, ra_state))
    retx_out = jax.tree.map(
        lambda x, y, z: jnp.where(r_has_data, x,
                                  jnp.where(s.fin_sent, y, z)),
        r_data_out, rf_out, ra_out)

    def pick(*pairs):
        state_out, seg_out = pairs[-1]
        for k, st, sg in reversed(pairs[:-1]):
            state_out = _sel(kind == k, st, state_out)
            seg_out = jax.tree.map(
                lambda x, y, k=k: jnp.where(kind == k, x, y), sg, seg_out)
        return state_out, seg_out

    out_state, out_seg = pick(
        (K_RST, rst_state, rst_out),
        (K_SYN, syn_state, syn_out),
        (K_RETX, retx_state, retx_out),
        (K_PROBE, p_state, p_out),
        (K_DATA, d_state, d_out),
        (K_FIN, f_state, f_out),
        (K_ACK, a_state, a_out),
        (s, none_out),
    )
    out_state = out_state._replace(
        last_retx=(out_seg[9] > 0) & (kind != K_NONE))
    return out_state, jnp.stack(out_seg)


# ---------------------------------------------------------------------------
# the event-step kernel
# ---------------------------------------------------------------------------

def _event_step_one(s: TcpPlane, kind, f, now_ms):
    """One event for one connection. Returns (state', out[18], ret)."""
    zero_out = jnp.zeros((18,), jnp.int32)
    ret = jnp.int32(0)

    s_oa = _ev_open_active(s, f, now_ms)
    s_op = _ev_open_passive(s, f, now_ms)
    s_wr, wr_ret = _ev_write(s, f, now_ms)
    s_rd, rd_ret = _ev_read(s, f)
    s_cl = _ev_close(s)
    s_ab = _ev_abort(s)
    s_sg = _ev_segment(s, f, now_ms)
    s_pl, pull_out = _ev_pull(s, now_ms)
    s_tr = _ev_timer_rto(s, f, now_ms)
    s_tp = _ev_timer_persist(s, f, now_ms)
    s_tw = _ev_timer_tw(s, f, now_ms)

    out_state = s
    for k, st in ((EV_OPEN_ACTIVE, s_oa), (EV_OPEN_PASSIVE, s_op),
                  (EV_WRITE, s_wr), (EV_READ, s_rd), (EV_CLOSE, s_cl),
                  (EV_ABORT, s_ab), (EV_SEG, s_sg), (EV_PULL, s_pl),
                  (EV_TIMER_RTO, s_tr), (EV_TIMER_PERSIST, s_tp),
                  (EV_TIMER_TW, s_tw)):
        out_state = _sel(kind == k, st, out_state)
    out = jnp.where(kind == EV_PULL, pull_out, zero_out)
    ret = jnp.where(kind == EV_WRITE, wr_ret,
                    jnp.where(kind == EV_READ, rd_ret, ret))
    return out_state, out, ret


_event_step = jax.vmap(_event_step_one, in_axes=(0, 0, 0, 0))


def _sched_step_one(s: TcpPlane, kind, f, now_ms):
    """One SCHEDULED event for one connection: the subset of kinds the
    flow engine's fused step dispatches (segment arrivals, timers, and
    opens). App-side kinds (WRITE/READ/CLOSE) and PULL are applied
    inline/batched by the driver (`floweng._fused_step`), so this kernel
    pays a 6-way merge instead of tcp_event_step's 11-way."""
    s_oa = _ev_open_active(s, f, now_ms)
    s_op = _ev_open_passive(s, f, now_ms)
    s_sg = _ev_segment(s, f, now_ms)
    s_tr = _ev_timer_rto(s, f, now_ms)
    s_tp = _ev_timer_persist(s, f, now_ms)
    s_tw = _ev_timer_tw(s, f, now_ms)
    out = s
    for k, st in ((EV_OPEN_ACTIVE, s_oa), (EV_OPEN_PASSIVE, s_op),
                  (EV_SEG, s_sg), (EV_TIMER_RTO, s_tr),
                  (EV_TIMER_PERSIST, s_tp), (EV_TIMER_TW, s_tw)):
        out = _sel(kind == k, st, out)
    return out


tcp_sched_step = jax.vmap(_sched_step_one, in_axes=(0, 0, 0, 0))

# batched PULL (= next_segment) over all connections
def tcp_pull_step(plane: TcpPlane, now_ms, gso_segs: int = 1):
    return jax.vmap(lambda s, n: _ev_pull(s, n, gso_segs))(plane, now_ms)


def sel_batched(pred, a: TcpPlane, b: TcpPlane) -> TcpPlane:
    """Per-field select with a [C] predicate (broadcast over trailing
    per-slot axes)."""
    def w(x, y):
        p = pred.reshape(pred.shape + (1,) * (x.ndim - 1))
        return jnp.where(p, x, y)
    return jax.tree.map(w, a, b)


def tcp_event_step(plane: TcpPlane, kind: jax.Array, fields: jax.Array,
                   now_ms: jax.Array):
    """Step C connections, one event each.

    kind [C] int32 EV_*, fields [C, 16] int32, now_ms [C] int32.
    Returns (plane', out [C, 18], ret [C]) — `out` is the PULL segment
    metadata (has, flags, seq, ack, window, paylen, wscale, ts, ts_echo,
    retx, sack_permitted, nsack, 3x(start, end)), `ret` the WRITE/READ
    return value."""
    return _event_step(plane, kind, fields, now_ms)


def tcp_replay(plane: TcpPlane, kinds: jax.Array, fields: jax.Array,
               now_ms: jax.Array):
    """Replay [C, T] event streams with one lax.scan over T.

    Returns (plane', outs [T, C, 10], rets [T, C])."""
    def step(p, ev):
        k, f, t = ev
        p, out, ret = tcp_event_step(p, k, f, t)
        return p, (out, ret)

    plane, (outs, rets) = jax.lax.scan(
        step, plane,
        (jnp.moveaxis(kinds, 1, 0), jnp.moveaxis(fields, 1, 0),
         jnp.moveaxis(now_ms, 1, 0)),
    )
    return plane, outs, rets
