"""Device-resident inter-host transport for live simulations.

This wires the batched network plane (`shadow_tpu.tpu.plane`) into the
Manager's round loop, replacing the per-packet cross-host push
(`src/main/core/worker.rs:629-639`) with one device round trip per
scheduling round:

- during a round, `Worker.send_packet` CAPTURES each surviving outbound
  packet (source-host RNG loss draw, routing counters, and statuses all
  happen on the CPU exactly as in CPU-transport mode, so the two modes
  consume identical RNG streams and produce identical drop decisions);
- at the round barrier the batch is ingested into the device egress
  arrays with per-packet send times;
- at the START of the next round, `window_step` computes deliver times
  (send + latency, clamped to the round barrier — `worker.rs:396-399`),
  routes packets into per-destination ingress rows with the deterministic
  (deliver, src, seq) order, and releases everything due in the new
  window; released entries are pushed into host event queues under the
  same (time, src_host_id, src_event_id) keys the CPU path uses — so
  event order is bitwise-identical between transport modes.

The device token bucket is transparent here (relays already rate-limit on
the host side, `relay/mod.rs`), and the device loss matrix is zero (the
draw happened at capture). The device owns the transport data motion:
latency lookup, per-destination scatter, due-release, and the min
next-event reduction that feeds the controller.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

log = logging.getLogger("shadow_tpu.tpu")

I32_MAX = 2**31 - 1


class DeviceTransport:
    def __init__(self, hosts, routing, ip_to_node_id, *,
                 egress_cap: int = 256, ingress_cap: int = 256):
        import jax
        import jax.numpy as jnp

        from . import plane

        self._plane = plane
        self._jnp = jnp
        # host index = host_id - 1 (Manager assigns ids densely from 1)
        self.hosts = sorted(hosts, key=lambda h: h.host_id)
        n = len(self.hosts)
        assert [h.host_id for h in self.hosts] == list(range(1, n + 1))

        # node-level tables straight from the routing plane ([M, M], M =
        # graph nodes actually used) + a host->node map; no O(N^2) host
        # pair materialization
        node_lat = np.asarray(routing.latency_ns)
        if node_lat.size and node_lat.max() >= I32_MAX:
            raise ValueError("path latency exceeds the int32 device budget")
        host_node = np.asarray(
            [routing.node_index(h.node_id) for h in self.hosts], np.int32)
        m = node_lat.shape[0]
        self.params = plane.make_params(
            node_lat.astype(np.int32),
            np.zeros((m, m), np.float32),  # loss drawn at capture, on CPU
            np.full(n, 8e12),  # transparent bucket: relays already paced
            host_node=host_node,
        )
        self.state = plane.make_state(n, egress_cap, ingress_cap,
                                      initial_tokens=np.full(
                                          n, I32_MAX // 2, np.int32))
        self._rng_root = jax.random.PRNGKey(0)  # unused: loss matrix is 0
        # qdisc ordering happened on the CPU NIC before capture (FIFO-only
        # compile) and loss was drawn there too (no_loss compiles out the
        # draw + table gather)
        self._step = jax.jit(
            lambda *a: plane.window_step(*a, rr_enabled=False, no_loss=True))
        # the device-resident window chain (delivery-free rounds never
        # leave the device); static_argnums: max_windows via default
        self._chain = jax.jit(
            lambda *a: plane.chain_windows(*a, rr_enabled=False,
                                           no_loss=True))
        self._ingest = jax.jit(plane.ingest)
        self._ingress_cap = ingress_cap

        # capture buffers (protected by the manager's round structure: all
        # appends happen during run_round, all reads at the barrier)
        self._pending: list[tuple] = []
        self._packets: dict[tuple[int, int], object] = {}
        self._prev_start: Optional[int] = None
        self.next_pending_abs: Optional[int] = None
        self._overflow_seen = 0
        self._overflow_prev = np.zeros(n, np.int64)
        self._batch_pad = 64

    # -- capture (called from Worker.send_packet, any worker thread) -----

    def capture(self, src_host, dst_host, packet, now_ns: int, seq: int,
                round_end_ns: int) -> None:
        src_idx = src_host.host_id - 1
        dst_idx = dst_host.host_id - 1
        self._pending.append((
            src_idx, dst_idx,
            packet.payload_size() + 40,  # wire size approximation
            packet.priority or 0, seq,
            packet.payload_size() == 0, now_ns, round_end_ns,
        ))
        self._packets[(src_idx, seq)] = packet

    @property
    def in_flight(self) -> int:
        return len(self._packets)

    # -- round barrier: ingest this round's captures ---------------------

    def finish_round(self, start_ns: int, end_ns: int) -> None:
        if not self._pending:
            return
        jnp = self._jnp
        batch = self._pending
        self._pending = []
        b = len(batch)
        pad = self._batch_pad
        while pad < b:
            pad *= 2
        self._batch_pad = pad
        # times go in relative to the DEVICE base (= this round's start,
        # except when a window chain overshot a cross-thread post and the
        # base sits ahead of the round — negative send_rel is fine, the
        # arithmetic is all offsets)
        base_ns = self._prev_start if self._prev_start is not None else start_ns
        arr = np.zeros((8, pad), np.int64)
        arr[0, b:] = len(self.hosts)  # pad slots: out-of-range src
        arr[7, b:] = base_ns  # harmless clamp for dead slots
        for i, row in enumerate(batch):
            for k in range(8):
                arr[k, i] = int(row[k])
        send_rel = arr[6] - base_ns
        clamp_rel = arr[7] - base_ns  # the send-round's end
        self.state = self._ingest(
            self.state,
            jnp.asarray(arr[0], jnp.int32), jnp.asarray(arr[1], jnp.int32),
            jnp.asarray(arr[2], jnp.int32), jnp.asarray(arr[3], jnp.int32),
            jnp.asarray(arr[4], jnp.int32),
            jnp.asarray(arr[5].astype(bool)),
            valid=jnp.asarray(np.arange(pad) < b),
            send_rel=jnp.asarray(send_rel, jnp.int32),
            clamp_rel=jnp.asarray(clamp_rel, jnp.int32),
        )

    # -- round start: release everything due in [start, end) -------------

    def release(self, start_ns: int, end_ns: int,
                horizon_ns: Optional[int] = None,
                runahead_ns: Optional[int] = None,
                stop_ns: Optional[int] = None) -> None:
        """Run the window step and push due deliveries into host queues.

        With `runahead_ns`/`stop_ns` given (the Manager's round loop), the
        device chains through consecutive delivery-free windows in one
        `lax.while_loop` — window boundaries identical to the ones the CPU
        controller would pick — and only returns to Python when a window
        delivers or the next device event reaches `horizon_ns` (the
        earliest CPU-side event). Without them: one window (direct
        callers, e.g. the bitwise parity tests)."""
        if not self._packets:
            # nothing on device: skip the step; rebasing is irrelevant
            # because every slot is invalid
            self._prev_start = start_ns
            self.next_pending_abs = None
            return
        shift = 0 if self._prev_start is None else start_ns - self._prev_start
        if shift < 0:
            # A previous chain advanced the device base past this window's
            # start (a cross-thread post — e.g. a managed-process death —
            # scheduled an earlier CPU event after the chain ran). The
            # device holds nothing before its base, so only [base, end)
            # needs releasing; a window entirely behind the base has
            # nothing on device at all.
            if end_ns <= self._prev_start:
                return
            start_ns = self._prev_start
            shift = 0
        assert shift < I32_MAX, "window shift exceeds int32 ns budget"
        jnp = self._jnp
        if runahead_ns is not None and stop_ns is not None:
            clamp = I32_MAX // 2
            horizon_rel = min((horizon_ns if horizon_ns is not None
                               else stop_ns) - start_ns, clamp)
            stop_rel = min(stop_ns - start_ns, clamp)
            self.state, delivered, off, next_rel, _n = self._chain(
                self.state, self.params, self._rng_root, jnp.int32(shift),
                jnp.int32(end_ns - start_ns), jnp.int32(runahead_ns),
                jnp.int32(horizon_rel), jnp.int32(stop_rel),
            )
            base_ns = start_ns + int(off)
        else:
            self.state, delivered, next_rel = self._step(
                self.state, self.params, self._rng_root,
                jnp.int32(shift), jnp.int32(end_ns - start_ns),
            )
            base_ns = start_ns
        self._prev_start = base_ns
        import jax

        mask, src, seq, d_t, overflow = jax.device_get((
            delivered["mask"], delivered["src"], delivered["seq"],
            delivered["deliver_rel"], self.state.n_overflow_dropped,
        ))
        total_overflow = int(overflow.sum())
        if total_overflow > self._overflow_seen:
            log.error(
                "device transport dropped %d packets to ingress-capacity "
                "overflow — raise experimental.tpu_ingress_cap",
                total_overflow - self._overflow_seen,
            )
            self._overflow_seen = total_overflow
            # surface device-side drops in the per-host tracker counters
            # (the packet objects never reach a CPU interface, so no
            # status-trace hook fires for them)
            deltas = overflow.astype(np.int64) - self._overflow_prev
            for i in np.nonzero(deltas > 0)[0]:
                for tracker in getattr(self.hosts[i], "trackers", []):
                    tracker.counters.packets_dropped += int(deltas[i])
            self._overflow_prev += np.maximum(deltas, 0)

        # deliveries are relative to the LAST window's start (base_ns =
        # start_ns when no chaining happened)
        rows, cols = np.nonzero(mask)
        if rows.size:
            srcs = src[rows, cols].tolist()
            seqs = seq[rows, cols].tolist()
            times = d_t[rows, cols].tolist()
            pop = self._packets.pop
            hosts = self.hosts
            for i, s, q, t in zip(rows.tolist(), srcs, seqs, times):
                packet = pop((s, q), None)
                if packet is None:
                    continue  # overflow-dropped at ingest (already counted)
                hosts[i].push_packet_event(packet, base_ns + t, s + 1, q)

        self.next_pending_abs = (
            base_ns + int(next_rel) if int(next_rel) < I32_MAX else None
        )
