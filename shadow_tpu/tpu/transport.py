"""Device-resident inter-host transport for live simulations.

This wires a LEAN device kernel set into the Manager's round loop,
replacing the per-packet cross-host push (`src/main/core/worker.rs:629-639`)
with batched device windows:

- during a round, `Worker.send_packet` CAPTURES each surviving outbound
  packet (source-host RNG loss draw, routing counters, and statuses all
  happen on the CPU exactly as in CPU-transport mode, so the two modes
  consume identical RNG streams and produce identical drop decisions);
- capture batches are ingested with per-packet send times and round-end
  clamps; the INGEST kernel computes each packet's deliver time
  (max(send + latency, round_end) — `worker.rs:396-399`, bit-identical
  to the CPU arithmetic) and scatters it into per-destination in-flight
  slots;
- each window's STEP kernel releases everything due in [start, end)
  under the same (time, src_host_id, src_event_id) keys the CPU path
  uses — so event order is bitwise-identical between transport modes.

Unlike the full network plane (`shadow_tpu.tpu.plane`, which models
qdiscs, token buckets, loss draws, and CoDel for pure-device simulation
— the PHOLD bench and the flow engine), the transport bridge needs NONE
of that on device: the CPU NIC already applied qdisc order, the relays
already rate-limited, and loss was drawn at capture. The round-3
transport routed through the full plane anyway and its ~6 large
per-window sorts capped the device at ~25-40 ms per window at 1k hosts
— slower than the CPU object plane it was meant to beat. The lean
kernels here keep in-flight slots SPARSE (no per-window compaction:
release is a mask clear, placement reuses freed slots), so a window step
is elementwise work plus one small sort over the ingest batch.

Packets are identified across the device by a POOL TAG (their slot in a
free-listed host-side pool) — no per-packet dict keyed by (src, seq).

Two execution modes (`experimental.tpu_transport_mode`):

- **sync** — the device is authoritative: each window blocks on the
  compacted released set before hosts execute, and delivery-free windows
  chain on device in one `lax.while_loop`. Right when the accelerator
  is locally attached (D2H pull = microseconds).
- **mirrored** — for links where per-window device interaction costs
  milliseconds (a tunneled / disaggregated TPU: ~100 ms per fresh D2H
  pull and ~50 ms effective per-dispatch turnaround measured on the
  round-4 dev machine). The CPU pushes each delivery at capture time
  with the exact same deliver-time arithmetic (bitwise-identical to CPU
  transport BY CONSTRUCTION), while the device re-executes the identical
  window sequence retrospectively in BATCHES of K windows per dispatch:
  one `lax.scan` whose body is [window step -> released-set fingerprint
  -> ingest that round's captures]. Each window's released set is
  reduced ON DEVICE to (count, order-independent u32 fingerprint of
  (tag, deliver) pairs) and compared against the CPU ledger's
  fingerprint, computed host-side in numpy with identical u32
  arithmetic and uploaded as two scalars per window. A device-resident
  divergence counter accumulates; it is pulled once at `finalize()`.
  Nothing in the round loop ever blocks on the device. Earlier round-4
  designs that dispatched (or worse, pulled) per window made rung 3
  3-10x SLOWER than CPU mode on this link; batching + fingerprinting is
  what makes the verified mirror cheap.
- **auto** — probe the D2H round trip at init and pick.
"""

from __future__ import annotations

import heapq
import logging
import time as _walltime
from typing import NamedTuple, Optional

import numpy as np

from ..core.capacity import CapacityError, CapacityTrajectory, next_pow2

log = logging.getLogger("shadow_tpu.tpu")

I32_MAX = 2**31 - 1

# capture row columns: src, dst, seq, tag, send_abs, clamp_abs
_NCOL = 6

_MIX_A = np.uint32(2654435761)  # Knuth multiplicative
_MIX_B = np.uint32(2246822519)  # xxhash prime
_MIX_C = np.uint32(3266489917)  # xxhash prime 3
_MIX_D = np.uint32(668265263)  # xxhash prime 4


def _fingerprint_np(tags: np.ndarray, deliver_rel: np.ndarray):
    """Order-independent fingerprint PAIR of a released set — numpy twin
    of the device reduction (identical wrap-around arithmetic). Two
    independent u32 mixes give a 64-bit-equivalent check without int64
    (TPUs run x32); this is a correctness GATE, not a tripwire, since
    round 5 (divergence fails the run)."""
    t = tags.astype(np.uint32)
    d = deliver_rel.astype(np.uint32)
    h1 = ((t * _MIX_A) ^ d) * _MIX_B
    h2 = ((t * _MIX_C) ^ (d * _MIX_D)) + (h1 >> 16)
    return int(h1.sum(dtype=np.uint32)), int(h2.sum(dtype=np.uint32))


def _probe_d2h_ms(jax, jnp) -> float:
    """Median wall cost of a small fresh-buffer device_get (the per-window
    blocking pull sync mode would pay). Run AFTER the first compile so the
    probe measures transport, not compilation."""
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((64,), jnp.int32)
    jax.device_get(f(x))  # compile + first transfer
    costs = []
    for _ in range(3):
        # The D2H link probe picks sync vs mirrored mode; both modes
        # are bitwise-identical by construction, so this wall read
        # can only change performance, never results.
        t0 = _walltime.monotonic()  # shadowlint: disable=SL101 -- link probe, see above
        jax.device_get(f(x))
        costs.append(_walltime.monotonic() - t0)  # shadowlint: disable=SL101 -- link probe, see above
    return sorted(costs)[1] * 1e3


class TransportState(NamedTuple):
    """Sparse per-destination in-flight slots, axis 0 = destination host.
    Slots are NOT compacted: release clears valid bits, ingest fills the
    lowest free columns (stable argsort on the valid mask)."""

    in_src: "jax.Array"  # int32 [N, CI]
    in_seq: "jax.Array"  # int32 [N, CI]
    in_tag: "jax.Array"  # int32 [N, CI] host-side pool slot
    in_deliver: "jax.Array"  # int32 [N, CI] rel to current device base
    in_valid: "jax.Array"  # bool [N, CI]
    n_overflow: "jax.Array"  # int32 [N]
    # telemetry counters (pure adds inside the kernels; harvested
    # asynchronously, never read on the hot path — see telemetry/)
    n_out: "jax.Array"  # int32 [N] packets ingested per SOURCE host
    n_released: "jax.Array"  # int32 [N] packets released per DEST host


class TransportGuard(NamedTuple):
    """Scalar device-side invariant accumulator for the transport
    kernels (guard plane, docs/robustness.md). Threaded as a static
    presence switch by `_build_kernels(guards=True)`: each window step
    re-checks the transport conservation law — everything ingested is
    released, overflow-dropped, or still occupying a slot — plus the
    idle-slot sentinel structure and clock monotonicity, with pure jnp
    compares. Tiny (3 scalars), pulled only at teardown."""

    violations: "jax.Array"  # scalar int32 bitmask (guards.plane bits)
    first_window: "jax.Array"  # scalar int32 guarded-dispatch index of
    # the first violation (I32_MAX = clean)
    windows: "jax.Array"  # scalar int32 — guarded dispatches checked


def make_transport_guard():
    import jax.numpy as jnp

    return TransportGuard(
        violations=jnp.zeros((), jnp.int32),
        first_window=jnp.full((), I32_MAX, jnp.int32),
        windows=jnp.zeros((), jnp.int32),
    )


class TransportHist(NamedTuple):
    """Per-destination log2 histograms for the transport kernels
    (docs/observability.md "Distributions and the flight recorder").
    Threaded as a static presence switch like `TransportGuard`
    (`enable_histograms()`; disabled compiles the section out), pure
    jnp adds over values the kernels already materialized, harvested
    through `histogram_arrays()` + the async TelemetryHarvester drain
    and delta-unwrapped like every modular counter."""

    #: [N, B] int32 — delivery latency (deliver - send, including the
    #: round-barrier clamp) per packet, attributed to the destination
    hist_delivery_ns: "jax.Array"
    #: [N, B] int32 — in-flight ring occupancy sampled once per window
    #: step, per destination
    hist_qdepth: "jax.Array"


def make_transport_hist(n_hosts: int) -> TransportHist:
    import jax.numpy as jnp

    from ..telemetry.histo import HIST_BUCKETS

    z = lambda: jnp.zeros((n_hosts, HIST_BUCKETS), jnp.int32)
    return TransportHist(hist_delivery_ns=z(), hist_qdepth=z())


class DeviceTransport:
    def __init__(self, hosts, routing, ip_to_node_id, *,
                 egress_cap: int = 256, ingress_cap: int = 256,
                 mode: str = "auto", compact_cap: int = 4096,
                 capacity_mode: str = "fixed", max_doublings: int = 3,
                 capacity_strict: bool | None = None):
        import jax
        import jax.numpy as jnp

        from . import enable_compilation_cache

        enable_compilation_cache()
        self._jax = jax
        self._jnp = jnp
        # host index = host_id - 1 (Manager assigns ids densely from 1)
        self.hosts = sorted(hosts, key=lambda h: h.host_id)
        n = len(self.hosts)
        assert [h.host_id for h in self.hosts] == list(range(1, n + 1))

        # node-level latency table ([M, M], M = graph nodes actually used)
        # + a host->node map; no O(N^2) host pair materialization
        node_lat = np.asarray(routing.latency_ns)
        if node_lat.size and node_lat.max() >= I32_MAX:
            raise ValueError("path latency exceeds the int32 device budget")
        host_node = np.asarray(
            [routing.node_index(h.node_id) for h in self.hosts], np.int32)
        # the UNDEGRADED table is kept host-side: the fault plane's
        # link_degrade events rebuild the device table from it
        # (`apply_fault_latency`)
        self._base_latency_np = node_lat.astype(np.int64)
        self._latency = jnp.asarray(node_lat.astype(np.int32))
        self._host_node = jnp.asarray(host_node)
        # transient-device-error retry policy (faults/healing.py); the
        # Manager sets attempts > 0 from `faults.device_retries`
        self.retry_attempts = 0
        self.retry_backoff_s = 0.05
        self.retry_cap_s = 2.0
        self.retry_jitter = 0.5
        self.retry_seed = 0

        CI = ingress_cap
        z = lambda shape: jnp.zeros(shape, jnp.int32)
        self.state = TransportState(
            in_src=z((n, CI)), in_seq=z((n, CI)), in_tag=z((n, CI)),
            in_deliver=jnp.full((n, CI), I32_MAX, jnp.int32),
            in_valid=jnp.zeros((n, CI), bool),
            n_overflow=z((n,)),
            n_out=z((n,)), n_released=z((n,)),
        )
        self._ingress_cap = CI
        self._compact_cap = compact_cap
        self._n = n
        # capacity policy (core/capacity.py, docs/robustness.md "Elastic
        # capacity"): the per-destination in-flight slots are this
        # plane's one ring dimension.
        # - elastic: a host-side occupancy mirror (exact while nothing
        #   drops — captures and releases are both visible here) grows
        #   the rings BEFORE an overflowing ingest, so no packet is
        #   ever dropped and no re-execution is needed: transport
        #   ingest fills the lowest free columns, so a pad-only grow is
        #   bitwise-identical to a pre-provisioned run by construction.
        # - strict: any ingress-capacity drop raises CapacityError with
        #   per-host blame (CLI exit 6) instead of the old log line.
        self._capacity_mode = capacity_mode
        self._capacity_strict = (capacity_strict if capacity_strict
                                 is not None
                                 else capacity_mode == "strict")
        self._max_doublings = max_doublings
        self._ingress_cap0 = CI
        self._exhausted_noted = False
        self.capacity = CapacityTrajectory(capacity_mode)
        self._cap_drained = 0  # drain_capacity_events cursor
        self._occ = np.zeros(n, np.int64)  # per-dest device occupancy
        # guard plane (docs/robustness.md): enable_guards() threads a
        # TransportGuard scalar pytree through every kernel dispatch
        # (static presence switch — disabled compiles the checks out)
        self._guards_enabled = False
        self._guard = None
        # histogram plane (docs/observability.md "Distributions and the
        # flight recorder"): enable_histograms() threads a TransportHist
        # pytree through every kernel dispatch (static presence switch)
        self._hist = None
        # CPU-side ledgers for cross-plane reconciliation
        # (guards/reconcile.py): the same capture/release events the
        # device kernels count, mirrored independently in numpy. The
        # capture-side increment runs on ANY worker thread (like the
        # shared packet counters), so it takes this lock; the release
        # side only moves at round barriers (single-threaded).
        import threading

        self._led_lock = threading.Lock()
        self._led_captured = np.zeros(n, np.int64)
        self._led_released = np.zeros(n, np.int64)
        # optional device-TCP retransmit source for the telemetry
        # harvest (attach_tcp_source; docs/observability.md)
        self._tcp_source = None
        self._build_kernels(n, CI, compact_cap)

        if mode == "auto":
            d2h_ms = _probe_d2h_ms(jax, jnp)
            mode = "sync" if d2h_ms < 2.0 else "mirrored"
            log.info("tpu transport auto mode: D2H probe %.2f ms -> %s",
                     d2h_ms, mode)
        if mode not in ("sync", "mirrored"):
            raise ValueError(f"unknown tpu_transport_mode {mode!r}")
        self.mode = mode
        self.mirrored = mode == "mirrored"

        # capture buffers (protected by the manager's round structure: all
        # appends happen during run_round, all reads at the barrier)
        self._pending: list[tuple] = []
        # slot-indexed pool: sync mode holds the Packet object; mirrored
        # holds a placeholder. Tags are freed only after the device has
        # released them (sync) or their window was dispatched (mirrored —
        # device execution is sequential, so a reused tag in a later
        # ingest can never collide on device).
        self._pool: list = []
        self._free: list[int] = []
        self._prev_start: Optional[int] = None
        self.next_pending_abs: Optional[int] = None
        self._overflow_seen = 0
        self._overflow_prev = np.zeros(n, np.int64)
        self._batch_pad = 64

        # mirrored-mode verification state: the CPU ledger heap, the
        # host-side per-round record batch, and a DEVICE-resident
        # divergence counter (pulled only at finalize)
        # (deliver_abs, tag, dst_idx) — tag is unique per live entry,
        # so dst never enters the heap comparison
        self._expect_heap: list[tuple[int, int, int]] = []
        self._div = jnp.int32(0)
        self._k = 32  # windows per batched dispatch
        self._records: list[tuple] = []  # (start, end, expected, ingest)
        self._open_record: Optional[tuple] = None
        self._dev_base: Optional[int] = None  # device window-start, abs ns
        self.divergence_count = 0
        self.verified_windows = 0
        self.verified_packets = 0
        self._finalized = False

    # -- kernels ---------------------------------------------------------

    def _build_kernels(self, N: int, CI: int, cap: int) -> None:
        import jax
        import jax.numpy as jnp

        latency = self._latency
        host_node = self._host_node

        def guard_update(g, st: TransportState, shift, window):
            """Guard plane (static presence: g=None compiles this out).
            Re-checks the transport conservation law — sum(ingested) ==
            sum(released) + sum(overflow-dropped) + slots occupied — the
            idle-slot deliver sentinel, and clock monotonicity; pure jnp
            compares, accumulated as a scalar bitmask (nothing raises
            inside jit; drivers pull the 3 scalars at teardown)."""
            if g is None:
                return None
            from ..guards import plane as gp

            occupancy = st.in_valid.sum(dtype=jnp.int32)
            conserved = (st.n_out.sum() - st.n_released.sum()
                         - st.n_overflow.sum()) == occupancy
            # a LIVE slot carrying the idle sentinel would never
            # release: a silent livelock. (Released slots legitimately
            # keep their stale deliver value — slots are sparse, not
            # compacted — so the inverse check would misfire.)
            struct_ok = (st.in_valid
                         & (st.in_deliver == I32_MAX)).sum() == 0
            clock_ok = (jnp.int32(shift) >= 0) & (jnp.int32(window) >= 0)
            bad = (jnp.where(conserved, 0, gp.GUARD_INGRESS_FLOW)
                   | jnp.where(struct_ok, 0, gp.GUARD_RING_STRUCT)
                   | jnp.where(clock_ok, 0, gp.GUARD_CLOCK)
                   ).astype(jnp.int32)
            hit = (g.violations == 0) & (bad != 0)
            return TransportGuard(
                violations=g.violations | bad,
                first_window=jnp.where(hit, g.windows, g.first_window),
                windows=g.windows + 1,
            )

        def hist_step(h, st: TransportState):
            """Histogram plane (static presence: h=None compiles this
            out): one in-flight-occupancy sample per destination per
            window step. Pure read."""
            if h is None:
                return None
            from ..telemetry import histo

            return h._replace(hist_qdepth=histo.accum_depth(
                h.hist_qdepth, st.in_valid.sum(axis=1, dtype=jnp.int32)))

        def ingest(st: TransportState, h, src, dst, seq, tag, send_rel,
                   clamp_rel, valid):
            """Place a capture batch ([B] columns, times relative to the
            device base) into per-destination free slots; deliver time
            computed here, bit-identical to the CPU (`worker.rs:396-399`):
            max(send + latency, send-round end). `h` (static presence)
            accumulates each placed packet's delivery latency
            (deliver - send) into the destination's log2 histogram;
            returns (st', h')."""
            B = src.shape[0]
            sc = jnp.clip(src, 0, N - 1)
            dc = jnp.clip(dst, 0, N - 1)
            lat = latency[host_node[sc], host_node[dc]]
            deliver = jnp.maximum(send_rel + lat, clamp_rel)
            if h is not None:
                from ..telemetry import histo

                h = h._replace(hist_delivery_ns=histo.accum_scatter(
                    h.hist_delivery_ns, dc,
                    histo.bucket_index(deliver - send_rel),
                    valid & (dst >= 0) & (dst < N)))
            # group by destination (stable: batch order preserved within)
            dkey = jnp.where(valid, dst, N)
            # shadowlint: disable=SL403 -- compact-cap capture batch, not the N*CE flat hot path; bucketed-diet follow-up tracked in docs/performance.md
            o_dst, o_src, o_seq, o_tag, o_del, o_valid = jax.lax.sort(
                (dkey, src, seq, tag, deliver, valid), dimension=0,
                is_stable=True, num_keys=1)
            idx = jnp.arange(B, dtype=jnp.int32)
            new_group = jnp.concatenate(
                [jnp.ones((1,), bool), o_dst[1:] != o_dst[:-1]])
            seg_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(new_group, idx, 0))
            rank = idx - seg_start  # k-th packet for this destination
            # the k-th free column of each row (stable: lowest first)
            free_cols = jnp.argsort(st.in_valid, axis=1, stable=True)
            n_free = (~st.in_valid).sum(axis=1).astype(jnp.int32)
            dsel = jnp.clip(o_dst, 0, N - 1)
            ok = o_valid & (o_dst < N) & (rank < n_free[dsel])
            col = free_cols[dsel, jnp.minimum(rank, CI - 1)]
            flat = jnp.where(ok, dsel * CI + col, N * CI)
            put = lambda buf, vals: buf.reshape(-1).at[flat].set(
                vals, mode="drop").reshape(N, CI)
            incoming = jnp.zeros((N,), jnp.int32).at[dsel].add(
                o_valid & (o_dst < N), mode="drop")
            placed = jnp.zeros((N,), jnp.int32).at[dsel].add(
                ok, mode="drop")
            return st._replace(
                in_src=put(st.in_src, o_src),
                in_seq=put(st.in_seq, o_seq),
                in_tag=put(st.in_tag, o_tag),
                in_deliver=put(st.in_deliver, o_del),
                in_valid=put(st.in_valid, jnp.ones_like(ok)),
                n_overflow=st.n_overflow + (incoming - placed),
                # telemetry: captured packets per SOURCE host (out-of-range
                # src on pad slots falls off via mode="drop")
                n_out=st.n_out.at[o_src].add(
                    o_valid & (o_dst < N), mode="drop"),
            ), h

        def step(st: TransportState, shift, window):
            """One window [0, window) after rebasing by shift: release =
            clear the due mask; returns the due mask view + next event."""
            deliver = jnp.where(st.in_valid, st.in_deliver - shift, I32_MAX)
            due = st.in_valid & (deliver < window)
            new_valid = st.in_valid & ~due
            keep = jnp.where(new_valid, deliver, I32_MAX)
            next_rel = keep.min()
            st = st._replace(in_deliver=jnp.where(st.in_valid, deliver,
                                                  I32_MAX),
                             in_valid=new_valid,
                             n_released=st.n_released
                             + due.sum(axis=1, dtype=jnp.int32))
            return st, due, deliver, next_rel

        def fingerprint(st: TransportState, due, deliver):
            t = st.in_tag.astype(jnp.uint32)
            d = deliver.astype(jnp.uint32)
            h1 = ((t * _MIX_A) ^ d) * _MIX_B
            h2 = ((t * _MIX_C) ^ (d * _MIX_D)) + (h1 >> 16)
            fp1 = jnp.where(due, h1, jnp.uint32(0)).sum(dtype=jnp.uint32)
            fp2 = jnp.where(due, h2, jnp.uint32(0)).sum(dtype=jnp.uint32)
            return fp1, fp2, due.sum(dtype=jnp.int32)

        def step_compact(st, g, h, shift, window):
            """Sync mode: one window + the released set front-packed into
            [cap] columns for one small D2H transfer (count first; the
            caller raises if count exceeds the compact cap — deliveries
            cannot be dropped, unlike a diagnostic pull)."""
            st, due, deliver, next_rel = step(st, shift, window)
            g = guard_update(g, st, shift, window)
            h = hist_step(h, st)
            flat = due.reshape(-1)
            idx = jnp.argsort(~flat, stable=True)[:cap]
            take = lambda a: a.reshape(-1)[idx]
            dst = jnp.where(take(due), (idx // CI).astype(jnp.int32), -1)
            comp = (due.sum(dtype=jnp.int32), dst, take(st.in_src),
                    take(st.in_seq), take(st.in_tag), take(deliver))
            return st, g, h, comp, next_rel, st.n_overflow.sum()

        def chain(st, g, h, shift0, window0, runahead, horizon, stop):
            """Sync mode: advance through delivery-free windows on device —
            the boundary rule of `plane.chain_windows` (itself the
            controller's `controller.rs:87-113` chain): the first window
            runs unconditionally; afterwards, while a window delivered
            nothing and the device's next event stays below both the
            horizon (earliest CPU-side event) and the stop, the next
            window opens at that next event with width
            min(runahead, stop - start)."""
            st, due, deliver, next_rel = step(st, shift0, window0)
            g = guard_update(g, st, shift0, window0)
            h = hist_step(h, st)
            hs = jnp.minimum(horizon, stop)

            def cond(c):
                st, g, h, due, deliver, off, next_rel, n = c
                return (~due.any()) & (next_rel < hs - off) \
                    & (n < jnp.int32(64))

            def body(c):
                st, g, h, due, deliver, off, next_rel, n = c
                off2 = off + next_rel
                width = jnp.minimum(runahead, stop - off2)
                st, due, deliver, next2 = step(st, next_rel, width)
                g = guard_update(g, st, next_rel, width)
                h = hist_step(h, st)
                return (st, g, h, due, deliver, off2, next2, n + 1)

            st, g, h, due, deliver, off, next_rel, _n = \
                jax.lax.while_loop(
                    cond, body,
                    (st, g, h, due, deliver, jnp.int32(0), next_rel,
                     jnp.int32(1)))
            flat = due.reshape(-1)
            idx = jnp.argsort(~flat, stable=True)[:cap]
            take = lambda a: a.reshape(-1)[idx]
            dst = jnp.where(take(due), (idx // CI).astype(jnp.int32), -1)
            comp = (due.sum(dtype=jnp.int32), dst, take(st.in_src),
                    take(st.in_seq), take(st.in_tag), take(deliver))
            return st, g, h, comp, off, next_rel, st.n_overflow.sum()

        def batch_verify(st, g, h, shifts, widths, ing, exp_fp, exp_fp2,
                         exp_n, div):
            """Mirrored mode: K windows per dispatch. Scan body = window
            step -> released-set fingerprint vs the CPU ledger -> ingest
            that round's captures (the exact per-round device sequence of
            sync mode)."""

            def body(carry, xs):
                st, g, h, div = carry
                shift, width, row, efp, efp2, en = xs
                st, due, deliver, _next = step(st, shift, width)
                fp1, fp2, cnt = fingerprint(st, due, deliver)
                ok = (fp1 == efp) & (fp2 == efp2) & (cnt == en)
                h = hist_step(h, st)
                st, h = ingest(st, h, row["src"], row["dst"],
                               row["seq"], row["tag"], row["send"],
                               row["clamp"], row["valid"])
                g = guard_update(g, st, shift, width)
                return (st, g, h, jnp.where(ok, div, div + 1)), None

            (st, g, h, div), _ = jax.lax.scan(
                body, (st, g, h, div),
                (shifts, widths, ing, exp_fp, exp_fp2, exp_n))
            return st, g, h, div

        def ingest_guarded(st, g, h, src, dst, seq, tag, send_rel,
                           clamp_rel, valid):
            """The standalone ingest dispatch, with the guard check run
            over the post-ingest state (the conservation identity holds
            at every kernel boundary, so an ingest that loses or
            double-places a packet trips here, one dispatch early)."""
            st, h = ingest(st, h, src, dst, seq, tag, send_rel,
                           clamp_rel, valid)
            # ingest rides between windows: a neutral (0, 0) clock
            return st, guard_update(g, st, 0, 0), h

        # every dispatch donates the TransportState pytree: XLA writes the
        # next window's slot arrays into the incoming buffers instead of
        # re-materializing the [N, CI] set per window. Safe because
        # self.state is rebound from each kernel's return before any
        # further use (the donation contract, docs/performance.md); on the
        # CPU test backend donating_jit compiles without donation.
        from . import donating_jit

        self._k_ingest = self._retrying(donating_jit(ingest_guarded),
                                        "ingest")
        self._k_step = self._retrying(donating_jit(step_compact), "step")
        self._k_chain = self._retrying(donating_jit(chain), "chain")
        self._k_batch_verify = self._retrying(
            donating_jit(batch_verify), "batch_verify")

    def _retrying(self, kernel, what: str):
        """Wrap a kernel dispatch in the transient-error retry loop
        (`faults/healing.retry_transient`) when the Manager configured
        retries. NOTE donation: the wrapped kernels donate the state
        pytree, but a dispatch that raises before enqueue leaves the
        input buffers valid — XLA only invalidates donated buffers it
        actually consumed, and a dispatch that died mid-execution is
        not retryable state anyway (the classifier treats data-plane
        poison like INTERNAL as non-transient)."""

        def call(*args, **kwargs):
            if not self.retry_attempts:
                return kernel(*args, **kwargs)
            from ..faults.healing import retry_transient

            return retry_transient(
                kernel, *args, attempts=self.retry_attempts,
                backoff_s=self.retry_backoff_s,
                cap_s=self.retry_cap_s, jitter=self.retry_jitter,
                seed=self.retry_seed,
                what=f"device transport {what}", **kwargs)

        return call

    # -- guard plane (docs/robustness.md) --------------------------------

    def enable_guards(self) -> None:
        """Thread a `TransportGuard` scalar pytree through every kernel
        dispatch from now on. Static presence switch: with guards never
        enabled the checks never compile (the kernels trace with a None
        pytree); enabling costs three scalar compares per dispatch."""
        if self._guard is None:
            self._guard = make_transport_guard()

    def guard_report(self) -> Optional[dict]:
        """Pull and decode the device guard accumulator (one tiny
        blocking transfer — call at teardown / harvest boundaries the
        caller already owns, never on the hot path). None when guards
        were never enabled."""
        if self._guard is None:
            return None
        from ..guards import plane as gp

        g = self._jax.device_get(self._guard)
        bits = int(g.violations)
        return {
            "clean": bits == 0,
            "classes": gp.decode_bits(bits),
            "first_window": int(g.first_window),
            "windows": int(g.windows),
        }

    def enable_histograms(self) -> None:
        """Thread a `TransportHist` pytree through every kernel
        dispatch from now on (static presence switch like
        `enable_guards`): per-destination delivery-latency and
        in-flight-depth log2 histograms, pure jnp adds, pulled only by
        the asynchronous harvester via `histogram_arrays()`."""
        if self._hist is None:
            self._hist = make_transport_hist(self._n)

    def histogram_arrays(self) -> dict:
        """Per-host [N, B] histogram counters for the
        TelemetryHarvester (empty when histograms were never enabled).
        Same freshness contract as `telemetry_arrays`: the `+ 0`
        copies are undonated buffers safe for the async D2H drain."""
        if self._hist is None:
            return {}
        return {name: getattr(self._hist, name) + 0
                for name in TransportHist._fields}

    def cpu_ledger(self) -> dict[str, np.ndarray]:
        """The CPU-plane reconciliation ledger: per-host capture /
        release counts maintained independently of (and compared
        against) the device kernels' n_out / n_released
        (guards/reconcile.py). Returns copies."""
        return {
            "captured": self._led_captured.copy(),
            "released": self._led_released.copy(),
        }

    def device_in_flight(self) -> int:
        """Slots currently occupied on device (one blocking scalar
        pull; teardown reconciliation only)."""
        return int(self._jax.device_get(
            self.state.in_valid.sum(dtype=self._jnp.int32)))

    def apply_fault_latency(self, lat_mult: np.ndarray) -> None:
        """Mirror a link_degrade/link_restore event onto the device:
        rebuild the latency table as base * mult (node-index space) and
        recompile the kernels against it, so on-device deliver times
        keep matching the CPU arithmetic bit for bit. Rare (once per
        link event); mirrored mode flushes its record batch FIRST so no
        dispatched window ever mixes tables."""
        import jax.numpy as jnp

        if self.mirrored and self._records:
            self._flush_mirrored()
        degraded = self._base_latency_np * np.asarray(lat_mult, np.int64)
        # shadowlint: disable=SL105 -- host-side numpy overflow guard, not a traced value
        if degraded.size and degraded.max() >= I32_MAX:
            raise ValueError(
                "fault-degraded path latency exceeds the int32 device "
                "budget; lower the latency_mult")
        self._latency = jnp.asarray(degraded.astype(np.int32))
        self._build_kernels(self._n, self._ingress_cap, self._compact_cap)

    # -- capacity policy (docs/robustness.md "Elastic capacity") ---------

    def drain_capacity_events(self) -> list[dict]:
        """Capacity-trajectory events recorded since the last drain —
        the Manager feeds these into telemetry heartbeats (and trace
        instants) at harvest boundaries."""
        events = self.capacity.events[self._cap_drained:]
        self._cap_drained = len(self.capacity.events)
        return list(events)

    def capacity_summary(self) -> dict:
        """The run's capacity record for sim-stats / snapshots."""
        out = self.capacity.as_dict()
        out["ingress_cap"] = self._ingress_cap
        out["ingress_cap_initial"] = self._ingress_cap0
        return out

    def _maybe_grow_for(self, batch, time_ns: int) -> None:
        """Elastic mode, called BEFORE an ingest dispatch: if this
        capture batch would overflow any destination's in-flight ring,
        grow the rings first (next power of two covering the need,
        bounded by max_doublings) so nothing is ever dropped. The
        occupancy mirror then absorbs the batch."""
        if self._capacity_mode != "elastic" or not batch:
            return
        counts = np.bincount(
            np.asarray([row[1] for row in batch], np.int64),
            minlength=self._n)
        need_per = self._occ + counts
        need = int(need_per.max())
        if need > self._ingress_cap:
            cap_max = self._ingress_cap0 << self._max_doublings
            new_ci = min(next_pow2(need), cap_max)
            if new_ci > self._ingress_cap:
                self._grow_ingress(
                    new_ci, time_ns=time_ns,
                    overflow=int(np.maximum(
                        need_per - self._ingress_cap, 0).sum()))
            if need > new_ci and not self._exhausted_noted:
                # growth budget exhausted: the overflow drops become
                # real (counted by _note_overflow / the device ring).
                # Once per run, like RingPolicy.note_drop — the
                # per-drop totals live in the metrics plane.
                self._exhausted_noted = True
                self.capacity.record_drop(
                    time_ns=time_ns, ring="transport-ingress",
                    cap=new_ci,
                    overflow=int(np.maximum(need_per - new_ci, 0).sum()),
                    plane="transport", exhausted=True)
        # post-ingest device occupancy per dest is min(occ + counts, CI)
        # — the ingest kernel drops the excess — so the mirror clamps
        # too; without the clamp, exhausted-budget drops (which never
        # release) would inflate the mirror forever
        self._occ = np.minimum(self._occ + counts, self._ingress_cap)

    def _note_released(self, dst_idx: np.ndarray) -> None:
        """Occupancy-mirror decrement for device-released packets (by
        destination index). Elastic mode only — the mirror is unused
        otherwise."""
        if self._capacity_mode == "elastic" and len(dst_idx):
            self._occ -= np.bincount(np.asarray(dst_idx, np.int64),
                                     minlength=self._n)

    def _grow_ingress(self, new_ci: int, *, time_ns: int,
                      overflow: int) -> None:
        """Repack the in-flight rings into `new_ci` columns and
        recompile the kernels against the new shape. Mirrored mode
        flushes its record batch FIRST (like apply_fault_latency) so no
        dispatched window ever mixes ring shapes; recompiles are
        bounded at log2 by the power-of-two growth."""
        from . import elastic

        if self.mirrored and self._records:
            self._flush_mirrored()
        self.capacity.record_growth(
            time_ns=time_ns, ring="transport-ingress",
            from_cap=self._ingress_cap, to_cap=new_ci, overflow=overflow,
            plane="transport")
        self.state = elastic.grow_transport_state(self.state, new_ci)
        self._ingress_cap = new_ci
        self._build_kernels(self._n, new_ci, self._compact_cap)

    # -- capture (called from Worker.send_packet, any worker thread) -----

    def capture(self, src_host, dst_host, packet, now_ns: int, seq: int,
                round_end_ns: int, deliver_ns: int) -> None:
        src_idx = src_host.host_id - 1
        dst_idx = dst_host.host_id - 1
        # cross-plane reconciliation ledger (guards/reconcile.py): the
        # CPU side counts the same event the device ingest kernel will
        # count into n_out — independently, in plain numpy. Locked: a
        # numpy element read-modify-write is not atomic, and this runs
        # on any worker thread — a lost count would make the guard
        # plane flag a healthy run.
        with self._led_lock:
            self._led_captured[src_idx] += 1
        if self._free:
            tag = self._free.pop()
        else:
            tag = len(self._pool)
            self._pool.append(None)
        if self.mirrored:
            self._pool[tag] = True  # ledger entry lives in the heap
            heapq.heappush(self._expect_heap, (deliver_ns, tag, dst_idx))
        else:
            self._pool[tag] = packet
        self._pending.append(
            (src_idx, dst_idx, seq, tag, now_ns, round_end_ns))

    @property
    def in_flight(self) -> int:
        return len(self._pool) - len(self._free)

    # -- round barrier: ingest this round's captures ---------------------

    def finish_round(self, start_ns: int, end_ns: int) -> None:
        if self.mirrored:
            # elastic capacity: grow BEFORE this round's captures are
            # recorded, so the batched replay never overflows a ring
            # (the flush inside _grow_ingress dispatches only the
            # already-recorded windows, which predate this batch)
            self._maybe_grow_for(self._pending, start_ns)
            rec, self._open_record = self._open_record, None
            if rec is not None:
                self._records.append((*rec, self._pending))
                self._pending = []
            elif self._pending:
                # captures during a round whose release was skipped (the
                # device was empty): a width-0 record carries the ingest
                # so these packets are on device before their delivery
                # window's step runs
                self._records.append((start_ns, start_ns, [],
                                      self._pending))
                self._pending = []
            if len(self._records) >= self._k:
                self._flush_mirrored()
            return
        if not self._pending:
            return
        jnp = self._jnp
        batch = self._pending
        self._pending = []
        # elastic capacity: grow the in-flight rings before an ingest
        # that would overflow them — nothing is ever dropped, and the
        # pad-only grow is bitwise-identical to a pre-provisioned run
        self._maybe_grow_for(batch, start_ns)
        b = len(batch)
        pad = self._batch_pad
        while pad < b:
            pad *= 2
        self._batch_pad = pad
        # times go in relative to the DEVICE base (= this round's start,
        # except when a window chain overshot a cross-thread post and the
        # base sits ahead of the round — negative send_rel is fine, the
        # arithmetic is all offsets)
        base_ns = self._prev_start if self._prev_start is not None else start_ns
        arr = np.zeros((_NCOL, pad), np.int64)
        arr[:, :b] = np.asarray(batch, np.int64).T  # vectorized transpose
        arr[0, b:] = self._n  # pad slots: out-of-range src
        arr[4, b:] = base_ns
        arr[5, b:] = base_ns
        self.state, self._guard, self._hist = self._k_ingest(
            self.state, self._guard, self._hist,
            jnp.asarray(arr[0], jnp.int32), jnp.asarray(arr[1], jnp.int32),
            jnp.asarray(arr[2], jnp.int32), jnp.asarray(arr[3], jnp.int32),
            jnp.asarray(arr[4] - base_ns, jnp.int32),
            jnp.asarray(arr[5] - base_ns, jnp.int32),
            jnp.asarray(np.arange(pad) < b),
        )

    # -- round start: release everything due in [start, end) -------------

    def release(self, start_ns: int, end_ns: int,
                horizon_ns: Optional[int] = None,
                runahead_ns: Optional[int] = None,
                stop_ns: Optional[int] = None) -> None:
        """Run the window step and surface due deliveries.

        sync mode: pushes released packets into host event queues before
        anyone executes; with `runahead_ns`/`stop_ns` given (the Manager's
        round loop), the device chains through consecutive delivery-free
        windows in one `lax.while_loop` — window boundaries identical to
        the ones the CPU controller would pick — and only returns to
        Python when a window delivers or the next device event reaches
        `horizon_ns` (the earliest CPU-side event).

        mirrored mode: the deliveries were pushed at capture; this opens
        a per-round record (window boundary + the CPU ledger's expected
        set) that the batched device dispatch replays and verifies
        retrospectively."""
        if self.mirrored:
            self._release_mirrored(start_ns, end_ns)
            return
        if self.in_flight == 0:
            # nothing on device: skip the step; rebasing is irrelevant
            # because every slot is invalid
            self._prev_start = start_ns
            self.next_pending_abs = None
            return
        shift = 0 if self._prev_start is None else start_ns - self._prev_start
        if shift < 0:
            # A previous chain advanced the device base past this window's
            # start (a cross-thread post — e.g. a managed-process death —
            # scheduled an earlier CPU event after the chain ran). The
            # device holds nothing before its base, so only [base, end)
            # needs releasing; a window entirely behind the base has
            # nothing on device at all.
            if end_ns <= self._prev_start:
                return
            start_ns = self._prev_start
            shift = 0
        assert shift < I32_MAX, "window shift exceeds int32 ns budget"
        jnp = self._jnp
        if runahead_ns is not None and stop_ns is not None:
            clamp = I32_MAX // 2
            horizon_rel = min((horizon_ns if horizon_ns is not None
                               else stop_ns) - start_ns, clamp)
            stop_rel = min(stop_ns - start_ns, clamp)
            (self.state, self._guard, self._hist, comp, off, next_rel,
             overflow) = self._k_chain(
                self.state, self._guard, self._hist, jnp.int32(shift),
                jnp.int32(end_ns - start_ns),
                jnp.int32(runahead_ns), jnp.int32(horizon_rel),
                jnp.int32(stop_rel),
            )
            base_ns = start_ns + int(off)
        else:
            self.state, self._guard, self._hist, comp, next_rel, \
                overflow = self._k_step(
                    self.state, self._guard, self._hist,
                    jnp.int32(shift),
                    jnp.int32(end_ns - start_ns),
                )
            base_ns = start_ns
        self._prev_start = base_ns

        # ONE blocking transfer per delivering window: the compacted
        # released set + the next-event scalar + the overflow total
        n, dst, src, seq, tag, d_t, next_rel_v, overflow_v = \
            self._jax.device_get((*comp, next_rel, overflow))
        n = int(n)
        if n > self._compact_cap:
            raise RuntimeError(
                f"released burst ({n}) exceeds tpu_compact_cap "
                f"({self._compact_cap}); raise experimental.tpu_compact_cap")
        dst, src, seq, tag, d_t = (a[:n] for a in (dst, src, seq, tag, d_t))

        self._note_overflow(int(overflow_v))

        # deliveries are relative to the LAST window's start (base_ns =
        # start_ns when no chaining happened)
        if n:
            # the release twin of the capture ledger: one count per
            # device-released packet, by destination host-id
            np.add.at(self._led_released, dst, 1)
            self._note_released(dst)
            hosts = self.hosts
            pool = self._pool
            free = self._free
            for i, s, q, g, t in zip(dst.tolist(), src.tolist(),
                                     seq.tolist(), tag.tolist(),
                                     d_t.tolist()):
                packet = pool[g]
                if packet is None:
                    continue  # overflow-dropped at ingest (already counted)
                pool[g] = None
                free.append(g)
                hosts[i].push_packet_event(packet, base_ns + t, s + 1, q)

        self.next_pending_abs = (
            base_ns + int(next_rel_v) if int(next_rel_v) < I32_MAX else None
        )

    # -- mirrored mode ---------------------------------------------------

    def _pop_expected(self, end_ns: int) -> list[tuple[int, int, int]]:
        """The CPU ledger for this window: every capture due before
        end_ns, as (deliver_abs, tag, dst_idx) triples. Split out so
        tests can intercept and poison it."""
        out = []
        heap = self._expect_heap
        while heap and heap[0][0] < end_ns:
            out.append(heapq.heappop(heap))
        return out

    def _release_mirrored(self, start_ns: int, end_ns: int) -> None:
        self.next_pending_abs = None  # CPU queues already hold everything
        if not self._expect_heap and self._open_record is None:
            # the device holds nothing undelivered (unfreed tags in
            # pending records are packets whose release windows are
            # already recorded). Flush what's recorded against the OLD
            # base, then teleport the base so an idle gap — which is
            # unbounded, e.g. timers seconds apart — never enters the
            # int32 shift arithmetic.
            if self._records:
                self._flush_mirrored()
            self._dev_base = start_ns
            return
        # with pending deliveries the gap is bounded by path latency
        # (< int32 by the init check), but split defensively anyway: a
        # width-0 no-op record per 2^30 ns hop keeps every shift in range
        last = self._records[-1][0] if self._records else self._dev_base
        if last is not None:
            while start_ns - last > (1 << 30):
                last += 1 << 30
                self._records.append((last, last, [], []))
                if len(self._records) >= self._k:
                    self._flush_mirrored()
        expected = self._pop_expected(end_ns)
        # occupancy mirror: these deliveries will release their device
        # slots when this window's record replays (step runs before the
        # ingest in the batched scan body, matching this call order)
        self._note_released([e[2] for e in expected])
        self._open_record = (start_ns, end_ns, expected)

    def _flush_mirrored(self) -> None:
        """Dispatch one batched verify for the accumulated records."""
        records = self._records
        self._records = []
        K = self._k
        assert len(records) <= K
        b_ing = max((len(r[3]) for r in records), default=0)
        # pads grow 4x so the scan recompiles at most a couple of times
        # over any run (each compile costs 10-20 s on a tunneled link;
        # the persistent cache pays it once per shape EVER)
        while self._batch_pad < b_ing:
            self._batch_pad *= 4
        B = self._batch_pad
        jnp = self._jnp

        shifts = np.zeros(K, np.int32)
        widths = np.zeros(K, np.int32)
        exp_fp = np.zeros(K, np.uint32)
        exp_fp2 = np.zeros(K, np.uint32)
        exp_n = np.zeros(K, np.int32)
        ing = np.zeros((_NCOL, K, B), np.int64)
        valid = np.zeros((K, B), bool)
        base = self._dev_base if self._dev_base is not None \
            else records[0][0]
        for i, (start, end, expected, batch) in enumerate(records):
            shift = start - base
            assert 0 <= shift < I32_MAX, "window shift exceeds int32 budget"
            shifts[i] = shift
            widths[i] = end - start
            base = start
            if expected:
                # [(deliver, tag, dst)] — the fingerprint hashes
                # (tag, deliver) exactly as before; dst feeds the
                # reconciliation ledger below
                pairs = np.asarray(expected, np.int64)
                exp_fp[i], exp_fp2[i] = _fingerprint_np(
                    pairs[:, 1], pairs[:, 0] - start)
                exp_n[i] = len(expected)
            if batch:
                ing[:, i, :len(batch)] = np.asarray(batch, np.int64).T
                valid[i, :len(batch)] = True
            # capture times go in relative to this record's window start
            ing[4, i] -= start
            ing[5, i] -= start
        ing[0][~valid] = self._n  # dead slots: out-of-range src
        ing[4][~valid] = 0  # keep dead-slot times inside int32
        ing[5][~valid] = 0

        col = lambda k: jnp.asarray(ing[k], jnp.int32)
        row = {
            "src": col(0), "dst": col(1), "seq": col(2), "tag": col(3),
            "send": col(4), "clamp": col(5), "valid": jnp.asarray(valid),
        }
        self.state, self._guard, self._hist, self._div = \
            self._k_batch_verify(
                self.state, self._guard, self._hist, jnp.asarray(shifts),
                jnp.asarray(widths), row,
                jnp.asarray(exp_fp), jnp.asarray(exp_fp2),
                jnp.asarray(exp_n), self._div,
            )
        self._dev_base = base
        pool, free = self._pool, self._free
        for start, _end, expected, _batch in records:
            # the CPU ledger is authoritative: tags come home when their
            # window is dispatched (device execution is sequential, so a
            # reused tag in a later ingest can never collide on device)
            for _deliver, tag, dst_idx in expected:
                pool[tag] = None
                free.append(tag)
                self._led_released[dst_idx] += 1
            self.verified_packets += len(expected)
        # count only REAL windows (width > 0 or a ledger to check) —
        # width-0 base-shift/tail-padding records are no-ops and would
        # inflate the coverage figure in the divergence failure message
        self.verified_windows += sum(
            1 for start, end, expected, _b in records
            if end > start or expected)

    def finalize(self) -> None:
        """Flush the partial record batch and pull the device-resident
        divergence counter — the only blocking transfer of a mirrored
        run."""
        if self._finalized or not self.mirrored:
            return
        self._finalized = True
        rec, self._open_record = self._open_record, None
        if rec is not None:  # a release whose round never finished
            self._records.append((*rec, self._pending))
            self._pending = []
        while self._records:
            batch = self._records[:self._k]
            rest = self._records[self._k:]
            # pad the tail batch with width-0 no-op records
            while len(batch) < self._k:
                batch.append((batch[-1][0], batch[-1][0], [], []))
            self._records = batch
            self._flush_mirrored()
            self._records = rest
        # packets still in flight past the stop time: their release
        # windows never ran; hand the tags back
        for _deliver, tag, _dst in self._expect_heap:
            self._pool[tag] = None
            self._free.append(tag)
        self._expect_heap.clear()
        self.divergence_count += int(self._jax.device_get(self._div))
        if self.divergence_count:
            log.error(
                "device transport diverged from the CPU ledger in %d "
                "window(s) (of %d verified)",
                self.divergence_count, self.verified_windows)
        self._note_overflow(
            int(self._jax.device_get(self.state.n_overflow.sum())))

    # -- telemetry -------------------------------------------------------

    def attach_tcp_source(self, plane_getter, conn_host) -> None:
        """Register a device-TCP retransmit source for the harvest
        path: `plane_getter()` returns the current `tpu/tcp.TcpPlane`
        and `conn_host` [C] maps each connection to its sending host
        index. Every harvest then folds the per-connection cumulative
        `retransmit_count` into the per-host `retransmits` telemetry
        field via `tcp.retransmits_by_host` + the harvester's standard
        delta-unwrap (docs/observability.md)."""
        self._tcp_source = (plane_getter, self._jnp.asarray(
            np.asarray(conn_host), self._jnp.int32))

    def telemetry_arrays(self) -> dict:
        """Per-host counter arrays for the TelemetryHarvester, keyed in
        the PlaneMetrics namespace (host index i = host_id i+1). The
        `+ 0` copies matter: the transport kernels DONATE the state
        pytree, so a later dispatch would invalidate the raw leaves
        while the harvester's asynchronous D2H copy is still in flight;
        the tiny [N] device-side copies are fresh, undonated buffers.
        No sync happens here — materialization is the harvester's
        drain, a full harvest interval later."""
        st = self.state
        out = {
            "pkts_out": st.n_out + 0,
            "pkts_in": st.n_released + 0,
            "drop_ring_full": st.n_overflow + 0,
        }
        if self._tcp_source is not None:
            from . import tcp as dtcp

            plane_getter, conn_host = self._tcp_source
            # the per-host array lands in the same int32 `retransmits`
            # slot `telemetry.add_retransmits` feeds on a PlaneMetrics
            # pytree — same dtype/namespace contract, no throwaway
            # zero pytree per harvest
            out["retransmits"] = dtcp.retransmits_by_host(
                plane_getter(), conn_host, self._n).astype(
                self._jnp.int32)
        return out

    # -- shared ----------------------------------------------------------

    def _note_overflow(self, total_overflow: int) -> None:
        if total_overflow <= self._overflow_seen:
            return
        delta = total_overflow - self._overflow_seen
        log.error(
            "device transport dropped %d packets to ingress-capacity "
            "overflow — raise experimental.tpu_ingress_cap or run "
            "capacity.mode=elastic",
            delta,
        )
        if self._capacity_strict:
            # the capacity policy's strict promotion (docs/robustness.md
            # "Elastic capacity"): a strict run refuses to silently
            # diverge from the reference's unbounded-queue semantics.
            # Blame comes from the per-host device overflow counters —
            # one tiny blocking pull on a path that is already fatal.
            overflow = np.asarray(
                self._jax.device_get(self.state.n_overflow), np.int64)
            blame = [self.hosts[i].name
                     for i in np.nonzero(overflow > 0)[0]]
            raise CapacityError(
                f"device transport dropped {delta} packet(s) to "
                f"ingress-capacity overflow under the strict capacity "
                f"policy (tpu_ingress_cap={self._ingress_cap}); raise "
                f"the cap or run capacity.mode=elastic",
                ring="transport-ingress", blame=blame)
        # structured once-per-run accounting: the first drop lands a
        # capacity-trajectory event (surfaced in sim-stats.json and
        # telemetry heartbeats), not only the log line above
        if not any(e["ring"] == "transport-ingress"
                   and e["kind"] != "capacity-growth"
                   for e in self.capacity.events):
            self.capacity.record_drop(
                time_ns=self._prev_start or 0, ring="transport-ingress",
                cap=self._ingress_cap, overflow=delta, plane="transport")
        self._overflow_seen = total_overflow
        if self.mirrored:
            # CPU-side delivery is authoritative in mirrored mode: a
            # device overflow is a divergence (it will also surface as
            # missing released fingerprints), not a simulated drop
            self.divergence_count += 1
            return
        # surface device-side drops in the per-host tracker counters
        # (the packet objects never reach a CPU interface, so no
        # status-trace hook fires for them) — per-host breakdown pulled
        # only when the total moved (rare)
        overflow = np.asarray(
            self._jax.device_get(self.state.n_overflow), np.int64)
        deltas = overflow - self._overflow_prev
        for i in np.nonzero(deltas > 0)[0]:
            for tracker in getattr(self.hosts[i], "trackers", []):
                tracker.counters.packets_dropped += int(deltas[i])
        self._overflow_prev += np.maximum(deltas, 0)
