"""Utility layer (parity: reference `src/main/utility/`)."""
