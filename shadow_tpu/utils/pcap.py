"""Per-host packet capture in standard pcap format.

Parity: reference `src/main/utility/pcap_writer.rs` + `PcapConfig`
(`host.rs:279-282`): each enabled host writes one pcap file per interface;
simulated packets are serialized with synthetic Ethernet/IPv4/TCP|UDP
headers so wireshark/tcpdump open them directly. The capture-size option
truncates stored payload bytes (snaplen semantics).
"""

from __future__ import annotations

import ipaddress
import struct
from typing import BinaryIO

from ..net.packet import Packet, Protocol

PCAP_MAGIC = 0xA1B2C3D4  # microsecond-resolution classic format
LINKTYPE_ETHERNET = 1


def _ip(addr: str) -> bytes:
    return ipaddress.IPv4Address(addr).packed


class PcapWriter:
    def __init__(self, fh: BinaryIO, capture_size: int = 65535):
        self._fh = fh
        self._snaplen = capture_size
        fh.write(
            struct.pack(
                "<IHHiIII", PCAP_MAGIC, 2, 4, 0, 0, capture_size, LINKTYPE_ETHERNET
            )
        )

    def record(self, packet: Packet, time_ns: int) -> None:
        frame = self._serialize(packet)
        orig_len = len(frame)
        if orig_len > self._snaplen:
            frame = frame[: self._snaplen]
        sec, rem = divmod(time_ns, 1_000_000_000)
        self._fh.write(struct.pack("<IIII", sec, rem // 1000, len(frame), orig_len))
        self._fh.write(frame)

    def close(self) -> None:
        self._fh.close()

    # -- serialization ---------------------------------------------------

    def _serialize(self, p: Packet) -> bytes:
        if p.protocol == Protocol.TCP:
            l4 = self._tcp_header(p) + p.payload
            proto = 6
        else:
            l4 = self._udp_header(p) + p.payload
            proto = 17
        ip_len = 20 + len(l4)
        ip = struct.pack(
            ">BBHHHBBH4s4s",
            0x45, 0, ip_len, 0, 0, 64, proto, 0, _ip(p.src[0]), _ip(p.dst[0]),
        )
        eth = b"\x02" * 6 + b"\x04" * 6 + b"\x08\x00"
        return eth + ip + l4

    @staticmethod
    def _tcp_header(p: Packet) -> bytes:
        h = p.header
        seq = h.seq if h else 0
        ack = h.ack if h else 0
        flags = h.flags if h else 0
        window = min(h.window if h else 0, 0xFFFF)
        return struct.pack(
            ">HHIIBBHHH",
            p.src[1], p.dst[1], seq, ack, 5 << 4, int(flags), window, 0, 0,
        )

    @staticmethod
    def _udp_header(p: Packet) -> bytes:
        return struct.pack(
            ">HHHH", p.src[1], p.dst[1], 8 + len(p.payload), 0
        )
