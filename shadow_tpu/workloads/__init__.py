"""The workload plane: declarative traffic scenarios for the device plane.

Every other plane (telemetry, faults, guards, elastic capacity) exercises
the network through exactly two traffic sources: the PHOLD respawn loop
and the tgen flow plan. This subsystem makes *structured* traffic — the
phase-dependent collective steps, incast bursts, and RPC fan-outs of
large-model training runs — a first-party, reproducible simulation input:

- `workloads/spec.py`    — the jax-free scenario DSL: seeded pattern
  instances (ring_allreduce, all_to_all, incast, rpc_fanout, onoff)
  with validation and a fingerprint that is a pure function of
  (spec, seed);
- `workloads/compile.py` — lowers a scenario to SoA "traffic program"
  arrays: per-(host, phase) dependency counts, hold times, and send
  tables;
- `workloads/device.py`  — the batched on-device generator:
  `workload_step` threads through the window drivers like the PHOLD
  respawn (bitwise-deterministic, composes with metrics/faults/guards
  as the same kind of static presence switch);
- `workloads/phold.py`   — the PHOLD respawn generator (relocated from
  `tpu/profiling.py`; the profiler is measurement-only again);
- `workloads/runner.py`  — the corpus runner: executes checked-in
  scenarios, records canonical digests + per-phase completion virtual
  times, and diffs against the golden corpus
  (`tools/run_scenarios.py --check`).

See docs/workloads.md for the DSL reference and determinism contract.
"""

from .spec import (PATTERN_KINDS, ScenarioError, ScenarioSpec,
                   load_scenario_file, parse_scenario, scenario_fingerprint)
from .compile import TrafficProgram, compile_program, program_digest
from .phold import respawn_batch

__all__ = [
    "PATTERN_KINDS",
    "ScenarioError",
    "ScenarioSpec",
    "TrafficProgram",
    "compile_program",
    "load_scenario_file",
    "parse_scenario",
    "program_digest",
    "respawn_batch",
    "scenario_fingerprint",
]
