"""Scenario -> traffic program: lower the DSL to SoA phase tables.

A compiled scenario is a *traffic program*: dense numpy tables the
device generator (`workloads/device.py`) walks without ever consulting
the spec again. Per (host, phase):

- ``dep[N, P]``       — deliveries the host must receive while in phase
  p before it may advance (the dependency count of a collective step,
  an RPC reply quota, an incast fan-in);
- ``hold_ns[N, P]``   — minimum virtual time in phase p before it may
  advance (on/off pacing; quantized to the window cadence by the
  device generator, docs/workloads.md "Determinism contract");
- ``send_peer/send_bytes/send_delay[N, P, K]`` — the messages emitted
  on ENTERING phase p (peer -1 = unused lane); ``send_delay`` offsets
  the emission time within the entry window (RPC think time, CBR
  burst gaps), shifting delivery exactly like the CPU plane's
  now + latency;
- ``n_phases[N]``     — the host's terminal phase (0 = not a
  participant: the host starts done and never emits).

Everything seeded (onoff peers and off periods, rpc think jitter) is
drawn HERE from ``np.random.default_rng((seed, pattern_index))`` — the
program, and therefore the traffic, is a pure function of (spec, seed);
``program_digest`` pins that (tests/test_workloads.py).

Phase semantics (shared with device.py — keep in sync):
- entering phase p emits ``sends[p]``; leaving phase p requires
  ``dep[p]`` deliveries received while in p AND ``hold_ns[p]``
  elapsed;
- hosts start IN phase 0 with its sends emitted by the driver's prime
  batch (`device.prime_batch`);
- a host at ``phase == n_phases`` is done.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple

import numpy as np

from .spec import PatternSpec, ScenarioError, ScenarioSpec

#: ack/control message size for closed-loop patterns (incast)
ACK_BYTES = 64


class TrafficProgram(NamedTuple):
    """SoA phase tables (numpy; `device.to_device` uploads them).

    The trailing flow fields exist only under ``transport: flows``
    (`_lower_flows`): one flow per distinct (src, dst, bytes) send
    triple, plus the [N, P, K] lane -> flow id bridge the generator's
    `enqueue` path consults. They stay None on direct-transport
    programs so the first six fields — and therefore
    `program_digest` of every existing corpus entry — are unchanged."""

    dep: np.ndarray  # [N, P] int32
    hold_ns: np.ndarray  # [N, P] int32
    send_peer: np.ndarray  # [N, P, K] int32 (-1 = unused lane)
    send_bytes: np.ndarray  # [N, P, K] int32
    send_delay: np.ndarray  # [N, P, K] int32 ns within the entry window
    n_phases: np.ndarray  # [N] int32 terminal phase per host
    n_hosts: int
    max_phases: int  # P
    max_sends: int  # K
    flow_src: np.ndarray | None = None  # [F] int32 (-1 = pad slot)
    flow_dst: np.ndarray | None = None  # [F] int32
    flow_bytes: np.ndarray | None = None  # [F] int32
    lane_flow: np.ndarray | None = None  # [N, P, K] int32 (-1 = none)
    #: per-(host, phase) service cost, lowered from the scenario's
    #: ``compute:`` block + the checked-in op-timing table
    #: (`serve.lower_service_table`); None without a compute block, so
    #: pre-compute programs digest unchanged
    compute_service_ns: np.ndarray | None = None  # [N, P] int32


class _Builder:
    """Accumulates per-host phase lists before padding to [N, P, K]."""

    def __init__(self, n_hosts: int, claimed: frozenset[int] = frozenset()):
        self.n = n_hosts
        #: hosts claimed by ANY pattern instance — peer pools that fall
        #: back to the fleet must avoid them (traffic into another
        #: pattern's host would anonymously satisfy its dependencies)
        self.claimed = claimed
        # per host: list of (dep, hold_ns, [(peer, bytes, delay), ...])
        self.phases: list[list[tuple]] = [[] for _ in range(n_hosts)]

    def add_phase(self, host: int, dep: int = 0, hold_ns: int = 0,
                  sends: list[tuple[int, int, int]] = ()):
        self.phases[host].append((dep, hold_ns, list(sends)))

    def finish(self) -> TrafficProgram:
        P = max((len(p) for p in self.phases), default=0)
        K = max((len(s) for p in self.phases for (_, _, s) in p),
                default=0)
        P, K = max(P, 1), max(K, 1)
        dep = np.zeros((self.n, P), np.int32)
        hold = np.zeros((self.n, P), np.int32)
        peer = np.full((self.n, P, K), -1, np.int32)
        nbytes = np.zeros((self.n, P, K), np.int32)
        delay = np.zeros((self.n, P, K), np.int32)
        n_phases = np.zeros((self.n,), np.int32)
        for h, plist in enumerate(self.phases):
            n_phases[h] = len(plist)
            for p, (d, hld, sends) in enumerate(plist):
                dep[h, p] = d
                hold[h, p] = hld
                for k, (pr, by, dl) in enumerate(sends):
                    peer[h, p, k] = pr
                    nbytes[h, p, k] = by
                    delay[h, p, k] = dl
        return TrafficProgram(
            dep=dep, hold_ns=hold, send_peer=peer, send_bytes=nbytes,
            send_delay=delay, n_phases=n_phases, n_hosts=self.n,
            max_phases=P, max_sends=K)


def _compile_ring_allreduce(b: _Builder, p: PatternSpec, rng):
    """`steps = 2*(count-1)` ring hops per round (reduce-scatter +
    all-gather): in every step, participant i sends one chunk to its
    ring successor and advances on the chunk from its predecessor."""
    steps = 2 * (p.count - 1)
    for i in range(p.count):
        h = p.first + i
        succ = p.first + (i + 1) % p.count
        for _ in range(p.rounds * steps):
            b.add_phase(h, dep=1, sends=[(succ, p.bytes, 0)])


def _compile_all_to_all(b: _Builder, p: PatternSpec, rng):
    """count-1 shifted-permutation phases per round: in phase s,
    participant i sends to (i+1+s) mod count and advances on the
    message from (i-1-s) mod count."""
    for i in range(p.count):
        h = p.first + i
        for _ in range(p.rounds):
            for s in range(p.count - 1):
                peer = p.first + (i + 1 + s) % p.count
                b.add_phase(h, dep=1, sends=[(peer, p.bytes, 0)])


def _compile_incast(b: _Builder, p: PatternSpec, rng):
    """Closed-loop fan-in: count-1 sources send `bytes` at the sink
    (host `first`); the sink, once all fan-in arrives, acks each
    source with a tiny control message, releasing the next round."""
    sink = p.first
    fanin = p.count - 1
    sources = [p.first + 1 + i for i in range(fanin)]
    for r in range(p.rounds):
        # sink: wait for the fan-in, then an ack-emission pass-through
        # phase (dep=0 -> the generator advances through it in the
        # same window it entered)
        b.add_phase(sink, dep=fanin)
        b.add_phase(sink, dep=0,
                    sends=[(s, ACK_BYTES, 0) for s in sources])
    for s in sources:
        for r in range(p.rounds):
            b.add_phase(s, dep=1, sends=[(sink, p.bytes, 0)])


def _compile_rpc_fanout(b: _Builder, p: PatternSpec, rng):
    """Request/response fan-out with think time: the root (host
    `first`) sends `bytes` requests to count-1 children; each child
    replies `resp_bytes` after a seeded per-(child, round) think
    delay; the root advances on the full reply quota."""
    root = p.first
    fanout = p.count - 1
    children = [p.first + 1 + i for i in range(fanout)]
    for r in range(p.rounds):
        b.add_phase(root, dep=fanout,
                    sends=[(c, p.bytes, 0) for c in children])
    # think[c, r]: base + uniform jitter, drawn in (child, round) order
    # so the stream is independent of compilation batching
    think = np.full((fanout, p.rounds), p.think_ns, np.int64)
    if p.think_jitter_ns:
        think = think + rng.integers(
            0, p.think_jitter_ns + 1, size=(fanout, p.rounds))
    for ci, c in enumerate(children):
        # phase r waits for round r's request; entering phase r+1
        # emits round r's reply (think time as an emission delay)
        for r in range(p.rounds):
            b.add_phase(c, dep=1)
            b.add_phase(c, dep=0,
                        sends=[(root, p.resp_bytes,
                                int(think[ci, r]))])


def _compile_onoff(b: _Builder, p: PatternSpec, rng):
    """Per-host on/off CBR with heavy-tail OFF periods: each cycle
    emits a `burst` of packets (gap_ns apart) at a seeded peer, holds
    `on_hold_ns`, then sleeps a bounded-Pareto OFF period. Peers are
    drawn over the pattern's own range when it spans more than one
    host, else over the fleet's UNCLAIMED hosts — traffic into another
    pattern's participants would anonymously satisfy their phase
    dependencies (deliveries credit the receiver's current phase, so a
    stray CBR packet would stand in for a collective chunk)."""
    cap = 2**29
    # Pareto scale for the requested mean: mean = x_m * a / (a - 1)
    x_m = max(1, int(p.off_mean_ns * (p.off_alpha - 1) / p.off_alpha))
    if p.count > 1:
        pool = [p.first + i for i in range(p.count)]
    else:
        pool = [x for x in range(b.n)
                if x == p.first or x not in b.claimed]
        if len(pool) < 2:
            raise ScenarioError(
                "onoff: a single-host pattern needs at least one "
                "unclaimed fleet host to target — every other host is "
                "claimed by another pattern; widen the onoff range or "
                "free a host")
    pool_arr = np.asarray(pool, np.int64)
    for i in range(p.count):
        h = p.first + i
        # all draws for host h come from h's own substream slice:
        # (cycle-ordered peer draws, then off draws) per host. The
        # skip-self draw is index arithmetic (r + (r >= self_idx)),
        # draw-for-draw identical to indexing a pool-minus-self list
        # but O(rounds) instead of O(count) per host
        self_idx = i if p.count > 1 else pool.index(h)
        r = rng.integers(0, len(pool) - 1, size=p.rounds)
        peers = pool_arr[r + (r >= self_idx)]
        u = rng.random(size=p.rounds)
        off = np.minimum((x_m * (1.0 - u) ** (-1.0 / p.off_alpha))
                         .astype(np.int64), cap).astype(np.int64)
        for c in range(p.rounds):
            sends = [(int(peers[c]), p.bytes, k * p.gap_ns)
                     for k in range(p.burst)]
            b.add_phase(h, dep=0, hold_ns=p.on_hold_ns, sends=sends)
            b.add_phase(h, dep=0, hold_ns=int(off[c]))


def _compile_serve(b: _Builder, p: PatternSpec, rng):
    """Open-loop serving arrivals (`serve._compile_serve` — kept in
    its own module with the op-timing machinery it pairs with)."""
    from . import serve
    serve._compile_serve(b, p, rng)


_COMPILERS = {
    "ring_allreduce": _compile_ring_allreduce,
    "all_to_all": _compile_all_to_all,
    "incast": _compile_incast,
    "rpc_fanout": _compile_rpc_fanout,
    "onoff": _compile_onoff,
    "serve": _compile_serve,
}


def _lower_flows(prog: TrafficProgram) -> TrafficProgram:
    """Enumerate the program's flows (``transport: flows``): one flow
    per distinct (src host, dst host, bytes) send triple, ids assigned
    in deterministic first-use order over (host, phase, lane) — a pure
    function of the program tables, so the flow layout rides the
    program digest. Fills `flow_src`/`flow_dst`/`flow_bytes` plus the
    `lane_flow` bridge. One segment = one message of the triple's
    byte size, so the phase dependency counts carry over unchanged.

    NOTE the per-lane ``send_delay`` does NOT survive the flow
    transport: emission is window-quantized by the flow plane's
    cwnd-gated window, so sub-window think/burst offsets quantize to
    the emission window (docs/workloads.md determinism contract)."""
    N, P, K = prog.send_peer.shape
    ids: dict[tuple[int, int, int], int] = {}
    lane_flow = np.full((N, P, K), -1, np.int32)
    for h in range(N):
        for p in range(int(prog.n_phases[h])):
            for k in range(K):
                peer = int(prog.send_peer[h, p, k])
                if peer < 0:
                    continue
                key = (h, peer, int(prog.send_bytes[h, p, k]))
                lane_flow[h, p, k] = ids.setdefault(key, len(ids))
    F = max(1, len(ids))  # >= 1 pad slot: zero-size arrays trace badly
    src = np.full((F,), -1, np.int32)
    dst = np.full((F,), -1, np.int32)
    nbytes = np.zeros((F,), np.int32)
    for (h, peer, by), f in ids.items():
        src[f], dst[f], nbytes[f] = h, peer, by
    return prog._replace(flow_src=src, flow_dst=dst, flow_bytes=nbytes,
                         lane_flow=lane_flow)


def compile_program(spec: ScenarioSpec) -> TrafficProgram:
    """Lower a validated scenario to its traffic program. Each pattern
    instance draws from its own `default_rng((seed, index))` substream,
    so adding a pattern never perturbs the others' draws."""
    b = _Builder(spec.n_hosts, claimed=frozenset(
        h for pat in spec.patterns for h in pat.hosts()))
    for idx, pat in enumerate(spec.patterns):
        rng = np.random.default_rng((spec.seed, idx))
        _COMPILERS[pat.kind](b, pat, rng)
    prog = b.finish()
    if prog.max_sends > spec.egress_cap:
        raise ScenarioError(
            f"scenario {spec.name!r}: a single phase emits up to "
            f"{prog.max_sends} messages from one host but "
            f"egress_cap={spec.egress_cap} — the append would be "
            f"guaranteed to overflow; raise egress_cap or shrink the "
            f"fan-out/burst")
    if spec.transport == "flows":
        prog = _lower_flows(prog)
    if spec.compute is not None:
        from . import serve
        prog = prog._replace(
            compute_service_ns=serve.lower_service_table(spec, prog))
    return prog


def program_digest(prog: TrafficProgram) -> str:
    """sha256 over the program tables — the compile-determinism pin:
    equal (spec, seed) must produce byte-equal tables. Flow tables
    (``transport: flows``) fold in only when present, so every
    direct-transport program's digest is unchanged by their
    existence."""
    h = hashlib.sha256()
    for arr in prog[:6]:
        a = np.asarray(arr)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(f"{prog.n_hosts}/{prog.max_phases}/{prog.max_sends}"
             .encode())
    if prog.flow_src is not None:
        for arr in (prog.flow_src, prog.flow_dst, prog.flow_bytes,
                    prog.lane_flow):
            a = np.asarray(arr)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    if prog.compute_service_ns is not None:
        # the lowered op-timing costs ride the digest, so editing the
        # checked-in table invalidates every memo/golden entry that
        # consumed it (tests/test_compute.py drift guard)
        a = np.asarray(prog.compute_service_ns)
        h.update(b"compute")
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()
