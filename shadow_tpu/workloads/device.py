"""The batched on-device traffic generator: `workload_step`.

The device half of the workload plane. A compiled traffic program
(`compile.TrafficProgram`) uploads once as a `WorkloadArrays` pytree;
per window, `workload_step` threads through the driver loop exactly
like the PHOLD respawn (`workloads/phold.respawn_batch` in bench.py /
chaos_smoke): it consumes the window's `delivered` dict, advances
per-host phase pointers, and emits the next phases' sends via
`ingest_rows` — fully inside the compiled chain, no host round trips,
bitwise-deterministic.

Phase semantics (compile.py is the other half of this contract):

- deliveries received this window credit the host's CURRENT phase;
- a host advances when its phase's dependency count is met AND its
  hold time has elapsed — at most ``max_advance`` phase advances per
  window (static; pass-through phases like the incast sink's
  ack-emission phase consume one each);
- hold times are quantized to the window cadence (decremented by
  ``window_ns`` per window) — pacing is deterministic, not
  ns-exact;
- ENTERING a phase emits its send table; per-lane ``send_delay``
  offsets the emission within the entry window (think time, burst
  gaps), shifting delivery exactly like a late CPU-plane send;
- the window index at which a host LEAVES each phase records into
  ``done_win`` (I32_MAX = not yet) — the per-phase completion times
  the corpus runner reports.

Composition: `metrics` / `guards` thread through the emission's
`ingest_rows` as the same static presence switches the other planes
use; `workload=None` in a driver means this module is never called —
the workloads-off world is bitwise-unchanged by the subsystem's
presence (pinned in tests/test_workloads.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..tpu.plane import I32_MAX, ingest_rows
from .compile import TrafficProgram

#: default phase-advance budget per window (static): covers every
#: in-tree pattern's longest same-window cascade (an incast sink's
#: wait -> ack pass-through -> next wait is 2; rpc's wait -> reply
#: emission is 2) with headroom for dep=0 chains
MAX_ADVANCE = 4


class WorkloadArrays(NamedTuple):
    """The uploaded traffic program (read-only on device)."""

    dep: jnp.ndarray  # [N, P] int32
    hold_ns: jnp.ndarray  # [N, P] int32
    send_peer: jnp.ndarray  # [N, P, K] int32 (-1 = unused lane)
    send_bytes: jnp.ndarray  # [N, P, K] int32
    send_delay: jnp.ndarray  # [N, P, K] int32
    n_phases: jnp.ndarray  # [N] int32


class WorkloadState(NamedTuple):
    """Mutable per-host generator state, axis 0 = host (sharded with
    the net-plane state over the mesh)."""

    phase: jnp.ndarray  # [N] int32 current phase (== n_phases: done)
    recv_acc: jnp.ndarray  # [N] int32 deliveries credited to it
    hold_left: jnp.ndarray  # [N] int32 ns left in the phase's hold
    seq: jnp.ndarray  # [N] int32 next send seq (per-source monotone)
    done_win: jnp.ndarray  # [N, P] int32 window idx the phase was left


def to_device(prog: TrafficProgram) -> WorkloadArrays:
    """Upload the program tables. Copies (jnp.array, not asarray) so a
    mutated numpy program can never alias device state — the same
    zero-copy trap the fault schedule hit (faults/plane.py)."""
    return WorkloadArrays(
        dep=jnp.array(prog.dep, jnp.int32),
        hold_ns=jnp.array(prog.hold_ns, jnp.int32),
        send_peer=jnp.array(prog.send_peer, jnp.int32),
        send_bytes=jnp.array(prog.send_bytes, jnp.int32),
        send_delay=jnp.array(prog.send_delay, jnp.int32),
        n_phases=jnp.array(prog.n_phases, jnp.int32),
    )


def make_workload_state(prog: TrafficProgram) -> WorkloadState:
    """Initial state: every participant IN phase 0 (its sends go out
    via `prime`), holds pre-armed from phase 0's table."""
    N, P = prog.dep.shape
    return WorkloadState(
        phase=jnp.zeros((N,), jnp.int32),
        recv_acc=jnp.zeros((N,), jnp.int32),
        hold_left=jnp.array(prog.hold_ns[:, 0], jnp.int32),
        seq=jnp.zeros((N,), jnp.int32),
        done_win=jnp.full((N, P), I32_MAX, jnp.int32),
    )


def _phase_sends(wl: WorkloadArrays, phase, entered):
    """[N, K] send lanes of each host's `phase`, masked by `entered`."""
    idx = jnp.clip(phase, 0, wl.dep.shape[1] - 1)[:, None, None]
    take = lambda a: jnp.take_along_axis(a, idx, axis=1)[:, 0, :]
    peer = take(wl.send_peer)
    valid = entered[:, None] & (peer >= 0)
    return valid, peer, take(wl.send_bytes), take(wl.send_delay)


def _emit(state, ws: WorkloadState, valid, peer, nbytes, delay, *,
          metrics=None, guards=None):
    """Append the emission batch to the egress rings with workload
    seqs assigned in lane order (cumsum rank over valid lanes, the
    same capacity-independent ranking the PHOLD respawn uses)."""
    rank = jnp.where(
        valid, jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1, 0)
    seq_vals = ws.seq[:, None] + rank
    out = ingest_rows(
        state, peer, nbytes,
        seq_vals,  # priority: FIFO-ish by emission order
        seq_vals, jnp.zeros_like(valid), valid=valid,
        send_rel=delay, metrics=metrics, guards=guards)
    # ingest_rows returns a bare state when neither presence switch is
    # threaded, else (state, metrics?, guards?) — normalize (a bare
    # NetPlaneState is itself a tuple, so test the switches, not the
    # type)
    if metrics is None and guards is None:
        state_out, extras = out, ()
    else:
        state_out, extras = out[0], tuple(out[1:])
    ws = ws._replace(seq=ws.seq + valid.sum(axis=1, dtype=jnp.int32))
    return state_out, extras, ws


def _lane_flows(ft, phase, entered):
    """[N, K] flow ids of each host's `phase` send lanes (the
    ``transport: flows`` bridge: `FlowTables.lane_flow` gathered
    exactly like `_phase_sends` gathers the send tables)."""
    idx = jnp.clip(phase, 0, ft.lane_flow.shape[1] - 1)[:, None, None]
    lf = jnp.take_along_axis(ft.lane_flow, idx, axis=1)[:, 0, :]
    return jnp.where(entered[:, None], lf, -1)


def prime(wl: WorkloadArrays, ws: WorkloadState, state, *,
          metrics=None, guards=None, flows=None):
    """Emit every participant's phase-0 sends (drivers call this once
    before the first window; hosts start IN phase 0). Returns
    (state', ws'[, metrics'][, guards']) like `workload_step`.

    ``flows=(ft, fs)`` (the flow transport) ENQUEUES the sends onto
    their flows instead of emitting raw packets — the driver follows
    with one `flows.flow_emit` so the cwnd-gated window goes out
    before window 0. The return becomes (state, ws, fs'[, metrics']
    [, guards']) with state/metrics/guards passed through untouched
    (enqueue writes flow state only)."""
    entered = wl.n_phases > 0
    phase0 = jnp.zeros_like(ws.phase)
    valid, peer, nbytes, delay = _phase_sends(wl, phase0, entered)
    if flows is not None:
        from ..tpu import flows as flows_mod

        ft, fs = flows
        fs = flows_mod.enqueue(ft, fs, _lane_flows(ft, phase0, entered),
                               valid)
        extras = tuple(p for p in (metrics, guards) if p is not None)
        return (state, ws, fs, *extras)
    state, extras, ws = _emit(state, ws, valid, peer, nbytes, delay,
                              metrics=metrics, guards=guards)
    return (state, ws, *extras)


def workload_step(wl: WorkloadArrays, ws: WorkloadState, state,
                  delivered, round_idx, window_ns, *,
                  max_advance: int = MAX_ADVANCE,
                  metrics=None, guards=None, flows=None,
                  credits=None):
    """Advance the generator by one window and emit the next sends.

    `delivered` is `window_step`'s released dict for THIS window;
    every delivery credits the receiving host's current phase (in a
    scenario world all traffic is workload traffic). `round_idx` is
    the driver's window counter (stamps `done_win`); `window_ns`
    decrements the hold clocks. Returns
    (state', ws'[, metrics'][, guards']) — the same presence-switch
    return discipline as `ingest_rows`.

    ``flows=(ft, fs, credits)`` switches the generator onto the flow
    transport (docs/robustness.md "Flow plane"): phase credits are
    the `credits` vector `flows.flow_recv` computed — ACKED in-order
    segments, never raw deliveries, so a duplicate from a spurious
    retransmit can never double-credit a phase — and the emission
    ENQUEUES segments onto their flows (`flows.enqueue`) for the
    driver's following `flow_emit` instead of appending raw packets.
    The return becomes (state, ws', fs'[, metrics'][, guards']) with
    state/metrics/guards passed through untouched.

    ``credits`` (direct transport only; the flows triple carries its
    own) overrides the raw per-host delivery count with an externally
    metered credit vector — the compute plane's delivery-AND-service
    gate (`tpu/compute.gate_credits`, docs/workloads.md "Serving load
    & the compute plane")."""
    N, P = wl.dep.shape
    if flows is not None:
        ft, fs, credits = flows
        got = credits
    elif credits is not None:
        got = credits
    else:
        got = delivered["mask"].sum(axis=1, dtype=jnp.int32)
    recv_acc = ws.recv_acc + got
    hold_left = jnp.maximum(ws.hold_left - jnp.int32(window_ns), 0)
    phase = ws.phase
    done_win = ws.done_win
    col = jnp.arange(P, dtype=jnp.int32)[None, :]
    lanes = []
    for _ in range(max_advance):
        cur = jnp.clip(phase, 0, P - 1)
        dep_cur = jnp.take_along_axis(wl.dep, cur[:, None],
                                      axis=1)[:, 0]
        live = phase < wl.n_phases
        adv = live & (recv_acc >= dep_cur) & (hold_left == 0)
        recv_acc = jnp.where(adv, recv_acc - dep_cur, recv_acc)
        # the window a phase was LEFT: min-scatter via a one-hot
        # compare (idempotent, no scatter dispatch — shards cleanly)
        done_win = jnp.minimum(
            done_win,
            jnp.where(adv[:, None] & (col == cur[:, None]),
                      jnp.int32(round_idx), I32_MAX))
        phase = jnp.where(adv, phase + 1, phase)
        entered = adv & (phase < wl.n_phases)
        new = jnp.clip(phase, 0, P - 1)
        hold_new = jnp.take_along_axis(wl.hold_ns, new[:, None],
                                       axis=1)[:, 0]
        hold_left = jnp.where(entered, hold_new, hold_left)
        lanes.append(_phase_sends(wl, phase, entered))
        if flows is not None:
            lanes[-1] = (*lanes[-1], _lane_flows(ft, phase, entered))
    valid = jnp.concatenate([ln[0] for ln in lanes], axis=1)
    peer = jnp.concatenate([ln[1] for ln in lanes], axis=1)
    nbytes = jnp.concatenate([ln[2] for ln in lanes], axis=1)
    delay = jnp.concatenate([ln[3] for ln in lanes], axis=1)
    ws = ws._replace(phase=phase, recv_acc=recv_acc,
                     hold_left=hold_left, done_win=done_win)
    if flows is not None:
        from ..tpu import flows as flows_mod

        lf = jnp.concatenate([ln[4] for ln in lanes], axis=1)
        fs = flows_mod.enqueue(ft, fs, lf, valid)
        extras = tuple(p for p in (metrics, guards) if p is not None)
        return (state, ws, fs, *extras)
    state, extras, ws = _emit(state, ws, valid, peer, nbytes, delay,
                              metrics=metrics, guards=guards)
    return (state, ws, *extras)


def all_done(wl: WorkloadArrays, ws: WorkloadState):
    """Scalar bool: every participant reached its terminal phase."""
    return (ws.phase >= wl.n_phases).all()


def completion_windows(ws: WorkloadState) -> np.ndarray:
    """[N, P] int64 window indices at which each phase was left
    (I32_MAX where never) — host-side, for the runner's reports."""
    return np.asarray(ws.done_win).astype(np.int64)
