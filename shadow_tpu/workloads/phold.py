"""The PHOLD respawn generator — the workload plane's oldest resident.

Relocated from `tpu/profiling.py` so the profiler module is
measurement-only again: PHOLD is a *workload* (the classic PDES
closed-loop benchmark Shadow ships configs for, `src/test/phold/`),
and every traffic source now lives under `shadow_tpu/workloads/`.
`tpu/profiling.respawn_batch` remains as a back-compat re-export;
bench.py / chaos_smoke / the profiler all import this home.
"""

from __future__ import annotations


def respawn_batch(delivered, spawn_seq, round_idx, n_hosts: int,
                  ingress_cap: int):
    """The PHOLD bench's deterministic respawn batch: each delivered
    packet triggers one new packet from the receiving host to a hashed
    destination (FIFO-ish priority = seq). ONE definition shared by
    `bench.py`'s scan body and the profiler's `ingest_rows` section,
    so the profiled batch is exactly the batch the bench feeds it —
    any workload change here changes both with it. Returns
    (valid_mask, dst, nbytes, seq, ctrl), all [N, CI]."""
    import jax.numpy as jnp

    mask = delivered["mask"]
    dst = (delivered["src"] * 40503
           + delivered["seq"] * 1566083941 + round_idx * 97) % n_hosts
    # seq rank = position among the row's DUE lanes, not the raw column
    # index: due lanes sit at the row TAIL of the delivered arrays, so a
    # column-index rank would bake the ring capacity into every respawned
    # seq — making the PHOLD stream capacity-dependent and breaking the
    # elastic-growth parity contract (docs/determinism.md "Growth is
    # bitwise-invisible"). The cumsum rank is identical at any CI.
    rank = jnp.where(
        mask, jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1, 0)
    seq = spawn_seq[:, None] + rank
    nbytes = jnp.full((n_hosts, ingress_cap), 1400, jnp.int32)
    ctrl = jnp.zeros((n_hosts, ingress_cap), bool)
    return mask, dst, nbytes, seq, ctrl
