"""The scenario corpus runner: execute, digest, and report workloads.

Drives a compiled scenario through the device plane: a deterministic
scenario world, the window loop composing `window_step` +
`workload_step`, and a JSON record per scenario carrying the fields
below. Worlds are lossless by default; a scenario that declares
``transport: flows`` runs the device flow plane (`tpu/flows.py`:
cwnd/RTO/go-back-N retransmit) under its declared ``loss_p`` — the
lossy half of the corpus, where phases credit ACKED in-order segments
and lost dependencies are retransmitted instead of stalling the
collective (docs/robustness.md "Flow plane"; direct-transport
scenarios with loss_p > 0 are refused at parse). Each record carries:

- the scenario ``fingerprint`` (pure function of (spec, seed)) and
  ``program_digest`` (the compiled tables);
- the ``canonical_digest`` of the final world — `elastic.
  canonical_state`-normalized net-plane state + the full workload
  state — the golden-corpus comparison key (two runs of one scenario
  must produce byte-identical records; `tools/run_scenarios.py
  --check` gates on it);
- per-phase completion *virtual* times (window-quantized: the end of
  the window in which the last participant left the phase — for
  ring_allreduce, the per-step collective completion times) and the
  per-host completion spread (stragglers);
- traffic/drop totals from a threaded `PlaneMetrics` (bitwise-
  invisible to the stream, like every presence switch).

Optional composition, same switches as the other planes: `guards=True`
threads the runtime invariant plane (a fault-injected scenario must
finish guards-clean — the CI proof), `fault_events` compiles a
`faults:`-style schedule, `mesh_devices` runs the whole scenario
host-axis-sharded (the canonical digest must not change — the
MULTICHIP parity contract extended to structured workloads), and
`telemetry` attaches a TelemetryHarvester whose heartbeat
``annotations`` carry the phase completions.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

import numpy as np

from .compile import TrafficProgram, compile_program, program_digest
from .spec import ScenarioSpec, scenario_fingerprint

MS = 1_000_000


def digest_pytrees(*pytrees) -> str:
    """sha256 over every leaf's dtype+bytes (the chaos_smoke digest
    discipline). ONE device_get for the whole tuple — tuple flattening
    preserves per-tree leaf order, so the digest bytes are identical
    to a per-tree pull (golden-pinned) without a D2H sync per pytree
    (the SL603 fence)."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(pytrees)):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def build_scenario_world(spec: ScenarioSpec):
    """Deterministic net-plane world for a scenario: host-pair latency
    table seeded from the scenario seed, the spec's uniform ``loss_p``
    (zero by default; parse-time validation requires ``transport:
    flows`` for anything else), 10 Gbit hosts, full initial token
    buckets. Returns (state, params)."""
    from ..tpu import make_params, make_state

    N = spec.n_hosts
    rng = np.random.default_rng([spec.seed, 0x57A7])
    lat = rng.integers(1 * MS, 5 * MS, size=(N, N), dtype=np.int32)
    lat = np.minimum(lat, lat.T)
    loss = np.full((N, N), spec.loss_p, np.float32)
    bw = np.full((N,), 10_000_000_000, np.int64)
    params = make_params(lat, loss, bw)
    state = make_state(N, egress_cap=spec.egress_cap,
                       ingress_cap=spec.ingress_cap,
                       initial_tokens=np.asarray(params.tb_cap))
    return state, params


def default_fault_schedule(spec: ScenarioSpec):
    """A small chaos schedule scaled to the scenario (the chaos_smoke
    shape): crash one participant for the middle quarter, degrade a
    link, corrupt a host's egress. Compiled through the REAL `faults:`
    schedule path so validation and mask semantics are identical."""
    from ..core.config import FaultsOptions
    from ..faults.schedule import compile_schedule

    w = lambda k: f"{max(1, k) * spec.window_ns}ns"
    q = max(2, spec.windows // 4)
    last = spec.n_hosts - 1
    events = [
        {"at": w(q), "kind": "host_crash", "host": f"h{last}"},
        {"at": w(2 * q), "kind": "host_reboot", "host": f"h{last}"},
        {"at": w(q // 2), "kind": "link_degrade", "src_node": 0,
         "dst_node": min(1, spec.n_hosts - 1), "latency_mult": 4,
         "duration": w(2 * q)},
        {"at": w(q), "kind": "corrupt_burst",
         "host": f"h{max(0, last - 1)}", "p": 0.3, "duration": w(q)},
    ]
    opts = FaultsOptions(events=events)
    return compile_schedule(
        opts, host_names=[f"h{i}" for i in range(spec.n_hosts)],
        n_nodes=spec.n_hosts, seed=spec.seed,
        stop_time_ns=(spec.windows + 1) * spec.window_ns)


def _shard_host_axis(tree, mesh):
    """Host-axis-shard a pytree: rank>=1 leaves split on axis 0 (every
    workload/metrics/guards array is host-major), rank-0 scalars
    (PlaneMetrics.windows/events/...) replicate."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..tpu.mesh import host_sharding

    sh, rep = host_sharding(mesh), NamedSharding(mesh, P())
    return jax.tree.map(
        lambda a: jax.device_put(a, sh if jnp_rank(a) >= 1 else rep),
        tree)


def jnp_rank(a) -> int:
    return int(getattr(a, "ndim", 0))


def run_scenario(spec: ScenarioSpec, *,
                 guards: bool = False,
                 fault_events=None,
                 use_default_faults: bool = False,
                 mesh_devices: Optional[int] = None,
                 telemetry=None,
                 telemetry_every: int = 16,
                 histograms: bool = True,
                 sample_every: Optional[int] = None,
                 trace_ring: int = 4096,
                 hops_sink=None,
                 max_advance: Optional[int] = None,
                 flow_emit_cap: Optional[int] = None,
                 flow_recv_wnd: Optional[int] = None,
                 memo=None,
                 tracer=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 16,
                 resume: bool = False,
                 kill_at: Optional[int] = None,
                 memo_cache: Optional[str] = None,
                 provenance: Optional[dict] = None) -> dict:
    """Execute one scenario for its full window budget. Returns the
    JSON-ready record (no wall-clock anywhere — byte-stable across
    runs by construction).

    `histograms` (default on) threads the log2 latency/depth
    distributions and records the per-scenario SLO percentiles
    (`latency` in the record — the "p99 delivery latency under incast"
    answer); `sample_every=K` additionally threads the flight recorder
    (seeded from the scenario seed) and drains sampled hops at the
    telemetry cadence into `hops_sink` (a path or file object). Both
    are presence switches: the canonical digest is bitwise-unchanged
    (docs/observability.md "Distributions and the flight recorder").

    `memo` (a config `MemoOptions`, a dict of its knobs, or True for
    defaults) turns on steady-state memoization (`tpu/memo.py`,
    docs/performance.md): chain spans whose full carry recurs bitwise
    — the drained tail of a completed collective, quiescent stretches
    of periodic traffic — replay their recorded post-state instead of
    executing, with the canonical digest pinned byte-equal to the cold
    run (the golden `--check` gate passes unchanged). The memo key
    folds the scenario fingerprint + program digest, every dynamics
    knob, the absolute round while any workload host is still live
    (done_win stamps absolute rounds), the flow plane's virtual clock
    while any flow could read it, and — under faults — the schedule's
    span fingerprint, so fault-injected spans never replay across
    non-identical fault contexts. Not supported with `mesh_devices`
    (the host-mirror fast-forward would collapse the sharding).

    `tracer` (a `telemetry/tracer.RunTracer`) records the run ledger:
    one span record per chain at the driver's existing boundary sync,
    harvest-tick annotations, and the folded memo report when
    memoized. Presence-invisible by contract — the returned record
    (and therefore the golden digests) is byte-identical with or
    without it; wall time lives ONLY on the ledger.

    `checkpoint_dir` + `checkpoint_every` make the run
    crash-survivable (`faults/runstate.py`, docs/robustness.md
    "Resumable runs"): the full carry — every presence plane, the
    fault-schedule position, the memo cache — spills atomically every
    K windows. `resume=True` restarts from the newest checkpoint for
    this scenario (cold start when none exists); the returned record
    is byte-identical to the uninterrupted run's, so provenance rides
    OUT OF BAND: the `provenance` dict (when given) is filled with
    ``resumed_from``/``start_round``/``checkpoints_written``, and the
    tracer gets a ``resume`` annotation. `memo_cache` (a file path)
    persists the `ChainMemo` across invocations: loaded before the
    run when present, saved after — the second invocation's spans
    replay from the persisted entries (``persisted_hits`` in the memo
    report is the witness)."""
    import jax
    import jax.numpy as jnp

    from ..guards import make_guards, summarize
    from ..telemetry import make_metrics
    from ..telemetry import flightrec as frmod
    from ..telemetry import histo
    from ..tpu import elastic
    from ..tpu.plane import unpack_planes, window_step
    from . import device as wdevice

    prog = compile_program(spec)
    state, params = build_scenario_world(spec)
    wl = wdevice.to_device(prog)
    ws = wdevice.make_workload_state(prog)
    N = spec.n_hosts
    use_flows = spec.transport == "flows"
    ftab = flowst = None
    emit_cap = recv_wnd = 0
    if use_flows:
        from ..tpu import flows as flowsmod

        # the `flows:` config-block knobs arrive here (run_scenarios
        # --config plumbs cfg.flows through); None = module defaults
        emit_cap = (flow_emit_cap if flow_emit_cap is not None
                    else flowsmod.EMIT_CAP)
        recv_wnd = (flow_recv_wnd if flow_recv_wnd is not None
                    else flowsmod.RECV_WND)
        if emit_cap < 1 or recv_wnd < 1 or emit_cap > recv_wnd:
            raise ValueError(
                f"flow knobs out of range: emit_cap={emit_cap} must be "
                f">= 1 and <= recv_wnd={recv_wnd} (the config block's "
                "validation rule, core/config.py)")
        ftab = flowsmod.make_flow_tables(
            prog.flow_src, prog.flow_dst, prog.flow_bytes,
            prog.lane_flow)
        flowst = flowsmod.make_flow_state(prog.flow_src.shape[0],
                                          recv_wnd=recv_wnd)
    use_compute = spec.compute is not None
    ctab = cstate = None
    if use_compute:
        from ..tpu import compute as computemod

        # per-host service model (`tpu/compute.py`): the occupancy
        # plane rides window_step as a presence switch; the credit
        # coupling ("delivered AND serviced") lives in this loop
        ctab = computemod.make_compute_tables(
            prog.compute_service_ns, spec.compute.queue_cap)
        cstate = computemod.make_compute_state(ctab)
    metrics = make_metrics(N)
    gstate = make_guards(N) if guards else None
    hstate = histo.make_histograms(N) if histograms else None
    fstate = recorder = None
    if sample_every is not None:
        fstate = frmod.make_flightrec(
            spec.seed, sample_every=sample_every, ring=trace_ring)
        recorder = frmod.FlightRecorder(window_ns=spec.window_ns,
                                        sink=hops_sink)
    schedule = fault_events
    if schedule is None and use_default_faults:
        schedule = default_fault_schedule(spec)
    if mesh_devices is not None and use_flows:
        raise ValueError(
            "transport: flows does not support --shard yet: the flow "
            "axis is flow-major, not host-major, and its credit "
            "scatter-adds need the cross-shard reduction the "
            "ROADMAP-2 shard_map cut will bring")
    if mesh_devices is not None and use_compute:
        raise ValueError(
            "the compute plane does not support --shard yet: the "
            "service tables ride the chain closure un-sharded, and "
            "mixing them with a host-sharded ComputeState waits on "
            "the same ROADMAP-2 shard_map cut as flows")
    if mesh_devices is not None:
        from ..tpu import make_mesh, shard_state

        mesh = make_mesh(mesh_devices)
        state, params = shard_state(state, params, mesh)
        wl = _shard_host_axis(wl, mesh)
        ws = _shard_host_axis(ws, mesh)
        metrics = _shard_host_axis(metrics, mesh)
        if gstate is not None:
            gstate = _shard_host_axis(gstate, mesh)
        if hstate is not None:
            # [N, B] histograms are host-major like every counter
            hstate = _shard_host_axis(hstate, mesh)
        # the flight-recorder ring is [R] (not host-major) and stays
        # replicated; the partitioner gathers the sampled events
    if use_flows:
        # prime enqueues phase-0 sends onto their flows; one flow_emit
        # puts the first cwnd-gated window on the wire before window 0
        # (exactly when the direct-mode prime emission would land)
        state, ws, flowst, metrics = wdevice.prime(
            wl, ws, state, metrics=metrics, flows=(ftab, flowst))
        state, flowst, metrics = flowsmod.flow_emit(
            ftab, flowst, state, emit_cap=emit_cap, metrics=metrics)
    else:
        state, ws, metrics = wdevice.prime(wl, ws, state,
                                           metrics=metrics)
    rng_root = jax.random.key(spec.seed)
    window = jnp.int32(spec.window_ns)
    adv = max_advance if max_advance is not None else wdevice.MAX_ADVANCE
    faulted = schedule is not None

    from ..tpu import elastic as _elastic

    def round_fn(carry, xs):
        state, ws, metrics, gstate, hstate, fstate, flowst, cstate = \
            carry
        if faulted:
            ridx, faults = xs
        else:
            ridx, faults = xs, None
        shift = jnp.where(ridx == 0, jnp.int32(0), window)
        out = window_step(state, params, rng_root, shift, window,
                          rr_enabled=False, faults=faults,
                          metrics=metrics, guards=gstate,
                          hist=hstate, flightrec=fstate,
                          compute=((ctab, cstate) if use_compute
                                   else None))
        if use_compute:
            ((state, delivered, _next), metrics, gstate, hstate,
             fstate, cstate) = unpack_planes(
                out, metrics=metrics, guards=gstate, hist=hstate,
                flightrec=fstate, compute=cstate)
        else:
            (state, delivered, _next), metrics, gstate, hstate, \
                fstate = unpack_planes(out, metrics=metrics,
                                       guards=gstate, hist=hstate,
                                       flightrec=fstate)
        if use_flows:
            # the split-form flow loop (tpu/flows.py): credit ACKED
            # in-order arrivals, advance the phase machine on those
            # credits, enqueue its sends onto their flows, then emit
            # the cwnd-gated window (+ retransmits + delayed acks)
            # through the normal ingest path
            flowst, credits = flowsmod.flow_recv(ftab, flowst,
                                                 delivered, window)
            if use_compute:
                # the serving coupling: the k-th credit advances the
                # phase machine only once the k-th service completion
                # has happened too (tpu/compute.gate_credits)
                cstate, credits = computemod.gate_credits(cstate,
                                                          credits)
            wout = wdevice.workload_step(
                wl, ws, state, delivered, ridx, window,
                max_advance=adv, metrics=metrics, guards=gstate,
                flows=(ftab, flowst, credits))
            if gstate is not None:
                state, ws, flowst, metrics, gstate = wout
            else:
                state, ws, flowst, metrics = wout
            eout = flowsmod.flow_emit(ftab, flowst, state,
                                      emit_cap=emit_cap,
                                      metrics=metrics, guards=gstate,
                                      flightrec=fstate)
            state, flowst = eout[0], eout[1]
            rest = list(eout[2:])
            metrics = rest.pop(0)
            if gstate is not None:
                gstate = rest.pop(0)
            if fstate is not None:
                fstate = rest.pop(0)
        else:
            credits = None
            if use_compute:
                cstate, credits = computemod.gate_credits(
                    cstate, delivered["mask"].sum(axis=1,
                                                  dtype=jnp.int32))
            wout = wdevice.workload_step(
                wl, ws, state, delivered, ridx, window,
                max_advance=adv, metrics=metrics, guards=gstate,
                credits=credits)
            if gstate is not None:
                state, ws, metrics, gstate = wout
            else:
                state, ws, metrics = wout
        if use_compute:
            # re-arm each host's per-request cost from the phase the
            # machine just advanced to (window_step never sees phases)
            cstate = computemod.phase_service(ctab, cstate, ws.phase)
        return (state, ws, metrics, gstate, hstate, fstate,
                flowst, cstate), None

    @jax.jit
    def chain(state, ws, metrics, gstate, hstate, fstate, flowst,
              cstate, rids, faults_stack):
        # K windows device-resident per dispatch (the shared driver's
        # contract): the fault-mask stack rides as per-round scan
        # inputs, every presence plane rides the carry — bitwise
        # identical to the per-window loop this replaced, once per
        # telemetry harvest instead of once per window
        xs = (rids, faults_stack) if faulted else rids
        carry, _ = jax.lax.scan(
            round_fn, (state, ws, metrics, gstate, hstate, fstate,
                       flowst, cstate), xs)
        return carry

    def per_round(r0, r1):
        stack = []
        for r in range(r0, r1):
            schedule.advance((r + 1) * spec.window_ns)
            stack.append(schedule.device_arrays())
        return jax.tree.map(lambda *xs: jnp.stack(xs), *stack)

    def chain_fn(state, extras, rids, faults_stack):
        ws, metrics, gstate, hstate, fstate, flowst, cstate = extras
        (state, ws, metrics, gstate, hstate, fstate, flowst,
         cstate) = chain(state, ws, metrics, gstate, hstate, fstate,
                         flowst, cstate, rids, faults_stack)
        return state, (ws, metrics, gstate, hstate, fstate,
                       flowst, cstate), 0, 0

    annotated = [0]

    def on_chain(r1, state, extras):
        ws, metrics, gstate, hstate, fstate, flowst, cstate = extras
        if r1 % telemetry_every == 0:
            if telemetry is not None:
                annotated[0] = _annotate_phases(
                    telemetry, spec, prog, ws, annotated[0])
                telemetry.tick(r1 * spec.window_ns,
                               device=_device_counters(metrics, hstate))
            if recorder is not None:
                recorder.tick(fstate)
            if tracer is not None:
                tracer.annotate("harvest", r=int(r1),
                                time_ns=int(r1) * spec.window_ns)

    memo_obj, memo_salt_fn, memo_chain = _build_memo(
        memo, spec=spec, prog=prog, schedule=schedule,
        mesh_devices=mesh_devices, adv=adv, emit_cap=emit_cap,
        recv_wnd=recv_wnd, guards=guards, histograms=histograms,
        sample_every=sample_every, trace_ring=trace_ring)

    if tracer is not None and memo_salt_fn is None and faulted:
        # no memo, but the ledger still wants the fault-span
        # fingerprint: the same schedule-position-preserving salt the
        # memoized path uses (advance to r0 is a no-op mid-run)
        def memo_salt_fn(r0, r1):
            schedule.advance(r0 * spec.window_ns)
            return schedule.span_fingerprint(
                r0 * spec.window_ns, r1 * spec.window_ns).encode()

    if memo_cache is not None:
        if memo_obj is None:
            raise ValueError("memo_cache requires memo: there is no "
                             "cache to persist on a non-memoized run")
        if os.path.isfile(memo_cache):
            memo_obj.load(memo_cache)

    checkpointer = None
    start_round = 0
    resumed_from = None
    if checkpoint_dir is not None:
        from ..faults import runstate
        from ..faults.checkpoint import CheckpointError

        if mesh_devices is not None:
            raise ValueError(
                "checkpointing does not support mesh_devices yet: the "
                "flattened carry re-uploads un-sharded arrays, "
                "collapsing the host-axis sharding")
        checkpointer = runstate.RunCheckpointer(
            checkpoint_dir, every=checkpoint_every, label=spec.name,
            window_ns=spec.window_ns, schedule=schedule, memo=memo_obj,
            kill_after=kill_at,
            extra_meta={"fingerprint": scenario_fingerprint(spec),
                        "program_digest": program_digest(prog)})
        if resume:
            ckpt_path = runstate.latest_checkpoint(checkpoint_dir,
                                                   label=spec.name)
            if ckpt_path is not None:
                # refuse world drift BEFORE touching the carry: a
                # same-named scenario with different physics should be
                # named as such, not as whatever leaf mismatches first
                want_fp = runstate.load_runstate(ckpt_path)[0].get(
                    "fingerprint")
                if want_fp != scenario_fingerprint(spec):
                    raise CheckpointError(
                        f"{ckpt_path}: scenario fingerprint mismatch "
                        f"(checkpoint {str(want_fp)[:12]}..., this run "
                        f"{scenario_fingerprint(spec)[:12]}...) — the "
                        f"checkpoint belongs to a different world")
                template = (state, (ws, metrics, gstate, hstate,
                                    fstate, flowst, cstate))
                res = runstate.resume_carry(template_carry=template,
                                            path=ckpt_path,
                                            schedule=schedule,
                                            memo=memo_obj)
                state, (ws, metrics, gstate, hstate, fstate,
                        flowst, cstate) = res["carry"]
                start_round = res["round"]
                resumed_from = os.path.basename(ckpt_path)
                if resumed_from.endswith(".runstate.npz"):
                    resumed_from = resumed_from[:-len(".runstate.npz")]
                if tracer is not None:
                    tracer.annotate("resume", checkpoint=resumed_from,
                                    r=start_round)

    need_cadence = telemetry is not None or recorder is not None
    state, extras = _elastic.drive_chained_windows(
        state, (ws, metrics, gstate, hstate, fstate, flowst, cstate),
        chain_fn,
        n_rounds=spec.windows,
        chain_len=(telemetry_every if need_cadence
                   else memo_chain if memo_obj is not None
                   else spec.windows),
        start_round=start_round,
        per_round=per_round if faulted else None,
        window_ns=spec.window_ns,
        on_chain=on_chain if need_cadence else None,
        memo=memo_obj, memo_span_salt=memo_salt_fn, tracer=tracer,
        checkpointer=checkpointer)
    ws, metrics, gstate, hstate, fstate, flowst, cstate = extras

    if memo_cache is not None and memo_obj is not None:
        memo_obj.save(memo_cache)
    if provenance is not None:
        provenance.update({
            "resumed_from": resumed_from,
            "start_round": int(start_round),
            "checkpoints_written": (checkpointer.saved
                                    if checkpointer is not None else 0),
        })

    jax.block_until_ready(state)
    done_win = wdevice.completion_windows(ws)
    m = jax.device_get(metrics)
    completion = _phase_completion(spec, prog, done_win)
    record = {
        "name": spec.name,
        "family": spec.family,
        "fingerprint": scenario_fingerprint(spec),
        "program_digest": program_digest(prog),
        "hosts": N,
        "windows": spec.windows,
        "window_ns": spec.window_ns,
        "phases": prog.max_phases,
        "faults_active": faulted,
        "transport": spec.transport,
        # flow worlds fold the per-flow state into the comparison key:
        # a retransmit-schedule divergence must fail the golden gate
        # even when the net-plane state happens to converge
        "canonical_digest": digest_pytrees(
            elastic.canonical_state(state), ws,
            *((flowst,) if use_flows else ()),
            *((cstate,) if use_compute else ())),
        "all_done": bool(np.asarray(
            jax.device_get(ws.phase) >= prog.n_phases).all()),
        "completed_hosts": int(
            (np.asarray(jax.device_get(ws.phase)) >= prog.n_phases)
            [prog.n_phases > 0].sum()),
        "participants": int((prog.n_phases > 0).sum()),
        "sent": int(np.asarray(jax.device_get(state.n_sent)).sum()),
        "delivered": int(np.asarray(
            jax.device_get(state.n_delivered)).sum()),
        "events": int(np.asarray(m.events)),
        "drops": {
            "ring_full": int(np.asarray(m.drop_ring_full).sum()),
            "qdisc": int(np.asarray(m.drop_qdisc).sum()),
            "loss": int(np.asarray(m.drop_loss).sum()),
            "fault": int(np.asarray(m.drop_fault).sum()),
        },
        "retransmits": int(np.asarray(m.retransmits)
                           .astype(np.int64).sum()),
        **completion,
    }
    if use_flows:
        record["flows"] = {
            **flowsmod.flow_totals(ftab, flowst),
            "emit_cap": emit_cap, "recv_wnd": recv_wnd,
        }
    if use_compute:
        # the serving record: compute-plane totals + the SLO block
        # (docs/workloads.md "SLO record schema") — request-sojourn
        # p99/p999 from the fleet-summed compute histograms, judged
        # against the scenario's `serve:` targets when declared
        c = jax.device_get(cstate)
        i64sum = lambda a: int(np.asarray(a).astype(np.int64).sum())
        record["compute"] = {
            "op": spec.compute.op,
            "queue_cap": spec.compute.queue_cap,
            "served": i64sum(c.n_served),
            "queued": i64sum(c.n_queued),
            "overflow": i64sum(c.n_overflow),
        }
        slo = {"wait_ns": histo.fleet_percentiles(c.hist_wait_ns),
               "sojourn_ns": histo.fleet_percentiles(c.hist_sojourn_ns)}
        if spec.serve is not None:
            soj = slo["sojourn_ns"]
            slo["targets"] = {
                q: {"target_ns": target, "measured_ns": soj[q],
                    "met": bool(soj[q] <= target)}
                for q, target in (("p99", spec.serve.p99_ns),
                                  ("p999", spec.serve.p999_ns))
                if target is not None}
        record["slo"] = slo
    if memo_obj is not None:
        record["memo"] = memo_obj.report()
        if tracer is not None:
            # ONE artifact: the ledger folds the same report
            # `--memo-report` publishes (trace_report --memo-view)
            tracer.memo_close(memo_obj)
    if gstate is not None:
        record["guards"] = summarize(gstate)
    if hstate is not None:
        # per-scenario SLO percentiles from the fleet-summed final
        # histograms (docs/observability.md bucket scheme: log2 upper
        # bounds) — byte-stable ints, "p99 delivery latency under
        # incast" answered per corpus entry
        h = jax.device_get(hstate)
        record["latency"] = {
            name[len(histo.HIST_PREFIX):] if name.startswith(
                histo.HIST_PREFIX) else name:
            histo.fleet_percentiles(arr)
            for name, arr in h._asdict().items()}
    if recorder is not None:
        # final drain: one tick to queue the last ring snapshot, one
        # materializing drain via finalize (the double-buffer contract)
        recorder.tick(fstate)
        recorder.finalize()
        record["flight_recorder"] = {
            **recorder.summary(), **frmod.flightrec_meta(fstate)}
    if telemetry is not None:
        # trailing annotations attach to the pending snapshot at the
        # harvester's next drain (finalize); only tick again when the
        # loop's cadence did NOT already harvest this exact instant —
        # a duplicate-timestamp heartbeat reads as a broken stream
        _annotate_phases(telemetry, spec, prog, ws, annotated[0])
        if spec.windows % telemetry_every != 0:
            telemetry.tick(spec.windows * spec.window_ns,
                           device=_device_counters(metrics, hstate))
    return record


def _build_memo(memo, *, spec, prog, schedule, mesh_devices, adv,
                emit_cap, recv_wnd, guards, histograms, sample_every,
                trace_ring):
    """Normalize the `memo` argument (None/bool/MemoOptions/dict) into
    a (ChainMemo, span_salt_fn, chain_len) triple for the driver.

    The static salt folds everything the chain closure captures that
    the carry cannot show: the scenario fingerprint (world build +
    seed + window_ns), the program digest (the compiled send tables),
    and every dynamics knob. `key_extra` folds the two
    state-conditional sensitivities (docstring of `run_scenario`):
    the absolute start round while any workload host is live, and the
    flow plane's raw virtual clock while anything could read it — a
    flow timer armed, an RTT probe outstanding, unacked stream bytes,
    a pending ack, receiver bitmap content, or ANY packet still in a
    net-plane ring (a stale duplicate ack re-arms timers on arrival).
    """
    if memo is None or memo is False:
        return None, None, None
    knob = (memo.get if isinstance(memo, dict)
            else lambda k, d: getattr(memo, k, d))
    if memo is not True and not knob("enabled", True):
        return None, None, None
    if mesh_devices is not None:
        raise ValueError(
            "memo does not support mesh_devices: the host-mirror "
            "fast-forward re-uploads un-sharded arrays, collapsing "
            "the host-axis sharding")
    from ..tpu import memo as memomod

    salt = "|".join([
        "memo-v1", scenario_fingerprint(spec), program_digest(prog),
        f"adv={adv}", f"emit={emit_cap}", f"wnd={recv_wnd}",
        f"guards={int(guards)}", f"hist={int(histograms)}",
        f"se={sample_every}", f"ring={trace_ring}",
    ]).encode()
    n_phases_host = np.asarray(prog.n_phases)

    def key_extra(carry, r0):
        mstate, mextras = carry
        mws, mflow = mextras[0], mextras[5]
        parts = []
        if bool((np.asarray(mws.phase) < n_phases_host).any()):
            parts.append(b"r0:%d" % r0)
        if mflow is not None:
            live = bool(
                np.asarray(mflow.rto_armed).any()
                or (np.asarray(mflow.rtt_seq) >= 0).any()
                or (np.asarray(mflow.snd_una)
                    != np.asarray(mflow.stream_len)).any()
                or np.asarray(mflow.ack_pending).any()
                or np.asarray(mflow.rcv_bits).any()
                or np.asarray(mstate.eg_valid).any()
                or np.asarray(mstate.in_valid).any())
            parts.append(b"clk:" + (
                np.ascontiguousarray(mflow.clock_ms).tobytes()
                if live else b"idle"))
        return b"|".join(parts)

    memo_obj = memomod.ChainMemo(
        max_bytes=int(knob("max_bytes", 64 << 20)),
        min_repeat=int(knob("min_repeat", 1)),
        salt=salt, key_extra=key_extra)
    salt_fn = None
    if schedule is not None:
        def salt_fn(r0, r1):
            # keep the schedule position current even across memo hits
            # (hits skip per_round, which is what normally advances
            # it); advancing to r0 is a no-op on the miss path
            schedule.advance(r0 * spec.window_ns)
            return schedule.span_fingerprint(
                r0 * spec.window_ns, r1 * spec.window_ns).encode()
    # 4-window spans by default: short enough that the drained tail
    # of every corpus entry yields equal-length recurring spans (the
    # final partial span would otherwise never match), long enough to
    # amortize the per-boundary host snapshot
    return memo_obj, salt_fn, int(knob("chain_len", 4))


def _device_counters(metrics, hstate):
    """The harvester's device dict: metrics + histogram leaves. Takes
    the live pytrees explicitly (the old closure-over-locals form
    silently captured stale loop variables)."""
    if hstate is None:
        return metrics
    return {**metrics._asdict(), **hstate._asdict()}


def _phase_completion(spec: ScenarioSpec, prog: TrafficProgram,
                      done_win: np.ndarray) -> dict:
    """Completion-time report from the [N, P] done-window table.

    Times are window-quantized VIRTUAL ns: a phase left during window
    w completed by (w+1) * window_ns. Per phase p, completion is the
    max over hosts whose program includes p (None while any of them
    hasn't left it); per host, completion is its terminal phase's
    time — min/p50/max expose the straggler spread."""
    P = prog.max_phases
    never = 2**31 - 1
    phase_ns: list[Optional[int]] = []
    for p in range(P):
        members = prog.n_phases > p
        if not members.any():
            phase_ns.append(None)
            continue
        wins = done_win[members, p]
        phase_ns.append(None if (wins >= never).any()
                        else int((wins.max() + 1) * spec.window_ns))
    hosts_done = []
    for h in range(prog.n_hosts):
        np_h = int(prog.n_phases[h])
        if np_h == 0:
            continue
        w = done_win[h, np_h - 1]
        if w < never:
            hosts_done.append(int((w + 1) * spec.window_ns))
    hosts_done.sort()
    spread = (
        {"min_ns": hosts_done[0],
         "p50_ns": hosts_done[len(hosts_done) // 2],
         "max_ns": hosts_done[-1]}
        if hosts_done else None)
    return {"phase_completion_ns": phase_ns,
            "host_completion": spread}


def _annotate_phases(harvester, spec: ScenarioSpec,
                     prog: TrafficProgram, ws, already: int):
    """Queue heartbeat annotations for phases fully completed since the
    last harvest (the one host-side pull the runner makes per harvest —
    this is a reporting tool, not the hot path). Returns the new
    annotated-phase count."""
    import jax

    done_win = np.asarray(jax.device_get(ws.done_win)).astype(np.int64)
    never = 2**31 - 1
    # phases complete in order per participant, so the fleet-wide
    # completed prefix is monotone and `already` tracks how many were
    # announced; a phase counts once EVERY host whose program includes
    # it has left it
    count = already
    for p in range(already, prog.max_phases):
        members = prog.n_phases > p
        if not members.any():
            break
        wins = done_win[members, p]
        if (wins >= never).any():
            break
        harvester.note_event({
            "kind": "workload_phase",
            "scenario": spec.name,
            "family": spec.family,
            "phase": p,
            "time_ns": int((wins.max() + 1) * spec.window_ns),
        })
        count = p + 1
    return count


def load_golden(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def golden_entry(record: dict) -> dict:
    """The per-scenario golden tuple: enough to tell 'the scenario
    changed' (fingerprint) from 'the compiler changed' (program
    digest) from 'determinism broke' (canonical digest)."""
    return {"fingerprint": record["fingerprint"],
            "program_digest": record["program_digest"],
            "canonical_digest": record["canonical_digest"]}


def check_against_golden(records: list[dict], golden: dict) -> list[str]:
    """Compare a corpus run against the golden file; returns a list of
    human-readable mismatch lines (empty = clean)."""
    problems = []
    seen = set()
    for rec in records:
        name = rec["name"]
        seen.add(name)
        want = golden.get(name)
        if want is None:
            problems.append(f"{name}: not in the golden corpus "
                            f"(run --update-golden after review)")
            continue
        got = golden_entry(rec)
        for key in ("fingerprint", "program_digest", "canonical_digest"):
            if got[key] != want.get(key):
                problems.append(
                    f"{name}: {key} mismatch\n"
                    f"  golden: {want.get(key)}\n"
                    f"  run:    {got[key]}")
    for name in sorted(set(golden) - seen):
        problems.append(f"{name}: in the golden corpus but not run")
    return problems
