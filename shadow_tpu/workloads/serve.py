"""The serving subsystem's compile half (jax-free, like spec/compile).

Two responsibilities, both pure functions of checked-in inputs:

1. **The open-loop ``serve`` pattern compiler** (`_compile_serve`,
   registered in `compile._COMPILERS`): a seeded arrival process over a
   client population. The pattern range's first ``servers`` hosts form
   the server tier; the remaining ``count - servers`` hosts are
   clients. Each client, after a seeded stagger phase (so the
   population does not fire in lockstep), emits ``rounds`` request
   batches: the inter-batch gap is exponential with a diurnal-modulated
   mean (``rate(t) = (1 + diurnal_amp * sin(2*pi*t /
   diurnal_period_ns)) / mean_gap_ns``, t = the client's own
   accumulated virtual send clock), the batch size is a bounded Pareto
   (``x_m = 1``, tail ``burst_alpha``, hard cap ``burst_cap``), and the
   target server is drawn uniformly. All draws come from the pattern's
   `default_rng((seed, index))` substream in (client, round) order —
   SL102: the device generator stays table-driven, no host-side RNG
   stream. Servers carry ONE aggregate phase whose dependency count is
   the total number of requests compiled at them, which is only
   deterministic under ``transport: flows`` (phases credit ACKED
   in-order segments; the spec parser enforces the pairing).

2. **Service-cost lowering** (`lower_service_table`): turn the
   scenario's ``compute: {op, queue_cap}`` block into the per-(host,
   phase) ``service_ns`` table the compute plane (`tpu/compute.py`)
   meters against, using the checked-in op-timing table
   ``workloads/op_timings.json`` (SCALE-Sim-validated affine per-op
   costs, arxiv 2603.22535: ``fixed_ns + per_kib_ns *
   ceil(bytes/1024)``). Only dep-bearing phases get a cost — a phase
   that waits on deliveries services them; emission-only phases
   (client request batches, incast acks) are compute-transparent. The
   lowered table is bounded at compile time so no int32 completion
   clock can overflow: ``svc_ns * (ingress_cap + queue_cap + 1)`` must
   fit the quarter budget (`tpu/plane.py` dtype discipline).

The op-timing table is drift-guarded: `op_timings_digest` is pinned by
tests/test_compute.py, and the table rides `compile.program_digest`
through the lowered ``compute_service_ns`` field, so editing a timing
invalidates every memo/golden entry that consumed it.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from functools import lru_cache

import numpy as np

from .spec import ScenarioError, ScenarioSpec, _I32_TIME_BUDGET

#: the checked-in per-op timing table (affine ns cost per request)
OP_TIMINGS_PATH = os.path.join(os.path.dirname(__file__),
                               "op_timings.json")


@lru_cache(maxsize=None)
def _load_raw(path: str) -> tuple[bytes, dict]:
    with open(path, "rb") as fh:
        blob = fh.read()
    raw = json.loads(blob)
    if not isinstance(raw, dict) or not isinstance(raw.get("ops"), dict):
        raise ScenarioError(
            f"op timing table {path}: expected a mapping with an "
            "'ops' mapping")
    for name, ent in raw["ops"].items():
        if (not isinstance(ent, dict)
                or not isinstance(ent.get("fixed_ns"), int)
                or not isinstance(ent.get("per_kib_ns"), int)
                or ent["fixed_ns"] < 0 or ent["per_kib_ns"] < 0):
            raise ScenarioError(
                f"op timing table {path}: op {name!r} needs "
                "non-negative integer fixed_ns and per_kib_ns")
    return blob, raw


def load_op_timings(path: str = OP_TIMINGS_PATH) -> dict:
    """The validated ``ops`` mapping (cached; schema-checked)."""
    return _load_raw(path)[1]["ops"]


def op_timings_digest(path: str = OP_TIMINGS_PATH) -> str:
    """sha256 over the table FILE BYTES — the drift guard tests pin
    (any edit, even whitespace, is a deliberate re-pin)."""
    return hashlib.sha256(_load_raw(path)[0]).hexdigest()


def op_service_ns(op: str, nbytes: int,
                  path: str = OP_TIMINGS_PATH) -> int:
    """Per-request service cost of ``op`` on an ``nbytes`` request."""
    ops = load_op_timings(path)
    if op not in ops:
        raise ScenarioError(
            f"compute.op {op!r} not in the op timing table "
            f"({sorted(ops)})")
    ent = ops[op]
    return int(ent["fixed_ns"]
               + ent["per_kib_ns"] * ((int(nbytes) + 1023) // 1024))


def _compile_serve(b, p, rng):
    """Lower one ``serve`` pattern instance (see module docstring).

    Draw order is (client, round): per client one stagger draw, then
    per round (u_gap, u_burst, server index) — adding rounds extends a
    client's tail without perturbing other clients' streams only in
    aggregate (the whole pattern shares one substream, like onoff's
    per-host slices: a pure function of (seed, pattern index))."""
    gap_cap = _I32_TIME_BUDGET // 4
    servers = [p.first + i for i in range(p.servers)]
    clients = [p.first + p.servers + i
               for i in range(p.count - p.servers)]
    server_load = {s: 0 for s in servers}
    for c in clients:
        # stagger: a seeded hold before the first batch so the
        # open-loop population decorrelates (every client entering
        # phase 0 in the prime batch would otherwise fire in lockstep)
        stagger = int(rng.integers(0, p.mean_gap_ns + 1))
        b.add_phase(c, dep=0, hold_ns=stagger)
        t = stagger  # the client's virtual send clock (diurnal phase)
        for _ in range(p.rounds):
            rate_mult = 1.0
            if p.diurnal_amp > 0.0:
                rate_mult += p.diurnal_amp * math.sin(
                    2.0 * math.pi * (t % p.diurnal_period_ns)
                    / p.diurnal_period_ns)
            u = rng.random()
            gap = int(min(-math.log1p(-u) * p.mean_gap_ns / rate_mult,
                          gap_cap))
            burst = min(p.burst_cap,
                        int((1.0 - rng.random()) ** (-1.0
                                                     / p.burst_alpha)))
            srv = servers[int(rng.integers(0, len(servers)))]
            server_load[srv] += burst
            b.add_phase(c, dep=0, hold_ns=gap,
                        sends=[(srv, p.bytes, 0)] * burst)
            t += gap
    for s in servers:
        # one aggregate phase: done when every request compiled at this
        # server has been ACKED through the flow plane (and, with the
        # compute plane on, serviced — gate_credits meters the count)
        b.add_phase(s, dep=server_load[s])


def lower_service_table(spec: ScenarioSpec, prog) -> np.ndarray:
    """The [N, P] int32 per-(host, phase) service table (see module
    docstring): ``op_service_ns(op, pattern bytes)`` on dep-bearing
    phases, 0 elsewhere. Bounds the worst completion clock inside the
    int32 quarter budget before anything reaches the device."""
    assert spec.compute is not None
    svc = np.zeros_like(prog.dep, dtype=np.int32)
    for pat in spec.patterns:
        cost = op_service_ns(spec.compute.op, pat.bytes)
        hosts = list(pat.hosts())
        svc[hosts] = np.where(prog.dep[hosts] > 0, cost, 0)
    worst = int(svc.max()) * (spec.ingress_cap
                              + spec.compute.queue_cap + 1)
    if worst > _I32_TIME_BUDGET // 4:
        raise ScenarioError(
            f"scenario {spec.name!r}: compute op "
            f"{spec.compute.op!r} costs up to {int(svc.max())} ns per "
            f"request; a full queue + window of arrivals could push a "
            f"completion clock to {worst} ns, past the int32 budget "
            f"({_I32_TIME_BUDGET // 4} ns) — shrink queue_cap, "
            f"ingress_cap, or the request bytes")
    return svc
